"""Resilience subsystem tests (ISSUE 3): retry/deadline policies, chaos
fault injection, graceful degradation in DataLoader and the fused kvstore
path, preemption-safe checkpointing, and the chaos end-to-end acceptance
run (mid-run fault → auto_resume → bit-identical parameters).

Every blocking path exercised here is deadline-bounded — the suite must
never hang.  The CI chaos lane re-runs this file with MXNET_CHAOS=1.
"""

import os
import signal
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.resilience import (
    ChaosTransientError, ChaosWorkerDeath, Deadline, KVStoreTimeoutError,
    Retry, RetryExhaustedError, chaos, policies,
)
from mxnet_tpu.telemetry import REGISTRY


def _metric(name):
    m = REGISTRY.get(name)
    return m.value if m is not None else 0


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.clear()
    yield
    chaos.clear()


# ---------------------------------------------------------------------------
# policies
# ---------------------------------------------------------------------------

def test_retry_absorbs_transient():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ChaosTransientError("flake")
        return "ok"

    before = _metric("mxnet_resilience_retries_total")
    r = Retry(max_retries=3, backoff_s=0.001, backoff_max_s=0.01, site="t")
    assert r.call(flaky) == "ok"
    assert len(calls) == 3
    assert _metric("mxnet_resilience_retries_total") == before + 2


def test_retry_exhausts_and_chains_cause():
    r = Retry(max_retries=2, backoff_s=0.001, site="t")
    with pytest.raises(RetryExhaustedError) as ei:
        r.call(lambda: (_ for _ in ()).throw(ChaosTransientError("always")))
    assert isinstance(ei.value.__cause__, ChaosTransientError)


def test_retry_does_not_retry_permanent_errors():
    calls = []

    def fatal():
        calls.append(1)
        raise ChaosWorkerDeath("dead")

    r = Retry(max_retries=5, backoff_s=0.001, site="t")
    with pytest.raises(ChaosWorkerDeath):
        r.call(fatal)
    assert len(calls) == 1  # no retry: the failure is not transient


def test_retry_env_defaults(monkeypatch):
    monkeypatch.setenv("MXNET_RESILIENCE_MAX_RETRIES", "7")
    monkeypatch.setenv("MXNET_RESILIENCE_BACKOFF_S", "0.125")
    r = Retry()
    assert r.max_retries == 7
    assert r.backoff_s == 0.125


def test_deadline_bounds_a_hung_call():
    d = Deadline(timeout_s=0.2, site="unit")
    before = _metric("mxnet_resilience_deadline_exceeded_total")
    t0 = time.monotonic()
    with pytest.raises(KVStoreTimeoutError, match="deadline"):
        d.call(time.sleep, 30)
    assert time.monotonic() - t0 < 5  # bounded, not 30s
    assert _metric("mxnet_resilience_deadline_exceeded_total") == before + 1


def test_deadline_passes_values_and_exceptions():
    d = Deadline(timeout_s=5, site="unit")
    assert d.call(lambda: 41 + 1) == 42
    with pytest.raises(ValueError, match="boom"):
        d.call(lambda: (_ for _ in ()).throw(ValueError("boom")))
    # disabled deadline = direct call
    assert Deadline(timeout_s=0).call(lambda: "direct") == "direct"


def test_deadline_reuses_worker_and_recovers_after_timeout():
    d = Deadline(timeout_s=0.5, site="unit")
    assert d.call(lambda: 1) == 1
    worker = d._worker
    assert d.call(lambda: 2) == 2
    assert d._worker is worker  # persistent: no per-call thread spawn
    with pytest.raises(KVStoreTimeoutError):
        d.call(time.sleep, 30)
    assert d.call(lambda: 3) == 3  # fresh worker after the wedged one
    assert d._worker is not worker
    d.close()


def test_timeout_is_not_retried():
    """Retry must not re-enter a timed-out collective (desync hazard)."""
    calls = []

    def wedged():
        calls.append(1)
        raise KVStoreTimeoutError("peer gone")

    with pytest.raises(KVStoreTimeoutError):
        Retry(max_retries=3, backoff_s=0.001).call(wedged)
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# chaos
# ---------------------------------------------------------------------------

def test_chaos_deterministic_counts():
    before = _metric("mxnet_resilience_faults_injected_total")
    chaos.inject("unit.site", kind="transient", times=2, after=1)
    chaos.hit("unit.site")  # hit 1: within `after`, passes
    with pytest.raises(ChaosTransientError):
        chaos.hit("unit.site")  # hit 2 fires
    with pytest.raises(ChaosTransientError):
        chaos.hit("unit.site")  # hit 3 fires
    chaos.hit("unit.site")  # times exhausted, passes
    assert chaos.fault_count("unit.site") >= 2
    assert _metric("mxnet_resilience_faults_injected_total") == before + 2
    chaos.clear("unit.site")
    assert not chaos.active()
    chaos.hit("unit.site")  # disarmed: no-op


def test_chaos_delay_kind():
    chaos.inject("unit.delay", kind="delay", times=1, delay_s=0.05)
    t0 = time.monotonic()
    chaos.hit("unit.delay")
    assert time.monotonic() - t0 >= 0.04


def test_chaos_env_arming_survives_malformed_spec(monkeypatch):
    monkeypatch.setenv("MXNET_CHAOS", "1")
    monkeypatch.setenv("MXNET_CHAOS_SITES",
                       "bad.site:transient:two,good.site:delay:1:0.001")
    with pytest.warns(UserWarning, match="malformed MXNET_CHAOS_SITES"):
        chaos._arm_from_env()  # a spec typo must not raise (import-time)
    try:
        assert "good.site" in chaos.sites()
        assert "bad.site" not in chaos.sites()
    finally:
        chaos.clear()


def test_chaos_env_arming(monkeypatch):
    monkeypatch.setenv("MXNET_CHAOS", "1")
    monkeypatch.setenv("MXNET_CHAOS_SITES",
                       "env.site:transient:2,env.other:delay:1:0.001")
    chaos._arm_from_env()
    try:
        assert "env.site" in chaos.sites()
        assert "env.other" in chaos.sites()
        with pytest.raises(ChaosTransientError):
            chaos.hit("env.site")
    finally:
        chaos.clear()


# ---------------------------------------------------------------------------
# kvstore wiring
# ---------------------------------------------------------------------------

def test_dist_kvstore_retry_absorbs_transient_faults():
    """Acceptance: injected transient kvstore faults are absorbed by
    retry with mxnet_resilience_retries_total > 0."""
    kv = mx.kv.create("dist_tpu_sync")
    kv._retry.backoff_s = 0.001
    kv.init(0, mx.nd.zeros((3,)))
    before = _metric("mxnet_resilience_retries_total")
    chaos.inject("kvstore.allreduce", kind="transient", times=2)
    kv.push(0, mx.nd.ones((3,)))
    out = mx.nd.zeros((3,))
    kv.pull(0, out)
    np.testing.assert_allclose(out.asnumpy(), 1.0)
    assert _metric("mxnet_resilience_retries_total") == before + 2


def test_dist_kvstore_retry_exhaustion_raises():
    kv = mx.kv.create("dist_tpu_sync")
    kv._retry = Retry(max_retries=1, backoff_s=0.001,
                      site="kvstore.allreduce")
    kv.init(1, mx.nd.zeros((2,)))
    chaos.inject("kvstore.allreduce", kind="transient", times=0)  # unbounded
    with pytest.raises(RetryExhaustedError):
        kv.push(1, mx.nd.ones((2,)))


def test_dist_barrier_chaos_site_and_timeout_message(monkeypatch):
    kv = mx.kv.create("dist_tpu_sync")
    # armed fault at the named site fires from barrier()
    chaos.inject("dist.barrier", kind="fatal", times=1)
    with pytest.raises(ChaosWorkerDeath):
        kv.barrier()
    chaos.clear()
    # a deadline expiry surfaces as KVStoreTimeoutError naming the rank
    # set a peer could be missing from (simulated multi-process)
    import jax
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        kv, "_allreduce",
        lambda arr: (_ for _ in ()).throw(KVStoreTimeoutError("deadline")))
    with pytest.raises(KVStoreTimeoutError, match=r"rank 0 .* ranks \[1\]"):
        kv.barrier()


def test_dist_bringup_timeout_names_rank(monkeypatch):
    """_ensure_dist with an unreachable coordinator must raise a clear
    KVStoreTimeoutError instead of hanging (satellite: _barrier/_ensure_dist
    hanging forever when a peer never arrives)."""
    import jax

    def fake_initialize(**kwargs):
        assert kwargs.get("initialization_timeout") == 2
        raise RuntimeError("rendezvous timed out waiting for peers")

    monkeypatch.setenv("MXNET_DIST_COORDINATOR", "127.0.0.1:1")
    monkeypatch.setenv("MXNET_DIST_NUM_WORKERS", "2")
    monkeypatch.setenv("MXNET_DIST_RANK", "0")
    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    kv = mx.kv.create("dist_tpu_sync")
    kv._deadline = Deadline(timeout_s=2, site="kvstore.allreduce")
    with pytest.raises(KVStoreTimeoutError, match="rank 0 .* 2 workers"):
        kv._ensure_dist()


def test_fused_bucket_failure_falls_back_per_key(monkeypatch):
    """Graceful degradation: a failing fused bucket replays per-key with
    the same result."""
    from mxnet_tpu.kvstore import fusion

    kv = mx.kv.create("local")
    kv.init([0, 1], [mx.nd.zeros((4,)), mx.nd.zeros((3,))])
    vals = [[mx.nd.ones((4,)), mx.nd.ones((4,)) * 2],
            [mx.nd.ones((3,)) * 3, mx.nd.ones((3,)) * 4]]
    outs = [mx.nd.zeros((4,)), mx.nd.zeros((3,))]

    def boom(self, bucket, arrays):
        raise RuntimeError("bucket executable failed")

    monkeypatch.setattr(fusion.GradBucketer, "reduce_bucket", boom)
    before = _metric("mxnet_resilience_fallbacks_total")
    with pytest.warns(UserWarning, match="falling back to per-key"):
        kv.pushpull_list([0, 1], vals, outs)
    np.testing.assert_allclose(outs[0].asnumpy(), 3.0)  # 1 + 2
    np.testing.assert_allclose(outs[1].asnumpy(), 7.0)  # 3 + 4
    # one degradation EVENT (the bucket); per-key detail rides the fused
    # fallback-keys counter
    assert _metric("mxnet_resilience_fallbacks_total") == before + 1
    assert _metric("mxnet_kvstore_fused_fallback_keys_total") >= 2


# ---------------------------------------------------------------------------
# DataLoader degradation
# ---------------------------------------------------------------------------

class _ArangeDataset(gluon.data.Dataset):
    def __init__(self, n=8):
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        return mx.nd.array(np.full((2,), i, np.float32))


def _batch_values(loader):
    return [b.asnumpy()[:, 0].tolist() for b in loader]


def test_dataloader_transient_fault_refetches_in_process():
    loader = gluon.data.DataLoader(_ArangeDataset(8), batch_size=2,
                                   num_workers=2, timeout=30)
    before = _metric("mxnet_resilience_fallbacks_total")
    chaos.inject("dataloader.fetch", kind="transient", times=1)
    with pytest.warns(UserWarning, match="refetched in-process"):
        vals = _batch_values(loader)
    assert vals == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert _metric("mxnet_resilience_fallbacks_total") == before + 1
    loader._shutdown_pool()


def test_dataloader_degrades_to_single_process(monkeypatch):
    monkeypatch.setenv("MXNET_DATALOADER_RETRIES", "1")
    loader = gluon.data.DataLoader(_ArangeDataset(12), batch_size=2,
                                   num_workers=2, timeout=30)
    assert loader._pool is not None
    # one fault = the full retry budget (retries=1): absorbed in-process,
    # then the loader degrades permanently to single-process loading
    chaos.inject("dataloader.fetch", kind="transient", times=1)
    with pytest.warns(UserWarning):
        vals = _batch_values(loader)
    # order and values survive the degradation, and the pool is gone
    assert vals == [[0, 1], [2, 3], [4, 5], [6, 7], [8, 9], [10, 11]]
    assert loader._pool is None


class _WorkerKillerDataset(gluon.data.Dataset):
    """__getitem__(0) kills the WORKER process (never the parent)."""

    def __init__(self, n=6):
        self._n = n
        self._parent = os.getpid()

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        if i == 0 and os.getpid() != self._parent:
            os._exit(1)  # real worker death
        return mx.nd.array(np.full((2,), i, np.float32))


@pytest.mark.slow
def test_dataloader_survives_real_worker_death():
    loader = gluon.data.DataLoader(_WorkerKillerDataset(6), batch_size=2,
                                   num_workers=1, timeout=3)
    with pytest.warns(UserWarning, match="refetched in-process"):
        vals = _batch_values(loader)
    assert vals == [[0, 1], [2, 3], [4, 5]]
    loader._shutdown_pool()


# ---------------------------------------------------------------------------
# checkpoint atomicity + SIGTERM + elastic resume
# ---------------------------------------------------------------------------

def test_killed_save_is_invisible_and_replayable(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path / "ck"), max_to_keep=5)
    mgr.save(0, extra={"x": mx.nd.array([1.0])})
    chaos.inject("checkpoint.save", kind="fatal", times=1)
    with pytest.raises(ChaosWorkerDeath):
        mgr.save(1, extra={"x": mx.nd.array([2.0])})
    chaos.clear()
    # the half-committed step is invisible...
    assert mgr.latest_step() == 0
    assert 1 in mgr.all_steps()  # ...even though its data is on disk
    # ...and the replayed save lands over the orphan
    mgr.save(1, extra={"x": mx.nd.array([2.5])})
    step, extra = mgr.restore()
    assert step == 1
    assert float(extra["x"].asnumpy()[0]) == 2.5


def _make_net_trainer(kvstore=None, lr=0.05):
    mx.random.seed(7)
    net = gluon.nn.Dense(4, in_units=6, prefix="net_")
    net.initialize(mx.initializer.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": lr},
                       kvstore=kvstore if kvstore is not None else "device")
    return net, tr


def _step(net, tr, x, y, lossf):
    with autograd.record():
        loss = lossf(net(x), y)
    loss.backward()
    tr.step(x.shape[0])
    return float(loss.mean().asnumpy())


def test_sigterm_triggers_emergency_save_and_clean_stop(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    r = np.random.RandomState(3)
    X = mx.nd.array(r.randn(8, 6).astype(np.float32))
    Y = mx.nd.array(r.randint(0, 4, (8,)))
    net, tr = _make_net_trainer()
    ckdir = str(tmp_path / "sig")

    def run(step):
        _step(net, tr, X, Y, lossf)
        if step == 2:
            os.kill(os.getpid(), signal.SIGTERM)  # preemption notice
        return step < 50  # would run long — SIGTERM must stop it

    prev_handler = signal.getsignal(signal.SIGTERM)
    with pytest.warns(UserWarning, match="SIGTERM"):
        last = mx.checkpoint.auto_resume(run, ckdir, net=net, trainer=tr,
                                         save_every=10)
    assert last == 2  # stopped at the preempted step, not 50
    mgr = mx.checkpoint.CheckpointManager(ckdir)
    assert mgr.latest_step() == 2  # emergency save happened off-cadence
    # prior SIGTERM disposition restored after auto_resume (SIG_DFL
    # historically; the flight recorder's chaining dump handler since
    # ISSUE 10 armed it at import)
    assert signal.getsignal(signal.SIGTERM) == prev_handler


def test_sigterm_during_fault_stops_without_replay(tmp_path):
    """Preemption + a failing step (peers already gone) must stop at the
    last checkpoint instead of replaying into a wedged collective."""
    pytest.importorskip("orbax.checkpoint")
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    X, Y = mx.nd.ones((4, 6)), mx.nd.array(np.zeros(4))
    net, tr = _make_net_trainer()

    def run(step):
        if step == 0:
            _step(net, tr, X, Y, lossf)
            return True  # step 0 completes and checkpoints
        os.kill(os.getpid(), signal.SIGTERM)  # preemption lands...
        raise RuntimeError("collective died during preemption")

    with pytest.warns(UserWarning, match="without replay"):
        last = mx.checkpoint.auto_resume(run, str(tmp_path / "sf"), net=net,
                                         trainer=tr, save_every=1)
    assert last == 0  # stopped at the checkpointed step, no replay loop


def test_auto_resume_restart_policy_replays_from_last_good(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    r = np.random.RandomState(1)
    X = mx.nd.array(r.randn(8, 6).astype(np.float32))
    Y = mx.nd.array(r.randint(0, 4, (8,)))
    net, tr = _make_net_trainer()
    steps_run = []

    def run(step):
        if step == 2 and steps_run.count(2) == 0:
            steps_run.append(step)
            raise RuntimeError("simulated worker fault")
        steps_run.append(step)
        _step(net, tr, X, Y, lossf)
        return step < 3

    before = _metric("mxnet_resilience_resumes_total")
    with pytest.warns(UserWarning, match="resumed from checkpoint step 1"):
        last = mx.checkpoint.auto_resume(run, str(tmp_path / "rs"), net=net,
                                         trainer=tr, save_every=1)
    assert last == 3
    assert steps_run == [0, 1, 2, 2, 3]  # step 2 replayed after the fault
    assert _metric("mxnet_resilience_resumes_total") == before + 1


def test_auto_resume_fault_before_first_checkpoint_reraises(tmp_path):
    pytest.importorskip("orbax.checkpoint")

    def run(step):
        raise RuntimeError("dead on arrival")

    with pytest.raises(RuntimeError, match="dead on arrival"):
        mx.checkpoint.auto_resume(run, str(tmp_path / "doa"))


def test_auto_resume_restarts_bounded(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    net, tr = _make_net_trainer()
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    X, Y = mx.nd.ones((4, 6)), mx.nd.array(np.zeros(4))
    calls = []

    def run(step):
        if step == 0 and not calls:
            calls.append("ok")
            _step(net, tr, X, Y, lossf)
            return True
        raise RuntimeError("permanent fault")

    with pytest.raises(RuntimeError, match="permanent fault"), \
            pytest.warns(UserWarning):
        mx.checkpoint.auto_resume(run, str(tmp_path / "bd"), net=net,
                                  trainer=tr, save_every=1, max_restarts=2)


# ---------------------------------------------------------------------------
# acceptance: chaos end-to-end
# ---------------------------------------------------------------------------

def test_chaos_e2e_mid_run_fault_resumes_bit_identical(tmp_path):
    """ISSUE 3 acceptance: a Gluon train loop with an injected mid-run
    worker fault resumes via auto_resume from the last atomic checkpoint
    and reaches parameters BIT-identical to an uninterrupted run with the
    same RNG seed; injected transient kvstore faults are absorbed by
    retry; every blocking path is deadline-bounded (no hangs)."""
    pytest.importorskip("orbax.checkpoint")
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    r = np.random.RandomState(0)
    X = mx.nd.array(r.randn(8, 6).astype(np.float32))
    Y = mx.nd.array(r.randint(0, 4, (8,)))
    total = 6

    def make_state():
        kv = mx.kv.create("dist_tpu_sync")
        kv.set_bucket_size(0)  # per-key path → every push crosses the
        kv._retry.backoff_s = 0.001  # kvstore.allreduce chaos site
        return _make_net_trainer(kvstore=kv)

    def params_of(net):
        return {k: p.data().asnumpy().copy()
                for k, p in net.collect_params().items()}

    # uninterrupted reference run
    net_r, tr_r = make_state()
    for _ in range(total):
        _step(net_r, tr_r, X, Y, lossf)
    ref = params_of(net_r)

    # chaos run: transient kvstore faults early + a fatal worker fault
    # mid-run (fires inside Trainer.step on the 4th step)
    retries_before = _metric("mxnet_resilience_retries_total")
    chaos.inject("kvstore.allreduce", kind="transient", times=2)
    chaos.inject("trainer.step", kind="fatal", times=1, after=3)
    net_c, tr_c = make_state()

    def run(step):
        _step(net_c, tr_c, X, Y, lossf)
        return step < total - 1

    with pytest.warns(UserWarning, match="resumed from checkpoint step 2"):
        last = mx.checkpoint.auto_resume(run, str(tmp_path / "e2e"),
                                         net=net_c, trainer=tr_c,
                                         save_every=1)
    assert last == total - 1
    assert _metric("mxnet_resilience_retries_total") > retries_before
    got = params_of(net_c)
    assert set(got) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)
