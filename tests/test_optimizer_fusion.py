"""Flat-buffer fused optimizer tests (ISSUE 5: optimizer_fusion).

The contract under test: with MXNET_OPTIMIZER_FUSED=1 (the default),
adam/sgd updates run as ONE donated jitted dispatch per dtype bucket and
are **bitwise identical** to the per-param path — across optimizers,
multi-precision, mixed dtypes, multi-replica, per-param lr/wd
multipliers, checkpoint resume, the kvstore flat-gradient handoff, and
TrainStep's traced update — with per-key fallback for sparse params and
loss-scale overflow skips, zero steady-state retraces, and a dispatch
count equal to the bucket count.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, optimizer_fusion as fus
from mxnet_tpu.gluon import utils as gutils


@pytest.fixture(autouse=True)
def _reset_fusion(monkeypatch):
    """Every test starts from the default knobs and a clean plan cache."""
    monkeypatch.delenv("MXNET_OPTIMIZER_FUSED", raising=False)
    monkeypatch.delenv("MXNET_OPTIMIZER_BUCKET_MB", raising=False)
    fus.reset()
    yield
    fus.reset()


def _mlp(n_layers=4, units=16, seed=7, dtype=None):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        for _ in range(n_layers):
            net.add(gluon.nn.Dense(units, activation="relu", in_units=units))
    net.initialize(mx.initializer.Xavier())
    if dtype is not None:
        net.cast(dtype)
    return net


def _params_np(net):
    return {k.split("_", 1)[-1]: v.data().asnumpy()
            for k, v in net.collect_params().items()}


def _train(fused, opt_name, opt_kw, steps=6, dtype=None, mp=False,
           lr_mult=False, monkeypatch=None, net_fn=_mlp):
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1" if fused else "0")
    fus.reset()
    net = net_fn(dtype=dtype)
    kw = dict(opt_kw)
    kw["multi_precision"] = mp
    if not fused:
        kw["aggregate_num"] = 1    # true per-param baseline
    tr = gluon.Trainer(net.collect_params(), opt_name, kw)
    if lr_mult:
        for k, p in net.collect_params().items():
            p.lr_mult = 0.5 if k.endswith("bias") else 1.5
            p.wd_mult = 0.0 if k.endswith("bias") else 2.0
    lf = gluon.loss.L2Loss()
    r = np.random.RandomState(3)
    x = mx.nd.array(r.randn(4, 16).astype(np.float32))
    y = mx.nd.array(r.randn(4, 16).astype(np.float32))
    if dtype is not None:
        x, y = x.astype(dtype), y.astype(dtype)
    for _ in range(steps):
        with autograd.record():
            loss = lf(net(x), y)
        loss.backward()
        tr.step(4)
    return _params_np(net), tr


def _assert_bitwise(a, b, msg=""):
    assert a.keys() == b.keys()
    for k in a:
        assert a[k].tobytes() == b[k].tobytes(), \
            f"{msg} param {k}: max |d| = " \
            f"{np.abs(a[k].astype(np.float64) - b[k].astype(np.float64)).max()}"


CASES = [
    ("adam", {"learning_rate": 1e-3, "wd": 0.01}, None, False, False),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 0.01},
     None, False, False),
    ("sgd", {"learning_rate": 0.05}, None, False, False),
    ("sgd", {"learning_rate": 0.05, "clip_gradient": 0.1}, None, False,
     False),
    ("adam", {"learning_rate": 1e-3, "wd": 0.01}, None, False, True),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 0.01},
     None, False, True),
]


@pytest.mark.parametrize("opt_name,kw,dtype,mp,lr_mult", CASES)
def test_fused_bit_identical_to_per_param(opt_name, kw, dtype, mp, lr_mult,
                                          monkeypatch):
    a, _ = _train(False, opt_name, kw, dtype=dtype, mp=mp, lr_mult=lr_mult,
                  monkeypatch=monkeypatch)
    b, _ = _train(True, opt_name, kw, dtype=dtype, mp=mp, lr_mult=lr_mult,
                  monkeypatch=monkeypatch)
    _assert_bitwise(a, b, f"{opt_name} {kw}")


MP_CASES = [
    ("adam", {"learning_rate": 1e-2, "wd": 0.01}, True),
    ("adam", {"learning_rate": 1e-2}, False),   # half states, no masters
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}, True),
    ("sgd", {"learning_rate": 0.05}, True),     # mp + stateless sgd
]


@pytest.mark.parametrize("opt_name,kw,mp", MP_CASES)
def test_fused_bit_identical_bf16(opt_name, kw, mp, monkeypatch):
    import ml_dtypes
    a, _ = _train(False, opt_name, kw, dtype=ml_dtypes.bfloat16, mp=mp,
                  monkeypatch=monkeypatch)
    b, _ = _train(True, opt_name, kw, dtype=ml_dtypes.bfloat16, mp=mp,
                  monkeypatch=monkeypatch)
    _assert_bitwise(a, b, f"bf16 {opt_name} mp={mp}")


def _mixed_net(dtype=None, seed=7):  # noqa: ARG001 — dtype fixed per layer
    """Two dtypes in one net → two buckets per step (mixed-dtype case)."""
    import ml_dtypes
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu", in_units=16))
        half = gluon.nn.Dense(16, activation="relu", in_units=16)
        net.add(half)
        net.add(gluon.nn.Dense(16, in_units=16))
    net.initialize(mx.initializer.Xavier())
    half.cast(ml_dtypes.bfloat16)
    return net


def test_fused_bit_identical_mixed_dtypes(monkeypatch):
    """bf16 + f32 params in one Trainer split into per-dtype buckets and
    still match the per-param path bit-for-bit (mp masters for the half
    bucket only)."""
    kw = {"learning_rate": 1e-2, "wd": 0.01}
    a, _ = _train(False, "adam", kw, mp=True, monkeypatch=monkeypatch,
                  net_fn=_mixed_net)
    b, tr = _train(True, "adam", kw, mp=True, monkeypatch=monkeypatch,
                   net_fn=_mixed_net)
    _assert_bitwise(a, b, "mixed dtypes")
    sig = tuple((tuple(p.data().shape), str(p.data().dtype), 1)
                for p in tr._params)
    assert len(fus.planner().plan(sig)) == 2  # one bucket per dtype


def test_fused_multi_replica_bit_identical(monkeypatch):
    """2 device replicas through kvstore 'device': the fused path consumes
    the flat reduced buckets straight off the fused allreduce
    (pushpull_flat) and every replica's weights stay bit-identical to
    the per-key path."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")

    def run(fused):
        monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1" if fused else "0")
        fus.reset()
        ctxs = [mx.cpu(0), mx.cpu(1)]
        mx.random.seed(7)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            for _ in range(3):
                net.add(gluon.nn.Dense(16, activation="relu", in_units=16))
        net.initialize(mx.initializer.Xavier(), ctx=ctxs)
        kw = {"learning_rate": 1e-3, "wd": 0.01}
        if not fused:
            kw["aggregate_num"] = 1
        tr = gluon.Trainer(net.collect_params(), "adam", kw,
                           kvstore="device")
        lf = gluon.loss.L2Loss()
        r = np.random.RandomState(3)
        X = mx.nd.array(r.randn(8, 16).astype(np.float32))
        Y = mx.nd.array(r.randn(8, 16).astype(np.float32))
        for _ in range(4):
            xs = gutils.split_and_load(X, ctxs)
            ys = gutils.split_and_load(Y, ctxs)
            with autograd.record():
                losses = [lf(net(x), y) for x, y in zip(xs, ys)]
            autograd.backward(losses)
            tr.step(8)
        return {(k.split("_", 1)[-1], j): d.asnumpy()
                for k, p in net.collect_params().items()
                for j, d in enumerate(p.list_data())}

    a, b = run(False), run(True)
    assert a.keys() == b.keys()
    for k in a:
        assert a[k].tobytes() == b[k].tobytes(), k


def test_flat_handoff_feeds_optimizer_directly(monkeypatch):
    """When the store has a cross-process wire step (_fused_needs_flat —
    simulated here on the local store, whose _allreduce_flat is the
    identity) the reduced gradients stay FLAT end to end and feed the
    fused optimizer directly, bitwise equal to the per-key path."""
    from mxnet_tpu import telemetry

    def run(fused, flat):
        monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1" if fused else "0")
        fus.reset()
        net = _mlp()
        kw = {"learning_rate": 1e-3, "wd": 0.01}
        if not fused:
            kw["aggregate_num"] = 1
        kv = mx.kv.create("local")
        if flat:
            kv._fused_needs_flat = lambda: True  # the dist condition
        tr = gluon.Trainer(net.collect_params(), "adam", kw, kvstore=kv)
        lf = gluon.loss.L2Loss()
        r = np.random.RandomState(3)
        x = mx.nd.array(r.randn(4, 16).astype(np.float32))
        y = mx.nd.array(r.randn(4, 16).astype(np.float32))
        for _ in range(3):
            with autograd.record():
                loss = lf(net(x), y)
            loss.backward()
            tr.step(4)
        return _params_np(net), tr

    a, _ = run(False, False)
    telemetry.enable()
    try:
        u0 = telemetry.counter("mxnet_optimizer_fused_updates_total").value
        b, tr = run(True, True)
        assert telemetry.counter(
            "mxnet_optimizer_fused_updates_total").value - u0 == 3
    finally:
        telemetry.disable()
    _assert_bitwise(a, b, "flat handoff")
    assert tr._flat_handoff is None  # consumed, not leaked
    # in-process stores skip the handoff (flat buffer = pure copy
    # overhead there): pushpull_flat declines and per-param fusion runs
    c, _ = run(True, False)
    _assert_bitwise(a, c, "in-process per-param fused")
    kv = mx.kv.create("local")
    assert kv.pushpull_flat([0], [mx.nd.ones((2,))],
                            [mx.nd.ones((2,))]) is None


def test_sparse_param_falls_back_per_key(monkeypatch):
    """A row_sparse-grad embedding rides the per-key path while the dense
    params stay fused — and the result matches per-param bitwise."""
    def run(fused):
        monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1" if fused else "0")
        fus.reset()
        mx.random.seed(7)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            emb = gluon.nn.Embedding(12, 8, sparse_grad=True)
            net.add(emb)
            net.add(gluon.nn.Dense(8, flatten=False, in_units=8))
        net.initialize(mx.initializer.Xavier())
        kw = {"learning_rate": 0.05}
        if not fused:
            kw["aggregate_num"] = 1
        tr = gluon.Trainer(net.collect_params(), "sgd", kw)
        r = np.random.RandomState(5)
        idx = mx.nd.array(r.randint(0, 12, (4, 3)).astype(np.float32))
        y = mx.nd.array(r.randn(4, 3, 8).astype(np.float32))
        lf = gluon.loss.L2Loss()
        for _ in range(3):
            with autograd.record():
                loss = lf(net(idx), y)
            loss.backward()
            tr.step(4)
        return _params_np(net)

    a, b = run(False), run(True)
    _assert_bitwise(a, b, "sparse fallback")


def test_loss_scale_overflow_skips_fused_update(monkeypatch):
    """amp dynamic-loss-scale overflow must skip the whole step (fused
    path included) and back the scaler off — reference amp contract."""
    from mxnet_tpu import amp
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")
    amp.init(target_dtype="float16")
    try:
        net = gluon.nn.Dense(2)
        net.initialize()
        x = mx.nd.ones((2, 3))
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.5})
        amp.init_trainer(tr)
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        w = list(net.collect_params().values())[0]
        g = w.list_grad()[0]
        g[:] = mx.nd.array(np.full(g.shape, np.inf, np.float32))
        before = w.data().asnumpy().copy()
        scale0 = tr._amp_loss_scaler.loss_scale
        tr.step(1)
        assert np.array_equal(w.data().asnumpy(), before)  # skipped
        assert tr._amp_loss_scaler.loss_scale == scale0 / 2
    finally:
        amp.off()


def test_update_on_kvstore_keeps_per_key_path(monkeypatch):
    """update_on_kvstore owns the optimizer inside push — the fused layer
    must stay out of the way entirely."""
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")
    net = _mlp(n_layers=2)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05},
                       kvstore="local", update_on_kvstore=True)
    assert tr._fused_kind() is None
    lf = gluon.loss.L2Loss()
    r = np.random.RandomState(3)
    x = mx.nd.array(r.randn(4, 16).astype(np.float32))
    y = mx.nd.array(r.randn(4, 16).astype(np.float32))
    before = _params_np(net)
    with autograd.record():
        loss = lf(net(x), y)
    loss.backward()
    tr.step(4)
    after = _params_np(net)
    assert any(not np.array_equal(before[k], after[k]) for k in before)


def test_unsupported_optimizer_keeps_legacy_path(monkeypatch):
    """Optimizers outside {Adam, SGD} (exact types) never enter the fused
    layer — subclass math must not be silently replaced."""
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")
    assert fus.supported_kind(mx.optimizer.Adam()) == "adam"
    assert fus.supported_kind(mx.optimizer.SGD()) == "sgd"
    assert fus.supported_kind(mx.optimizer.AdamW()) is None
    assert fus.supported_kind(mx.optimizer.LARS()) is None
    net = _mlp(n_layers=2)
    tr = gluon.Trainer(net.collect_params(), "lamb", {"learning_rate": 1e-3})
    assert tr._fused_kind() is None


def test_sgd_subclass_keeps_legacy_update_multi(monkeypatch):
    """Review regression: an SGD subclass inherits update_multi; the
    fused gate must reject it (exact types only) and the legacy
    multi_sgd aggregation path must carry the step instead of raising."""
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")

    class MySGD(mx.optimizer.SGD):
        pass

    net = _mlp(n_layers=2)
    tr = gluon.Trainer(net.collect_params(),
                       MySGD(learning_rate=0.05, momentum=0.9))
    assert tr._fused_kind() is None
    lf = gluon.loss.L2Loss()
    r = np.random.RandomState(3)
    x = mx.nd.array(r.randn(4, 16).astype(np.float32))
    y = mx.nd.array(r.randn(4, 16).astype(np.float32))
    before = _params_np(net)
    with autograd.record():
        loss = lf(net(x), y)
    loss.backward()
    tr.step(4)   # aggregation path (aggregate_num default 4), no raise
    after = _params_np(net)
    assert any(not np.array_equal(before[k], after[k]) for k in before)


def test_bucket_mb_zero_disables_every_entry(monkeypatch):
    """Review regression: MXNET_OPTIMIZER_BUCKET_MB<=0 must disable
    fusion through update_multi too, not only through Trainer's gate."""
    from mxnet_tpu import telemetry
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")
    monkeypatch.setenv("MXNET_OPTIMIZER_BUCKET_MB", "0")
    assert not fus.fusion_active(mx.optimizer.SGD())
    telemetry.enable()
    try:
        c0 = telemetry.counter("mxnet_optimizer_fused_buckets_total").value
        opt = mx.optimizer.SGD(learning_rate=0.1)
        r = np.random.RandomState(0)
        ws = [nd.array(r.standard_normal((4,)).astype(np.float32))
              for _ in range(2)]
        gs = [nd.array(r.standard_normal((4,)).astype(np.float32))
              for _ in range(2)]
        opt.update_multi([0, 1], ws, gs, [None, None])
        assert telemetry.counter(
            "mxnet_optimizer_fused_buckets_total").value == c0
    finally:
        telemetry.disable()


def test_bucket_mb_change_replans(monkeypatch):
    """Review regression: flipping MXNET_OPTIMIZER_BUCKET_MB at runtime
    must rebuild the planner (the on-chip sweep recipe relies on it)."""
    monkeypatch.setenv("MXNET_OPTIMIZER_BUCKET_MB", "25")
    sig = (((64, 64), "float32", 1),) * 4
    assert len(fus.planner().plan(sig)) == 1
    monkeypatch.setenv("MXNET_OPTIMIZER_BUCKET_MB", "0.017")  # ~1 tensor
    assert len(fus.planner().plan(sig)) == 4


def test_knob_off_restores_per_param(monkeypatch):
    """MXNET_OPTIMIZER_FUSED=0 must leave zero fused telemetry behind."""
    from mxnet_tpu import telemetry
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "0")
    telemetry.enable()
    try:
        c0 = telemetry.counter("mxnet_optimizer_fused_buckets_total").value
        _train(False, "adam", {"learning_rate": 1e-3}, steps=2,
               monkeypatch=monkeypatch)
        assert telemetry.counter(
            "mxnet_optimizer_fused_buckets_total").value == c0
    finally:
        telemetry.disable()


def test_steady_state_dispatch_count_and_no_retrace(monkeypatch):
    """The acceptance invariant: at steady state Trainer.step dispatches
    exactly ONE fused call per bucket (telemetry-counted), compiles
    nothing (analysis.runtime.no_retrace), and the executable cache
    stops growing after the first step."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.analysis import runtime as rt
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")
    monkeypatch.setenv("MXNET_OPTIMIZER_BUCKET_MB", "0.002")  # tiny → >1 bucket
    fus.reset()
    net = _mlp()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    lf = gluon.loss.L2Loss()
    r = np.random.RandomState(3)
    x = mx.nd.array(r.randn(4, 16).astype(np.float32))
    y = mx.nd.array(r.randn(4, 16).astype(np.float32))

    def step():
        with autograd.record():
            loss = lf(net(x), y)
        loss.backward()
        tr.step(4)

    step()   # warm-up: plans buckets, builds executables
    step()
    builds = fus.exec_builds()
    sig = tuple((tuple(p.data().shape), str(p.data().dtype), 1)
                for p in tr._params)
    n_buckets = len(fus.planner().plan(sig))
    assert n_buckets > 1   # the tiny bound actually split the params
    telemetry.enable()
    try:
        c0 = telemetry.counter("mxnet_optimizer_fused_buckets_total").value
        u0 = telemetry.counter("mxnet_optimizer_fused_updates_total").value
        with rt.no_retrace():
            step()
        assert telemetry.counter(
            "mxnet_optimizer_fused_buckets_total").value - c0 == n_buckets
        assert telemetry.counter(
            "mxnet_optimizer_fused_updates_total").value - u0 == 1
    finally:
        telemetry.disable()
    assert fus.exec_builds() == builds   # retrace-count invariant


def test_save_load_states_resumes_bit_identically(monkeypatch, tmp_path):
    """Checkpoint round trip through the fused path: states stay in the
    per-param format and a resumed run continues bit-identically."""
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")

    def run(resume_at=None):
        fus.reset()
        net = _mlp()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-2, "wd": 0.01})
        lf = gluon.loss.L2Loss()
        r = np.random.RandomState(3)
        x = mx.nd.array(r.randn(4, 16).astype(np.float32))
        y = mx.nd.array(r.randn(4, 16).astype(np.float32))
        for s in range(6):
            if s == resume_at:
                f = str(tmp_path / "states")
                tr.save_states(f)
                tr.load_states(f)
            with autograd.record():
                loss = lf(net(x), y)
            loss.backward()
            tr.step(4)
        return _params_np(net)

    _assert_bitwise(run(None), run(resume_at=3), "resume")


def test_update_multi_api_routes_fused(monkeypatch):
    """Optimizer.update_multi (adam) is the fused entry: one call updates
    N params bitwise like N update_multi_precision calls."""
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")
    r = np.random.RandomState(0)
    shapes = [(8, 8), (8,), (4, 8)]

    def mk():
        ws = [nd.array(r2.standard_normal(s).astype(np.float32))
              for s in shapes]
        return ws

    r2 = np.random.RandomState(0)
    ws_a = [nd.array(r2.standard_normal(s).astype(np.float32)) for s in shapes]
    r2 = np.random.RandomState(0)
    ws_b = [nd.array(r2.standard_normal(s).astype(np.float32)) for s in shapes]
    gs = [nd.array(r.standard_normal(s).astype(np.float32)) for s in shapes]

    opt_a = mx.optimizer.Adam(learning_rate=1e-2, wd=0.01)
    sts_a = [opt_a.create_state_multi_precision(i, w)
             for i, w in enumerate(ws_a)]
    for i in range(3):
        opt_a.update_multi_precision(i, ws_a[i], gs[i], sts_a[i])

    opt_b = mx.optimizer.Adam(learning_rate=1e-2, wd=0.01)
    sts_b = [opt_b.create_state_multi_precision(i, w)
             for i, w in enumerate(ws_b)]
    opt_b.update_multi([0, 1, 2], ws_b, gs, sts_b)

    for i in range(3):
        assert ws_a[i].asnumpy().tobytes() == ws_b[i].asnumpy().tobytes()
        for st_a, st_b in zip(sts_a[i], sts_b[i]):
            assert st_a.asnumpy().tobytes() == st_b.asnumpy().tobytes()


def test_trainstep_fused_matches_per_param(monkeypatch):
    """parallel.TrainStep with the fused traced update reproduces the
    per-param traced step (same losses, same final params)."""
    from mxnet_tpu import parallel
    import jax

    def run(fused):
        monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1" if fused else "0")
        fus.reset()
        mx.random.seed(11)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(32, activation="relu", in_units=16))
            net.add(gluon.nn.Dense(16, in_units=32))
        net.initialize(mx.initializer.Xavier())
        mesh = parallel.make_mesh(shape=(1,), devices=jax.devices()[:1])
        step = parallel.TrainStep(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                                  mx.optimizer.Adam(learning_rate=1e-3),
                                  mesh=mesh)
        r = np.random.RandomState(5)
        x = nd.array(r.randn(8, 16).astype(np.float32))
        y = nd.array(r.randn(8, 16).astype(np.float32))
        losses = [float(step(x, y).asscalar()) for _ in range(3)]
        assert (step._fused is not None) == fused
        return losses, _params_np(net)

    la, pa = run(False)
    lb, pb = run(True)
    np.testing.assert_allclose(la, lb, rtol=1e-6)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


def test_donation_invalidates_raw_refs(monkeypatch):
    """The documented donation invariant: raw jax buffers captured before
    a fused step are dead after it; the NDArray handles stay valid."""
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "1")
    fus.reset()
    net = _mlp(n_layers=2)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    lf = gluon.loss.L2Loss()
    x = mx.nd.array(np.ones((2, 16), np.float32))
    y = mx.nd.array(np.zeros((2, 16), np.float32))
    with autograd.record():
        loss = lf(net(x), y)
    loss.backward()
    p = list(net.collect_params().values())[0]
    raw = p.data()._data          # raw jax.Array alias
    tr.step(2)
    assert raw.is_deleted()       # donated
    assert np.isfinite(p.data().asnumpy()).all()  # handle still live
