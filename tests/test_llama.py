"""Llama stretch-config tests (BASELINE config 5): architecture
correctness + TP-sharded train step over a dp×tp mesh."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, parallel
from mxnet_tpu.gluon.model_zoo import llama


def _tiny(vocab=101):
    net = llama.llama_model("llama_tiny", vocab_size=vocab)
    net.initialize(mx.initializer.Normal(0.02))
    return net


def test_forward_shape_and_causality(seeded):
    net = _tiny()
    toks = mx.nd.array(np.random.RandomState(0).randint(0, 101, (2, 16)))
    out = net(toks)
    assert out.shape == (2, 16, 101)
    mutated = toks.asnumpy().copy()
    mutated[:, 10:] = 7
    out2 = net(mx.nd.array(mutated))
    # causal: earlier logits are independent of later tokens
    np.testing.assert_allclose(out.asnumpy()[:, :10],
                               out2.asnumpy()[:, :10], atol=1e-5)
    assert not np.allclose(out.asnumpy()[:, 10:], out2.asnumpy()[:, 10:])


def test_gqa_head_counts():
    blk = llama.LlamaBlock(64, 172, heads=4, kv_heads=2)
    blk.initialize()
    x = mx.nd.ones((2, 8, 64))
    assert blk(x).shape == (2, 8, 64)
    p = blk.collect_params()
    kw = next(v for k, v in p.items() if k.endswith("k_weight"))
    qw = next(v for k, v in p.items() if k.endswith("q_weight"))
    assert kw.shape[0] == qw.shape[0] // 2  # kv projection half-sized


def test_rmsnorm_matches_reference(seeded):
    norm = llama.RMSNorm(8)
    norm.initialize()
    x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
    out = norm(mx.nd.array(x)).asnumpy()
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_training_reduces_loss(seeded):
    net = _tiny()
    toks = mx.nd.array(np.random.RandomState(0).randint(0, 101, (4, 12)))
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(6):
        with autograd.record():
            logits = net(toks)
            loss = lossf(logits.reshape((-1, 101)),
                         mx.nd.array(toks.asnumpy().reshape(-1)))
        loss.backward()
        tr.step(4)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0]


def test_tp_sharding_annotations():
    net = _tiny()
    llama.apply_tp_shardings(net, axis="tp")
    p = net.collect_params()
    col = next(v for k, v in p.items() if k.endswith("gate_weight"))
    row = next(v for k, v in p.items() if k.endswith("down_weight"))
    emb = next(v for k, v in p.items() if k.endswith("tok_weight"))
    assert col.sharding == ("tp", None)
    assert row.sharding == (None, "tp")
    assert emb.sharding == ("tp", None)


def test_tp_dp_mesh_train_step(seeded):
    """The stretch acceptance: full train step jitted over a dp×tp mesh
    with megatron shardings — the llama analog of dryrun_multichip."""
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    mesh = parallel.DeviceMesh(shape=(2, 2), axis_names=("dp", "tp"),
                               devices=jax.devices()[:4])
    net = llama.llama_model("llama_tiny", vocab_size=64)
    net.initialize(mx.initializer.Normal(0.02))
    llama.apply_tp_shardings(net, axis="tp")

    def loss_fn(logits, labels):
        return mx.nd.softmax_cross_entropy(
            logits.reshape((-1, logits.shape[-1])).astype("float32"),
            labels.reshape((-1,))) / labels.size

    opt = mx.optimizer.Adam(learning_rate=1e-3)
    step = parallel.TrainStep(net, loss_fn, opt, mesh=mesh)
    r = np.random.RandomState(0)
    toks = mx.nd.array(r.randint(0, 64, (8, 16)).astype(np.int32))
    losses = [float(step(toks, toks).asnumpy()) for _ in range(3)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sp_attention_train_step_parity(impl, seeded):
    """Sequence-parallel llama (contrib.sp_att_qkv over a dp×sp mesh)
    reproduces the dense-attention train-step loss exactly — the dryrun
    'sp' lane as a pytest (VERDICT r3 item 4)."""
    from mxnet_tpu import nd
    vocab, seq = 64, 16
    mesh = parallel.DeviceMesh(shape=(2, 4), axis_names=("dp", "sp"))
    r = np.random.RandomState(7)
    toks = r.randint(0, vocab, (4, seq)).astype("int32")
    labs = np.roll(toks, -1, axis=1).astype("int32")

    def loss_fn(o, l):
        return mx.nd.softmax_cross_entropy(
            o.reshape((-1, o.shape[-1])), l.reshape((-1,))) / l.size

    losses = {}
    prev = parallel.current_mesh()
    try:
        for cur_impl, m in (("fused", None), (impl, mesh)):
            parallel.set_mesh(m)
            mx.random.seed(11)
            net = llama.llama_model("llama_tiny", vocab_size=vocab,
                                    attn_impl=cur_impl)
            net.initialize(mx.initializer.Normal(0.05))
            step = parallel.TrainStep(
                net, loss_fn, mx.optimizer.Adam(learning_rate=1e-3),
                mesh=mesh, donate=False)
            losses[cur_impl] = float(step(
                nd.array(toks, dtype="int32"),
                nd.array(labs, dtype="int32")).asscalar())
    finally:
        parallel.set_mesh(prev)
    assert np.isfinite(losses[impl])
    np.testing.assert_allclose(losses[impl], losses["fused"], rtol=2e-4)


def test_sp_att_qkv_no_mesh_fallback(seeded):
    """Without an active mesh the sp op degrades to local attention and
    matches masked_att_qkv (full valid_length, causal)."""
    r = np.random.RandomState(3)
    B, H, L, D = 2, 4, 16, 8
    q = mx.nd.array(r.randn(B, H, L, D).astype("float32"))
    k = mx.nd.array(r.randn(B, H // 2, L, D).astype("float32"))
    v = mx.nd.array(r.randn(B, H // 2, L, D).astype("float32"))
    out_sp = mx.nd.contrib.sp_att_qkv(q, k, v, impl="ring", axis="sp",
                                      num_kv_groups=2, causal=True)
    vl = mx.nd.array(np.full((B,), L, np.float32))
    out_ref = mx.nd.contrib.masked_att_qkv(q, k, v, vl, num_kv_groups=2,
                                           causal=True)
    np.testing.assert_allclose(out_sp.asnumpy(), out_ref.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_llama_remat_parity():
    """MXNET_BACKWARD_DO_MIRROR analog: remat per decoder block gives the
    SAME forward and gradients as the stored-activation path (gluon.utils
    .remat_call underneath — jax.checkpoint recompute in backward)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.model_zoo.llama import LlamaModel

    r = np.random.RandomState(0)
    toks = mx.nd.array(r.randint(0, 64, (2, 16)).astype(np.int32))

    losses, grads = [], []
    for remat in (False, True):
        mx.random.seed(0)
        m = LlamaModel(vocab_size=64, num_layers=2, units=32, hidden=96,
                       heads=4, kv_heads=2, remat=remat,
                       prefix=f"remat{int(remat)}_")
        m.initialize(mx.initializer.Normal(0.05))
        with autograd.record():
            out = m(toks)
            loss = (out.astype("float32") ** 2).mean()
        loss.backward()
        losses.append(float(loss.asnumpy()))
        g = {k.split("_", 1)[1]: p.data().grad.asnumpy().copy()
             for k, p in m.collect_params().items()
             if p.data().grad is not None}
        grads.append(g)
    assert np.allclose(losses[0], losses[1], rtol=1e-5)
    assert set(grads[0]) == set(grads[1]) and len(grads[0]) > 4
    for k in grads[0]:
        np.testing.assert_allclose(grads[0][k], grads[1][k],
                                   rtol=1e-4, atol=1e-6, err_msg=k)


def test_llama_remat_trainstep():
    """The remat path must trace through parallel.TrainStep (the bench
    llama lane's exact mechanism) and match the no-remat loss."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel
    from mxnet_tpu.gluon.model_zoo.llama import LlamaModel

    r = np.random.RandomState(0)
    toks = r.randint(0, 64, (1, 8, 16)).astype(np.int32)
    labs = r.randint(0, 64, (1, 8, 16)).astype(np.int32)

    losses = []
    for remat in (False, True):
        mx.random.seed(0)
        model = LlamaModel(vocab_size=64, num_layers=2, units=32, hidden=96,
                           heads=4, kv_heads=2, remat=remat,
                           prefix=f"ts_remat{int(remat)}_")
        model.initialize(mx.initializer.Normal(0.05))

        def loss_fn(out, labels):
            return mx.nd.softmax_cross_entropy(
                out.reshape((-1, out.shape[-1])).astype("float32"),
                labels.reshape((-1,))) / labels.size

        step = parallel.TrainStep(model, loss_fn,
                                  mx.optimizer.Adam(learning_rate=1e-3),
                                  mesh=parallel.make_mesh())
        out = step.run(nd.array(toks), nd.array(labs))
        losses.append(float(np.asarray(out.asnumpy())[-1]))
    assert np.allclose(losses[0], losses[1], rtol=1e-5), losses
