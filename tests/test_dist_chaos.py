"""n=4 distributed chaos suite (ISSUE 8 satellite / ROADMAP 4): the PR-3
resilience machinery against a REAL 4-process topology — worker death
mid-allreduce, preemption mid-checkpoint — asserting bit-identical
elastic resume.

Flow (one shared checkpoint tree, four launches of
tests/_chaos_dist_worker.py):

 1. ``die-allreduce``: rank 3 chaos-exits inside step 3's gradient
    reduction.  Survivors must exit promptly via the deadline (no hang)
    and nobody commits step 3 — every rank's manifest stays aligned at
    step 2, which is what makes the elastic restart consistent.
 2. ``die-checkpoint``: the restarted job replays step 3 and every rank
    chaos-exits INSIDE step 4's checkpoint save (data written, manifest
    not committed).  The orphaned step-4 directory must stay invisible.
 3. ``clean``: the final restart resumes from the committed step 3,
    replays 4 and 5, and dumps final params.
 4. A separate uninterrupted ``clean`` reference run.

Acceptance: the thrice-killed job's final parameters are BIT-identical
to the uninterrupted run's, on every rank.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # four 4-process jax launches (~2 min)


def _launch(mode, outdir, n=4, timeout=240):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        from launch import launch_local
    finally:
        sys.path.pop(0)
    worker = os.path.join(repo, "tests", "_chaos_dist_worker.py")
    env = {
        "MXNET_TPU_JIT_IMPERATIVE": "1",
        # a dead peer must surface as KVStoreTimeoutError well before the
        # launcher kill — this bound IS the no-hang assertion.  It must
        # also undercut the launcher's 15s straggler grace: a rank still
        # blocked in gloo when the grace expires is SIGKILLed, the one
        # death even the flight recorder cannot observe
        "MXNET_KVSTORE_TIMEOUT_S": "10",
        "MXNET_RESILIENCE_BACKOFF_S": "0.001",
        # observability plane (ISSUE 10): telemetry on with a collection
        # dir + flight-recorder dir, so every death leaves a postmortem
        # and every rank leaves a mergeable telemetry shard
        "MXNET_TELEMETRY": "1",
        "MXNET_TELEMETRY_DIR": os.path.join(outdir, "telemetry"),
        "MXNET_FLIGHTREC_DIR": os.path.join(outdir, "flightrec"),
    }
    t0 = time.monotonic()
    codes = launch_local(n, [sys.executable, worker, mode, outdir],
                         env_extra=env, cpu_devices_per_worker=1,
                         timeout=timeout)
    return codes, time.monotonic() - t0


def _committed_steps(outdir):
    path = os.path.join(outdir, "ckpt", "manifest.json")
    with open(path) as f:
        return sorted(json.load(f)["committed"])


def _finals(outdir, n=4):
    out = {}
    for r in range(n):
        with np.load(os.path.join(outdir, f"final_rank{r}.npz")) as z:
            out[r] = {k: z[k].copy() for k in z.files}
    return out


def test_n4_chaos_death_and_preemption_resume_bit_identical(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    n = 4
    chaotic = str(tmp_path / "chaotic")
    ref = str(tmp_path / "ref")
    os.makedirs(chaotic)
    os.makedirs(ref)

    # 1. worker death mid-allreduce: every rank must exit nonzero and
    #    PROMPTLY (deadline, not launcher kill), with no rank committing
    #    the dying step
    codes, elapsed = _launch("die-allreduce", chaotic)
    assert all(c != 0 for c in codes), codes
    assert elapsed < 180, f"survivors hung {elapsed:.0f}s (deadline broken)"
    assert _committed_steps(chaotic) == [0, 1, 2]  # step 3 never committed

    # ISSUE 10 acceptance: the death left per-rank flight-recorder dumps
    # (rank 3 dumped inside the chaos 'exit', survivors on the blown
    # deadline and/or the unhandled KVStoreTimeoutError), and rank 0 can
    # render ONE merged Chrome trace + ONE merged Prometheus snapshot
    # from the collection dir (dying/raising ranks export their shard
    # through the flight recorder / atexit).
    frdir = os.path.join(chaotic, "flightrec")
    dumps = sorted(os.listdir(frdir))
    dump_ranks = {int(f.split("-")[1][4:]) for f in dumps
                  if f.startswith("flightrec-") and f.endswith(".json")}
    assert dump_ranks == set(range(n)), (dump_ranks, dumps)
    killer = [f for f in dumps if "chaos.exit.kvstore.allreduce" in f]
    assert killer and f"rank{n - 1:05d}" in killer[0]
    with open(os.path.join(frdir, killer[0])) as f:
        rec = json.load(f)
    assert rec["rank"] == n - 1
    assert rec["chaos"]["faults_fired"] >= 1
    assert any(e.get("cat") == "kvstore" for e in rec["spans"])

    from mxnet_tpu.telemetry import aggregate
    teldir = os.path.join(chaotic, "telemetry")
    snaps = aggregate.load_snapshots(teldir)
    assert [s["rank"] for s in snaps] == list(range(n))
    trace = aggregate.merged_chrome_trace(snaps)
    span_pids = {e["pid"] for e in trace["traceEvents"]
                 if e.get("ph") == "X"}
    assert span_pids == set(range(n))
    # merged Prometheus snapshot: every rank's steps 0-2 moved bytes
    # through the collective, so the rank-summed counter must exceed any
    # single rank's (survivors may die on a fast gloo error OR the
    # deadline — either way their shard reached the collection dir)
    prom = aggregate.merged_prometheus(snaps)
    row = [ln for ln in prom.splitlines()
           if ln.startswith("mxnet_kvstore_allreduce_bytes_total")]
    per_rank = [m["value"] for s in snaps for m in s["metrics"]
                if m["name"] == "mxnet_kvstore_allreduce_bytes_total"]
    assert len(per_rank) == n and all(v > 0 for v in per_rank)
    assert float(row[0].split()[1]) == sum(per_rank)

    # 2. elastic restart replays step 3, then preemption mid-checkpoint
    #    at step 4: data written, manifest commit never reached
    codes, _ = _launch("die-checkpoint", chaotic)
    assert all(c != 0 for c in codes), codes
    assert _committed_steps(chaotic)[-1] == 3  # step 4's save is invisible

    # 3. final elastic restart: resumes at 4, finishes, dumps params
    codes, _ = _launch("clean", chaotic)
    assert codes == [0] * n, codes

    # 4. uninterrupted reference
    codes, _ = _launch("clean", ref)
    assert codes == [0] * n, codes

    got, want = _finals(chaotic), _finals(ref)
    for r in range(n):
        assert set(got[r]) == set(want[r])
        for k in want[r]:
            np.testing.assert_array_equal(
                got[r][k], want[r][k],
                err_msg=f"rank {r} param {k} diverged after chaos resume")
        # replicas agree across ranks too (the reduction kept them synced)
        for k in want[0]:
            np.testing.assert_array_equal(got[r][k], got[0][k])
