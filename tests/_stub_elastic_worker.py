"""Stdlib-only stub worker for the FAST elastic-controller tests
(tests/test_elastic.py).  Speaks the documented heartbeat file protocol
and checkpoint-manifest format directly — no jax, no mxnet_tpu import —
so controller spawn/watch/resize/adopt paths run in milliseconds.  Not
collected by pytest (no test_ prefix).

Modes (argv[1]); behavior keys off MXNET_ELASTIC_INCARNATION so one
command covers a whole resize story:

 - ``ok``            — beat running, beat done, exit 0.
 - ``forever``       — beat until killed, or until a ``finish-flag``
   file appears in the cwd (the controller runs workers with
   cwd=workdir), then beat done and exit 0.
 - ``bringup-fail``  — beat phase=failed (the bring-up-timeout surface)
   and exit 1; the controller must restart at the SAME world size.
 - ``resize``        — incarnation 0: the highest rank exits 3 (worker
   death), peers run forever; incarnation 1 (degraded): rank 0 commits
   checkpoint-manifest steps so the controller's regrow probation can
   elapse; incarnation 2+ (regrown): clean completion.
 - ``hang``          — incarnation 0: the highest rank goes silent
   (alive, no beats) — the controller must SIGKILL it on staleness;
   later incarnations complete.
 - ``straggler``     — incarnation 0: every rank beats a crafted
   stepclock summary (rank 1 compute-bound and slow, peers comms-bound)
   and runs forever; the controller must kill rank 1 and resize; later
   incarnations complete.
"""

import json
import os
import sys
import time

RANK = int(os.environ.get("MXNET_DIST_RANK", "0"))
N = int(os.environ.get("MXNET_DIST_NUM_WORKERS", "1"))
INC = int(os.environ.get("MXNET_ELASTIC_INCARNATION", "0"))
HB = os.environ.get("MXNET_ELASTIC_HEARTBEAT_DIR")
BEAT_S = float(os.environ.get("MXNET_ELASTIC_HEARTBEAT_S", "0.1"))


def beat(phase="running", step=None, stepclock=None, error=None):
    if not HB:
        return
    os.makedirs(HB, exist_ok=True)
    rec = {"rank": RANK, "pid": os.getpid(), "time": time.time(),
           "phase": phase, "step": step, "incarnation": INC, "world": N,
           "stepclock": stepclock or {"steps": 0, "verdict": "idle"},
           "error": error}
    path = os.path.join(HB, f"hb-rank{RANK:05d}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)


def write_manifest(steps):
    os.makedirs("ckpt", exist_ok=True)
    tmp = os.path.join("ckpt", f"manifest.json.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump({"committed": steps}, f)
    os.replace(tmp, os.path.join("ckpt", "manifest.json"))


def run_forever(one_beat):
    while True:
        one_beat()
        if os.path.exists("finish-flag"):
            beat("done")
            return 0
        time.sleep(BEAT_S)


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "ok"
    if mode == "ok":
        beat("running", step=0)
        time.sleep(BEAT_S)
        beat("done")
        return 0
    if mode == "forever":
        return run_forever(lambda: beat("running"))
    if mode == "bringup-fail":
        if INC == 0:
            beat("failed", error="bringup-timeout: stub rendezvous")
            return 1
        beat("running")
        beat("done")
        return 0
    if mode == "resize":
        if INC == 0:
            beat("running", step=0)
            if RANK == N - 1:
                time.sleep(2 * BEAT_S)
                return 3                       # worker death mid-job
            return run_forever(lambda: beat("running"))
        if INC == 1:                           # degraded probation
            k = 0
            while True:
                if RANK == 0:
                    write_manifest(list(range(k + 1)))
                    k += 1
                beat("running", step=k)
                if os.path.exists("finish-flag"):
                    beat("done")
                    return 0
                time.sleep(BEAT_S)
        beat("running")                        # regrown world
        time.sleep(BEAT_S)
        beat("done")
        return 0
    if mode == "hang":
        if INC == 0:
            if RANK == N - 1:
                beat("running")
                time.sleep(3600)               # alive but silent
                return 0
            return run_forever(lambda: beat("running"))
        beat("running")
        beat("done")
        return 0
    if mode == "straggler":
        if INC == 0:
            slow = RANK == 1
            sc = {"steps": 8,
                  "verdict": "compute-bound" if slow else "comms-bound",
                  "phases": {"compute": {"median": 0.5 if slow else 0.01}}}
            return run_forever(
                lambda: beat("running", step=8, stepclock=sc))
        beat("running")
        beat("done")
        return 0
    raise SystemExit(f"unknown stub mode {mode!r}")


if __name__ == "__main__":
    sys.exit(main())
