"""Auto-sharder (ISSUE 14): planner determinism, fit/no-fit semantics,
Plan round-trip + TrainStep consumption, microbatched TrainStep
bit-identity/parity, and the slow 8-device OOM-avoidance lane (the
dryrun proof's pytest twin)."""

import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel, autoshard, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn as gnn, loss as gloss
from mxnet_tpu.telemetry import costmodel as cm


def _llama_small_shapes(vocab=64):
    """Shape-only param table (no weights) — the CLI's planning input
    (the shared autoshard.zoo_shapes helper, so tests, CLI, and the
    committed golden can't drift apart)."""
    shapes, family = autoshard.zoo_shapes("llama_small", vocab=vocab)
    assert family == "llama"
    return shapes


# ---------------------------------------------------------------------------
# planner semantics
# ---------------------------------------------------------------------------

def test_infer_family():
    assert autoshard.infer_family(_llama_small_shapes()) == "llama"
    assert autoshard.infer_family(
        ["b_attn_qkv_weight", "b_ffn1_weight"]) == "bert"
    assert autoshard.infer_family(["w", "b"]) is None


def test_unbounded_plan_prefers_pure_dp():
    """With no budget the crossover doctrine keeps the simplest layout:
    pure dp, no microbatching, no remat, replicated rules."""
    p = autoshard.plan(_llama_small_shapes(), global_batch=16,
                       n_devices=8, seq=16)
    assert p.mesh_shape == {"dp": 8}
    assert p.rule_pack is None and p.n_micro == 1 and not p.remat


def test_budget_forces_fsdp_crossover():
    """The 0.4×dp-only budget window (the dryrun proof's) must force a
    model-parallel layout that carries fsdp, picked over same-ways tp
    by the matmul-tile-efficiency term."""
    shapes = _llama_small_shapes()
    dp_only = cm.estimate_memory(shapes, {"dp": 8}, None, batch=16,
                                 seq=16, data_axes=("dp",))
    p = autoshard.plan(shapes, global_batch=16, n_devices=8, seq=16,
                       hbm_budget_bytes=int(dp_only["total_bytes"] * 0.4))
    assert "fsdp" in p.mesh_axes, p
    assert p.rule_pack.endswith("_fsdp")
    assert p.estimate["total_bytes"] <= int(dp_only["total_bytes"] * 0.4)


def test_no_fit_raises_with_closest_candidate():
    with pytest.raises(MXNetError, match="NO layout fits"):
        autoshard.plan(_llama_small_shapes(), global_batch=16,
                       n_devices=8, seq=16, hbm_budget_bytes=1000)


def test_plan_deterministic_and_round_trips(tmp_path):
    """Same inputs ⇒ byte-identical plan.json (the CI golden contract);
    load_plan round-trips losslessly."""
    shapes = _llama_small_shapes()
    kw = dict(global_batch=16, n_devices=8, seq=16,
              hbm_budget_bytes=20 << 20)
    a = autoshard.plan(shapes, **kw)
    b = autoshard.plan(shapes, **kw)
    assert a.to_json() == b.to_json()
    path = os.path.join(tmp_path, "plan.json")
    a.save(path)
    loaded = autoshard.load_plan(path)
    assert loaded.to_json() == a.to_json()
    assert loaded.mesh_shape == a.mesh_shape
    assert loaded.data_spec == a.data_spec
    # the artifact is valid sorted-key JSON with the schema version
    d = json.loads(open(path).read())
    assert d["version"] == autoshard.PLAN_VERSION


def test_plan_version_mismatch_raises(tmp_path):
    p = autoshard.plan(_llama_small_shapes(), global_batch=16,
                       n_devices=8, seq=16)
    d = p.to_dict()
    d["version"] = 999
    path = os.path.join(tmp_path, "bad.json")
    with open(path, "w") as f:
        json.dump(d, f)
    with pytest.raises(MXNetError, match="version"):
        autoshard.load_plan(path)


def test_candidate_constraints():
    """sp candidates require seq % sp == 0; batch must divide by
    n_micro*dp*fsdp; every candidate's mesh multiplies to n_devices."""
    cands, _fam = autoshard.enumerate_candidates(
        _llama_small_shapes(), 8, global_batch=4, seq=6)
    for c in cands:
        m = c["mesh"]
        total = 1
        for s in m.values():
            total *= s
        assert total == 8
        assert 6 % m.get("sp", 1) == 0
        assert 4 % (c["n_micro"] * m.get("dp", 1)
                    * m.get("fsdp", 1)) == 0


def test_planner_telemetry_counters():
    prev = telemetry.enable()
    try:
        c_plans = telemetry.counter("mxnet_autoshard_plans_total")
        c_fits = telemetry.counter("mxnet_autoshard_fits_total")
        c_nofit = telemetry.counter("mxnet_autoshard_no_fit_total")
        p0, f0, n0 = c_plans.value, c_fits.value, c_nofit.value
        autoshard.plan(_llama_small_shapes(), global_batch=16,
                       n_devices=8, seq=16)
        assert c_plans.value == p0 + 1
        assert c_fits.value > f0
        with pytest.raises(MXNetError):
            autoshard.plan(_llama_small_shapes(), global_batch=16,
                           n_devices=8, seq=16, hbm_budget_bytes=1)
        assert c_nofit.value == n0 + 1
    finally:
        if not prev:
            telemetry.disable()


# ---------------------------------------------------------------------------
# estimator extensions (fsdp gather / n_micro / remat knobs)
# ---------------------------------------------------------------------------

def test_estimate_memory_fsdp_terms():
    shapes = _llama_small_shapes()
    base = cm.estimate_memory(shapes, {"dp": 2, "fsdp": 4},
                              "llama_fsdp", batch=16, seq=16,
                              data_axes=("dp", "fsdp"))
    assert base["fsdp_gather_bytes"] > 0
    # params/state shard ~4x vs dp-only (norms/biases replicate, so
    # slightly above an exact quarter)
    dp = cm.estimate_memory(shapes, {"dp": 8}, None, batch=16, seq=16,
                            data_axes=("dp",))
    assert dp["params_bytes"] / 4 <= base["params_bytes"] \
        <= dp["params_bytes"] / 3.9
    assert dp["opt_state_bytes"] / 4 <= base["opt_state_bytes"] \
        <= dp["opt_state_bytes"] / 3.9
    # microbatching: activations drop, a full-gather grad set joins
    micro = cm.estimate_memory(shapes, {"dp": 2, "fsdp": 4},
                               "llama_fsdp", batch=16, seq=16,
                               data_axes=("dp", "fsdp"), n_micro=2)
    assert micro["activation_bytes"] < base["activation_bytes"]
    assert micro["grads_bytes"] > base["grads_bytes"]
    assert micro["fsdp_gather_bytes"] >= base["fsdp_gather_bytes"]
    # remat halves the modeled activation residency
    remat = cm.estimate_memory(shapes, {"dp": 8}, None, batch=16,
                               seq=16, data_axes=("dp",), remat=True)
    assert remat["activation_bytes"] == dp["activation_bytes"] // 2


def test_estimate_memory_indivisible_fsdp_dim_no_gather():
    """A param whose dims the fsdp axis cannot divide degrades to
    replicated — and must NOT be charged a gather."""
    est = cm.estimate_memory({"w_q_weight": (7, 5)}, {"fsdp": 4},
                             [(r".*", ("fsdp", None))], batch=4,
                             data_axes=())
    assert est["fsdp_gather_bytes"] == 0
    assert est["params_bytes"] == 7 * 5 * 4      # fully replicated


# ---------------------------------------------------------------------------
# microbatched TrainStep (gradient accumulation)
# ---------------------------------------------------------------------------

def _tiny_net(seed=5):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gnn.HybridSequential()
    with net.name_scope():
        net.add(gnn.Dense(16, activation="tanh", in_units=8))
        net.add(gnn.Dense(4, in_units=16))
    net.initialize(mx.initializer.Xavier())
    return net


def _micro_run(n_micro, steps=3, mesh=None, remat=False):
    import jax
    mesh = mesh or parallel.DeviceMesh(shape=(2,), axis_names=("dp",),
                                       devices=jax.devices()[:2])
    net = _tiny_net()
    st = parallel.TrainStep(net, lambda o, l: gloss.L2Loss()(o, l),
                            mx.optimizer.Adam(learning_rate=1e-2),
                            mesh=mesh, n_micro=n_micro, remat=remat,
                            donate=False)
    x = np.random.RandomState(0).randn(8, 8).astype("float32")
    y = np.random.RandomState(1).randn(8, 4).astype("float32")
    losses = [float(st(nd.array(x), nd.array(y)).asscalar())
              for _ in range(steps)]
    return losses, [p.data().asnumpy().copy()
                    for p in net.collect_params().values()], st


def test_n_micro_1_bit_identical_to_default_step():
    """The ISSUE 14 acceptance bar: an explicitly microbatched step at
    n_micro=1 is BIT-identical to the existing TrainStep (same trace —
    losses and every parameter byte equal)."""
    l_def, p_def, _ = _micro_run(None)
    l_one, p_one, _ = _micro_run(1)
    assert l_def == l_one
    for a, b in zip(p_def, p_one):
        np.testing.assert_array_equal(a, b)


def test_n_micro_accumulation_parity():
    """n_micro=2/4 match the single-pass trajectory within fp tolerance
    (mean-of-micro-means == full-batch mean for per-sample-mean losses;
    accumulation is fixed-association so the result is deterministic)."""
    l_one, p_one, _ = _micro_run(1)
    for n in (2, 4):
        l_n, p_n, _ = _micro_run(n)
        np.testing.assert_allclose(l_n, l_one, rtol=2e-4)
        for a, b in zip(p_one, p_n):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)
        # determinism: same n_micro twice is bitwise-equal
        l_n2, p_n2, _ = _micro_run(n)
        assert l_n == l_n2
        for a, b in zip(p_n, p_n2):
            np.testing.assert_array_equal(a, b)


def test_n_micro_remat_composition():
    """remat composes with microbatching (single-output net) at parity."""
    l_one, _, _ = _micro_run(1)
    l_r, _, _ = _micro_run(2, remat=True)
    np.testing.assert_allclose(l_r, l_one, rtol=2e-4)


def test_n_micro_divisibility_raises():
    import jax
    mesh = parallel.DeviceMesh(shape=(2,), axis_names=("dp",),
                               devices=jax.devices()[:2])
    st = parallel.TrainStep(_tiny_net(), lambda o, l: gloss.L2Loss()(o, l),
                            "sgd", {"learning_rate": 0.1}, mesh=mesh,
                            n_micro=3, donate=False)
    with pytest.raises(MXNetError, match="divisible"):
        st(nd.array(np.zeros((8, 8), "float32")),
           nd.array(np.zeros((8, 4), "float32")))
    with pytest.raises(MXNetError, match="n_micro"):
        parallel.TrainStep(_tiny_net(), lambda o, l: o, "sgd",
                           {"learning_rate": 0.1}, mesh=mesh, n_micro=0)


def test_microbatch_knob_default(monkeypatch):
    monkeypatch.setenv("MXNET_MICROBATCH", "2")
    import jax
    mesh = parallel.DeviceMesh(shape=(2,), axis_names=("dp",),
                               devices=jax.devices()[:2])
    st = parallel.TrainStep(_tiny_net(), lambda o, l: gloss.L2Loss()(o, l),
                            "sgd", {"learning_rate": 0.1}, mesh=mesh,
                            donate=False)
    assert st._n_micro == 2


def test_run_stacked_with_microbatching():
    """run() (the lax.scan multi-step path) composes with n_micro."""
    import jax
    mesh = parallel.DeviceMesh(shape=(2,), axis_names=("dp",),
                               devices=jax.devices()[:2])
    net = _tiny_net()
    st = parallel.TrainStep(net, lambda o, l: gloss.L2Loss()(o, l),
                            mx.optimizer.Adam(learning_rate=1e-2),
                            mesh=mesh, n_micro=2, donate=False)
    x = np.random.RandomState(0).randn(2, 8, 8).astype("float32")
    y = np.random.RandomState(1).randn(2, 8, 4).astype("float32")
    losses = st.run(nd.array(x), nd.array(y)).asnumpy()
    assert losses.shape == (2,) and np.isfinite(losses).all()


# ---------------------------------------------------------------------------
# the 8-device OOM-avoidance lane (dryrun proof's pytest twin; slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_autoshard_oom_avoidance_8dev():
    """Estimator-confirmed dp-only OOM model trains under the
    auto-chosen fsdp layout with loss parity and no retrace on the
    8-device virtual mesh — TrainStep consuming the Plan directly."""
    from mxnet_tpu.analysis.runtime import no_retrace
    from mxnet_tpu.gluon.model_zoo.llama import llama_model

    vocab, seq, batch = 64, 16, 16

    def llama_loss(o, l):
        return mx.nd.softmax_cross_entropy(
            o.reshape((-1, o.shape[-1])), l.reshape((-1,))) / l.size

    toks = np.random.RandomState(23).randint(
        0, vocab, (batch, seq)).astype("int32")
    labs = np.roll(toks, -1, axis=1).astype("int32")

    mx.random.seed(29)
    probe = llama_model("llama_small", vocab_size=vocab)
    probe.initialize(mx.initializer.Normal(0.05))
    dp_est = cm.estimate_memory(probe, {"dp": 8}, None, batch=batch,
                                seq=seq, data_axes=("dp",))["total_bytes"]
    budget = int(dp_est * 0.4)
    plan = autoshard.plan(probe, global_batch=batch, seq=seq,
                          n_devices=8, hbm_budget_bytes=budget)
    assert "fsdp" in plan.mesh_axes

    def run(mesh=None, use_plan=None, steps=2):
        mx.random.seed(29)
        net = llama_model("llama_small", vocab_size=vocab)
        net.initialize(mx.initializer.Normal(0.05))
        st = parallel.TrainStep(
            net, llama_loss, mx.optimizer.Adam(learning_rate=1e-3),
            mesh=mesh, donate=False, plan=use_plan)
        return net, st, [float(st(nd.array(toks, dtype="int32"),
                                  nd.array(labs, dtype="int32"))
                               .asscalar()) for _ in range(steps)]

    _, _, dense = run(mesh=parallel.DeviceMesh(shape=(8,),
                                               axis_names=("dp",)))
    net_p, st_p, sharded = run(use_plan=plan)
    np.testing.assert_allclose(sharded, dense, rtol=2e-4)
    q = next(p for n, p in net_p.collect_params().items()
             if n.endswith("layer0_q_weight"))._data._data
    assert "fsdp" in str(q.sharding.spec)
    with no_retrace():
        st_p(nd.array(toks, dtype="int32"),
             nd.array(labs, dtype="int32"))
