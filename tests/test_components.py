"""Estimator, CustomOp, optimize_for, opperf, im2rec, parse_log tests
(VERDICT r2 remaining component gaps)."""

import io
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# estimator
# ---------------------------------------------------------------------------

def _toy_loader(n=64, d=8, k=4, batch=16, seed=0):
    r = np.random.RandomState(seed)
    X = mx.nd.array(r.randn(n, d).astype(np.float32))
    y = mx.nd.array(r.randint(0, k, (n,)))
    return gluon.data.DataLoader(gluon.data.ArrayDataset(X, y),
                                 batch_size=batch)


def test_estimator_fit_and_evaluate(seeded):
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.initializer.Xavier())
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=["acc"])
    loader = _toy_loader()
    est.fit(loader, epochs=3)
    rows = est.evaluate(loader)
    names = [r[0] for r in rows]
    assert any("loss" in n for n in names)
    assert any("accuracy" in n for n in names)


def test_estimator_early_stopping(seeded):
    from mxnet_tpu.gluon.contrib.estimator import (Estimator,
                                                   EarlyStoppingHandler)
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=["acc"],
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.0}))
    # lr=0: metric never improves → stop after patience epochs, not 50
    stopper = EarlyStoppingHandler(monitor=est.train_loss_metric,
                                   patience=2, min_delta=1e-9, mode="min")
    est.fit(_toy_loader(), epochs=50, event_handlers=[stopper])
    assert stopper.stopped_epoch is not None
    assert stopper.stopped_epoch <= 5


def test_estimator_checkpoint_handler(seeded, tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                                   Estimator)
    net = gluon.nn.Dense(2, in_units=8)
    net.initialize()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss())
    ck = CheckpointHandler(str(tmp_path), model_prefix="m")
    est.fit(_toy_loader(k=2), epochs=2, event_handlers=[ck])
    assert (tmp_path / "m-epoch0.params").exists()
    assert (tmp_path / "m-epoch1.params").exists()


# ---------------------------------------------------------------------------
# CustomOp
# ---------------------------------------------------------------------------

@mx.operator.register("test_straight_through")
class _STProp(mx.operator.CustomOpProp):
    """Sign forward, identity backward — autodiff would give zero grad,
    so this proves op.backward (not autodiff) drives the vjp."""

    def create_operator(self, ctx, shapes, dtypes):  # noqa: ARG002
        class Op(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):  # noqa: ARG002
                self.assign(out_data[0], req[0], mx.nd.sign(in_data[0]))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):  # noqa: ARG002
                self.assign(in_grad[0], req[0], out_grad[0])

        return Op()


def test_custom_op_straight_through(seeded):
    x = mx.nd.array(np.array([0.7, -0.2, 1.5], np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="test_straight_through")
    y.backward(mx.nd.array(np.array([1.0, 2.0, 3.0], np.float32)))
    np.testing.assert_array_equal(y.asnumpy(), [1.0, -1.0, 1.0])
    # identity backward, NOT sign's zero autodiff grad
    np.testing.assert_array_equal(x.grad.asnumpy(), [1.0, 2.0, 3.0])


def test_custom_op_kwargs_are_strings():
    seen = {}

    @mx.operator.register("test_kwarg_echo")
    class P(mx.operator.CustomOpProp):
        def __init__(self, alpha="1"):
            super().__init__()
            seen["alpha"] = alpha

        def create_operator(self, ctx, shapes, dtypes):  # noqa: ARG002
            class Op(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):  # noqa: ARG002
                    self.assign(out_data[0], req[0], in_data[0])

            return Op()

    mx.nd.Custom(mx.nd.ones((2,)), op_type="test_kwarg_echo", alpha=2.5)
    assert seen["alpha"] == "2.5"  # reference attr-dict string round-trip


def test_custom_op_errors():
    with pytest.raises(MXNetError, match="not registered"):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="nope_never")
    with pytest.raises(MXNetError, match="expects 1 inputs"):
        mx.nd.Custom(mx.nd.ones((2,)), mx.nd.ones((2,)),
                     op_type="test_straight_through")


# ---------------------------------------------------------------------------
# optimize_for
# ---------------------------------------------------------------------------

def test_optimize_for_builtin_and_custom():
    s = mx.sym.var("x") * 2
    assert s.optimize_for("TPU") is s
    assert s.optimize_for("default") is s
    with pytest.raises(MXNetError, match="not registered"):
        s.optimize_for("tensorrt")

    calls = {}

    @mx.symbol.register_backend("test_backend")
    def _pass(sym, args, aux, **kwargs):
        calls["kwargs"] = kwargs
        return sym

    assert s.optimize_for("test_backend", flag=3) is s
    assert calls["kwargs"] == {"flag": 3}


# ---------------------------------------------------------------------------
# tools
# ---------------------------------------------------------------------------

def test_opperf_rows():
    sys.path.insert(0, os.path.join(REPO, "benchmark", "opperf"))
    try:
        import opperf
    finally:
        sys.path.pop(0)
    rows = opperf.run(["dot", "softmax", "relu"], output="json", runs=2)
    by_op = {r["op"]: r for r in rows}
    assert by_op["dot"]["fwd_ms"] > 0
    assert "fwd_bwd_ms" in by_op["dot"]


def test_im2rec_roundtrip(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import im2rec
    finally:
        sys.path.pop(0)
    # build a tiny image tree with cv2 (baked in)
    import cv2
    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            img = np.random.RandomState(i).randint(
                0, 255, (8, 8, 3), np.uint8)
            cv2.imwrite(str(root / cls / f"{i}.jpg"), img)
    prefix = str(tmp_path / "data")
    lst, n, classes = im2rec.make_list(prefix, str(root))
    assert n == 6 and classes == ["cat", "dog"]
    n, skipped = im2rec.make_rec(prefix, str(root))
    assert n == 6 and skipped == 0
    # read back through the framework's RecordIO
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    header, payload = recordio.unpack(rec.read_idx(0))
    assert header.label in (0.0, 1.0)
    img = cv2.imdecode(np.frombuffer(payload, np.uint8), cv2.IMREAD_COLOR)
    assert img.shape == (8, 8, 3)


def test_parse_log():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    lines = [
        "INFO Epoch[0] Train-accuracy=0.50",
        "INFO Epoch[0] Validation-accuracy=0.40",
        "INFO Epoch[1] Train-accuracy=0.80",
        "INFO Epoch[1] Batch [20] Speed: 150.0 samples/sec",
    ]
    table = parse_log.parse(lines)
    assert table[0]["train-accuracy"] == 0.5
    assert table[0]["validation-accuracy"] == 0.4
    assert table[1]["samples"] == 150.0
    out = io.StringIO()
    parse_log.render(table, "md", out)
    assert "| epoch |" in out.getvalue()


def test_library_load_python_oplib(tmp_path):
    """mx.library.load (reference python/mxnet/library.py MXLoadLib role):
    a python op library registers through the public seams and its ops
    land on mx.nd; .so files get the documented guidance error."""
    import mxnet_tpu as mx
    lib = os.path.join(str(tmp_path), "myops.py")
    with open(lib, "w") as f:
        f.write(
            "from mxnet_tpu.ops.registry import register\n"
            "@register('my_plus_two')\n"
            "def _my_plus_two(x):\n"
            "    return x + 2\n")
    new = mx.library.load(lib, verbose=False)
    assert "my_plus_two" in new
    out = mx.nd.my_plus_two(mx.nd.ones((2, 2)))
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 3.0))
    assert lib in mx.library.loaded_libraries()
    # symbol namespace too
    s = mx.sym.my_plus_two(mx.sym.var("x"))
    ex = s.bind(mx.cpu(), {"x": mx.nd.zeros((2,))})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [2.0, 2.0])
    with pytest.raises(mx.MXNetError, match="PYTHON"):
        fake = os.path.join(str(tmp_path), "lib.so")
        open(fake, "wb").close()
        mx.library.load(fake)
    with pytest.raises(mx.MXNetError, match="registered no"):
        empty = os.path.join(str(tmp_path), "empty.py")
        with open(empty, "w") as f:
            f.write("x = 1\n")
        mx.library.load(empty)


def test_library_load_idempotent_and_rolls_back(tmp_path):
    """Re-loading a library returns the cached ops; a library that raises
    mid-registration rolls back so a fixed version can load (review
    regressions)."""
    import mxnet_tpu as mx
    lib = os.path.join(str(tmp_path), "relib.py")
    with open(lib, "w") as f:
        f.write("from mxnet_tpu.ops.registry import register\n"
                "@register('relib_op')\n"
                "def _f(x):\n    return x * 3\n")
    first = mx.library.load(lib, verbose=False)
    assert mx.library.load(lib, verbose=False) == first   # no re-exec crash
    broken = os.path.join(str(tmp_path), "broken.py")
    with open(broken, "w") as f:
        f.write("from mxnet_tpu.ops.registry import register\n"
                "@register('broken_ok')\n"
                "def _a(x):\n    return x\n"
                "raise RuntimeError('boom')\n")
    with pytest.raises(RuntimeError, match="boom"):
        mx.library.load(broken, verbose=False)
    with open(broken, "w") as f:   # fixed version must now load cleanly
        f.write("from mxnet_tpu.ops.registry import register\n"
                "@register('broken_ok')\n"
                "def _a(x):\n    return x + 1\n")
    assert "broken_ok" in mx.library.load(broken, verbose=False)
