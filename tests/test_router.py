"""Serving-router failure matrix (ISSUE 13) — fast, jax-free tier.

Every test drives the REAL router + the real ``ReplicaServer`` protocol
code; only the engine behind each replica is the deterministic stub in
``tests/_stub_replica.py`` (oracle tokens, millisecond latencies), so
the whole matrix — death mid-decode, death in the ``serving.reply`` ack
window, hedging with loser cancellation, admission-control shedding,
hang SIGKILL, rolling-restart drain, and router-death re-adoption —
runs inside the tier-1 budget.  The real-llama twin of the headline
rows lives in tests/test_router_chaos.py (slow, the router-chaos CI
lane).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.resilience import chaos
from mxnet_tpu.serving import engine as serving_engine
from mxnet_tpu.serving.router import (
    ReplicaDeadError, Router, RouterOverloaded, STATE_FILE,
)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _stub_replica import oracle_tokens  # noqa: E402

STUB = [sys.executable,
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_stub_replica.py")]
FAST_HB = {"MXNET_ELASTIC_HEARTBEAT_S": "0.1"}


def _counter(name):
    m = telemetry.REGISTRY.get(name)
    return 0 if m is None else m.value


def _router(tmp_path, n=2, **kw):
    kw.setdefault("env_extra", dict(FAST_HB))
    kw.setdefault("queue_max", 64)
    return Router(STUB, n, str(tmp_path), **kw).start()


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def test_router_dispatch_results_and_balance(tmp_path):
    """Basic tier: results are oracle-identical and least-loaded
    dispatch spreads concurrent work over BOTH replicas."""
    telemetry.enable()
    d0 = _counter("mxnet_router_dispatched_total")
    r = _router(tmp_path, env_extra={"STUB_TOKEN_DELAY_S": "0.02",
                                     **FAST_HB})
    try:
        assert r.wait_up() == 2
        prompts = [[i, i + 1, 5] for i in range(8)]
        hs = [r.submit(p, max_new_tokens=4) for p in prompts]
        res = [h.result(timeout=30) for h in hs]
        for p, got in zip(prompts, res):
            assert got == oracle_tokens(p, 4), p
        assert _counter("mxnet_router_dispatched_total") - d0 == 8
        served = {e["args"]["replica"]
                  for e in telemetry.get_tracer().events()
                  if e.get("cat") == "router.request"
                  and e.get("name") == "dispatched"}
        assert served == {0, 1}, served
    finally:
        r.stop()
        if not telemetry.env_enabled():
            telemetry.disable()


def test_router_prefix_affinity_dispatch(tmp_path):
    """ISSUE 15 satellite: least-loaded TIES prefer the replica that
    last served the same prompt-prefix hash (so the tier hits the
    per-replica paged-KV prefix cache), distinct prefixes still rotate,
    and a drained affinity target falls back cleanly to a survivor."""
    telemetry.enable()
    r = _router(tmp_path, affinity_tokens=4)
    try:
        assert r.wait_up() == 2

        def served_by(handle):
            for e in telemetry.get_tracer().events():
                if e.get("cat") == "router.request" \
                        and e.get("name") == "dispatched" \
                        and e.get("id") == handle.rid:
                    return e["args"]["replica"]
            raise AssertionError(f"no dispatch event for {handle.rid}")

        base = [3, 1, 4, 1]
        homes = []
        for i in range(6):          # sequential: replicas tie on load
            p = base + [10 + i]
            h = r.submit(p, max_new_tokens=3)
            assert h.result(timeout=30) == oracle_tokens(p, 3)
            homes.append(served_by(h))
        # every shared-prefix request stuck to ONE replica
        assert len(set(homes)) == 1, homes
        # distinct prefixes keep rotating over the tier
        spread = []
        for i in range(4):
            p = [50 + i, 60 + i, 70 + i, 80 + i, 1]
            h = r.submit(p, max_new_tokens=3)
            assert h.result(timeout=30) == oracle_tokens(p, 3)
            spread.append(served_by(h))
        assert set(spread) == {0, 1}, spread
        # fallback: the affinity target goes away -> survivor serves
        assert r.drain(homes[0], restart=False)
        p = base + [99]
        h = r.submit(p, max_new_tokens=3)
        assert h.result(timeout=30) == oracle_tokens(p, 3)
        assert served_by(h) == 1 - homes[0]
    finally:
        r.stop()
        if not telemetry.env_enabled():
            telemetry.disable()


def test_replica_death_mid_decode_retry_token_identical(tmp_path):
    """A replica dying BEFORE it computes (the mid-decode death shape)
    has its request transparently resubmitted to the survivor, which
    returns oracle-identical tokens; the corpse respawns on budget."""
    deaths0 = _counter("mxnet_router_replica_deaths_total")
    retries0 = _counter("mxnet_router_retries_total")
    r = _router(tmp_path, env_extra={
        "STUB_DIE_TOKEN": "77",
        "STUB_ONCE_MARKER": str(tmp_path / "die.marker"), **FAST_HB})
    try:
        killer = [77, 3, 9]
        hs = [r.submit(p, max_new_tokens=5)
              for p in (killer, [4, 5], [6, 7])]
        res = [h.result(timeout=30) for h in hs]
        for p, got in zip((killer, [4, 5], [6, 7]), res):
            assert got == oracle_tokens(p, 5), p
        assert _counter("mxnet_router_replica_deaths_total") > deaths0
        assert _counter("mxnet_router_retries_total") > retries0
        # the corpse comes back: both replicas up again
        _wait(lambda: all(s["state"] == "up"
                          for s in r.replica_status()),
              msg="respawn after death")
    finally:
        r.stop()


def test_reply_ack_window_death_no_duplicate_tokens(tmp_path):
    """serving.reply chaos: replica 0 computes the result, then dies
    BEFORE acking.  The retry on the survivor must hand the client the
    tokens exactly once, token-identical — never a duplicate/concat."""
    retries0 = _counter("mxnet_router_retries_total")
    r = _router(tmp_path, env_per_replica={
        0: {"MXNET_CHAOS": "1",
            "MXNET_CHAOS_SITES": "serving.reply:exit:1"}})
    try:
        assert r.wait_up() == 2
        p = [9, 8, 7]
        # tie-break dispatches the first request to replica 0 (the
        # chaos-armed one): it computes, hits serving.reply, and dies
        got = r.submit(p, max_new_tokens=6).result(timeout=30)
        assert got == oracle_tokens(p, 6)
        assert _counter("mxnet_router_retries_total") > retries0
    finally:
        r.stop()


def test_hedge_fires_and_loser_cancelled(tmp_path):
    """A straggling dispatch is duplicated after MXNET_ROUTER_HEDGE_S;
    the fast twin wins, and the slow loser receives a cancel (visible in
    its replica-side cancel log)."""
    hedges0 = _counter("mxnet_router_hedges_total")
    r = _router(tmp_path, hedge_s=0.25,
                env_per_replica={0: {"STUB_TOKEN_DELAY_S": "0.5"}})
    try:
        assert r.wait_up() == 2
        p = [11, 12]
        t0 = time.monotonic()
        h = r.submit(p, max_new_tokens=4)     # tie-break -> slow replica 0
        got = h.result(timeout=30)
        wall = time.monotonic() - t0
        assert got == oracle_tokens(p, 4)
        assert _counter("mxnet_router_hedges_total") == hedges0 + 1
        assert h.stats()["hedged"]
        assert wall < 1.5, f"hedge should beat the 2s straggler: {wall}"
        cancel_log = tmp_path / "cancels-0000.log"
        _wait(cancel_log.exists, msg="loser cancel log")
        assert h.rid in cancel_log.read_text().split()
    finally:
        r.stop()


def test_admission_shed_fails_fast_and_bounded(tmp_path):
    """Overload: submits beyond MXNET_ROUTER_QUEUE shed IMMEDIATELY with
    RouterOverloaded (never hang), and every admitted request still
    completes with a bounded e2e."""
    sheds0 = _counter("mxnet_router_shed_total")
    r = _router(tmp_path, n=1, queue_max=4,
                env_extra={"STUB_TOKEN_DELAY_S": "0.05", **FAST_HB})
    try:
        admitted, shed = [], 0
        for i in range(12):
            t0 = time.monotonic()
            try:
                admitted.append((i, r.submit([i, 2], max_new_tokens=4)))
            except RouterOverloaded:
                shed += 1
                assert time.monotonic() - t0 < 0.1, "shed must not block"
        assert shed >= 6 and len(admitted) >= 4
        assert _counter("mxnet_router_shed_total") - sheds0 == shed
        for i, h in admitted:
            assert h.result(timeout=30) == oracle_tokens([i, 2], 4)
            assert h.stats()["e2e_s"] < 10.0
    finally:
        r.stop()


def test_deadline_propagates_to_replica(tmp_path):
    """The remaining budget rides the dispatch: a request that cannot
    finish inside its deadline fails with RequestDeadlineExceeded
    promptly (not the full result timeout)."""
    r = _router(tmp_path, n=1,
                env_extra={"STUB_TOKEN_DELAY_S": "0.1", **FAST_HB})
    try:
        h = r.submit([3, 4], max_new_tokens=20, deadline_s=0.3)
        t0 = time.monotonic()
        with pytest.raises(serving_engine.RequestDeadlineExceeded):
            h.result(timeout=30)
        assert time.monotonic() - t0 < 5.0
    finally:
        r.stop()


def test_drain_rolling_restart(tmp_path):
    """drain() stops dispatch, lets in-flight finish, restarts the
    replica with a fresh pid, and the tier keeps serving — the
    rolling-restart primitive."""
    r = _router(tmp_path, env_extra={"STUB_TOKEN_DELAY_S": "0.02",
                                     **FAST_HB})
    try:
        assert r.wait_up() == 2
        hs = [r.submit([i, 9], max_new_tokens=4) for i in range(4)]
        pid0 = r.replica_status()[0]["pid"]
        assert r.drain(0, restart=True, timeout_s=30)
        for i, h in enumerate(hs):
            assert h.result(timeout=30) == oracle_tokens([i, 9], 4)
        _wait(lambda: r.replica_status()[0]["state"] == "up",
              msg="replica 0 back up after drain")
        assert r.replica_status()[0]["pid"] != pid0
        h = r.submit([42], max_new_tokens=3)
        assert h.result(timeout=30) == oracle_tokens([42], 3)
    finally:
        r.stop()


def test_hung_replica_sigkilled_and_request_retried(tmp_path):
    """A wedged replica (heartbeat stale, RPC thread blocked) is
    SIGKILLed on MXNET_ROUTER_HANG_S and its request retried."""
    deaths0 = _counter("mxnet_router_replica_deaths_total")
    r = _router(tmp_path, hang_s=1.0, env_extra={
        "STUB_WEDGE_TOKEN": "88",
        "STUB_ONCE_MARKER": str(tmp_path / "wedge.marker"), **FAST_HB})
    try:
        p = [88, 5]
        got = r.submit(p, max_new_tokens=4).result(timeout=30)
        assert got == oracle_tokens(p, 4)
        assert _counter("mxnet_router_replica_deaths_total") > deaths0
    finally:
        r.stop()


def test_replica_spawn_chaos_transient_absorbed(tmp_path):
    """router.replica_spawn chaos: a transient spawn fault is absorbed
    by the Retry policy and the tier still comes up."""
    chaos.inject("router.replica_spawn", kind="transient", times=1)
    try:
        r = _router(tmp_path, n=1)
        try:
            assert chaos.fault_count("router.replica_spawn") >= 1
            h = r.submit([5, 6], max_new_tokens=3)
            assert h.result(timeout=30) == oracle_tokens([5, 6], 3)
        finally:
            r.stop()
    finally:
        chaos.clear("router.replica_spawn")


def test_retry_budget_exhaustion_fails_not_hangs(tmp_path):
    """When every dispatch dies and the budgets are spent, the handle
    fails with ReplicaDeadError promptly instead of hanging."""
    r = _router(tmp_path, n=1, max_retries=1, max_respawns=1,
                env_extra={"STUB_DIE_TOKEN": "77", **FAST_HB})
    try:
        # no once-marker: the respawned replica dies on the retry too
        h = r.submit([77], max_new_tokens=3)
        with pytest.raises(ReplicaDeadError):
            h.result(timeout=60)
    finally:
        r.stop()


def test_router_death_mid_dispatch_readoption(tmp_path):
    """The headline crash window: the router dies (chaos 'exit' at
    router.dispatch) with requests journaled but unsent and replicas
    mid-compute.  A restarted router on the same workdir re-adopts the
    LIVE replicas through their port files and re-dispatches the
    journal: every accepted request resolves oracle-identically."""
    here = os.path.dirname(os.path.abspath(__file__))
    reqs = [{"tag": f"t{i}", "prompt": [i, 3], "max_new_tokens": 4}
            for i in range(6)]
    req_file = tmp_path / "reqs.json"
    req_file.write_text(json.dumps(reqs))
    out_file = tmp_path / "out.json"
    env = dict(os.environ, STUB_TOKEN_DELAY_S="0.1",
               **FAST_HB)
    base = [sys.executable, os.path.join(here, "_router_driver.py"),
            "--workdir", str(tmp_path), "-n", "2",
            "--requests", str(req_file), "--out", str(out_file),
            "--queue-max", "16"]
    p1 = subprocess.run(base + ["--dispatch-exit-after", "2",
                                "--keep-replicas"],
                        env=env, timeout=60)
    assert p1.returncode != 0          # chaos exit killed it mid-dispatch
    assert not out_file.exists()
    st = json.loads((tmp_path / STATE_FILE).read_text())
    assert st["phase"] == "running" and st["requests"]
    pids1 = {r["index"]: r["pid"] for r in st["replicas"]}
    p2 = subprocess.run(base + ["--resume"], env=env, timeout=120)
    assert p2.returncode == 0, "resumed driver failed"
    out = json.loads(out_file.read_text())
    for rec in reqs:
        got = out["results"][rec["tag"]]
        assert got.get("tokens") == oracle_tokens(rec["prompt"], 4), \
            (rec["tag"], got)
    # the journal's live pids were re-adopted, not respawned
    adopted = {r["index"]: r for r in
               ({s["index"]: s for s in out["replicas"]}.values())}
    assert any(r["adopted"] and r["pid"] == pids1[r["index"]]
               for r in adopted.values()), out["replicas"]
