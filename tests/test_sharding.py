"""GSPMD sharding engine (ISSUE 8): match_partition_rules semantics,
rule packs, TrainStep wiring (rules -> NamedShardings at trace time,
sharded optimizer state, no-retrace), Trainer mesh_reduced allreduce
skip, and the sharded checkpoint round trip."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel, sharding
from mxnet_tpu.base import MXNetError
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


# ---------------------------------------------------------------------------
# match_partition_rules semantics
# ---------------------------------------------------------------------------

def test_first_match_wins():
    rules = [(r"q_weight$", ("tp", None)),
             (r"weight$", (None, "tp")),
             (r".*", ())]
    specs = sharding.match_partition_rules(
        rules, {"layer0_q_weight": (8, 8), "layer0_o_weight": (8, 8)})
    assert specs["layer0_q_weight"] == ("tp", None)   # rule 1, not rule 2
    assert specs["layer0_o_weight"] == (None, "tp")


def test_tok_weight_shadowing_needs_order():
    """'tok_weight' ends with 'k_weight' — the documented first-match
    guard in llama_rules: the embedding rule must come first."""
    specs = sharding.match_partition_rules(
        sharding.llama_rules(), {"m0_tok_weight": (64, 16),
                                 "m0_layer0_k_weight": (32, 16)})
    assert specs["m0_tok_weight"] == ("tp", None)
    assert specs["m0_layer0_k_weight"] == ("tp", None)


def test_scalars_never_partition():
    rules = [(r".*", ("tp",))]
    specs = sharding.match_partition_rules(
        rules, {"gain": (), "one_elem": (1,), "vec": (8,)})
    assert specs["gain"] == ()
    assert specs["one_elem"] == ()
    assert specs["vec"] == ("tp",)


def test_unmatched_replicates_by_default_and_errors_on_request():
    rules = [(r"q_weight$", ("tp", None))]
    specs = sharding.match_partition_rules(rules, {"stray": (4, 4)})
    assert specs["stray"] == ()
    with pytest.raises(MXNetError, match="stray"):
        sharding.match_partition_rules(rules, {"stray": (4, 4)},
                                       on_unmatched="error")


def test_rule_validation():
    with pytest.raises(MXNetError, match="unknown logical axis"):
        sharding.match_partition_rules([(r".*", ("bogus",))], {"w": (4,)})
    with pytest.raises(MXNetError, match="invalid regex"):
        sharding.match_partition_rules([(r"(", ())], {"w": (4,)})
    # spec rank beyond the param rank is a layout bug, not a fallback
    with pytest.raises(MXNetError, match="rank"):
        sharding.match_partition_rules([(r".*", ("tp", None, None))],
                                       {"w": (4, 4)})


def test_deferred_shape_raises():
    class Leaf:
        shape = None
    with pytest.raises(MXNetError, match="deferred"):
        sharding.match_partition_rules([(r".*", ())], {"w": Leaf()})


# ---------------------------------------------------------------------------
# resolve_spec degradation
# ---------------------------------------------------------------------------

def test_resolve_spec_degrades_absent_axis_and_indivisible_dims():
    mesh = parallel.DeviceMesh(shape=(4, 2), axis_names=("dp", "tp"))
    sh, sharded = sharding.resolve_spec(("tp", None), mesh, shape=(8, 6))
    assert sharded and sh.spec == mesh.spec("tp", None)
    # axis the mesh doesn't carry -> replicated
    sh, sharded = sharding.resolve_spec(("ep", None), mesh, shape=(8, 6))
    assert not sharded and sh.is_fully_replicated
    # indivisible dim (7 % 2) -> that dim unsharded
    sh, sharded = sharding.resolve_spec(("tp", None), mesh, shape=(7, 6))
    assert not sharded and sh.is_fully_replicated
    # multi-axis dim entry ('dp','tp') shards dim0 over 8
    sh, sharded = sharding.resolve_spec((("dp", "tp"),), mesh, shape=(16,))
    assert sharded


def test_mesh_spec_rejects_unknown_axis():
    mesh = parallel.DeviceMesh(shape=(8,), axis_names=("dp",))
    with pytest.raises(MXNetError, match="no axis"):
        mesh.sharded("tp")


# ---------------------------------------------------------------------------
# rule packs over the real zoo param trees
# ---------------------------------------------------------------------------

def _names_with_spec(specs, spec):
    return sorted(n for n, s in specs.items() if s == spec)


def test_llama_pack_covers_every_matrix():
    from mxnet_tpu.gluon.model_zoo.llama import llama_model
    net = llama_model("llama_tiny", vocab_size=64)
    net.initialize(mx.initializer.Normal(0.02))
    specs = sharding.match_partition_rules(
        sharding.llama_rules(), net.collect_params(),
        on_unmatched="error")  # the pack must cover the whole tree
    col = _names_with_spec(specs, ("tp", None))
    row = _names_with_spec(specs, (None, "tp"))
    assert any(n.endswith("tok_weight") for n in col)
    assert any(n.endswith("lm_head_weight") for n in col)
    assert all(n.endswith(("o_weight", "down_weight")) for n in row)
    # norms replicate
    assert all(specs[n] == () for n in specs if n.endswith("norm_weight"))


def test_bert_pack_and_legacy_helper_delegate():
    from mxnet_tpu.gluon.model_zoo import bert
    net = bert.bert_model("bert_3_128_2", vocab_size=100, max_length=16,
                          dropout=0.0)
    net.initialize(mx.initializer.Normal(0.02))
    bert.apply_tp_shardings(net, axis="tp")
    params = net.collect_params()
    assert params["bertmodel0_enc_layer0_attn_qkv_weight"].sharding \
        == ("tp", None)
    assert params["bertmodel0_enc_layer0_ffn2_weight"].sharding \
        == (None, "tp")
    assert params["bertmodel0_word_weight"].sharding == ("tp", None)
    assert params["bertmodel0_embln_gamma"].sharding is None  # replicated


def test_transformer_pack_covers_decoder():
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    net = TransformerModel(vocab_size=50, num_layers=1, units=16,
                           hidden_size=32, num_heads=2, max_length=8,
                           dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    specs = sharding.match_partition_rules(
        sharding.transformer_rules(), net.collect_params(),
        on_unmatched="error")
    assert any(s == (None, "tp") for s in specs.values())
    assert any(s == ("tp", None) for s in specs.values())


def test_rule_pack_registry():
    assert sharding.rule_pack("llama")[0][1] == ("tp", None)
    with pytest.raises(MXNetError, match="unknown rule pack"):
        sharding.rule_pack("resnet")


# ---------------------------------------------------------------------------
# TrainStep wiring
# ---------------------------------------------------------------------------

class _MLP(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.fc1 = nn.Dense(16, flatten=False, in_units=8,
                                prefix="fc1_")
            self.fc2 = nn.Dense(4, flatten=False, in_units=16,
                                prefix="fc2_")

    def hybrid_forward(self, F, x):
        return self.fc2(F.relu(self.fc1(x)))


_MLP_RULES = [(r"fc1_weight$", ("tp", None)),
              (r"fc2_weight$", (None, "tp")),
              (r"fc1_bias$", ("tp",))]


def _mlp_losses(mesh, rules, steps=3, seed=3):
    mx.random.seed(seed)
    net = _MLP(prefix="mlp_")
    net.initialize(mx.initializer.Xavier())
    step = parallel.TrainStep(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                              mx.optimizer.Adam(learning_rate=1e-2),
                              mesh=mesh, donate=False,
                              partition_rules=rules)
    r = np.random.RandomState(0)
    x = nd.array(r.randn(8, 8).astype(np.float32))
    y = nd.array(r.randn(8, 4).astype(np.float32))
    return net, step, [float(step(x, y).asscalar()) for _ in range(steps)]


def test_trainstep_rules_match_replicated_run():
    mesh = parallel.DeviceMesh(shape=(4, 2), axis_names=("dp", "tp"))
    net_s, step_s, ls = _mlp_losses(mesh, _MLP_RULES)
    net_d, _, ld = _mlp_losses(parallel.DeviceMesh(shape=(8,),
                                                   axis_names=("dp",)), None)
    np.testing.assert_allclose(ls, ld, rtol=2e-5)
    # the rules really landed: param AND its adam state carry tp shardings
    w = net_s.collect_params()["mlp_fc1_weight"]
    assert "tp" in str(w._data._data.sharding.spec)
    i = step_s._trainable.index(w)
    owner_states = [s for s, o in zip(step_s._state_nds, step_s._state_owner)
                    if o == i]
    assert owner_states and all(
        "tp" in str(s._data.sharding.spec) for s in owner_states)


def test_trainstep_rules_no_retrace_and_dispatch_counters():
    from mxnet_tpu.analysis.runtime import no_retrace
    from mxnet_tpu.telemetry import REGISTRY
    import mxnet_tpu.telemetry as tel
    mesh = parallel.DeviceMesh(shape=(4, 2), axis_names=("dp", "tp"))
    net, step, _ = _mlp_losses(mesh, _MLP_RULES, steps=2)
    r = np.random.RandomState(0)
    x = nd.array(r.randn(8, 8).astype(np.float32))
    y = nd.array(r.randn(8, 4).astype(np.float32))
    tel.enable()
    try:
        d0 = REGISTRY.get("mxnet_sharding_step_dispatches_total").value
        t0 = REGISTRY.get("mxnet_sharding_retraces_total").value
        with no_retrace():
            step(x, y)
            step(x, y)
        assert REGISTRY.get(
            "mxnet_sharding_step_dispatches_total").value == d0 + 2
        assert REGISTRY.get("mxnet_sharding_retraces_total").value == t0
    finally:
        tel.disable()


def test_trainstep_rules_authoritative_over_stale_hints():
    """With partition_rules the rule mapping is authoritative: a
    construction-time Parameter.sharding hint must NOT resurrect for an
    unmatched param (the unmatched-replicates bit-identity contract)."""
    mesh = parallel.DeviceMesh(shape=(4, 2), axis_names=("dp", "tp"))
    mx.random.seed(3)
    net = _MLP(prefix="mlp_")
    net.initialize(mx.initializer.Xavier())
    net.collect_params()["mlp_fc2_weight"].sharding = ("tp", None)
    step = parallel.TrainStep(
        net, lambda o, l: gluon.loss.L2Loss()(o, l),
        mx.optimizer.Adam(learning_rate=1e-2), mesh=mesh, donate=False,
        partition_rules=[(r"fc1_weight$", ("tp", None))])
    r = np.random.RandomState(0)
    step(nd.array(r.randn(8, 8).astype(np.float32)),
         nd.array(r.randn(8, 4).astype(np.float32)))
    params = net.collect_params()
    assert params["mlp_fc2_weight"]._data._data.sharding \
        .is_fully_replicated  # unmatched: the stale hint did not win
    assert "tp" in str(params["mlp_fc1_weight"]._data._data.sharding.spec)


def test_trainstep_data_spec_empty_replicates_batch():
    """data_spec=() is an explicit request to replicate the batch — it
    must not fall back to the default dp sharding.  A batch size the dp
    axis doesn't divide (3 over 8 devices) can only run replicated."""
    mesh = parallel.DeviceMesh(shape=(8,), axis_names=("dp",))
    mx.random.seed(3)
    net = _MLP(prefix="mlp_")
    net.initialize(mx.initializer.Xavier())
    step = parallel.TrainStep(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                              mx.optimizer.Adam(learning_rate=1e-2),
                              mesh=mesh, donate=False, data_spec=())
    r = np.random.RandomState(0)
    loss = step(nd.array(r.randn(3, 8).astype(np.float32)),
                nd.array(r.randn(3, 4).astype(np.float32)))
    assert np.isfinite(float(loss.asscalar()))


def test_sharding_coverage_counters_count_each_param_once():
    """resolved + fallback covers EVERY param exactly once per resolve
    (replicated-by-empty-spec params land in fallback), independent of
    step count — the layout-coverage contract the PROFILE.md r9 recipe
    reads."""
    from mxnet_tpu.telemetry import REGISTRY
    import mxnet_tpu.telemetry as tel
    mesh = parallel.DeviceMesh(shape=(4, 2), axis_names=("dp", "tp"))
    tel.enable()
    try:
        r0 = REGISTRY.get("mxnet_sharding_resolved_params_total").value
        f0 = REGISTRY.get("mxnet_sharding_fallback_params_total").value
        net, step, _ = _mlp_losses(mesh, _MLP_RULES, steps=2)
        dr = REGISTRY.get(
            "mxnet_sharding_resolved_params_total").value - r0
        df = REGISTRY.get(
            "mxnet_sharding_fallback_params_total").value - f0
    finally:
        tel.disable()
    assert dr + df == len(step._params)
    assert dr == 3   # fc1_weight, fc2_weight, fc1_bias per _MLP_RULES
    assert df == 1   # fc2_bias: no rule matched -> replicated, counted


def test_trainstep_data_spec_tuple_of_axes():
    """A data_spec entry may shard ONE dim over several mesh axes —
    the same N-axis entries DeviceMesh.spec()/sharded() take."""
    mesh = parallel.DeviceMesh(shape=(2, 2, 2),
                               axis_names=("dp", "tp", "sp"))
    mx.random.seed(3)
    net = _MLP(prefix="mlp_")
    net.initialize(mx.initializer.Xavier())
    step = parallel.TrainStep(net, lambda o, l: gluon.loss.L2Loss()(o, l),
                              mx.optimizer.Adam(learning_rate=1e-2),
                              mesh=mesh, donate=False,
                              data_spec=(("dp", "sp"),))
    r = np.random.RandomState(0)
    loss = step(nd.array(r.randn(8, 8).astype(np.float32)),
                nd.array(r.randn(8, 4).astype(np.float32)))
    assert np.isfinite(float(loss.asscalar()))


def test_trainer_update_on_kvstore_rejects_mesh_reduced():
    """update_on_kvstore=True can't honor mesh_reduced (the store
    reduces inside push — double-count) and must fail loudly."""
    net, ctxs = _two_ctx_net()
    params = net.collect_params()
    params["mlp_fc1_weight"].mesh_reduced = True
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore="device", update_on_kvstore=True)
    _set_grads(net, ctxs)
    with pytest.raises(MXNetError, match="mesh_reduced"):
        tr.step(1)


def test_trainstep_data_spec_validates():
    mesh = parallel.DeviceMesh(shape=(8,), axis_names=("dp",))
    with pytest.raises(MXNetError, match="data_spec"):
        parallel.TrainStep(_MLP(), lambda o, l: o, "sgd", mesh=mesh,
                           data_spec=("dp", "sp"))


# ---------------------------------------------------------------------------
# Trainer skips the allreduce for mesh-reduced params
# ---------------------------------------------------------------------------

def _two_ctx_net():
    ctxs = [mx.cpu(0), mx.cpu(1)]
    mx.random.seed(5)
    net = _MLP(prefix="mlp_")
    net.initialize(mx.initializer.Xavier(), ctx=ctxs)
    return net, ctxs


def _set_grads(net, ctxs):
    """Per-ctx grads = (i + 1) * ones, so the reduced value (sum = 3) is
    distinguishable from any single replica's."""
    for p in net.collect_params().values():
        for i, g in enumerate(p.list_grad()):
            g[:] = nd.ones(p.shape, ctx=ctxs[i]) * (i + 1)


def test_trainer_skips_mesh_reduced_params():
    net, ctxs = _two_ctx_net()
    params = net.collect_params()
    marked = params["mlp_fc1_weight"]
    marked.mesh_reduced = True
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore="device")
    _set_grads(net, ctxs)
    tr.allreduce_grads()
    # the flagged param kept its per-replica grads (mesh owns them)...
    np.testing.assert_allclose(marked.list_grad()[0].asnumpy(), 1.0)
    np.testing.assert_allclose(marked.list_grad()[1].asnumpy(), 2.0)
    # ...every other param was reduced to the 1+2 sum on both replicas
    other = params["mlp_fc2_weight"]
    for g in other.list_grad():
        np.testing.assert_allclose(g.asnumpy(), 3.0)


def test_trainer_skip_knob_off_restores_reduction(monkeypatch):
    monkeypatch.setenv("MXNET_SHARDING_SKIP_ALLREDUCE", "0")
    net, ctxs = _two_ctx_net()
    params = net.collect_params()
    params["mlp_fc1_weight"].mesh_reduced = True
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore="device")
    _set_grads(net, ctxs)
    tr.allreduce_grads()
    for g in params["mlp_fc1_weight"].list_grad():
        np.testing.assert_allclose(g.asnumpy(), 3.0)


def test_mark_mesh_reduced_helper():
    net, _ = _two_ctx_net()
    sharding.mark_mesh_reduced(net)
    assert all(p.mesh_reduced for p in net.collect_params().values())
    sharding.mark_mesh_reduced(net, False)
    assert not any(p.mesh_reduced for p in net.collect_params().values())


# ---------------------------------------------------------------------------
# sharded checkpoint round trip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sharded_save", [0, 1])
def test_checkpoint_roundtrips_sharded_params(tmp_path, monkeypatch,
                                              sharded_save):
    pytest.importorskip("orbax.checkpoint")
    monkeypatch.setenv("MXNET_CHECKPOINT_SHARDED", str(sharded_save))
    mesh = parallel.DeviceMesh(shape=(4, 2), axis_names=("dp", "tp"))

    # uninterrupted reference: 4 sharded steps
    net_r, step_r, _ = _mlp_losses(mesh, _MLP_RULES, steps=4)
    ref = {k: p.data().asnumpy().copy()
           for k, p in net_r.collect_params().items()}

    # save after 2 sharded steps (params now carry NamedShardings), then
    # restore into a FRESH net and run the remaining 2
    net_a, step_a, _ = _mlp_losses(mesh, _MLP_RULES, steps=2)
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path / f"s{sharded_save}"))
    assert mgr.save(2, net=net_a)

    net_b, step_b, _ = _mlp_losses(mesh, _MLP_RULES, steps=0)
    got_step, _ = mgr.restore(net=net_b)
    assert got_step == 2
    # adam state must continue too: reuse net_a's live TrainStep states by
    # restoring into net_a itself (param path) — the trainer-states path
    # is covered by test_checkpoint; here the point is the PARAM layout
    mgr.restore(net=net_a)
    r = np.random.RandomState(0)
    x = nd.array(r.randn(8, 8).astype(np.float32))
    y = nd.array(r.randn(8, 4).astype(np.float32))
    for _ in range(2):
        step_a(x, y)
    got = {k: p.data().asnumpy()
           for k, p in net_a.collect_params().items()}
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


# ---------------------------------------------------------------------------
# fsdp rule packs (ISSUE 14): ZeRO-3 resolution edge cases
# ---------------------------------------------------------------------------

def test_fsdp_pack_composes_with_tp_on_same_mesh():
    """llama_fsdp_rules on a dp×fsdp×tp mesh: column-parallel weights
    carry tp on dim0 AND fsdp on dim1; row-parallel the mirror; the
    embedding shards vocab over both."""
    import jax
    mesh = parallel.DeviceMesh(shape=(2, 2, 2),
                               axis_names=("dp", "fsdp", "tp"))
    specs = sharding.match_partition_rules(
        sharding.llama_fsdp_rules(),
        {"m_tok_weight": (64, 16), "m_layer0_q_weight": (16, 16),
         "m_layer0_down_weight": (16, 44), "m_layer0_attn_norm_weight":
         (16,), "m_scale": ()})
    assert specs["m_layer0_q_weight"] == ("tp", "fsdp")
    assert specs["m_layer0_down_weight"] == ("fsdp", "tp")
    assert specs["m_tok_weight"] == (("tp", "fsdp"), None)
    assert specs["m_layer0_attn_norm_weight"] == ()   # norms replicate
    assert specs["m_scale"] == ()                     # scalars never shard
    sh, did = sharding.resolve_spec(specs["m_layer0_q_weight"], mesh,
                                    shape=(16, 16))
    assert did and str(sh.spec) == str(
        jax.sharding.PartitionSpec("tp", "fsdp"))


def test_fsdp_pack_degrades_without_fsdp_axis():
    """The same rule set on a mesh WITHOUT fsdp resolves to the pure tp
    layout (one rule set per model, every mesh) — and on a dp-only mesh
    to full replication."""
    tp_mesh = parallel.DeviceMesh(shape=(4, 2), axis_names=("dp", "tp"))
    sh, did = sharding.resolve_spec(("tp", "fsdp"), tp_mesh,
                                    shape=(16, 16))
    assert did and "tp" in str(sh.spec) and "fsdp" not in str(sh.spec)
    dp_mesh = parallel.DeviceMesh(shape=(8,), axis_names=("dp",))
    sh, did = sharding.resolve_spec(("tp", "fsdp"), dp_mesh,
                                    shape=(16, 16))
    assert not did    # full replication, bit-identity contract


def test_fsdp_indivisible_dim_degrades_to_replicated():
    """A dim not divisible by its fsdp axis (or the tp×fsdp product on
    a combined entry) drops to unsharded instead of erroring."""
    mesh = parallel.DeviceMesh(shape=(2, 2, 2),
                               axis_names=("dp", "fsdp", "tp"))
    # dim1 = 7 not divisible by fsdp=2 -> that dim unsharded, dim0 keeps tp
    sh, did = sharding.resolve_spec(("tp", "fsdp"), mesh, shape=(16, 7))
    assert did
    s = str(sh.spec)
    assert "tp" in s and "fsdp" not in s
    # combined ('tp','fsdp') entry over a dim divisible by 2 but not 4
    sh, did = sharding.resolve_spec((("tp", "fsdp"), None), mesh,
                                    shape=(6, 16))
    assert not did    # 6 % (2*2) != 0 -> whole entry degrades


def test_fsdp_scalar_state_replicates_in_trainstep():
    """Optimizer state that does not match its owner param's shape
    (scalar / odd-shaped state) replicates even under an fsdp pack,
    while same-shaped adam state rides the param's fsdp layout."""
    import numpy as np
    from mxnet_tpu.gluon import nn as gnn, loss as gloss
    mesh = parallel.DeviceMesh(shape=(2, 2, 2),
                               axis_names=("dp", "fsdp", "tp"))
    mx.random.seed(3)
    net = gnn.Dense(16, flatten=False, in_units=16, use_bias=False,
                    prefix="fsdpnet_")
    net.initialize(mx.initializer.Xavier())
    st = parallel.TrainStep(
        net, lambda o, l: gloss.L2Loss()(o, l),
        mx.optimizer.Adam(learning_rate=0.1), mesh=mesh, donate=False,
        partition_rules=[(r"weight$", ("tp", "fsdp"))],
        data_spec=(("dp", "fsdp"),))
    x = np.random.RandomState(0).randn(8, 16).astype("float32")
    y = np.random.RandomState(1).randn(8, 16).astype("float32")
    st(nd.array(x), nd.array(y))
    p_sh, s_sh = st._shardings()
    # every adam m/v state is weight-shaped here: all ride the layout
    assert all("fsdp" in str(sh.spec) for sh in p_sh)
    assert all("fsdp" in str(sh.spec) for sh in s_sh)
    # scalar state (shape != owner param's): the mismatch branch must
    # replicate it — inject one scalar state NDArray next to the real
    # adam slots and re-resolve
    st._state_nds = st._state_nds + [nd.zeros(())]
    st._state_owner = st._state_owner + [0]
    st._p_sh = st._s_sh = None
    _, s_sh2 = st._shardings()
    assert "fsdp" not in str(s_sh2[-1].spec) \
        and str(s_sh2[-1].spec) == "PartitionSpec()"
    assert all("fsdp" in str(sh.spec) for sh in s_sh2[:-1])
