"""Hardware-free perf-regression gate (ISSUE 16): tolerance bands,
baseline digest/validation, added/removed lanes, the live-delta plane,
the injected-regression red path, and the sweep/CLI wiring.

The diff engine is pure dict-math, so most of this file runs in
microseconds; the red test runs the kvstore lane in-process twice (the
knob is read at kvstore construction), and the subprocess tests drive
the actual CLIs the CI lanes call.
"""

import json
import os
import subprocess
import sys
import urllib.request

import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import costmodel, httpd, tracer
from mxnet_tpu.telemetry import perfgate as pg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tests", "perf_baseline.json")


def _rec(**over):
    """A synthetic lane record shaped exactly like _finish_record's."""
    rec = {
        "config": {"batch": 4, "seq_len": 32},
        "metrics": {
            "dispatches_per_step": 2.0, "executables": 3,
            "retraces_steady": 0, "flops": 1000000,
            "bytes_accessed": 400000, "peak_hbm_bytes": 800000,
            "analytic_mfu": 0.25, "analytic_step_s": 2e-06,
            "verdict": "compute-bound",
        },
        "sites": {"train.step": {
            "executables": 1, "calls": 4, "flops": 1000000.0,
            "bytes_accessed": 400000.0, "peak_bytes": 800000}},
        "counters": {"mxnet_op_dispatch_total": 8},
        "observed": {"steady_wall_s": 0.5, "wall_s_per_step": 0.25,
                     "measured_mfu": 0.01},
    }
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(rec.get(k), dict):
            rec[k] = {**rec[k], **v}
        else:
            rec[k] = v
    return rec


# -- tolerance bands ---------------------------------------------------------

def test_identical_lanes_pass():
    report = pg.diff_snapshots({"a": _rec()}, {"a": _rec()})
    assert report["ok"]
    assert report["lanes"]["a"]["verdict"] == "ok"


@pytest.mark.parametrize("metric,base,inside,outside", [
    ("flops", 1000000, 1019000, 1021000),              # ±2% class
    ("bytes_accessed", 400000, 407600, 408800),
    ("analytic_mfu", 0.25, 0.2549, 0.2552),
    ("peak_hbm_bytes", 800000, 839000, 841000),        # ±5% class
])
def test_relative_band_boundaries(metric, base, inside, outside):
    b = _rec(metrics={metric: base})
    ok = pg.diff_lane(b, _rec(metrics={metric: inside}))
    assert not [f for f in ok if f["metric"] == metric], ok
    bad = pg.diff_lane(b, _rec(metrics={metric: outside}))
    assert [f for f in bad if f["metric"] == metric]


@pytest.mark.parametrize("metric,base,drifted", [
    ("dispatches_per_step", 2.0, 2.5),    # structural: ANY change fails
    ("executables", 3, 4),
    ("retraces_steady", 0, 1),
    ("verdict", "compute-bound", "memory-bound"),
])
def test_exact_metrics_fail_on_any_drift(metric, base, drifted):
    fails = pg.diff_lane(_rec(metrics={metric: base}),
                         _rec(metrics={metric: drifted}))
    assert [f for f in fails if f["metric"] == metric]


def test_counters_config_and_sites_are_exact():
    base = _rec()
    fails = pg.diff_lane(base, _rec(counters={"mxnet_op_dispatch_total": 9}))
    assert any(f["metric"] == "counters.mxnet_op_dispatch_total"
               for f in fails)
    fails = pg.diff_lane(base, _rec(config={"batch": 8, "seq_len": 32}))
    assert any(f["metric"] == "config" for f in fails)
    # a site disappearing (e.g. a fused path silently skipped) is loud
    siteless = _rec()
    siteless["sites"] = {}
    fails = pg.diff_lane(base, siteless)
    assert any(f["metric"] == "sites.train.step" for f in fails)
    # a metric KEY vanishing is a failure, not a silent skip
    fresh = _rec()
    del fresh["metrics"]["retraces_steady"]
    fails = pg.diff_lane(base, fresh)
    assert any(f["metric"] == "retraces_steady" and f["got"] is None
               for f in fails)


def test_added_and_removed_lanes_are_loud():
    base = {"a": _rec(), "b": _rec()}
    report = pg.diff_snapshots(base, {"a": _rec(), "c": _rec()})
    assert not report["ok"]
    assert report["added"] == ["c"]
    assert report["removed"] == ["b"]
    assert report["lanes"]["b"]["verdict"] == "removed"
    assert report["lanes"]["c"]["verdict"] == "added"
    lines = "\n".join(pg.report_lines(report))
    assert "[ADDED]" in lines and "[GONE ]" in lines
    assert "perfgate verdict: FAIL" in lines


# -- canonical serialization + digest ----------------------------------------

def test_canonical_strips_volatile_observed_block():
    lanes = pg.canonical_lanes({"a": _rec()})
    assert "observed" not in lanes["a"]
    assert "metrics" in lanes["a"]
    # wall-clock differences therefore never move the digest
    other = _rec(observed={"steady_wall_s": 99.0, "wall_s_per_step": 9.0,
                           "measured_mfu": 0.9})
    assert pg.lanes_digest({"a": _rec()}) == pg.lanes_digest({"a": other})


def test_dump_doc_is_byte_deterministic(tmp_path):
    doc1 = pg.canonical_doc({"a": _rec()}, reasons=[{"reason": "r"}])
    doc2 = pg.canonical_doc({"a": _rec()}, reasons=[{"reason": "r"}])
    assert pg.dump_doc(doc1) == pg.dump_doc(doc2)
    p = tmp_path / "b.json"
    p.write_text(pg.dump_doc(doc1))
    assert pg.load_baseline(str(p))["digest"] == doc1["digest"]


def test_hand_edited_baseline_rejected(tmp_path):
    doc = pg.canonical_doc({"a": _rec()}, reasons=[])
    doc["lanes"]["a"]["metrics"]["flops"] += 1          # the hand edit
    p = tmp_path / "edited.json"
    p.write_text(pg.dump_doc(doc))
    with pytest.raises(pg.BaselineError, match="digest mismatch"):
        pg.load_baseline(str(p))


def test_corrupt_and_invalid_baselines_rejected(tmp_path):
    p = tmp_path / "x.json"
    with pytest.raises(pg.BaselineError, match="no committed baseline"):
        pg.load_baseline(str(p))
    p.write_text("{not json")
    with pytest.raises(pg.BaselineError, match="not valid JSON"):
        pg.load_baseline(str(p))
    with pytest.raises(pg.BaselineError, match="schema"):
        pg.validate_baseline({"schema": 99, "lanes": {"a": _rec()}})
    incomplete = _rec()
    del incomplete["metrics"]["analytic_mfu"]
    doc = pg.canonical_doc({"a": incomplete}, reasons=[])
    with pytest.raises(pg.BaselineError, match="missing metrics"):
        pg.validate_baseline(doc)


def test_committed_baseline_is_valid_and_covers_lane_registry():
    doc = pg.load_baseline(BASELINE)
    assert set(doc["lanes"]) == set(pg.lane_names())
    assert len(doc["lanes"]) >= 6
    assert doc["reasons"], "the append-only reason log must not be empty"


def test_default_baseline_path_env_override(monkeypatch):
    monkeypatch.setenv("MXNET_PERFGATE_BASELINE", "/tmp/elsewhere.json")
    assert pg.default_baseline_path() == "/tmp/elsewhere.json"
    monkeypatch.delenv("MXNET_PERFGATE_BASELINE")
    assert pg.default_baseline_path() == BASELINE


# -- live delta (httpd /perfgate.json + telemetry_report --perf-diff) --------

def _doc_one_lane():
    return pg.canonical_doc({"a": _rec()}, reasons=[])


def test_live_delta_overlap_within_band():
    delta = pg.live_delta(_doc_one_lane(), {
        "train.step": {"flops": 1010000.0, "bytes_accessed": 402000.0,
                       "peak_bytes": 820000, "executables": 5, "calls": 99}})
    assert delta["ok"] and delta["overlap_sites"] == 1
    assert delta["lanes"]["a"]["verdict"] == "ok"


def test_live_delta_drift_and_no_overlap():
    delta = pg.live_delta(_doc_one_lane(),
                          {"train.step": {"flops": 2000000.0,
                                          "bytes_accessed": 400000.0,
                                          "peak_bytes": 800000}},
                          counters={"mxnet_op_dispatch_total": 3})
    assert not delta["ok"]
    assert any(f["metric"] == "sites.train.step.flops"
               for f in delta["lanes"]["a"]["failures"])
    assert delta["live_counters"] == {"mxnet_op_dispatch_total": 3}
    empty = pg.live_delta(_doc_one_lane(), {"other.site": {"flops": 1.0}})
    assert empty["ok"] and empty["overlap_sites"] == 0
    assert empty["lanes"]["a"]["verdict"] == "no-overlap"


def test_httpd_perfgate_endpoint(monkeypatch):
    port = httpd.start(port=0)
    try:
        # no committed baseline at the override path -> 404 with JSON body
        monkeypatch.setenv("MXNET_PERFGATE_BASELINE", "/nonexistent/b.json")
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/perfgate.json", timeout=10)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert json.load(e)["error"] == "no committed baseline"
        # the committed repo baseline -> 200 live delta
        monkeypatch.delenv("MXNET_PERFGATE_BASELINE")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/perfgate.json", timeout=10) as r:
            body = json.load(r)
        assert body["baseline_path"] == BASELINE
        assert "lanes" in body and "ok" in body
        # the /statusz row renders the same verdict machinery
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz", timeout=10) as r:
            status = json.load(r)
        assert status["perfgate"] in ("ok", "no-overlap", "drift")
    finally:
        httpd.stop()


def test_telemetry_report_perf_diff(tmp_path):
    base = pg.canonical_doc({"a": _rec()}, reasons=[])
    bp = tmp_path / "b.json"
    bp.write_text(pg.dump_doc(base))
    shard = {
        "rank": 0, "pid": 1, "host": "t", "events": [], "metrics": [
            {"kind": "counter", "name": "mxnet_op_dispatch_total",
             "value": 4}],
        "costmodel": {"entries": [
            {"site": "train.step", "flops": 1000000.0,
             "bytes_accessed": 400000.0, "peak_bytes": 800000}],
            "calls": {"train.step": 4}},
    }
    (tmp_path / "telemetry-rank0-pid1.json").write_text(json.dumps(shard))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         "--dir", str(tmp_path), "--perf-diff", str(bp)],
        capture_output=True, text=True, env=env, timeout=120, cwd=REPO)
    assert ok.returncode == 0, ok.stderr
    assert "perf-diff verdict: ok" in ok.stdout
    # drift the shard's flops far past the 2% band -> exit 2
    shard["costmodel"]["entries"][0]["flops"] = 2000000.0
    (tmp_path / "telemetry-rank0-pid1.json").write_text(json.dumps(shard))
    bad = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         "--dir", str(tmp_path), "--perf-diff", str(bp)],
        capture_output=True, text=True, env=env, timeout=120, cwd=REPO)
    assert bad.returncode == 2, bad.stdout + bad.stderr
    assert "DRIFT" in bad.stderr


# -- the injected-regression red path ----------------------------------------

@pytest.fixture
def clean_capture():
    """Lane runners arm the tracer/ledger; restore the disarmed default."""
    yield
    costmodel.disarm()
    costmodel.LEDGER.clear()
    tracer.disable()
    telemetry.clear()


def test_injected_regression_turns_gate_red(monkeypatch, clean_capture):
    """MXNET_KVSTORE_BUCKET_MB=0 degrades fused pushpull to the per-key
    loop; the gate must catch the dispatch-per-step explosion.  The knob
    is read at kvstore construction, so two in-process lane runs see the
    clean and the degraded worlds."""
    monkeypatch.delenv("MXNET_KVSTORE_BUCKET_MB", raising=False)
    clean = pg.run_lane("trainer_fused_kvstore")
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", "0")
    degraded = pg.run_lane("trainer_fused_kvstore")
    report = pg.diff_snapshots({"trainer_fused_kvstore": clean},
                               {"trainer_fused_kvstore": degraded})
    assert not report["ok"], "the gate stayed green under the regression"
    fails = report["lanes"]["trainer_fused_kvstore"]["failures"]
    assert any(f["metric"] == "dispatches_per_step" for f in fails), fails
    # the explosion direction: strictly more dispatches than the fused path
    assert (degraded["metrics"]["dispatches_per_step"]
            > clean["metrics"]["dispatches_per_step"])


# -- CLI wiring --------------------------------------------------------------

def _run(cmd, timeout=120, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.run([sys.executable] + cmd, capture_output=True,
                          text=True, env=env, timeout=timeout, cwd=REPO)


def test_cli_check_rejects_corrupt_baseline(tmp_path):
    p = tmp_path / "corrupt.json"
    p.write_text("{not json")
    r = _run([os.path.join(REPO, "tools", "perfgate.py"), "--check",
              "--baseline", str(p)])
    assert r.returncode == 2
    assert "not valid JSON" in r.stderr


def test_cli_write_baseline_requires_reason():
    r = _run([os.path.join(REPO, "tools", "perfgate.py"),
              "--write-baseline"])
    assert r.returncode == 2
    assert "--reason" in r.stderr


def test_cli_list_names_every_lane():
    r = _run([os.path.join(REPO, "tools", "perfgate.py"), "--list"])
    assert r.returncode == 0, r.stderr
    for name in pg.lane_names():
        assert name in r.stdout


def test_cli_write_baseline_byte_deterministic(tmp_path):
    """Two independent child snapshots of the same lane serialize to the
    exact same bytes — the acceptance bar for committing the baseline."""
    cmd = [os.path.join(REPO, "tools", "perfgate.py"), "--write-baseline",
           "--reason", "determinism test", "--lanes",
           "trainer_fused_kvstore"]
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    for p in (a, b):
        r = _run(cmd + ["--baseline", str(p)], timeout=300)
        assert r.returncode == 0, r.stderr
    assert a.read_bytes() == b.read_bytes()
    doc = pg.load_baseline(str(a))
    assert list(doc["lanes"]) == ["trainer_fused_kvstore"]


# -- the on-chip sweep (ROADMAP 1) -------------------------------------------

def test_sweep_dryrun_executes_every_lane(tmp_path):
    """The CPU wiring proof: every r6–r12 addendum lane runs end to end,
    emits one consolidated BENCH row, and the analytic-MFU pin against
    the committed baseline holds."""
    out = tmp_path / "sweep.json"
    r = _run([os.path.join(REPO, "tools", "onchip_sweep.py"), "--dryrun",
              "--json", str(out)], timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    rows = [json.loads(l) for l in r.stdout.splitlines()
            if l.startswith("{")]
    by_metric = {row["metric"]: row for row in rows}
    for lane in ("r06_opt_fusion", "r07_serve_knee", "r08_data_pipeline",
                 "r09_mesh_mfu", "r10_analytic_mfu", "r11_fsdp_crossover",
                 "r12_spec_prefix"):
        assert by_metric[f"sweep_{lane}"]["ok"], by_metric[f"sweep_{lane}"]
    summary = by_metric["onchip_sweep_summary"]
    assert summary["lanes"] == 7 and summary["failed"] == []
    # the analytic rows answer to the same committed baseline as the gate
    for lane in ("sweep_r09_mesh_mfu", "sweep_r10_analytic_mfu"):
        assert by_metric[lane]["mfu"]["analytic_within_gate_band"]
    # r7+r12 ride ONE serve_bench child
    assert by_metric["sweep_r12_spec_prefix"].get("shared_run") is True
    # the planner lane re-proves the committed golden
    assert by_metric["sweep_r11_fsdp_crossover"]["plan_matches_golden"]
    report = json.loads(out.read_text())
    assert len(report["lanes"]) == 7


def test_sweep_budget_exhaustion_skips_loudly():
    r = _run([os.path.join(REPO, "tools", "onchip_sweep.py"), "--dryrun",
              "--budget-s", "0", "--lanes", "r11"])
    assert r.returncode == 1
    row = json.loads(r.stdout.splitlines()[0])
    assert row["skipped"] == "budget exhausted"
    assert "budget exhausted" in r.stderr


def test_sweep_unknown_lane_rejected():
    r = _run([os.path.join(REPO, "tools", "onchip_sweep.py"), "--dryrun",
              "--lanes", "r99"])
    assert r.returncode != 0
    assert "unknown lane" in (r.stderr + r.stdout)
