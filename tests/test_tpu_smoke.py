"""Real-TPU smoke lane (VERDICT r2 item 9; SURVEY §4.2 GPU-suite trick).

Run with ``MXNET_TEST_DEVICE=tpu python -m pytest tests/test_tpu_smoke.py``
on a machine with the axon chip: conftest then leaves the TPU platform
active and these tests cross-check every kernel against the CPU backend —
``check_consistency(cpu, tpu)``, the universal kernel oracle.

Skipped on the CPU-only test platform (the rest of the suite).
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import check_consistency

pytestmark = pytest.mark.skipif(
    os.environ.get("MXNET_TEST_DEVICE") != "tpu",
    reason="TPU smoke lane: set MXNET_TEST_DEVICE=tpu on the chip")


def _ctxs():
    return [mx.cpu(), mx.tpu()]


def test_tpu_visible():
    assert mx.context.num_tpus() >= 1
    a = mx.nd.ones((2, 2), ctx=mx.tpu())
    assert "tpu" in str(a.ctx).lower() or "axon" in str(a.ctx).lower() \
        or a.ctx.device_type in ("tpu", "gpu")


@pytest.mark.parametrize("op,shapes", [
    (lambda a, b: mx.nd.dot(a, b), [(8, 16), (16, 4)]),
    (lambda a, b: mx.nd.broadcast_add(a, b), [(4, 5), (1, 5)]),
    (lambda a, b: a * b + 2, [(3, 3), (3, 3)]),
    (lambda a, b: mx.nd.batch_dot(a, b), [(2, 3, 4), (2, 4, 5)]),
])
def test_binary_kernels_cpu_vs_tpu(op, shapes):
    r = np.random.RandomState(0)
    ins = [r.randn(*s).astype(np.float32) for s in shapes]
    check_consistency(op, ins, ctx_list=_ctxs(), rtol=2e-2, atol=2e-3)


@pytest.mark.parametrize("op,shape", [
    (lambda a: mx.nd.softmax(a, axis=-1), (6, 10)),
    (lambda a: mx.nd.log_softmax(a, axis=-1), (6, 10)),
    (lambda a: mx.nd.relu(a), (4, 4)),
    (lambda a: mx.nd.sigmoid(a), (4, 4)),
    (lambda a: mx.nd.tanh(a), (4, 4)),
    (lambda a: mx.nd.exp(a), (4, 4)),
    (lambda a: mx.nd.sum(a, axis=1), (5, 7)),
    (lambda a: mx.nd.max(a, axis=0), (5, 7)),
    (lambda a: mx.nd.LayerNorm(a, mx.nd.ones((7,)), mx.nd.zeros((7,))),
     (5, 7)),
    (lambda a: mx.nd.transpose(a), (3, 8)),
    (lambda a: mx.nd.topk(a, k=3, axis=-1, ret_typ="value"), (4, 9)),
])
def test_unary_kernels_cpu_vs_tpu(op, shape):
    r = np.random.RandomState(1)
    # LayerNorm closure builds params on the default ctx; rebuild per ctx
    ins = [r.randn(*shape).astype(np.float32)]
    outs = []
    for ctx in _ctxs():
        with mx.Context(ctx):
            a = mx.nd.array(ins[0], ctx=ctx)
            outs.append(np.asarray(op(a).asnumpy()))
    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-2, atol=2e-3)


def test_conv_bn_cpu_vs_tpu():
    r = np.random.RandomState(2)
    x = r.randn(2, 3, 16, 16).astype(np.float32)
    w = r.randn(8, 3, 3, 3).astype(np.float32)

    def f(xa, wa):
        return mx.nd.Convolution(xa, wa, kernel=(3, 3), pad=(1, 1),
                                 num_filter=8, no_bias=True)

    check_consistency(f, [x, w], ctx_list=_ctxs(), rtol=2e-2, atol=2e-3)


def test_grad_cpu_vs_tpu():
    r = np.random.RandomState(3)
    xn = r.randn(4, 6).astype(np.float32)
    wn = r.randn(6, 2).astype(np.float32)
    grads = []
    for ctx in _ctxs():
        w = mx.nd.array(wn, ctx=ctx)
        w.attach_grad()
        x = mx.nd.array(xn, ctx=ctx)
        with autograd.record():
            loss = mx.nd.softmax_cross_entropy(
                mx.nd.dot(x, w), mx.nd.array([0, 1, 0, 1], ctx=ctx))
        loss.backward()
        grads.append(w.grad.asnumpy())
    np.testing.assert_allclose(grads[0], grads[1], rtol=2e-2, atol=2e-3)


def test_gluon_train_step_cpu_vs_tpu():
    from mxnet_tpu import gluon
    # per-ctx RNG streams differ by design (reference: per-device seeds),
    # so draw the params ONCE host-side and load them into both runs
    rp = np.random.RandomState(11)
    w0 = (rp.randn(4, 8) * 0.3).astype(np.float32)
    b0 = np.zeros((4,), np.float32)
    losses = {}
    for ctx in _ctxs():
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize(ctx=ctx)
        net.weight.set_data(mx.nd.array(w0, ctx=ctx))
        net.bias.set_data(mx.nd.array(b0, ctx=ctx))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        lf = gluon.loss.SoftmaxCrossEntropyLoss()
        r = np.random.RandomState(5)
        x = mx.nd.array(r.randn(8, 8).astype(np.float32), ctx=ctx)
        y = mx.nd.array(r.randint(0, 4, (8,)), ctx=ctx)
        cur = []
        for _ in range(3):
            with autograd.record():
                loss = lf(net(x), y)
            loss.backward()
            tr.step(8)
            cur.append(float(loss.mean().asnumpy()))
        losses[str(ctx)] = cur
    vals = list(losses.values())
    np.testing.assert_allclose(vals[0], vals[1], rtol=2e-2, atol=2e-3)


def test_tpu_int8_quantized_fc_consistency():
    """INT8 path produces identical quantized results cpu-vs-tpu (integer
    arithmetic — results are exact, not approximate)."""
    r = np.random.RandomState(12)
    x = r.randn(32, 64).astype(np.float32)
    w = (r.randn(16, 64) * 0.4).astype(np.float32)
    outs = {}
    for ctx in _ctxs():
        nd = mx.nd
        qx, xmin, xmax = nd.contrib.quantize_v2(nd.array(x, ctx=ctx))
        qw, wmin, wmax = nd.contrib.quantize_v2(nd.array(w, ctx=ctx))
        o32, omin, omax = nd.contrib.quantized_fully_connected(
            qx, qw, xmin, xmax, wmin, wmax)
        outs[str(ctx)] = nd.contrib.dequantize(o32, omin, omax).asnumpy()
    vals = list(outs.values())
    np.testing.assert_allclose(vals[0], vals[1], rtol=1e-5, atol=1e-6)


def test_tpu_ctc_loss_consistency():
    r = np.random.RandomState(13)
    logits = r.randn(12, 2, 6).astype(np.float32)
    label = np.array([[1, 2, 3, 0], [4, 2, 0, 0]], np.float32)
    outs = {}
    for ctx in _ctxs():
        outs[str(ctx)] = mx.nd.ctc_loss(
            mx.nd.array(logits, ctx=ctx),
            mx.nd.array(label, ctx=ctx)).asnumpy()
    vals = list(outs.values())
    np.testing.assert_allclose(vals[0], vals[1], rtol=1e-4, atol=1e-5)


def test_tpu_deformable_conv_consistency():
    r = np.random.RandomState(14)
    x = r.randn(1, 3, 8, 8).astype(np.float32)
    w = r.randn(4, 3, 3, 3).astype(np.float32)
    off = (r.randn(1, 18, 6, 6) * 0.5).astype(np.float32)
    outs = {}
    for ctx in _ctxs():
        outs[str(ctx)] = mx.nd.contrib.DeformableConvolution(
            mx.nd.array(x, ctx=ctx), mx.nd.array(off, ctx=ctx),
            mx.nd.array(w, ctx=ctx), kernel=(3, 3),
            num_filter=4).asnumpy()
    vals = list(outs.values())
    np.testing.assert_allclose(vals[0], vals[1], rtol=1e-3, atol=1e-4)


def test_tpu_flash_attention_consistency():
    """flash ≡ dense numerics ON THE CHIP (VERDICT r3 item 1).

    On the tpu ctx, contrib.masked_selfatt lowers to the in-house Pallas
    flash kernel (kernels/flash_attention.py); on the cpu ctx the same op
    lowers to the dense fp32 path.  Agreement across the two ctxs is the
    flash-vs-dense oracle running where it matters.  The probe assert
    proves the kernel actually compiled (no silent dense fallback)."""
    from mxnet_tpu.ops import contrib as C
    L, B, H, D = 256, 2, 4, 64
    r = np.random.RandomState(21)
    qkv = (r.randn(L, B, 3 * H * D) * 0.3).astype(np.float32)
    vl = np.array([200, 256], np.float32)
    outs = {}
    for ctx in _ctxs():
        outs[str(ctx)] = mx.nd.contrib.masked_selfatt(
            mx.nd.array(qkv, ctx=ctx), mx.nd.array(vl, ctx=ctx),
            heads=H).asnumpy()
    # the probe only proves a compile when the backend really is TPU —
    # off-tpu it short-circuits True and the dense path runs everywhere
    import jax
    assert jax.default_backend() == "tpu", \
        "smoke lane expected the TPU backend, got " + jax.default_backend()
    assert C._PALLAS_PROBE[0] is True, \
        "Pallas flash kernel failed its compile probe on this toolchain"
    vals = list(outs.values())
    # valid q rows only: pad rows are defined (pad attends pad) but noisy
    mask = (np.arange(L)[:, None, None] < vl[None, :, None])
    np.testing.assert_allclose(vals[0] * mask, vals[1] * mask,
                               rtol=5e-2, atol=5e-3)


def test_tpu_flash_attention_grad_consistency():
    """Custom-VJP flash backward ≡ dense autodiff backward on the chip,
    causal + GQA via masked_att_qkv (the llama path)."""
    r = np.random.RandomState(22)
    B, Hq, Hkv, L, D = 2, 4, 2, 256, 64   # L >= 256: the flash floor
    qn = (r.randn(B, Hq, L, D) * 0.3).astype(np.float32)
    kn = (r.randn(B, Hkv, L, D) * 0.3).astype(np.float32)
    vn = (r.randn(B, Hkv, L, D) * 0.3).astype(np.float32)
    vl = np.array([100, 128], np.float32)
    grads = {}
    for ctx in _ctxs():
        q = mx.nd.array(qn, ctx=ctx)
        k = mx.nd.array(kn, ctx=ctx)
        v = mx.nd.array(vn, ctx=ctx)
        for t in (q, k, v):
            t.attach_grad()
        # mask pad rows OUT of the loss: flash hard-masks pads while dense
        # soft-masks (-1e9), so pad-position outputs/grads differ by design
        # and say nothing about the kernel (same reason the forward test
        # compares valid rows only)
        wmask = mx.nd.array(
            (np.arange(L)[None, None, :, None] < vl[None, :, None, None])
            .astype(np.float32).transpose(1, 0, 2, 3), ctx=ctx)
        with autograd.record():
            out = mx.nd.contrib.masked_att_qkv(
                q, k, v, mx.nd.array(vl, ctx=ctx),
                num_kv_groups=Hq // Hkv, causal=True)
            loss = (out * out * wmask).sum()
        loss.backward()
        grads[str(ctx)] = [t.grad.asnumpy() for t in (q, k, v)]
    a, b = list(grads.values())
    vmask = (np.arange(L)[None, None, :, None] < vl[:, None, None, None])
    for name, ga, gb in zip("qkv", a, b):
        np.testing.assert_allclose(ga * vmask, gb * vmask,
                                   rtol=5e-2, atol=5e-2,
                                   err_msg=f"d{name} mismatch")


def test_tpu_sparse_dot_consistency():
    """csr SpMM kernel (gather + segment-sum) cpu-vs-tpu."""
    from mxnet_tpu.ndarray import sparse
    r = np.random.RandomState(31)
    d = r.randn(8, 12).astype(np.float32)
    d[r.rand(8, 12) > 0.35] = 0.0
    rhs_np = r.randn(12, 5).astype(np.float32)   # ONE draw for both ctxs
    outs = {}
    for ctx in _ctxs():
        with mx.context.Context(ctx):
            csr = sparse.csr_matrix(d, ctx=ctx)
            rhs = mx.nd.array(rhs_np, ctx=ctx)
            outs[str(ctx)] = sparse.dot(csr, rhs).asnumpy()
    vals = list(outs.values())
    np.testing.assert_allclose(vals[0], vals[1], rtol=2e-2, atol=2e-3)
    np.testing.assert_allclose(vals[0], d @ rhs_np, rtol=2e-2, atol=2e-3)


def test_tpu_multi_sgd_consistency():
    """Fused multi-tensor update matches singles ON THE CHIP."""
    r = np.random.RandomState(33)
    ws = [r.randn(6, 4).astype(np.float32) for _ in range(3)]
    gs = [r.randn(6, 4).astype(np.float32) for _ in range(3)]
    lrs = np.array([0.1, 0.05, 0.2], np.float32)
    wds = np.array([0.0, 0.01, 0.0], np.float32)
    outs = {}
    for ctx in _ctxs():
        ins = [x for w, g in zip(ws, gs)
               for x in (mx.nd.array(w, ctx=ctx), mx.nd.array(g, ctx=ctx))]
        res = mx.nd.multi_sgd_update(
            *ins, mx.nd.array(lrs, ctx=ctx), mx.nd.array(wds, ctx=ctx),
            rescale_grad=1.0, num_weights=3)
        outs[str(ctx)] = [o.asnumpy() for o in res]
    a, b = list(outs.values())
    for x, y in zip(a, b):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)


def test_tpu_flash_encdec_attention_consistency():
    """Cross-attention (contrib.masked_encdec_att, r5 — the MT decoder's
    fused op) flash ≡ dense ON THE CHIP, with Lq != Lk and source-padding
    masking via the kernel's separate seg_q/seg_kv inputs."""
    Lq, Lk, B, H, D = 256, 512, 2, 4, 64
    r = np.random.RandomState(23)
    q = (r.randn(Lq, B, H * D) * 0.3).astype(np.float32)
    kv = (r.randn(Lk, B, 2 * H * D) * 0.3).astype(np.float32)
    vl = np.array([400, 512], np.float32)
    outs = {}
    for ctx in _ctxs():
        outs[str(ctx)] = mx.nd.contrib.masked_encdec_att(
            mx.nd.array(q, ctx=ctx), mx.nd.array(kv, ctx=ctx),
            mx.nd.array(vl, ctx=ctx), heads=H).asnumpy()
    vals = list(outs.values())
    np.testing.assert_allclose(vals[0], vals[1], rtol=5e-2, atol=5e-3)
