"""SparseMoE (gluon.contrib.moe) — dense-dispatch MoE + expert parallelism.

Reference: ABSENT upstream (SURVEY §2.4 "Expert parallel / MoE: ABSENT") —
validates the new GShard/Switch-style design: routing correctness vs a
per-token numpy oracle, capacity semantics, gradient flow, hybridize parity,
and an expert-parallel TrainStep on a dp×ep mesh matching single-device
numerics.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon.contrib import SparseMoE


def _make(units=8, hidden=16, E=4, k=2, cf=8.0, seed=0):
    moe = SparseMoE(units, hidden, E, num_experts_per_token=k,
                    capacity_factor=cf)
    mx.random.seed(seed)
    moe.initialize(mx.init.Xavier())
    return moe


def _numpy_oracle(moe, x):
    """Per-token reference: route each token to its top-k experts (no
    capacity pressure when cf is large), run the expert MLPs densely."""
    import scipy.special as sp
    g = moe.gate_weight.data().asnumpy()
    w1 = moe.expert_w1.data().asnumpy()
    b1 = moe.expert_b1.data().asnumpy()
    w2 = moe.expert_w2.data().asnumpy()
    b2 = moe.expert_b2.data().asnumpy()
    xf = x.reshape(-1, x.shape[-1])
    logits = xf @ g
    probs = sp.softmax(logits, axis=-1)
    k = moe._k
    out = np.zeros_like(xf)
    for n in range(xf.shape[0]):
        top = np.argsort(-probs[n])[:k]
        # Switch (k=1): raw router prob; GShard (k>1): normalized over top-k
        gates = probs[n][top] if k == 1 \
            else probs[n][top] / probs[n][top].sum()
        for gi, e in zip(gates, top):
            h = xf[n] @ w1[e] + b1[e]
            h = 0.5 * h * (1 + sp.erf(h / np.sqrt(2)))  # exact gelu
            out[n] += gi * (h @ w2[e] + b2[e])
    return out.reshape(x.shape)


def test_moe_matches_per_token_oracle():
    moe = _make()
    x = np.random.RandomState(1).randn(6, 3, 8).astype(np.float32)
    y, aux = moe(mx.nd.array(x))
    assert y.shape == x.shape
    assert aux.shape == ()
    np.testing.assert_allclose(y.asnumpy(), _numpy_oracle(moe, x),
                               rtol=2e-4, atol=2e-5)
    # balanced-ish random routing: aux stays near its minimum of 1.0
    assert 0.5 < float(aux.asnumpy()) < float(moe._E)


def test_moe_capacity_drops_overflow_tokens():
    """capacity_factor small enough that an expert overflows: dropped tokens
    contribute zero output (residual semantics), none crash."""
    units, E = 4, 2
    moe = SparseMoE(units, 8, E, num_experts_per_token=1,
                    capacity_factor=0.25)
    mx.random.seed(3)
    moe.initialize(mx.init.Xavier())
    N = 16
    x = np.random.RandomState(2).randn(N, units).astype(np.float32)
    y, _ = moe(mx.nd.array(x))
    # capacity C = ceil(1*16/2*0.25) = 2 slots/expert → ≤ 4 tokens served
    served = (np.abs(y.asnumpy()).sum(axis=1) > 1e-9).sum()
    assert served <= 2 * E


def test_moe_gradients_flow():
    moe = _make(seed=5)
    x = mx.nd.array(np.random.RandomState(4).randn(8, 8).astype(np.float32))
    with autograd.record():
        y, aux = moe(x)
        loss = y.square().mean() + 0.01 * aux
    loss.backward()
    for name, p in moe.collect_params().items():
        g = p.grad().asnumpy()
        assert np.isfinite(g).all(), name
    # router must receive gradient through both gates and aux loss
    assert np.abs(moe.gate_weight.grad().asnumpy()).max() > 0


@pytest.mark.parametrize("k", [1, 2])
def test_moe_router_gets_task_gradient_imperatively(k):
    """The combine-weight path must carry task-loss gradient to the router
    WITHOUT the aux term, in imperative mode (topk outputs are detached on
    the tape; gates are re-gathered from probs differentiably)."""
    moe = SparseMoE(8, 16, 4, num_experts_per_token=k, capacity_factor=8.0)
    mx.random.seed(9)
    moe.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(10).randn(8, 8).astype(np.float32))
    with autograd.record():
        y, _ = moe(x)
        loss = y.square().mean()      # no aux — pure task loss
    loss.backward()
    assert np.abs(moe.gate_weight.grad().asnumpy()).max() > 0


def test_moe_hybridize_parity():
    moe = _make(seed=7)
    x = mx.nd.array(np.random.RandomState(6).randn(4, 2, 8).astype(np.float32))
    y_imp, aux_imp = moe(x)
    moe.hybridize()
    y_hyb, aux_hyb = moe(x)
    np.testing.assert_allclose(y_imp.asnumpy(), y_hyb.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(aux_imp.asnumpy(), aux_hyb.asnumpy(),
                               rtol=1e-5)


def test_moe_expert_parallel_trainstep():
    """dp×ep mesh: expert weights sharded over 'ep', numerics match the
    single-device run step-for-step."""
    import jax
    from mxnet_tpu.parallel import DeviceMesh, TrainStep
    from mxnet_tpu.gluon import nn, HybridBlock

    units, hidden, E = 8, 16, 4

    class MoENet(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.moe = SparseMoE(units, hidden, E,
                                     num_experts_per_token=2,
                                     capacity_factor=8.0)
                self.head = nn.Dense(2, flatten=False, in_units=units)

        def hybrid_forward(self, F, x):
            y, aux = self.moe(x)
            self._aux = aux
            return self.head(y)

    def loss_fn(out, label):
        from mxnet_tpu.gluon import loss as gloss
        return gloss.L2Loss()(out, label)

    rs = np.random.RandomState(8)
    x = rs.randn(16, units).astype(np.float32)
    lbl = rs.randn(16, 2).astype(np.float32)

    def run(mesh):
        mx.random.seed(11)
        net = MoENet()
        net.initialize(mx.init.Xavier())
        step = TrainStep(net, loss_fn, "sgd", {"learning_rate": 0.1},
                         mesh=mesh)
        losses = [float(step(mx.nd.array(x), mx.nd.array(lbl)).asnumpy())
                  for _ in range(3)]
        return losses

    single = run(DeviceMesh(devices=jax.devices()[:1], axis_names=("dp",)))
    mesh = DeviceMesh(shape=(2, 4), axis_names=("dp", "ep"))
    sharded = run(mesh)
    np.testing.assert_allclose(single, sharded, rtol=1e-4, atol=1e-5)
    assert sharded[-1] < sharded[0]
