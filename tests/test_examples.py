"""Example-lane smoke tests: every script in examples/ must run end-to-end
with tiny settings and actually learn (reference: tests/python/train/ +
the CI example runners in ci/docker/runtime_functions.sh)."""

import importlib.util
import os
import sys

import numpy as np
import pytest

_EX = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _load(rel):
    path = os.path.join(_EX, rel)
    spec = importlib.util.spec_from_file_location(
        rel.replace("/", "_").replace(".py", ""), path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_train_mnist_learns():
    mod = _load("image_classification/train_mnist.py")
    hist = mod.run(ctx_name="cpu", epochs=2, batch_size=32, lr=0.02,
                   log=False, synthetic_samples=256)
    assert hist[-1]["acc"] > hist[0]["acc"] or hist[-1]["acc"] > 0.5
    assert hist[-1]["loss"] < hist[0]["loss"]


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_train_resnet_reports_throughput():
    mod = _load("image_classification/train_resnet.py")
    rec = mod.run(model="resnet18_v1", batch_size=4, image_size=32,
                  steps=2, warmup=1, classes=10, log=False)
    assert rec["images_per_sec"] > 0


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_bert_pretrain_loss_drops():
    mod = _load("bert/pretrain.py")
    rec = mod.run(num_layers=2, units=64, heads=4, batch=8, seq_len=32,
                  vocab=200, steps=6, warmup=1, lr=5e-3, log=False)
    assert rec["last_loss"] < rec["first_loss"]


@pytest.mark.slow  # compile-heavy; excluded from the tier-1 timing budget
def test_lstm_lm_perplexity_drops():
    mod = _load("rnn/lstm_lm.py")
    hist = mod.run(vocab=32, emb=16, hidden=32, layers=1, bptt=8,
                   batch_size=4, epochs=2, corpus_len=1024, log=False)
    assert hist[-1]["perplexity"] < hist[0]["perplexity"]


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_ssd_trains_and_detects():
    mod = _load("ssd/train_ssd.py")
    rec = mod.run(batch=16, steps=40, log=False)
    assert rec["last_loss"] < rec["first_loss"]
    assert rec["mean_top_iou"] > 0.05     # detections overlap ground truth


def test_pipeline_example_dp_pp():
    mod = _load("pipeline/train_pipeline.py")
    rec = mod.run(depth=4, pp=4, dp=2, steps=15, log=False)
    assert rec["last_loss"] < rec["first_loss"]
    assert rec["bubble_fraction"] < 0.5


def test_moe_example_expert_parallel():
    mod = _load("moe/train_moe.py")
    rec = mod.run(steps=12, dp=2, ep=4, log=False)
    assert rec["last_loss"] < rec["first_loss"]


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_quantize_net_example():
    mod = _load("quantization/quantize_net.py")
    rec = mod.run(model="resnet18_v1", batch=4, image_size=32, classes=10,
                  calib_mode="naive", calib_batches=2, log=False)
    assert rec["top1_agreement"] >= 0.75
    assert rec["max_rel_err"] < 0.2


def test_matrix_factorization_model_parallel():
    mod = _load("model_parallel/matrix_factorization.py")
    rec = mod.run(num_users=64, num_items=64, factor=16, batch=64,
                  steps=10, mp=2, lr=0.1, log=False)
    assert rec["last_loss"] < rec["first_loss"]
    # single-device run matches the mp=2 run step-for-step
    rec1 = mod.run(num_users=64, num_items=64, factor=16, batch=64,
                   steps=10, mp=1, lr=0.1, log=False)
    np.testing.assert_allclose(rec["last_loss"], rec1["last_loss"],
                               rtol=1e-4)


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_dist_train_example_two_workers():
    """The examples/distributed lane end-to-end: 2 localhost workers via
    tools/launch.py, dist_tpu_sync Trainer, loss drops, exact grad-sum
    (VERDICT r3 item 3; reference tools/launch.py + dist_sync flow)."""
    import subprocess
    root = os.path.dirname(_EX)
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "launch.py"),
         "-n", "2", "--cpu-devices", "1",
         sys.executable, os.path.join(_EX, "distributed", "dist_train.py"),
         "--steps", "15"],
        capture_output=True, text=True, timeout=420, cwd=root)
    assert r.returncode == 0, r.stdout[-800:] + r.stderr[-800:]
    assert "OK" in r.stdout


def test_launch_ssh_command_construction():
    """ssh launcher builds per-rank commands with coordinator/rank env
    inlined (dmlc_tracker/ssh.py role) and round-robins hosts."""
    import importlib.util
    root = os.path.dirname(_EX)
    spec = importlib.util.spec_from_file_location(
        "launch", os.path.join(root, "tools", "launch.py"))
    launch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch)
    cmds = launch.build_ssh_commands(
        3, ["hostA", "hostB"], ["python", "train.py", "--lr", "0.1"],
        port=12345)
    assert len(cmds) == 3
    assert cmds[0][-2] == "hostA" and cmds[1][-2] == "hostB" \
        and cmds[2][-2] == "hostA"          # round-robin
    for rank, c in enumerate(cmds):
        assert c[0] == "ssh"
        remote = c[-1]
        assert f"MXNET_DIST_RANK={rank}" in remote
        assert "MXNET_DIST_COORDINATOR=hostA:12345" in remote
        assert "MXNET_DIST_NUM_WORKERS=3" in remote
        assert remote.endswith("python train.py --lr 0.1")
    # dry-run path prints and reports success without spawning
    codes = launch.launch_ssh(2, ["h1"], ["echo", "hi"], dry_run=True)
    assert codes == [0, 0]


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_transformer_mt_learns():
    mod = _load("transformer_mt/train_mt.py")
    rec = mod.run(vocab=24, layers=1, units=32, hidden=64, heads=2,
                  batch=8, steps=30, lr=3e-3, warmup=10, log=False,
                  decode_samples=2)
    assert rec["last_loss"] < rec["first_loss"]


@pytest.mark.slow  # compile-heavy; excluded from the tier-1 timing budget
def test_yolo3_trains_and_detects():
    mod = _load("yolo/train_yolo.py")
    rec = mod.run(batch=8, steps=25, log=False)
    assert rec["last_loss"] < rec["first_loss"]
