"""Ring attention (sequence parallelism) tests — parity against dense
attention on the virtual 8-device mesh, forward AND backward."""

import numpy as np
import pytest

import mxnet_tpu  # noqa: F401 — configures jax (x64 etc.)
import jax
import jax.numpy as jnp

from mxnet_tpu import parallel
from mxnet_tpu.kernels import sequence_parallel_attention


def _dense_ref(q, k, v, seg_q=None, seg_kv=None, causal=False, scale=1.0):
    s = np.einsum("bhqd,bhkd->bhqk", q, k).astype(np.float64) * scale
    B, H, Lq, Lk = s.shape
    mask = np.ones((B, 1, Lq, Lk), bool)
    if seg_q is not None:
        mask &= seg_q[:, None, :, None] == seg_kv[:, None, None, :]
    if causal:
        mask &= (np.arange(Lq)[:, None] >= np.arange(Lk)[None])[None, None]
    s = np.where(mask, s, -1e30)
    s -= s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v.astype(np.float64))


def _mesh(n):
    devs = jax.devices()[:n]
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return jax.sharding.Mesh(np.array(devs), ("sp",))


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal, seeded):
    B, H, L, D, n = 2, 3, 32, 8, 4
    r = np.random.RandomState(0)
    q = r.randn(B, H, L, D).astype(np.float32)
    k = r.randn(B, H, L, D).astype(np.float32)
    v = r.randn(B, H, L, D).astype(np.float32)
    mesh = _mesh(n)
    out = sequence_parallel_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), mesh, axis="sp",
                                      causal=causal, sm_scale=0.5)
    ref = _dense_ref(q, k, v, causal=causal, scale=0.5)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_ring_segment_mask(seeded):
    B, H, L, D, n = 2, 2, 16, 4, 4
    r = np.random.RandomState(1)
    q = r.randn(B, H, L, D).astype(np.float32)
    k = r.randn(B, H, L, D).astype(np.float32)
    v = r.randn(B, H, L, D).astype(np.float32)
    # sample 0: 10 valid tokens; sample 1: full
    seg = np.ones((B, L), np.int32)
    seg[0, 10:] = 0
    mesh = _mesh(n)
    out = sequence_parallel_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh, axis="sp",
        seg_q=jnp.asarray(seg), seg_kv=jnp.asarray(seg), sm_scale=1.0)
    ref = _dense_ref(q, k, v, seg_q=seg, seg_kv=seg)
    np.testing.assert_allclose(np.asarray(out)[0, :, :10],
                               ref[0, :, :10], rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out)[1], ref[1], rtol=2e-4,
                               atol=2e-5)


def test_ring_seg_kv_only_is_honored(seeded):
    """A kv-side-only padding mask must not be silently dropped: padded
    keys (seg id != 0) are excluded from every query's context."""
    B, H, L, D, n = 1, 2, 16, 4, 4
    r = np.random.RandomState(2)
    q = r.randn(B, H, L, D).astype(np.float32)
    k = r.randn(B, H, L, D).astype(np.float32)
    v = r.randn(B, H, L, D).astype(np.float32)
    seg_kv = np.zeros((B, L), np.int32)
    seg_kv[0, 12:] = 1                    # last 4 keys are padding
    mesh = _mesh(n)
    out = sequence_parallel_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh, axis="sp",
        seg_kv=jnp.asarray(seg_kv), sm_scale=1.0)
    ref = _dense_ref(q, k, v, seg_q=np.zeros((B, L), np.int32),
                     seg_kv=seg_kv)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # compile-heavy; excluded from the tier-1 timing budget
def test_ring_gradients_match_dense(seeded):
    B, H, L, D, n = 1, 2, 16, 4, 4
    r = np.random.RandomState(2)
    q = jnp.asarray(r.randn(B, H, L, D).astype(np.float32))
    k = jnp.asarray(r.randn(B, H, L, D).astype(np.float32))
    v = jnp.asarray(r.randn(B, H, L, D).astype(np.float32))
    mesh = _mesh(n)

    def ring_loss(q, k, v):
        o = sequence_parallel_attention(q, k, v, mesh, axis="sp",
                                        causal=True, sm_scale=0.7)
        return (o.astype(jnp.float32) ** 2).sum()

    def dense_loss(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.7
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return (o ** 2).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=2e-3, atol=2e-4, err_msg=name)


def test_ring_rejects_indivisible_length():
    mesh = _mesh(4)
    x = jnp.zeros((1, 1, 10, 4))
    with pytest.raises(ValueError, match="divide"):
        sequence_parallel_attention(x, x, x, mesh, axis="sp")


def test_parallel_namespace_exports():
    assert parallel.attention is sequence_parallel_attention
    from mxnet_tpu.kernels.ring_attention import ring_attention
    assert parallel.ring_attention is ring_attention
