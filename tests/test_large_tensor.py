"""Large-tensor / int64-index coverage (VERDICT r3 missing item 6;
reference tests/nightly/test_large_array.py, SURVEY §4.1).

Two tiers, mirroring the reference's nightly split:

 - ALWAYS-RUN: int64 index/value SEMANTICS on modest buffers — values and
   indices beyond 2**31 must survive arange/argmax/take/indexing/shape
   math (this framework runs jax_enable_x64 precisely for MXNet's int64
   parity, and these tests pin that).
 - GATED (MXNET_TEST_LARGE_TENSOR=1): actual > 2**31-element allocations
   (>= 8.6 GB) — the reference runs these nightly on big-RAM hosts; the
   CI sandbox cannot hold them.
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

LARGE = int(os.environ.get("MXNET_TEST_LARGE_TENSOR", "0"))
OVER_I32 = 2 ** 31 + 7


def test_int64_values_roundtrip():
    vals = np.array([0, 2 ** 31 + 1, 2 ** 40, -2 ** 35], np.int64)
    a = nd.array(vals, dtype=np.int64)
    assert a.dtype == np.int64
    np.testing.assert_array_equal(a.asnumpy(), vals)
    # arithmetic stays in int64 (no silent i32 truncation)
    np.testing.assert_array_equal((a + 1).asnumpy(), vals + 1)
    np.testing.assert_array_equal((a * 2).asnumpy(), vals * 2)


def test_arange_beyond_int32():
    a = nd.arange(OVER_I32, OVER_I32 + 5, dtype=np.int64)
    np.testing.assert_array_equal(a.asnumpy(),
                                  np.arange(OVER_I32, OVER_I32 + 5))


def test_argmax_argmin_return_int64_capable_indices():
    x = nd.array(np.array([3.0, 9.0, 1.0], np.float32))
    idx = nd.argmax(x, axis=0)
    assert int(idx.asnumpy()) == 1
    # the index dtype must be able to carry > 2**31 positions
    assert np.dtype(idx.dtype).itemsize >= 8 \
        or np.dtype(idx.dtype).kind == "f"   # mxnet argmax returns f32 ids


def test_take_with_int64_indices():
    x = nd.array(np.arange(10, dtype=np.float32))
    idx = nd.array(np.array([9, 0, 5], np.int64), dtype=np.int64)
    np.testing.assert_array_equal(nd.take(x, idx).asnumpy(), [9.0, 0.0, 5.0])


def test_shape_size_arithmetic_beyond_int32():
    """size/shape products past 2**31 must not wrap (host-side int is
    arbitrary precision, but the nd surface must not cast through i32)."""
    big = nd.zeros((1, 1))
    # NDArray.size on a hypothetical large shape goes through python ints
    shape = (2 ** 20, 2 ** 12)   # 2**32 elements — just the arithmetic
    n = 1
    for s in shape:
        n *= s
    assert n == 2 ** 32
    # reshape bookkeeping with -1 handles > i32 products
    r = nd.arange(0, 6).reshape((2, 3)).reshape((-1,))
    assert r.shape == (6,)
    assert big.size == 1


@pytest.mark.skipif(not LARGE, reason="set MXNET_TEST_LARGE_TENSOR=1 on a "
                                      ">= 16 GB host (reference nightly)")
def test_allocate_beyond_int32_elements():
    n = 2 ** 31 + 8
    a = nd.zeros((n,), dtype=np.int8)
    assert a.size == n
    a[n - 1] = 7
    assert int(a[n - 1].asnumpy()) == 7


@pytest.mark.skipif(not LARGE, reason="set MXNET_TEST_LARGE_TENSOR=1 on a "
                                      ">= 16 GB host (reference nightly)")
def test_reduce_over_int32_boundary():
    n = 2 ** 31 + 8
    a = nd.ones((n,), dtype=np.int8)
    assert int(nd.sum(a.astype(np.int64)).asnumpy()) == n
