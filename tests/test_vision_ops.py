"""Vision/spatial op tests (reference test_operator.py patterns for
UpSampling/GridGenerator/BilinearSampler/SpatialTransformer/ROI/
Correlation + indexing misc)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_upsampling_nearest():
    x = nd.array(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert out.shape == (1, 1, 4, 4)
    np.testing.assert_array_equal(
        out.asnumpy()[0, 0],
        np.repeat(np.repeat(x.asnumpy()[0, 0], 2, 0), 2, 1))


def test_grid_generator_identity_affine():
    theta = nd.array(np.array([[1, 0, 0, 0, 1, 0]], np.float32))
    grid = nd.GridGenerator(theta, transform_type="affine",
                            target_shape=(3, 3))
    assert grid.shape == (1, 2, 3, 3)
    g = grid.asnumpy()
    np.testing.assert_allclose(g[0, 0, 0], [-1, 0, 1], atol=1e-6)  # x row
    np.testing.assert_allclose(g[0, 1, :, 0], [-1, 0, 1], atol=1e-6)  # y col


def test_bilinear_sampler_identity():
    r = np.random.RandomState(0)
    x = nd.array(r.randn(1, 2, 4, 4).astype(np.float32))
    theta = nd.array(np.array([[1, 0, 0, 0, 1, 0]], np.float32))
    grid = nd.GridGenerator(theta, transform_type="affine",
                            target_shape=(4, 4))
    out = nd.BilinearSampler(x, grid)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), atol=1e-5)


def test_spatial_transformer_shift():
    # translate by +2 pixels in x (theta tx in normalized coords)
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    tx = 2.0 * 2 / 3  # 2 pixels on a width-4 grid
    theta = nd.array(np.array([[1, 0, tx, 0, 1, 0]], np.float32))
    out = nd.SpatialTransformer(x, theta, target_shape=(4, 4),
                                transform_type="affine",
                                sampler_type="bilinear")
    o = out.asnumpy()[0, 0]
    xx = x.asnumpy()[0, 0]
    np.testing.assert_allclose(o[:, 0], xx[:, 2], atol=1e-4)
    np.testing.assert_allclose(o[:, 1], xx[:, 3], atol=1e-4)
    np.testing.assert_allclose(o[:, 2:], 0.0, atol=1e-5)  # out-of-range


def test_roi_align_constant_region():
    # constant image: every roi bin averages to the constant
    x = nd.array(np.full((1, 3, 8, 8), 5.0, np.float32))
    rois = nd.array(np.array([[0, 1, 1, 6, 6]], np.float32))
    out = nd.contrib.roi_align(x, rois, pooled_size=(2, 2),
                               spatial_scale=1.0, sample_ratio=2)
    assert out.shape == (1, 3, 2, 2)
    np.testing.assert_allclose(out.asnumpy(), 5.0, atol=1e-5)


def test_roi_pooling_shape_and_range():
    r = np.random.RandomState(1)
    x = nd.array(r.rand(2, 4, 8, 8).astype(np.float32))
    rois = nd.array(np.array([[0, 0, 0, 7, 7], [1, 2, 2, 6, 6]], np.float32))
    out = nd.ROIPooling(x, rois, pooled_size=(3, 3), spatial_scale=1.0)
    assert out.shape == (2, 4, 3, 3)
    assert out.asnumpy().min() >= 0.0
    assert out.asnumpy().max() <= 1.0


def test_crop():
    x = nd.array(np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4))
    out = nd.Crop(x, offset=(1, 1), h_w=(2, 2))
    np.testing.assert_array_equal(out.asnumpy()[0, 0],
                                  x.asnumpy()[0, 0, 1:3, 1:3])
    like = nd.zeros((1, 2, 2, 3))
    out2 = nd.Crop(x, like, num_args=2)
    assert out2.shape == (1, 2, 2, 3)


def test_correlation_self_displacement_zero():
    r = np.random.RandomState(2)
    x = nd.array(r.randn(1, 3, 6, 6).astype(np.float32))
    out = nd.Correlation(x, x, kernel_size=1, max_displacement=1,
                         stride1=1, stride2=1, pad_size=1)
    assert out.shape == (1, 9, 6, 6)
    o = out.asnumpy()
    # center channel (zero displacement) == mean over C of x*x
    np.testing.assert_allclose(o[0, 4], (x.asnumpy()[0] ** 2).mean(0),
                               rtol=1e-5)


def test_correlation_stride2_and_sad():
    """stride2 ∤ max_displacement keeps the zero-displacement center channel
    (reference radius = d // stride2), and is_multiply=False is a POSITIVE
    SAD cost volume (reference accumulates fabsf)."""
    r = np.random.RandomState(3)
    x = nd.array(r.randn(1, 2, 5, 5).astype(np.float32))
    out = nd.Correlation(x, x, kernel_size=1, max_displacement=3, stride2=2,
                         pad_size=3)
    # radius = 3 // 2 = 1 → displacements {-2, 0, 2} → 9 channels
    assert out.shape[1] == 9
    np.testing.assert_allclose(out.asnumpy()[0, 4],
                               (x.asnumpy()[0] ** 2).mean(0), rtol=1e-5)
    sad = nd.Correlation(x, x, kernel_size=1, max_displacement=1,
                         is_multiply=False).asnumpy()
    assert (sad >= 0).all()          # positive cost volume
    np.testing.assert_allclose(sad[0, 4], 0.0, atol=1e-6)  # self-SAD = 0


def test_batch_take_and_reshape_like():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    idx = nd.array(np.array([1, 3, 0]))
    np.testing.assert_array_equal(nd.batch_take(a, idx).asnumpy(),
                                  [1.0, 7.0, 8.0])
    b = nd.zeros((2, 6))
    np.testing.assert_array_equal(
        nd.reshape_like(a, b).asnumpy(), a.asnumpy().reshape(2, 6))


def test_ravel_unravel_roundtrip():
    flat = nd.array(np.array([0, 5, 11], np.int64))
    coords = nd.unravel_index(flat, shape=(3, 4))
    np.testing.assert_array_equal(coords.asnumpy(), [[0, 1, 2], [0, 1, 3]])
    back = nd.ravel_multi_index(coords, shape=(3, 4))
    np.testing.assert_array_equal(back.asnumpy(), [0, 5, 11])


def test_svm_output_hinge_gradient():
    from mxnet_tpu import autograd
    x = nd.array(np.array([[0.2, -0.3, 2.0]], np.float32))
    x.attach_grad()
    lab = nd.array(np.array([0.0]))
    with autograd.record():
        out = nd.SVMOutput(x, lab, margin=1.0,
                           regularization_coefficient=1.0, use_linear=True)
    np.testing.assert_array_equal(out.asnumpy(), x.asnumpy())  # identity fwd
    out.backward(nd.ones((1, 3)))
    # t = [+1, -1, -1]; violations: s0*1=0.2<1 yes; s1*-1=0.3<1 yes;
    # s2*-1=-2<1 yes → grads -t = [-1, +1, +1]
    np.testing.assert_allclose(x.grad.asnumpy(), [[-1.0, 1.0, 1.0]])
    # non-violating score: s2=2.0 with t=-1 → margin - (-2.0) = 3 > 0 still
    # violates; check a satisfied case: label-class score above margin
    x2 = nd.array(np.array([[5.0, -5.0]], np.float32))
    x2.attach_grad()
    with autograd.record():
        out2 = nd.SVMOutput(x2, nd.array(np.array([0.0])), use_linear=True)
    out2.backward(nd.ones((1, 2)))
    np.testing.assert_allclose(x2.grad.asnumpy(), 0.0)  # both satisfied


def test_roi_pooling_takes_max_not_center():
    # peak off the bin center must win (max pooling, not center sampling)
    img = np.zeros((1, 1, 8, 8), np.float32)
    img[0, 0, 1, 1] = 9.0
    rois = nd.array(np.array([[0, 0, 0, 7, 7]], np.float32))
    out = nd.ROIPooling(nd.array(img), rois, pooled_size=(2, 2),
                        spatial_scale=1.0)
    assert out.asnumpy()[0, 0, 0, 0] == 9.0  # exact pixel max found
    assert out.asnumpy()[0, 0, 1, 1] == 0.0


def test_correlation_zero_taps_no_wraparound():
    # taps beyond the image read ZEROS, never wrap to the other edge
    x = nd.array(np.array([[[[1, 2], [3, 4]]]], np.float32))
    out = nd.Correlation(x, x, kernel_size=1, max_displacement=1,
                         stride1=1, stride2=1, pad_size=1)
    o = out.asnumpy()
    assert o.shape == (1, 9, 2, 2)
    # channel (dy=0, dx=+1): out[i,j] = x[i,j] * x[i,j+1], zero past edge
    np.testing.assert_allclose(o[0, 5], [[2.0, 0.0], [12.0, 0.0]])
    # channel (dy=0, dx=-1): zero past the LEFT edge
    np.testing.assert_allclose(o[0, 3], [[0.0, 2.0], [0.0, 12.0]])
    with np.testing.assert_raises(Exception):
        nd.Correlation(x, x, kernel_size=2)  # even kernels rejected


def test_reshape_like_partial_ranges():
    a = nd.array(np.arange(210, dtype=np.float32).reshape(30, 7))
    b = nd.zeros((15, 2, 4))
    out = nd.reshape_like(a, b, lhs_begin=0, lhs_end=1, rhs_begin=0,
                          rhs_end=2)
    assert out.shape == (15, 2, 7)


def test_upsampling_bilinear_deconv_weight():
    # bilinear mode consumes a learnable deconv weight (reference lowers
    # to Deconvolution); with the standard bilinear kernel the output of
    # a constant image stays constant in the interior
    scale, C = 2, 1
    k = 2 * scale - scale % 2
    w = np.zeros((C, 1, k, k), np.float32)
    # standard bilinear upsample kernel
    f = (k + 1) // 2
    c = (k - 1) / (2.0 * f) if k % 2 == 0 else (k - 1) / 2.0 / f
    og = np.ogrid[:k, :k]
    filt = ((1 - abs(og[0] / f - c)) * (1 - abs(og[1] / f - c)))
    w[:, 0] = filt
    x = nd.array(np.ones((1, C, 3, 3), np.float32))
    out = nd.UpSampling(x, nd.array(w), scale=scale,
                        sample_type="bilinear", num_filter=C, num_args=2)
    assert out.shape[2] >= 6 and out.shape[3] >= 6
    # interior of a constant image stays ~constant
    interior = out.asnumpy()[0, 0, 2:-2, 2:-2]
    np.testing.assert_allclose(interior, interior.flat[0], rtol=1e-5)
