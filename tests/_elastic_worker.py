"""Worker body for the elastic-controller e2e suite (ISSUE 11
acceptance; tests/test_elastic_chaos.py).  Not collected by pytest.

This worker demonstrates the documented contract that makes "resize the
world" bit-exact — **shard-resident gradient accumulation** over a data
space fixed by ``MXNET_ELASTIC_WORLD_TARGET`` (W), independent of the
live world size n:

 - every step's global batch is W shards, seeded by (step, shard) only;
 - live rank r owns shards {s : s mod n == r}; for each shard s IN FIXED
   ORDER the job runs ONE kvstore allreduce to which exactly one rank
   contributes that shard's gradient and every other rank contributes
   zeros — so the summed result is the shard gradient EXACTLY (x + 0 is
   exact in IEEE arithmetic, in any association the collective picks);
 - each rank accumulates the W reduced shard gradients in the same fixed
   order and applies the same SGD update in float32.

Under that contract the parameter trajectory is a pure function of the
step count: killing ranks, shrinking to n=3, growing back to n=4, and
replaying from the topology-free checkpoint all reproduce the
uninterrupted fixed-n run's parameters BIT-identically.  The *resize
points* (which incarnation executed which steps) are recorded in the
checkpoint manifest's per-step world audit — that record is the "modulo
documented resize points" part of the acceptance criterion.

Modes (argv[1]):
 - ``clean`` — run all steps at the launched world size.
 - ``die``   — in incarnation 0 ONLY, the highest rank arms a chaos
   ``exit`` on ``kvstore.allreduce`` at step DIE_STEP: real worker death
   mid-collective.  Survivors exit via SIGTERM (controller drain) or the
   Deadline — every rank leaves a flight-recorder postmortem.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    try:
        # multi-proc CPU collectives need gloo BEFORE backend init
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass

jax.distributed.initialize(
    coordinator_address=os.environ["MXNET_DIST_COORDINATOR"],
    num_processes=int(os.environ["MXNET_DIST_NUM_WORKERS"]),
    process_id=int(os.environ["MXNET_DIST_RANK"]))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.resilience import chaos, heartbeat  # noqa: E402

TOTAL = 8
DIE_STEP = 2
LR = np.float32(0.05)


def main():
    mode, outdir = sys.argv[1], sys.argv[2]
    rank = int(os.environ["MXNET_DIST_RANK"])
    n = int(os.environ["MXNET_DIST_NUM_WORKERS"])
    wt = os.environ.get("MXNET_ELASTIC_WORLD_TARGET")
    W = int(wt) if wt else n
    inc = int(os.environ.get("MXNET_ELASTIC_INCARNATION", "0"))

    kv = mx.kv.create("dist_tpu_sync")
    kv.set_bucket_size(0)
    _ = kv.rank          # force bring-up: heartbeat + rank tagging start

    mx.random.seed(11)   # identical init on every rank, every incarnation
    net = gluon.nn.Dense(3, in_units=5, prefix="net_")
    net.initialize(mx.initializer.Xavier())
    params = net.collect_params()
    lossf = gluon.loss.L2Loss()
    shapes = [(name, tuple(p.shape)) for name, p in params.items()]
    flat_n = sum(int(np.prod(s)) for _, s in shapes)
    kv.init("flat", mx.nd.zeros((flat_n,)))

    # topology-free checkpoints; keep every step so the manifest's
    # world audit preserves the full resize record for the test
    mgr = mx.checkpoint.CheckpointManager(os.path.join(outdir, "ckpt"),
                                          max_to_keep=2 * TOTAL)
    last, _ = mgr.restore(net=net)
    start = last + 1 if last is not None else 0

    def shard_batch(step, s):
        r = np.random.RandomState(9000 + 17 * step + s)  # (step, shard) only
        return (mx.nd.array(r.randn(4, 5).astype(np.float32)),
                mx.nd.array(r.randn(4, 3).astype(np.float32)))

    zeros = np.zeros((flat_n,), np.float32)
    out = mx.nd.zeros((flat_n,))
    for step in range(start, TOTAL):
        heartbeat.set_step(step)
        if mode == "die" and inc == 0 and rank == n - 1 \
                and step == DIE_STEP:
            # the NEXT allreduce is this step's shard-0 reduction:
            # death strictly mid-collective
            chaos.inject("kvstore.allreduce", kind="exit", times=1)
        tot = zeros.copy()
        for s in range(W):                 # fixed shard order, any n
            if s % n == rank:
                x, y = shard_batch(step, s)
                with autograd.record():
                    loss = lossf(net(x), y)
                loss.backward()
                g = np.concatenate(
                    [p.grad().asnumpy().ravel() for _, p in
                     params.items()]).astype(np.float32, copy=False)
            else:
                g = zeros
            kv.push("flat", mx.nd.array(g))
            kv.pull("flat", out=out)
            tot = tot + out.asnumpy()      # fixed association order
        off = 0
        for name, shape in shapes:
            size = int(np.prod(shape))
            gpart = tot[off:off + size].reshape(shape)
            off += size
            p = params[name]
            p.set_data(mx.nd.array(p.data().asnumpy() - LR * gpart))
        mgr.save(step, net=net)

    np.savez(os.path.join(outdir, f"final_rank{rank}.npz"),
             **{k: p.data().asnumpy() for k, p in params.items()})
    heartbeat.mark_done()
    print(f"worker {rank}/{n} inc{inc} [{mode}]: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
