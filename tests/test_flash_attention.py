"""In-house Pallas flash-attention kernel tests (VERDICT r3 item 1).

Interpreter-mode parity on the CPU platform: ``kernels/flash_attention.py``
forward + custom backward against the dense fp32 oracle
(``ops/contrib.py::_dense_sdpa``), across causal x segment-masking x dtypes
— the same configuration grid the on-chip compile probe walks.  The real-
chip cross-check lives in ``test_tpu_smoke.py`` (flash-vs-dense on the TPU).

Reference role: src/operator/contrib/transformer.cc fused attention ops
(SURVEY §5.7 — the long-context O(L)-memory requirement).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.kernels.flash_attention import flash_attention
from mxnet_tpu.ops.contrib import _dense_sdpa


def _inputs(dt, B=2, H=2, L=256, D=64, valid=(200, 256), seed=7):
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(B, H, L, D), dt)
    k = jnp.asarray(r.randn(B, H, L, D), dt)
    v = jnp.asarray(r.randn(B, H, L, D), dt)
    seg = jnp.asarray(
        (np.arange(L)[None, :] < np.asarray(valid)[:, None]).astype(np.int32))
    return q, k, v, seg


def _valid_mask(seg):
    # compare only rows whose query is a real token; pad rows are defined
    # (pad attends pad) but not interesting
    return np.asarray(seg, bool)[:, None, :, None]


@pytest.mark.parametrize("dt,tol", [(jnp.float32, 1e-5),
                                    (jnp.bfloat16, 5e-2)])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_fwd_parity(dt, tol, causal):
    q, k, v, seg = _inputs(dt)
    scale = 1.0 / q.shape[-1] ** 0.5
    out = flash_attention(q, k, v, seg, seg, causal, scale, interpret=True)
    ref = _dense_sdpa(q, k, v, seg, causal, scale)
    assert out.dtype == q.dtype and out.shape == q.shape
    d = np.abs(np.asarray(out, np.float32)
               - np.asarray(ref, np.float32)) * _valid_mask(seg)
    assert d.max() < tol, f"fwd max diff {d.max()}"


@pytest.mark.parametrize("dt,tol", [(jnp.float32, 1e-4),
                                    (jnp.bfloat16, 1e-1)])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_parity(dt, tol, causal):
    q, k, v, seg = _inputs(dt)
    scale = 1.0 / q.shape[-1] ** 0.5
    w = jnp.asarray(_valid_mask(seg), jnp.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, seg, seg, causal, scale, interpret=True)
        return jnp.sum(o.astype(jnp.float32) * w * 0.01)

    def loss_dense(q, k, v):
        o = _dense_sdpa(q, k, v, seg, causal, scale)
        return jnp.sum(o.astype(jnp.float32) * w * 0.01)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g1, g2):
        d = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b.astype(jnp.float32))))
        assert d < tol, f"d{name} max diff {d}"


def test_flash_no_segment_ids():
    """seg=None means full (or pure-causal) attention over every position
    — the STATIC no-mask kernel specialization, fwd AND bwd (the llama
    default path compiles exactly these kernels)."""
    q, k, v, _ = _inputs(jnp.float32)
    scale = 0.125
    ones = jnp.ones(q.shape[:1] + q.shape[2:3], jnp.int32)
    for causal in (True, False):
        out = flash_attention(q, k, v, None, None, causal, scale,
                              interpret=True)
        ref = _dense_sdpa(q, k, v, ones, causal, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        # backward: no-seg dq/dkv kernels against the dense autodiff
        w = jnp.asarray(np.random.RandomState(4).randn(*q.shape),
                        jnp.float32)

        def lf(q, k, v, _c=causal):
            return jnp.sum(flash_attention(q, k, v, None, None, _c, scale,
                                           interpret=True) * w * 0.01)

        def ld(q, k, v, _c=causal):
            return jnp.sum(_dense_sdpa(q, k, v, ones, _c, scale) * w * 0.01)

        g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            d = float(jnp.max(jnp.abs(a - b)))
            assert d < 1e-4, f"no-seg d{name} ({'causal' if causal else 'full'})"


def test_flash_one_sided_segments_rejected():
    """Mixed None/array segment ids raise (equality masking cannot express
    one-sided all-valid — zero-filling silently masked EVERYTHING)."""
    q, k, v, seg = _inputs(jnp.float32, L=128)
    with pytest.raises(ValueError, match="BOTH seg_q and seg_kv"):
        flash_attention(q, k, v, seg, None, False, 0.125, interpret=True)
    with pytest.raises(ValueError, match="BOTH seg_q and seg_kv"):
        flash_attention(q, k, v, None, seg, False, 0.125, interpret=True)


def test_flash_cross_lengths():
    """Lq != Lk (cross-attention shapes): kv segment ids take K's length."""
    r = np.random.RandomState(3)
    B, H, D, Lq, Lk = 2, 2, 64, 128, 256
    q = jnp.asarray(r.randn(B, H, Lq, D), jnp.float32)
    k = jnp.asarray(r.randn(B, H, Lk, D), jnp.float32)
    v = jnp.asarray(r.randn(B, H, Lk, D), jnp.float32)
    seg_q = jnp.ones((B, Lq), jnp.int32)
    seg_kv = jnp.asarray(
        (np.arange(Lk)[None, :] < np.array([180, 256])[:, None])
        .astype(np.int32))
    scale = 1.0 / D ** 0.5
    out = flash_attention(q, k, v, seg_q, seg_kv, False, scale,
                          interpret=True)
    # dense oracle with an explicit rectangular mask
    att = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask = seg_q[:, None, :, None] == seg_kv[:, None, None, :]
    att = jnp.where(mask, att, -1e9)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(att, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_fully_masked_rows_finite():
    """Rows whose segment id appears nowhere in kv yield 0 output and 0
    grads — never NaN (the safe_l guard in the kernel's _finish step)."""
    q, k, v, _ = _inputs(jnp.float32, L=128)
    seg_q = jnp.ones((2, 128), jnp.int32)       # queries segment 1
    seg_kv = jnp.zeros((2, 128), jnp.int32)     # keys segment 0 -> no match

    def loss(q, k, v):
        o = flash_attention(q, k, v, seg_q, seg_kv, False, 0.125,
                            interpret=True)
        return jnp.sum(o)

    out = flash_attention(q, k, v, seg_q, seg_kv, False, 0.125,
                          interpret=True)
    assert np.all(np.asarray(out) == 0.0)
    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.all(np.isfinite(np.asarray(g)))
        assert float(jnp.max(jnp.abs(g))) == 0.0


def test_masked_selfatt_flash_eligible_shape():
    """contrib.masked_selfatt at a flash-eligible shape (L=256, D=64)
    matches explicit padding-masked attention math; on this CPU platform
    the platform_dependent picks the dense branch, but the flash gating
    path (probe + eligibility) is exercised end to end."""
    import mxnet_tpu as mx
    from mxnet_tpu.ops import contrib as C
    L, B, H, D = 256, 2, 2, 64
    assert C._flash_eligible(L, D)
    assert not C._flash_eligible(128, D)   # measured floor: dense wins there
    r = np.random.RandomState(5)
    qkv = (r.randn(L, B, 3 * H * D) * 0.3).astype(np.float32)
    vl = np.array([200, 256], np.float32)
    out = mx.nd.contrib.masked_selfatt(mx.nd.array(qkv), mx.nd.array(vl),
                                       heads=H).asnumpy()
    x = qkv.reshape(L, B, H, 3, D)
    q, k, v = (np.transpose(x[:, :, :, i], (1, 2, 0, 3)) for i in range(3))
    seg = (np.arange(L)[None, :] < vl[:, None]).astype(np.int32)
    att = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    mask = seg[:, None, :, None] == seg[:, None, None, :]
    att = np.where(mask, att, -1e9)
    att = att - att.max(-1, keepdims=True)
    p = np.exp(att)
    p /= p.sum(-1, keepdims=True)
    ref = np.transpose(np.einsum("bhqk,bhkd->bhqd", p, v),
                       (2, 0, 1, 3)).reshape(L, B, H * D)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_masked_att_qkv_gqa_flash_shape():
    """masked_att_qkv with GQA groups at a flash-eligible shape."""
    import mxnet_tpu as mx
    B, Hq, Hkv, L, D = 2, 4, 2, 256, 64
    r = np.random.RandomState(9)
    q = (r.randn(B, Hq, L, D) * 0.3).astype(np.float32)
    k = (r.randn(B, Hkv, L, D) * 0.3).astype(np.float32)
    v = (r.randn(B, Hkv, L, D) * 0.3).astype(np.float32)
    vl = np.array([L, L], np.float32)
    out = mx.nd.contrib.masked_att_qkv(
        mx.nd.array(q), mx.nd.array(k), mx.nd.array(v), mx.nd.array(vl),
        num_kv_groups=Hq // Hkv, causal=True).asnumpy()
    kk = np.repeat(k, Hq // Hkv, axis=1)
    vv = np.repeat(v, Hq // Hkv, axis=1)
    att = np.einsum("bhqd,bhkd->bhqk", q, kk) / np.sqrt(D)
    cm = np.tril(np.ones((L, L), bool))
    att = np.where(cm[None, None], att, -1e9)
    att = att - att.max(-1, keepdims=True)
    p = np.exp(att)
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, vv)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_parity_multi_tile(causal):
    """Explicit small blocks force the SPLIT dq/dkv kernels (multi-tile
    grids) — the default-path tests at L<=512 take the single-tile fused
    backward, so this pins the long-seq accumulation path."""
    q, k, v, seg = _inputs(jnp.float32)
    scale = 1.0 / q.shape[-1] ** 0.5
    w = jnp.asarray(_valid_mask(seg), jnp.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, seg, seg, causal, scale,
                            block_q=128, block_k=128, interpret=True)
        return jnp.sum(o.astype(jnp.float32) * w * 0.01)

    def loss_dense(q, k, v):
        o = _dense_sdpa(q, k, v, seg, causal, scale)
        return jnp.sum(o.astype(jnp.float32) * w * 0.01)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g1, g2):
        d = float(jnp.max(jnp.abs(a - b)))
        assert d < 1e-4, f"multi-tile d{name} max diff {d}"
