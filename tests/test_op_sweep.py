"""Registry-WIDE operator sweep (VERDICT r4 item 4; reference
tests/python/unittest/test_operator.py breadth, SURVEY §4.1/§4.2).

Three auto-discovered tiers over every registered kernel (aliases dedup
to one sweep each, same rule as opperf):

 1. ``test_sweep_forward``: the op runs on synthesized canonical inputs
    and returns finite values.  Input synthesis REUSES opperf's table
    (benchmark/opperf) so the two stay in lockstep; an op that cannot be
    synthesized must appear in ``SYNTH_SKIP`` with a reason — silent
    drops fail the meta-test.
 2. ``test_sweep_numpy_oracle``: ops whose name is also a numpy ufunc
    are checked against numpy on the same inputs.
 3. ``test_sweep_numeric_gradient``: every differentiable op gets a
    DIRECTIONAL finite-difference check — grad . d vs
    (f(x+eps*d) - f(x-eps*d)) / 2eps along one random direction per
    input (one FD pair per input instead of per element, which is what
    makes a 300-op sweep affordable).  Non-smooth ops are skipped with
    reasons (``FD_SKIP``).
"""

import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops import registry

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmark", "opperf"))
import opperf  # noqa: E402  (the shared input-synthesis table)


def _kernels():
    seen, names = set(), []
    for n in registry.list_ops():
        if n.startswith("_"):
            # internal kernels (same rule as opperf --all): exercised via
            # their public wrappers (x / 2 -> _div_scalar, etc.)
            continue
        if n.startswith("np."):
            # the mx.np layer is thin jnp delegation with its OWN parity
            # sweep (tests/test_numpy_broad.py, ~125 cases vs numpy);
            # sweeping the delegates here would re-test jnp itself
            continue
        op_id = id(registry.get(n))
        if op_id in seen:
            continue
        seen.add(op_id)
        names.append(n)
    return names


KERNELS = _kernels()

# Structured-input synthesizers for ops the GENERIC synthesizer cannot
# drive (ISSUE 8 satellite — the SYNTH_SKIP burn-down: 30 former skips
# now run the real forward sweep).  Each entry builds fresh (args, attrs)
# per call; int-index ops get valid indices, loss heads get labels,
# optimizer update kernels get (weight, grad, state...) triples,
# sequence ops get time-major (L, B) data + per-batch lengths.
_OVERRIDE_KEYS = None  # memoized table keys: non-override calls are free


def _sweep_override(name):
    global _OVERRIDE_KEYS
    if name is not None and _OVERRIDE_KEYS is not None \
            and name not in _OVERRIDE_KEYS:
        return None
    r = np.random.RandomState(0)
    x = nd.array(np.abs(r.randn(4, 5)).astype(np.float32) + 0.5)
    idx = nd.array(np.array([0, 2, 1, 3], np.int32), dtype="int32")
    lab = nd.array(r.randint(0, 5, (4,)).astype(np.float32))
    w = nd.array(r.randn(4, 5).astype(np.float32))
    g = nd.array(r.randn(4, 5).astype(np.float32) * 0.1)
    z = lambda: nd.zeros((4, 5))  # noqa: E731 — fresh optimizer state
    slen = nd.array(np.array([3, 2, 4, 1, 2], np.float32))
    table = {
        "one_hot": lambda: ([idx], {"depth": 5}),
        "take": lambda: ([x, idx], {"axis": 0}),
        "gather_nd": lambda: ([x, nd.array(
            np.array([[0, 1, 2], [1, 2, 3]], np.int32), dtype="int32")], {}),
        "scatter_nd": lambda: ([nd.array(np.ones(3, np.float32)), nd.array(
            np.array([[0, 1, 2], [1, 2, 3]], np.int32), dtype="int32")],
            {"shape": (4, 5)}),
        "pick": lambda: ([x, nd.array(np.array([0, 1, 2, 3],
                                               np.float32))], {}),
        "Embedding": lambda: ([idx, w],
                              {"input_dim": 4, "output_dim": 5}),
        "batch_take": lambda: ([x, idx], {}),
        "boolean_mask": lambda: ([x, nd.array(
            np.array([1, 0, 1, 1], np.float32))], {}),
        "index_add": lambda: ([x, nd.array(
            np.array([[0, 2]], np.int32), dtype="int32"),
            nd.array(np.ones((2, 5), np.float32))], {}),
        "index_copy": lambda: ([x, nd.array(
            np.array([0, 2], np.int32), dtype="int32"),
            nd.array(np.ones((2, 5), np.float32))], {}),
        "ravel_multi_index": lambda: ([nd.array(
            np.array([[0, 1], [2, 3]], np.int32), dtype="int32")],
            {"shape": (4, 5)}),
        "unravel_index": lambda: ([nd.array(
            np.array([5, 11], np.int32), dtype="int32")], {"shape": (4, 5)}),
        "histogram": lambda: ([x], {"bin_cnt": 5, "range": (0.0, 3.0)}),
        "smooth_l1": lambda: ([x], {"scalar": 1.0}),
        "SequenceLast": lambda: ([x, slen], {"use_sequence_length": True}),
        "SequenceMask": lambda: ([x, slen], {"use_sequence_length": True}),
        "SequenceReverse": lambda: ([x, slen],
                                    {"use_sequence_length": True}),
        "SoftmaxOutput": lambda: ([x, lab], {}),
        "SVMOutput": lambda: ([x, lab], {}),
        "LinearRegressionOutput": lambda: ([x, w], {}),
        "MAERegressionOutput": lambda: ([x, w], {}),
        "LogisticRegressionOutput": lambda: ([x, w], {}),
        "softmax_cross_entropy": lambda: ([x, lab], {}),
        "einsum": lambda: ([x, x], {"subscripts": "ij,kj->ik"}),
        "adadelta_update": lambda: ([w, g, z(), z()], {}),
        "adagrad_update": lambda: ([w, g, z()], {"lr": 0.01}),
        "rmsprop_update": lambda: ([w, g, z()], {"lr": 0.01}),
        "signum_update": lambda: ([w, g, z()], {"lr": 0.01}),
        "nag_mom_update": lambda: ([w, g, z()], {"lr": 0.01}),
        "ftrl_update": lambda: ([w, g, z(), z()], {"lr": 0.01}),
        # ISSUE 11 satellite burn-down: 15 more former skips run the
        # real forward sweep on structured inputs
        "adamw_update": lambda: ([w, g, z(), z()], {"lr": 0.01}),
        "rmspropalex_update": lambda: ([w, g, z(), z(), z()],
                                       {"lr": 0.01}),
        "lars_update": lambda: ([w, g, z()], {"lr": 0.01}),
        "lamb_update_phase1": lambda: ([w, g, z(), z()], {"t": 1}),
        "lamb_update_phase2": lambda: ([w, g, nd.array(
            np.array([1.0], np.float32)), nd.array(
            np.array([1.0], np.float32))], {"lr": 0.01}),
        "lamb_full_update": lambda: ([w, g, z(), z()], {"lr": 0.01}),
        "ctc_loss": lambda: ([nd.array(r.randn(6, 2, 5)
                                       .astype(np.float32)),
                              nd.array(np.array([[1, 2], [2, 3]],
                                                np.float32))], {}),
        "center_loss": lambda: ([x, nd.array(
            np.array([0, 1, 2, 3], np.float32)),
            nd.array(r.randn(5, 5).astype(np.float32))], {}),
        "im2col": lambda: ([nd.array(r.randn(1, 2, 6, 6)
                                     .astype(np.float32))],
                           {"kernel": (3, 3)}),
        "col2im": lambda: ([nd.array(r.randn(1, 18, 16)
                                     .astype(np.float32))],
                           {"output_size": (6, 6), "kernel": (3, 3)}),
        "contrib.fft": lambda: ([x], {}),
        "contrib.ifft": lambda: ([nd.array(r.randn(4, 6)
                                           .astype(np.float32))], {}),
        "contrib.count_sketch": lambda: ([x, nd.array(
            np.array([0, 3, 1, 7, 2], np.float32)),
            nd.array(np.array([1, -1, 1, 1, -1], np.float32))],
            {"out_dim": 8}),
        "contrib.box_iou": lambda: ([nd.array(np.array(
            [[0.1, 0.1, 0.5, 0.5], [0.3, 0.3, 0.9, 0.8],
             [0.0, 0.2, 0.4, 0.9]], np.float32)),
            nd.array(np.array([[0.2, 0.2, 0.6, 0.6],
                               [0.5, 0.1, 0.8, 0.7]], np.float32))], {}),
        "contrib.dequantize": lambda: ([nd.array(
            np.array(r.randint(-127, 128, (4, 5)), np.int8),
            dtype="int8"),
            nd.array(np.array([-1.0], np.float32)),
            nd.array(np.array([1.0], np.float32))], {}),
        # ISSUE 12 satellite burn-down: the interleaved-attention family,
        # detection heads, STN/correlation, quantized matmuls, linalg
        # contracts, and hawkes_ll now run the real forward sweep on
        # structured inputs (layout contracts documented per entry).
        # interleaved qkv layout: (L, B, 3*H*hd), time-major
        "contrib.interleaved_matmul_selfatt_qk": lambda: (
            [nd.array(r.randn(4, 2, 24).astype(np.float32))],
            {"heads": 2}),
        "contrib.interleaved_matmul_selfatt_valatt": lambda: (
            [nd.array(r.randn(4, 2, 24).astype(np.float32)),
             nd.array(np.abs(r.randn(4, 4, 4)).astype(np.float32))],
            {"heads": 2}),
        # encdec: q (Lq, B, E), kv (Lk, B, 2E) interleaved k/v
        "contrib.interleaved_matmul_encdec_qk": lambda: (
            [nd.array(r.randn(4, 2, 8).astype(np.float32)),
             nd.array(r.randn(5, 2, 16).astype(np.float32))],
            {"heads": 2}),
        "contrib.interleaved_matmul_encdec_valatt": lambda: (
            [nd.array(r.randn(5, 2, 16).astype(np.float32)),
             nd.array(np.abs(r.randn(4, 4, 5)).astype(np.float32))],
            {"heads": 2}),
        # detection heads: anchors in corner format inside [0, 1]
        "contrib.MultiBoxPrior": lambda: (
            [nd.array(r.randn(1, 3, 4, 4).astype(np.float32))],
            {"sizes": (0.5, 0.25), "ratios": (1.0, 2.0)}),
        "contrib.MultiBoxTarget": lambda: (
            [nd.array(np.array([[[0.1, 0.1, 0.4, 0.4],
                                 [0.3, 0.3, 0.8, 0.8],
                                 [0.5, 0.1, 0.9, 0.6],
                                 [0.0, 0.5, 0.5, 1.0]]], np.float32)),
             nd.array(np.array([[[0.0, 0.12, 0.12, 0.38, 0.42],
                                 [1.0, 0.3, 0.3, 0.8, 0.75]]],
                               np.float32)),
             nd.array(np.abs(r.randn(1, 3, 4)).astype(np.float32))], {}),
        "contrib.MultiBoxDetection": lambda: (
            [nd.array(np.abs(r.rand(1, 3, 4)).astype(np.float32)),
             nd.array((r.randn(1, 16) * 0.1).astype(np.float32)),
             nd.array(np.array([[[0.1, 0.1, 0.4, 0.4],
                                 [0.3, 0.3, 0.8, 0.8],
                                 [0.5, 0.1, 0.9, 0.6],
                                 [0.0, 0.5, 0.5, 1.0]]], np.float32))],
            {}),
        # RPN proposals: cls (1, 2A, H, W), bbox (1, 4A, H, W),
        # im_info rows [h, w, scale]; A = scales x ratios
        "contrib.Proposal": lambda: (
            [nd.array(np.abs(r.rand(1, 8, 4, 4)).astype(np.float32)),
             nd.array((r.randn(1, 16, 4, 4) * 0.1).astype(np.float32)),
             nd.array(np.array([[64.0, 64.0, 1.0]], np.float32))],
            {"scales": (8, 16), "ratios": (0.5, 1.0),
             "rpn_pre_nms_top_n": 12, "rpn_post_nms_top_n": 4,
             "rpn_min_size": 1}),
        "contrib.MultiProposal": lambda: (
            [nd.array(np.abs(r.rand(2, 8, 4, 4)).astype(np.float32)),
             nd.array((r.randn(2, 16, 4, 4) * 0.1).astype(np.float32)),
             nd.array(np.array([[64.0, 64.0, 1.0],
                                [64.0, 64.0, 1.0]], np.float32))],
            {"scales": (8, 16), "ratios": (0.5, 1.0),
             "rpn_pre_nms_top_n": 12, "rpn_post_nms_top_n": 4,
             "rpn_min_size": 1}),
        # roi ops: rois rows [batch_idx, x0, y0, x1, y1] in image coords
        "contrib.roi_align": lambda: (
            [nd.array(r.randn(1, 2, 8, 8).astype(np.float32)),
             nd.array(np.array([[0, 1.0, 1.0, 5.0, 5.0],
                                [0, 2.0, 0.0, 7.0, 6.0]], np.float32))],
            {"pooled_size": (2, 2), "spatial_scale": 1.0}),
        "contrib.PSROIPooling": lambda: (
            [nd.array(r.randn(1, 8, 8, 8).astype(np.float32)),
             nd.array(np.array([[0, 1.0, 1.0, 6.0, 6.0]], np.float32))],
            {"output_dim": 2, "pooled_size": 2, "group_size": 2}),
        # STN: loc = flat affine (1, 6) rows; identity-ish transform
        "SpatialTransformer": lambda: (
            [nd.array(r.randn(1, 2, 6, 6).astype(np.float32)),
             nd.array(np.array([[1.0, 0.1, 0.0, -0.1, 1.0, 0.0]],
                               np.float32))],
            {"target_shape": (4, 4), "transform_type": "affine",
             "sampler_type": "bilinear"}),
        "Correlation": lambda: (
            [nd.array(r.randn(1, 2, 6, 6).astype(np.float32)),
             nd.array(r.randn(1, 2, 6, 6).astype(np.float32))],
            {"kernel_size": 1, "max_displacement": 1, "stride1": 1,
             "stride2": 1, "pad_size": 1}),
        "Crop": lambda: (
            [nd.array(r.randn(1, 2, 6, 6).astype(np.float32))],
            {"h_w": (4, 4), "offset": (1, 1)}),
        # quantized matmuls: int8 operands + float range scalars
        "contrib.quantized_dot": lambda: (
            [nd.array(np.array(r.randint(-127, 128, (4, 5)), np.int8),
                      dtype="int8"),
             nd.array(np.array(r.randint(-127, 128, (5, 6)), np.int8),
                      dtype="int8"),
             nd.array(np.array([-1.0], np.float32)),
             nd.array(np.array([1.0], np.float32)),
             nd.array(np.array([-2.0], np.float32)),
             nd.array(np.array([2.0], np.float32))], {}),
        "contrib.quantized_fully_connected": lambda: (
            [nd.array(np.array(r.randint(-127, 128, (4, 5)), np.int8),
                      dtype="int8"),
             nd.array(np.array(r.randint(-127, 128, (6, 5)), np.int8),
                      dtype="int8"),
             nd.array(np.array([-1.0], np.float32)),
             nd.array(np.array([1.0], np.float32)),
             nd.array(np.array([-2.0], np.float32)),
             nd.array(np.array([2.0], np.float32))],
            {"num_hidden": 6}),
        "contrib.requantize": lambda: (
            [nd.array(np.array(r.randint(-2 ** 20, 2 ** 20, (4, 5)),
                               np.int32), dtype="int32"),
             nd.array(np.array([-4.0], np.float32)),
             nd.array(np.array([4.0], np.float32))], {}),
        # linalg contracts: gemm's axpby triple, tensorinv's even-order
        # square reshape (prod(shape[:ind]) == prod(shape[ind:]))
        "linalg.gemm": lambda: (
            [nd.array(r.randn(3, 4).astype(np.float32)),
             nd.array(r.randn(4, 5).astype(np.float32)),
             nd.array(r.randn(3, 5).astype(np.float32))],
            {"alpha": 2.0, "beta": 0.5}),
        "linalg.tensorinv": lambda: (
            [nd.array((np.eye(6) + 0.1 * r.randn(6, 6))
                      .reshape(2, 3, 2, 3).astype(np.float32))],
            {"ind": 2}),
        # hawkes: lda (N, K), alpha/beta (K,), state (N, K), lags/marks
        # (N, T), valid_length (N,), max_time (N,)
        "contrib.hawkes_ll": lambda: (
            [nd.array(np.abs(r.rand(2, 3)).astype(np.float32) + 0.5),
             nd.array(np.abs(r.rand(3)).astype(np.float32) * 0.5),
             nd.array(np.abs(r.rand(3)).astype(np.float32) + 1.0),
             nd.array(np.zeros((2, 3), np.float32)),
             nd.array(np.abs(r.rand(2, 4)).astype(np.float32)),
             nd.array(np.array([[0, 1, 2, 0], [2, 1, 0, 1]], np.float32)),
             nd.array(np.array([4, 3], np.float32)),
             nd.array(np.array([5.0, 5.0], np.float32))], {}),
        # ISSUE 13 satellite burn-down: the aux-state norm ops, RNN, the
        # loss-head Softmax alias, offset/int8 convolutions, the fused
        # mp-sgd multi-tensor pair, and the fused masked-attention family
        # now run the real forward sweep on structured inputs.
        # BatchNorm contract: (data NCHW, gamma, beta, moving_mean,
        # moving_var) — train mode normalizes with BATCH stats, the
        # moving inputs are state
        "BatchNorm": lambda: (
            [nd.array(r.randn(2, 3, 4, 4).astype(np.float32)),
             nd.array((np.abs(r.rand(3)) + 0.5).astype(np.float32)),
             nd.array((r.randn(3) * 0.1).astype(np.float32)),
             nd.array(np.zeros(3, np.float32)),
             nd.array(np.ones(3, np.float32))], {}),
        "BatchNormWithReLU": lambda: (
            [nd.array(r.randn(2, 3, 4, 4).astype(np.float32)),
             nd.array((np.abs(r.rand(3)) + 0.5).astype(np.float32)),
             nd.array((r.randn(3) * 0.1).astype(np.float32)),
             nd.array(np.zeros(3, np.float32)),
             nd.array(np.ones(3, np.float32))], {}),
        # RNN: time-major (L, B, I) data, packed params, (layers, B, H)
        # initial state; single-layer rnn_tanh keeps the packing tiny
        "RNN": lambda: (
            [nd.array(r.randn(4, 2, 3).astype(np.float32)),
             nd.array((r.randn(5 * (3 + 5 + 2)) * 0.1)
                      .astype(np.float32)),
             nd.array(np.zeros((1, 2, 5), np.float32))],
            {"state_size": 5, "num_layers": 1, "mode": "rnn_tanh"}),
        # Softmax (capital) is the upstream SoftmaxOutput loss-head
        # alias: (data, label)
        "Softmax": lambda: ([x, lab], {}),
        # deformable conv: (data, offset (2*k*k ch), weight, bias)
        "contrib.DeformableConvolution": lambda: (
            [nd.array(r.randn(1, 2, 6, 6).astype(np.float32)),
             nd.array((r.randn(1, 18, 6, 6) * 0.1).astype(np.float32)),
             nd.array(r.randn(3, 2, 3, 3).astype(np.float32)),
             nd.array(np.zeros(3, np.float32))],
            {"kernel": (3, 3), "num_filter": 3, "pad": (1, 1)}),
        # int8 NCHW conv + range scalars (the quantized_dot recipe)
        "contrib.quantized_conv": lambda: (
            [nd.array(np.array(r.randint(-127, 128, (1, 2, 6, 6)),
                               np.int8), dtype="int8"),
             nd.array(np.array(r.randint(-127, 128, (3, 2, 3, 3)),
                               np.int8), dtype="int8"),
             nd.array(np.array([-1.0], np.float32)),
             nd.array(np.array([1.0], np.float32)),
             nd.array(np.array([-2.0], np.float32)),
             nd.array(np.array([2.0], np.float32))], {"pad": (1, 1)}),
        # fused mp-sgd: (w, g, w32)*K [+ m for mom] then lrs, wds arrays
        "multi_mp_sgd_update": lambda: (
            [w, g, w.astype("float32"),
             nd.array(np.array([0.01], np.float32)),
             nd.array(np.array([0.0], np.float32))],
            {"num_weights": 1}),
        "multi_mp_sgd_mom_update": lambda: (
            [w, g, z(), w.astype("float32"),
             nd.array(np.array([0.01], np.float32)),
             nd.array(np.array([0.0], np.float32))],
            {"num_weights": 1}),
        # masked attention family (dense fallback path off-TPU):
        # selfatt keeps the reference interleaved (L, B, 3*H*D) layout
        "contrib.masked_selfatt": lambda: (
            [nd.array(r.randn(4, 2, 24).astype(np.float32))],
            {"heads": 2}),
        # qkv entry: separate (B, H, L, D) tensors
        "contrib.masked_att_qkv": lambda: (
            [nd.array(r.randn(2, 2, 4, 8).astype(np.float32)),
             nd.array(r.randn(2, 2, 4, 8).astype(np.float32)),
             nd.array(r.randn(2, 2, 4, 8).astype(np.float32))], {}),
        # encdec: q (Lq, B, H*D), kv (Lk, B, 2*H*D) interleaved k/v
        "contrib.masked_encdec_att": lambda: (
            [nd.array(r.randn(4, 2, 8).astype(np.float32)),
             nd.array(r.randn(5, 2, 16).astype(np.float32))],
            {"heads": 2}),
        # mha-named wrappers (ISSUE 14 satellite): separate time-major
        # (L, B, H*D) projections
        "contrib.multihead_attention_qk": lambda: (
            [nd.array(r.randn(4, 2, 8).astype(np.float32)),
             nd.array(r.randn(5, 2, 8).astype(np.float32))],
            {"heads": 2}),
        "contrib.multihead_attention_valatt": lambda: (
            [nd.array(np.abs(r.randn(4, 3, 5)).astype(np.float32)),
             nd.array(r.randn(5, 2, 8).astype(np.float32))],
            {"heads": 2}),
        "contrib.multihead_attention": lambda: (
            [nd.array(r.randn(4, 2, 8).astype(np.float32)),
             nd.array(r.randn(4, 2, 8).astype(np.float32)),
             nd.array(r.randn(4, 2, 8).astype(np.float32))],
            {"heads": 2}),
        # ISSUE 14 satellite — the LAST SYNTH_SKIP burned down: the SP
        # attention entry point, driven through its documented
        # single-device degradation (the axis name misses every mesh a
        # prior test may have left active, so the op runs the local
        # fused/dense path deterministically); the actual ring/Ulysses
        # SP numerics are parity-tested by test_ring_attention /
        # test_ulysses on real dp×sp meshes.
        "contrib.sp_att_qkv": lambda: (
            [nd.array(r.randn(2, 2, 4, 8).astype(np.float32)),
             nd.array(r.randn(2, 2, 4, 8).astype(np.float32)),
             nd.array(r.randn(2, 2, 4, 8).astype(np.float32))],
            {"axis": "sweep_no_such_axis"}),
    }
    _OVERRIDE_KEYS = frozenset(table)
    if name is None:
        return _OVERRIDE_KEYS      # the override name set, for the meta-test
    fn = table.get(name)
    return fn() if fn is not None else None


# ops the generic synthesizer cannot drive, with the reason (tier-1 skip
# list — the meta-test asserts this list only names real registry ops).
# ISSUE 14 satellite burn-down: EMPTY.  The final entry
# (contrib.sp_att_qkv, "mesh-dependent") now runs the real forward
# sweep via its _sweep_override — the op's own single-device
# degradation contract makes the sweep deterministic regardless of any
# globally active mesh, and the SP paths stay parity-tested by
# test_ring_attention/test_ulysses.  Every registered op either sweeps
# or fails the meta-test.
SYNTH_SKIP = {}


def _inputs(name):
    """(args, attrs) for an op or None — the sweep's structured-input
    override table first, then opperf's table at small shapes."""
    spec = _sweep_override(name)
    if spec is not None:
        return spec
    old_n = opperf._N
    opperf._N = 8
    try:
        spec = opperf._inputs_for(name, mx)
    finally:
        opperf._N = old_n
    if spec is not None:
        return spec
    r = np.random.RandomState(0)
    x = nd.array(np.abs(r.randn(6, 7)).astype(np.float32) + 0.5)
    op = registry.get(name)
    for args in ([x], [x, x]):
        try:
            registry.invoke(op, args, {})
            return args, {}
        except Exception:  # noqa: BLE001
            continue
    return None


def test_sweep_skip_list_is_honest():
    for name in SYNTH_SKIP:
        assert name in registry.list_ops(), \
            f"SYNTH_SKIP names unknown op {name!r}"


def test_sweep_override_table_is_honest():
    """Every structured-input override names a real registry op and is
    not ALSO skip-listed (an overridden op must actually run)."""
    names = _sweep_override(None)
    assert names, "override table unexpectedly empty"
    for name in names:
        assert name in registry.list_ops(), \
            f"_sweep_override names unknown op {name!r}"
        assert name not in SYNTH_SKIP, \
            f"{name!r} is both overridden and skip-listed"


@pytest.mark.parametrize("name", KERNELS)
def test_sweep_forward(name):
    if name in SYNTH_SKIP:
        pytest.skip(SYNTH_SKIP[name])
    spec = _inputs(name)
    if spec is None:
        pytest.fail(f"op {name!r} has no input synthesizer and is not in "
                    "SYNTH_SKIP — add an opperf override or a skip reason")
    args, attrs = spec
    out = registry.invoke(registry.get(name), list(args), dict(attrs))
    outs = out if isinstance(out, list) else [out]
    for o in outs:
        a = o.asnumpy()
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), f"{name}: non-finite output"


_NUMPY_ORACLE_SKIP = {
    # mx op semantics intentionally differ from the same-named numpy fn
    "clip": "mx.clip takes a_min/a_max attrs, not positional",
    "round": "mx rounds half away from zero (reference semantics); "
             "numpy rounds half to even",
}


@pytest.mark.parametrize("name", [
    n for n in KERNELS
    if hasattr(np, n) and callable(getattr(np, n))
    and n not in SYNTH_SKIP])
def test_sweep_numpy_oracle(name):
    if name in _NUMPY_ORACLE_SKIP:
        pytest.skip(_NUMPY_ORACLE_SKIP[name])
    spec = _inputs(name)
    if spec is None:
        pytest.skip("no synthesizer (covered by test_sweep_forward policy)")
    args, attrs = spec
    if attrs:
        pytest.skip("attr-carrying op; oracle comparison not 1:1")
    np_in = [a.asnumpy() for a in args]
    try:
        want = getattr(np, name)(*np_in)
    except TypeError:
        pytest.skip("numpy signature differs")
    got = registry.invoke(registry.get(name), list(args), {})
    got = (got[0] if isinstance(got, list) else got).asnumpy()
    if not isinstance(want, np.ndarray):
        want = np.asarray(want)
    assert got.shape == want.shape or got.size == want.size, \
        f"{name}: shape {got.shape} vs numpy {want.shape}"
    np.testing.assert_allclose(got.reshape(want.shape), want,
                               rtol=2e-5, atol=2e-6, err_msg=name)


# non-smooth / non-real-gradient ops: directional FD is meaningless
FD_SKIP = {
    "sign": "piecewise-constant", "floor": "piecewise-constant",
    "ceil": "piecewise-constant", "round": "piecewise-constant",
    "rint": "piecewise-constant", "fix": "piecewise-constant",
    "trunc": "piecewise-constant",
    "abs": "kink at 0 is fine but |x| synth crosses it in FD noise",
    "topk": "selection op", "sort": "permutation op",
    "argsort": "selection op",
    "Dropout": "stochastic", "dropout": "stochastic",
    "shuffle": "stochastic",
    "LeakyReLU": "rrelu branch stochastic; leaky kink",
    "relu": "kink at 0", "hard_sigmoid": "kinks",
    "clip": "kinks at bounds",
    "erfinv": "FD ill-conditioned near synth range edges",
    "reciprocal": "FD ill-conditioned for |x| < 1",
    "rsqrt": "FD ill-conditioned near 0", "rcbrt": "FD ill-conditioned",
    "log": "FD needs strictly positive well-scaled inputs",
    "log2": "FD scale", "log10": "FD scale", "log1p": "FD scale",
    "sqrt": "FD near 0", "cbrt": "FD near 0",
    "gamma": "FD overflow on synth range",
    "gammaln": "FD scale", "digamma": "FD poles",
    "tan": "poles", "cot": "poles",
    "Pooling": "max-pool selection kinks",
    "max": "selection", "min": "selection",
    "batch_dot": "opperf shapes (batched) fine but fwd-only here",
    "norm": "kink at 0 for ord=1 path",
    "exp": "magnifies FD noise on synth range",
    "expm1": "FD scale",
    "softmax_cross_entropy": "label input",
    "where": "bool first input",
    "BlockGrad": "gradient is 0 by definition (stop-gradient op)",
    "linalg.extracttrian": "offset-attr contract",
    "mod": "kinks at multiples", "broadcast_mod": "kinks at multiples",
    "erf": "fine but |grad| tiny at synth range edges",
    "arcsin": "domain-edge sensitivity", "arccos": "domain-edge",
    "arctanh": "domain-edge", "arccosh": "domain-edge",
    "L2Normalization": "norm kink sensitivity at synth scale",
    "adam_update": "optimizer update mutates, not a math grad",
    "adadelta_update": "optimizer update", "adagrad_update": "optimizer update",
    "rmsprop_update": "optimizer update", "signum_update": "optimizer update",
    "nag_mom_update": "optimizer update", "ftrl_update": "optimizer update",
    "adamw_update": "optimizer update",
    "rmspropalex_update": "optimizer update",
    "lars_update": "optimizer update",
    "lamb_update_phase1": "optimizer update",
    "lamb_update_phase2": "optimizer update",
    "lamb_full_update": "optimizer update",
    "ctc_loss": "loss head: backward is the CTC loss grad; labels are "
                "integer selectors",
    "center_loss": "loss head with aux center update (train-mode "
                   "mutation); backward is the loss grad",
    "contrib.dequantize": "range inputs kink at |min|==|max| (max of "
                          "abs); data input is int8",
    "contrib.fft": "reference layout contract casts to float32 inside; "
                   "float64 FD precision lost (forward swept)",
    "contrib.ifft": "float32-inside cast (same as contrib.fft)",
    # loss heads: backward is the LOSS gradient by contract, not
    # d(forward)/dx — FD against the forward is meaningless
    "SoftmaxOutput": "loss head: backward = softmax - label",
    "SVMOutput": "loss head: backward = hinge grad",
    "LinearRegressionOutput": "loss head: backward = pred - label",
    "MAERegressionOutput": "loss head: backward = sign(pred - label)",
    "LogisticRegressionOutput": "loss head: backward = sigmoid - label",
    "histogram": "piecewise-constant bin counts",
    "one_hot": "int input; output independent of any float input",
    "sgd_update": "optimizer update", "sgd_mom_update": "optimizer update",
    "mp_sgd_update": "optimizer update",
    "mp_sgd_mom_update": "optimizer update",
    "multi_sgd_update": "optimizer update",
    "multi_sgd_mom_update": "optimizer update",
    "preloaded_multi_sgd_update": "optimizer update",
    "preloaded_multi_sgd_mom_update": "optimizer update",
    "amp_multicast": "dtype-cast utility (gradient is identity-cast)",
    "linalg.gelqf": "QR-based factorization; grad not defined upstream",
    "BilinearSampler": "grid-cell boundary kinks (floor of sample coords)",
    # ISSUE 12 satellite burn-down: forward now swept; backward exempt
    # with the honest reason per entry
    "contrib.roi_align": "bin-boundary kinks (bilinear sampling grid, "
                         "same class as BilinearSampler)",
    "contrib.PSROIPooling": "bin-boundary kinks (floor of roi bin edges)",
    "SpatialTransformer": "grid-cell kinks via its BilinearSampler step",
    "Correlation": "zero-padded displacement windows kink at the image "
                   "border taps",
    "contrib.quantized_dot": "int8 operands; range inputs kink at "
                             "|min|==|max| (max-of-abs)",
    "contrib.quantized_fully_connected": "int8 operands; range max-of-abs "
                                         "kinks",
    "contrib.requantize": "int32 data; round/clip staircase",
    "linalg.tensorinv": "FD through a 6x6 inverse amplifies eps by "
                        "cond^2; forward swept on a well-conditioned "
                        "operand",
    "contrib.hawkes_ll": "marks/valid_length are integer selectors and "
                         "the state output rides a scan; backward is "
                         "covered by the LL head's analytic grad in "
                         "test_contrib_ops",
    # ISSUE 13 satellite burn-down: forward now swept; backward exempt
    # with the honest reason per entry
    "Softmax": "loss head (SoftmaxOutput alias): backward = softmax - "
               "label by contract, not d(forward)/dx",
    "BatchNormWithReLU": "relu kink at 0 on top of the normalization",
    "multi_mp_sgd_update": "optimizer update",
    "multi_mp_sgd_mom_update": "optimizer update",
    "contrib.DeformableConvolution": "bilinear sampling grid kinks "
                                     "(BilinearSampler class) in the "
                                     "offset path",
    "contrib.quantized_conv": "int8 operands; range inputs kink at "
                              "|min|==|max| (max-of-abs)",
    "BatchNorm": "batch-stat normalization runs float32 on the x64-less "
                 "lattice; 1e-5-eps FD loses precision (backward "
                 "covered by test_operator/test_gluon BatchNorm tests)",
    "contrib.masked_selfatt": "softmax core float32 on the x64-less "
                              "lattice (float64 FD precision lost, the "
                              "contrib.fft class); grads parity-tested "
                              "by test_flash_attention",
    "contrib.masked_att_qkv": "float32 softmax core (same class as "
                              "masked_selfatt); test_flash_attention",
    "contrib.masked_encdec_att": "float32 softmax core (same class as "
                                 "masked_selfatt); transformer grads in "
                                 "test_model_zoo",
    # ISSUE 14 satellite: the mha-named fused wrapper + the SP entry
    # share the masked_selfatt float32-softmax-core class; their grads
    # are covered by test_contrib_ops.test_multihead_attention_grads_flow
    # and test_ring_attention/test_ulysses respectively.  The unfused
    # qk/valatt wrappers are plain matmuls and DO run the FD sweep.
    "contrib.multihead_attention": "float32 softmax core (masked_selfatt "
                                   "class); grads in test_contrib_ops",
    "contrib.sp_att_qkv": "float32 softmax core via the degradation "
                          "path; SP grads in test_ring_attention/"
                          "test_ulysses",
}


# ops whose trailing float inputs are semantically integer SELECTORS
# (sequence lengths, pick indices): perturbing them flips the selection
# (FD explodes) while the analytic grad is correctly zero — FD checks
# only the data input
FD_DATA_INPUT_ONLY = {"SequenceLast", "SequenceMask", "SequenceReverse",
                      "pick",
                      # h (bucket indices) and s (signs) are selectors
                      "contrib.count_sketch"}


@pytest.mark.parametrize("name", [
    n for n in KERNELS
    if registry.get(n).differentiable and n not in SYNTH_SKIP
    and n not in FD_SKIP])
def test_sweep_numeric_gradient(name):
    spec = _inputs(name)
    if spec is None:
        pytest.skip("no synthesizer")
    args, attrs = spec
    float_idx = [i for i, a in enumerate(args)
                 if np.dtype(a.dtype).kind == "f"]
    if name in FD_DATA_INPUT_ONLY:
        float_idx = float_idx[:1]
    if not float_idx:
        pytest.skip("no float inputs")
    from mxnet_tpu import autograd
    op = registry.get(name)

    def f(*xs):
        out = registry.invoke(op, list(xs), dict(attrs))
        out = out[0] if isinstance(out, list) else out
        return out.astype("float64").sum()

    ins = [a.astype("float64") if i in float_idx else a
           for i, a in enumerate(args)]
    for i in float_idx:
        ins[i].attach_grad()
    with autograd.record():
        y = f(*ins)
    try:
        y.backward()
    except Exception as e:  # noqa: BLE001
        pytest.fail(f"{name}: backward raised {type(e).__name__}: {e}")
    eps = 1e-5
    r = np.random.RandomState(1)
    for i in float_idx:
        if ins[i].grad is None:
            continue
        d = r.randn(*ins[i].shape)
        d /= max(np.linalg.norm(d), 1e-12)
        xp = ins[i].asnumpy() + eps * d
        xm = ins[i].asnumpy() - eps * d
        args_p = [nd.array(xp) if j == i else ins[j]
                  for j in range(len(ins))]
        args_m = [nd.array(xm) if j == i else ins[j]
                  for j in range(len(ins))]
        fd = (float(f(*args_p).asnumpy())
              - float(f(*args_m).asnumpy())) / (2 * eps)
        an = float((ins[i].grad.asnumpy() * d).sum())
        denom = max(abs(fd), abs(an), 1e-6)
        assert abs(fd - an) / denom < 5e-3, \
            f"{name} input {i}: directional grad {an} vs FD {fd}"
