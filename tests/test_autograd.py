"""Autograd tests (reference tests/python/unittest/test_autograd.py +
test_higher_order_grad.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal


def test_basic_backward():
    x = nd.array([1., 2., 3.])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), [2, 4, 6])


def test_chain_and_fanout():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        a = x * 3
        b = x * 5
        y = a * b  # y = 15 x^2 → dy/dx = 30x
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), [60.0])


def test_head_gradient():
    x = nd.array([1., 2.])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(nd.array([10., 100.]))
    assert_almost_equal(x.grad.asnumpy(), [20, 200])


def test_grad_req_add_and_write():
    x = nd.array([1., 1.])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
    assert_almost_equal(x.grad.asnumpy(), [6, 6])
    x.attach_grad(grad_req="write")
    for _ in range(3):
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
    assert_almost_equal(x.grad.asnumpy(), [2, 2])


def test_detach_stops_grad():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y.detach() * 5
    z.backward()
    # z does not reach x through detach
    assert_almost_equal(x.grad.asnumpy(), [0.0])


def test_stop_gradient_op():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + nd.stop_gradient(x * 4)
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), [6.0])


def test_training_flags():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_autograd_grad_api():
    x = nd.array([1., 2., 3.])
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
        g = autograd.grad(y, x, create_graph=False, retain_graph=True)
    assert_almost_equal(g.asnumpy(), 3 * np.array([1, 4, 9.0]))


def test_higher_order():
    x = nd.array([1., 2., 3.])
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
        g = autograd.grad(y, x, create_graph=True, retain_graph=True)
        z = (g * g).sum()
    z.backward()
    assert_almost_equal(x.grad.asnumpy(), 36 * np.array([1., 8., 27.]))


def test_higher_order_sigmoid():
    x = nd.array([0.5])
    x.attach_grad()
    with autograd.record():
        y = nd.sigmoid(x)
        g = autograd.grad(y, x, create_graph=True, retain_graph=True)
        z = g.sum()
    z.backward()
    s = 1 / (1 + np.exp(-0.5))
    d2 = s * (1 - s) * (1 - 2 * s)
    assert_almost_equal(x.grad.asnumpy(), [d2], rtol=1e-4, atol=1e-5)


def test_unreached_variable_raises():
    w = nd.ones((2,))
    w.attach_grad()
    x = nd.ones((2,))
    x.attach_grad()
    with autograd.record():
        y = (x * 2).sum()
    with pytest.raises(mx.MXNetError):
        autograd.grad(y, [w])


def test_custom_function():
    class ScaleGrad(autograd.Function):
        def forward(self, x):
            return x * 1.0

        def backward(self, dy):
            return dy * 7.0

    x = nd.array([1., 2.])
    x.attach_grad()
    f = ScaleGrad()
    with autograd.record():
        y = f(x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), [7, 7])


def test_mark_variables():
    x = nd.array([2.0])
    g = nd.zeros((1,))
    autograd.mark_variables(x, g)
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(g.asnumpy(), [4.0])


def test_exc_propagates_at_sync():
    """Async error surfacing contract (reference test_exc_handling.py):
    errors surface no later than the next sync point."""
    with pytest.raises(Exception):
        a = nd.array([1.0, 2.0])
        b = nd.array([1.0, 2.0, 3.0])
        c = nd.broadcast_add(a, b)  # incompatible shapes
        c.asnumpy()


def test_double_backward_raises():
    """ADVICE r1: second backward on a freed graph raises, not silent no-op."""
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    with pytest.raises(mx.MXNetError):
        y.backward()
    # retain_graph=True permits a second pass
    with autograd.record():
        y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()


def test_inplace_on_recorded_raises():
    """ADVICE r1: += on an array that is an output of recorded compute."""
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with pytest.raises(mx.MXNetError):
            y += 1
