"""Pipeline parallelism (mxnet_tpu/pipeline.py) — GPipe schedule tests.

Reference: ABSENT upstream (SURVEY §2.4 "Pipeline parallel: ABSENT") — these
tests validate the new TPU-native design: output/grad parity between the
pipelined schedule and the plain sequential stack, on pp-only and dp×pp
meshes (8 virtual CPU devices via conftest).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import pipeline as pl
from mxnet_tpu.parallel import DeviceMesh


def _mlp_stage(params, x):
    import jax.numpy as jnp
    h = jnp.dot(x, params["w"]) + params["b"]
    return jnp.tanh(h)


def _make_params(S, d, seed=0):
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(S, d, d).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(S, d).astype(np.float32) * 0.1),
    }


def _sequential(params, x):
    import jax
    import jax.numpy as jnp

    def body(h, i):
        h = _mlp_stage(jax.tree_util.tree_map(lambda p: p[i], params), h)
        return h, None
    S = params["w"].shape[0]
    h, _ = jax.lax.scan(body, x, jnp.arange(S))
    return h


def test_gpipe_forward_matches_sequential():
    S, M, B, d = 4, 4, 16, 8
    mesh = DeviceMesh(shape=(S,), axis_names=("pp",),
                      devices=None if S == 8 else __import__("jax").devices()[:S])
    params = _make_params(S, d)
    x = np.random.RandomState(1).randn(B, d).astype(np.float32)
    fn = pl.gpipe(_mlp_stage, S, M, mesh, axis="pp")
    out = np.asarray(fn(params, x))
    ref = np.asarray(_sequential(params, x))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_gpipe_grad_matches_sequential():
    import jax
    import jax.numpy as jnp
    S, M, B, d = 4, 2, 8, 4
    mesh = DeviceMesh(shape=(S,), axis_names=("pp",),
                      devices=jax.devices()[:S])
    params = _make_params(S, d, seed=3)
    x = np.random.RandomState(2).randn(B, d).astype(np.float32)
    fn = pl.gpipe(_mlp_stage, S, M, mesh, axis="pp")

    def loss_pipe(p):
        return jnp.sum(fn(p, x) ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5)


def test_gpipe_dp_pp_mesh():
    """2-D mesh: batch sharded over dp, stages over pp."""
    import jax
    S, M, B, d = 4, 4, 16, 8
    mesh = DeviceMesh(shape=(2, S), axis_names=("dp", "pp"))
    params = _make_params(S, d, seed=5)
    x = np.random.RandomState(4).randn(B, d).astype(np.float32)
    xs = jax.device_put(x, mesh.sharded("dp"))
    fn = pl.gpipe(_mlp_stage, S, M, mesh, axis="pp", data_axis="dp")
    out = np.asarray(fn(params, xs))
    ref = np.asarray(_sequential(params, x))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_pipeline_apply_single_stage():
    import jax
    mesh = DeviceMesh(shape=(1,), axis_names=("pp",),
                      devices=jax.devices()[:1])
    params = _make_params(1, 4)
    x = np.random.RandomState(0).randn(4, 4).astype(np.float32)
    out = np.asarray(pl.pipeline_apply(_mlp_stage, params, x, mesh,
                                       n_microbatches=2))
    ref = np.asarray(_sequential(params, x))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_pipelined_block_gluon():
    """Gluon bridge: stack_blocks + PipelinedBlock vs running blocks serially."""
    from mxnet_tpu.gluon import nn
    import jax
    S, B, d = 4, 8, 8
    blocks = []
    for i in range(S):
        blk = nn.HybridSequential()
        blk.add(nn.Dense(d, activation="tanh", flatten=False))
        blk.initialize(mx.init.Xavier(rnd_type="uniform", magnitude=2 + i))
        blocks.append(blk)
    mesh = DeviceMesh(shape=(S,), axis_names=("pp",),
                      devices=jax.devices()[:S])
    x = mx.nd.array(np.random.RandomState(7).randn(B, d).astype(np.float32))
    piped = pl.PipelinedBlock(blocks, mesh, n_microbatches=4)
    out = piped(x).asnumpy()
    ref = x
    for blk in blocks:
        ref = blk(ref)
    np.testing.assert_allclose(out, ref.asnumpy(), rtol=1e-5, atol=1e-5)
