"""SymbolBlock.imports — the json+params interchange round trip
(reference gluon/block.py :: SymbolBlock.imports over Symbol.save +
save_checkpoint artifacts; r2 verdict weak #8)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon


def _train_and_save(tmp_path):
    """Train a small symbolic net via Module, save_checkpoint, return
    (prefix, reference predictions, input)."""
    from mxnet_tpu.module import Module
    x = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(x, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    out = mx.sym.FullyConnected(h, num_hidden=3, name="fc2")
    out = mx.sym.SoftmaxOutput(out, name="softmax")

    mod = Module(out, data_names=["data"], label_names=["softmax_label"])
    r = np.random.RandomState(0)
    xs = r.randn(64, 8).astype(np.float32)
    ys = r.randint(0, 3, (64,)).astype(np.float32)
    it = mx.io.NDArrayIter(data=xs, label=ys, batch_size=16,
                           label_name="softmax_label")
    mod.fit(it, num_epoch=2,
            optimizer_params={"learning_rate": 0.1})
    prefix = os.path.join(str(tmp_path), "small")
    mod.save_checkpoint(prefix, 2)
    probe = xs[:8]
    pred = mod.predict(mx.io.NDArrayIter(data=probe, batch_size=8))
    if isinstance(pred, list):
        pred = pred[0]
    ref = pred.asnumpy()
    return prefix, ref, probe


def test_symbol_block_imports_checkpoint(tmp_path):
    prefix, ref, probe = _train_and_save(tmp_path)
    # checkpoint carries a SoftmaxOutput loss head: strip it down to the
    # logits + an explicit softmax, the upstream inference-import pattern
    loaded = mx.sym.load(f"{prefix}-symbol.json")
    logits = loaded.get_internals()["fc2_output"]
    infer_sym = mx.sym.softmax(logits)
    blk = gluon.SymbolBlock.imports(infer_sym, ["data"],
                                    f"{prefix}-0002.params")
    out = blk(nd.array(probe)).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    # second call with a new batch size rebinds transparently
    out2 = blk(nd.array(probe[:4])).asnumpy()
    np.testing.assert_allclose(out2, ref[:4], rtol=1e-4, atol=1e-5)


def test_symbol_block_unbound_label_raises_helpfully(tmp_path):
    prefix, _, probe = _train_and_save(tmp_path)
    blk = gluon.SymbolBlock.imports(f"{prefix}-symbol.json", ["data"],
                                    f"{prefix}-0002.params")
    with pytest.raises(mx.MXNetError, match="softmax_label"):
        blk(nd.array(probe))


def test_symbol_block_imports_without_params(tmp_path):
    """Importing only the graph: params default to executor zeros."""
    x = mx.sym.Variable("data")
    y = mx.sym.FullyConnected(x, num_hidden=2, name="fc")
    path = os.path.join(str(tmp_path), "g-symbol.json")
    y.save(path)
    blk = gluon.SymbolBlock.imports(path, "data")
    out = blk(nd.ones((3, 4)))
    assert out.shape == (3, 2)


def test_symbol_block_callable_path_still_works():
    blk = gluon.SymbolBlock(lambda a: a * 2)
    out = blk(nd.ones((2, 2)))
    np.testing.assert_allclose(out.asnumpy(), 2.0)
