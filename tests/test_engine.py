"""Engine-contract tests (VERDICT r2 next-round item 9).

Reference models: tests/python/unittest/test_engine.py +
test_exc_handling.py (async error surfacing) and the NaiveEngine
serialized differential oracle (SURVEY §4.2/§5.2 — 'the serialized-vs-
async equivalence trick')."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon
from mxnet_tpu.base import MXNetError


@pytest.fixture
def naive_engine():
    engine.set_engine_type("NaiveEngine")
    yield
    engine.set_engine_type("ThreadedEnginePerDevice")


def _op_battery(ctx=None):
    """A small cross-section of the op corpus: elemwise, reduce, matmul,
    nn, indexing, RNG-free results returned as numpy."""
    r = np.random.RandomState(42)
    a = mx.nd.array(r.randn(4, 5).astype(np.float32), ctx=ctx)
    b = mx.nd.array(r.randn(5, 3).astype(np.float32), ctx=ctx)
    idx = mx.nd.array(np.array([0, 2], np.int32), ctx=ctx)
    outs = [
        mx.nd.dot(a, b),
        (a * 2 + 1).sum(axis=1),
        mx.nd.softmax(a, axis=-1),
        mx.nd.take(a, idx, axis=0),
        mx.nd.relu(a) - mx.nd.sigmoid(a),
        mx.nd.topk(a, k=2, axis=-1, ret_typ="value"),
    ]
    # a gradient through a couple of ops
    w = mx.nd.array(r.randn(5, 3).astype(np.float32), ctx=ctx)
    w.attach_grad()
    with autograd.record():
        loss = (mx.nd.dot(a, w) ** 2).sum()
    loss.backward()
    outs.append(w.grad)
    return [o.asnumpy() for o in outs]


def test_naive_vs_async_differential():
    """NaiveEngine (serialize after every dispatch) must be numerically
    identical to the default async engine — the reference's determinism
    oracle (MXNET_ENGINE_TYPE=NaiveEngine CI trick)."""
    default = _op_battery()
    engine.set_engine_type("NaiveEngine")
    try:
        assert engine.is_naive()
        naive = _op_battery()
    finally:
        engine.set_engine_type("ThreadedEnginePerDevice")
    assert len(default) == len(naive)
    for d, n in zip(default, naive):
        np.testing.assert_array_equal(d, n)


def test_naive_engine_training_matches(naive_engine):
    mx.random.seed(3)
    net = gluon.nn.Dense(3, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    x = mx.nd.ones((2, 4))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    tr.step(2)
    assert np.isfinite(loss.asnumpy()).all()


# ---------------------------------------------------------------------------
# exception handling (test_exc_handling analog)
# ---------------------------------------------------------------------------

def test_invalid_shape_raises_promptly():
    a = mx.nd.ones((2, 3))
    b = mx.nd.ones((4, 5))
    with pytest.raises(Exception):  # noqa: B017 — dot shape mismatch
        mx.nd.dot(a, b)


def test_async_error_surfaces_at_sync_point():
    """The reference test_exc_handling contract: an invalid computation
    queued lazily raises at the NEXT sync point (wait_to_read/asnumpy),
    not at dispatch.  Lazy reshape views reproduce this exactly."""
    out = mx.nd.ones((2,)).reshape((5, 5))  # lazy view — no error yet
    with pytest.raises(Exception, match="reshape"):
        out.asnumpy()  # sync point surfaces the error
    with pytest.raises(Exception, match="reshape"):
        out.wait_to_read()


def test_error_is_synchronous_in_naive_mode(naive_engine):
    # NaiveEngine blocks after every dispatch, so errors become
    # synchronous (reference NaiveEngine semantics); views still
    # validate lazily but any fetch raises immediately after
    out = mx.nd.ones((2,)).reshape((5, 5))
    with pytest.raises(Exception, match="reshape"):
        out.asnumpy()


def test_custom_function_error_propagates():
    class Bad(autograd.Function):
        def forward(self, x):
            raise RuntimeError("boom in custom forward")

        def backward(self, dy):
            return dy

    with pytest.raises(RuntimeError, match="boom"):
        with autograd.record():
            Bad()(mx.nd.ones((2,)))


def test_error_in_hybridized_block_surfaces():
    class Broken(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.reshape(x, shape=(7, 7))  # impossible for (2, 3)

    net = Broken()
    net.initialize()
    net.hybridize()
    with pytest.raises(Exception):  # noqa: B017 — surfaces at first call
        net(mx.nd.ones((2, 3))).asnumpy()


def test_waitall_noop_and_bulk_scope():
    with engine.bulk(16):
        x = mx.nd.ones((8,)) * 3
    mx.nd.waitall()
    np.testing.assert_array_equal(x.asnumpy(), 3.0)
