"""tanh-GELU satellite (ISSUE 7): the approximate-tanh variant
0.5x(1+tanh(sqrt(2/pi)(x+0.044715x^3))) vs the exact erf form, through
every door (the ``gelu`` op, ``LeakyReLU(act_type='gelu')``,
``gluon.nn.GELU``) and the ``MXNET_GELU_TANH`` default knob.

The knob resolves when an executable is FIRST BUILT for the attr set
(trace time, same contract as MXNET_FUSED_ATTENTION) — the knob tests
use fresh shapes so jax traces anew under the flipped environment.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import nn


def _erf_gelu(x):
    from scipy.special import erf
    return 0.5 * x * (1.0 + erf(x / np.sqrt(2.0)))


def _tanh_gelu(x):
    return 0.5 * x * (1.0 + np.tanh(
        np.sqrt(2.0 / np.pi) * (x + 0.044715 * x ** 3)))


X = np.linspace(-6.0, 6.0, 193, dtype=np.float32)


def test_gelu_exact_erf_by_default():
    pytest.importorskip("scipy")
    out = mx.nd.gelu(mx.nd.array(X)).asnumpy()
    np.testing.assert_allclose(out, _erf_gelu(X.astype(np.float64)),
                               rtol=1e-6, atol=1e-6)


def test_gelu_tanh_matches_closed_form_fp32():
    out = mx.nd.gelu(mx.nd.array(X), approximate=True).asnumpy()
    np.testing.assert_allclose(out, _tanh_gelu(X.astype(np.float64)),
                               rtol=1e-5, atol=1e-6)


def test_gelu_tanh_vs_erf_parity_fp32():
    """The approximation's analytic error bound: |tanh-gelu - erf-gelu|
    <= ~1e-3 absolute everywhere (max ~3e-4 near |x|~2) — tight enough
    to swap in as an MFU lever without touching convergence."""
    exact = mx.nd.gelu(mx.nd.array(X), approximate=False).asnumpy()
    approx = mx.nd.gelu(mx.nd.array(X), approximate=True).asnumpy()
    assert np.max(np.abs(exact - approx)) < 1e-3
    assert not np.array_equal(exact, approx)   # genuinely different path


def test_gelu_tanh_vs_erf_parity_bf16():
    """In bf16 the two forms are indistinguishable beyond bf16 epsilon
    (~0.8% relative): the approximation error drowns in the format."""
    import jax.numpy as jnp
    xb = mx.nd.array(X).astype("bfloat16")
    exact = mx.nd.gelu(xb, approximate=False).asnumpy().astype(np.float32)
    approx = mx.nd.gelu(xb, approximate=True).asnumpy().astype(np.float32)
    assert exact.dtype == np.float32 and xb.dtype == jnp.bfloat16.dtype
    np.testing.assert_allclose(exact, approx, rtol=1e-2, atol=1e-2)


def test_leaky_relu_gelu_attr_routes_both_forms():
    x = mx.nd.array(X)
    erf_out = mx.nd.LeakyReLU(x, act_type="gelu").asnumpy()
    tanh_out = mx.nd.LeakyReLU(x, act_type="gelu",
                               approximate=True).asnumpy()
    np.testing.assert_array_equal(
        erf_out, mx.nd.gelu(x, approximate=False).asnumpy())
    np.testing.assert_array_equal(
        tanh_out, mx.nd.gelu(x, approximate=True).asnumpy())


def test_gluon_gelu_block_approximate_arg():
    x = mx.nd.array(X)
    exact = nn.GELU()(x).asnumpy()
    approx = nn.GELU(approximate=True)(x).asnumpy()
    np.testing.assert_array_equal(
        exact, mx.nd.gelu(x, approximate=False).asnumpy())
    np.testing.assert_array_equal(
        approx, mx.nd.gelu(x, approximate=True).asnumpy())
    assert "approximate=True" in repr(nn.GELU(approximate=True))


def test_gelu_tanh_knob_flips_defaults(monkeypatch):
    """MXNET_GELU_TANH=1 makes the DEFAULT (no explicit attr) pick the
    tanh form in ops and new GELU blocks; an explicit approximate= always
    wins over the knob.  Fresh shapes force fresh traces so the knob is
    read under the patched environment."""
    monkeypatch.setenv("MXNET_GELU_TANH", "1")
    xk = np.linspace(-3.0, 3.0, 41, dtype=np.float32)   # unseen shape
    x = mx.nd.array(xk)
    want_tanh = _tanh_gelu(xk.astype(np.float64))
    np.testing.assert_allclose(mx.nd.gelu(x).asnumpy(), want_tanh,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        mx.nd.LeakyReLU(x, act_type="gelu").asnumpy(), want_tanh,
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(nn.GELU()(x).asnumpy(), want_tanh,
                               rtol=1e-5, atol=1e-6)
    # explicit attr beats the knob
    out = mx.nd.gelu(x, approximate=False).asnumpy()
    assert np.max(np.abs(out - want_tanh)) > 1e-6
    out = nn.GELU(approximate=False)(x).asnumpy()
    assert np.max(np.abs(out - want_tanh)) > 1e-6
