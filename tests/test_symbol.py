"""Symbol API tests (reference tests/python/unittest/test_symbol.py +
test_operator.py symbolic cases).  Covers VERDICT r1 item 4: auto-created
param vars, infer_shape through nn ops, bind/simple_bind fwd+bwd."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_auto_created_param_vars():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=10, name="fc1")
    assert fc.list_arguments() == ["data", "fc1_weight", "fc1_bias"]
    fc_nb = mx.sym.FullyConnected(data, num_hidden=10, no_bias=True,
                                  name="fc2")
    assert fc_nb.list_arguments() == ["data", "fc2_weight"]
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="c1")
    assert conv.list_arguments() == ["data", "c1_weight", "c1_bias"]
    bn = mx.sym.BatchNorm(conv, name="bn1")
    assert bn.list_arguments() == \
        ["data", "c1_weight", "c1_bias", "bn1_gamma", "bn1_beta"]
    assert bn.list_auxiliary_states() == \
        ["bn1_moving_mean", "bn1_moving_var"]


def test_explicit_weight_symbol():
    data = mx.sym.var("data")
    w = mx.sym.var("myw")
    fc = mx.sym.FullyConnected(data, w, num_hidden=10, no_bias=True,
                               name="fc1")
    assert fc.list_arguments() == ["data", "myw"]
    # keyword form too
    fc2 = mx.sym.FullyConnected(data=data, weight=w, num_hidden=10,
                                no_bias=True, name="fc2")
    assert fc2.list_arguments() == ["data", "myw"]


def test_infer_shape_through_nn():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.relu(net)
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(8, 20))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (32, 20)
    assert d["fc1_bias"] == (32,)
    assert d["fc2_weight"] == (4, 32)
    assert out_shapes == [(8, 4)]

    # through conv + bn
    img = mx.sym.var("img")
    c = mx.sym.Convolution(img, kernel=(3, 3), num_filter=6, pad=(1, 1),
                           name="c1")
    b = mx.sym.BatchNorm(c, name="b1")
    arg_shapes, out_shapes, aux_shapes = b.infer_shape(img=(2, 3, 8, 8))
    d = dict(zip(b.list_arguments(), arg_shapes))
    assert d["c1_weight"] == (6, 3, 3, 3)
    assert d["b1_gamma"] == (6,)
    assert out_shapes == [(2, 6, 8, 8)]
    assert aux_shapes == [(6,), (6,)]


def test_bind_forward_backward():
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    out = mx.sym.broadcast_mul(data, w)
    loss = mx.sym.sum(out)
    x = nd.array(np.array([[1., 2.], [3., 4.]], "float32"))
    wv = nd.array(np.array([[2., 3.], [4., 5.]], "float32"))
    gx = nd.zeros((2, 2))
    gw = nd.zeros((2, 2))
    ex = loss.bind(mx.cpu(), {"data": x, "w": wv},
                   {"data": gx, "w": gw})
    (o,) = ex.forward(is_train=True)
    assert_almost_equal(o.asnumpy(), np.sum([[2, 6], [12, 20]]))
    ex.backward()
    assert_almost_equal(gx.asnumpy(), wv.asnumpy())
    assert_almost_equal(gw.asnumpy(), x.asnumpy())


def test_simple_bind_and_grad():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = mx.sym.sum(net)
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 5))
    assert ex.arg_dict["fc_weight"].shape == (3, 5)
    x = np.random.randn(4, 5).astype("float32")
    ex.arg_dict["fc_weight"][:] = 0.1
    ex.arg_dict["fc_bias"][:] = 0.0
    ex.forward(is_train=True, data=nd.array(x))
    ex.backward()
    # d sum(xW^T+b) / d b = batch size
    assert_almost_equal(ex.grad_dict["fc_bias"].asnumpy(),
                        np.full(3, 4.0, "float32"))


def test_symbolic_batchnorm_aux_update():
    """BN moving stats must update during symbolic training forward
    (FMutateInputs writeback, VERDICT r1)."""
    data = mx.sym.var("data")
    bn = mx.sym.BatchNorm(data, name="bn")
    ex = bn.simple_bind(ctx=mx.cpu(), data=(16, 4))
    ex.aux_dict["bn_moving_var"][:] = 1.0
    ex.arg_dict["bn_gamma"][:] = 1.0
    before = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    x = np.random.randn(16, 4).astype("float32") + 5.0
    ex.forward(is_train=True, data=nd.array(x))
    after = ex.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(before, after), "moving_mean did not update"


def test_tojson_roundtrip():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    net = mx.sym.tanh(net)
    js = net.tojson()
    back = mx.sym.load_json(js)
    assert back.list_arguments() == net.list_arguments()
    x = np.random.randn(2, 4).astype("float32")
    wv = np.random.randn(8, 4).astype("float32")
    bv = np.random.randn(8).astype("float32")
    kw = {"data": nd.array(x), "fc_weight": nd.array(wv),
          "fc_bias": nd.array(bv)}
    (o1,) = net.eval(**kw)
    (o2,) = back.eval(**kw)
    assert_almost_equal(o1.asnumpy(), o2.asnumpy())


def test_group_and_internals():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    s = a + b
    p = a * b
    g = mx.sym.Group(s, p)
    assert g.num_outputs == 2
    outs = g.eval(a=nd.array([2.0]), b=nd.array([3.0]))
    assert_almost_equal(outs[0].asnumpy(), [5.0])
    assert_almost_equal(outs[1].asnumpy(), [6.0])


def test_grouped_output_shapes():
    a = mx.sym.var("a")
    s1 = mx.sym.sum(a)
    s2 = a * 2
    g = mx.sym.Group(s1, s2)
    _, out_shapes, _ = g.infer_shape(a=(3, 2))
    assert out_shapes == [(), (3, 2)]
