"""Elastic controller e2e (ISSUE 11 acceptance; slow): a REAL n=4 jax
job under the controller survives

 (a) rank death mid-collective → world resized 4 → 3 → probation →
     grown back to 4, and
 (b) the CONTROLLER dying mid-resize (chaos ``controller.resize`` exit)
     → a restarted controller re-adopts the job from its state file and
     finishes the resize,

with the final parameters on every rank BIT-identical to an
uninterrupted fixed-n reference run.  The worker's documented
shard-resident gradient accumulation (tests/_elastic_worker.py) is what
makes the trajectory world-size-invariant; the *resize points*
themselves are recorded in the checkpoint manifest's per-step world
audit, which this test also asserts (steps committed by a world of 3
sit between steps committed by worlds of 4).

Observability acceptance: every induced failure leaves per-rank
flight-recorder postmortems (the dying rank's chaos-exit dump, the
survivors' SIGTERM/deadline dumps, the controller's own resize-chaos
dump) and the terminal roll-up renders ONE merged Chrome trace whose
process lanes cover every worker rank AND the controller.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # three controller jobs, five jax bring-ups

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_elastic_worker.py")
LAUNCH = os.path.join(REPO, "tools", "elastic_launch.py")
N = 4


def _run_controller(workdir, mode, extra_env=None, timeout=280):
    env = dict(os.environ)
    # the controller owns the job's observability dirs (assertions below
    # depend on their layout); drop suite-level redirects and chaos
    for k in ("MXNET_TELEMETRY_DIR", "MXNET_FLIGHTREC_DIR",
              "MXNET_CHAOS", "MXNET_CHAOS_SITES"):
        env.pop(k, None)
    env.update({
        # a dead peer must surface via the Deadline well before the
        # drain grace — this bound IS the survivors' no-hang assertion
        "MXNET_KVSTORE_TIMEOUT_S": "10",
        "MXNET_RESILIENCE_BACKOFF_S": "0.01",
        "MXNET_ELASTIC_MIN_WORKERS": "2",
        "MXNET_ELASTIC_REGROW_STEPS": "2",
        "MXNET_ELASTIC_HEARTBEAT_S": "0.5",
        "MXNET_TPU_JIT_IMPERATIVE": "1",
    })
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, LAUNCH, "-n", str(N), "--workdir", str(workdir),
         "--grace-s", "8", "--max-restarts", "4", "--cpu-devices", "1",
         "--", sys.executable, WORKER, mode, str(workdir)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=timeout)


def _finals(outdir):
    out = {}
    for r in range(N):
        with np.load(os.path.join(outdir, f"final_rank{r}.npz")) as z:
            out[r] = {k: z[k].copy() for k in z.files}
    return out


def test_elastic_resize_and_controller_death_bit_identical(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    chaotic = str(tmp_path / "chaotic")
    ref = str(tmp_path / "ref")

    # 1. rank 3 dies mid-allreduce at step 2; the controller starts the
    #    4 → 3 resize and is itself chaos-killed MID-RESIZE (old world
    #    drained, new world not yet spawned)
    r1 = _run_controller(
        chaotic, "die",
        extra_env={"MXNET_CHAOS": "1",
                   "MXNET_CHAOS_SITES": "controller.resize:exit:1"})
    assert r1.returncode != 0, r1.stdout.decode()
    with open(os.path.join(chaotic, "controller.json")) as f:
        st = json.load(f)
    assert st["phase"] == "draining", st  # died in the resize window
    assert st["next_world"] == 3
    fails = [e for e in st["history"] if e["event"] == "worker_failure"]
    assert fails and fails[0]["kind"] == "worker_death"

    # every induced death left a postmortem: rank 3's chaos exit,
    # survivors' SIGTERM/deadline dumps, the controller's (rank N) own
    # resize-chaos dump
    frdir = os.path.join(chaotic, "flightrec")
    dumps = sorted(os.listdir(frdir))
    dump_ranks = {int(d.split("-")[1][4:]) for d in dumps
                  if d.startswith("flightrec-") and d.endswith(".json")}
    assert set(range(N + 1)) <= dump_ranks, (dump_ranks, dumps)
    killer = [d for d in dumps if "chaos.exit.kvstore.allreduce" in d]
    assert killer and f"rank{N - 1:05d}" in killer[0], dumps
    ctl_dump = [d for d in dumps if "chaos.exit.controller.resize" in d]
    assert ctl_dump and f"rank{N:05d}" in ctl_dump[0], dumps

    # 2. a fresh controller on the same workdir finishes the resize from
    #    the state file: n=3 probation, regrow to n=4, clean completion
    r2 = _run_controller(chaotic, "die")
    assert r2.returncode == 0, r2.stdout.decode()
    with open(os.path.join(chaotic, "report", "summary.json")) as f:
        summary = json.load(f)
    assert summary["outcome"] == "done"
    assert summary["final_world"] == N
    assert summary["restarts"] == 1
    kinds = [e["event"] for e in summary["history"]]
    assert "recover" in kinds and "resume_resize" in kinds \
        and "regrow" in kinds
    resizes = [(e["from_world"], e["to_world"])
               for e in summary["history"] if e["event"] == "resized"]
    assert (3, 4) in resizes            # the grow-back
    # (the 4→3 shrink was executed by the killed controller's recovery
    # path — it shows as resume_resize, not a resized event)

    # resume-with-different-n audit: the manifest records which world
    # committed each step — 4s, then 3s, then 4s again
    with open(os.path.join(chaotic, "ckpt", "manifest.json")) as f:
        man = json.load(f)
    worlds = {int(k): v["n"] for k, v in man["world"].items()}
    assert worlds[0] == 4 and worlds[1] == 4
    assert worlds[2] == 3               # degraded incarnation's steps
    assert worlds[max(worlds)] == 4     # finished at full strength
    assert sorted(man["committed"])[-1] == 7

    # merged Chrome trace: one process lane per worker rank plus the
    # controller's own job-lifecycle lane
    with open(os.path.join(chaotic, "report", "merged_trace.json")) as f:
        trace = json.load(f)
    span_pids = {e["pid"] for e in trace["traceEvents"]
                 if e.get("ph") == "X"}
    assert set(range(N)) <= span_pids, span_pids
    assert N in span_pids               # the controller lane
    ctl_spans = {e["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "X" and e.get("pid") == N}
    assert {"controller.spawn", "controller.drain"} <= ctl_spans

    # 3. uninterrupted fixed-n reference
    r3 = _run_controller(ref, "clean")
    assert r3.returncode == 0, r3.stdout.decode()

    # 4. THE acceptance: bit-identical finals, every rank, despite one
    #    rank death, two resizes, and a controller death
    got, want = _finals(chaotic), _finals(ref)
    for r in range(N):
        assert set(got[r]) == set(want[r])
        for k in want[r]:
            np.testing.assert_array_equal(
                got[r][k], want[r][k],
                err_msg=f"rank {r} param {k} diverged across resizes")
        for k in want[0]:               # replicas agree across ranks
            np.testing.assert_array_equal(got[r][k], got[0][k])
