"""Elastic controller fast suite (ISSUE 11): spawn/watch/resize/survive
against stdlib STUB workers that speak the heartbeat + manifest file
protocols directly — every control-plane path (worker death → shrink →
regrow, bring-up failure, hang, straggler, chaos sites, controller death
mid-resize, re-adoption) runs in seconds with no jax bring-up.  The real
n=4 jax end-to-end (bit-identity across resize points) lives in
tests/test_elastic_chaos.py (slow).
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

import mxnet_tpu as mx  # noqa: F401 — conftest platform setup
from mxnet_tpu.resilience import (
    ElasticController, JobFailedError, chaos, controller as ctl_mod,
    heartbeat as hb,
)
from mxnet_tpu.resilience.policies import Retry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO, "tests", "_stub_elastic_worker.py")
LAUNCH = os.path.join(REPO, "tools", "elastic_launch.py")


@pytest.fixture(autouse=True)
def _restore_observability():
    """Controller runs enable telemetry and re-tag the process rank;
    undo both (and any armed chaos) so the rest of the suite is
    unaffected."""
    import mxnet_tpu.telemetry as tel
    was_enabled = tel.enabled()
    yield
    chaos.clear()
    tel.aggregate.set_rank(None)
    tel.tracer.get_tracer().set_process_label("mxnet_tpu")
    if not was_enabled and not tel.env_enabled():
        tel.disable()


def _ctl(mode, workdir, n, **kw):
    kw.setdefault("poll_s", 0.03)
    kw.setdefault("grace_s", 2.0)
    kw.setdefault("heartbeat_s", 0.1)
    kw.setdefault("max_restarts", 4)
    return ElasticController([sys.executable, STUB, mode], n, str(workdir),
                             **kw)


def _events(summary, kind):
    return [e for e in summary["history"] if e["event"] == kind]


# -- protocol units ---------------------------------------------------------

def test_heartbeat_protocol_roundtrip(tmp_path, monkeypatch):
    d = str(tmp_path / "hb")
    monkeypatch.setenv("MXNET_ELASTIC_HEARTBEAT_DIR", d)
    monkeypatch.setenv("MXNET_DIST_RANK", "2")
    assert hb.enabled()
    try:
        assert hb.start(interval_s=0.05)
        hb.set_step(7)
        hb.set_phase("running")
        recs = hb.read_all(d)
        assert recs[2]["phase"] == "running"
        assert recs[2]["step"] == 7
        assert recs[2]["pid"] == os.getpid()
        assert "verdict" in recs[2]["stepclock"]
        hb.mark_failed("bringup-timeout: test")
        recs = hb.read_all(d)
        assert recs[2]["phase"] == "failed"
        assert "bringup-timeout" in recs[2]["error"]
        hb.mark_done()
        assert hb.read_all(d)[2]["phase"] == "done"
    finally:
        hb.stop()
    # torn/corrupt files are skipped, good ones survive
    with open(os.path.join(d, "hb-rank00003.json"), "w") as f:
        f.write("{not json")
    recs = hb.read_all(d)
    assert 2 in recs and 3 not in recs


def test_heartbeat_inert_without_dir(monkeypatch):
    monkeypatch.delenv("MXNET_ELASTIC_HEARTBEAT_DIR", raising=False)
    assert not hb.enabled()
    assert hb.start() is False
    assert hb.beat() is None


def test_find_straggler_rules():
    def rank(r, verdict, med, steps=5, phase="running"):
        return {"rank": r, "phase": phase,
                "stepclock": {"steps": steps, "verdict": verdict,
                              "phases": {"compute": {"median": med}}}}

    hbs = {0: rank(0, "comms-bound", 0.01),
           1: rank(1, "comms-bound", 0.012),
           2: rank(2, "compute-bound", 0.09)}
    assert ctl_mod.find_straggler(hbs, 3.0) == 2
    assert ctl_mod.find_straggler(hbs, 20.0) is None   # not slow enough
    assert ctl_mod.find_straggler(hbs, 0) is None      # disabled
    # two ranks = no quorum; two non-comms ranks = no unique straggler
    assert ctl_mod.find_straggler(
        {k: hbs[k] for k in (0, 2)}, 3.0) is None
    hbs4 = dict(hbs)
    hbs4[3] = rank(3, "compute-bound", 0.09)
    assert ctl_mod.find_straggler(hbs4, 3.0) is None
    # idle/bringup ranks don't count toward the quorum
    hbs[1] = rank(1, "comms-bound", 0.012, steps=0)
    assert ctl_mod.find_straggler(hbs, 3.0) is None


def test_retry_backoff_delay_schedule():
    r = Retry(backoff_s=0.1, backoff_max_s=0.8, jitter=0)
    assert [r.backoff_delay(k) for k in (-1, 0, 1, 2, 3, 9)] == \
        [0.0, 0.1, 0.2, 0.4, 0.8, 0.8]


def test_state_file_roundtrip_and_corruption(tmp_path):
    c = _ctl("ok", tmp_path, 2)
    c._incarnation = 1
    c._world = 2
    c._save_state("running", extra_key="x")
    st = c._load_state()
    assert st["phase"] == "running" and st["incarnation"] == 1
    assert st["extra_key"] == "x"
    with open(c._state_path(), "w") as f:
        f.write("{torn")
    assert c._load_state() is None


def test_manifest_latest_reads_commit_ledger(tmp_path):
    c = _ctl("ok", tmp_path, 2)
    assert c._manifest_latest() is None
    os.makedirs(tmp_path / "ckpt")
    with open(tmp_path / "ckpt" / "manifest.json", "w") as f:
        json.dump({"committed": [0, 1, 4]}, f)
    assert c._manifest_latest() == 4


# -- whole-job control-plane stories (stub workers) -------------------------

def test_clean_job_completes_with_report(tmp_path):
    c = _ctl("ok", tmp_path, 3)
    summary = c.run()
    assert summary["outcome"] == "done"
    assert summary["final_world"] == 3
    assert summary["restarts"] == 0
    assert summary["incarnations"] == 1
    st = c._load_state()
    assert st["phase"] == "done"
    # terminal roll-up: summary + merged trace + prom + report text
    rd = tmp_path / "report"
    with open(rd / "summary.json") as f:
        assert json.load(f)["outcome"] == "done"
    with open(rd / "merged_trace.json") as f:
        trace = json.load(f)
    # the controller's own job-lifecycle spans ride the merged trace,
    # under a process lane labeled as the controller
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "controller.spawn" in names
    assert any(e.get("ph") == "M" and e.get("name") == "process_name"
               and "controller" in e["args"]["name"]
               for e in trace["traceEvents"])
    assert (rd / "merged.prom").exists()
    assert (rd / "report.txt").exists()


def test_worker_death_resizes_down_then_regrows(tmp_path):
    c = _ctl("resize", tmp_path, 4, min_workers=2, regrow_steps=3)
    summary = c.run()
    assert summary["outcome"] == "done"
    assert summary["restarts"] == 1
    assert summary["final_world"] == 4          # grew back
    assert summary["incarnations"] == 3
    fails = _events(summary, "worker_failure")
    assert fails and fails[0]["kind"] == "worker_death"
    assert fails[0]["bringup"] is False
    resizes = _events(summary, "resized")
    assert [(e["from_world"], e["to_world"]) for e in resizes] == \
        [(4, 3), (3, 4)]
    assert resizes[0]["planned"] is False and resizes[1]["planned"] is True
    assert _events(summary, "regrow")


def test_bringup_failure_restarts_at_same_world(tmp_path):
    c = _ctl("bringup-fail", tmp_path, 3)
    summary = c.run()
    assert summary["outcome"] == "done"
    assert summary["restarts"] == 1
    assert summary["final_world"] == 3          # never shrank
    fails = _events(summary, "worker_failure")
    assert fails and fails[0]["bringup"] is True
    assert not _events(summary, "resized")


def test_hang_detection_kills_and_resizes(tmp_path):
    c = _ctl("hang", tmp_path, 3, hang_s=0.6, min_workers=2)
    summary = c.run()
    assert summary["outcome"] == "done"
    assert summary["final_world"] == 2
    hangs = _events(summary, "worker_hang")
    assert hangs and hangs[0]["rank"] == 2
    assert _events(summary, "worker_failure")[0]["kind"] == "hang"


def test_straggler_mitigation_from_stepclock_verdicts(tmp_path):
    c = _ctl("straggler", tmp_path, 4, straggler_factor=3.0, min_workers=2)
    summary = c.run()
    assert summary["outcome"] == "done"
    assert summary["final_world"] == 3
    stragglers = _events(summary, "straggler")
    assert stragglers and stragglers[0]["rank"] == 1
    assert _events(summary, "worker_failure")[0]["kind"] == "straggler"


def test_restart_budget_exhaustion_is_terminal(tmp_path):
    c = _ctl("bringup-fail", tmp_path, 2, max_restarts=0)
    with pytest.raises(JobFailedError):
        c.run()
    st = c._load_state()
    assert st["phase"] == "failed"
    with open(tmp_path / "report" / "summary.json") as f:
        assert json.load(f)["outcome"] == "failed"


def test_controller_chaos_sites_fire_deterministically(tmp_path):
    """ISSUE 11 satellite: controller.spawn / controller.resize chaos
    sites with exact hit counts — 3 spawns (initial, shrink, regrow) and
    2 resizes for the canonical death→shrink→regrow story."""
    spawn0 = chaos.fault_count("controller.spawn")
    resize0 = chaos.fault_count("controller.resize")
    chaos.inject("controller.spawn", kind="delay", times=0, delay_s=0)
    chaos.inject("controller.resize", kind="delay", times=0, delay_s=0)
    try:
        c = _ctl("resize", tmp_path, 4, min_workers=2, regrow_steps=3)
        summary = c.run()
    finally:
        chaos.clear()
    assert summary["outcome"] == "done"
    assert chaos.fault_count("controller.spawn") - spawn0 == 3
    assert chaos.fault_count("controller.resize") - resize0 == 2


# -- the controller's own death (subprocess CLI) ----------------------------

def _cli_env(extra=None):
    env = dict(os.environ)
    # the controller must own the job's observability dirs (the test
    # asserts dump locations); drop any suite-level redirects
    for k in ("MXNET_TELEMETRY_DIR", "MXNET_FLIGHTREC_DIR",
              "MXNET_CHAOS", "MXNET_CHAOS_SITES"):
        env.pop(k, None)
    env.update(extra or {})
    return env


def _cli(workdir, n, mode, extra_env=None, extra_args=()):
    return subprocess.Popen(
        [sys.executable, LAUNCH, "-n", str(n), "--workdir", str(workdir),
         "--grace-s", "2", "--max-restarts", "4", *extra_args,
         "--", sys.executable, STUB, mode],
        env=_cli_env(extra_env), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)


@pytest.mark.slow  # two CLI controller launches (~13s)
def test_controller_death_mid_resize_then_recovery(tmp_path):
    """Kill the CONTROL PLANE in the resize crash window (old world
    drained, new world not spawned) via the controller.resize chaos
    site, then restart it: recovery must finish the resize from the
    state file and drive the job to completion."""
    wd = str(tmp_path / "job")
    p = _cli(wd, 3, "resize",
             extra_env={"MXNET_CHAOS": "1",
                        "MXNET_CHAOS_SITES": "controller.resize:exit:1",
                        "MXNET_ELASTIC_REGROW_STEPS": "3",
                        "MXNET_ELASTIC_MIN_WORKERS": "2"})
    out, _ = p.communicate(timeout=60)
    assert p.returncode != 0, out.decode()
    with open(os.path.join(wd, "controller.json")) as f:
        st = json.load(f)
    assert st["phase"] == "draining"            # died mid-resize
    assert st["next_world"] == 2
    # the control plane left its own postmortem
    dumps = os.listdir(os.path.join(wd, "flightrec"))
    assert any("chaos.exit.controller.resize" in d for d in dumps), dumps

    p = _cli(wd, 3, "resize",
             extra_env={"MXNET_ELASTIC_REGROW_STEPS": "3",
                        "MXNET_ELASTIC_MIN_WORKERS": "2"})
    out, _ = p.communicate(timeout=60)
    assert p.returncode == 0, out.decode()
    with open(os.path.join(wd, "report", "summary.json")) as f:
        summary = json.load(f)
    assert summary["outcome"] == "done"
    assert summary["final_world"] == 3          # regrew to target
    kinds = [e["event"] for e in summary["history"]]
    assert "recover" in kinds and "resume_resize" in kinds
    # chaos bookkeeping surfaced in the roll-up (hit-count assertion for
    # the first, killed, controller lives in its state-file history)
    assert "chaos" in summary


@pytest.mark.slow  # two CLI controller launches (~5s)
def test_controller_readoption_of_live_workers(tmp_path):
    """SIGKILL a controller whose workers are healthy; a fresh
    controller on the same workdir must ADOPT the live pids (no respawn)
    and see the job through."""
    wd = str(tmp_path / "job")
    p1 = _cli(wd, 2, "forever")
    state_path = os.path.join(wd, "controller.json")
    deadline = time.time() + 30
    st = None
    while time.time() < deadline:
        try:
            with open(state_path) as f:
                st = json.load(f)
            if st["phase"] == "running" and len(st["workers"]) == 2:
                break
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    assert st and st["phase"] == "running"
    pids = [w["pid"] for w in st["workers"]]
    os.kill(p1.pid, signal.SIGKILL)
    p1.wait(timeout=10)
    assert all(ctl_mod._pid_alive(pid) for pid in pids)  # orphans live on

    p2 = _cli(wd, 2, "forever")
    time.sleep(0.5)
    with open(os.path.join(wd, "finish-flag"), "w") as f:
        f.write("done")
    out, _ = p2.communicate(timeout=60)
    assert p2.returncode == 0, out.decode()
    with open(os.path.join(wd, "report", "summary.json")) as f:
        summary = json.load(f)
    assert summary["outcome"] == "done"
    adopted = [e for e in summary["history"] if e["event"] == "adopted"]
    assert adopted and sorted(adopted[0]["live"]) == [0, 1]
    assert summary["incarnations"] == 1         # no respawn happened
