"""Broad table-driven mx.np ↔ numpy parity sweep (reference
test_numpy_op.py's per-op coverage style, P3/N7 numpy families).

Each case runs the mx.np function and the same-named numpy function on
identical inputs and asserts elementwise agreement — ~90 functions across
unary/binary/reduction/shape/linalg families, plus np.random statistical
checks and npx.set_np semantics."""

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

np = mx.np


def _r(shape, seed=0, positive=False, small=False):
    r = onp.random.RandomState(seed)
    x = r.randn(*shape).astype(onp.float32)
    if positive:
        x = onp.abs(x) + 0.1
    if small:
        x = x * 0.4
    return x


UNARY = [
    ("exp", {}), ("expm1", {}), ("log", {"positive": True}),
    ("log2", {"positive": True}), ("log10", {"positive": True}),
    ("log1p", {"positive": True}), ("sqrt", {"positive": True}),
    ("cbrt", {}), ("square", {}), ("abs", {}), ("sign", {}),
    ("floor", {}), ("ceil", {}), ("trunc", {}), ("rint", {}),
    ("sin", {}), ("cos", {}), ("tan", {"small": True}),
    ("arcsin", {"small": True}), ("arccos", {"small": True}),
    ("arctan", {}), ("sinh", {}), ("cosh", {}), ("tanh", {}),
    ("arcsinh", {}), ("arctanh", {"small": True}),
    ("degrees", {}), ("radians", {}), ("reciprocal", {"positive": True}),
    ("negative", {}), ("exp2", {"small": True}),
]


@pytest.mark.parametrize("name,opts", UNARY, ids=[u[0] for u in UNARY])
def test_np_unary(name, opts):
    if not hasattr(np, name) or not hasattr(onp, name):
        pytest.skip(f"{name} not on both surfaces")
    x = _r((3, 5), positive=opts.get("positive", False),
           small=opts.get("small", False))
    got = getattr(np, name)(np.array(x)).asnumpy()
    want = getattr(onp, name)(x)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


BINARY = ["add", "subtract", "multiply", "divide", "power", "maximum",
          "minimum", "hypot", "arctan2", "fmod", "copysign",
          "greater", "greater_equal", "less", "less_equal", "equal",
          "not_equal", "logaddexp"]


@pytest.mark.parametrize("name", BINARY)
def test_np_binary(name):
    if not hasattr(np, name) or not hasattr(onp, name):
        pytest.skip(f"{name} not on both surfaces")
    a = onp.abs(_r((4, 3), 1)) + 0.5
    b = onp.abs(_r((4, 3), 2)) + 0.5
    got = getattr(np, name)(np.array(a), np.array(b)).asnumpy()
    want = getattr(onp, name)(a, b)
    onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                rtol=2e-5, atol=2e-6)


REDUCTIONS = ["sum", "prod", "mean", "std", "var", "max", "min",
              "argmax", "argmin", "cumsum", "cumprod"]


@pytest.mark.parametrize("name", REDUCTIONS)
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_np_reductions(name, axis):
    x = onp.abs(_r((3, 4), 3)) * 0.5 + 0.5
    got = getattr(np, name)(np.array(x), axis=axis).asnumpy()
    want = getattr(onp, name)(x, axis=axis)
    onp.testing.assert_allclose(onp.asarray(got, dtype=want.dtype), want,
                                rtol=2e-5, atol=1e-5)


SHAPE_FNS = [
    ("reshape", lambda m, x: m.reshape(m.array(x), (6, 2)),
     lambda x: onp.reshape(x, (6, 2))),
    ("transpose", lambda m, x: m.transpose(m.array(x)),
     lambda x: onp.transpose(x)),
    ("concatenate", lambda m, x: m.concatenate([m.array(x), m.array(x)],
                                               axis=0),
     lambda x: onp.concatenate([x, x], axis=0)),
    ("stack", lambda m, x: m.stack([m.array(x), m.array(x)], axis=1),
     lambda x: onp.stack([x, x], axis=1)),
    ("split", lambda m, x: m.split(m.array(x), 2, axis=0)[1],
     lambda x: onp.split(x, 2, axis=0)[1]),
    ("flip", lambda m, x: m.flip(m.array(x), axis=1),
     lambda x: onp.flip(x, axis=1)),
    ("roll", lambda m, x: m.roll(m.array(x), 2, axis=0),
     lambda x: onp.roll(x, 2, axis=0)),
    ("tile", lambda m, x: m.tile(m.array(x), (2, 1)),
     lambda x: onp.tile(x, (2, 1))),
    ("repeat", lambda m, x: m.repeat(m.array(x), 2, axis=1),
     lambda x: onp.repeat(x, 2, axis=1)),
    ("expand_dims", lambda m, x: m.expand_dims(m.array(x), 0),
     lambda x: onp.expand_dims(x, 0)),
    ("squeeze", lambda m, x: m.squeeze(m.expand_dims(m.array(x), 0)),
     lambda x: x),
    ("where", lambda m, x: m.where(m.array(x) > 0, m.array(x),
                                   m.zeros_like(m.array(x))),
     lambda x: onp.where(x > 0, x, onp.zeros_like(x))),
    ("clip", lambda m, x: m.clip(m.array(x), -0.5, 0.5),
     lambda x: onp.clip(x, -0.5, 0.5)),
    ("sort", lambda m, x: m.sort(m.array(x), axis=1),
     lambda x: onp.sort(x, axis=1)),
    ("argsort", lambda m, x: m.argsort(m.array(x), axis=1),
     lambda x: onp.argsort(x, axis=1)),
    ("unique", lambda m, x: m.unique(m.array(onp.round(x))),
     lambda x: onp.unique(onp.round(x))),
    ("diff", lambda m, x: m.diff(m.array(x), axis=1),
     lambda x: onp.diff(x, axis=1)),
    ("pad", lambda m, x: m.pad(m.array(x), ((1, 1), (0, 0))),
     lambda x: onp.pad(x, ((1, 1), (0, 0)))),
    ("trace", lambda m, x: m.trace(m.array(x)),
     lambda x: onp.trace(x)),
    ("outer", lambda m, x: m.outer(m.array(x[0]), m.array(x[1])),
     lambda x: onp.outer(x[0], x[1])),
    ("einsum", lambda m, x: m.einsum("ij,kj->ik", m.array(x), m.array(x)),
     lambda x: onp.einsum("ij,kj->ik", x, x)),
    ("dot", lambda m, x: m.dot(m.array(x), m.array(x.T)),
     lambda x: onp.dot(x, x.T)),
    ("matmul", lambda m, x: m.matmul(m.array(x), m.array(x.T)),
     lambda x: onp.matmul(x, x.T)),
    ("tensordot", lambda m, x: m.tensordot(m.array(x), m.array(x),
                                           axes=([1], [1])),
     lambda x: onp.tensordot(x, x, axes=([1], [1]))),
    ("kron", lambda m, x: m.kron(m.array(x[:2, :2]), m.array(x[:2, :2])),
     lambda x: onp.kron(x[:2, :2], x[:2, :2])),
    ("meshgrid", lambda m, x: m.meshgrid(m.array(x[0]), m.array(x[1]))[0],
     lambda x: onp.meshgrid(x[0], x[1])[0]),
    ("atleast_2d", lambda m, x: m.atleast_2d(m.array(x[0])),
     lambda x: onp.atleast_2d(x[0])),
    ("ravel", lambda m, x: m.ravel(m.array(x)),
     lambda x: onp.ravel(x)),
    ("triu", lambda m, x: m.triu(m.array(x)), lambda x: onp.triu(x)),
    ("tril", lambda m, x: m.tril(m.array(x)), lambda x: onp.tril(x)),
]


@pytest.mark.parametrize("case", SHAPE_FNS, ids=[c[0] for c in SHAPE_FNS])
def test_np_shape_and_linalgish(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np, name):
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 3), 7)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp_fn(x)
    onp.testing.assert_allclose(onp.asarray(got, dtype=want.dtype), want,
                                rtol=2e-5, atol=2e-6)


LINALG = [
    ("norm", lambda m, a: m.linalg.norm(a), lambda a: onp.linalg.norm(a)),
    ("det", lambda m, a: m.linalg.det(a), lambda a: onp.linalg.det(a)),
    ("inv", lambda m, a: m.linalg.inv(a), lambda a: onp.linalg.inv(a)),
    ("slogdet", lambda m, a: m.linalg.slogdet(a)[1],
     lambda a: onp.linalg.slogdet(a)[1]),
    ("solve", lambda m, a: m.linalg.solve(a, m.ones((3, 1))
                                          if hasattr(m, 'ones') else None),
     lambda a: onp.linalg.solve(a, onp.ones((3, 1), onp.float32))),
    ("cholesky", lambda m, a: m.linalg.cholesky(a),
     lambda a: onp.linalg.cholesky(a)),
    ("eigvalsh", lambda m, a: m.linalg.eigvalsh(a),
     lambda a: onp.linalg.eigvalsh(a)),
    ("matrix_rank", lambda m, a: m.linalg.matrix_rank(a),
     lambda a: onp.linalg.matrix_rank(a)),
    ("pinv", lambda m, a: m.linalg.pinv(a), lambda a: onp.linalg.pinv(a)),
]


@pytest.mark.parametrize("case", LINALG, ids=[c[0] for c in LINALG])
def test_np_linalg(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np.linalg, name):
        pytest.skip(f"mx.np.linalg.{name} absent")
    r = onp.random.RandomState(11)
    a = r.randn(3, 3).astype(onp.float32)
    spd = (a @ a.T + 3 * onp.eye(3)).astype(onp.float32)  # SPD for chol etc.
    got = mx_fn(np, np.array(spd))
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp_fn(spd)
    onp.testing.assert_allclose(got, onp.asarray(want), rtol=5e-4,
                                atol=5e-5)


def test_np_random_statistics():
    mx.random.seed(7)
    u = np.random.uniform(0, 1, size=(20000,)).asnumpy()
    assert 0.48 < u.mean() < 0.52
    assert u.min() >= 0 and u.max() <= 1
    g = np.random.normal(2.0, 3.0, size=(20000,)).asnumpy()
    assert abs(g.mean() - 2.0) < 0.1
    assert abs(g.std() - 3.0) < 0.1
    ri = np.random.randint(0, 10, size=(5000,)).asnumpy()
    assert set(onp.unique(ri)) <= set(range(10))


def test_np_autograd_through_np_functions():
    """mx.np functions record on the imperative tape like nd ops."""
    x = np.array(_r((3, 3), 13))
    x.attach_grad()
    with autograd.record():
        y = np.sum(np.tanh(x) * np.exp(x * 0.1))
    y.backward()
    g = x.grad.asnumpy()
    xv = x.asnumpy()
    want = (1 - onp.tanh(xv) ** 2) * onp.exp(xv * 0.1) \
        + onp.tanh(xv) * 0.1 * onp.exp(xv * 0.1)
    onp.testing.assert_allclose(g, want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# delegated-surface parity extension (ISSUE 8 satellite, VERDICT weak #6):
# a representative ~30-function slice across the three behavioral axes the
# thin delegation could silently get wrong — dtype promotion, axis kwargs
# (tuple / negative / keepdims), and python-scalar / 0-d operands.
# ---------------------------------------------------------------------------

# dtype pairs where numpy and the XLA lattice agree (int32+float32 is
# deliberately absent: numpy value-promotes it to float64, which the
# x64-disabled backend cannot represent — a documented divergence)
_PROMO_PAIRS = [("int16", "float32"), ("int8", "float32"),
                ("int8", "int32"), ("uint8", "int32"),
                ("bool", "int32"), ("int32", "int32"),
                ("float32", "float32")]
_PROMO_FNS = ["add", "subtract", "multiply", "maximum", "minimum"]


@pytest.mark.parametrize("da,db", _PROMO_PAIRS,
                         ids=[f"{a}+{b}" for a, b in _PROMO_PAIRS])
@pytest.mark.parametrize("name", _PROMO_FNS)
def test_np_dtype_promotion(name, da, db):
    av = onp.array([1, 0, 3]).astype(da)
    bv = onp.array([2, 5, 1]).astype(db)
    got = getattr(np, name)(np.array(av), np.array(bv)).asnumpy()
    want = getattr(onp, name)(av, bv)
    assert onp.dtype(got.dtype) == want.dtype, \
        f"{name}({da},{db}): promoted to {got.dtype}, numpy {want.dtype}"
    onp.testing.assert_array_equal(got, want)


def test_np_division_promotes_ints_to_float():
    """true_divide of ints must yield a float (numpy: float64; here the
    x64-disabled analog float32) with numpy's values."""
    a = np.array(onp.array([7, 8, 9], onp.int32))
    b = np.array(onp.array([2, 4, 3], onp.int32))
    got = np.divide(a, b).asnumpy()
    assert onp.dtype(got.dtype).kind == "f"
    onp.testing.assert_allclose(
        got, onp.divide(onp.array([7, 8, 9]), onp.array([2, 4, 3])),
        rtol=1e-6)


_AXIS_FNS = ["sum", "mean", "prod", "std", "var", "max", "min"]


@pytest.mark.parametrize("axis", [(0, 2), (1,), -1, -2, None],
                         ids=["tuple02", "tuple1", "neg1", "neg2", "none"])
@pytest.mark.parametrize("keepdims", [False, True])
@pytest.mark.parametrize("name", _AXIS_FNS)
def test_np_reduction_axis_kwargs(name, axis, keepdims):
    x = onp.abs(_r((2, 3, 4), 21)) + 0.5
    got = getattr(np, name)(np.array(x), axis=axis,
                            keepdims=keepdims).asnumpy()
    want = getattr(onp, name)(x, axis=axis, keepdims=keepdims)
    assert got.shape == want.shape, \
        f"{name} axis={axis} keepdims={keepdims}: {got.shape} vs {want.shape}"
    onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                rtol=2e-5, atol=1e-5)


@pytest.mark.parametrize("name", ["argmax", "argmin", "cumsum"])
@pytest.mark.parametrize("axis", [-1, 0])
def test_np_index_and_scan_negative_axis(name, axis):
    x = _r((3, 4), 22)
    got = getattr(np, name)(np.array(x), axis=axis).asnumpy()
    want = getattr(onp, name)(x, axis=axis)
    if name == "cumsum":  # XLA's log-depth scan reassociates the sum
        onp.testing.assert_allclose(got, want, rtol=2e-6, atol=2e-6)
    else:
        onp.testing.assert_array_equal(got, want)


_SCALAR_CASES = [
    ("add", lambda m, a: m.add(a, 2)),
    ("subtract", lambda m, a: m.subtract(a, 1.5)),
    ("multiply", lambda m, a: m.multiply(a, 3)),
    ("divide", lambda m, a: m.divide(a, 2.0)),
    ("power", lambda m, a: m.power(a, 2)),
    ("maximum", lambda m, a: m.maximum(a, 1.5)),
    ("minimum", lambda m, a: m.minimum(a, 1.5)),
    ("mod", lambda m, a: m.mod(a, 3)),
    ("floor_divide", lambda m, a: m.floor_divide(a, 3)),
    ("arctan2", lambda m, a: m.arctan2(a, 2.0)),
]


@pytest.mark.parametrize("case", _SCALAR_CASES,
                         ids=[c[0] for c in _SCALAR_CASES])
def test_np_python_scalar_operand(case):
    name, fn = case
    x = onp.abs(_r((3, 4), 23)) + 1.0
    got = fn(np, np.array(x)).asnumpy()
    want = fn(onp, x)
    onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                rtol=2e-5, atol=2e-6)


def test_np_zero_d_arrays():
    """0-d arrays flow through unary/binary/reduction like numpy's."""
    z = np.array(3.5)
    assert z.shape == ()
    assert float(np.add(z, 1.5).asnumpy()) == 5.0
    assert float(np.exp(np.array(0.0)).asnumpy()) == 1.0
    # reducing a 0-d array is the identity, as in numpy
    assert float(np.sum(z).asnumpy()) == 3.5
    assert np.sum(z).shape == ()
    # reducing a 1-d array to 0-d round-trips through python float
    s = np.sum(np.array(onp.ones(4, onp.float32)))
    assert s.shape == () and float(s.asnumpy()) == 4.0
    # 0-d broadcasts against arrays like a scalar
    got = np.multiply(np.array(onp.array([1.0, 2.0], onp.float32)), z)
    onp.testing.assert_allclose(got.asnumpy(), [3.5, 7.0])


# ---------------------------------------------------------------------------
# delegated-surface parity extension round 2 (ISSUE 11 satellite): another
# ~34-function slice — searching/counting, nan-aware statistics, logic
# predicates, integer/bit math, construction, and axis manipulation —
# the families where thin jnp delegation could silently diverge from
# numpy (bool/int result dtypes, nan propagation, negative-axis moves).
# ---------------------------------------------------------------------------

def _xnan():
    x = _r((3, 4), 31)
    x[0, 1] = onp.nan
    x[2, 2] = onp.inf
    return x


EXT_FNS = [
    ("searchsorted",
     lambda m, x: m.searchsorted(m.sort(m.array(x.ravel())),
                                 m.array(x[0])),
     lambda x: onp.searchsorted(onp.sort(x.ravel()), x[0])),
    ("count_nonzero",
     lambda m, x: m.count_nonzero(m.array(x) > 0, axis=1),
     lambda x: onp.count_nonzero(x > 0, axis=1)),
    ("nonzero",
     lambda m, x: m.nonzero(m.array(x) > 0)[0],
     lambda x: onp.nonzero(x > 0)[0]),
    ("flatnonzero",
     lambda m, x: m.flatnonzero(m.array(x) > 0),
     lambda x: onp.flatnonzero(x > 0)),
    ("argwhere",
     lambda m, x: m.argwhere(m.array(x) > 0),
     lambda x: onp.argwhere(x > 0)),
    ("median", lambda m, x: m.median(m.array(x), axis=1),
     lambda x: onp.median(x, axis=1)),
    ("percentile", lambda m, x: m.percentile(m.array(x), 30, axis=0),
     lambda x: onp.percentile(x, 30, axis=0)),
    ("quantile", lambda m, x: m.quantile(m.array(x), 0.7),
     lambda x: onp.quantile(x, 0.7)),
    ("average",
     lambda m, x: m.average(m.array(x), axis=1),
     lambda x: onp.average(x, axis=1)),
    ("ptp", lambda m, x: m.ptp(m.array(x), axis=0),
     lambda x: onp.ptp(x, axis=0)),
    ("nanmean", lambda m, x: m.nanmean(m.array(_xnan()), axis=0),
     lambda x: onp.nanmean(_xnan(), axis=0)),
    ("nansum", lambda m, x: m.nansum(m.array(_xnan()), axis=1),
     lambda x: onp.nansum(_xnan(), axis=1)),
    ("nanmax", lambda m, x: m.nanmax(m.array(_xnan()[:2]), axis=1),
     lambda x: onp.nanmax(_xnan()[:2], axis=1)),
    ("nanstd", lambda m, x: m.nanstd(m.array(_xnan()[:2]), axis=1),
     lambda x: onp.nanstd(_xnan()[:2], axis=1)),
    ("isnan", lambda m, x: m.isnan(m.array(_xnan())),
     lambda x: onp.isnan(_xnan())),
    ("isinf", lambda m, x: m.isinf(m.array(_xnan())),
     lambda x: onp.isinf(_xnan())),
    ("isfinite", lambda m, x: m.isfinite(m.array(_xnan())),
     lambda x: onp.isfinite(_xnan())),
    ("signbit", lambda m, x: m.signbit(m.array(x)),
     lambda x: onp.signbit(x)),
    ("logical_and",
     lambda m, x: m.logical_and(m.array(x) > 0, m.array(x) < 1),
     lambda x: onp.logical_and(x > 0, x < 1)),
    ("logical_or",
     lambda m, x: m.logical_or(m.array(x) > 1, m.array(x) < -1),
     lambda x: onp.logical_or(x > 1, x < -1)),
    ("logical_xor",
     lambda m, x: m.logical_xor(m.array(x) > 0, m.array(x) > 1),
     lambda x: onp.logical_xor(x > 0, x > 1)),
    ("logical_not", lambda m, x: m.logical_not(m.array(x) > 0),
     lambda x: onp.logical_not(x > 0)),
    ("isclose",
     lambda m, x: m.isclose(m.array(x), m.array(x + 1e-7)),
     lambda x: onp.isclose(x, x + 1e-7)),
    ("fmax", lambda m, x: m.fmax(m.array(x), m.array(-x)),
     lambda x: onp.fmax(x, -x)),
    ("fmin", lambda m, x: m.fmin(m.array(x), m.array(-x)),
     lambda x: onp.fmin(x, -x)),
    ("fabs", lambda m, x: m.fabs(m.array(x)), lambda x: onp.fabs(x)),
    ("heaviside", lambda m, x: m.heaviside(m.array(x), 0.5),
     lambda x: onp.heaviside(x, onp.float32(0.5))),
    ("nan_to_num", lambda m, x: m.nan_to_num(m.array(_xnan())),
     lambda x: onp.nan_to_num(_xnan())),
    ("ldexp",
     lambda m, x: m.ldexp(m.array(x),
                          m.array(onp.arange(5, dtype=onp.int32))),
     lambda x: onp.ldexp(x, onp.arange(5, dtype=onp.int32))),
    ("gcd",
     lambda m, x: m.gcd(m.array(onp.array([12, 18, 7], onp.int32)),
                        m.array(onp.array([8, 27, 21], onp.int32))),
     lambda x: onp.gcd(onp.array([12, 18, 7], onp.int32),
                       onp.array([8, 27, 21], onp.int32))),
    ("lcm",
     lambda m, x: m.lcm(m.array(onp.array([4, 6, 5], onp.int32)),
                        m.array(onp.array([6, 8, 7], onp.int32))),
     lambda x: onp.lcm(onp.array([4, 6, 5], onp.int32),
                       onp.array([6, 8, 7], onp.int32))),
    ("linspace", lambda m, x: m.linspace(-2.0, 2.0, 9),
     lambda x: onp.linspace(-2.0, 2.0, 9).astype(onp.float32)),
    ("logspace", lambda m, x: m.logspace(0.0, 2.0, 5),
     lambda x: onp.logspace(0.0, 2.0, 5).astype(onp.float32)),
    ("eye", lambda m, x: m.eye(4, 5, 1), lambda x: onp.eye(4, 5, 1)),
    ("tri", lambda m, x: m.tri(4, 4, -1), lambda x: onp.tri(4, 4, -1)),
    ("diag", lambda m, x: m.diag(m.diag(m.array(x[:3, :3]))),
     lambda x: onp.diag(onp.diag(x[:3, :3]))),
    ("rot90", lambda m, x: m.rot90(m.array(x)),
     lambda x: onp.rot90(x)),
    ("fliplr", lambda m, x: m.fliplr(m.array(x)),
     lambda x: onp.fliplr(x)),
    ("flipud", lambda m, x: m.flipud(m.array(x)),
     lambda x: onp.flipud(x)),
    ("moveaxis",
     lambda m, x: m.moveaxis(m.array(x[:, :3].reshape(2, 2, 3)), 0, -1),
     lambda x: onp.moveaxis(x[:, :3].reshape(2, 2, 3), 0, -1)),
    ("swapaxes", lambda m, x: m.swapaxes(m.array(x), 0, 1),
     lambda x: onp.swapaxes(x, 0, 1)),
    ("broadcast_to",
     lambda m, x: m.broadcast_to(m.array(x[0]), (3, 5)),
     lambda x: onp.broadcast_to(x[0], (3, 5))),
    ("bincount",
     lambda m, x: m.bincount(m.array(onp.array([0, 1, 1, 3, 2, 1],
                                               onp.int32))),
     lambda x: onp.bincount(onp.array([0, 1, 1, 3, 2, 1], onp.int32))),
    ("digitize",
     lambda m, x: m.digitize(m.array(x),
                             m.array(onp.array([-1.0, 0.0, 1.0],
                                               onp.float32))),
     lambda x: onp.digitize(x, onp.array([-1.0, 0.0, 1.0], onp.float32))),
    ("interp",
     lambda m, x: m.interp(m.array(x.ravel()),
                           m.array(onp.array([-2.0, 0.0, 2.0],
                                             onp.float32)),
                           m.array(onp.array([0.0, 1.0, 4.0],
                                             onp.float32))),
     lambda x: onp.interp(x.ravel(),
                          onp.array([-2.0, 0.0, 2.0], onp.float32),
                          onp.array([0.0, 1.0, 4.0], onp.float32))),
    ("cross",
     lambda m, x: m.cross(m.array(x[:, :3]), m.array(x[:, 1:4])),
     lambda x: onp.cross(x[:, :3], x[:, 1:4])),
    ("corrcoef", lambda m, x: m.corrcoef(m.array(x)),
     lambda x: onp.corrcoef(x)),
    ("cov", lambda m, x: m.cov(m.array(x)), lambda x: onp.cov(x)),
    ("ediff1d", lambda m, x: m.ediff1d(m.array(x)),
     lambda x: onp.ediff1d(x)),
    ("array_split",
     lambda m, x: m.array_split(m.array(x), 3, axis=1)[1],
     lambda x: onp.array_split(x, 3, axis=1)[1]),
    ("column_stack",
     lambda m, x: m.column_stack([m.array(x[0]), m.array(x[1])]),
     lambda x: onp.column_stack([x[0], x[1]])),
    ("dstack", lambda m, x: m.dstack([m.array(x), m.array(x)]),
     lambda x: onp.dstack([x, x])),
    ("take_along_axis",
     lambda m, x: m.take_along_axis(m.array(x),
                                    m.argsort(m.array(x), axis=1), 1),
     lambda x: onp.take_along_axis(x, onp.argsort(x, axis=1), 1)),
    ("float_power",
     lambda m, x: m.float_power(m.array(onp.abs(x) + 0.5), 2.5),
     lambda x: onp.float_power(onp.abs(x) + 0.5, 2.5)),
    ("remainder",
     lambda m, x: m.remainder(m.array(x), 0.75),
     lambda x: onp.remainder(x, onp.float32(0.75))),
]


@pytest.mark.parametrize("case", EXT_FNS, ids=[c[0] for c in EXT_FNS])
def test_np_extended_surface(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np, name):
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 5), 29)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp_fn(x)
    assert got.shape == onp.asarray(want).shape, \
        f"{name}: shape {got.shape} vs numpy {onp.asarray(want).shape}"
    if onp.asarray(want).dtype.kind == "b":
        assert onp.dtype(got.dtype).kind == "b", \
            f"{name}: bool result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    elif onp.asarray(want).dtype.kind in "iu":
        assert onp.dtype(got.dtype).kind in "iu", \
            f"{name}: integer result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, onp.asarray(want))
    else:
        onp.testing.assert_allclose(
            onp.asarray(got, onp.asarray(want).dtype), want,
            rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# delegated-surface parity extension round 3 (ISSUE 12 satellite): another
# ~34-function slice — array surgery (append/delete/insert/splits),
# selection (compress/extract/select/choose/piecewise), products
# (inner/vdot/convolve/correlate), index constructors, complex-view and
# sign helpers, the nan-aware argmin/argmax/cum family, and predicate
# reducers — again targeting the spots where thin jnp delegation could
# silently diverge (int/bool result dtypes, axis conventions, nan rules).
# ---------------------------------------------------------------------------

EXT_FNS3 = [
    ("append",
     lambda m, x: m.append(m.array(x), m.array(x[:1]), axis=0),
     lambda x: onp.append(x, x[:1], axis=0)),
    ("delete", lambda m, x: m.delete(m.array(x), 2, axis=1),
     lambda x: onp.delete(x, 2, axis=1)),
    ("insert",
     lambda m, x: m.insert(m.array(x), 1, m.array(x[0]), axis=0),
     lambda x: onp.insert(x, 1, x[0], axis=0)),
    ("hsplit", lambda m, x: m.hsplit(m.array(x[:, :4]), 2)[1],
     lambda x: onp.hsplit(x[:, :4], 2)[1]),
    ("vsplit", lambda m, x: m.vsplit(m.array(x), 2)[0],
     lambda x: onp.vsplit(x, 2)[0]),
    ("compress",
     lambda m, x: m.compress(m.array([0, 1, 1, 0]), m.array(x), axis=0),
     lambda x: onp.compress([0, 1, 1, 0], x, axis=0)),
    ("extract",
     lambda m, x: m.extract(m.array(x) > 0, m.array(x)),
     lambda x: onp.extract(x > 0, x)),
    ("select",
     lambda m, x: m.select([m.array(x) > 1, m.array(x) < -1],
                           [m.array(x), -m.array(x)], 0.0),
     lambda x: onp.select([x > 1, x < -1], [x, -x], onp.float32(0.0))),
    ("choose",
     lambda m, x: m.choose(m.array(onp.array([0, 1, 1, 0, 1],
                                             onp.int32)),
                           [m.array(x[0]), m.array(x[1])]),
     lambda x: onp.choose(onp.array([0, 1, 1, 0, 1], onp.int32),
                          [x[0], x[1]])),
    ("piecewise",
     lambda m, x: m.piecewise(m.array(x), [m.array(x) < 0,
                                           m.array(x) >= 0],
                              [lambda v: -v, lambda v: v * 2]),
     lambda x: onp.piecewise(x, [x < 0, x >= 0],
                             [lambda v: -v, lambda v: v * 2])),
    ("trim_zeros",
     lambda m, x: m.trim_zeros(m.array(onp.array([0, 0, 1, 2, 0, 3, 0],
                                                 onp.float32))),
     lambda x: onp.trim_zeros(onp.array([0, 0, 1, 2, 0, 3, 0],
                                        onp.float32))),
    ("inner", lambda m, x: m.inner(m.array(x), m.array(x)),
     lambda x: onp.inner(x, x)),
    ("vdot", lambda m, x: m.vdot(m.array(x), m.array(x)),
     lambda x: onp.vdot(x, x)),
    ("convolve",
     lambda m, x: m.convolve(m.array(x[0]),
                             m.array(onp.array([1.0, 0.5, 0.25],
                                               onp.float32))),
     lambda x: onp.convolve(x[0], onp.array([1.0, 0.5, 0.25],
                                            onp.float32))),
    ("correlate",
     lambda m, x: m.correlate(m.array(x[0]), m.array(x[1]), mode="full"),
     lambda x: onp.correlate(x[0], x[1], mode="full")),
    ("sinc", lambda m, x: m.sinc(m.array(x)), lambda x: onp.sinc(x)),
    ("i0", lambda m, x: m.i0(m.array(x[0])), lambda x: onp.i0(x[0])),
    ("nextafter",
     lambda m, x: m.nextafter(m.array(x), m.array(x + 1.0)),
     lambda x: onp.nextafter(x, x + 1.0)),
    ("tril_indices",
     lambda m, x: m.tril_indices(4, 0, 5)[0],
     lambda x: onp.tril_indices(4, 0, 5)[0]),
    ("triu_indices",
     lambda m, x: m.triu_indices(4, 1, 5)[1],
     lambda x: onp.triu_indices(4, 1, 5)[1]),
    ("diag_indices",
     lambda m, x: m.diag_indices(4)[0],
     lambda x: onp.diag_indices(4)[0]),
    ("diagonal",
     lambda m, x: m.diagonal(m.array(x), offset=1, axis1=0, axis2=1),
     lambda x: onp.diagonal(x, offset=1, axis1=0, axis2=1)),
    ("angle", lambda m, x: m.angle(m.array(x)), lambda x: onp.angle(x)),
    ("real", lambda m, x: m.real(m.array(x)), lambda x: onp.real(x)),
    ("imag", lambda m, x: m.imag(m.array(x)), lambda x: onp.imag(x)),
    ("conj", lambda m, x: m.conj(m.array(x)), lambda x: onp.conj(x)),
    ("positive", lambda m, x: m.positive(m.array(x)),
     lambda x: onp.positive(x)),
    ("negative", lambda m, x: m.negative(m.array(x)),
     lambda x: onp.negative(x)),
    ("around", lambda m, x: m.around(m.array(x * 3), 1),
     lambda x: onp.around(x * 3, 1)),
    ("nancumsum", lambda m, x: m.nancumsum(m.array(_xnan()[:2]), axis=1),
     lambda x: onp.nancumsum(_xnan()[:2], axis=1)),
    ("nanprod", lambda m, x: m.nanprod(m.array(_xnan()[:2]), axis=0),
     lambda x: onp.nanprod(_xnan()[:2], axis=0)),
    ("nanargmax", lambda m, x: m.nanargmax(m.array(_xnan()[:2]), axis=1),
     lambda x: onp.nanargmax(_xnan()[:2], axis=1)),
    ("nanargmin", lambda m, x: m.nanargmin(m.array(_xnan()[:2]), axis=1),
     lambda x: onp.nanargmin(_xnan()[:2], axis=1)),
    ("nanmin", lambda m, x: m.nanmin(m.array(_xnan()[:2]), axis=0),
     lambda x: onp.nanmin(_xnan()[:2], axis=0)),
    ("nanvar", lambda m, x: m.nanvar(m.array(_xnan()[:2]), axis=1),
     lambda x: onp.nanvar(_xnan()[:2], axis=1)),
    ("nanmedian", lambda m, x: m.nanmedian(m.array(_xnan()[:2]), axis=1),
     lambda x: onp.nanmedian(_xnan()[:2], axis=1)),
    ("gradient", lambda m, x: m.gradient(m.array(x), axis=1),
     lambda x: onp.gradient(x, axis=1)),
    ("allclose",
     lambda m, x: m.allclose(m.array(x), m.array(x + 1e-7)),
     lambda x: onp.allclose(x, x + 1e-7)),
    ("array_equal",
     lambda m, x: m.array_equal(m.array(x), m.array(x)),
     lambda x: onp.array_equal(x, x)),
]


@pytest.mark.parametrize("case", EXT_FNS3, ids=[c[0] for c in EXT_FNS3])
def test_np_extended_surface_round3(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np, name):
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 5), 37)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp.asarray(onp_fn(x))
    assert got.shape == want.shape, \
        f"{name}: shape {got.shape} vs numpy {want.shape}"
    if want.dtype.kind == "b":
        assert onp.dtype(got.dtype).kind == "b", \
            f"{name}: bool result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    elif want.dtype.kind in "iu":
        assert onp.dtype(got.dtype).kind in "iu", \
            f"{name}: integer result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    else:
        onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                    rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# delegated-surface parity extension round 4 (ISSUE 13 satellite): another
# ~32-function slice toward the ~250-function namespace — stacking/split
# helpers, integer bitwise/shift ops (int result dtypes asserted), nan/inf
# predicates, angle conversions, histogramming, index-grid constructors
# (indices/ravel_multi_index/unravel_index), *_like constructors, the
# predicate-reduction aliases (all/any/amax/amin), and take/rollaxis/
# broadcast_arrays — again the thin-jnp-delegation spots where axis
# conventions and result dtypes could silently diverge.
# ---------------------------------------------------------------------------

def _xi():
    return onp.array([[5, 3, 12, 6, 9], [2, 7, 1, 8, 4]], onp.int32)


EXT_FNS4 = [
    ("absolute", lambda m, x: m.absolute(m.array(x)),
     lambda x: onp.absolute(x)),
    ("all", lambda m, x: m.all(m.array(x) > -100, axis=0),
     lambda x: onp.all(x > -100, axis=0)),
    ("any", lambda m, x: m.any(m.array(x) > 1, axis=1),
     lambda x: onp.any(x > 1, axis=1)),
    ("amax", lambda m, x: m.amax(m.array(x), axis=1),
     lambda x: onp.amax(x, axis=1)),
    ("amin", lambda m, x: m.amin(m.array(x), axis=0),
     lambda x: onp.amin(x, axis=0)),
    ("atleast_1d", lambda m, x: m.atleast_1d(m.array(x[0, 0])),
     lambda x: onp.atleast_1d(onp.float32(x[0, 0]))),
    ("atleast_3d", lambda m, x: m.atleast_3d(m.array(x)),
     lambda x: onp.atleast_3d(x)),
    ("bitwise_and", lambda m, x: m.bitwise_and(m.array(_xi()),
                                               m.array(_xi() + 1)),
     lambda x: onp.bitwise_and(_xi(), _xi() + 1)),
    ("bitwise_or", lambda m, x: m.bitwise_or(m.array(_xi()),
                                             m.array(_xi() + 1)),
     lambda x: onp.bitwise_or(_xi(), _xi() + 1)),
    ("bitwise_xor", lambda m, x: m.bitwise_xor(m.array(_xi()),
                                               m.array(_xi() + 1)),
     lambda x: onp.bitwise_xor(_xi(), _xi() + 1)),
    ("invert", lambda m, x: m.invert(m.array(_xi())),
     lambda x: onp.invert(_xi())),
    ("left_shift", lambda m, x: m.left_shift(m.array(_xi()), 2),
     lambda x: onp.left_shift(_xi(), 2)),
    ("right_shift", lambda m, x: m.right_shift(m.array(_xi()), 1),
     lambda x: onp.right_shift(_xi(), 1)),
    ("broadcast_arrays",
     lambda m, x: m.broadcast_arrays(m.array(x[:1]), m.array(x))[0],
     lambda x: onp.broadcast_arrays(x[:1], x)[0]),
    ("conjugate", lambda m, x: m.conjugate(m.array(x)),
     lambda x: onp.conjugate(x)),
    ("copy", lambda m, x: m.copy(m.array(x)), lambda x: onp.copy(x)),
    ("deg2rad", lambda m, x: m.deg2rad(m.array(x * 90)),
     lambda x: onp.deg2rad(x * 90)),
    ("rad2deg", lambda m, x: m.rad2deg(m.array(x)),
     lambda x: onp.rad2deg(x)),
    ("dsplit", lambda m, x: m.dsplit(m.array(x.reshape(2, 5, 2)), 2)[1],
     lambda x: onp.dsplit(x.reshape(2, 5, 2), 2)[1]),
    ("fix", lambda m, x: m.fix(m.array(x * 3)),
     lambda x: onp.fix(x * 3)),
    ("full_like", lambda m, x: m.full_like(m.array(x), 2.5),
     lambda x: onp.full_like(x, 2.5)),
    ("ones_like", lambda m, x: m.ones_like(m.array(_xi())),
     lambda x: onp.ones_like(_xi())),
    ("histogram",
     lambda m, x: m.histogram(m.array(x), bins=5,
                              range=(-3.0, 3.0))[0],
     lambda x: onp.histogram(x, bins=5, range=(-3.0, 3.0))[0]),
    ("hstack",
     lambda m, x: m.hstack((m.array(x), m.array(x[:, :2]))),
     lambda x: onp.hstack((x, x[:, :2]))),
    ("vstack",
     lambda m, x: m.vstack((m.array(x), m.array(x[:1]))),
     lambda x: onp.vstack((x, x[:1]))),
    ("indices", lambda m, x: m.indices((3, 4))[1],
     lambda x: onp.indices((3, 4))[1]),
    ("ravel_multi_index",
     lambda m, x: m.ravel_multi_index(
         (m.array(onp.array([0, 1, 2], onp.int32)),
          m.array(onp.array([3, 0, 4], onp.int32))), (4, 5)),
     lambda x: onp.ravel_multi_index(
         (onp.array([0, 1, 2]), onp.array([3, 0, 4])), (4, 5))),
    ("unravel_index",
     lambda m, x: m.unravel_index(
         m.array(onp.array([5, 11, 19], onp.int32)), (4, 5))[1],
     lambda x: onp.unravel_index(onp.array([5, 11, 19]), (4, 5))[1]),
    ("iscomplex", lambda m, x: m.iscomplex(m.array(x)),
     lambda x: onp.iscomplex(x)),
    ("isreal", lambda m, x: m.isreal(m.array(x)),
     lambda x: onp.isreal(x)),
    ("isneginf",
     lambda m, x: m.isneginf(m.array(
         onp.array([-onp.inf, 1.0, onp.inf], onp.float32))),
     lambda x: onp.isneginf(onp.array([-onp.inf, 1.0, onp.inf],
                                      onp.float32))),
    ("isposinf",
     lambda m, x: m.isposinf(m.array(
         onp.array([-onp.inf, 1.0, onp.inf], onp.float32))),
     lambda x: onp.isposinf(onp.array([-onp.inf, 1.0, onp.inf],
                                      onp.float32))),
    ("logaddexp2",
     lambda m, x: m.logaddexp2(m.array(x), m.array(x + 1.0)),
     lambda x: onp.logaddexp2(x, x + 1.0)),
    ("nancumprod",
     lambda m, x: m.nancumprod(m.array(_xnan()[:2] * 0.5), axis=1),
     lambda x: onp.nancumprod(_xnan()[:2] * 0.5, axis=1)),
    ("rollaxis", lambda m, x: m.rollaxis(m.array(x), 1, 0),
     lambda x: onp.rollaxis(x, 1, 0)),
    ("take",
     lambda m, x: m.take(m.array(x),
                         m.array(onp.array([3, 0, 2], onp.int32)),
                         axis=1),
     lambda x: onp.take(x, onp.array([3, 0, 2]), axis=1)),
]


@pytest.mark.parametrize("case", EXT_FNS4, ids=[c[0] for c in EXT_FNS4])
def test_np_extended_surface_round4(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np, name):
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 5), 41)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp.asarray(onp_fn(x))
    assert got.shape == want.shape, \
        f"{name}: shape {got.shape} vs numpy {want.shape}"
    if want.dtype.kind == "b":
        assert onp.dtype(got.dtype).kind == "b", \
            f"{name}: bool result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    elif want.dtype.kind in "iu":
        assert onp.dtype(got.dtype).kind in "iu", \
            f"{name}: integer result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    else:
        onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                    rtol=2e-5, atol=2e-6)


def test_np_dtype_introspection_helpers():
    """result_type / promote_types / can_cast answer with the x64-less
    lattice where it AGREES with numpy (the divergent int32+f32 case is
    pinned by test_np_dtype_promotion)."""
    assert onp.dtype(np.result_type("float32", "float32")) == onp.float32
    assert onp.dtype(np.result_type("int32", "int8")) == onp.int32
    assert onp.dtype(np.promote_types("float32", "float64")) == onp.float64
    assert bool(np.can_cast("int32", "int64"))
    assert not bool(np.can_cast("float64", "int32"))


def test_npx_set_np_toggles():
    mx.npx.set_np()
    try:
        from mxnet_tpu.util import is_np_array
        assert is_np_array()
    finally:
        mx.npx.reset_np()
    from mxnet_tpu.util import is_np_array
    assert not is_np_array()


# ---------------------------------------------------------------------------
# delegated-surface parity extension round 5 (ISSUE 14 satellite): the
# ~38-function slice that closes most of the remaining shared-name gap —
# the comparison ufuncs (bool result dtypes asserted), the reduction
# core (sum/mean/prod/std/var/max/min + arg/cum forms with negative
# axes), constructors (arange/full/identity/ones/zeros/*_like incl.
# value+dtype), binary float helpers (copysign/hypot/logaddexp/
# true_divide), histogram2d, trapezoid integration, and the ndim/shape/
# size introspection helpers — again the thin-jnp-delegation spots where
# result dtypes and axis conventions could silently diverge.
# ---------------------------------------------------------------------------

EXT_FNS5 = [
    ("arange", lambda m, x: m.arange(2.0, 8.0, 1.5),
     lambda x: onp.arange(2.0, 8.0, 1.5)),
    ("arccosh", lambda m, x: m.arccosh(m.array(onp.abs(x) + 1.5)),
     lambda x: onp.arccosh(onp.abs(x) + 1.5)),
    ("argmax", lambda m, x: m.argmax(m.array(x), axis=-1),
     lambda x: onp.argmax(x, axis=-1)),
    ("argmin", lambda m, x: m.argmin(m.array(x), axis=0),
     lambda x: onp.argmin(x, axis=0)),
    ("array", lambda m, x: m.array(x), lambda x: onp.array(x)),
    ("asarray", lambda m, x: m.asarray(x), lambda x: onp.asarray(x)),
    ("ascontiguousarray", lambda m, x: m.ascontiguousarray(m.array(x).T),
     lambda x: onp.ascontiguousarray(x.T)),
    ("bitwise_not", lambda m, x: m.bitwise_not(m.array(_xi())),
     lambda x: onp.bitwise_not(_xi())),
    ("copysign", lambda m, x: m.copysign(m.array(x), m.array(-x)),
     lambda x: onp.copysign(x, -x)),
    ("cumprod", lambda m, x: m.cumprod(m.array(x * 0.5), axis=1),
     lambda x: onp.cumprod(x * 0.5, axis=1)),
    ("cumsum", lambda m, x: m.cumsum(m.array(x), axis=-1),
     lambda x: onp.cumsum(x, axis=-1)),
    ("equal", lambda m, x: m.equal(m.array(_xi()), m.array(_xi())),
     lambda x: onp.equal(_xi(), _xi())),
    ("not_equal",
     lambda m, x: m.not_equal(m.array(_xi()), m.array(_xi() * 0 + 5)),
     lambda x: onp.not_equal(_xi(), _xi() * 0 + 5)),
    ("greater", lambda m, x: m.greater(m.array(x), 0.0),
     lambda x: onp.greater(x, 0.0)),
    ("greater_equal",
     lambda m, x: m.greater_equal(m.array(x), m.array(x[:1])),
     lambda x: onp.greater_equal(x, x[:1])),
    ("less", lambda m, x: m.less(m.array(x), 0.5),
     lambda x: onp.less(x, 0.5)),
    ("less_equal", lambda m, x: m.less_equal(m.array(x), m.array(x)),
     lambda x: onp.less_equal(x, x)),
    ("full", lambda m, x: m.full((3, 4), 2.5),
     lambda x: onp.full((3, 4), 2.5)),
    ("histogram2d",
     lambda m, x: m.histogram2d(
         m.array(x.ravel()), m.array((x * 2).ravel()), bins=4,
         range=((-3.0, 3.0), (-6.0, 6.0)))[0],
     lambda x: onp.histogram2d(
         x.ravel(), (x * 2).ravel(), bins=4,
         range=((-3.0, 3.0), (-6.0, 6.0)))[0]),
    ("hypot", lambda m, x: m.hypot(m.array(x), m.array(x + 1.0)),
     lambda x: onp.hypot(x, x + 1.0)),
    ("identity", lambda m, x: m.identity(5),
     lambda x: onp.identity(5, dtype=onp.float32)),
    ("logaddexp",
     lambda m, x: m.logaddexp(m.array(x), m.array(x - 1.0)),
     lambda x: onp.logaddexp(x, x - 1.0)),
    ("max", lambda m, x: m.max(m.array(x), axis=1),
     lambda x: onp.max(x, axis=1)),
    ("min", lambda m, x: m.min(m.array(x), axis=-1, keepdims=True),
     lambda x: onp.min(x, axis=-1, keepdims=True)),
    ("mean", lambda m, x: m.mean(m.array(x), axis=0),
     lambda x: onp.mean(x, axis=0)),
    ("sum", lambda m, x: m.sum(m.array(x), axis=(0, 1)),
     lambda x: onp.sum(x, axis=(0, 1))),
    ("prod", lambda m, x: m.prod(m.array(x * 0.5 + 1.0), axis=1),
     lambda x: onp.prod(x * 0.5 + 1.0, axis=1)),
    ("std", lambda m, x: m.std(m.array(x), axis=1),
     lambda x: onp.std(x, axis=1)),
    ("var", lambda m, x: m.var(m.array(x), axis=0),
     lambda x: onp.var(x, axis=0)),
    ("ndim", lambda m, x: onp.int64(m.ndim(m.array(x))),
     lambda x: onp.int64(onp.ndim(x))),
    ("shape", lambda m, x: onp.array(m.shape(m.array(x))),
     lambda x: onp.array(onp.shape(x))),
    ("size", lambda m, x: onp.int64(m.size(m.array(x))),
     lambda x: onp.int64(onp.size(x))),
    ("ones", lambda m, x: m.ones((2, 3)),
     lambda x: onp.ones((2, 3), onp.float32)),
    ("zeros", lambda m, x: m.zeros((2, 3)),
     lambda x: onp.zeros((2, 3), onp.float32)),
    ("zeros_like", lambda m, x: m.zeros_like(m.array(_xi())),
     lambda x: onp.zeros_like(_xi())),
    ("round", lambda m, x: m.round(m.array(x * 3), 1),
     lambda x: onp.round(x * 3, 1)),
    ("true_divide",
     lambda m, x: m.true_divide(m.array(_xi()), m.array(_xi() + 1)),
     lambda x: onp.true_divide(_xi(), _xi() + 1)),
    ("trapezoid",
     lambda m, x: m.trapezoid(m.array(x), dx=0.5, axis=1),
     lambda x: getattr(onp, "trapezoid", getattr(onp, "trapz", None))(
         x, dx=0.5, axis=1)),
]


# ---------------------------------------------------------------------------
# delegated-surface parity extension round 6 (ISSUE 15 satellite): the
# ~50-function slice that closes the set-operation / window-function /
# polynomial / bit-packing families plus the numpy-2 array-API aliases
# (concat, permute_dims, matrix_transpose, vecdot) and the functional
# constructors (fromfunction, apply_along_axis/over_axes) — thin jnp
# delegation where result dtypes (bool/int asserts below), tuple-of-array
# returns (divmod/frexp/modf/ix_/indices-from), python-scalar returns
# (isscalar, broadcast_shapes) and CALLBACK arguments (mask_indices takes
# a mask_func — the delegated mx.np.triu returning NDArray into jnp was
# this round's delegation catch, now unwrapped host-side) could silently
# diverge.
# ---------------------------------------------------------------------------

EXT_FNS6 = [
    ("apply_along_axis",
     lambda m, x: m.apply_along_axis(lambda v: v.sum(), 1, m.array(x)),
     lambda x: onp.apply_along_axis(lambda v: v.sum(), 1, x)),
    ("apply_over_axes",
     lambda m, x: m.apply_over_axes(
         lambda a, ax: a.sum(ax, keepdims=True), m.array(x), [0]),
     lambda x: onp.apply_over_axes(
         lambda a, ax: a.sum(ax, keepdims=True), x, [0])),
    ("argpartition",
     lambda m, x: m.sort(m.argpartition(m.array(x[0]), 2)[:3]),
     lambda x: onp.sort(onp.argpartition(x[0], 2)[:3])),
    ("array_equiv", lambda m, x: m.array_equiv(m.array(x), m.array(x)),
     lambda x: onp.array_equiv(x, x)),
    ("bartlett", lambda m, x: m.bartlett(7), lambda x: onp.bartlett(7)),
    ("blackman", lambda m, x: m.blackman(7), lambda x: onp.blackman(7)),
    ("hamming", lambda m, x: m.hamming(7), lambda x: onp.hamming(7)),
    ("hanning", lambda m, x: m.hanning(7), lambda x: onp.hanning(7)),
    ("kaiser", lambda m, x: m.kaiser(7, 8.6),
     lambda x: onp.kaiser(7, 8.6)),
    ("broadcast_shapes",
     lambda m, x: onp.array(m.broadcast_shapes((3, 1), (1, 4))),
     lambda x: onp.array(onp.broadcast_shapes((3, 1), (1, 4)))),
    ("concat", lambda m, x: m.concat([m.array(x), m.array(x)]),
     lambda x: onp.concatenate([x, x])),
    ("diagflat", lambda m, x: m.diagflat(m.array(x[0, :3])),
     lambda x: onp.diagflat(x[0, :3])),
    ("diag_indices_from",
     lambda m, x: m.diag_indices_from(m.array(x[:4, :4]))[0],
     lambda x: onp.diag_indices_from(x[:4, :4])[0]),
    ("divmod", lambda m, x: m.divmod(m.array(_xi()), 3)[1],
     lambda x: onp.divmod(_xi(), 3)[1]),
    ("frexp", lambda m, x: m.frexp(m.array(x))[0],
     lambda x: onp.frexp(x)[0]),
    ("fromfunction",
     lambda m, x: m.fromfunction(lambda i, j: i + j, (3, 3)),
     lambda x: onp.fromfunction(lambda i, j: i + j, (3, 3))),
    ("geomspace", lambda m, x: m.geomspace(1.0, 64.0, 7),
     lambda x: onp.geomspace(1.0, 64.0, 7)),
    ("histogram_bin_edges",
     lambda m, x: m.histogram_bin_edges(m.array(x.ravel()), bins=5),
     lambda x: onp.histogram_bin_edges(x.ravel(), bins=5)),
    ("histogramdd",
     lambda m, x: m.histogramdd(m.array(x[:, :2]), bins=3)[0],
     lambda x: onp.histogramdd(x[:, :2], bins=3)[0]),
    ("intersect1d",
     lambda m, x: m.intersect1d(m.array(_xi().ravel()),
                                m.array(_xi().ravel()[:5])),
     lambda x: onp.intersect1d(_xi().ravel(), _xi().ravel()[:5])),
    ("isin",
     lambda m, x: m.isin(m.array(_xi()),
                         m.array(onp.array([1, 2], onp.int32))),
     lambda x: onp.isin(_xi(), onp.array([1, 2]))),
    ("iscomplexobj", lambda m, x: m.iscomplexobj(m.array(x)),
     lambda x: onp.iscomplexobj(x)),
    ("isrealobj", lambda m, x: m.isrealobj(m.array(x)),
     lambda x: onp.isrealobj(x)),
    ("isscalar", lambda m, x: m.isscalar(3.0),
     lambda x: onp.isscalar(3.0)),
    ("ix_",
     lambda m, x: m.ix_(m.array(onp.array([0, 2])),
                        m.array(onp.array([1, 3])))[0],
     lambda x: onp.ix_(onp.array([0, 2]), onp.array([1, 3]))[0]),
    ("lexsort", lambda m, x: m.lexsort((m.array(x[0]), m.array(x[1]))),
     lambda x: onp.lexsort((x[0], x[1]))),
    ("mask_indices", lambda m, x: m.mask_indices(3, m.triu)[0],
     lambda x: onp.mask_indices(3, onp.triu)[0]),
    ("matrix_transpose", lambda m, x: m.matrix_transpose(m.array(x)),
     lambda x: onp.swapaxes(x, -1, -2)),
    ("modf", lambda m, x: m.modf(m.array(x))[0],
     lambda x: onp.modf(x)[0]),
    ("nanpercentile", lambda m, x: m.nanpercentile(m.array(x), 40.0),
     lambda x: onp.nanpercentile(x, 40.0)),
    ("nanquantile", lambda m, x: m.nanquantile(m.array(x), 0.4),
     lambda x: onp.nanquantile(x, 0.4)),
    ("packbits",
     lambda m, x: m.packbits(m.array((_xi() % 2).astype(onp.uint8))),
     lambda x: onp.packbits((_xi() % 2).astype(onp.uint8))),
    ("unpackbits",
     lambda m, x: m.unpackbits(m.array(onp.array([7, 200], onp.uint8))),
     lambda x: onp.unpackbits(onp.array([7, 200], onp.uint8))),
    ("partition", lambda m, x: m.partition(m.array(x[0]), 2)[2],
     lambda x: onp.partition(x[0], 2)[2]),
    ("permute_dims", lambda m, x: m.permute_dims(m.array(x), (1, 0)),
     lambda x: onp.transpose(x, (1, 0))),
    ("polyadd",
     lambda m, x: m.polyadd(m.array(x[0, :3]), m.array(x[1, :3])),
     lambda x: onp.polyadd(x[0, :3], x[1, :3])),
    ("polyder", lambda m, x: m.polyder(m.array(x[0, :4])),
     lambda x: onp.polyder(x[0, :4])),
    ("polyint", lambda m, x: m.polyint(m.array(x[0, :4])),
     lambda x: onp.polyint(x[0, :4])),
    ("polymul",
     lambda m, x: m.polymul(m.array(x[0, :3]), m.array(x[1, :3])),
     lambda x: onp.polymul(x[0, :3], x[1, :3])),
    ("polysub",
     lambda m, x: m.polysub(m.array(x[0, :3]), m.array(x[1, :3])),
     lambda x: onp.polysub(x[0, :3], x[1, :3])),
    ("polyval",
     lambda m, x: m.polyval(m.array(x[0, :3]), m.array(x[1])),
     lambda x: onp.polyval(x[0, :3], x[1])),
    ("resize", lambda m, x: m.resize(m.array(x), (2, 3)),
     lambda x: onp.resize(x, (2, 3))),
    ("setdiff1d",
     lambda m, x: m.setdiff1d(m.array(_xi().ravel()),
                              m.array(onp.array([0, 1], onp.int32))),
     lambda x: onp.setdiff1d(_xi().ravel(), onp.array([0, 1]))),
    ("setxor1d",
     lambda m, x: m.setxor1d(m.array(onp.array([1, 2, 3])),
                             m.array(onp.array([2, 3, 4]))),
     lambda x: onp.setxor1d(onp.array([1, 2, 3]),
                            onp.array([2, 3, 4]))),
    ("sort_complex",
     lambda m, x: m.sort_complex(m.array(onp.array([3.0, 1.0, 2.0]))),
     lambda x: onp.sort_complex(onp.array([3.0, 1.0, 2.0]))),
    ("spacing", lambda m, x: m.spacing(m.array(x)),
     lambda x: onp.spacing(x)),
    ("tril_indices_from",
     lambda m, x: m.tril_indices_from(m.array(x[:4, :4]))[0],
     lambda x: onp.tril_indices_from(x[:4, :4])[0]),
    ("triu_indices_from",
     lambda m, x: m.triu_indices_from(m.array(x[:4, :4]))[1],
     lambda x: onp.triu_indices_from(x[:4, :4])[1]),
    ("union1d",
     lambda m, x: m.union1d(m.array(onp.array([1, 2, 3])),
                            m.array(onp.array([2, 5]))),
     lambda x: onp.union1d(onp.array([1, 2, 3]), onp.array([2, 5]))),
    ("unwrap", lambda m, x: m.unwrap(m.array(x[0] * 3)),
     lambda x: onp.unwrap(x[0] * 3)),
    ("vander", lambda m, x: m.vander(m.array(x[0, :3]), 3),
     lambda x: onp.vander(x[0, :3], 3)),
    ("vecdot", lambda m, x: m.vecdot(m.array(x), m.array(x)),
     lambda x: (x * x).sum(-1)),
]


@pytest.mark.parametrize("case", EXT_FNS6, ids=[c[0] for c in EXT_FNS6])
def test_np_extended_surface_round6(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np, name):
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 5), 61)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp.asarray(onp_fn(x))
    assert got.shape == want.shape, \
        f"{name}: shape {got.shape} vs numpy {want.shape}"
    if want.dtype.kind == "b":
        assert onp.dtype(got.dtype).kind == "b", \
            f"{name}: bool result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    elif want.dtype.kind in "iu":
        assert onp.dtype(got.dtype).kind in "iu", \
            f"{name}: integer result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    else:
        onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                    rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("case", EXT_FNS5, ids=[c[0] for c in EXT_FNS5])
def test_np_extended_surface_round5(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np, name):
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 5), 51)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp.asarray(onp_fn(x))
    assert got.shape == want.shape, \
        f"{name}: shape {got.shape} vs numpy {want.shape}"
    if want.dtype.kind == "b":
        assert onp.dtype(got.dtype).kind == "b", \
            f"{name}: bool result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    elif want.dtype.kind in "iu":
        assert onp.dtype(got.dtype).kind in "iu", \
            f"{name}: integer result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    else:
        onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                    rtol=2e-5, atol=2e-6)


# -- round 7 (ISSUE 16): array-API aliases, polynomial solvers, unique_*
# quartet, popcount/shift family, block assembly, and the put/place/
# fill_diagonal copy-returning shims (documented divergence: jax arrays
# are immutable, numpy mutates in place).

def _put_ref(x):
    y = x.copy()
    onp.put(y, [0, 2], [9.0, 8.0])
    return y


def _place_ref(x):
    y = x.copy()
    onp.place(y, x > 0, [5.0])
    return y


def _fill_diag_ref(x):
    y = x[:4, :4].copy()
    onp.fill_diagonal(y, 7.0)
    return y


def _popcount_ref(x):
    xi = _xi()
    return onp.array([[bin(int(v)).count("1") for v in row] for row in xi],
                     onp.int32)


EXT_FNS7 = [
    ("acos", lambda m, x: m.acos(m.array(onp.tanh(x))),
     lambda x: onp.arccos(onp.tanh(x))),
    ("acosh", lambda m, x: m.acosh(m.array(1.0 + x * x)),
     lambda x: onp.arccosh(1.0 + x * x)),
    ("asin", lambda m, x: m.asin(m.array(onp.tanh(x))),
     lambda x: onp.arcsin(onp.tanh(x))),
    ("asinh", lambda m, x: m.asinh(m.array(x)),
     lambda x: onp.arcsinh(x)),
    ("atan", lambda m, x: m.atan(m.array(x)), lambda x: onp.arctan(x)),
    ("atan2", lambda m, x: m.atan2(m.array(x), m.array(x + 1.5)),
     lambda x: onp.arctan2(x, x + 1.5)),
    ("atanh", lambda m, x: m.atanh(m.array(onp.tanh(x) * 0.9)),
     lambda x: onp.arctanh(onp.tanh(x) * 0.9)),
    ("pow", lambda m, x: m.pow(m.array(onp.abs(x) + 0.5), 2),
     lambda x: onp.power(onp.abs(x) + 0.5, 2)),
    ("bitwise_count", lambda m, x: m.bitwise_count(m.array(_xi())),
     _popcount_ref),
    ("bitwise_invert", lambda m, x: m.bitwise_invert(m.array(_xi())),
     lambda x: onp.invert(_xi())),
    ("bitwise_left_shift",
     lambda m, x: m.bitwise_left_shift(m.array(_xi()), 2),
     lambda x: onp.left_shift(_xi(), 2)),
    ("bitwise_right_shift",
     lambda m, x: m.bitwise_right_shift(m.array(_xi()), 1),
     lambda x: onp.right_shift(_xi(), 1)),
    ("block", lambda m, x: m.block([[m.array(x)], [m.array(x)]]),
     lambda x: onp.block([[x], [x]])),
    ("cumulative_sum",
     lambda m, x: m.cumulative_sum(m.array(x), axis=1),
     lambda x: onp.cumsum(x, axis=1)),
    ("cumulative_prod",
     lambda m, x: m.cumulative_prod(m.array(x), axis=1),
     lambda x: onp.cumprod(x, axis=1)),
    ("astype", lambda m, x: m.astype(m.array(x * 10), "int32"),
     lambda x: (x * 10).astype(onp.int32)),
    ("fmod", lambda m, x: m.fmod(m.array(_xi()), 3),
     lambda x: onp.fmod(_xi(), 3)),
    ("isdtype",
     lambda m, x: onp.array(m.isdtype(onp.dtype("float32"),
                                      "real floating")),
     lambda x: onp.array(True)),
    ("poly", lambda m, x: m.poly(m.array(x[0, :3])),
     lambda x: onp.poly(x[0, :3])),
    ("polydiv",
     lambda m, x: m.polydiv(m.array(onp.array([1.0, 3.0, 2.0])),
                            m.array(onp.array([1.0, 1.0])))[0],
     lambda x: onp.polydiv(onp.array([1.0, 3.0, 2.0]),
                           onp.array([1.0, 1.0]))[0]),
    ("polyfit",
     lambda m, x: m.polyfit(m.array(onp.arange(5.0)), m.array(x[1]), 1),
     lambda x: onp.polyfit(onp.arange(5.0), x[1], 1)),
    ("roots",
     lambda m, x: m.sort(m.abs(m.roots(
         m.array(onp.array([1.0, -3.0, 2.0]))))),
     lambda x: onp.sort(onp.abs(onp.roots(onp.array([1.0, -3.0, 2.0]))))),
    ("unique_all", lambda m, x: m.unique_all(m.array(_xi()))[0],
     lambda x: onp.unique(_xi())),
    ("unique_counts", lambda m, x: m.unique_counts(m.array(_xi()))[1],
     lambda x: onp.unique(_xi(), return_counts=True)[1]),
    ("unique_inverse", lambda m, x: m.unique_inverse(m.array(_xi()))[1],
     lambda x: onp.unique(_xi(), return_inverse=True)[1].reshape(
         _xi().shape)),
    ("unique_values", lambda m, x: m.unique_values(m.array(_xi())),
     lambda x: onp.unique(_xi())),
    ("unstack", lambda m, x: m.unstack(m.array(x))[1],
     lambda x: x[1]),
    ("put",
     lambda m, x: m.put(m.array(x), m.array(onp.array([0, 2])),
                        m.array(onp.array([9.0, 8.0], onp.float32))),
     _put_ref),
    ("place",
     lambda m, x: m.place(m.array(x), m.array(x > 0),
                          m.array(onp.array([5.0], onp.float32))),
     _place_ref),
    ("fill_diagonal",
     lambda m, x: m.fill_diagonal(m.array(x[:4, :4]), 7.0),
     _fill_diag_ref),
]


@pytest.mark.parametrize("case", EXT_FNS7, ids=[c[0] for c in EXT_FNS7])
def test_np_extended_surface_round7(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np, name):
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 5), 71)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp.asarray(onp_fn(x))
    assert got.shape == want.shape, \
        f"{name}: shape {got.shape} vs numpy {want.shape}"
    if want.dtype.kind == "b":
        assert onp.dtype(got.dtype).kind == "b", \
            f"{name}: bool result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    elif want.dtype.kind in "iu":
        assert onp.dtype(got.dtype).kind in "iu", \
            f"{name}: integer result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    else:
        onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                    rtol=2e-5, atol=2e-6)


# -- round 8 (ISSUE 19): the np.fft subnamespace, the remaining linalg
# array-API members (diagonal/matrix_transpose/tensordot/vecdot), the
# host-data constructors (frombuffer/fromiter), vectorize, and the
# host-returning helpers (array_repr/array_str/einsum_path/issubdtype/
# iterable).  Dotted names resolve through subnamespaces; fft cases
# compare magnitudes so the complex64-vs-complex128 width difference
# stays inside the float tolerance.

def _np_attr(m, dotted):
    for part in dotted.split("."):
        if not hasattr(m, part):
            return None
        m = getattr(m, part)
    return m


EXT_FNS8 = [
    ("fft.fft", lambda m, x: m.abs(m.fft.fft(m.array(x), axis=1)),
     lambda x: onp.abs(onp.fft.fft(x, axis=1))),
    ("fft.ifft", lambda m, x: m.abs(m.fft.ifft(m.array(x), axis=1)),
     lambda x: onp.abs(onp.fft.ifft(x, axis=1))),
    ("fft.rfft", lambda m, x: m.abs(m.fft.rfft(m.array(x), axis=1)),
     lambda x: onp.abs(onp.fft.rfft(x, axis=1))),
    ("fft.irfft", lambda m, x: m.fft.irfft(m.array(x), axis=1),
     lambda x: onp.fft.irfft(x, axis=1)),
    ("fft.fft2", lambda m, x: m.abs(m.fft.fft2(m.array(x))),
     lambda x: onp.abs(onp.fft.fft2(x))),
    ("fft.ifft2", lambda m, x: m.abs(m.fft.ifft2(m.array(x))),
     lambda x: onp.abs(onp.fft.ifft2(x))),
    ("fft.fftn", lambda m, x: m.abs(m.fft.fftn(m.array(x))),
     lambda x: onp.abs(onp.fft.fftn(x))),
    ("fft.ifftn", lambda m, x: m.abs(m.fft.ifftn(m.array(x))),
     lambda x: onp.abs(onp.fft.ifftn(x))),
    ("fft.rfft2", lambda m, x: m.abs(m.fft.rfft2(m.array(x))),
     lambda x: onp.abs(onp.fft.rfft2(x))),
    ("fft.irfft2", lambda m, x: m.fft.irfft2(m.array(x)),
     lambda x: onp.fft.irfft2(x)),
    ("fft.rfftn", lambda m, x: m.abs(m.fft.rfftn(m.array(x))),
     lambda x: onp.abs(onp.fft.rfftn(x))),
    ("fft.irfftn", lambda m, x: m.fft.irfftn(m.array(x)),
     lambda x: onp.fft.irfftn(x)),
    ("fft.hfft", lambda m, x: m.fft.hfft(m.array(x), axis=1),
     lambda x: onp.fft.hfft(x, axis=1)),
    ("fft.ihfft", lambda m, x: m.abs(m.fft.ihfft(m.array(x), axis=1)),
     lambda x: onp.abs(onp.fft.ihfft(x, axis=1))),
    ("fft.fftfreq", lambda m, x: m.fft.fftfreq(8, d=0.5),
     lambda x: onp.fft.fftfreq(8, d=0.5)),
    ("fft.rfftfreq", lambda m, x: m.fft.rfftfreq(8, d=0.5),
     lambda x: onp.fft.rfftfreq(8, d=0.5)),
    ("fft.fftshift", lambda m, x: m.fft.fftshift(m.array(x), axes=1),
     lambda x: onp.fft.fftshift(x, axes=1)),
    ("fft.ifftshift", lambda m, x: m.fft.ifftshift(m.array(x), axes=1),
     lambda x: onp.fft.ifftshift(x, axes=1)),
    ("linalg.diagonal",
     lambda m, x: m.linalg.diagonal(m.array(x[:4, :4])),
     lambda x: onp.linalg.diagonal(x[:4, :4])),
    ("linalg.matrix_transpose",
     lambda m, x: m.linalg.matrix_transpose(m.array(x)),
     lambda x: x.T),
    ("linalg.tensordot",
     lambda m, x: m.linalg.tensordot(m.array(x), m.array(x.T), axes=1),
     lambda x: onp.tensordot(x, x.T, axes=1)),
    ("linalg.vecdot",
     lambda m, x: m.linalg.vecdot(m.array(x), m.array(x + 1.0)),
     lambda x: onp.einsum("ij,ij->i", x, x + 1.0)),
    ("frombuffer",
     lambda m, x: m.frombuffer(x.tobytes(), dtype="float32"),
     lambda x: onp.frombuffer(x.tobytes(), dtype=onp.float32)),
    ("fromiter",
     lambda m, x: m.fromiter((float(i) for i in range(6)),
                             dtype="float32", count=6),
     lambda x: onp.fromiter((float(i) for i in range(6)),
                            dtype=onp.float32, count=6)),
    ("vectorize",
     lambda m, x: m.vectorize(lambda a, b: a * b + 1.0)(
         m.array(x), m.array(x)),
     lambda x: x * x + 1.0),
]


@pytest.mark.parametrize("case", EXT_FNS8, ids=[c[0] for c in EXT_FNS8])
def test_np_extended_surface_round8(case):
    name, mx_fn, onp_fn = case
    if _np_attr(np, name) is None:
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 5), 81)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp.asarray(onp_fn(x))
    assert got.shape == want.shape, \
        f"{name}: shape {got.shape} vs numpy {want.shape}"
    if want.dtype.kind == "b":
        assert onp.dtype(got.dtype).kind == "b", \
            f"{name}: bool result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    elif want.dtype.kind in "iu":
        assert onp.dtype(got.dtype).kind in "iu", \
            f"{name}: integer result came back as {got.dtype}"
        onp.testing.assert_array_equal(got, want)
    else:
        onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                    rtol=2e-4, atol=2e-5)


def test_np_round8_host_helpers():
    """The string/bool-returning helpers stay host-side: they take an
    NDArray and hand back plain python values, never op outputs."""
    a = np.array(onp.arange(4.0, dtype=onp.float32))
    r = np.array_repr(a)
    s = np.array_str(a)
    assert isinstance(r, str) and "3." in r
    assert isinstance(s, str) and "3." in s
    assert np.iterable(a) is True
    assert np.iterable(3.0) is False
    assert np.issubdtype(onp.float32, onp.floating)
    assert not np.issubdtype(onp.int32, onp.floating)
    # (jnp's path omits numpy's "einsum_path" header element and hands
    # back opt_einsum's PathInfo object where numpy prints a string — the
    # contraction report lives in its str())
    path, info = np.einsum_path("ij,jk->ik", a.reshape(2, 2),
                                a.reshape(2, 2))
    assert isinstance(path, list)
    assert "Complete contraction" in str(info)
