"""Broad table-driven mx.np ↔ numpy parity sweep (reference
test_numpy_op.py's per-op coverage style, P3/N7 numpy families).

Each case runs the mx.np function and the same-named numpy function on
identical inputs and asserts elementwise agreement — ~90 functions across
unary/binary/reduction/shape/linalg families, plus np.random statistical
checks and npx.set_np semantics."""

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

np = mx.np


def _r(shape, seed=0, positive=False, small=False):
    r = onp.random.RandomState(seed)
    x = r.randn(*shape).astype(onp.float32)
    if positive:
        x = onp.abs(x) + 0.1
    if small:
        x = x * 0.4
    return x


UNARY = [
    ("exp", {}), ("expm1", {}), ("log", {"positive": True}),
    ("log2", {"positive": True}), ("log10", {"positive": True}),
    ("log1p", {"positive": True}), ("sqrt", {"positive": True}),
    ("cbrt", {}), ("square", {}), ("abs", {}), ("sign", {}),
    ("floor", {}), ("ceil", {}), ("trunc", {}), ("rint", {}),
    ("sin", {}), ("cos", {}), ("tan", {"small": True}),
    ("arcsin", {"small": True}), ("arccos", {"small": True}),
    ("arctan", {}), ("sinh", {}), ("cosh", {}), ("tanh", {}),
    ("arcsinh", {}), ("arctanh", {"small": True}),
    ("degrees", {}), ("radians", {}), ("reciprocal", {"positive": True}),
    ("negative", {}), ("exp2", {"small": True}),
]


@pytest.mark.parametrize("name,opts", UNARY, ids=[u[0] for u in UNARY])
def test_np_unary(name, opts):
    if not hasattr(np, name) or not hasattr(onp, name):
        pytest.skip(f"{name} not on both surfaces")
    x = _r((3, 5), positive=opts.get("positive", False),
           small=opts.get("small", False))
    got = getattr(np, name)(np.array(x)).asnumpy()
    want = getattr(onp, name)(x)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


BINARY = ["add", "subtract", "multiply", "divide", "power", "maximum",
          "minimum", "hypot", "arctan2", "fmod", "copysign",
          "greater", "greater_equal", "less", "less_equal", "equal",
          "not_equal", "logaddexp"]


@pytest.mark.parametrize("name", BINARY)
def test_np_binary(name):
    if not hasattr(np, name) or not hasattr(onp, name):
        pytest.skip(f"{name} not on both surfaces")
    a = onp.abs(_r((4, 3), 1)) + 0.5
    b = onp.abs(_r((4, 3), 2)) + 0.5
    got = getattr(np, name)(np.array(a), np.array(b)).asnumpy()
    want = getattr(onp, name)(a, b)
    onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                rtol=2e-5, atol=2e-6)


REDUCTIONS = ["sum", "prod", "mean", "std", "var", "max", "min",
              "argmax", "argmin", "cumsum", "cumprod"]


@pytest.mark.parametrize("name", REDUCTIONS)
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_np_reductions(name, axis):
    x = onp.abs(_r((3, 4), 3)) * 0.5 + 0.5
    got = getattr(np, name)(np.array(x), axis=axis).asnumpy()
    want = getattr(onp, name)(x, axis=axis)
    onp.testing.assert_allclose(onp.asarray(got, dtype=want.dtype), want,
                                rtol=2e-5, atol=1e-5)


SHAPE_FNS = [
    ("reshape", lambda m, x: m.reshape(m.array(x), (6, 2)),
     lambda x: onp.reshape(x, (6, 2))),
    ("transpose", lambda m, x: m.transpose(m.array(x)),
     lambda x: onp.transpose(x)),
    ("concatenate", lambda m, x: m.concatenate([m.array(x), m.array(x)],
                                               axis=0),
     lambda x: onp.concatenate([x, x], axis=0)),
    ("stack", lambda m, x: m.stack([m.array(x), m.array(x)], axis=1),
     lambda x: onp.stack([x, x], axis=1)),
    ("split", lambda m, x: m.split(m.array(x), 2, axis=0)[1],
     lambda x: onp.split(x, 2, axis=0)[1]),
    ("flip", lambda m, x: m.flip(m.array(x), axis=1),
     lambda x: onp.flip(x, axis=1)),
    ("roll", lambda m, x: m.roll(m.array(x), 2, axis=0),
     lambda x: onp.roll(x, 2, axis=0)),
    ("tile", lambda m, x: m.tile(m.array(x), (2, 1)),
     lambda x: onp.tile(x, (2, 1))),
    ("repeat", lambda m, x: m.repeat(m.array(x), 2, axis=1),
     lambda x: onp.repeat(x, 2, axis=1)),
    ("expand_dims", lambda m, x: m.expand_dims(m.array(x), 0),
     lambda x: onp.expand_dims(x, 0)),
    ("squeeze", lambda m, x: m.squeeze(m.expand_dims(m.array(x), 0)),
     lambda x: x),
    ("where", lambda m, x: m.where(m.array(x) > 0, m.array(x),
                                   m.zeros_like(m.array(x))),
     lambda x: onp.where(x > 0, x, onp.zeros_like(x))),
    ("clip", lambda m, x: m.clip(m.array(x), -0.5, 0.5),
     lambda x: onp.clip(x, -0.5, 0.5)),
    ("sort", lambda m, x: m.sort(m.array(x), axis=1),
     lambda x: onp.sort(x, axis=1)),
    ("argsort", lambda m, x: m.argsort(m.array(x), axis=1),
     lambda x: onp.argsort(x, axis=1)),
    ("unique", lambda m, x: m.unique(m.array(onp.round(x))),
     lambda x: onp.unique(onp.round(x))),
    ("diff", lambda m, x: m.diff(m.array(x), axis=1),
     lambda x: onp.diff(x, axis=1)),
    ("pad", lambda m, x: m.pad(m.array(x), ((1, 1), (0, 0))),
     lambda x: onp.pad(x, ((1, 1), (0, 0)))),
    ("trace", lambda m, x: m.trace(m.array(x)),
     lambda x: onp.trace(x)),
    ("outer", lambda m, x: m.outer(m.array(x[0]), m.array(x[1])),
     lambda x: onp.outer(x[0], x[1])),
    ("einsum", lambda m, x: m.einsum("ij,kj->ik", m.array(x), m.array(x)),
     lambda x: onp.einsum("ij,kj->ik", x, x)),
    ("dot", lambda m, x: m.dot(m.array(x), m.array(x.T)),
     lambda x: onp.dot(x, x.T)),
    ("matmul", lambda m, x: m.matmul(m.array(x), m.array(x.T)),
     lambda x: onp.matmul(x, x.T)),
    ("tensordot", lambda m, x: m.tensordot(m.array(x), m.array(x),
                                           axes=([1], [1])),
     lambda x: onp.tensordot(x, x, axes=([1], [1]))),
    ("kron", lambda m, x: m.kron(m.array(x[:2, :2]), m.array(x[:2, :2])),
     lambda x: onp.kron(x[:2, :2], x[:2, :2])),
    ("meshgrid", lambda m, x: m.meshgrid(m.array(x[0]), m.array(x[1]))[0],
     lambda x: onp.meshgrid(x[0], x[1])[0]),
    ("atleast_2d", lambda m, x: m.atleast_2d(m.array(x[0])),
     lambda x: onp.atleast_2d(x[0])),
    ("ravel", lambda m, x: m.ravel(m.array(x)),
     lambda x: onp.ravel(x)),
    ("triu", lambda m, x: m.triu(m.array(x)), lambda x: onp.triu(x)),
    ("tril", lambda m, x: m.tril(m.array(x)), lambda x: onp.tril(x)),
]


@pytest.mark.parametrize("case", SHAPE_FNS, ids=[c[0] for c in SHAPE_FNS])
def test_np_shape_and_linalgish(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np, name):
        pytest.skip(f"mx.np.{name} absent")
    x = _r((4, 3), 7)
    got = mx_fn(np, x)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp_fn(x)
    onp.testing.assert_allclose(onp.asarray(got, dtype=want.dtype), want,
                                rtol=2e-5, atol=2e-6)


LINALG = [
    ("norm", lambda m, a: m.linalg.norm(a), lambda a: onp.linalg.norm(a)),
    ("det", lambda m, a: m.linalg.det(a), lambda a: onp.linalg.det(a)),
    ("inv", lambda m, a: m.linalg.inv(a), lambda a: onp.linalg.inv(a)),
    ("slogdet", lambda m, a: m.linalg.slogdet(a)[1],
     lambda a: onp.linalg.slogdet(a)[1]),
    ("solve", lambda m, a: m.linalg.solve(a, m.ones((3, 1))
                                          if hasattr(m, 'ones') else None),
     lambda a: onp.linalg.solve(a, onp.ones((3, 1), onp.float32))),
    ("cholesky", lambda m, a: m.linalg.cholesky(a),
     lambda a: onp.linalg.cholesky(a)),
    ("eigvalsh", lambda m, a: m.linalg.eigvalsh(a),
     lambda a: onp.linalg.eigvalsh(a)),
    ("matrix_rank", lambda m, a: m.linalg.matrix_rank(a),
     lambda a: onp.linalg.matrix_rank(a)),
    ("pinv", lambda m, a: m.linalg.pinv(a), lambda a: onp.linalg.pinv(a)),
]


@pytest.mark.parametrize("case", LINALG, ids=[c[0] for c in LINALG])
def test_np_linalg(case):
    name, mx_fn, onp_fn = case
    if not hasattr(np.linalg, name):
        pytest.skip(f"mx.np.linalg.{name} absent")
    r = onp.random.RandomState(11)
    a = r.randn(3, 3).astype(onp.float32)
    spd = (a @ a.T + 3 * onp.eye(3)).astype(onp.float32)  # SPD for chol etc.
    got = mx_fn(np, np.array(spd))
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = onp_fn(spd)
    onp.testing.assert_allclose(got, onp.asarray(want), rtol=5e-4,
                                atol=5e-5)


def test_np_random_statistics():
    mx.random.seed(7)
    u = np.random.uniform(0, 1, size=(20000,)).asnumpy()
    assert 0.48 < u.mean() < 0.52
    assert u.min() >= 0 and u.max() <= 1
    g = np.random.normal(2.0, 3.0, size=(20000,)).asnumpy()
    assert abs(g.mean() - 2.0) < 0.1
    assert abs(g.std() - 3.0) < 0.1
    ri = np.random.randint(0, 10, size=(5000,)).asnumpy()
    assert set(onp.unique(ri)) <= set(range(10))


def test_np_autograd_through_np_functions():
    """mx.np functions record on the imperative tape like nd ops."""
    x = np.array(_r((3, 3), 13))
    x.attach_grad()
    with autograd.record():
        y = np.sum(np.tanh(x) * np.exp(x * 0.1))
    y.backward()
    g = x.grad.asnumpy()
    xv = x.asnumpy()
    want = (1 - onp.tanh(xv) ** 2) * onp.exp(xv * 0.1) \
        + onp.tanh(xv) * 0.1 * onp.exp(xv * 0.1)
    onp.testing.assert_allclose(g, want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# delegated-surface parity extension (ISSUE 8 satellite, VERDICT weak #6):
# a representative ~30-function slice across the three behavioral axes the
# thin delegation could silently get wrong — dtype promotion, axis kwargs
# (tuple / negative / keepdims), and python-scalar / 0-d operands.
# ---------------------------------------------------------------------------

# dtype pairs where numpy and the XLA lattice agree (int32+float32 is
# deliberately absent: numpy value-promotes it to float64, which the
# x64-disabled backend cannot represent — a documented divergence)
_PROMO_PAIRS = [("int16", "float32"), ("int8", "float32"),
                ("int8", "int32"), ("uint8", "int32"),
                ("bool", "int32"), ("int32", "int32"),
                ("float32", "float32")]
_PROMO_FNS = ["add", "subtract", "multiply", "maximum", "minimum"]


@pytest.mark.parametrize("da,db", _PROMO_PAIRS,
                         ids=[f"{a}+{b}" for a, b in _PROMO_PAIRS])
@pytest.mark.parametrize("name", _PROMO_FNS)
def test_np_dtype_promotion(name, da, db):
    av = onp.array([1, 0, 3]).astype(da)
    bv = onp.array([2, 5, 1]).astype(db)
    got = getattr(np, name)(np.array(av), np.array(bv)).asnumpy()
    want = getattr(onp, name)(av, bv)
    assert onp.dtype(got.dtype) == want.dtype, \
        f"{name}({da},{db}): promoted to {got.dtype}, numpy {want.dtype}"
    onp.testing.assert_array_equal(got, want)


def test_np_division_promotes_ints_to_float():
    """true_divide of ints must yield a float (numpy: float64; here the
    x64-disabled analog float32) with numpy's values."""
    a = np.array(onp.array([7, 8, 9], onp.int32))
    b = np.array(onp.array([2, 4, 3], onp.int32))
    got = np.divide(a, b).asnumpy()
    assert onp.dtype(got.dtype).kind == "f"
    onp.testing.assert_allclose(
        got, onp.divide(onp.array([7, 8, 9]), onp.array([2, 4, 3])),
        rtol=1e-6)


_AXIS_FNS = ["sum", "mean", "prod", "std", "var", "max", "min"]


@pytest.mark.parametrize("axis", [(0, 2), (1,), -1, -2, None],
                         ids=["tuple02", "tuple1", "neg1", "neg2", "none"])
@pytest.mark.parametrize("keepdims", [False, True])
@pytest.mark.parametrize("name", _AXIS_FNS)
def test_np_reduction_axis_kwargs(name, axis, keepdims):
    x = onp.abs(_r((2, 3, 4), 21)) + 0.5
    got = getattr(np, name)(np.array(x), axis=axis,
                            keepdims=keepdims).asnumpy()
    want = getattr(onp, name)(x, axis=axis, keepdims=keepdims)
    assert got.shape == want.shape, \
        f"{name} axis={axis} keepdims={keepdims}: {got.shape} vs {want.shape}"
    onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                rtol=2e-5, atol=1e-5)


@pytest.mark.parametrize("name", ["argmax", "argmin", "cumsum"])
@pytest.mark.parametrize("axis", [-1, 0])
def test_np_index_and_scan_negative_axis(name, axis):
    x = _r((3, 4), 22)
    got = getattr(np, name)(np.array(x), axis=axis).asnumpy()
    want = getattr(onp, name)(x, axis=axis)
    if name == "cumsum":  # XLA's log-depth scan reassociates the sum
        onp.testing.assert_allclose(got, want, rtol=2e-6, atol=2e-6)
    else:
        onp.testing.assert_array_equal(got, want)


_SCALAR_CASES = [
    ("add", lambda m, a: m.add(a, 2)),
    ("subtract", lambda m, a: m.subtract(a, 1.5)),
    ("multiply", lambda m, a: m.multiply(a, 3)),
    ("divide", lambda m, a: m.divide(a, 2.0)),
    ("power", lambda m, a: m.power(a, 2)),
    ("maximum", lambda m, a: m.maximum(a, 1.5)),
    ("minimum", lambda m, a: m.minimum(a, 1.5)),
    ("mod", lambda m, a: m.mod(a, 3)),
    ("floor_divide", lambda m, a: m.floor_divide(a, 3)),
    ("arctan2", lambda m, a: m.arctan2(a, 2.0)),
]


@pytest.mark.parametrize("case", _SCALAR_CASES,
                         ids=[c[0] for c in _SCALAR_CASES])
def test_np_python_scalar_operand(case):
    name, fn = case
    x = onp.abs(_r((3, 4), 23)) + 1.0
    got = fn(np, np.array(x)).asnumpy()
    want = fn(onp, x)
    onp.testing.assert_allclose(onp.asarray(got, want.dtype), want,
                                rtol=2e-5, atol=2e-6)


def test_np_zero_d_arrays():
    """0-d arrays flow through unary/binary/reduction like numpy's."""
    z = np.array(3.5)
    assert z.shape == ()
    assert float(np.add(z, 1.5).asnumpy()) == 5.0
    assert float(np.exp(np.array(0.0)).asnumpy()) == 1.0
    # reducing a 0-d array is the identity, as in numpy
    assert float(np.sum(z).asnumpy()) == 3.5
    assert np.sum(z).shape == ()
    # reducing a 1-d array to 0-d round-trips through python float
    s = np.sum(np.array(onp.ones(4, onp.float32)))
    assert s.shape == () and float(s.asnumpy()) == 4.0
    # 0-d broadcasts against arrays like a scalar
    got = np.multiply(np.array(onp.array([1.0, 2.0], onp.float32)), z)
    onp.testing.assert_allclose(got.asnumpy(), [3.5, 7.0])


def test_npx_set_np_toggles():
    mx.npx.set_np()
    try:
        from mxnet_tpu.util import is_np_array
        assert is_np_array()
    finally:
        mx.npx.reset_np()
    from mxnet_tpu.util import is_np_array
    assert not is_np_array()
