"""Checkpoint tests: dmlc .params byte format + orbax manager +
kill-and-resume loss-curve reproduction (VERDICT r2 next-round item 8)."""

import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError


# ---------------------------------------------------------------------------
# dmlc .params byte format
# ---------------------------------------------------------------------------

def test_dmlc_roundtrip_dict(tmp_path):
    f = str(tmp_path / "x.params")
    data = {"arg:w": mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)),
            "arg:b": mx.nd.array(np.array([1.5], np.float64)),
            "aux:i": mx.nd.array(np.array([[7, 8]], np.int64))}
    mx.nd.save(f, data, format="dmlc")
    out = mx.nd.load(f)
    assert set(out) == set(data)
    for k in data:
        np.testing.assert_array_equal(out[k].asnumpy(), data[k].asnumpy())
        assert out[k].dtype == data[k].dtype


def test_dmlc_roundtrip_list(tmp_path):
    f = str(tmp_path / "l.params")
    data = [mx.nd.ones((3,)), mx.nd.zeros((2, 2))]
    mx.nd.save(f, data, format="dmlc")
    out = mx.nd.load(f)
    assert isinstance(out, list) and len(out) == 2
    np.testing.assert_array_equal(out[0].asnumpy(), 1.0)


def test_dmlc_exact_golden_bytes(tmp_path):
    # pin the byte layout (reference ndarray.cc NDArray::Save): any format
    # drift breaks interchange silently — assert the exact bytes
    from mxnet_tpu import dmlc_params
    arr = np.array([[1.0, 2.0]], np.float32)
    blob = dmlc_params.save_bytes([arr], ["arg:w"])
    expect = b"".join([
        struct.pack("<QQ", 0x112, 0),          # list magic + reserved
        struct.pack("<Q", 1),                  # one array
        struct.pack("<I", 0xF993FAC9),         # NDArray V2 magic
        struct.pack("<i", 0),                  # dense stype
        struct.pack("<I", 2),                  # ndim
        struct.pack("<qq", 1, 2),              # int64 dims
        struct.pack("<ii", 1, 0),              # cpu:0
        struct.pack("<i", 0),                  # type_flag f32
        arr.tobytes(),
        struct.pack("<Q", 1),                  # one name
        struct.pack("<Q", 5), b"arg:w",
    ])
    assert blob == expect
    back, names = dmlc_params.load_bytes(blob)
    np.testing.assert_array_equal(back[0], arr)
    assert names == ["arg:w"]


def test_dmlc_reads_v1_era_32bit_dims():
    # V1-era files carried 32-bit dims; the reader probes both widths
    from mxnet_tpu import dmlc_params
    arr = np.array([3.0, 4.0, 5.0], np.float32)
    blob = b"".join([
        struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1),
        struct.pack("<I", 0xF993FAC9), struct.pack("<i", 0),
        struct.pack("<I", 1), struct.pack("<i", 3),   # 32-bit dim
        struct.pack("<ii", 1, 0), struct.pack("<i", 0),
        arr.tobytes(), struct.pack("<Q", 0),
    ])
    back, names = dmlc_params.load_bytes(blob)
    np.testing.assert_array_equal(back[0], arr)


def test_dmlc_reads_v1_era_2d_f64():
    # the width probe must not let int64 parsing swallow a 2-D 32-bit-dims
    # header (code-review regression: f64 (3,4) misparsed as a huge shape)
    from mxnet_tpu import dmlc_params
    arr = np.zeros((3, 4), np.float64)
    arr[0, 1] = 2.5
    blob = b"".join([
        struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1),
        struct.pack("<I", 0xF993FAC9), struct.pack("<i", 0),
        struct.pack("<I", 2), struct.pack("<ii", 3, 4),  # 32-bit dims
        struct.pack("<ii", 1, 0), struct.pack("<i", 1),  # f64
        arr.tobytes(), struct.pack("<Q", 0),
    ])
    back, _ = dmlc_params.load_bytes(blob)
    np.testing.assert_array_equal(back[0], arr)


def test_dmlc_rejects_garbage():
    from mxnet_tpu import dmlc_params
    with pytest.raises(MXNetError, match="magic"):
        dmlc_params.load_bytes(b"\x00" * 64)
    assert not dmlc_params.is_dmlc_params(b"PK\x03\x04....")


def test_npz_default_unchanged(tmp_path):
    f = str(tmp_path / "y.params")
    mx.nd.save(f, {"w": mx.nd.ones((2,))})
    with open(f, "rb") as fh:
        assert fh.read(2) == b"PK"  # zip container (np.savez)
    out = mx.nd.load(f)
    np.testing.assert_array_equal(out["w"].asnumpy(), 1.0)


# ---------------------------------------------------------------------------
# orbax manager + auto-resume
# ---------------------------------------------------------------------------

def _make_net_trainer(lr=0.05):
    mx.random.seed(7)
    # fixed prefix: checkpoint keys are structural names, and the global
    # name counter would otherwise differ between the two "processes"
    net = gluon.nn.Dense(4, in_units=6, prefix="net_")
    net.initialize(mx.initializer.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": lr})
    return net, tr


def _step(net, tr, x, y, lossf):
    with autograd.record():
        loss = lossf(net(x), y)
    loss.backward()
    tr.step(x.shape[0])
    return float(loss.mean().asnumpy())


def test_checkpoint_manager_roundtrip(tmp_path):
    net, tr = _make_net_trainer()
    x = mx.nd.ones((8, 6))
    y = mx.nd.array(np.arange(8) % 4)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    _step(net, tr, x, y, lossf)
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    assert mgr.latest_step() is None
    mgr.save(0, net=net, trainer=tr, extra={"epoch": mx.nd.array([3.0])})
    w0 = list(net.collect_params().values())[0].data().asnumpy().copy()
    _step(net, tr, x, y, lossf)  # mutate
    step, extra = mgr.restore(net=net, trainer=tr)
    assert step == 0
    np.testing.assert_allclose(
        list(net.collect_params().values())[0].data().asnumpy(), w0)
    assert float(extra["epoch"].asnumpy()[0]) == 3.0


def test_manifest_world_audit_on_resized_restore(tmp_path):
    """Resume-with-different-n audit (ISSUE 11): the manifest records
    the world that committed each step; restoring into a different world
    warns (a documented resize point), counts, and still restores the
    topology-free params."""
    import json
    import os
    import warnings
    from mxnet_tpu.telemetry import REGISTRY

    net, _tr = _make_net_trainer()
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path / "ck"))
    mgr.save(0, net=net)
    # single-process save records world n=1, unsharded
    assert mgr.world_size(0) == 1
    man_path = os.path.join(str(tmp_path / "ck"), "manifest.json")
    with open(man_path) as f:
        man = json.load(f)
    assert man["world"]["0"] == {"n": 1, "sharded": False}
    # same-world restore: silent, uncounted
    before = REGISTRY.get("mxnet_checkpoint_resize_restores_total").value
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        mgr.restore(net=net)
    assert REGISTRY.get(
        "mxnet_checkpoint_resize_restores_total").value == before
    # pretend a 4-rank world committed step 0 → elastic resize point
    man["world"]["0"] = {"n": 4, "sharded": False}
    with open(man_path, "w") as f:
        json.dump(man, f)
    with pytest.warns(UserWarning, match="elastic resize point"):
        step, _ = mgr.restore(net=net)
    assert step == 0
    assert REGISTRY.get(
        "mxnet_checkpoint_resize_restores_total").value == before + 1
    # a SHARDED save restoring elsewhere gets the louder warning
    man["world"]["0"] = {"n": 4, "sharded": True}
    with open(man_path, "w") as f:
        json.dump(man, f)
    with pytest.warns(UserWarning, match="topology-bound"):
        mgr.restore(net=net)
    # pre-audit manifests (no world map) stay silent
    del man["world"]
    with open(man_path, "w") as f:
        json.dump(man, f)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        mgr.restore(net=net)
    assert mgr.world_size(0) is None


def test_kill_and_resume_reproduces_loss_curve(tmp_path):
    # VERDICT acceptance: kill mid-training and resume; the resumed curve
    # must equal the unkilled one (params + adam state + step counts)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    r = np.random.RandomState(0)
    X = mx.nd.array(r.randn(8, 6).astype(np.float32))
    Y = mx.nd.array(r.randint(0, 4, (8,)))
    total = 8

    # unkilled reference run
    net, tr = _make_net_trainer()
    ref = [_step(net, tr, X, Y, lossf) for _ in range(total)]

    # killed run: stop after 3 steps...
    ckdir = str(tmp_path / "resume")
    losses_a = []

    def run_a(step):
        losses_a.append(_step(*state_a, X, Y, lossf))
        return step < 2  # steps 0,1,2 then stop (simulated preemption)

    state_a = _make_net_trainer()
    mx.checkpoint.auto_resume(run_a, ckdir, net=state_a[0],
                              trainer=state_a[1], save_every=1)

    # ...new process: fresh objects, resume from the checkpoint dir
    losses_b = []

    def run_b(step):
        losses_b.append(_step(*state_b, X, Y, lossf))
        return step < total - 1

    state_b = _make_net_trainer()  # fresh (different) init — must be overwritten
    last = mx.checkpoint.auto_resume(run_b, ckdir, net=state_b[0],
                                     trainer=state_b[1], save_every=1)
    assert last == total - 1
    curve = losses_a + losses_b
    assert len(curve) == total
    np.testing.assert_allclose(curve, ref, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# edge cases (ISSUE 3 satellites): pruning order, corruption fallback,
# trainer-state round-trip
# ---------------------------------------------------------------------------

def test_max_to_keep_prunes_oldest_first(tmp_path):
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path / "keep"),
                                          max_to_keep=2)
    for s in range(5):
        mgr.save(s, extra={"v": mx.nd.array([float(s)])})
    # oldest steps pruned, newest retained, in order
    assert mgr.all_steps() == [3, 4]
    assert mgr.committed_steps() == [3, 4]
    assert mgr.latest_step() == 4
    # the manifest never references pruned steps
    step, extra = mgr.restore()
    assert step == 4 and float(extra["v"].asnumpy()[0]) == 4.0


def test_restore_falls_back_past_corrupted_latest(tmp_path):
    import glob
    import os as _os
    d = str(tmp_path / "corrupt")
    mgr = mx.checkpoint.CheckpointManager(d, max_to_keep=4)
    mgr.save(0, extra={"v": mx.nd.array([10.0])})
    mgr.save(1, extra={"v": mx.nd.array([11.0])})
    # trash every data file of the latest step
    for f in glob.glob(_os.path.join(d, "1", "**", "*"), recursive=True):
        if _os.path.isfile(f):
            with open(f, "wb") as fh:
                fh.write(b"garbage")
    with pytest.warns(UserWarning, match="falling back"):
        step, extra = mgr.restore()
    assert step == 0
    assert float(extra["v"].asnumpy()[0]) == 10.0
    # an EXPLICITLY requested corrupted step still errors
    with pytest.raises(Exception):
        mgr.restore(step=1)


def test_trainer_state_roundtrip_equality(tmp_path):
    import tempfile
    from mxnet_tpu import autograd, gluon  # noqa: F811

    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    r = np.random.RandomState(5)
    X = mx.nd.array(r.randn(8, 6).astype(np.float32))
    Y = mx.nd.array(r.randint(0, 4, (8,)))
    net, tr = _make_net_trainer()
    _step(net, tr, X, Y, lossf)  # adam state becomes non-trivial
    _step(net, tr, X, Y, lossf)

    def state_bytes(trainer):
        with tempfile.NamedTemporaryFile(suffix=".states") as f:
            trainer.save_states(f.name)
            with open(f.name, "rb") as fh:
                return fh.read()

    mgr = mx.checkpoint.CheckpointManager(str(tmp_path / "tr"))
    mgr.save(0, net=net, trainer=tr)
    want = state_bytes(tr)
    _step(net, tr, X, Y, lossf)  # mutate optimizer state past the save
    assert state_bytes(tr) != want
    step, _ = mgr.restore(net=net, trainer=tr)
    assert step == 0
    assert state_bytes(tr) == want  # byte-exact optimizer state round-trip
