"""Registry-level sparse-storage op tests (VERDICT r3 item 7), mirroring
the reference's tests/python/unittest/test_sparse_operator.py patterns:
dense-oracle forward parity + numeric gradients through the recorded
tape.  Reference kernels: src/operator/tensor/dot.cc (FComputeEx csr
paths), square_sum.cc, sparse_retain.cc, indexing_op.cc (row_sparse
Embedding backward)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.ndarray import sparse


def _rand_csr(r, m, n, density=0.3):
    d = r.randn(m, n).astype(np.float32)
    d[r.rand(m, n) > density] = 0.0
    return d, sparse.csr_matrix(d)


def test_csr_dot_forward_matches_dense(seeded):
    r = np.random.RandomState(0)
    d, csr = _rand_csr(r, 6, 9)
    rhs = nd.array(r.randn(9, 4).astype(np.float32))
    out = sparse.dot(csr, rhs)
    np.testing.assert_allclose(out.asnumpy(), d @ rhs.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_csr_dot_transpose_forward(seeded):
    r = np.random.RandomState(1)
    d, csr = _rand_csr(r, 6, 9)
    rhs = nd.array(r.randn(6, 3).astype(np.float32))
    out = sparse.dot(csr, rhs, transpose_a=True)
    np.testing.assert_allclose(out.asnumpy(), d.T @ rhs.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_csr_dot_grads(seeded):
    """d/drhs [csr @ rhs] == csr.T @ dout and d/dvalues flows to the
    stored elements — both through the recorded tape."""
    r = np.random.RandomState(2)
    d, csr = _rand_csr(r, 5, 7)
    rhs = nd.array(r.randn(7, 3).astype(np.float32))
    rhs.attach_grad()
    csr.data.attach_grad()
    w = nd.array(r.randn(5, 3).astype(np.float32))
    with autograd.record():
        out = sparse.dot(csr, rhs)
        loss = (out * w).sum()
    loss.backward()
    np.testing.assert_allclose(rhs.grad.asnumpy(), d.T @ w.asnumpy(),
                               rtol=1e-5, atol=1e-5)
    # grad wrt stored values: dL/ddata[k] = rhs[col_k] . w[row_k]
    rows, cols = np.nonzero(d)
    want = np.einsum("kj,kj->k", rhs.asnumpy()[cols], w.asnumpy()[rows])
    np.testing.assert_allclose(csr.data.grad.asnumpy(), want,
                               rtol=1e-5, atol=1e-5)


def test_square_sum_axes_and_grad(seeded):
    r = np.random.RandomState(3)
    dense = r.randn(8, 4).astype(np.float32)
    dense[[1, 3, 5, 6]] = 0.0
    rsp = sparse.row_sparse_array(dense)
    np.testing.assert_allclose(sparse.square_sum(rsp).asnumpy(),
                               (dense ** 2).sum(), rtol=1e-5)
    np.testing.assert_allclose(sparse.square_sum(rsp, axis=1).asnumpy(),
                               (dense ** 2).sum(1), rtol=1e-5)
    np.testing.assert_allclose(sparse.square_sum(rsp, axis=0).asnumpy(),
                               (dense ** 2).sum(0), rtol=1e-5)
    np.testing.assert_allclose(
        sparse.square_sum(rsp, axis=1, keepdims=True).asnumpy(),
        (dense ** 2).sum(1, keepdims=True), rtol=1e-5)
    # gradient: d/dx sum(x^2) = 2x on stored rows
    rsp.data.attach_grad()
    with autograd.record():
        loss = sparse.square_sum(rsp)
    loss.backward()
    np.testing.assert_allclose(rsp.data.grad.asnumpy(),
                               2 * rsp.data.asnumpy(), rtol=1e-5)


def test_sparse_retain_function(seeded):
    dense = np.zeros((6, 3), np.float32)
    dense[[0, 2, 4]] = np.arange(9, dtype=np.float32).reshape(3, 3) + 1
    rsp = sparse.row_sparse_array(dense)
    kept = sparse.sparse_retain(rsp, nd.array(np.array([2, 5])))
    out = kept.tostype("default").asnumpy()
    want = np.zeros_like(dense)
    want[2] = dense[2]
    np.testing.assert_allclose(out, want)
    # the registry masking kernel agrees with the container compaction
    masked = nd._sparse_retain_values(
        rsp.data, rsp.indices, nd.array(np.array([2, 5])))
    np.testing.assert_allclose(
        masked.asnumpy(),
        np.where(np.isin([0, 2, 4], [2, 5])[:, None],
                 rsp.data.asnumpy(), 0.0))


def test_embedding_sparse_grad_rowsparse_view(seeded):
    """Embedding(sparse_grad=True): param.grad() returns a row_sparse
    gradient carrying exactly the touched rows (reference indexing_op.cc
    SparseEmbedding backward contract)."""
    vocab, dim = 20, 4
    emb = gluon.nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize(mx.initializer.Normal(0.5))
    tokens = nd.array(np.array([[3, 7, 3], [11, 7, 19]], np.float32))
    w = emb.weight
    assert w.grad_stype == "row_sparse"
    with autograd.record():
        out = emb(tokens)
        loss = (out * out).sum()
    loss.backward()
    g = w.grad()
    assert isinstance(g, sparse.RowSparseNDArray)
    touched = sorted(set(np.asarray(tokens.asnumpy(), np.int64).ravel()))
    assert sorted(g.indices.asnumpy().tolist()) == touched
    # values match the dense grad restricted to those rows
    dense_g = w.grad(stype="default").asnumpy()
    np.testing.assert_allclose(g.tostype("default").asnumpy(), dense_g,
                               rtol=1e-6)
    assert np.abs(dense_g[touched]).sum() > 0


def test_sparse_retain_grad_flows_to_values(seeded):
    """sparse_retain's value path rides differentiable registry ops
    (_sparse_retain_values + take): grads reach the stored rows."""
    dense = np.zeros((6, 3), np.float32)
    dense[[0, 2, 4]] = np.arange(9, dtype=np.float32).reshape(3, 3) + 1
    rsp = sparse.row_sparse_array(dense)
    rsp.data.attach_grad()
    with autograd.record():
        kept = sparse.sparse_retain(rsp, nd.array(np.array([2, 5])))
        loss = (kept.data * kept.data).sum()
    loss.backward()
    want = np.zeros_like(dense[[0, 2, 4]])
    want[1] = 2 * dense[2]
    np.testing.assert_allclose(rsp.data.grad.asnumpy(), want)
