"""Test harness config.

Mirrors the reference strategy (SURVEY §4): the suite runs on a *virtual
8-device CPU platform* so multi-device/sharding paths are exercised without
TPU hardware — XLA_FLAGS must be set before jax imports.  Seeding follows
tests/python/unittest/common.py: MXNET_TEST_SEED / MXNET_MODULE_SEED control
reproduction; each test gets a seed logged on failure via the with_seed
fixture below.
"""

import os

# The sandbox presets JAX_PLATFORMS=axon (the real chip); the suite runs on
# the virtual 8-CPU platform per SURVEY §4 unless explicitly pointed at TPU
# with MXNET_TEST_DEVICE=tpu.  A pytest plugin imports jax before this
# conftest runs, so env vars alone are too late — go through jax.config
# (safe: backends have not been initialized yet at collection time).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        (flags + " --xla_force_host_platform_device_count=8").strip()
if os.environ.get("MXNET_TEST_DEVICE", "cpu") != "tpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

# The flight recorder (ISSUE 10) is always-on and several suites
# deliberately trigger its dump conditions (deadline-exceeded, chaos
# faults); point the dumps at a scratch dir so test runs don't litter the
# repo root.  Tests that assert on dumps monkeypatch their own dir.
if "MXNET_FLIGHTREC_DIR" not in os.environ:
    import tempfile
    os.environ["MXNET_FLIGHTREC_DIR"] = tempfile.mkdtemp(
        prefix="mxnet-flightrec-")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def seeded(request):
    """Per-test deterministic seeding with printed repro seed on failure
    (reference common.py :: with_seed)."""
    import mxnet_tpu as mx
    seed = int(os.environ.get("MXNET_TEST_SEED",
                              abs(hash(request.node.name)) % (2 ** 31)))
    np.random.seed(seed)
    mx.random.seed(seed)
    yield
    # seed printed by pytest on failure via -ra and the node repr


@pytest.fixture
def ctx():
    from mxnet_tpu.test_utils import default_context
    return default_context()
