"""mx.contrib.text tests (reference python/mxnet/contrib/text/ — vocab
counting, index maps, file-loaded embeddings, composition)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text


def test_count_tokens():
    c = text.count_tokens_from_str("a b b\nc a  a", to_lower=False)
    assert c == {"a": 3, "b": 2, "c": 1}
    c2 = text.count_tokens_from_str("A a", to_lower=True)
    assert c2["a"] == 2


def test_vocabulary_ranking_and_lookup():
    c = text.count_tokens_from_str("dog cat cat bird dog dog")
    v = text.Vocabulary(c, unknown_token="<unk>", reserved_tokens=["<pad>"])
    # freq rank: dog(3), cat(2), bird(1); <unk>=0, <pad>=1
    assert v.idx_to_token == ["<unk>", "<pad>", "dog", "cat", "bird"]
    assert v.to_indices("dog") == 2
    assert v.to_indices(["bird", "missing"]) == [4, 0]
    assert v.to_tokens([2, 3]) == ["dog", "cat"]
    with pytest.raises(mx.MXNetError):
        v.to_tokens([99])
    v2 = text.Vocabulary(c, most_freq_count=2, min_freq=2)
    assert v2.idx_to_token == ["<unk>", "dog", "cat"]


def test_custom_embedding_and_composite(tmp_path):
    p1 = os.path.join(str(tmp_path), "e1.txt")
    with open(p1, "w") as f:
        f.write("dog 1 2\ncat 3 4\nbird 5 6\n")
    p2 = os.path.join(str(tmp_path), "e2.txt")
    with open(p2, "w") as f:
        f.write("dog 10\ncat 30\n")
    e1 = text.CustomEmbedding(p1)
    assert e1.vec_len == 2 and len(e1) == 4   # <unk> + 3 tokens
    np.testing.assert_allclose(
        e1.get_vecs_by_tokens(["dog", "nope"]).asnumpy(),
        [[1, 2], [0, 0]])
    np.testing.assert_allclose(e1.get_vecs_by_tokens("cat").asnumpy(),
                               [3, 4])
    e1.update_token_vectors("dog", mx.nd.array(np.array([[9., 9.]])))
    np.testing.assert_allclose(e1.get_vecs_by_tokens("dog").asnumpy(),
                               [9, 9])
    with pytest.raises(mx.MXNetError):
        e1.update_token_vectors("nope", mx.nd.array(np.array([[1., 1.]])))

    vocab = text.Vocabulary(
        text.count_tokens_from_str("dog cat dog"))
    e2 = text.CustomEmbedding(p2)
    comp = text.CompositeEmbedding(vocab, [e1, e2])
    assert comp.vec_len == 3
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("dog").asnumpy(), [9, 9, 10])
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("cat").asnumpy(), [3, 4, 30])


def test_custom_embedding_vocab_filter_and_errors(tmp_path):
    p = os.path.join(str(tmp_path), "e.txt")
    with open(p, "w") as f:
        f.write("a 1 2\nb 3 4\n")
    vocab = text.Vocabulary(text.count_tokens_from_str("a c a"))
    e = text.CustomEmbedding(p, vocabulary=vocab)
    assert e.idx_to_token == ["<unk>", "a"]   # only vocab∩file tokens
    bad = os.path.join(str(tmp_path), "bad.txt")
    with open(bad, "w") as f:
        f.write("a 1 2\nb 3\n")
    with pytest.raises(mx.MXNetError):
        text.CustomEmbedding(bad)


def test_svrg_matches_oracle_and_converges():
    """SVRGTrainer (reference svrg_optimization role): the update equals
    the numpy SVRG oracle g(w) - g(w~) + g_full on a linear model, and
    drives a convex loss down."""
    from mxnet_tpu import gluon
    from mxnet_tpu.contrib.svrg import SVRGTrainer
    r = np.random.RandomState(0)
    X = r.randn(64, 5).astype(np.float32)
    w_true = r.randn(5, 1).astype(np.float32)
    Y = X @ w_true

    mx.random.seed(0)
    net = gluon.nn.Dense(1, use_bias=False, in_units=5)
    net.initialize(mx.initializer.Normal(0.1))

    def loss_fn(out, label):
        return ((out - label) ** 2).mean()

    tr = SVRGTrainer(net, loss_fn, learning_rate=0.05, update_freq=1)
    batches = [(mx.nd.array(X[i:i + 16]), mx.nd.array(Y[i:i + 16]))
               for i in range(0, 64, 16)]
    w0 = net.weight.data().asnumpy().copy()
    tr.update_full_grads(iter(batches))

    # numpy oracle for the FIRST step on batch 0
    def grad_at(w, xb, yb):
        # loss = mean((x w^T - y)^2); dW = 2/n * (xw - y)^T x
        e = xb @ w.T - yb
        return (2.0 / len(xb)) * e.T @ xb
    g_full = np.mean([grad_at(w0, X[i:i + 16], Y[i:i + 16])
                      for i in range(0, 64, 16)], axis=0)
    want = w0 - 0.05 * (grad_at(w0, X[:16], Y[:16])
                        - grad_at(w0, X[:16], Y[:16]) + g_full)
    first_loss = tr.step(*batches[0])
    np.testing.assert_allclose(net.weight.data().asnumpy(), want,
                               rtol=1e-4, atol=1e-6)

    losses = [first_loss]
    for epoch in range(6):
        tr.maybe_refresh(iter(batches))
        for xb, yb in batches:
            losses.append(tr.step(xb, yb))
    assert losses[-1] < 0.2 * losses[0]


def test_custom_embedding_fasttext_header_and_cap(tmp_path):
    """fastText '<n> <dim>' header line is skipped (review regression),
    and most_freq_count budgets exclude special tokens first."""
    p = os.path.join(str(tmp_path), "ft.txt")
    with open(p, "w") as f:
        f.write("2 3\n")                    # header
        f.write("dog 1 2 3\ncat 4 5 6\n")
    e = text.CustomEmbedding(p)
    assert e.vec_len == 3
    np.testing.assert_allclose(e.get_vecs_by_tokens("cat").asnumpy(),
                               [4, 5, 6])
    c = {"<pad>": 5, "a": 3, "b": 2}
    v = text.Vocabulary(c, most_freq_count=2, reserved_tokens=["<pad>"])
    assert v.idx_to_token == ["<unk>", "<pad>", "a", "b"]
