"""SyncBatchNorm contract tests (VERDICT r3 item 9; reference
src/operator/contrib/sync_batch_norm.cc + gluon.contrib SyncBatchNorm).

The absorption claim: under ``parallel.TrainStep`` (one SPMD program, the
batch axis global) plain BN statistics ARE the synchronized statistics —
GSPMD inserts the cross-device reduction.  Test 1 pins that: an 8-way
data-parallel TrainStep must produce bit-comparable running stats and
loss to the SAME model stepped on the full batch without a mesh.

Test 2 pins the DOCUMENTED divergence of the legacy replica path
(per-ctx eager forwards a la split_and_load): each replica folds its OWN
half-batch statistics into the running buffers sequentially — per-replica
stats, exactly what upstream plain BatchNorm would do per device.
"""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel
from mxnet_tpu.gluon.contrib.nn import SyncBatchNorm


def _make_net(seed=3):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(6, in_units=5))
        net.add(SyncBatchNorm(in_channels=6, num_devices=8))
        net.add(gluon.nn.Dense(3, in_units=6))
    net.initialize(mx.initializer.Xavier())
    return net


def _stats(net):
    out = {}
    for name, p in net.collect_params().items():
        for key in ("running_mean", "running_var"):
            if key in name:
                out[key] = p.data().asnumpy().copy()
    return out


def test_trainstep_bn_stats_are_global_batch():
    """dp=8 TrainStep running stats == no-mesh full-batch stats."""
    r = np.random.RandomState(0)
    x = (r.randn(16, 5) * 2 + 1).astype(np.float32)
    y = r.randn(16, 3).astype(np.float32)

    def loss_fn(o, l):
        return ((o - l) ** 2).mean()

    results = {}
    for mode in ("sharded", "full"):
        import jax
        net = _make_net()
        mesh = parallel.make_mesh() if mode == "sharded" else \
            parallel.DeviceMesh(devices=jax.devices()[:1], shape=(1,),
                                axis_names=("dp",))
        if mode == "sharded":
            assert mesh.axis_size(mesh.axis_names[0]) == 8
        step = parallel.TrainStep(
            net, loss_fn, mx.optimizer.SGD(learning_rate=0.1), mesh=mesh,
            donate=False)
        loss = float(step(nd.array(x), nd.array(y)).asscalar())
        results[mode] = (loss, _stats(net))

    l_sh, st_sh = results["sharded"]
    l_full, st_full = results["full"]
    assert np.isfinite(l_sh)
    np.testing.assert_allclose(l_sh, l_full, rtol=1e-6)
    assert st_sh and sorted(st_sh) == sorted(st_full)
    for k in st_sh:
        np.testing.assert_allclose(st_sh[k], st_full[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)
    # and the stats really moved (the test would pass vacuously otherwise)
    assert not np.allclose(st_sh["running_mean"], 0.0)


def test_replica_path_keeps_per_replica_stats():
    """Eager per-ctx forwards (the split_and_load pattern) fold HALF-batch
    stats sequentially — the documented per-replica behavior."""
    r = np.random.RandomState(1)
    x = (r.randn(8, 5) * 3).astype(np.float32)
    halves = [x[:4], x[4:]]

    bn = SyncBatchNorm(in_channels=5, num_devices=2, momentum=0.9)
    bn.initialize()
    for h in halves:                      # replica forwards, in sequence
        with autograd.record():
            bn(nd.array(h))
    got = bn.params.get("running_mean").data().asnumpy()

    # oracle: sequential momentum updates with PER-HALF means
    want = np.zeros(5, np.float32)
    for h in halves:
        want = 0.9 * want + 0.1 * h.mean(axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # which is NOT the full-batch statistic — the divergence the docstring
    # warns about (use TrainStep when synchronized stats matter)
    full = 0.9 * (0.9 * np.zeros(5) + 0.1 * x.mean(axis=0)) \
        + 0.1 * x.mean(axis=0)
    assert not np.allclose(got, full, rtol=1e-3)
