"""Ulysses all-to-all sequence parallelism (kernels/ulysses.py) —
parity vs dense attention and vs ring attention, incl. gradients.
Reference: ABSENT upstream (SURVEY §5.7)."""

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401 — ensures package import order
from mxnet_tpu.parallel import DeviceMesh


def _dense(q, k, v, causal=False):
    import jax.numpy as jnp
    import jax
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (D ** 0.5)
    if causal:
        L = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((L, L), bool))[None, None],
                      s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


def _mk(B=2, H=4, L=16, D=8, seed=0):
    r = np.random.RandomState(seed)
    return tuple(r.randn(B, H, L, D).astype(np.float32) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    from mxnet_tpu.kernels.ulysses import ulysses_sequence_parallel_attention
    import jax
    mesh = DeviceMesh(shape=(4,), axis_names=("sp",),
                      devices=jax.devices()[:4])
    q, k, v = _mk()
    out = np.asarray(ulysses_sequence_parallel_attention(
        q, k, v, mesh, axis="sp", causal=causal,
        sm_scale=1.0 / (q.shape[-1] ** 0.5)))
    ref = np.asarray(_dense(*map(np.asarray, (q, k, v)), causal=causal))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ulysses_grad_matches_dense():
    from mxnet_tpu.kernels.ulysses import ulysses_sequence_parallel_attention
    import jax
    import jax.numpy as jnp
    mesh = DeviceMesh(shape=(4,), axis_names=("sp",),
                      devices=jax.devices()[:4])
    q, k, v = _mk(seed=3)

    sc = 1.0 / (q.shape[-1] ** 0.5)
    g_u = jax.grad(lambda qq: jnp.sum(
        ulysses_sequence_parallel_attention(qq, k, v, mesh, axis="sp",
                                            causal=True,
                                            sm_scale=sc) ** 2))(q)
    g_d = jax.grad(lambda qq: jnp.sum(
        _dense(qq, jnp.asarray(k), jnp.asarray(v), causal=True) ** 2))(
        jnp.asarray(q))
    np.testing.assert_allclose(np.asarray(g_u), np.asarray(g_d),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_ulysses_matches_ring():
    from mxnet_tpu.kernels.ulysses import ulysses_sequence_parallel_attention
    from mxnet_tpu.kernels.ring_attention import sequence_parallel_attention
    import jax
    mesh = DeviceMesh(shape=(4,), axis_names=("sp",),
                      devices=jax.devices()[:4])
    q, k, v = _mk(seed=5)
    sc = 1.0 / (q.shape[-1] ** 0.5)
    out_u = np.asarray(ulysses_sequence_parallel_attention(
        q, k, v, mesh, axis="sp", causal=True, sm_scale=sc))
    out_r = np.asarray(sequence_parallel_attention(
        q, k, v, mesh, axis="sp", causal=True, sm_scale=sc))
    np.testing.assert_allclose(out_u, out_r, rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_bad_heads():
    from mxnet_tpu.kernels.ulysses import ulysses_sequence_parallel_attention
    import jax
    mesh = DeviceMesh(shape=(4,), axis_names=("sp",),
                      devices=jax.devices()[:4])
    r = np.random.RandomState(0)
    q = k = v = r.randn(1, 3, 16, 8).astype(np.float32)  # 3 heads, n=4
    with pytest.raises(Exception, match="heads"):
        ulysses_sequence_parallel_attention(q, k, v, mesh, axis="sp")
