"""Worker body for the n=4 distributed chaos suite (ISSUE 8 satellite /
ROADMAP 4): the PR-3 resilience machinery — deadline-bounded collectives,
atomic manifest checkpoints, auto_resume — exercised against a REAL
4-process topology.  Not collected by pytest (no test_ prefix).

Modes (argv[1]):
 - ``clean``          — run all steps, checkpoint each, dump final params.
 - ``die-allreduce``  — the highest rank arms a chaos ``exit`` fault on
   the ``kvstore.allreduce`` site right before step 3's reduction:
   worker death MID-ALLREDUCE.  Survivors must NOT hang — the PR-3
   Deadline turns the dead peer into KVStoreTimeoutError and the run
   exits nonzero with every rank's last COMMITTED step aligned (the
   dying step never completes anywhere).
 - ``die-checkpoint`` — every rank arms a chaos ``exit`` on the
   ``checkpoint.save`` site at step 4: preemption MID-CHECKPOINT, inside
   the atomicity-critical window (data written, manifest not yet
   committed).  On restart the orphaned step must be invisible and the
   job resumes from the previous committed step.

Each rank trains the same seeded net on rank+step-deterministic data, so
a ``clean`` run after any fault sequence must reproduce the uninterrupted
reference run's final parameters BIT-identically.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    try:
        # multi-proc CPU collectives need gloo BEFORE backend init
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass

jax.distributed.initialize(
    coordinator_address=os.environ["MXNET_DIST_COORDINATOR"],
    num_processes=int(os.environ["MXNET_DIST_NUM_WORKERS"]),
    process_id=int(os.environ["MXNET_DIST_RANK"]))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.resilience import chaos  # noqa: E402

TOTAL = 6


def main():
    mode, outdir = sys.argv[1], sys.argv[2]
    rank = int(os.environ["MXNET_DIST_RANK"])
    n = int(os.environ["MXNET_DIST_NUM_WORKERS"])

    kv = mx.kv.create("dist_tpu_sync")
    kv.set_bucket_size(0)   # per-key pushes: every one crosses the
    #                         kvstore.allreduce chaos site
    mx.random.seed(7)       # identical init on every rank
    net = gluon.nn.Dense(4, in_units=6, prefix="net_")
    net.initialize(mx.initializer.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05}, kvstore=kv)
    lossf = gluon.loss.L2Loss()

    def batch(step):
        r = np.random.RandomState(1000 * rank + step)
        return (mx.nd.array(r.randn(8, 6).astype(np.float32)),
                mx.nd.array(r.randn(8, 4).astype(np.float32)))

    def train_fn(step):
        if mode == "die-allreduce" and rank == n - 1 and step == 3:
            # the NEXT allreduce hit is step 3's gradient reduction:
            # death strictly mid-allreduce, no hit counting needed
            chaos.inject("kvstore.allreduce", kind="exit", times=1)
        if mode == "die-checkpoint" and step == 4:
            # fires inside CheckpointManager.save between data write and
            # manifest commit — the window atomicity must cover
            chaos.inject("checkpoint.save", kind="exit", times=1)
        x, y = batch(step)
        with autograd.record():
            loss = lossf(net(x), y)
        loss.backward()
        tr.step(x.shape[0])
        return step < TOTAL - 1

    # ONE shared checkpoint tree for the whole job (the orbax multihost
    # contract: the primary process writes, every process barriers) — a
    # per-rank directory would desync the manager's cross-process
    # coordination
    last = mx.checkpoint.auto_resume(
        train_fn, os.path.join(outdir, "ckpt"),
        net=net, trainer=tr, save_every=1, max_restarts=0)
    assert last == TOTAL - 1, last

    np.savez(os.path.join(outdir, f"final_rank{rank}.npz"),
             **{k: p.data().asnumpy()
                for k, p in net.collect_params().items()})
    print(f"worker {rank}/{n} [{mode}]: OK (last step {last})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
