"""Sparse parameter-server path tests (VERDICT r2 item 10; reference
tests/nightly/dist_sync_kvstore.py row_sparse cases + sparse optimizer
lazy-update semantics)."""

import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore.sparse_ps import SparsePS
from mxnet_tpu.ndarray.sparse import RowSparseNDArray, cast_storage


def _rsp(values, rows, shape):
    return RowSparseNDArray(mx.nd.array(np.asarray(values, np.float32)),
                            mx.nd.array(np.asarray(rows, np.int64)), shape)


def test_ps_init_push_pull_exact():
    ps = SparsePS()
    ps.init("emb", mx.nd.zeros((10, 2)))
    # no optimizer: raw accumulation
    ps.push("emb", _rsp([[1, 1], [2, 2]], [3, 7], (10, 2)))
    out = ps.row_sparse_pull("emb", mx.nd.array([3, 7, 5]))
    np.testing.assert_array_equal(out.indices.asnumpy(), [3, 5, 7])
    dense = ps.pull_dense("emb").asnumpy()
    np.testing.assert_array_equal(dense[3], 1.0)
    np.testing.assert_array_equal(dense[7], 2.0)
    np.testing.assert_array_equal(dense[5], 0.0)


def test_ps_duplicate_rows_aggregate():
    ps = SparsePS()
    ps.init("t", mx.nd.zeros((6, 1)))
    ps.push("t", _rsp([[1], [2], [4]], [2, 2, 5], (6, 1)))
    dense = ps.pull_dense("t").asnumpy()
    assert dense[2, 0] == 3.0  # merged duplicates (reference merge buffer)
    assert dense[5, 0] == 4.0


def test_ps_server_side_sgd_lazy():
    # optimizer runs server-side on touched rows ONLY (lazy update)
    ps = SparsePS()
    ps.init("w", mx.nd.array(np.ones((8, 2), np.float32)))
    ps.set_optimizer(mx.optimizer.SGD(learning_rate=0.5, rescale_grad=1.0))
    ps.push("w", _rsp([[2, 2]], [1], (8, 2)))
    dense = ps.pull_dense("w").asnumpy()
    np.testing.assert_allclose(dense[1], 0.0)   # 1 - 0.5*2
    np.testing.assert_allclose(dense[0], 1.0)   # untouched rows unchanged
    np.testing.assert_allclose(dense[7], 1.0)


def test_ps_server_side_adagrad_state_per_row():
    # adaptive optimizer state must persist per row across pushes
    ps = SparsePS()
    ps.init("w", mx.nd.zeros((4, 1)))
    ps.set_optimizer(mx.optimizer.AdaGrad(learning_rate=1.0, eps=1e-8))
    g = _rsp([[1.0]], [2], (4, 1))
    ps.push("w", g)
    after1 = ps.pull_dense("w").asnumpy()[2, 0]
    ps.push("w", g)
    after2 = ps.pull_dense("w").asnumpy()[2, 0]
    # adagrad: first step ≈ -1.0, second smaller (state accumulated)
    np.testing.assert_allclose(after1, -1.0, rtol=1e-4)
    assert abs(after2 - after1) < 1.0  # second step shrank
    assert abs(after2 - after1) > 0.1
    # rows never pushed keep zero state and value
    assert ps.pull_dense("w").asnumpy()[0, 0] == 0.0


def test_dist_kvstore_routes_sparse_keys():
    kv = mx.kv.create("dist_tpu_sync")
    kv.init("emb", cast_storage(mx.nd.zeros((12, 3)), "row_sparse"))
    kv.init(0, mx.nd.ones((4,)))  # dense key still works alongside
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0, rescale_grad=1.0))
    kv.push("emb", _rsp([[1, 1, 1]], [4], (12, 3)))
    out = kv.row_sparse_pull("emb", row_ids=mx.nd.array([4, 6]))
    np.testing.assert_allclose(out.data.asnumpy()[0], -1.0)  # sgd applied
    np.testing.assert_allclose(out.data.asnumpy()[1], 0.0)
    # dense pull of the sparse table
    dense = mx.nd.zeros((12, 3))
    kv.pull("emb", dense)
    np.testing.assert_allclose(dense.asnumpy()[4], -1.0)


def test_dist_push_aggregates_replicas_before_update():
    # two replica grads must produce ONE stateful-optimizer step on the
    # merged grad (reference aggregate-then-update), not two
    kv = mx.kv.create("dist_tpu_sync")
    kv.init("w", cast_storage(mx.nd.zeros((4, 1)), "row_sparse"))
    kv.set_optimizer(mx.optimizer.AdaGrad(learning_rate=1.0, eps=1e-8))
    g1 = _rsp([[0.5]], [2], (4, 1))
    g2 = _rsp([[0.5]], [2], (4, 1))
    kv.push("w", [g1, g2])
    dense = mx.nd.zeros((4, 1))
    kv.pull("w", dense)
    # merged grad 1.0 → one adagrad step of -1.0 (two 0.5-steps ≈ -1.71)
    np.testing.assert_allclose(dense.asnumpy()[2, 0], -1.0, rtol=1e-4)


def test_dist_sparse_list_key_forms():
    kv = mx.kv.create("dist_tpu_sync")
    kv.init(["emb"], [cast_storage(mx.nd.zeros((6, 2)), "row_sparse")])
    kv.push(["emb"], [_rsp([[1, 1]], [3], (6, 2))])
    out = mx.nd.zeros((6, 2))
    kv.pull(["emb"], [out])
    np.testing.assert_allclose(out.asnumpy()[3], 1.0)
    # per-out row_ids honored
    o1 = cast_storage(mx.nd.zeros((6, 2)), "row_sparse")
    o2 = cast_storage(mx.nd.zeros((6, 2)), "row_sparse")
    kv.row_sparse_pull("emb", out=[o1, o2],
                       row_ids=[mx.nd.array([3]), mx.nd.array([0, 3])])
    assert o1.indices.asnumpy().tolist() == [3]
    assert o2.indices.asnumpy().tolist() == [0, 3]


def test_ps_multi_precision_master_weights_init_from_rows():
    # first-touch state init runs create_state on the CURRENT row values:
    # an fp32 master-weight leaf must start at the row values, not zeros
    import ml_dtypes
    ps = SparsePS()
    table = np.full((4, 2), 2.0, np.float32).astype(ml_dtypes.bfloat16)
    ps.init("w", mx.nd.array(table))
    ps.set_optimizer(mx.optimizer.SGD(learning_rate=0.25, rescale_grad=1.0,
                                      multi_precision=True))
    g = RowSparseNDArray(mx.nd.array(np.ones((1, 2), np.float32)
                                     .astype(ml_dtypes.bfloat16)),
                         mx.nd.array([1]), (4, 2))
    ps.push("w", g)
    dense = ps.pull_dense("w").asnumpy().astype(np.float32)
    # master starts at 2.0 → 2.0 - 0.25*1 = 1.75 (zero master gives -0.25)
    np.testing.assert_allclose(dense[1], 1.75)
    np.testing.assert_allclose(dense[0], 2.0)


def test_ps_set_optimizer_resets_state():
    ps = SparsePS()
    ps.init("w", mx.nd.zeros((3, 1)))
    ps.set_optimizer(mx.optimizer.SGD(learning_rate=1.0, momentum=0.9,
                                      rescale_grad=1.0))
    g = _rsp([[1.0]], [0], (3, 1))
    ps.push("w", g)
    ps.push("w", g)  # momentum now non-zero for row 0
    ps.set_optimizer(mx.optimizer.AdaGrad(learning_rate=1.0, eps=1e-8))
    ps.push("w", g)
    tbl = ps._tables["w"]
    # adagrad history after ONE push must be g^2, not stale sgd momentum
    np.testing.assert_allclose(tbl.state_leaves[0][0], 1.0, rtol=1e-6)


def test_dist_pull_sparse_out_contract():
    kv = mx.kv.create("dist_tpu_sync")
    kv.init("e", cast_storage(mx.nd.ones((4, 2)), "row_sparse"))
    sparse_out = cast_storage(mx.nd.zeros((4, 2)), "row_sparse")
    kv.pull("e", sparse_out)  # ignore_sparse default: skipped, no crash
    with pytest.raises(MXNetError, match="row_sparse_pull"):
        kv.pull("e", sparse_out, ignore_sparse=False)


def test_ps_errors():
    ps = SparsePS()
    with pytest.raises(MXNetError, match="not initialized"):
        ps.push("nope", _rsp([[1]], [0], (2, 1)))
    ps.init("k", mx.nd.zeros((2, 1)))
    with pytest.raises(MXNetError, match="already"):
        ps.init("k", mx.nd.zeros((2, 1)))


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_fm_example_trains():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "examples", "sparse"))
    try:
        import factorization_machine as fm
    finally:
        sys.path.pop(0)
    result, losses = fm.run(num_features=2000, batches=60, batch_size=128,
                            nnz=10, lr=0.2, log=False)
    assert result["loss_last"] < result["loss_first"], losses[:3]
    assert result["value"] > 0  # samples/sec reported