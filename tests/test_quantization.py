"""INT8 quantization (ops/quantization.py + contrib/quantization.py).

Reference: src/operator/quantization/ + python/mxnet/contrib/quantization.py
(SURVEY N11/P19) — op-level round-trip/matmul accuracy, KL calibration, and
quantize_net end-to-end accuracy on an MLP and a small CNN.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.gluon import nn


def test_quantize_dequantize_roundtrip():
    x = np.random.RandomState(0).randn(64, 32).astype(np.float32) * 3
    q, mn, mxr = nd.contrib.quantize_v2(nd.array(x))
    assert q.asnumpy().dtype == np.int8
    real = max(abs(x.min()), abs(x.max()))
    np.testing.assert_allclose(float(mxr.asnumpy()), real, rtol=1e-6)
    back = nd.contrib.dequantize(q, mn, mxr).asnumpy()
    # max error is half a quantization step
    assert np.abs(back - x).max() <= real / 127 * 0.5 + 1e-6


def test_quantize_with_calib_range_clips():
    x = nd.array(np.array([[-10.0, -1.0, 0.5, 9.0]], np.float32))
    q, mn, mxr = nd.contrib.quantize_v2(x, min_calib_range=-2.0,
                                        max_calib_range=2.0)
    qa = q.asnumpy()
    assert qa[0, 0] == -127 and qa[0, 3] == 127      # clipped
    np.testing.assert_allclose(float(mxr.asnumpy()), 2.0)


def test_quantized_fully_connected_accuracy():
    r = np.random.RandomState(1)
    x = r.randn(16, 32).astype(np.float32)
    w = r.randn(8, 32).astype(np.float32) * 0.5
    qx, xmin, xmax = nd.contrib.quantize_v2(nd.array(x))
    qw, wmin, wmax = nd.contrib.quantize_v2(nd.array(w))
    out32, omin, omax = nd.contrib.quantized_fully_connected(
        qx, qw, xmin, xmax, wmin, wmax, num_hidden=8)
    assert out32.asnumpy().dtype == np.int32
    y = nd.contrib.dequantize(out32, omin, omax).asnumpy()
    ref = x @ w.T
    # int8 matmul keeps ~1% relative error at this K
    assert np.abs(y - ref).max() / np.abs(ref).max() < 0.02


def test_requantize_int32_to_int8():
    r = np.random.RandomState(2)
    x = r.randn(8, 16).astype(np.float32)
    w = r.randn(4, 16).astype(np.float32)
    qx, xmin, xmax = nd.contrib.quantize_v2(nd.array(x))
    qw, wmin, wmax = nd.contrib.quantize_v2(nd.array(w))
    out32, omin, omax = nd.contrib.quantized_fully_connected(
        qx, qw, xmin, xmax, wmin, wmax)
    q8, nmin, nmax = nd.contrib.requantize(out32, omin, omax)
    assert q8.asnumpy().dtype == np.int8
    y = nd.contrib.dequantize(q8, nmin, nmax).asnumpy()
    ref = x @ w.T
    assert np.abs(y - ref).max() / np.abs(ref).max() < 0.03


def test_kl_threshold_clips_outliers():
    """A gaussian bulk + one huge outlier: the KL-optimal threshold should
    sit near the bulk, well below the outlier."""
    r = np.random.RandomState(3)
    vals = np.concatenate([r.randn(100000).astype(np.float32),
                           np.array([50.0], np.float32)])
    st = qz._histogram_collect(None, vals)
    t = qz.optimal_threshold_kl(st["hist"], st["width"])
    assert t < 25.0                      # not fooled by the outlier
    assert t > 2.0                       # covers the bulk


def _mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"))
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(10))
    return net


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
def test_quantize_net_mlp_accuracy(calib_mode):
    mx.random.seed(4)
    net = _mlp()
    net.initialize(mx.init.Xavier())
    r = np.random.RandomState(5)
    x = nd.array(r.randn(32, 16).astype(np.float32))
    ref = net(x).asnumpy()
    calib = [nd.array(r.randn(32, 16).astype(np.float32)) for _ in range(4)]
    calib.append(x)
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode=calib_mode)
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max()
    # entropy calibration deliberately clips distribution tails (KL picks
    # resolution over range), so its worst-case elementwise error compounds
    # across layers — judge it on mean error; naive keeps tight max error
    if calib_mode == "entropy":
        assert np.abs(out - ref).mean() / scale < 0.03
        assert np.abs(out - ref).max() / scale < 0.30
    else:
        assert np.abs(out - ref).max() / scale < 0.05, calib_mode


def test_quantize_net_excludes_layers():
    net = _mlp()
    net.initialize()
    x = nd.array(np.random.RandomState(6).randn(4, 8).astype(np.float32))
    net(x)
    qz.quantize_net(net, calib_data=[x], calib_mode="naive",
                    exclude_layers_match=["2"])   # keep the head in float
    kinds = [type(c).__name__ for c in net._children.values()]
    assert kinds.count("QuantizedDense") == 2
    assert kinds.count("Dense") == 1


def test_quantize_net_on_hybridized_net():
    """A hybridized float net must calibrate through the imperative path
    (hooks) and drop its stale CachedOp trace after conversion."""
    mx.random.seed(9)
    net = _mlp()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.array(np.random.RandomState(9).randn(8, 16).astype(np.float32))
    ref = net(x).asnumpy()          # builds the cached op
    qz.quantize_net(net, calib_data=[x], calib_mode="naive")
    out = net(x).asnumpy()          # must not hit the stale trace
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.05


def test_quantize_net_cnn():
    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"))
    net.add(nn.Conv2D(16, kernel_size=3, strides=2, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(8).randn(2, 3, 16, 16)
                 .astype(np.float32))
    ref = net(x).asnumpy()
    qz.quantize_net(net, calib_data=[x], calib_mode="naive")
    out = net(x).asnumpy()
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.06


def test_optimize_for_int8_pass_rewrites_fc():
    """sym.optimize_for('INT8') is a REAL graph rewrite (reference
    quantize_graph_pass.cc through the subgraph-backend seam): FC nodes
    become quantize -> int8 FC -> dequantize (+ float bias), agree with
    the float graph within int8 tolerance, and respect exclusions."""
    import mxnet_tpu as mx
    from mxnet_tpu import symbol as S
    r = np.random.RandomState(0)
    x = S.var("data")
    w1, b1 = S.var("w1"), S.var("b1")
    w2 = S.var("w2")
    h = S.relu(S.FullyConnected(x, w1, b1, num_hidden=8, name="fc1"))
    out = S.FullyConnected(h, w2, None, num_hidden=3, no_bias=True,
                           name="fc2")

    args = {"data": mx.nd.array(r.randn(4, 6).astype(np.float32)),
            "w1": mx.nd.array((r.randn(8, 6) * 0.4).astype(np.float32)),
            "b1": mx.nd.array(r.randn(8).astype(np.float32) * 0.1),
            "w2": mx.nd.array((r.randn(3, 8) * 0.4).astype(np.float32))}

    ref = out.bind(mx.cpu(), dict(args)).forward()[0].asnumpy()

    q = out.optimize_for("INT8")
    assert q.attr("__int8_quantized_nodes__") == "2"
    got = q.bind(mx.cpu(), dict(args)).forward()[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=0.1, atol=0.05)
    # int8 path really runs: exact float equality would be a miracle
    assert not np.allclose(got, ref, rtol=1e-7, atol=1e-8)

    # exclusion keeps fc1 float: only one node rewritten
    q1 = out.optimize_for("INT8", excluded_sym_names=["fc1"])
    assert q1.attr("__int8_quantized_nodes__") == "1"
    names = " ".join(s._name for s in q1._walk())
    assert "fc2_quantized" in names and "fc1_quantized" not in names

    # calibrated ranges ride in as static quantize attrs
    q2 = out.optimize_for("INT8", calib_ranges={"fc1": (-3.0, 3.0)})
    qnode = [s for s in q2._walk() if s._name == "fc1_qdata"]
    assert qnode and qnode[0].attr("min_calib_range") == -3.0
