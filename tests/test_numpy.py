"""mx.np semantics tests (reference tests/python/unittest/test_numpy_op.py
/ test_numpy_ndarray.py patterns, P3)."""

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

np = mx.np
npx = mx.npx


def test_array_roundtrip_and_zero_dim():
    a = np.array(3.5)
    assert a.shape == ()
    assert float(a.asnumpy()) == 3.5
    b = np.array([[1, 2], [3, 4]], dtype=np.float32)
    onp.testing.assert_array_equal(b.asnumpy(), [[1, 2], [3, 4]])


@pytest.mark.parametrize("name,args", [
    ("zeros", ((2, 3),)), ("ones", ((4,),)), ("eye", (3,)),
    ("arange", (5,)), ("linspace", (0.0, 1.0, 5)),
])
def test_creation_matches_numpy(name, args):
    got = getattr(np, name)(*args).asnumpy()
    want = getattr(onp, name)(*args)
    onp.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.parametrize("name", ["exp", "log1p", "sqrt", "tanh", "floor",
                                  "sign", "square"])
def test_unary_matches_numpy(name, seeded):
    x = onp.abs(onp.random.RandomState(0).randn(3, 4)).astype(onp.float32)
    got = getattr(np, name)(np.array(x)).asnumpy()
    onp.testing.assert_allclose(got, getattr(onp, name)(x), rtol=1e-5)


def test_broadcasting_and_promotion():
    a = np.array(onp.ones((3, 1), onp.float32))
    b = np.array(onp.arange(4, dtype=onp.float32))
    out = np.add(a, b)
    assert out.shape == (3, 4)
    # int + float promotes to float (numpy semantics via jnp)
    c = np.array(onp.array([1, 2], onp.int32))
    d = np.array(onp.array([0.5, 0.5], onp.float32))
    assert onp.dtype(np.add(c, d).dtype).kind == "f"


def test_reductions_and_axis_tuples(seeded):
    x = onp.random.RandomState(1).randn(2, 3, 4).astype(onp.float32)
    a = np.array(x)
    onp.testing.assert_allclose(np.sum(a, axis=(0, 2)).asnumpy(),
                                x.sum(axis=(0, 2)), rtol=1e-5)
    onp.testing.assert_allclose(np.mean(a).asnumpy(), x.mean(), rtol=1e-5)
    assert np.argmax(a).asnumpy() == x.argmax()


def test_einsum_matmul(seeded):
    r = onp.random.RandomState(2)
    A = r.randn(3, 4).astype(onp.float32)
    B = r.randn(4, 5).astype(onp.float32)
    onp.testing.assert_allclose(np.matmul(np.array(A), np.array(B)).asnumpy(),
                                A @ B, rtol=1e-5)
    onp.testing.assert_allclose(
        np.einsum("ij,jk->ik", np.array(A), np.array(B)).asnumpy(),
        A @ B, rtol=1e-5)


def test_linalg_namespace(seeded):
    r = onp.random.RandomState(3)
    M = r.randn(4, 4).astype(onp.float32)
    M = M @ M.T + 4 * onp.eye(4, dtype=onp.float32)  # SPD
    a = np.array(M)
    onp.testing.assert_allclose(np.linalg.det(a).asnumpy(),
                                onp.linalg.det(M), rtol=1e-3)
    onp.testing.assert_allclose(
        (np.linalg.inv(a).asnumpy() @ M), onp.eye(4), atol=1e-4)
    L = np.linalg.cholesky(a).asnumpy()
    onp.testing.assert_allclose(L @ L.T, M, rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(np.linalg.norm(a).asnumpy(),
                                onp.linalg.norm(M), rtol=1e-5)
    q, rr = np.linalg.qr(a)
    onp.testing.assert_allclose(q.asnumpy() @ rr.asnumpy(), M, rtol=1e-4,
                                atol=1e-4)


def test_random_namespace_shapes_and_stats():
    mx.random.seed(0)
    u = np.random.uniform(0.0, 1.0, size=(2000,))
    assert u.shape == (2000,)
    assert 0.0 <= float(u.asnumpy().min()) and float(u.asnumpy().max()) <= 1.0
    assert abs(float(u.asnumpy().mean()) - 0.5) < 0.05
    n = np.random.normal(2.0, 0.5, size=(2000,))
    assert abs(float(n.asnumpy().mean()) - 2.0) < 0.1
    r = np.random.randint(0, 7, size=(100,))
    vals = r.asnumpy()
    assert vals.min() >= 0 and vals.max() < 7
    # seeded reproducibility
    mx.random.seed(42)
    a = np.random.normal(size=(5,)).asnumpy()
    mx.random.seed(42)
    b = np.random.normal(size=(5,)).asnumpy()
    onp.testing.assert_array_equal(a, b)


def test_autograd_through_np_ops(seeded):
    x = np.array(onp.array([1.0, 2.0, 3.0], onp.float32))
    x.attach_grad()
    with autograd.record():
        y = np.sum(np.square(x) * 2.0)
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [4.0, 8.0, 12.0])


def test_np_indexing_and_manip(seeded):
    x = onp.arange(24, dtype=onp.float32).reshape(2, 3, 4)
    a = np.array(x)
    onp.testing.assert_array_equal(
        np.transpose(a, (2, 0, 1)).asnumpy(), x.transpose(2, 0, 1))
    onp.testing.assert_array_equal(
        np.concatenate([a, a], axis=1).asnumpy(),
        onp.concatenate([x, x], axis=1))
    onp.testing.assert_array_equal(np.where(a > 10, a, 0 * a).asnumpy(),
                                   onp.where(x > 10, x, 0))
    onp.testing.assert_array_equal(np.take(a.reshape(-1),
                                           np.array([0, 5, 7])).asnumpy(),
                                   x.reshape(-1)[[0, 5, 7]])


def test_npx_set_np_roundtrip():
    assert not mx.util.is_np_array()
    npx.set_np()
    try:
        assert mx.util.is_np_array()
    finally:
        npx.reset_np()
    assert not mx.util.is_np_array()
