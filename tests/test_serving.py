"""Serving engine tests (ISSUE 6): paged KV cache + continuous batching.

The load-bearing assertions:
- incremental paged decode is TOKEN-IDENTICAL to the full re-encode
  forward, across batch sizes, block sizes, and early-EOS patterns;
- block reuse (free -> realloc) cannot leak stale KV into a new sequence;
- the steady-state decode loop holds the no-retrace invariant while
  sequences of different lengths join and leave the batch;
- SLA deadlines evict, preemption-by-recompute converges, telemetry SLOs
  populate.

One shared llama engine config keeps the jit-compile count low — the
jitted decode/prefill entries are module-level in serving.models, so
engines with equal config + shapes share executables.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.analysis.runtime import no_retrace
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo import llama, transformer
from mxnet_tpu.serving.cache import BlockAllocator, CacheOOMError

EOS = 2
BOS = 1


@pytest.fixture(scope="module")
def llama_net():
    mx.random.seed(7)
    np.random.seed(7)
    net = llama.llama_model("llama_tiny", vocab_size=101)
    net.initialize(mx.initializer.Normal(0.05))
    net(mx.nd.array(np.zeros((1, 4), np.int32)))     # finish deferred init
    return net


@pytest.fixture(scope="module")
def tf_net():
    mx.random.seed(11)
    np.random.seed(11)
    m = transformer.transformer_model("transformer_test", vocab_size=50,
                                      max_length=32, dropout=0.0)
    m.initialize(mx.initializer.Normal(0.3))
    return m


def _llama_engine(net, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("block_tokens", 4)
    kw.setdefault("max_seq", 64)
    kw.setdefault("prefill_tokens", 16)
    return serving.ServingEngine(net, eos_id=EOS, **kw)


def _ref_greedy_llama(net, prompt, max_new, eos=EOS, pad_to=32):
    """Oracle: full re-encode greedy decode on a fixed (1, pad_to) buffer
    (causality hides the tail — one compiled shape)."""
    assert len(prompt) + max_new <= pad_to
    buf = np.zeros((1, pad_to), np.int32)
    buf[0, :len(prompt)] = prompt
    n, out = len(prompt), []
    for _ in range(max_new):
        logits = net(mx.nd.array(buf)).asnumpy()
        nxt = int(logits[0, n - 1].argmax())
        out.append(nxt)
        if nxt == eos:
            break
        buf[0, n] = nxt
        n += 1
    return out


# -- allocator / cache units (no jax) ---------------------------------------

def test_block_allocator_alloc_free_oom():
    a = BlockAllocator(6)                 # blocks 1..5 usable
    assert a.free_blocks == 5
    got = a.alloc(3)
    assert len(got) == 3 and a.free_blocks == 2
    with pytest.raises(CacheOOMError):
        a.alloc(3)
    a.free(got)
    assert a.free_blocks == 5
    with pytest.raises(MXNetError, match="double free"):
        a.free(got[:1])                   # already on the free list


def test_block_allocator_scratch_reserved():
    a = BlockAllocator(4)
    taken = a.alloc(3)
    assert 0 not in taken                 # scratch never issued
    with pytest.raises(MXNetError, match="invalid block"):
        a.free([0])


def test_paged_cache_admit_release_reuse():
    c = serving.PagedKVCache(max_batch=2, max_blocks_per_seq=4,
                             block_tokens=4, num_blocks=9)
    blocks = c.admit(0, 7)                # ceil(7/4) = 2 blocks
    assert len(blocks) == 2 and c.free_blocks == 6
    c.ctx_len[0] = 7
    c.ensure_capacity(0)                  # pos 7 inside block 1: no alloc
    assert c.free_blocks == 6
    c.ctx_len[0] = 8
    c.ensure_capacity(0)                  # pos 8 opens block 2
    assert c.free_blocks == 5
    freed = c.release(0)
    assert len(freed) == 3 and c.free_blocks == 8
    assert (c.tables[0] == 0).all() and c.ctx_len[0] == 0
    reused = c.admit(1, 4)                # LIFO: the freed block comes back
    assert reused[0] in freed


def test_paged_cache_prefix_share_refcount_evict():
    """Prefix-cache bookkeeping without jax: registration, full-block
    sharing with refcounts, COW pair production, LRU eviction of
    refcount-0 cached blocks under pressure."""
    c = serving.PagedKVCache(max_batch=3, max_blocks_per_seq=4,
                             block_tokens=4, num_blocks=6,
                             prefix_cache=True)
    prompt = list(range(10, 20))          # 10 tokens = 2 full blocks + 2
    c.admit(0, 10, prompt)
    c.register_prefix(0, prompt)
    assert c.prefix_hits == 0             # cold admission
    # a second identical-prefix admission shares the 2 full blocks
    h0 = c.prefix_hit_tokens
    c.admit(1, 10, prompt)
    assert c.prefix_hits == 1 and c.prefix_hit_tokens - h0 == 8
    assert c.tables[1][0] == c.tables[0][0]
    assert c.tables[1][1] == c.tables[0][1]
    # COW: slot 1 about to write inside the SHARED second block
    pairs = c.prepare_write(1, 5)
    assert len(pairs) == 1 and pairs[0][0] == c.tables[0][1]
    assert c.tables[1][1] == pairs[0][1] != c.tables[0][1]
    assert c.cow_copies == 1
    # sole-owner writes need no copy
    assert c.prepare_write(0, 5) == []
    # release both: registered blocks park on the cached LRU, not free
    c.release(0)
    c.release(1)
    assert c.cached_blocks == 2
    # pressure: a big admission evicts cached blocks LRU-first
    c.admit(2, 16)                        # 4 blocks > 3 free
    assert c.evictions == 1 and c.cached_blocks == 1
    c.release(2)
    # the evicted deeper key is gone; the surviving first block still hits
    _blocks, toks = c.match_prefix(prompt)
    assert toks == 4


# -- llama: token identity ---------------------------------------------------

def test_llama_paged_decode_token_identical(llama_net):
    """Mixed-length prompts through the continuous batch == per-request
    full re-encode greedy decode, token for token."""
    eng = _llama_engine(llama_net)
    prompts = [[5, 9, 11], [7, 8, 9, 10, 3, 4], [40, 41], [12] * 9]
    outs = eng.generate(prompts, max_new_tokens=12)
    for p, got in zip(prompts, outs):
        assert got == _ref_greedy_llama(llama_net, p, 12), p


@pytest.mark.parametrize("block_tokens", [2, 8])
def test_llama_block_sizes_token_identical(llama_net, block_tokens):
    eng = _llama_engine(llama_net, block_tokens=block_tokens)
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
    outs = eng.generate(prompts, max_new_tokens=9)
    for p, got in zip(prompts, outs):
        assert got == _ref_greedy_llama(llama_net, p, 9), p


def test_llama_batch_size_independent(llama_net):
    """The same request decodes identically alone and in a full batch
    (B_max=2 vs 4 engines) — slot count is not observable."""
    p = [6, 28, 3, 17]
    solo = _llama_engine(llama_net, max_batch=2).generate(
        [p], max_new_tokens=10)[0]
    crowd = _llama_engine(llama_net).generate(
        [p, [1, 2, 3], [50] * 7, [30, 31]], max_new_tokens=10)[0]
    assert solo == crowd == _ref_greedy_llama(llama_net, p, 10)


def test_llama_early_eos_and_backfill(llama_net):
    """Sequences that stop early (engineered EOS) free their slots for
    queued requests; every request still matches its oracle."""
    prompts = [[5, 9, 11], [7, 8, 9, 10, 3, 4], [40, 41], [12] * 9,
               [33, 2, 7], [64, 65, 66, 67], [90], [13, 37]]
    refs = [_ref_greedy_llama(llama_net, p, 10, eos=-1) for p in prompts]
    # eos = what request 0 emits 3rd: its row ends early, others vary
    eos = refs[0][2]
    net_refs = [_ref_greedy_llama(llama_net, p, 10, eos=eos)
                for p in prompts]
    eng = serving.ServingEngine(llama_net, eos_id=eos, max_batch=3,
                                block_tokens=4, max_seq=64,
                                prefill_tokens=16)
    outs = eng.generate(prompts, max_new_tokens=10)   # 8 reqs, 3 slots
    assert outs == net_refs
    assert any(o[-1] == eos and len(o) < 10 for o in outs)  # early stop real


def test_llama_block_reuse_no_stale_kv(llama_net):
    """free -> realloc cannot leak stale KV: a request decoded over
    just-freed (never zeroed) blocks matches a fresh-engine decode.
    The LIFO allocator guarantees the probe gets the churned blocks."""
    eng = _llama_engine(llama_net)
    churn = eng.generate([[23, 24, 25, 26, 27, 28], [71, 72, 73]],
                         max_new_tokens=14)
    probe = [44, 45, 46, 47]
    probe_blocks = None
    orig_admit = eng.cache.admit

    def spying_admit(slot, n):
        nonlocal probe_blocks
        probe_blocks = orig_admit(slot, n)
        return probe_blocks

    eng.cache.admit = spying_admit
    reused = eng.generate([probe], max_new_tokens=14)[0]
    fresh = _llama_engine(llama_net).generate([probe],
                                              max_new_tokens=14)[0]
    assert reused == fresh == _ref_greedy_llama(llama_net, probe, 14)
    assert churn and probe_blocks  # pool churned, probe really realloc'd


# -- no-retrace invariant ----------------------------------------------------

def test_no_retrace_mixed_lengths(llama_net):
    """Acceptance: the steady-state decode loop compiles NOTHING while
    sequences of differing lengths join and leave the batch."""
    eng = _llama_engine(llama_net)
    eng.generate([[5, 6, 7], [8, 9, 10, 11, 12]], max_new_tokens=6)  # warm
    with no_retrace():
        outs = eng.generate(
            [[1], [2, 3], [4, 5, 6, 7], [9] * 11, [10, 11], [12] * 7],
            max_new_tokens=9)
    assert len(outs) == 6 and all(len(o) == 9 for o in outs)


# -- scheduling: deadlines, preemption, async -------------------------------

def test_sla_deadline_evicts(llama_net):
    eng = _llama_engine(llama_net)
    before = telemetry.counter(
        "mxnet_serving_requests_evicted_total").value
    h = eng.submit([5, 6, 7], max_new_tokens=8, deadline_s=1e-9)
    import time
    time.sleep(0.01)
    eng.step()
    with pytest.raises(serving.RequestDeadlineExceeded, match="SLA"):
        h.result(timeout=5)
    after = telemetry.counter("mxnet_serving_requests_evicted_total").value
    assert after == before + 1


def test_reject_oversized(llama_net):
    eng = _llama_engine(llama_net)
    h = eng.submit(list(range(3, 20)), max_new_tokens=4)   # > prefill cap
    with pytest.raises(serving.ServingError, match="cannot fit"):
        h.result(timeout=5)
    h2 = eng.submit([5, 6], max_new_tokens=63)             # > max_seq
    with pytest.raises(serving.ServingError, match="cannot fit"):
        h2.result(timeout=5)


def test_preemption_recompute_converges(llama_net):
    """An oversubscribed pool (too small for both sequences' full length)
    forces preemption; the preempted request re-prefills with
    prompt+generated and still matches its oracle exactly."""
    before = telemetry.counter(
        "mxnet_serving_requests_preempted_total").value
    # eos 255 is never emitted (vocab 101): both sequences must run their
    # full 10 tokens, oversubscribing the 4-block pool (7 blocks demand)
    eng = serving.ServingEngine(llama_net, eos_id=255, max_batch=2,
                                block_tokens=4, max_seq=16,
                                prefill_tokens=16, num_blocks=5)
    prompts = [[5, 9, 11, 13], [7, 8, 9, 10]]
    outs = eng.generate(prompts, max_new_tokens=10)
    for p, got in zip(prompts, outs):
        assert got == _ref_greedy_llama(llama_net, p, 10, eos=-1), p
    after = telemetry.counter(
        "mxnet_serving_requests_preempted_total").value
    assert after > before                 # pressure actually preempted


def test_async_background_thread(llama_net):
    eng = _llama_engine(llama_net)
    eng.start()
    try:
        hs = [eng.submit(p, max_new_tokens=7)
              for p in ([15, 16], [17, 18, 19], [20])]
        results = [h.result(timeout=60) for h in hs]
    finally:
        eng.stop()
    for p, got in zip([[15, 16], [17, 18, 19], [20]], results):
        assert got == _ref_greedy_llama(llama_net, p, 7)


def test_stop_fails_pending_requests(llama_net):
    """stop() must error abandoned handles promptly — not leave callers
    blocked on the full resilience-Deadline timeout."""
    eng = _llama_engine(llama_net)
    h = eng.submit([5, 6, 7], max_new_tokens=8)   # queued, loop never ran
    eng.stop()
    with pytest.raises(serving.ServingError, match="abandoned"):
        h.result(timeout=5)
    assert h.stats()["e2e_s"] is not None         # terminal -> finish_t set
    assert eng.cache.free_blocks == eng.cache.allocator.num_blocks - 1
    late = eng.submit([8, 9], max_new_tokens=4)   # stop() is terminal
    with pytest.raises(serving.ServingError, match="stopped"):
        late.result(timeout=5)


def test_static_policy_matches_tokens(llama_net):
    """policy='static' (the bench baseline) produces the same tokens —
    only the scheduling differs."""
    prompts = [[5, 9, 11], [7, 8, 9], [40, 41], [12, 13], [1, 2, 3]]
    cont = _llama_engine(llama_net).generate(prompts, max_new_tokens=6)
    stat = _llama_engine(llama_net, policy="static").generate(
        prompts, max_new_tokens=6)
    assert cont == stat


# -- engine hardening (ISSUE 13 satellite) ----------------------------------

def test_engine_load_atomic_triple(llama_net):
    """load() returns one consistent (queue_depth, active_slots,
    free_blocks) snapshot under the scheduler lock — the replica-ack /
    least-loaded dispatch signal."""
    eng = _llama_engine(llama_net)
    total_free = eng.cache.allocator.num_blocks - 1
    assert eng.load() == (0, 0, total_free)
    assert eng.free_slots == eng.max_batch
    h = eng.submit([5, 6], max_new_tokens=4)
    assert eng.load() == (1, 0, total_free)      # queued, nothing admitted
    eng.drain()
    assert h.result(timeout=5)
    assert eng.load() == (0, 0, total_free)


def test_submit_blown_deadline_fails_at_submit(llama_net):
    """A non-positive remaining budget (a router forwarding an already
    blown deadline) fails the handle at submit — no queue round-trip,
    no prefill."""
    eng = _llama_engine(llama_net)
    p0 = telemetry.counter("mxnet_serving_prefills_total").value
    h = eng.submit([5, 6, 7], max_new_tokens=4, deadline_s=-0.5)
    assert h.ready()
    with pytest.raises(serving.RequestDeadlineExceeded):
        h.result(timeout=5)
    assert telemetry.counter(
        "mxnet_serving_prefills_total").value == p0


def test_deadline_lapsing_during_admission_skips_prefill(llama_net):
    """A request whose deadline lapses while EARLIER admissions in the
    same scheduler iteration burn prefills is evicted at its own
    admission turn — it must not pay a prefill first."""
    eng = _llama_engine(llama_net)
    orig_prefill = eng.adapter.prefill
    calls = []

    def slow_prefill(slot, prompt, table_row):
        calls.append(slot)
        import time as _t
        _t.sleep(0.08)
        return orig_prefill(slot, prompt, table_row)

    eng.adapter.prefill = slow_prefill
    try:
        ha = eng.submit([5, 6], max_new_tokens=2)           # admits first
        hb = eng.submit([7, 8], max_new_tokens=2, deadline_s=0.03)
        p0 = telemetry.counter("mxnet_serving_prefills_total").value
        eng.step()      # admits A (80ms prefill) -> B's deadline lapses
        with pytest.raises(serving.RequestDeadlineExceeded):
            hb.result(timeout=5)
        assert telemetry.counter(
            "mxnet_serving_prefills_total").value == p0 + 1
        eng.drain()
        assert ha.result(timeout=5)
    finally:
        eng.adapter.prefill = orig_prefill


# -- prefix caching (ISSUE 15 tentpole) --------------------------------------

SYS12 = [30 + i for i in range(12)]       # 3 full blocks at T=4


def test_prefix_cache_hit_token_identical(llama_net):
    """Shared-system-prompt workload: prefix-cache-hit generations are
    bitwise-equal to cold-start, tail-only prefill computes fewer
    positions, and the hit/hit-token telemetry moves."""
    prompts = [SYS12 + [60 + i] for i in range(5)]
    cold = [_ref_greedy_llama(llama_net, p, 8) for p in prompts]
    h0 = telemetry.counter("mxnet_serving_prefix_hits_total").value
    p0 = telemetry.counter("mxnet_serving_prefill_positions_total").value
    eng = _llama_engine(llama_net, prefix_cache=True)
    outs = eng.generate(prompts, max_new_tokens=8)
    assert outs == cold
    assert eng.cache.prefix_hits == 4           # req 0 is the cold fill
    assert eng.cache.prefix_hit_tokens == 4 * len(SYS12)
    assert telemetry.counter(
        "mxnet_serving_prefix_hits_total").value - h0 == 4
    ppos = telemetry.counter(
        "mxnet_serving_prefill_positions_total").value - p0
    # 1 cold padded prefill + 4 one-block tail chunks << 5 cold prefills
    assert ppos == eng.adapter.prefill_tokens + 4 * eng.block_tokens
    assert ppos < 5 * eng.adapter.prefill_tokens


def test_prefix_cow_on_scratch_adjacent_block(llama_net):
    """Two CONCURRENT sequences with the same block-aligned prompt: the
    sharer's boundary chunk must write the last shared block (the one
    adjacent to the scratch-padded table tail) -> copy-on-write fires
    and both outputs stay bitwise-equal to the cold path.  A non-aligned
    duplicate (partial tail block) needs no COW: its tail starts at a
    block boundary in a private block."""
    p8 = [3, 1, 4, 1, 5, 9, 2, 6]               # 2 full blocks exactly
    cold = _ref_greedy_llama(llama_net, p8, 8)
    c0 = telemetry.counter("mxnet_serving_prefix_cow_total").value
    eng = _llama_engine(llama_net, prefix_cache=True)
    outs = eng.generate([p8, list(p8)], max_new_tokens=8)
    assert outs == [cold, cold]
    assert eng.cache.cow_copies >= 1
    assert telemetry.counter(
        "mxnet_serving_prefix_cow_total").value - c0 >= 1
    p9 = p8 + [7]                               # partial third block
    cold9 = _ref_greedy_llama(llama_net, p9, 8)
    eng2 = _llama_engine(llama_net, prefix_cache=True)
    outs2 = eng2.generate([p9, list(p9)], max_new_tokens=8)
    assert outs2 == [cold9, cold9]
    assert eng2.cache.cow_copies == 0 and eng2.cache.prefix_hits == 1


def test_prefix_preemption_of_shared_blocks(llama_net):
    """Cache-pressure corner: preempting a sequence whose blocks are
    SHARED (refcount > 1) frees only its private blocks; the preempted
    request recomputes and every output still matches the cold oracle."""
    before = telemetry.counter(
        "mxnet_serving_requests_preempted_total").value
    sysp = [40 + i for i in range(8)]           # 2 shared full blocks
    prompts = [sysp + [70], sysp + [71]]
    eng = serving.ServingEngine(llama_net, eos_id=255, max_batch=2,
                                block_tokens=4, max_seq=16,
                                prefill_tokens=16, num_blocks=6,
                                prefix_cache=True)
    outs = eng.generate(prompts, max_new_tokens=6)
    for p, got in zip(prompts, outs):
        assert got == _ref_greedy_llama(llama_net, p, 6, eos=-1), p
    after = telemetry.counter(
        "mxnet_serving_requests_preempted_total").value
    assert after > before                        # pressure really preempted
    assert eng.cache.prefix_hits >= 1            # sharing really happened


def test_prefix_eviction_races_readmission(llama_net):
    """Cache-pressure corner: an unrelated admission evicts the cached
    prefix between a request's first run and its resubmission — the
    resubmit takes the cold path and stays token-identical."""
    eng = serving.ServingEngine(llama_net, eos_id=EOS, max_batch=1,
                                block_tokens=4, max_seq=24,
                                prefill_tokens=16, num_blocks=6,
                                prefix_cache=True)
    pa = [5, 6, 7, 8, 9, 10, 11, 12]            # 2 registered full blocks
    ra = eng.generate([pa], max_new_tokens=4)[0]
    assert eng.cache.cached_blocks == 2
    pb = list(range(50, 66))                    # 16 tokens: 4 blocks
    eng.generate([pb], max_new_tokens=4)
    assert eng.cache.evictions >= 1             # the race: prefix evicted
    hits0 = eng.cache.prefix_hits
    rb = eng.generate([pa], max_new_tokens=4)[0]
    assert rb == ra == _ref_greedy_llama(llama_net, pa, 4)
    assert eng.cache.prefix_hits == hits0       # evicted: no hit, cold path


# -- speculative decoding (ISSUE 15 tentpole) --------------------------------

@pytest.fixture(scope="module")
def draft_net():
    """A DIVERGENT draft (same llama_tiny config — the module-level jits
    are shared — different seed): low acceptance, so the target-token
    fallback path is exercised on every few dispatches."""
    mx.random.seed(23)
    np.random.seed(23)
    net = llama.llama_model("llama_tiny", vocab_size=101)
    net.initialize(mx.initializer.Normal(0.05))
    net(mx.nd.array(np.zeros((1, 4), np.int32)))
    return net


def test_spec_decode_token_identical_mixed_batch(llama_net, draft_net):
    """Speculative greedy output is bitwise-equal to plain greedy across
    a mixed-length batch and across batch sizes."""
    prompts = [[5, 9, 11], [7, 8, 9, 10, 3, 4], [40, 41], [12] * 9]
    eng = _llama_engine(llama_net, draft_model=draft_net, spec_k=3)
    outs = eng.generate(prompts, max_new_tokens=12)
    for p, got in zip(prompts, outs):
        assert got == _ref_greedy_llama(llama_net, p, 12), p
    solo = _llama_engine(llama_net, max_batch=2, draft_model=draft_net,
                         spec_k=2).generate([prompts[0]],
                                            max_new_tokens=10)[0]
    assert solo == _ref_greedy_llama(llama_net, prompts[0], 10)


def test_spec_decode_early_eos(llama_net, draft_net):
    """EOS inside an accepted run truncates the emission mid-chunk;
    every sequence still matches its oracle exactly."""
    prompts = [[5, 9, 11], [7, 8, 9, 10, 3, 4], [40, 41], [12] * 9,
               [33, 2, 7], [90]]
    free = [_ref_greedy_llama(llama_net, p, 10, eos=-1) for p in prompts]
    eos = free[0][2]
    refs = [_ref_greedy_llama(llama_net, p, 10, eos=eos) for p in prompts]
    eng = serving.ServingEngine(llama_net, eos_id=eos, max_batch=3,
                                block_tokens=4, max_seq=64,
                                prefill_tokens=16,
                                draft_model=draft_net, spec_k=3)
    outs = eng.generate(prompts, max_new_tokens=10)
    assert outs == refs
    assert any(o[-1] == eos and len(o) < 10 for o in outs)


def test_spec_decode_preemption_token_identical(llama_net, draft_net):
    """Pool pressure with speculation armed: preemption-by-recompute
    still converges bit-identically (the spec chunk reserves multiple
    positions per slot, so pressure bites earlier)."""
    before = telemetry.counter(
        "mxnet_serving_requests_preempted_total").value
    eng = serving.ServingEngine(llama_net, eos_id=255, max_batch=2,
                                block_tokens=4, max_seq=16,
                                prefill_tokens=16, num_blocks=5,
                                draft_model=draft_net, spec_k=2)
    prompts = [[5, 9, 11, 13], [7, 8, 9, 10]]
    outs = eng.generate(prompts, max_new_tokens=10)
    for p, got in zip(prompts, outs):
        assert got == _ref_greedy_llama(llama_net, p, 10, eos=-1), p
    assert telemetry.counter(
        "mxnet_serving_requests_preempted_total").value > before


def test_spec_identical_draft_tokens_per_dispatch(llama_net):
    """An identical-weights draft accepts ~everything: generated tokens
    per target dispatch >= 1.5 (the serve-bench gate's mechanism) and
    the accepted-draft-length histogram populates."""
    telemetry.enable()
    try:
        t0 = telemetry.counter("mxnet_serving_tokens_total").value
        s0 = telemetry.counter("mxnet_serving_decode_steps_total").value
        hist = telemetry.REGISTRY.get("mxnet_serving_accepted_draft_tokens")
        hc0 = hist.count if hist is not None else 0
        eng = _llama_engine(llama_net, draft_model=llama_net, spec_k=3)
        outs = eng.generate([[5, 6, 7], [8, 9]], max_new_tokens=12)
        for p, got in zip([[5, 6, 7], [8, 9]], outs):
            assert got == _ref_greedy_llama(llama_net, p, 12), p
        toks = telemetry.counter("mxnet_serving_tokens_total").value - t0
        steps = telemetry.counter(
            "mxnet_serving_decode_steps_total").value - s0
        assert steps > 0 and toks / steps >= 1.5, (toks, steps)
        hist = telemetry.REGISTRY.get("mxnet_serving_accepted_draft_tokens")
        assert hist.count > hc0
    finally:
        if not telemetry.env_enabled():
            telemetry.disable()


def test_prefix_and_spec_no_retrace(llama_net, draft_net):
    """Acceptance: steady-state serving with BOTH features armed
    compiles nothing — cold prefills, tail chunks, draft steps and
    verify dispatches all hold their fixed shapes."""
    eng = _llama_engine(llama_net, prefix_cache=True,
                        draft_model=draft_net, spec_k=3)
    # warm every executable: cold prefill, a prefix-hit tail chunk,
    # draft steps, and the (B, K) verify
    eng.generate([SYS12 + [77], SYS12 + [78], [1, 2, 3]],
                 max_new_tokens=6)
    with no_retrace():
        outs = eng.generate(
            [SYS12 + [88], SYS12 + [89], [4, 5], [9] * 7],
            max_new_tokens=9)
    cold = [_ref_greedy_llama(llama_net, p, 9)
            for p in [SYS12 + [88], SYS12 + [89], [4, 5], [9] * 7]]
    assert outs == cold


# -- transformer (encoder-decoder) ------------------------------------------

def test_transformer_paged_decode_token_identical(tf_net):
    """Paged incremental MT decode == greedy_decode (the re-encode path)
    for every row, including a padded short source."""
    r = np.random.RandomState(0)
    src = r.randint(3, 50, (3, 8)).astype(np.int32)
    vls = [8, 6, 4]
    ref = transformer.greedy_decode(
        tf_net, mx.nd.array(src), BOS, EOS, max_len=12,
        src_valid_length=mx.nd.array(np.array(vls, np.int32)))
    eng = serving.ServingEngine(tf_net, eos_id=EOS, bos_id=BOS,
                                max_batch=4, block_tokens=4, max_seq=16,
                                prefill_tokens=16)
    outs = eng.generate([list(src[i, :vls[i]]) for i in range(3)],
                        max_new_tokens=11)
    for i, got in enumerate(outs):
        want = list(ref[i, 1:])           # strip BOS
        assert got[:len(want)] == want[:len(got)], (i, got, want)


def test_transformer_rejects_max_seq_past_pos_table(tf_net):
    """max_seq beyond the sinusoid table must error at construction —
    jnp.take would clamp those decode positions and emit wrong tokens."""
    with pytest.raises(MXNetError, match="positional table"):
        serving.ServingEngine(tf_net, eos_id=EOS, bos_id=BOS,
                              max_batch=2, block_tokens=4, max_seq=64,
                              prefill_tokens=16)   # tf_net max_length=32


def test_transformer_no_retrace(tf_net):
    eng = serving.ServingEngine(tf_net, eos_id=EOS, bos_id=BOS,
                                max_batch=4, block_tokens=4, max_seq=16,
                                prefill_tokens=16)
    eng.generate([[5, 6, 7]], max_new_tokens=4)          # warm
    with no_retrace():
        outs = eng.generate([[8, 9], [10, 11, 12, 13], [14]],
                            max_new_tokens=6)
    assert all(len(o) == 6 for o in outs)


# -- encode-once satellite ---------------------------------------------------

def test_encode_once_matches_full_forward(tf_net):
    """encode() + decode_from_memory() == the one-shot hybrid forward —
    the contract that lets greedy/beam decode encode the source once."""
    r = np.random.RandomState(3)
    src = mx.nd.array(r.randint(3, 50, (2, 7)).astype(np.int32))
    tgt = mx.nd.array(r.randint(3, 50, (2, 5)).astype(np.int32))
    vl = mx.nd.array(np.array([7, 4], np.int32))
    full = tf_net(src, tgt, vl).asnumpy()
    mem = tf_net.encode(src, vl)
    two_step = tf_net.decode_from_memory(mem, tgt, vl).asnumpy()
    np.testing.assert_allclose(full, two_step, rtol=1e-5, atol=1e-6)


def test_greedy_decode_counts_one_encoder_pass(tf_net, monkeypatch):
    """greedy_decode must hit the encoder exactly once however many
    tokens it emits."""
    calls = {"n": 0}
    orig = type(tf_net).encode

    def counting(self, *a, **kw):
        calls["n"] += 1
        return orig(self, *a, **kw)

    monkeypatch.setattr(type(tf_net), "encode", counting)
    src = mx.nd.array(np.array([[5, 6, 7, 8]], np.int32))
    out = transformer.greedy_decode(tf_net, src, BOS, EOS, max_len=8)
    assert out.shape[0] == 1 and calls["n"] == 1


# -- telemetry SLOs ----------------------------------------------------------

def test_serving_telemetry_slos(llama_net):
    telemetry.enable()
    try:
        t0 = telemetry.counter("mxnet_serving_tokens_total").value
        s0 = telemetry.counter("mxnet_serving_decode_steps_total").value
        p0 = telemetry.counter(
            "mxnet_serving_token_positions_total").value
        ttft = telemetry.REGISTRY.get("mxnet_serving_ttft_seconds")
        e2e = telemetry.REGISTRY.get("mxnet_serving_e2e_seconds")
        h0, e0 = ttft.count, e2e.count
        eng = _llama_engine(llama_net)
        outs = eng.generate([[5, 6], [7, 8, 9]], max_new_tokens=5)
        n_tokens = sum(len(o) for o in outs)
        assert telemetry.counter(
            "mxnet_serving_tokens_total").value == t0 + n_tokens
        steps = telemetry.counter(
            "mxnet_serving_decode_steps_total").value - s0
        assert steps >= 4                   # 5 new tokens, first via prefill
        positions = telemetry.counter(
            "mxnet_serving_token_positions_total").value - p0
        # 2 prefills at the padded shape + B_max per decode step
        assert positions == 2 * eng.adapter.prefill_tokens \
            + steps * eng.max_batch
        assert ttft.count == h0 + 2 and e2e.count == e0 + 2
        assert telemetry.gauge("mxnet_serving_queue_depth").value == 0
        assert telemetry.gauge("mxnet_serving_active_slots").value == 0
    finally:
        if not telemetry.env_enabled():
            telemetry.disable()


def test_serving_request_span_tree(llama_net):
    """ISSUE 10: every request is a linked async span tree in the trace —
    'b' at submit, 'n' markers at admission/first token, 'e' at finish,
    all keyed by request id; prefill spans and decode-step spans carry
    the rid linkage in their args."""
    telemetry.enable()
    telemetry.clear()
    try:
        eng = _llama_engine(llama_net)
        h1, h2 = (eng.submit(p, max_new_tokens=4) for p in ([5, 6], [7, 8]))
        eng.drain()
        out1, out2 = h1.result(5), h2.result(5)
        assert out1 and out2
        evs = telemetry.get_tracer().events()
        for h in (h1, h2):
            rid = str(h.rid)
            tree = [e for e in evs if e.get("cat") == "serving.request"
                    and e.get("id") == rid]
            phs = [e["ph"] for e in tree]
            assert phs[0] == "b" and phs[-1] == "e"
            marks = {e["name"] for e in tree if e["ph"] == "n"}
            assert {"admitted", "first_token"} <= marks
            end = tree[-1]
            assert end["args"]["tokens"] == len(
                (out1 if h is h1 else out2))
            # the tree threads in timestamp order: queue -> ... -> finish
            ts = [e["ts"] for e in tree]
            assert ts == sorted(ts)
        prefill_rids = {e["args"]["rid"] for e in evs
                        if e.get("name") == "serving.prefill"}
        assert {h1.rid, h2.rid} <= prefill_rids
        decode_rids = set()
        for e in evs:
            if e.get("name") == "serving.decode_step":
                decode_rids.update(e["args"]["rids"])
        assert {h1.rid, h2.rid} <= decode_rids
    finally:
        telemetry.clear()
        if not telemetry.env_enabled():
            telemetry.disable()
