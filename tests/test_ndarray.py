"""NDArray semantics tests (reference tests/python/unittest/test_ndarray.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32
    assert nd.zeros((3, 4)).asnumpy().sum() == 0
    assert nd.ones((3, 4)).asnumpy().sum() == 12
    assert_almost_equal(nd.full((2, 2), 7).asnumpy(), np.full((2, 2), 7.0))
    assert_almost_equal(nd.arange(0, 10, 2).asnumpy(), np.arange(0, 10, 2))


def test_python_float_default_dtype():
    a = nd.array([1.5, 2.5])
    assert a.dtype == np.float32  # reference: float64 source → float32


def test_arithmetic():
    a = nd.array([[1., 2.], [3., 4.]])
    b = nd.array([[5., 6.], [7., 8.]])
    assert_almost_equal((a + b).asnumpy(), [[6, 8], [10, 12]])
    assert_almost_equal((a - b).asnumpy(), [[-4, -4], [-4, -4]])
    assert_almost_equal((a * b).asnumpy(), [[5, 12], [21, 32]])
    assert_almost_equal((b / a).asnumpy(), [[5, 3], [7 / 3, 2]])
    assert_almost_equal((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    assert_almost_equal((2 + a).asnumpy(), [[3, 4], [5, 6]])
    assert_almost_equal((2 - a).asnumpy(), [[1, 0], [-1, -2]])
    assert_almost_equal((2 / a).asnumpy(), [[2, 1], [2 / 3, 0.5]])
    assert_almost_equal((-a).asnumpy(), [[-1, -2], [-3, -4]])
    assert_almost_equal(abs(-a).asnumpy(), [[1, 2], [3, 4]])


def test_comparison():
    a = nd.array([1., 2., 3.])
    b = nd.array([3., 2., 1.])
    assert_almost_equal((a == b).asnumpy(), [0, 1, 0])
    assert_almost_equal((a < b).asnumpy(), [1, 0, 0])
    assert_almost_equal((a >= b).asnumpy(), [0, 1, 1])


def test_inplace_ops():
    a = nd.array([1., 2., 3.])
    a += 1
    assert_almost_equal(a.asnumpy(), [2, 3, 4])
    a *= 2
    assert_almost_equal(a.asnumpy(), [4, 6, 8])
    a /= 4
    assert_almost_equal(a.asnumpy(), [1, 1.5, 2])


def test_setitem():
    a = nd.zeros((3, 4))
    a[:] = 2
    assert a.asnumpy().sum() == 24
    a[1] = 5
    assert_almost_equal(a.asnumpy()[1], np.full(4, 5.0))
    a[0, 1:3] = 7
    assert_almost_equal(a.asnumpy()[0], [2, 7, 7, 2])
    a[2] = np.array([1, 2, 3, 4])
    assert_almost_equal(a.asnumpy()[2], [1, 2, 3, 4])


def test_view_aliasing():
    """Basic-index views share storage both directions (reference NDArray
    Slice/At semantics)."""
    a = nd.array(np.arange(12).reshape(3, 4).astype("float32"))
    v = a[1]
    a[1] = 99.0
    assert_almost_equal(v.asnumpy(), np.full(4, 99.0))
    v[:] = 7.0
    assert_almost_equal(a.asnumpy()[1], np.full(4, 7.0))
    r = a.reshape(4, 3)
    r[0, 0] = -1.0
    assert a.asnumpy()[0, 0] == -1.0


def test_advanced_indexing_copies():
    a = nd.array(np.arange(6).astype("float32"))
    c = a[np.array([0, 2, 4])]
    assert_almost_equal(c.asnumpy(), [0, 2, 4])
    c[:] = 9
    assert a.asnumpy()[0] == 0  # copy, not view


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, -1, 3, 4)).shape == (2, 1, 3, 4)


def test_reductions():
    x = np.random.uniform(-1, 1, (3, 4, 5)).astype("float32")
    a = nd.array(x)
    assert_almost_equal(a.sum().asnumpy(), x.sum())
    assert_almost_equal(a.mean(axis=1).asnumpy(), x.mean(axis=1))
    assert_almost_equal(a.max(axis=(0, 2)).asnumpy(), x.max(axis=(0, 2)))
    assert_almost_equal(nd.sum(a, axis=1, keepdims=True).asnumpy(),
                        x.sum(axis=1, keepdims=True))
    assert_almost_equal(nd.sum(a, axis=1, exclude=True).asnumpy(),
                        x.sum(axis=(0, 2)))


def test_dot():
    x = np.random.uniform(-1, 1, (4, 5)).astype("float32")
    y = np.random.uniform(-1, 1, (5, 3)).astype("float32")
    assert_almost_equal(nd.dot(nd.array(x), nd.array(y)).asnumpy(),
                        x.dot(y), rtol=1e-4, atol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(x), nd.array(y.T), transpose_b=True).asnumpy(),
        x.dot(y), rtol=1e-4, atol=1e-4)
    bx = np.random.uniform(-1, 1, (2, 4, 5)).astype("float32")
    by = np.random.uniform(-1, 1, (2, 5, 3)).astype("float32")
    assert_almost_equal(nd.batch_dot(nd.array(bx), nd.array(by)).asnumpy(),
                        np.matmul(bx, by), rtol=1e-4, atol=1e-4)


def test_shape_ops():
    x = np.arange(24).reshape(2, 3, 4).astype("float32")
    a = nd.array(x)
    assert_almost_equal(a.transpose().asnumpy(), x.T)
    assert_almost_equal(a.transpose((1, 0, 2)).asnumpy(),
                        x.transpose(1, 0, 2))
    assert_almost_equal(a.swapaxes(0, 2).asnumpy(), x.swapaxes(0, 2))
    assert_almost_equal(a.expand_dims(1).asnumpy(), x[:, None])
    assert_almost_equal(nd.concat(a, a, dim=1).asnumpy(),
                        np.concatenate([x, x], axis=1))
    assert_almost_equal(nd.stack(a, a, axis=0).asnumpy(),
                        np.stack([x, x]))
    assert_almost_equal(nd.flip(a, axis=2).asnumpy(), x[:, :, ::-1])
    assert_almost_equal(nd.tile(a, reps=(1, 2, 1)).asnumpy(),
                        np.tile(x, (1, 2, 1)))


def test_slice_ops():
    x = np.arange(24).reshape(4, 6).astype("float32")
    a = nd.array(x)
    assert_almost_equal(a.slice([1, 2], [3, 5]).asnumpy(), x[1:3, 2:5])
    assert_almost_equal(a.slice_axis(1, 2, 4).asnumpy(), x[:, 2:4])
    parts = nd.split(a, num_outputs=2, axis=0)
    assert_almost_equal(parts[0].asnumpy(), x[:2])


def test_take_pick_onehot():
    x = np.random.uniform(size=(4, 5)).astype("float32")
    a = nd.array(x)
    idx = nd.array(np.array([0, 2]))
    assert_almost_equal(a.take(idx, axis=0).asnumpy(), x[[0, 2]])
    picked = a.pick(nd.array(np.array([1, 0, 3, 2])), axis=1)
    assert_almost_equal(picked.asnumpy(), x[np.arange(4), [1, 0, 3, 2]])
    oh = nd.one_hot(nd.array(np.array([0, 2])), depth=4)
    assert_almost_equal(oh.asnumpy(), np.eye(4)[[0, 2]])


def test_ordering():
    x = np.random.uniform(size=(3, 6)).astype("float32")
    a = nd.array(x)
    assert_almost_equal(a.sort(axis=1).asnumpy(), np.sort(x, axis=1))
    assert_almost_equal(a.argsort(axis=1).asnumpy(),
                        np.argsort(x, axis=1).astype("float32"))
    v = a.topk(k=2, ret_typ="value", axis=1)
    assert_almost_equal(v.asnumpy(), -np.sort(-x, axis=1)[:, :2])
    am = a.argmax(axis=1)
    assert_almost_equal(am.asnumpy(), np.argmax(x, axis=1).astype("float32"))


def test_astype_copy():
    a = nd.array([1.5, 2.5])
    b = a.astype(np.int32)
    assert b.dtype == np.int32
    assert a.astype(np.float32, copy=False) is a


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert int(a) == 3
    assert a.asscalar() == pytest.approx(3.5)
    with pytest.raises(Exception):
        nd.array([1.0, 2.0]).asscalar()


def test_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "x.params")
    d = {"w": nd.array(np.random.randn(3, 4).astype("float32")),
         "b": nd.array(np.random.randn(4).astype("float32"))}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"].asnumpy(), d["w"].asnumpy())
    lst = [nd.array([1.0]), nd.array([2.0, 3.0])]
    nd.save(fname, lst)
    back = nd.load(fname)
    assert isinstance(back, list) and len(back) == 2
    assert_almost_equal(back[1].asnumpy(), [2.0, 3.0])


def test_wait_and_context():
    a = nd.ones((2, 2))
    a.wait_to_read()
    assert a.ctx.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b is a
    nd.waitall()


def test_iter_len():
    a = nd.array(np.arange(6).reshape(3, 2).astype("float32"))
    assert len(a) == 3
    rows = [r.asnumpy() for r in a]
    assert len(rows) == 3
    assert_almost_equal(rows[1], [2, 3])


def test_zeros_like_ones_like():
    a = nd.array(np.random.randn(2, 3).astype("float32"))
    assert nd.zeros_like(a).asnumpy().sum() == 0
    assert nd.ones_like(a).asnumpy().sum() == 6


def test_inplace_alias_visibility():
    """ADVICE r1: a += b must mutate the slot so aliases observe the write."""
    a = nd.array([1.0, 1.0])
    alias = a
    a += 1
    assert_almost_equal(alias.asnumpy(), [2.0, 2.0])
    # through a view too
    v = a[0:2]
    a += 1
    assert_almost_equal(v.asnumpy(), [3.0, 3.0])


def test_array_preserves_float64():
    """ADVICE r1: numpy float64 sources keep their dtype."""
    src = np.array([1.0, 2.0], dtype=np.float64)
    a = nd.array(src)
    assert a.dtype == np.float64
    assert_almost_equal(a.asnumpy(), src)
    # python lists still default to float32
    assert nd.array([1.0, 2.0]).dtype == np.float32
