"""Multi-core decode pipeline tests (ISSUE 7): the shared-memory pooled
decode path must be BIT-IDENTICAL to single-process decode (same records,
same per-index augmentation RNG) through both front doors
(``ImageRecordIter(decoder='pool')`` and the gluon ``DataLoader`` over a
decode-aware dataset), and a killed decode worker must degrade through
the ISSUE 3 ladder (in-process re-decode → pool rebuild → permanent
single-process) without dropping or duplicating a record.

Pool spin-up is forkserver-based (~1s per pipeline), so the suite shares
one RecordIO pack and keeps epochs tiny.
"""

import os

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import recordio, telemetry  # noqa: E402
from mxnet_tpu.io.io import _mix_seed  # noqa: E402
from mxnet_tpu.io.pipeline import _read_payload  # noqa: E402


N_IMAGES, JPEG_SIZE, CROP, BATCH = 48, 96, 64, 8


@pytest.fixture(scope="module")
def rec_path(tmp_path_factory):
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "benchmark"))
    try:
        from io_bench import make_dataset
    finally:
        sys.path.pop(0)
    root = tmp_path_factory.mktemp("io_pipeline")
    return make_dataset(str(root / "pack"), N_IMAGES, JPEG_SIZE)


def _make_iter(rec, threads, seed=13, **kw):
    return mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, CROP, CROP), batch_size=BATCH,
        shuffle=True, rand_crop=True, rand_mirror=True, seed=seed,
        preprocess_threads=threads, decoder="pool", ctx=mx.cpu(),
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.1, std_b=57.4, **kw)


def _epoch(it):
    return [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it]


def _assert_epochs_equal(ref, got):
    assert len(ref) == len(got) > 0
    for (rd, rl), (gd, gl) in zip(ref, got):
        np.testing.assert_array_equal(rd, gd)
        np.testing.assert_array_equal(rl, gl)


# ---------------------------------------------------------------------------
# unit pieces
# ---------------------------------------------------------------------------

def test_mix_seed_deterministic_and_spread():
    a = [_mix_seed(7, k) for k in range(256)]
    assert a == [_mix_seed(7, k) for k in range(256)]  # pure function
    assert len(set(a)) == 256                          # no collisions here
    assert all(0 <= s < 2 ** 32 for s in a)
    assert _mix_seed(7, 0) != _mix_seed(8, 0)          # seed matters


def test_payload_spans_match_read_idx(rec_path):
    """Workers pread spans the parent resolved; the bytes they see must be
    exactly what read_idx returns (both native-scan and idx-fallback
    shapes of payload_spans)."""
    idx_path = os.path.splitext(rec_path)[0] + ".idx"
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    keys = list(rec.keys)[:5]
    offs, lens = rec.payload_spans(keys)
    fd = os.open(rec_path, os.O_RDONLY)
    try:
        for k, off, length in zip(keys, offs, lens):
            assert _read_payload(fd, int(off), int(length)) == \
                rec.read_idx(k)
        # the scanner-less shape: offsets are RECORD starts (the .idx
        # sidecar positions) and the worker parses the 8-byte framing
        # itself — must yield the same payload bytes
        for k in keys:
            start = int(rec.idx[rec.key_type(k)])
            assert _read_payload(fd, start, -1) == rec.read_idx(k)
    finally:
        os.close(fd)
        rec.close()


def test_io_pool_knob_off(rec_path, monkeypatch):
    """MXNET_IO_POOL=0 forces in-process decode: no pipeline is built even
    at preprocess_threads>1 with decoder='pool'."""
    monkeypatch.setenv("MXNET_IO_POOL", "0")
    it = _make_iter(rec_path, threads=2)
    batches = _epoch(it)
    assert it._pipeline is None and len(batches) == N_IMAGES // BATCH
    it.close()


# ---------------------------------------------------------------------------
# bit-identity (the tentpole acceptance contract)
# ---------------------------------------------------------------------------

def test_pooled_bit_identical_to_single_process(rec_path):
    """Pooled epochs — including a mid-epoch reset — replay the exact
    bytes of single-process decode: same shuffle order, same crop/mirror
    draws, same labels, across epochs."""
    single = _make_iter(rec_path, threads=1)
    pooled = _make_iter(rec_path, threads=2)
    try:
        e0 = _epoch(single)
        _assert_epochs_equal(e0, _epoch(pooled))
        # epoch 2 reshuffles from the epoch-mixed seed; must still agree
        single.reset()
        pooled.reset()
        e1 = _epoch(single)
        assert not np.array_equal(e0[0][1], e1[0][1]) or len(e0) == 1
        _assert_epochs_equal(e1, _epoch(pooled))
        # mid-epoch reset: consume part of epoch 3 pooled, reset both,
        # epoch 4 must be identical again (drain() discards cleanly)
        single.reset()
        pooled.reset()
        next(pooled)
        single.reset()
        pooled.reset()
        _assert_epochs_equal(_epoch(single), _epoch(pooled))
    finally:
        single.close()
        pooled.close()


def test_pooled_decode_telemetry(rec_path):
    """The decode counter/histogram observe pooled work (queue gauge and
    decode seconds ride the same flag)."""
    telemetry.enable()
    try:
        dec = telemetry.REGISTRY.get("mxnet_io_decoded_images_total")
        before = dec.value
        it = _make_iter(rec_path, threads=2)
        n = sum(d.shape[0] for d, _ in _epoch(it))
        it.close()
        assert dec.value - before >= n
    finally:
        telemetry.disable()


def test_pool_worker_counter_shipping(rec_path, monkeypatch):
    """ISSUE 10: decode workers ship their counters back on the existing
    ack channel.  Chaos armed at io.decode (delay, worker-side only)
    increments the WORKER's fault counter; the parent's registry must see
    those increments arrive through the (n, seconds, deltas) acks."""
    monkeypatch.setenv("MXNET_CHAOS", "1")
    monkeypatch.setenv("MXNET_CHAOS_SITES", "io.decode:delay:0:0.001")
    faults = telemetry.REGISTRY.get("mxnet_resilience_faults_injected_total")
    before = faults.value
    it = _make_iter(rec_path, threads=2)
    try:
        n_batches = len(_epoch(it))
        assert n_batches > 0
        # one fault fires per decoded chunk, all inside worker processes;
        # every ack's delta leg lands them in the parent's counter
        assert faults.value - before >= n_batches
    finally:
        it.close()


def test_dataloader_decode_pool_bit_identical(rec_path):
    """The gluon DataLoader routes a decode-aware dataset through the
    shared-memory pool when num_workers>0 — batches identical to
    num_workers=0, across two epochs of the same loader (pipeline
    persists; the generic pickle pool is never built)."""
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.vision.datasets import DecodedImageRecordDataset
    ds = DecodedImageRecordDataset(
        rec_path, (3, CROP, CROP), rand_crop=True, rand_mirror=True,
        mean=(123.68, 116.78, 103.94), std=(58.4, 57.1, 57.4), seed=5)
    dl0 = DataLoader(ds, batch_size=BATCH, shuffle=False, num_workers=0)
    dl2 = DataLoader(ds, batch_size=BATCH, shuffle=False, num_workers=2)
    try:
        assert dl2._use_decode_pool and dl2._pool is None
        ref = [(d.asnumpy(), l.asnumpy()) for d, l in dl0]
        _assert_epochs_equal(ref, [(d.asnumpy(), l.asnumpy())
                                   for d, l in dl2])
        _assert_epochs_equal(ref, [(d.asnumpy(), l.asnumpy())
                                   for d, l in dl2])  # epoch 2, same pipe
    finally:
        dl0._shutdown_pool()
        dl2._shutdown_pool()


def test_dataloader_nested_iteration_correct(rec_path):
    """Nested iteration of one decode-pool DataLoader must not corrupt
    either stream: the pipeline is a single ordered stream, so the inner
    generator decodes in-process while the outer keeps its schedule —
    both yield exactly the single-process batches."""
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.vision.datasets import DecodedImageRecordDataset
    ds = DecodedImageRecordDataset(
        rec_path, (3, CROP, CROP), rand_crop=True, rand_mirror=True,
        mean=(123.68, 116.78, 103.94), std=(58.4, 57.1, 57.4), seed=5)
    dl0 = DataLoader(ds, batch_size=BATCH, shuffle=False, num_workers=0)
    dl2 = DataLoader(ds, batch_size=BATCH, shuffle=False, num_workers=2)
    try:
        ref = [(d.asnumpy(), l.asnumpy()) for d, l in dl0]
        outer, inner = [], []
        for d, l in dl2:
            outer.append((d.asnumpy(), l.asnumpy()))
            if len(outer) == 1:   # nest a full epoch mid-outer-epoch
                inner = [(di.asnumpy(), li.asnumpy()) for di, li in dl2]
        _assert_epochs_equal(ref, outer)
        _assert_epochs_equal(ref, inner)
        assert dl2._use_decode_pool    # no failure episodes were burned
    finally:
        dl0._shutdown_pool()
        dl2._shutdown_pool()


def test_dataset_getitem_matches_iterator_decode(rec_path):
    """DecodedImageRecordDataset[i] is the same pure decode function the
    pool runs — spot-check a sample against a manual seeded decode."""
    from mxnet_tpu.gluon.data.vision.datasets import DecodedImageRecordDataset
    from mxnet_tpu.io.io import _decode_record
    ds = DecodedImageRecordDataset(
        rec_path, (3, CROP, CROP), rand_crop=True, rand_mirror=True,
        seed=9)
    img, label = ds[3]
    raw = ds._rec.read_idx(ds._keys[3])
    img2, label2 = _decode_record(
        raw, ds._cfg, np.random.RandomState(ds._sample_seed(3)))
    np.testing.assert_array_equal(img, img2)
    assert label == label2


def test_steady_state_epoch_no_retrace(rec_path):
    """ISSUE 7 acceptance: the pooled path hands the consumer fixed-shape
    private arrays, so a steady-state epoch feeding a jitted op performs
    ZERO XLA compilations (analysis.runtime.no_retrace — the dynamic GC02
    twin) — batch shapes never churn the jit cache."""
    from mxnet_tpu.analysis import runtime
    it = _make_iter(rec_path, threads=2)
    try:
        for b in it:                       # warm-up epoch: traces compile
            (b.data[0] * 2.0).asnumpy()
        it.reset()
        with runtime.no_retrace():
            for b in it:                   # steady state: cache hits only
                (b.data[0] * 2.0).asnumpy()
    finally:
        it.close()


# ---------------------------------------------------------------------------
# degradation ladder (chaos worker-kill — ISSUE 3 semantics)
# ---------------------------------------------------------------------------

def test_chaos_worker_kill_degrades_without_record_loss(rec_path,
                                                        monkeypatch):
    """A decode worker hard-killed mid-epoch (chaos io.decode:exit — real
    os._exit in the worker) rides the ladder: affected chunks re-decode
    in-process from the same seeds, the pool is rebuilt, and the epoch's
    batches stay bit-identical to single-process — nothing dropped,
    nothing duplicated."""
    single = _make_iter(rec_path, threads=1)
    ref = _epoch(single)
    single.close()
    # env-armed so the POOL WORKERS arm it (parent stays clean); each
    # fresh worker kills itself on its first chunk, so every pool
    # generation fails and the ladder is walked end to end
    monkeypatch.setenv("MXNET_CHAOS", "1")
    monkeypatch.setenv("MXNET_CHAOS_SITES", "io.decode:exit:1")
    pooled = _make_iter(rec_path, threads=2)
    try:
        with pytest.warns(UserWarning, match="io decode pool"):
            got = _epoch(pooled)
        _assert_epochs_equal(ref, got)
        assert pooled._pipeline._failures >= 1
    finally:
        pooled.close()


def test_chaos_permanent_degradation_completes(rec_path, monkeypatch):
    """Unbounded worker kills exhaust MXNET_DATALOADER_RETRIES and the
    pipeline degrades PERMANENTLY to single-process decode — the epoch
    (and the next one) still completes bit-identically."""
    single = _make_iter(rec_path, threads=1)
    ref0 = _epoch(single)
    single.reset()
    ref1 = _epoch(single)
    single.close()
    monkeypatch.setenv("MXNET_CHAOS", "1")
    monkeypatch.setenv("MXNET_CHAOS_SITES", "io.decode:exit:0")
    monkeypatch.setenv("MXNET_DATALOADER_RETRIES", "1")
    pooled = _make_iter(rec_path, threads=2)
    try:
        with pytest.warns(UserWarning, match="degrading permanently"):
            got0 = _epoch(pooled)
        _assert_epochs_equal(ref0, got0)
        assert pooled._pipeline._permanent
        pooled.reset()   # epoch 2 runs fully in-process, no pool attempt
        _assert_epochs_equal(ref1, _epoch(pooled))
    finally:
        pooled.close()
