"""Bucketed gradient fusion (kvstore/fusion.py, ISSUE 2).

The contract under test: ``pushpull_list`` with fusion enabled is
BIT-identical to the per-key push+pull loop — multi-replica, mixed dtypes
(separate buckets per dtype), odd sizes, key gaps from ``grad_req='null'``
params, and per-key fallback for sparse / compressed / update-on-kvstore
keys — while steady-state steps reuse cached plans and executables
(no retraces after step one).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.kvstore.fusion import GradBucketer


def _make_values(shapes, dtypes, n_rep, seed=0):
    rng = np.random.RandomState(seed)
    vals = []
    for s, dt in zip(shapes, dtypes):
        reps = [nd.array(rng.standard_normal(s).astype(dt), ctx=mx.cpu(r))
                for r in range(n_rep)]
        vals.append(reps if n_rep > 1 else reps[0])
    return vals


def _run_pushpull(bucket_mb, keys, shapes, dtypes, vals, kv_type="local"):
    kv = mx.kv.create(kv_type)
    kv.set_bucket_size(bucket_mb)
    for k, s, dt in zip(keys, shapes, dtypes):
        kv.init(k, nd.zeros(s, dtype=dt))
    n_rep = len(vals[0]) if isinstance(vals[0], list) else 1
    outs = [[nd.zeros(s, dtype=dt, ctx=mx.cpu(r)) for r in range(n_rep)]
            if n_rep > 1 else nd.zeros(s, dtype=dt)
            for s, dt in zip(shapes, dtypes)]
    kv.pushpull_list(keys, vals, outs)
    return kv, outs


def _assert_bit_identical(outs_a, outs_b):
    for j, (a, b) in enumerate(zip(outs_a, outs_b)):
        la = a if isinstance(a, list) else [a]
        lb = b if isinstance(b, list) else [b]
        for r, (x, y) in enumerate(zip(la, lb)):
            xa, ya = x.asnumpy(), y.asnumpy()
            assert xa.dtype == ya.dtype
            assert np.array_equal(xa, ya), (j, r)


# ---------------------------------------------------------------------------
# bucket planning
# ---------------------------------------------------------------------------

def test_bucketer_plan_splits_by_size_and_dtype():
    b = GradBucketer(bucket_bytes=100)  # tiny bound to force splits
    sig = (
        ((10,), "float32", 1),   # 40 B
        ((10,), "float32", 1),   # 40 B  -> fits (80)
        ((10,), "float32", 1),   # 40 B  -> would be 120: new bucket
        ((10,), "float16", 1),   # different dtype: own bucket group
        ((100,), "float32", 1),  # 400 B oversized: own bucket
    )
    buckets = b.plan(sig)
    groups = [tuple(bk.positions) for bk in buckets]
    assert groups == [(0, 1), (2,), (3,), (4,)]
    assert b.plan(sig) is buckets  # cached plan object


def test_bucketer_plan_groups_by_replica_count():
    b = GradBucketer(bucket_bytes=1 << 20)
    sig = (((4,), "float32", 2), ((4,), "float32", 1), ((4,), "float32", 2))
    buckets = b.plan(sig)
    assert [tuple(bk.positions) for bk in buckets] == [(0, 2), (1,)]


# ---------------------------------------------------------------------------
# numerics: fused == per-key, bitwise
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_rep", [1, 2, 4])
def test_fused_bit_identical_multi_replica(n_rep):
    shapes = [(3, 5), (7,), (11, 3), (1,), (2, 2, 3)]
    dtypes = ["float32"] * 5
    keys = [0, 1, 3, 4, 7]  # gaps: grad_req='null' params drop out of the list
    vals = _make_values(shapes, dtypes, n_rep)
    _, fused = _run_pushpull(25, keys, shapes, dtypes, vals)
    _, perkey = _run_pushpull(0, keys, shapes, dtypes, vals)
    _assert_bit_identical(fused, perkey)


def test_fused_bit_identical_mixed_dtypes_multiple_buckets():
    # interleaved dtypes + a tiny bucket bound: several buckets per dtype
    shapes = [(64,), (32,), (64,), (128,), (16,), (33,)]
    dtypes = ["float32", "float16", "float32", "float16", "float32",
              "float32"]
    keys = list(range(6))
    vals = _make_values(shapes, dtypes, n_rep=2)
    kv, fused = _run_pushpull(256 / (1 << 20), keys, shapes, dtypes, vals)
    _, perkey = _run_pushpull(0, keys, shapes, dtypes, vals)
    _assert_bit_identical(fused, perkey)
    sig = tuple((tuple(s), dt, 2) for s, dt in zip(shapes, dtypes))
    assert len(kv._bucketer.plan(sig)) > 2  # the bound actually split


def test_fused_updates_store_like_per_key():
    shapes, dtypes, keys = [(4,), (6,)], ["float32"] * 2, [0, 1]
    vals = _make_values(shapes, dtypes, n_rep=2)
    kv_f, _ = _run_pushpull(25, keys, shapes, dtypes, vals)
    kv_p, _ = _run_pushpull(0, keys, shapes, dtypes, vals)
    for k in keys:
        # a later plain pull must see the reduced value either way
        of = nd.zeros(shapes[k], dtype=dtypes[k])
        op = nd.zeros(shapes[k], dtype=dtypes[k])
        kv_f.pull(k, of)
        kv_p.pull(k, op)
        assert np.array_equal(of.asnumpy(), op.asnumpy())


def test_fused_dist_store_single_process():
    shapes, dtypes = [(5,), (3, 3)], ["float32"] * 2
    keys = [0, 1]
    vals = _make_values(shapes, dtypes, n_rep=2)
    _, fused = _run_pushpull(25, keys, shapes, dtypes, vals, "dist_tpu_sync")
    _, perkey = _run_pushpull(0, keys, shapes, dtypes, vals, "dist_tpu_sync")
    _assert_bit_identical(fused, perkey)


# ---------------------------------------------------------------------------
# fallback rules
# ---------------------------------------------------------------------------

def test_sparse_key_falls_back_per_key():
    from mxnet_tpu.ndarray import sparse as sp
    kv = mx.kv.create("local")
    kv.set_bucket_size(25)
    dense = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    rsp = sp.cast_storage(dense, "row_sparse")
    kv.init(0, nd.zeros((4,)))
    kv.init(1, rsp)          # sparse stored value
    kv.init(2, nd.zeros((2,)))
    v0 = nd.array(np.ones(4, np.float32))
    v2 = nd.array(np.full(2, 3.0, np.float32))
    o0, o2 = nd.zeros((4,)), nd.zeros((2,))
    o1 = nd.zeros((3, 4))
    kv.pushpull_list([0, 1, 2], [v0, dense, v2], [o0, o1, o2])
    np.testing.assert_array_equal(o0.asnumpy(), np.ones(4))
    np.testing.assert_array_equal(o1.asnumpy(), dense.asnumpy())
    np.testing.assert_array_equal(o2.asnumpy(), np.full(2, 3.0))


def test_compression_falls_back_whole_list():
    keys, shapes, dtypes = [0, 1], [(8,), (6,)], ["float32"] * 2
    vals = _make_values(shapes, dtypes, n_rep=2)

    def run(bucket_mb):
        kv = mx.kv.create("local")
        kv.set_bucket_size(bucket_mb)
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        for k, s in zip(keys, shapes):
            kv.init(k, nd.zeros(s))
        outs = [nd.zeros(s) for s in shapes]
        kv.pushpull_list(keys, vals, outs)
        assert kv._bucketer is None  # compressed keys never built buckets
        return outs

    _assert_bit_identical(run(25), run(0))


def test_update_on_kvstore_falls_back():
    kv = mx.kv.create("local")
    kv.set_bucket_size(25)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0, rescale_grad=1.0))
    w = nd.array(np.full(4, 10.0, np.float32))
    kv.init(0, w)
    grad = nd.array(np.ones(4, np.float32))
    out = nd.zeros((4,))
    kv.pushpull_list([0], [grad], [out])
    # the store ran SGD: w - lr*grad = 9, proving the per-key updater path
    np.testing.assert_allclose(out.asnumpy(), np.full(4, 9.0))
    assert kv._bucketer is None


def test_bucket_mb_zero_disables_fusion():
    keys, shapes, dtypes = [0, 1], [(4,), (5,)], ["float32"] * 2
    vals = _make_values(shapes, dtypes, n_rep=1)
    kv, outs = _run_pushpull(0, keys, shapes, dtypes, vals)
    assert kv._bucketer is None
    _, perkey = _run_pushpull(0, keys, shapes, dtypes, vals)
    _assert_bit_identical(outs, perkey)


# ---------------------------------------------------------------------------
# retrace / cache behavior
# ---------------------------------------------------------------------------

def test_steady_state_reuses_cached_executables():
    shapes = [(3, 5), (7,), (16,)]
    dtypes = ["float32", "float32", "float16"]
    keys = [0, 1, 2]
    kv = mx.kv.create("local")
    kv.set_bucket_size(25)
    for k, s, dt in zip(keys, shapes, dtypes):
        kv.init(k, nd.zeros(s, dtype=dt))
    outs = [nd.zeros(s, dtype=dt) for s, dt in zip(shapes, dtypes)]
    for step in range(4):
        vals = _make_values(shapes, dtypes, n_rep=2, seed=step)
        kv.pushpull_list(keys, vals, outs)
        if step == 0:
            builds_after_first = kv._bucketer.builds
            assert builds_after_first > 0
    assert kv._bucketer.builds == builds_after_first
    assert len(kv._bucketer._plan_cache) == 1
    # the jitted executables themselves compiled exactly once each
    for fn in kv._bucketer._reduce_keys_cache.values():
        assert fn._cache_size() == 1


def test_set_bucket_size_resets_plans():
    keys, shapes, dtypes = [0, 1], [(4,), (5,)], ["float32"] * 2
    vals = _make_values(shapes, dtypes, n_rep=2)
    kv, _ = _run_pushpull(25, keys, shapes, dtypes, vals)
    assert kv._bucketer is not None
    kv.set_bucket_size(1)
    assert kv._bucketer is None  # stale plans dropped with the old bound


# ---------------------------------------------------------------------------
# trainer integration + telemetry
# ---------------------------------------------------------------------------

def _train(bucket_mb, n_ctx=2, steps=3):
    np.random.seed(0)
    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(ctx=[mx.cpu(i) for i in range(n_ctx)])
    # a grad_req='null' param in the middle of the key sequence
    list(net.collect_params().values())[1].grad_req = "null"
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    xs = [nd.array(np.random.randn(8, 10).astype("float32"), ctx=mx.cpu(i))
          for i in range(n_ctx)]
    for _ in range(steps):
        for x in xs:
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
        tr._init_kvstore()
        tr._kvstore.set_bucket_size(bucket_mb)
        tr.step(8)
    return [p.data().asnumpy() for p in net.collect_params().values()], tr


def test_trainer_fused_bit_identical_to_per_key():
    fused, tr = _train(25)
    perkey, _ = _train(0)
    for a, b in zip(fused, perkey):
        assert np.array_equal(a, b)
    bucketer = tr._kvstore._bucketer
    assert bucketer is not None and bucketer.builds > 0
    assert len(bucketer._plan_cache) == 1  # steady-state: one signature


def test_fused_telemetry_metrics():
    from mxnet_tpu import telemetry
    telemetry.enable()
    try:
        telemetry.REGISTRY.reset()
        keys, shapes, dtypes = [0, 1, 2], [(4,), (5,), (6,)], ["float32"] * 3
        vals = _make_values(shapes, dtypes, n_rep=2)
        _run_pushpull(25, keys, shapes, dtypes, vals)
        assert telemetry.counter(
            "mxnet_kvstore_fused_pushpulls_total").value == 1
        assert telemetry.counter(
            "mxnet_kvstore_fused_buckets_total").value == 1
        assert telemetry.counter("mxnet_kvstore_fused_keys_total").value == 3
        nbytes = sum(4 * int(np.prod(s)) for s in shapes) * 2
        assert telemetry.counter(
            "mxnet_kvstore_fused_bytes_total").value == nbytes
        assert telemetry.histogram(
            "mxnet_kvstore_fused_bucket_seconds").count == 1
        text = telemetry.to_prometheus()
        assert "mxnet_kvstore_fused_buckets_total" in text
    finally:
        telemetry.disable()
        telemetry.clear()
