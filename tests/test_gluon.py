"""Gluon tests (reference tests/python/unittest/test_gluon.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    return net


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize(ctx=mx.cpu())
    assert p.data().shape == (3, 4)
    assert p.grad().shape == (3, 4)
    p.set_data(nd.ones((3, 4)))
    assert p.data().asnumpy().sum() == 12


def test_parameter_deferred_init():
    d = nn.Dense(8)
    d.initialize()
    with pytest.raises(Exception):
        d.weight.data()  # deferred until first forward
    x = nd.ones((2, 5))
    d(x)
    assert d.weight.shape == (8, 5)


def test_collect_params_prefix_and_select():
    net = _mlp()
    params = net.collect_params()
    names = list(params.keys())
    assert all(n.startswith(net.prefix) for n in names)
    ws = net.collect_params(".*weight")
    assert all(n.endswith("weight") for n in ws.keys())


def test_shared_params():
    d1 = nn.Dense(8, in_units=4)
    d2 = nn.Dense(8, in_units=4, params=d1.params)
    d1.initialize()
    x = nd.ones((2, 4))
    assert_almost_equal(d1(x).asnumpy(), d2(x).asnumpy())


def test_dense_flatten_modes():
    d = nn.Dense(6, flatten=False)
    d.initialize()
    x = nd.ones((2, 3, 5))
    assert d(x).shape == (2, 3, 6)


def test_sequential_indexing():
    net = _mlp()
    assert len(net) == 2
    assert isinstance(net[0], nn.Dense)
    sub = net[0:1]
    assert len(sub) == 1


def test_hybridize_parity():
    net = _mlp()
    net.initialize()
    x = nd.array(np.random.randn(4, 10).astype("float32"))
    out1 = net(x).asnumpy()
    net.hybridize()
    out2 = net(x).asnumpy()
    assert_almost_equal(out1, out2, rtol=1e-5, atol=1e-5)


def test_hybridize_grad_parity():
    x = nd.array(np.random.randn(4, 10).astype("float32"))

    def grads(hybrid):
        mx.random.seed(3)
        net = _mlp()
        net.initialize()
        if hybrid:
            net.hybridize()
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        return {k: p.grad().asnumpy()
                for k, p in net.collect_params().items()}

    g_imp = grads(False)
    g_hyb = grads(True)
    for k in g_imp:
        ki = k.split("_", 1)[1]
        match = [kk for kk in g_hyb if kk.split("_", 1)[1] == ki]
        assert match, f"missing param {k}"
        assert_almost_equal(g_imp[k], g_hyb[match[0]], rtol=1e-4, atol=1e-4,
                            names=(k, match[0]))


def test_trainer_sgd_training_converges():
    np.random.seed(0)
    mx.random.seed(0)
    net = _mlp()
    net.initialize(init="xavier")
    x = nd.array(np.random.randn(32, 10).astype("float32"))
    y = nd.array(np.random.randint(0, 4, (32,)))
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01})
    first = last = None
    for i in range(20):
        with autograd.record():
            L = lossf(net(x), y).mean()
        L.backward()
        tr.step(1)
        v = float(L.asnumpy())
        first = v if first is None else first
        last = v
    assert last < first * 0.7


def test_trainer_save_load_states(tmp_path):
    net = _mlp()
    net.initialize()
    x = nd.ones((2, 10))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    with autograd.record():
        L = net(x).sum()
    L.backward()
    tr.step(1)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr.load_states(f)


def test_save_load_parameters(tmp_path):
    net = _mlp()
    net.initialize()
    x = nd.ones((2, 10))
    ref = net(x).asnumpy()
    f = str(tmp_path / "p.params")
    net.save_parameters(f)
    net2 = _mlp()
    net2.load_parameters(f)
    assert_almost_equal(net2(x).asnumpy(), ref)


def test_block_repr_and_children():
    net = _mlp()
    r = repr(net)
    assert "Dense" in r
    assert len(net._children) == 2


def test_losses():
    pred = nd.array(np.random.randn(4, 5).astype("float32"))
    label = nd.array(np.random.randint(0, 5, (4,)))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    p = pred.asnumpy()
    lp = p - np.log(np.exp(p - p.max(-1, keepdims=True)).sum(
        -1, keepdims=True)) - p.max(-1, keepdims=True)
    ref = -lp[np.arange(4), label.asnumpy().astype(int)]
    assert_almost_equal(l.asnumpy(), ref, rtol=1e-4, atol=1e-4)

    a = nd.array(np.random.randn(4, 3).astype("float32"))
    b = nd.array(np.random.randn(4, 3).astype("float32"))
    assert_almost_equal(
        gluon.loss.L2Loss()(a, b).asnumpy(),
        0.5 * ((a.asnumpy() - b.asnumpy()) ** 2).mean(axis=1),
        rtol=1e-4, atol=1e-5)
    assert_almost_equal(
        gluon.loss.L1Loss()(a, b).asnumpy(),
        np.abs(a.asnumpy() - b.asnumpy()).mean(axis=1),
        rtol=1e-4, atol=1e-5)


def test_ctc_loss_runs():
    pred = nd.array(np.random.uniform(-1, 1, (2, 10, 5)).astype("float32"))
    label = nd.array(np.array([[1, 2, 0], [2, 3, 4]], dtype="float32"))
    loss = gluon.loss.CTCLoss()(pred, label)
    assert loss.shape == (2,)
    assert np.isfinite(loss.asnumpy()).all()


def test_constant_param():
    class Net(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.c = self.params.get_constant("c", [[1.0, 2.0]])

        def hybrid_forward(self, F, x, c):
            return x * c

    net = Net()
    net.initialize()
    out = net(nd.ones((2, 2)))
    assert_almost_equal(out.asnumpy(), [[1, 2], [1, 2]])


def test_embedding_layer():
    emb = nn.Embedding(10, 6)
    emb.initialize()
    out = emb(nd.array(np.array([1, 2, 3])))
    assert out.shape == (3, 6)


def test_batchnorm_layer_global_stats():
    bn = nn.BatchNorm(use_global_stats=True, in_channels=3)
    bn.initialize()
    x = nd.array(np.random.randn(2, 3, 4, 4).astype("float32"))
    out = bn(x)  # uses running stats (0 mean, 1 var)
    assert_almost_equal(out.asnumpy(), x.asnumpy(), rtol=1e-2, atol=1e-2)


def test_apply_and_hooks():
    net = _mlp()
    net.initialize()
    seen = []
    net.apply(lambda b: seen.append(type(b).__name__))
    assert "Dense" in seen and "HybridSequential" in seen
    calls = []
    net.register_forward_hook(lambda blk, inp, out: calls.append(1))
    net(nd.ones((1, 10)))
    assert calls
