"""Detection data-pipeline tests (VERDICT r3 item 6; reference
python/mxnet/image/detection.py + src/io ImageDetRecordIter + im2rec
--pack-label)."""

import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image, recordio

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _packed_label(boxes):
    """[A=4, B=5, 0, 0, (cls x0 y0 x1 y1)*]"""
    flat = [4, 5, 0, 0]
    for b in boxes:
        flat.extend(b)
    return np.asarray(flat, np.float32)


def _write_det_rec(tmp_path, n=10, size=40, seed=0):
    import cv2
    prefix = os.path.join(str(tmp_path), "det")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rng = np.random.RandomState(seed)
    truths = []
    for i in range(n):
        img = np.zeros((size, size, 3), np.uint8)
        w = rng.randint(10, 18)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - w)
        img[y0:y0 + w, x0:x0 + w] = (255, 128, 0)
        box = [float(i % 3), x0 / size, y0 / size,
               (x0 + w) / size, (y0 + w) / size]
        truths.append(box)
        ok, buf = cv2.imencode(".png", img)
        assert ok
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, _packed_label([box]), i, 0),
            buf.tobytes()))
    rec.close()
    return prefix + ".rec", truths


def test_parse_det_label_and_errors():
    objs = image._parse_det_label(_packed_label([[1, .1, .2, .5, .6],
                                                 [0, 0, 0, 1, 1]]))
    assert objs.shape == (2, 5)
    np.testing.assert_allclose(objs[0], [1, .1, .2, .5, .6])
    with pytest.raises(mx.MXNetError):
        image._parse_det_label(np.array([9, 1, 2], np.float32))  # B < 5


def test_det_horizontal_flip_boxes():
    src = np.arange(4 * 4 * 3, dtype=np.uint8).reshape(4, 4, 3)
    label = np.array([[0, 0.1, 0.2, 0.4, 0.9]], np.float32)
    aug = image.DetHorizontalFlipAug(p=1.0)
    out, lab = aug(src, label)
    np.testing.assert_array_equal(out, src[:, ::-1])
    np.testing.assert_allclose(lab[0], [0, 0.6, 0.2, 0.9, 0.9], atol=1e-6)


def test_det_random_crop_keeps_covered_boxes(seeded):
    src = np.zeros((40, 40, 3), np.uint8)
    label = np.array([[2, 0.25, 0.25, 0.75, 0.75]], np.float32)
    aug = image.DetRandomCropAug(min_object_covered=0.9,
                                 area_range=(0.8, 1.0),
                                 min_eject_coverage=0.5, max_attempts=50)
    out, lab = aug(src, label)
    assert lab.shape[0] == 1                 # box survived
    assert (lab[:, 1:] >= 0).all() and (lab[:, 1:] <= 1).all()
    assert lab[0, 3] > lab[0, 1] and lab[0, 4] > lab[0, 2]


def test_det_random_pad_shrinks_boxes(seeded):
    src = np.full((20, 20, 3), 200, np.uint8)
    label = np.array([[1, 0.0, 0.0, 1.0, 1.0]], np.float32)
    aug = image.DetRandomPadAug(area_range=(1.5, 2.5), max_attempts=50)
    out, lab = aug(src, label)
    assert out.shape[0] >= 20 and out.shape[1] >= 20
    # the (full-image) box now covers a strict subset of the canvas
    w = lab[0, 3] - lab[0, 1]
    h = lab[0, 4] - lab[0, 2]
    assert w * h < 1.0
    assert (lab[:, 1:] >= 0).all() and (lab[:, 1:] <= 1).all()


def test_image_det_iter_over_records(tmp_path, seeded):
    rec_path, truths = _write_det_rec(tmp_path, n=10)
    it = image.ImageDetIter(batch_size=5, data_shape=(3, 32, 32),
                            path_imgrec=rec_path)
    assert it.label_shape == (1, 5)
    batches = list(it)
    assert len(batches) == 2
    seen = []
    for b in batches:
        assert b.data[0].shape == (5, 3, 32, 32)
        lab = b.label[0].asnumpy()
        assert lab.shape == (5, 1, 5)
        for row in lab[:, 0]:
            assert row[0] >= 0              # every record has one object
            assert (row[1:] >= 0).all() and (row[1:] <= 1).all()
            seen.append(tuple(np.round(row, 5)))
    # unshuffled: labels come back in record order
    np.testing.assert_allclose([s for s in seen],
                               np.asarray(truths, np.float32), atol=1e-5)


def test_image_det_iter_pads_variable_objects(tmp_path):
    import cv2
    prefix = os.path.join(str(tmp_path), "multi")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    counts = [1, 3, 2]
    for i, cnt in enumerate(counts):
        img = np.zeros((24, 24, 3), np.uint8)
        boxes = [[c, 0.1 * (c + 1), 0.1, 0.1 * (c + 1) + 0.2, 0.4]
                 for c in range(cnt)]
        ok, buf = cv2.imencode(".png", img)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, _packed_label(boxes), i, 0),
            buf.tobytes()))
    rec.close()
    it = image.ImageDetIter(batch_size=3, data_shape=(3, 24, 24),
                            path_imgrec=prefix + ".rec")
    assert it.label_shape == (3, 5)
    b = next(it)
    lab = b.label[0].asnumpy()
    for i, cnt in enumerate(counts):
        assert (lab[i, :cnt, 0] >= 0).all()
        assert (lab[i, cnt:, 0] == -1).all()   # -1 padding rows


def test_im2rec_pack_label_roundtrip(tmp_path):
    import importlib.util
    import cv2
    spec = importlib.util.spec_from_file_location(
        "im2rec", os.path.join(_ROOT, "tools", "im2rec.py"))
    im2rec = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(im2rec)

    root = os.path.join(str(tmp_path), "imgs")
    os.makedirs(root)
    for i in range(3):
        cv2.imwrite(os.path.join(root, f"im{i}.png"),
                    np.full((16, 16, 3), 50 * i, np.uint8))
    prefix = os.path.join(str(tmp_path), "detpack")
    with open(prefix + ".lst", "w") as f:
        for i in range(3):
            boxes = f"{4}\t{5}\t0\t0\t{i}\t0.1\t0.2\t0.5\t0.6"
            f.write(f"{i}\t{boxes}\tim{i}.png\n")
    n, skipped = im2rec.make_rec(prefix, root, pack_label=True)
    assert (n, skipped) == (3, 0)

    it = image.ImageDetIter(batch_size=3, data_shape=(3, 16, 16),
                            path_imgrec=prefix + ".rec")
    lab = next(it).label[0].asnumpy()
    np.testing.assert_allclose(lab[:, 0, 0], [0, 1, 2])
    np.testing.assert_allclose(lab[:, 0, 1:], [[0.1, 0.2, 0.5, 0.6]] * 3,
                               atol=1e-6)


def test_ssd_example_trains_from_records(tmp_path):
    """The SSD lane fed by PACKED RECORDS instead of synthetic arrays
    (VERDICT r3 item 6 'feed the SSD example from packed records')."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "train_ssd", os.path.join(_ROOT, "examples", "ssd", "train_ssd.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.make_det_records(os.path.join(str(tmp_path), "shapes"),
                               n=96, size=32, seed=1)
    out = mod.run(batch=16, steps=40, log=False, from_records=rec)
    assert out["last_loss"] < out["first_loss"]
    assert out["mean_top_iou"] > 0.05


def test_image_det_iter_from_lst(tmp_path):
    """Packed .lst path keeps every box (label_width=-1 variable labels —
    review regression: a fixed width silently dropped all objects)."""
    import cv2
    root = os.path.join(str(tmp_path), "imgs")
    os.makedirs(root)
    for i in range(2):
        cv2.imwrite(os.path.join(root, f"a{i}.png"),
                    np.full((16, 16, 3), 90, np.uint8))
    lst = os.path.join(str(tmp_path), "det.lst")
    with open(lst, "w") as f:
        f.write("0\t4\t5\t0\t0\t1\t0.1\t0.2\t0.5\t0.6\ta0.png\n")
        f.write("1\t4\t5\t0\t0\t2\t0.3\t0.3\t0.9\t0.8\t0\t0\t0\t1\t1"
                "\ta1.png\n")
    it = image.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                            path_imglist=lst, path_root=root)
    assert it.label_shape == (2, 5)
    lab = next(it).label[0].asnumpy()
    np.testing.assert_allclose(lab[0, 0], [1, 0.1, 0.2, 0.5, 0.6],
                               atol=1e-6)
    assert lab[0, 1, 0] == -1                       # padded slot
    np.testing.assert_allclose(lab[1, 1], [0, 0, 0, 1, 1], atol=1e-6)


def test_image_det_iter_truncates_wide_objects(tmp_path):
    """Records with B=6 extra attributes + explicit label_shape width 5:
    extra columns are truncated, not a broadcast crash (review
    regression)."""
    import cv2
    prefix = os.path.join(str(tmp_path), "wide")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    img = np.zeros((16, 16, 3), np.uint8)
    ok, buf = cv2.imencode(".png", img)
    label = np.array([4, 6, 0, 0, 1, 0.1, 0.2, 0.5, 0.6, 0.77], np.float32)
    rec.write_idx(0, recordio.pack(recordio.IRHeader(0, label, 0, 0),
                                   buf.tobytes()))
    rec.close()
    it = image.ImageDetIter(batch_size=1, data_shape=(3, 16, 16),
                            path_imgrec=prefix + ".rec",
                            label_shape=(1, 5))
    lab = next(it).label[0].asnumpy()
    np.testing.assert_allclose(lab[0, 0], [1, 0.1, 0.2, 0.5, 0.6],
                               atol=1e-6)
