"""Worker body for the N-process dist_tpu_sync tests (run via
tools/launch.py; mirrors tests/nightly/dist_sync_kvstore.py exact-value
checks).  Not collected by pytest (no test_ prefix).

Every expected value is a closed form in N = num_workers, so the same
body runs the 2-process tier-1 test and the 4-process scaling test
(ISSUE 7 satellite) unchanged.  The 2-bit-compression section needs an
even N: ranks 0/1 drive the exact quantization pattern and every higher
rank pair pushes values that stay strictly inside the threshold band
(quantize to 0 in both rounds), keeping the wire sums N-independent."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# the axon PJRT plugin overrides the JAX_PLATFORMS env var, so pin the
# platform through jax.config (same trick as tests/conftest.py)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    # multi-process computations on the CPU backend need a host
    # collectives implementation (ISSUE 3 satellite: this missing config
    # was the failure behind the 2-proc dist tier-1 flake — the psum
    # raised "Multiprocess computations aren't implemented on the CPU
    # backend"); must be set BEFORE backend initialization
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass  # older jaxlib without gloo: the kvstore deadline bounds it

# distributed init MUST precede backend init (jax.distributed contract)
jax.distributed.initialize(
    coordinator_address=os.environ["MXNET_DIST_COORDINATOR"],
    num_processes=int(os.environ["MXNET_DIST_NUM_WORKERS"]),
    process_id=int(os.environ["MXNET_DIST_RANK"]))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def main():
    kv = mx.kv.create("dist_tpu_sync")
    N = kv.num_workers
    assert N == int(os.environ["MXNET_DIST_NUM_WORKERS"]), N
    rank = kv.rank
    shape = (3, 4)
    tri = N * (N + 1) // 2           # sum_r (r + 1)

    # 1. exact-value dense allreduce: each worker pushes rank+1 everywhere
    kv.init(3, mx.nd.zeros(shape))
    kv.push(3, mx.nd.array(np.full(shape, rank + 1.0, np.float32)))
    out = mx.nd.zeros(shape)
    kv.pull(3, out)
    np.testing.assert_allclose(out.asnumpy(), float(tri))

    # 2. second round with different values (checks no stale state)
    kv.push(3, mx.nd.array(np.full(shape, (rank + 1) * 10.0, np.float32)))
    kv.pull(3, out)
    np.testing.assert_allclose(out.asnumpy(), 10.0 * tri)

    # 3. rank-dependent structured values: position (i, j) gets
    #    sum_r (r + i + j) = N*(i + j) + N(N-1)/2
    base = np.add.outer(np.arange(3), np.arange(4)).astype(np.float32)
    kv.push(3, mx.nd.array(base + rank))
    kv.pull(3, out)
    np.testing.assert_allclose(out.asnumpy(),
                               N * base + N * (N - 1) / 2.0)

    # 4. barrier + multi-key list API
    kv.barrier()
    kv.init([5, 7], [mx.nd.zeros((2,)), mx.nd.zeros((2,))])
    kv.push([5, 7], [mx.nd.ones((2,)) * (rank + 1),
                     mx.nd.ones((2,)) * (rank + 5)])
    outs = [mx.nd.zeros((2,)), mx.nd.zeros((2,))]
    kv.pull([5, 7], outs)
    np.testing.assert_allclose(outs[0].asnumpy(), float(tri))
    np.testing.assert_allclose(outs[1].asnumpy(), float(tri + 4 * N))

    # 5. fused pushpull_list (ISSUE 2): the whole key list buckets into
    #    flat buffers and crosses processes as ONE psum per bucket
    kv.init([20, 21, 22], [mx.nd.zeros((3,)), mx.nd.zeros((2, 2)),
                           mx.nd.zeros((5,))])
    for rnd in range(2):  # second round re-uses the cached plan/executables
        vals = [mx.nd.ones((3,)) * (rank + 1 + rnd),
                mx.nd.ones((2, 2)) * (rank + 2 + rnd),
                mx.nd.ones((5,)) * (rank + 3 + rnd)]
        outs = [mx.nd.zeros((3,)), mx.nd.zeros((2, 2)), mx.nd.zeros((5,))]
        kv.pushpull_list([20, 21, 22], vals, outs)
        np.testing.assert_allclose(outs[0].asnumpy(),
                                   float(tri + N * rnd))
        np.testing.assert_allclose(outs[1].asnumpy(),
                                   float(tri + N * (1 + rnd)))
        np.testing.assert_allclose(outs[2].asnumpy(),
                                   float(tri + N * (2 + rnd)))
    assert kv._bucketer is not None and kv._bucketer.builds == 2  # 1 bucket

    # 6. 2-bit compression over the wire (packed allgather path), exact
    #    values at threshold t=0.5.  Ranks 0/1 replay the canonical
    #    pattern: +0.7 → +t / -0.6 → -t (sum 0), then residual-fed
    #    0.2+0.4 → +t / -0.1-0.3 → 0 (sum +t).  Ranks >= 2 push ±0.1
    #    then ±0.1 again: accumulated ±0.2 never crosses t, so they
    #    quantize to 0 BOTH rounds and the sums stay N-independent.
    assert N % 2 == 0, "2-bit section is designed for even N"
    kv2 = mx.kv.create("dist_tpu_sync")
    kv2.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    shape2 = (2, 3)
    kv2.init(11, mx.nd.zeros(shape2))
    if rank == 0:
        first, second = 0.7, 0.4
    elif rank == 1:
        first, second = -0.6, -0.3
    else:
        first = second = 0.1 if rank % 2 == 0 else -0.1
    kv2.push(11, mx.nd.array(np.full(shape2, first, np.float32)))
    out2 = mx.nd.zeros(shape2)
    kv2.pull(11, out2)
    np.testing.assert_allclose(out2.asnumpy(), 0.0)
    kv2.push(11, mx.nd.array(np.full(shape2, second, np.float32)))
    kv2.pull(11, out2)
    np.testing.assert_allclose(out2.asnumpy(), 0.5)

    print(f"worker {rank}/{N}: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
