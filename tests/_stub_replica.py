#!/usr/bin/env python
"""jax-free stub replica worker — the fast router tests' engine.

Drives the EXACT protocol/supervision code the real llama replica uses
(``serving.replica.ReplicaServer``) with a deterministic token oracle,
so the router failure matrix (death, ack-window death, hedge, shed,
hang, re-adoption) runs in milliseconds per request instead of paying a
jit compile per replica.

Oracle: ``tokens[k] = (sum(prompt) % 97 * 31 + k) % 97`` — replica- and
batching-independent, so a retried request's output on a survivor is
token-identical by construction, mirroring the greedy-decode determinism
of identically seeded real replicas.

Failure knobs (env):
  STUB_TOKEN_DELAY_S   per-token sleep (load / hedging / shed tests)
  STUB_DIE_TOKEN       prompt containing this token => os._exit(1)
                       BEFORE computing (death mid-decode)
  STUB_WEDGE_TOKEN     prompt containing this token => stop the
                       heartbeat and block the RPC thread forever (the
                       hang the router must SIGKILL out of the tier)
  STUB_ONCE_MARKER     marker-file path making die/wedge fire ONCE
                       across respawns (the respawned twin must serve)
  MXNET_CHAOS(_SITES)  the usual chaos grammar; ``serving.reply:exit:1``
                       is the ack-window death.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.resilience import heartbeat as hb              # noqa: E402
from mxnet_tpu.serving.engine import RequestDeadlineExceeded  # noqa: E402
from mxnet_tpu.serving.replica import ReplicaServer           # noqa: E402


def oracle_tokens(prompt, max_new_tokens):
    s = sum(int(t) for t in prompt) % 97
    return [(s * 31 + k) % 97 for k in range(int(max_new_tokens))]


class _Handle:
    def __init__(self):
        self._ev = threading.Event()
        self.tokens = None
        self.error = None

    def wait(self, timeout_s=None):
        return self._ev.wait(timeout_s)

    def result(self, timeout=None):
        self._ev.wait(timeout if timeout else 300.0)
        if self.error is not None:
            raise self.error
        if self.tokens is None:
            raise RequestDeadlineExceeded("stub handle never resolved")
        return list(self.tokens)


class StubEngine:
    max_batch = 4

    def __init__(self):
        self.delay = float(os.environ.get("STUB_TOKEN_DELAY_S", "0"))
        self.die_token = int(os.environ.get("STUB_DIE_TOKEN", "-1"))
        self.wedge_token = int(os.environ.get("STUB_WEDGE_TOKEN", "-1"))
        self.once_marker = os.environ.get("STUB_ONCE_MARKER", "")
        self._lock = threading.Lock()
        self._queued = 0
        self._active = 0

    def _fire_once(self):
        """Destructive triggers fire once per marker file, so the
        respawned/surviving twin serves the retried request instead of
        dying on the same prompt forever."""
        if not self.once_marker:
            return True
        try:
            fd = os.open(self.once_marker,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            return True
        except FileExistsError:
            return False

    def submit(self, prompt, max_new_tokens=32, deadline_s=None):
        prompt = [int(t) for t in prompt]
        if self.die_token in prompt and self._fire_once():
            os._exit(1)                     # death before any token
        if self.wedge_token in prompt and self._fire_once():
            hb.stop()                       # heartbeat goes stale...
            time.sleep(10000)               # ...and the RPC thread hangs
        h = _Handle()
        with self._lock:
            self._queued += 1

        def work():
            with self._lock:
                self._queued -= 1
                self._active += 1
            t0 = time.monotonic()
            try:
                for _ in range(int(max_new_tokens)):
                    if self.delay:
                        time.sleep(self.delay)
                    if deadline_s is not None \
                            and time.monotonic() - t0 > float(deadline_s):
                        h.error = RequestDeadlineExceeded(
                            f"stub request blew its {deadline_s}s budget")
                        return
                h.tokens = oracle_tokens(prompt, max_new_tokens)
            finally:
                with self._lock:
                    self._active -= 1
                h._ev.set()

        threading.Thread(target=work, daemon=True).start()
        return h

    def load(self):
        with self._lock:
            return (self._queued, self._active, 999)

    def stop(self):
        pass


def main():
    workdir = os.environ["MXNET_ROUTER_DIR"]
    index = int(os.environ["MXNET_ROUTER_INDEX"])
    hb.start()
    hb.set_phase("bringup")
    srv = ReplicaServer(StubEngine(), workdir, index)
    srv.bind()
    hb.set_phase("running")
    srv.run()
    hb.mark_done()
    return 0


if __name__ == "__main__":
    sys.exit(main())
