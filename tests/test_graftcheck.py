"""graftcheck tests (ISSUE 4): fixture snippets that trigger and suppress
each rule GC01–GC05, the GC00 suppression-hygiene contract, a whole-repo
clean run, and the dynamic twin (runtime.no_retrace) on a real Trainer
steady-state step."""

import os
import textwrap

import numpy as np
import pytest

from mxnet_tpu import analysis
from mxnet_tpu.analysis import check_source
from mxnet_tpu.analysis.core import parse_suppressions

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return [f.rule for f in findings]


def _check(src, rel):
    return check_source(textwrap.dedent(src), rel=rel)


# --------------------------------------------------------------------------
# GC01 — host-sync on the hot path
# --------------------------------------------------------------------------

def test_gc01_flags_item_and_casts_on_traced_values():
    findings, _ = _check("""
        import jax.numpy as jnp

        def reduce_bucket(nds):
            x = jnp.stack(nds)
            total = float(x)          # cast syncs
            n = len(x)                # len on traced value
            v = x.item()              # explicit sync
            return total, n, v
        """, rel="kvstore/fusion.py")
    assert _rules(findings).count("GC01") == 3


def test_gc01_flags_asnumpy_asarray_waitall():
    findings, _ = _check("""
        import numpy as np

        def push(value):
            a = value._data
            h = np.asarray(a)
            value.asnumpy()
            nd.waitall()
            return h
        """, rel="kvstore/fusion.py")
    assert _rules(findings).count("GC01") == 3


def test_gc01_ignores_cold_modules_and_host_values():
    findings, _ = _check("""
        import numpy as np
        import jax.numpy as jnp

        def anything(x):
            return float(jnp.sum(x))  # not a designated hot module
        """, rel="image.py")
    assert "GC01" not in _rules(findings)
    # host-side values (shapes, lists) never flag inside hot modules
    findings, _ = _check("""
        def plan(shapes):
            sizes = [int(d) for s in shapes for d in s]
            return len(sizes)
        """, rel="kvstore/fusion.py")
    assert "GC01" not in _rules(findings)


def test_gc01_suppression_with_justification():
    findings, suppressed = _check("""
        def reduce(v):
            # graftcheck: ignore[GC01] — sparse merge is host-side by design
            return v._data.item()
        """, rel="kvstore/fusion.py")
    assert "GC01" not in _rules(findings)
    assert len(suppressed) == 1


# --------------------------------------------------------------------------
# GC02 — retrace hazards
# --------------------------------------------------------------------------

def test_gc02_flags_self_capture():
    findings, _ = _check("""
        import jax

        class Runner:
            def build(self):
                def raw(x):
                    return x * self.scale
                return jax.jit(raw)
        """, rel="anything.py")
    assert "GC02" in _rules(findings)


def test_gc02_flags_mutable_global_and_reassigned_local():
    findings, _ = _check("""
        import jax

        _mode = "fast"

        def set_mode(m):
            global _mode
            _mode = m

        def build():
            scale = 1.0
            scale = 2.0

            def raw(x):
                if _mode == "fast":
                    return x * scale
                return x
            return jax.jit(raw)
        """, rel="anything.py")
    assert _rules(findings).count("GC02") == 2  # global + local


def test_gc02_flags_jit_per_call_and_mutable_default():
    findings, _ = _check("""
        import jax

        def run(x):
            return jax.jit(lambda a: a + 1)(x)

        def build():
            def raw(x, opts={"mode": 1}):
                return x
            return jax.jit(raw)
        """, rel="anything.py")
    assert _rules(findings).count("GC02") == 2


def test_gc02_flags_untyped_kwargs():
    findings, _ = _check("""
        import jax

        def build():
            def raw(x, **attrs):
                return x
            return jax.jit(raw)

        def build_ok():
            def raw(x, **attrs):
                return x
            return jax.jit(raw, static_argnames=("mode",))
        """, rel="anything.py")
    assert _rules(findings).count("GC02") == 1


def test_gc02_clean_patterns_pass():
    findings, _ = _check("""
        import jax

        def build(n_keys, n_rep):
            def fuse(*arrs):
                return sum(arrs[:n_keys]) * n_rep
            return jax.jit(fuse)

        def build_defaults(fn, static):
            def wrapper(vals, *arrays, _fn=fn, _keys=("a",)):
                return _fn(*arrays)
            return jax.jit(wrapper)
        """, rel="anything.py")
    assert "GC02" not in _rules(findings)


def test_gc02_suppression():
    findings, suppressed = _check("""
        import jax

        class C:
            def build(self):
                def raw(x):
                    return x * self.scale
                # graftcheck: ignore[GC02] — cache keyed on shapes+epoch
                return jax.jit(raw)
        """, rel="anything.py")
    assert "GC02" not in _rules(findings)
    assert len(suppressed) == 1


# --------------------------------------------------------------------------
# GC03 — knob hygiene
# --------------------------------------------------------------------------

def test_gc03_flags_env_reads_outside_config():
    findings, _ = _check("""
        import os

        def knobs(kind):
            a = os.environ.get("MXNET_FOO", "1")
            b = os.environ["MXNET_BAR"]
            c = os.getenv("MXNET_BAZ")
            d = os.environ.get(
                "MXNET_QUX_A" if kind == "a" else "MXNET_QUX_B")
            return a, b, c, d
        """, rel="kvstore/somewhere.py")
    assert _rules(findings).count("GC03") == 4


def test_gc03_config_py_and_non_mxnet_vars_exempt():
    findings, _ = _check("""
        import os

        def get(name):
            x = os.environ.get("MXNET_ANYTHING")
            y = os.environ.get("JAX_PLATFORMS")
            return x, y
        """, rel="config.py")
    assert "GC03" not in _rules(findings)
    findings, _ = _check("""
        import os
        v = os.environ.get("JAX_COORDINATOR_ADDRESS")
        """, rel="kvstore/dist.py")
    assert "GC03" not in _rules(findings)


def test_gc03_readme_knob_table(tmp_path):
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "config.py").write_text(textwrap.dedent("""
        KNOWN_VARS = {
            "MXNET_DOCUMENTED": ("1", int, "doc'd"),
            "MXNET_FORGOTTEN": ("0", int, "not in readme"),
        }
        """))
    (tmp_path / "README.md").write_text("only `MXNET_DOCUMENTED` here\n")
    findings, _, _ = analysis.analyze_paths([str(pkg)],
                                            repo_root=str(tmp_path))
    msgs = [f.message for f in findings if f.rule == "GC03"]
    assert len(msgs) == 1 and "MXNET_FORGOTTEN" in msgs[0]


# --------------------------------------------------------------------------
# GC04 — lock discipline
# --------------------------------------------------------------------------

def test_gc04_flags_mixed_lock_discipline():
    findings, _ = _check("""
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def inc(self):
                with self._lock:
                    self._count += 1

            def reset(self):
                self._count = 0
        """, rel="telemetry/metrics.py")
    assert _rules(findings) == ["GC04"]
    assert "reset" in findings[0].message


def test_gc04_module_global_and_exemptions():
    findings, _ = _check("""
        import threading

        _lock = threading.Lock()
        _counts = {}

        def hit(site):
            with _lock:
                _counts[site] = _counts.get(site, 0) + 1

        def sneaky(site):
            _counts[site] = 0
        """, rel="resilience/chaos.py")
    assert _rules(findings) == ["GC04"]
    # all-lock-free modules (no mixed discipline) and cold modules: clean
    findings, _ = _check("""
        class C:
            def a(self):
                self._x = 1

            def b(self):
                self._x = 2
        """, rel="telemetry/metrics.py")
    assert "GC04" not in _rules(findings)


def test_gc04_suppression():
    findings, suppressed = _check("""
        import threading

        class C:
            def locked(self):
                with self._lock:
                    self._x = 1

            def helper(self):
                # graftcheck: ignore[GC04] — caller holds self._lock
                self._x = 2
        """, rel="telemetry/metrics.py")
    assert "GC04" not in _rules(findings)
    assert len(suppressed) == 1


# --------------------------------------------------------------------------
# GC05 — telemetry-flag discipline
# --------------------------------------------------------------------------

def test_gc05_flags_double_flag_read():
    findings, _ = _check("""
        from ..telemetry import tracer as _ttrace

        def invoke(op):
            t0 = 1 if _ttrace._ENABLED else None
            run(op)
            if _ttrace._ENABLED:
                record(t0)
        """, rel="ops/registry.py")
    assert _rules(findings) == ["GC05"]


def test_gc05_single_read_and_cold_module_pass():
    findings, _ = _check("""
        def invoke(op):
            enabled = _ttrace._ENABLED
            if enabled:
                start()
            run(op)
            if enabled:
                stop()
        """, rel="ops/registry.py")
    assert "GC05" not in _rules(findings)
    findings, _ = _check("""
        def anywhere():
            if _ttrace._ENABLED and _ttrace._ENABLED:
                pass
        """, rel="random.py")
    assert "GC05" not in _rules(findings)


# --------------------------------------------------------------------------
# GC00 — suppression hygiene
# --------------------------------------------------------------------------

def test_gc00_bare_suppression_is_a_finding():
    findings, suppressed = _check("""
        def reduce(v):
            return v._data.item()  # graftcheck: ignore[GC01]
        """, rel="kvstore/fusion.py")
    rules = _rules(findings)
    assert "GC00" in rules and "GC01" in rules  # unjustified = not honored
    assert not suppressed


def test_gc00_bare_suppression_without_finding_still_flagged():
    # an unjustified ignore is a finding even when it suppresses nothing
    # (it would otherwise rot silently once the flagged code is fixed)
    findings, suppressed = _check("""
        def f():
            pass  # graftcheck: ignore[GC01]
        """, rel="anything.py")
    assert _rules(findings) == ["GC00"]
    assert not suppressed


def test_gc00_trailing_suppression_not_dropped():
    # a dangling ignore at EOF governs nothing but must not vanish
    findings, _ = _check("""
        def f():
            pass
        # graftcheck: ignore[GC99] — justified but bogus rule
        """, rel="anything.py")
    assert "GC00" in _rules(findings)


def test_gc00_unknown_rule_is_a_finding():
    findings, _ = _check("""
        def f():
            pass  # graftcheck: ignore[GC99] — justified but bogus
        """, rel="anything.py")
    assert "GC00" in _rules(findings)


def test_suppression_parsing_stacked_comments():
    sup = parse_suppressions([
        "# graftcheck: ignore[GC01] — reason one",
        "# more prose",
        "x = sync()",
    ])
    assert 3 in sup
    rules, just, at = sup[3]
    assert rules == frozenset({"GC01"}) and just == "reason one" and at == 1


# --------------------------------------------------------------------------
# whole-repo contract (the CI gate)
# --------------------------------------------------------------------------

def test_repo_is_clean():
    """The acceptance bar: zero unsuppressed findings over mxnet_tpu/,
    and every suppression that exists carries a justification (a bare
    one would surface as GC00 above)."""
    pkg = os.path.join(REPO_ROOT, "mxnet_tpu")
    findings, suppressed, modules = analysis.analyze_paths(
        [pkg], repo_root=REPO_ROOT)
    assert len(modules) > 100
    assert findings == [], "\n".join(f.render() for f in findings)
    # the suppression ledger stays deliberate: every entry is justified
    assert suppressed, "expected the documented suppressions to register"


def test_cli_exit_codes(tmp_path):
    from mxnet_tpu.analysis import core
    pkg = os.path.join(REPO_ROOT, "mxnet_tpu")
    assert core.main([pkg, "-q"], repo_root=REPO_ROOT) == 0
    dirty = tmp_path / "mxnet_tpu"
    dirty.mkdir()
    (dirty / "bad.py").write_text(
        "import os\nv = os.environ.get('MXNET_ROGUE')\n")
    assert core.main([str(dirty), "-q"], repo_root=str(tmp_path)) == 1
    # baseline swallows the known finding; a new one still fails
    base = tmp_path / "baseline.json"
    assert core.main([str(dirty), "--write-baseline", str(base), "-q"],
                     repo_root=str(tmp_path)) == 0
    assert core.main([str(dirty), "--baseline", str(base), "-q"],
                     repo_root=str(tmp_path)) == 0
    (dirty / "bad2.py").write_text(
        "import os\nw = os.environ.get('MXNET_ROGUE2')\n")
    assert core.main([str(dirty), "--baseline", str(base), "-q"],
                     repo_root=str(tmp_path)) == 1
    assert core.main(["--no-such-flag"], repo_root=str(tmp_path)) == 2


def test_baseline_is_a_multiset(tmp_path):
    """One baseline entry excuses exactly ONE occurrence: copy-pasting an
    identical violation next to a baselined one must still fail."""
    from mxnet_tpu.analysis import core
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    line = "v = os.environ.get('MXNET_ROGUE')\n"
    (pkg / "bad.py").write_text("import os\n" + line)
    base = tmp_path / "baseline.json"
    assert core.main([str(pkg), "--write-baseline", str(base), "-q"],
                     repo_root=str(tmp_path)) == 0
    assert core.main([str(pkg), "--baseline", str(base), "-q"],
                     repo_root=str(tmp_path)) == 0
    # same text, second occurrence in the same file: same fingerprint,
    # but the single baseline entry must not cover it
    (pkg / "bad.py").write_text("import os\n" + line + line)
    assert core.main([str(pkg), "--baseline", str(base), "-q"],
                     repo_root=str(tmp_path)) == 1


# --------------------------------------------------------------------------
# runtime twin: no_retrace() on a real Trainer steady state
# --------------------------------------------------------------------------

def test_trainer_steady_state_no_retrace():
    """The dynamic half of GC02: after one warm-up step, a Trainer step
    (dispatch + fused allreduce path + optimizer) must be pure jit-cache
    hits — zero XLA compilations."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.analysis.runtime import no_retrace, RetraceError

    net = nn.Dense(4)
    net.initialize()
    x = nd.array(np.random.randn(8, 4).astype("float32"))
    y = nd.array(np.random.randn(8, 4).astype("float32"))
    lossf = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01})

    def step():
        with autograd.record():
            loss = lossf(net(x), y).mean()
        loss.backward()
        tr.step(1)
        return loss

    for _ in range(2):          # warm-up: trace + compile everything
        step()
    with no_retrace():
        step()                  # steady state: must not compile

    # and the guard actually fires on a real retrace: a fresh jit
    # instance always compiles on first call, whatever ran before
    import jax
    import jax.numpy as jnp
    fresh = jax.jit(lambda a: a - 0.123)
    with pytest.raises(RetraceError):
        with no_retrace():
            fresh(jnp.ones((3,)))
