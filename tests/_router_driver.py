#!/usr/bin/env python
"""Router-tier driver — the subprocess the router-death chaos tests run.

Run 1 submits a request file through a ``serving.Router`` and (when
``--dispatch-exit-after K`` arms the ``router.dispatch`` chaos site)
DIES mid-dispatch: the chaos 'exit' fires inside the dispatcher thread
after K dispatches, dumping a flight-recorder postmortem and pulling the
plug with requests journaled-but-unsent — the exact crash window the
router's write-ahead journal exists for.  Run 2 (``--resume``) restarts
the router on the same workdir: it re-adopts the live replicas through
their port files, re-dispatches the journal (``router.recovered()``),
and this driver submits whatever its request file says is still missing,
then writes every result to ``--out``.

Progress (submits/sheds, with elapsed seconds) is appended to
``progress.log`` line-by-line as it happens, so a killed run 1 still
leaves the shed/fail-fast evidence the test asserts on.
"""

import argparse
import json
import os
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", required=True)
    ap.add_argument("-n", "--nreplicas", type=int, default=2)
    ap.add_argument("--replica-cmd", default=None,
                    help="replica argv as a JSON list (default: the "
                         "jax-free stub worker)")
    ap.add_argument("--replica-env", default=None,
                    help="JSON {index: {ENV: VAL}} per-replica env")
    ap.add_argument("--requests", required=True,
                    help="JSON list of {tag, prompt, max_new_tokens"
                         "[, deadline_s]}")
    ap.add_argument("--out", required=True)
    ap.add_argument("--queue-max", type=int, default=64)
    ap.add_argument("--hedge-s", type=float, default=0.0)
    ap.add_argument("--max-retries", type=int, default=2)
    ap.add_argument("--max-respawns", type=int, default=8)
    ap.add_argument("--hang-s", type=float, default=20.0)
    ap.add_argument("--dispatch-exit-after", type=int, default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--result-timeout", type=float, default=120.0)
    ap.add_argument("--keep-replicas", action="store_true",
                    help="leave replicas running at exit (a later "
                         "--resume run re-adopts them)")
    args = ap.parse_args(argv)

    workdir = os.path.abspath(args.workdir)
    # the router's own lane must land in the tier's collection dirs,
    # BEFORE mxnet_tpu imports (flightrec/atexit arm against these)
    os.environ["MXNET_TELEMETRY"] = "1"
    os.environ["MXNET_TELEMETRY_DIR"] = os.path.join(workdir, "telemetry")
    os.environ["MXNET_FLIGHTREC_DIR"] = os.path.join(workdir, "flightrec")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu import telemetry
    from mxnet_tpu.resilience import chaos
    from mxnet_tpu.serving.router import Router, RouterOverloaded

    if args.dispatch_exit_after is not None:
        chaos.inject("router.dispatch", kind="exit",
                     after=args.dispatch_exit_after, times=1)

    cmd = json.loads(args.replica_cmd) if args.replica_cmd else \
        [sys.executable, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "_stub_replica.py")]
    env_per = {int(k): v for k, v in
               json.loads(args.replica_env).items()} \
        if args.replica_env else None

    with open(args.requests) as f:
        want = json.load(f)

    progress = open(os.path.join(workdir, "progress.log"), "a")

    def note(kind, tag, t0):
        progress.write(f"{kind} {tag} {time.perf_counter() - t0:.4f}\n")
        progress.flush()

    router = Router(cmd, args.nreplicas, workdir,
                    queue_max=args.queue_max, hedge_s=args.hedge_s,
                    max_retries=args.max_retries,
                    max_respawns=args.max_respawns,
                    hang_s=args.hang_s, env_per_replica=env_per).start()
    handles = dict(router.recovered()) if args.resume else {}
    t0 = time.perf_counter()
    shed = []
    for rec in want:
        tag = rec["tag"]
        if tag in handles:
            continue
        try:
            handles[tag] = router.submit(
                rec["prompt"], rec.get("max_new_tokens", 8),
                deadline_s=rec.get("deadline_s"), tag=tag)
            note("submitted", tag, t0)
        except RouterOverloaded:
            shed.append(tag)
            note("shed", tag, t0)

    results = {}
    for tag, h in handles.items():
        try:
            results[tag] = {"tokens": h.result(
                timeout=args.result_timeout)}
        except Exception as exc:  # noqa: BLE001 — recorded for the test
            results[tag] = {"error": type(exc).__name__,
                            "message": str(exc)[:200]}
    for tag in shed:
        results.setdefault(tag, {"error": "RouterOverloaded"})

    out = {
        "results": results,
        "shed": shed,
        "replicas": router.replica_status(),
        "counters": {
            name: telemetry.REGISTRY.get(name).value
            for name in ("mxnet_router_dispatched_total",
                         "mxnet_router_retries_total",
                         "mxnet_router_hedges_total",
                         "mxnet_router_shed_total",
                         "mxnet_router_replica_deaths_total",
                         "mxnet_router_respawns_total")
            if telemetry.REGISTRY.get(name) is not None
        },
    }
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, args.out)
    router.stop(shutdown_replicas=not args.keep_replicas)
    progress.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
