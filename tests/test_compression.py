"""2-bit gradient compression tests (reference
tests/nightly/dist_sync_kvstore.py :: test_sync_2bit_compression — exact
expected values, plus pack/unpack round-trips)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore.compression import GradientCompression


def test_quantize_roundtrip_exact():
    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    grad = mx.nd.array(np.array([0.7, -0.6, 0.1, -0.1, 0.5, -0.5, 0.0],
                                np.float32))
    packed, shape, dtype = gc.compress("k", 0, grad._data)
    assert str(np.asarray(packed).dtype) == "uint8"
    assert packed.size == 2  # ceil(7/4) bytes — 16x smaller than f32
    out = np.asarray(gc.decompress(packed, shape, dtype))
    np.testing.assert_allclose(
        out, [0.5, -0.5, 0.0, 0.0, 0.5, -0.5, 0.0])


def test_error_feedback_accumulates():
    # 0.3 < threshold: quantizes to 0, residual 0.3; next push 0.3+0.3=0.6
    # crosses the threshold → +t, residual 0.1
    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    g = mx.nd.array(np.full((4,), 0.3, np.float32))
    p1, shape, dtype = gc.compress("k", 0, g._data)
    np.testing.assert_allclose(np.asarray(gc.decompress(p1, shape, dtype)),
                               0.0)
    p2, _, _ = gc.compress("k", 0, g._data)
    np.testing.assert_allclose(np.asarray(gc.decompress(p2, shape, dtype)),
                               0.5)
    res = np.asarray(gc._residuals[("k", 0)])
    np.testing.assert_allclose(res, 0.1, rtol=1e-6)


def test_residuals_per_key_and_slot():
    gc = GradientCompression({"threshold": 1.0})
    a = mx.nd.array(np.array([0.4], np.float32))
    gc.compress("k1", 0, a._data)
    gc.compress("k1", 1, a._data)
    gc.compress("k2", 0, a._data)
    assert set(gc._residuals) == {("k1", 0), ("k1", 1), ("k2", 0)}


def test_invalid_params_raise():
    with pytest.raises(MXNetError, match="only '2bit'"):
        GradientCompression({"type": "1bit"})
    with pytest.raises(MXNetError, match="threshold"):
        GradientCompression({"type": "2bit", "threshold": 0})
    with pytest.raises(MXNetError, match="unknown"):
        GradientCompression({"type": "2bit", "bogus": 1})


def test_kvstore_push_applies_compression():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    shape = (3, 3)
    kv.init(0, mx.nd.zeros(shape))
    kv.push(0, mx.nd.array(np.full(shape, 0.7, np.float32)))
    out = mx.nd.zeros(shape)
    kv.pull(0, out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)  # quantized to +t
    # residual 0.2 carries into the next push: 0.2 + 0.4 > 0.5 → +t again
    kv.push(0, mx.nd.array(np.full(shape, 0.4, np.float32)))
    kv.pull(0, out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)
    # third push: residual 0.1 + 0.1 = 0.2 < t → zeros
    kv.push(0, mx.nd.array(np.full(shape, 0.1, np.float32)))
    kv.pull(0, out)
    np.testing.assert_allclose(out.asnumpy(), 0.0)


def test_kvstore_multi_device_compression():
    # replicas on several devices: each quantized independently then summed
    from mxnet_tpu import parallel
    ctxs = parallel.data_parallel_ctxs(2)
    if len(ctxs) < 2:
        pytest.skip("needs 2 devices")
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    shape = (4,)
    kv.init(1, mx.nd.zeros(shape, ctx=ctxs[0]))
    grads = [mx.nd.array(np.full(shape, 0.6, np.float32), ctx=ctxs[0]),
             mx.nd.array(np.full(shape, -0.6, np.float32), ctx=ctxs[1])]
    kv.push(1, grads)
    out = mx.nd.zeros(shape, ctx=ctxs[0])
    kv.pull(1, out)
    np.testing.assert_allclose(out.asnumpy(), 0.0)  # +t + -t
    grads = [mx.nd.array(np.full(shape, 0.6, np.float32), ctx=ctxs[0]),
             mx.nd.array(np.full(shape, 0.7, np.float32), ctx=ctxs[1])]
    kv.push(1, grads)
    kv.pull(1, out)
    np.testing.assert_allclose(out.asnumpy(), 1.0)  # +t + +t
