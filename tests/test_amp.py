"""AMP + monitor + contrib namespace tests.

Reference models: tests/python/unittest/test_amp.py (lists consistency,
convert_model dtype checks) and the monitor example in
python/mxnet/monitor.py docstrings.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon


@pytest.fixture
def amp_off_after():
    yield
    amp.off()


def test_lazy_names_resolve():
    # VERDICT r2 missing #1: every advertised lazy must import
    for name in ("amp", "monitor", "contrib", "gluon", "optimizer", "metric",
                 "initializer", "lr_scheduler", "io", "image", "kvstore",
                 "profiler", "runtime", "symbol", "parallel", "test_utils",
                 "recordio", "callback", "model", "util", "numpy",
                 "numpy_extension", "module"):
        assert getattr(mx, name) is not None
    assert hasattr(mx, "amp")
    assert not hasattr(mx, "definitely_not_a_module")


def test_amp_op_lists_disjoint():
    lp = set(amp.list_lp16_ops())
    f32 = set(amp.list_fp32_ops())
    widest = set(amp.list_widest_ops())
    assert not lp & f32
    assert not lp & widest
    assert not f32 & widest
    from mxnet_tpu.ops import registry
    known = set(registry.list_ops())
    for name in lp | f32 | widest:
        assert name in known, f"amp list references unknown op {name}"


def test_amp_init_casts_matmul(amp_off_after):
    amp.init()
    a = mx.nd.ones((4, 4))
    out = mx.nd.dot(a, a)
    assert str(out.dtype) == "bfloat16"
    # fp32-forced op keeps float32 even from bf16 inputs
    s = mx.nd.softmax(out)
    assert str(s.dtype) == "float32"
    amp.off()
    assert str(mx.nd.dot(a, a).dtype) == "float32"


def test_amp_widest_cast(amp_off_after):
    amp.init()
    import ml_dtypes
    a = mx.nd.ones((4,)).astype(ml_dtypes.bfloat16)
    b = mx.nd.ones((4,))  # float32
    out = mx.nd.broadcast_add(a, b)
    assert str(out.dtype) == "float32"


def test_amp_hybridized_retraces(amp_off_after):
    net = gluon.nn.Dense(8)
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 4))
    assert str(net(x).dtype) == "float32"
    amp.init()
    assert str(net(x).dtype) == "bfloat16"
    amp.off()
    assert str(net(x).dtype) == "float32"


def test_amp_training_step_matches_fp32_shape(amp_off_after):
    amp.init()
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    amp.init_trainer(tr)
    assert tr._amp_loss_scaler.loss_scale == 1.0  # bf16: no scaling
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.nd.array(np.random.RandomState(0).randn(8, 8))
    y = mx.nd.array(np.random.RandomState(1).randint(0, 4, (8,)))
    with autograd.record():
        loss = lossf(net(x), y)
    before = [p.data().asnumpy().copy() for p in net.collect_params().values()]
    with amp.scale_loss(loss, tr) as scaled:
        scaled.backward()
    tr.step(8)
    after = [p.data().asnumpy() for p in net.collect_params().values()]
    assert any(not np.allclose(b, a) for b, a in zip(before, after))
    assert np.isfinite(loss.asnumpy()).all()


def test_loss_scaler_dynamic_fp16():
    sc = amp.LossScaler(init_scale=256.0, scale_window=2,
                        target_dtype="float16")
    good = mx.nd.ones((3,))
    bad = mx.nd.array(np.array([1.0, np.inf, 0.0]))
    assert sc.has_overflow([bad])
    assert sc.loss_scale == 128.0
    assert not sc.has_overflow([good])
    assert not sc.has_overflow([good])
    assert sc.loss_scale == 256.0  # doubled after scale_window clean steps


def test_overflow_skips_update(amp_off_after):
    amp.init(target_dtype="float16")
    net = gluon.nn.Dense(2)
    net.initialize()
    x = mx.nd.ones((2, 3))
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5})
    amp.init_trainer(tr)
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    # poison the gradient
    w = list(net.collect_params().values())[0]
    g = w.list_grad()[0]
    g[:] = mx.nd.array(np.full(g.shape, np.inf, np.float32))
    before = w.data().asnumpy().copy()
    scale0 = tr._amp_loss_scaler.loss_scale
    tr.step(1)
    assert np.allclose(w.data().asnumpy(), before)  # update skipped
    assert tr._amp_loss_scaler.loss_scale == scale0 / 2


def test_amp_grads_stay_param_dtype(amp_off_after):
    # cast sits inside the differentiated fn, so f32 params get f32 grads
    amp.init()
    net = gluon.nn.Dense(4)
    net.initialize()
    x = mx.nd.ones((2, 3))
    with autograd.record():
        out = net(x)
    out.backward()
    for p in net.collect_params().values():
        assert str(np.dtype(p.list_grad()[0].dtype)) == "float32"


def test_unscale_then_step_no_double_divide(amp_off_after):
    amp.init(target_dtype="float16")
    net = gluon.nn.Dense(1, use_bias=False)
    net.initialize(mx.initializer.One())
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 1.0})
    amp.init_trainer(tr)
    tr._amp_loss_scaler.loss_scale = 256.0  # fp16-representable for the test
    x = mx.nd.ones((1, 1))
    with autograd.record():
        loss = net(x).sum()
        with amp.scale_loss(loss, tr) as scaled:
            scaled.backward()
    w = list(net.collect_params().values())[0]
    amp.unscale(tr)  # grads now unscaled in place
    g = w.list_grad()[0].asnumpy()
    assert np.allclose(g, 1.0), g  # dL/dw = x = 1 after unscale
    tr.step(1)
    # w <- 1 - lr*1 = 0; double-divide would give w ≈ 1 - 1/65536
    assert np.allclose(w.data().asnumpy(), 0.0, atol=1e-3)


def test_overflow_skip_update_on_kvstore(amp_off_after):
    amp.init(target_dtype="float16")
    net = gluon.nn.Dense(2)
    net.initialize()
    x = mx.nd.ones((2, 3))
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5},
                       kvstore="local", update_on_kvstore=True)
    amp.init_trainer(tr)
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    w = list(net.collect_params().values())[0]
    g = w.list_grad()[0]
    g[:] = mx.nd.array(np.full(g.shape, np.nan, np.float32))
    before = w.data().asnumpy().copy()
    tr.step(1)
    assert np.isfinite(w.data().asnumpy()).all()
    assert np.allclose(w.data().asnumpy(), before)


def test_monitor_safe_under_hybridize_trace():
    mon = mx.monitor.Monitor(interval=1)
    mon.install()
    try:
        net = gluon.nn.Dense(3)
        net.initialize()
        net.hybridize()
        mon.tic()
        net(mx.nd.ones((2, 2)))
        rows = mon.toc()  # must not raise on trace-time tracers
        assert all(isinstance(r[2], float) for r in rows)
    finally:
        mon.uninstall()


def test_convert_hybrid_block(amp_off_after):
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8), gluon.nn.BatchNorm(), gluon.nn.Dense(2))
    net.initialize()
    net(mx.nd.ones((2, 4)))
    amp.convert_hybrid_block(net, "bfloat16")
    dts = {name: str(np.dtype(p.dtype)) for name, p in net.collect_params().items()}
    for name, dt in dts.items():
        if any(m in name for m in ("gamma", "beta", "running_", "moving_")):
            assert dt == "float32", (name, dt)
        else:
            assert dt == "bfloat16", (name, dt)


def test_convert_model_symbolic(amp_off_after):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    arg = {"fc_weight": mx.nd.ones((4, 3)), "fc_bias": mx.nd.zeros((4,))}
    sym2, arg2, aux2 = amp.convert_model(net, arg, {}, "bfloat16")
    assert sym2 is net
    assert str(arg2["fc_weight"].dtype) == "bfloat16"
    assert aux2 == {}


def test_monitor_collects_stats():
    mon = mx.monitor.Monitor(interval=1, pattern=".*")
    mon.install()
    try:
        mon.tic()
        a = mx.nd.ones((3, 3))
        (a * 2).sum()
        rows = mon.toc()
        assert rows, "monitor captured nothing"
        names = [r[1] for r in rows]
        assert any("mul" in n or "sum" in n for n in names)
        assert all(isinstance(r[2], float) for r in rows)
    finally:
        mon.uninstall()


def test_monitor_interval_and_pattern():
    mon = mx.monitor.Monitor(interval=2, pattern=".*sum.*")
    mon.install()
    try:
        mon.tic()  # step 0: active
        mx.nd.ones((2,)).sum()
        rows0 = mon.toc()
        assert rows0 and all("sum" in r[1] for r in rows0)
        mon.tic()  # step 1: inactive
        mx.nd.ones((2,)).sum()
        assert mon.toc() == []
    finally:
        mon.uninstall()


def test_contrib_namespace():
    assert mx.contrib.amp is mx.amp
    out = mx.contrib.ndarray.div_sqrt_dim(mx.nd.ones((2, 16)))
    assert np.allclose(out.asnumpy(), 1.0 / 4.0)
    with pytest.raises(AttributeError, match="StableHLO"):
        mx.contrib.onnx  # noqa: B018
    # INT8 quantization is rebuilt (N11/P19): the namespace must resolve
    assert hasattr(mx.contrib.quantization, "quantize_net")
