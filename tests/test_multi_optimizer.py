"""Multi-tensor fused optimizer tests (VERDICT r3 item 8; reference
src/operator/optimizer_op.cc multi_sgd_update / multi_mp_sgd_* kernels +
the optimizer aggregation the reference drives through
MXNET_OPTIMIZER_AGGREGATION_SIZE)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, profiler


def _rand(shape, seed):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_multi_sgd_update_matches_singles():
    ws = [_rand((4, 3), i) for i in range(3)]
    gs = [_rand((4, 3), 10 + i) for i in range(3)]
    lrs = np.array([0.1, 0.05, 0.2], np.float32)
    wds = np.array([0.0, 0.01, 0.001], np.float32)
    outs = nd.multi_sgd_update(
        *[x for w, g in zip(ws, gs) for x in (nd.array(w), nd.array(g))],
        nd.array(lrs), nd.array(wds), rescale_grad=0.5, num_weights=3)
    for i in range(3):
        single = nd.sgd_update(nd.array(ws[i]), nd.array(gs[i]),
                               lr=float(lrs[i]), wd=float(wds[i]),
                               rescale_grad=0.5)
        np.testing.assert_allclose(outs[i].asnumpy(), single.asnumpy(),
                                   rtol=1e-6)


def test_multi_sgd_mom_update_matches_singles():
    ws = [_rand((5,), i) for i in range(2)]
    gs = [_rand((5,), 7 + i) for i in range(2)]
    ms = [_rand((5,), 20 + i) for i in range(2)]
    lrs = np.array([0.1, 0.3], np.float32)
    wds = np.array([0.01, 0.0], np.float32)
    ins = [x for w, g, m in zip(ws, gs, ms)
           for x in (nd.array(w), nd.array(g), nd.array(m))]
    outs = nd.multi_sgd_mom_update(*ins, nd.array(lrs), nd.array(wds),
                                   momentum=0.9, num_weights=2)
    for i in range(2):
        sw, sm = nd.sgd_mom_update(
            nd.array(ws[i]), nd.array(gs[i]), nd.array(ms[i]),
            lr=float(lrs[i]), wd=float(wds[i]), momentum=0.9)
        np.testing.assert_allclose(outs[2 * i].asnumpy(), sw.asnumpy(),
                                   rtol=1e-6)
        np.testing.assert_allclose(outs[2 * i + 1].asnumpy(), sm.asnumpy(),
                                   rtol=1e-6)


def test_multi_mp_sgd_update_casts_and_masters():
    import ml_dtypes
    w16 = nd.array(_rand((6,), 0).astype(ml_dtypes.bfloat16))
    g16 = nd.array(_rand((6,), 1).astype(ml_dtypes.bfloat16))
    w32 = w16.astype(np.float32)
    outs = nd.multi_mp_sgd_update(w16, g16, w32,
                                  nd.array(np.array([0.1], np.float32)),
                                  nd.array(np.array([0.0], np.float32)),
                                  num_weights=1)
    want32 = w32.asnumpy() - 0.1 * g16.astype(np.float32).asnumpy()
    np.testing.assert_allclose(outs[1].asnumpy(), want32, rtol=1e-6)
    assert outs[0].dtype == w16.dtype
    np.testing.assert_allclose(outs[0].astype(np.float32).asnumpy(),
                               want32.astype(ml_dtypes.bfloat16)
                               .astype(np.float32), rtol=1e-6)


def _train(agg, steps=3, n_layers=6, seed=5):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        for _ in range(n_layers):
            net.add(gluon.nn.Dense(8, activation="relu", in_units=8))
    net.initialize(mx.initializer.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9,
                        "wd": 0.01, "aggregate_num": agg})
    lf = gluon.loss.L2Loss()
    r = np.random.RandomState(3)
    x = mx.nd.array(r.randn(4, 8).astype(np.float32))
    y = mx.nd.array(r.randn(4, 8).astype(np.float32))
    for _ in range(steps):
        with autograd.record():
            loss = lf(net(x), y)
        loss.backward()
        tr.step(4)
    # key by the name suffix: the gluon global name counters advance
    # between runs (hybridsequentialN_ prefixes differ)
    return {k.split("_", 1)[-1]: v.data().asnumpy()
            for k, v in net.collect_params().items()}


def test_trainer_aggregated_matches_per_param(monkeypatch):
    """aggregate_num>1 routes through multi_sgd_mom_update groups; params
    after 3 steps match the per-param path bit-for-bit in formula.
    (Flat-buffer fusion off: it supersedes aggregation when enabled.)"""
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "0")
    base = _train(agg=0)
    fused = _train(agg=4)
    assert base.keys() == fused.keys()
    for k in base:
        np.testing.assert_allclose(fused[k], base[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_aggregation_reduces_dispatch_count(monkeypatch):
    """The point of the multi-tensor path: fewer host dispatches per step
    (reference: one multi_sgd kernel per aggregate group).  Counted via
    the profiler's dispatch ledger.  Runs with the flat-buffer fused
    optimizer OFF — it supersedes aggregation when enabled (ISSUE 5;
    tests/test_optimizer_fusion.py covers that path)."""
    monkeypatch.setenv("MXNET_OPTIMIZER_FUSED", "0")

    def count_update_dispatches(agg):
        mx.random.seed(1)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            for _ in range(8):
                net.add(gluon.nn.Dense(4, in_units=4))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1,
                            "aggregate_num": agg})
        lf = gluon.loss.L2Loss()
        x = mx.nd.array(np.ones((2, 4), np.float32))
        y = mx.nd.array(np.zeros((2, 4), np.float32))
        with autograd.record():
            loss = lf(net(x), y)
        loss.backward()
        profiler.set_state("run")
        tr.step(2)
        table = profiler.dumps(reset=True)
        profiler.set_state("stop")

        def calls(op):
            for line in table.splitlines():
                parts = line.split()
                if parts and parts[0] == op:
                    return int(parts[1])
            return 0

        return calls("sgd_update"), calls("multi_sgd_update")

    single_n, single_m = count_update_dispatches(agg=0)
    agg_n, agg_m = count_update_dispatches(agg=4)
    assert single_n == 16 and single_m == 0   # 8 weights + 8 biases
    assert agg_n == 0 and agg_m >= 1          # grouped dispatches only
    assert agg_m <= 4                          # ceil(16/4)


def test_multi_sgd_preserves_half_dtype():
    """f32 lr/wd vectors must not promote bf16 params (review regression:
    the fused path silently flipped weights to f32 after one step)."""
    import ml_dtypes
    w = nd.array(_rand((4,), 0).astype(ml_dtypes.bfloat16))
    g = nd.array(_rand((4,), 1).astype(ml_dtypes.bfloat16))
    m = nd.array(np.zeros(4, ml_dtypes.bfloat16))
    outs = nd.multi_sgd_update(w, g,
                               nd.array(np.array([0.1], np.float32)),
                               nd.array(np.array([0.0], np.float32)),
                               num_weights=1)
    assert outs.dtype == w.dtype if not isinstance(outs, list) \
        else outs[0].dtype == w.dtype
    outs2 = nd.multi_sgd_mom_update(
        w, g, m, nd.array(np.array([0.1], np.float32)),
        nd.array(np.array([0.0], np.float32)), momentum=0.9, num_weights=1)
    assert outs2[0].dtype == w.dtype and outs2[1].dtype == m.dtype


def test_lars_update_matches_oracle():
    """lars_update (reference optimizer_op.cc lars_* family): trust-ratio
    scaled momentum SGD, zero-norm fallback to ratio 1."""
    r = np.random.RandomState(1)
    w = r.randn(8).astype(np.float32)
    g = r.randn(8).astype(np.float32)
    m = r.randn(8).astype(np.float32) * 0.1
    wn, mn = nd.lars_update(nd.array(w), nd.array(g), nd.array(m),
                            lr=0.2, momentum=0.9, eta=0.01, wd=0.001)
    wnorm = np.linalg.norm(w)
    gnorm = np.linalg.norm(g)
    trust = wnorm / (gnorm + 0.001 * wnorm + 1e-8)
    mref = 0.9 * m + 0.2 * 0.01 * trust * (g + 0.001 * w)
    np.testing.assert_allclose(mn.asnumpy(), mref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(wn.asnumpy(), w - mref, rtol=1e-5, atol=1e-6)
    # zero weight -> trust ratio 1 (no div-by-zero blowup)
    w0 = np.zeros(4, np.float32)
    wn0, _ = nd.lars_update(nd.array(w0), nd.array(np.ones(4, np.float32)),
                            nd.array(np.zeros(4, np.float32)), lr=0.1,
                            momentum=0.0, eta=0.5)
    # reference guard: zero norms -> PLAIN lr (eta only inside the ratio)
    np.testing.assert_allclose(wn0.asnumpy(), -0.1 * np.ones(4), rtol=1e-6)


def test_lars_optimizer_trains():
    from mxnet_tpu import gluon
    mx.random.seed(2)
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize(mx.initializer.Normal(0.2))
    tr = gluon.Trainer(net.collect_params(), "lars",
                       {"learning_rate": 1.0, "eta": 0.1, "momentum": 0.9})
    lf = gluon.loss.L2Loss()
    r = np.random.RandomState(0)
    X = r.randn(32, 4).astype(np.float32)
    Y = (X @ r.randn(4, 1)).astype(np.float32)
    losses = []
    for _ in range(25):
        with autograd.record():
            loss = lf(net(mx.nd.array(X)), mx.nd.array(Y))
        loss.backward()
        tr.step(32)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < 0.5 * losses[0]


def test_reference_camelcase_aliases():
    """Upstream exposes legacy CamelCase op names alongside snake_case —
    both must resolve to the same kernels."""
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(nd.SwapAxis(x, dim1=0, dim2=1).asnumpy(),
                               x.asnumpy().T)
    np.testing.assert_allclose(nd.Reshape(x, shape=(3, 2)).asnumpy(),
                               x.asnumpy().reshape(3, 2))
    np.testing.assert_allclose(nd.Flatten(x).asnumpy(), x.asnumpy())
    np.testing.assert_allclose(
        nd.Concat(x, x, dim=0).asnumpy(),
        np.concatenate([x.asnumpy()] * 2, axis=0))
    np.testing.assert_allclose(
        nd.logical_xor(nd.array(np.array([0., 1., 1.])),
                       nd.array(np.array([1., 1., 0.]))).asnumpy(),
        [1.0, 0.0, 1.0])
    seq = nd.SequenceMask(
        nd.array(np.ones((3, 2, 2), np.float32)),
        nd.array(np.array([1., 2.])),
        use_sequence_length=True)
    assert seq.asnumpy()[2, 0].sum() == 0.0   # masked beyond length


def test_lars_skips_trust_for_bias_gamma_beta():
    """Reference LARS excludes bias/gamma/beta from layer adaptation:
    those params update with plain momentum SGD."""
    opt = mx.optimizer.create("lars", learning_rate=0.5, momentum=0.0,
                              eta=0.001,
                              param_idx2name={0: "fc_weight", 1: "fc_bias"})
    w = np.ones(4, np.float32)
    g = np.full(4, 0.2, np.float32)
    wt = nd.array(w)
    opt.update(1, wt, nd.array(g), opt.create_state(1, wt))
    # plain sgd: w - lr*g (no tiny-eta trust scaling)
    np.testing.assert_allclose(wt.asnumpy(), w - 0.5 * g, rtol=1e-6)
    wt2 = nd.array(w)
    opt.update(0, wt2, nd.array(g), opt.create_state(0, wt2))
    assert not np.allclose(wt2.asnumpy(), w - 0.5 * g)   # trust applied


def test_lars_trainer_excludes_bias(seeded):
    """The bias exclusion must work through the PRIMARY path — gluon
    Trainer populates param_dict, not idx2name (review regression)."""
    from mxnet_tpu import gluon
    mx.random.seed(4)
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize(mx.initializer.Normal(0.3))
    tr = gluon.Trainer(net.collect_params(), "lars",
                       {"learning_rate": 0.5, "momentum": 0.0,
                        "eta": 0.001})
    lf = gluon.loss.L2Loss()
    x = mx.nd.array(np.ones((4, 3), np.float32))
    y = mx.nd.array(np.zeros((4, 2), np.float32))
    b0 = net.bias.data().asnumpy().copy()
    with autograd.record():
        loss = lf(net(x), y)
    loss.backward()
    gb = net.bias.grad().asnumpy().copy()
    tr.step(1)
    # bias updated with PLAIN lr (trust forced to 1), i.e. -lr * grad,
    # not the ~1000x smaller eta-scaled step
    np.testing.assert_allclose(net.bias.data().asnumpy(), b0 - 0.5 * gb,
                               rtol=1e-4, atol=1e-6)
