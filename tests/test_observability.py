"""Distributed observability plane (ISSUE 10): cross-process aggregation,
step-time attribution, and the crash flight recorder.

Covers: snapshot export/merge round trips (in-process, and across real
subprocesses through the MXNET_TELEMETRY_DIR collection protocol), merged
Chrome-trace metadata (pid=rank, process/thread names, shared timeline),
merged Prometheus summation, StepClock phase accounting and the
input-/comms-/compute-bound verdicts on REAL runs (slow DataLoader →
input-bound; chaos-delayed allreduce → comms-bound), telemetry.report(),
the tools/telemetry_report.py CLI, and flight-recorder dumps on every
trigger (unhandled exception, chaos 'exit', deadline-exceeded, SIGUSR2).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry
from mxnet_tpu.telemetry import aggregate, flightrec, stepclock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    def reset():
        telemetry.disable()
        telemetry.clear()
        telemetry.REGISTRY.reset()
        aggregate.set_rank(None)
        telemetry.get_tracer().set_process_label("mxnet_tpu")
        from mxnet_tpu.resilience import chaos
        chaos.clear()
    reset()
    yield
    reset()


def _subprocess(code, env=None, timeout=120):
    full_env = {**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})}
    return subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=timeout,
                          env=full_env)


# ---------------------------------------------------------------------------
# aggregation: snapshot export + merge
# ---------------------------------------------------------------------------

def test_snapshot_export_atomic_roundtrip(tmp_path):
    telemetry.enable()
    with telemetry.span("work", "test", k=1):
        pass
    telemetry.counter("t_obs_total").inc(7)
    path = aggregate.export_snapshot(directory=str(tmp_path))
    assert os.path.basename(path).startswith("telemetry-rank00000-")
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    with open(path) as f:
        snap = json.load(f)
    assert snap["version"] == aggregate.SNAPSHOT_VERSION
    assert snap["rank"] == 0 and snap["pid"] == os.getpid()
    assert any(e["name"] == "work" for e in snap["events"])
    assert any(m["name"] == "t_obs_total" and m["value"] == 7
               for m in snap["metrics"])
    assert snap["wall_anchor_us"] > 0
    assert snap["stepclock"]["verdict"] == "idle"
    # re-export from the same process replaces the same file
    assert aggregate.export_snapshot(directory=str(tmp_path)) == path
    assert len(aggregate.load_snapshots(str(tmp_path))) == 1


def test_merged_chrome_trace_and_prometheus(tmp_path):
    telemetry.enable()
    with telemetry.span("step", "test"):
        pass
    telemetry.counter("t_merge_total").inc(3)
    telemetry.histogram("t_merge_seconds", buckets=(0.5, 1.0)).observe(0.1)
    aggregate.export_snapshot(directory=str(tmp_path))          # rank 0
    aggregate.set_rank(1)
    telemetry.counter("t_merge_total").inc(2)                   # now 5
    aggregate.export_snapshot(directory=str(tmp_path))          # rank 1
    snaps = aggregate.load_snapshots(str(tmp_path))
    assert [s["rank"] for s in snaps] == [0, 1]

    trace = aggregate.merged_chrome_trace(snaps)
    evs = trace["traceEvents"]
    names = [(e["name"], e.get("pid")) for e in evs if e.get("ph") == "M"]
    assert ("process_name", 0) in names and ("process_name", 1) in names
    assert ("process_sort_index", 0) in names
    assert any(n == "thread_name" for n, _ in names)
    spans = [e for e in evs if e.get("ph") == "X"]
    assert {e["pid"] for e in spans} == {0, 1}  # pid rewritten to rank
    labels = {e["args"]["name"] for e in evs
              if e["name"] == "process_name"}
    assert "mxnet_tpu rank 1" in labels

    prom = aggregate.merged_prometheus(snaps)
    # counters sum across ranks: 3 (rank0) + 5 (rank1 exported later)
    assert "t_merge_total 8" in prom
    # histogram buckets sum too (one observation per snapshot)
    assert 't_merge_seconds_bucket{le="0.5"} 2' in prom
    assert 't_merge_seconds_bucket{le="+Inf"} 2' in prom


def test_merge_skips_corrupt_shards(tmp_path):
    telemetry.enable()
    aggregate.export_snapshot(directory=str(tmp_path))
    with open(tmp_path / "telemetry-rank00009-pid1.json", "w") as f:
        f.write("{ truncated")
    snaps = aggregate.load_snapshots(str(tmp_path))
    assert [s["rank"] for s in snaps] == [0]


def test_two_subprocess_collection_roundtrip(tmp_path):
    """The real protocol end to end: two separate processes, telemetry on,
    MXNET_TELEMETRY_DIR set, export at EXIT (atexit, no explicit call);
    this process plays rank 0's merge role."""
    code = """
import mxnet_tpu as mx
from mxnet_tpu import telemetry
with telemetry.span('subwork', 'test'):
    pass
telemetry.counter('t_sub_total').inc(4)
"""
    procs = [subprocess.Popen(
        [sys.executable, "-c", code], cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "MXNET_TELEMETRY": "1",
             "MXNET_TELEMETRY_DIR": str(tmp_path),
             "MXNET_DIST_RANK": str(r)}) for r in (0, 1)]
    for p in procs:
        _, err = p.communicate(timeout=120)
        assert p.returncode == 0, err.decode()[-500:]
    snaps = aggregate.load_snapshots(str(tmp_path))
    assert [s["rank"] for s in snaps] == [0, 1]
    trace = aggregate.merged_chrome_trace(snaps)
    sub = [e for e in trace["traceEvents"] if e["name"] == "subwork"]
    assert {e["pid"] for e in sub} == {0, 1}
    assert "t_sub_total 8" in aggregate.merged_prometheus(snaps)


def test_counter_delta_shipping_inprocess():
    """The decode-pool ack-channel protocol, worker side + parent side."""
    c = telemetry.counter("t_ship_total")
    c.inc(5)
    first = aggregate.counter_deltas()
    ship = [d for d in first if d[0] == "t_ship_total"]
    assert ship and ship[0][2] == 5
    assert not [d for d in aggregate.counter_deltas()
                if d[0] == "t_ship_total"]   # nothing new since last ack
    c.inc(2)
    again = [d for d in aggregate.counter_deltas()
             if d[0] == "t_ship_total"]
    assert again[0][2] == 2
    before = telemetry.counter("t_absorb_total").value
    aggregate.absorb_counter_deltas([("t_absorb_total", {}, 3)])
    assert telemetry.counter("t_absorb_total").value == before + 3


# ---------------------------------------------------------------------------
# StepClock: phases, verdicts, report
# ---------------------------------------------------------------------------

def test_stepclock_phase_accounting():
    clock = stepclock.StepClock(window=8)
    clock.note("data_wait", 0.05)          # between-steps note → pending
    clock.begin_step()
    clock.note("comms", 0.02)
    with clock.phase("h2d"):
        pass
    clock.end_step()
    s = clock.summary()
    assert s["steps"] == 1
    rec = s["phases"]
    assert rec["data_wait"]["median"] == pytest.approx(0.05)
    assert rec["comms"]["median"] == pytest.approx(0.02)
    # unattributed remainder lands in compute; phases sum ~ total
    total = s["phases"]["total"]["median"]
    parts = sum(rec[p]["median"] for p in stepclock.PHASES)
    assert parts == pytest.approx(total, rel=1e-6)
    with pytest.raises(ValueError):
        clock.note("warp", 1.0)


def test_stepclock_abandoned_step_discarded():
    clock = stepclock.StepClock(window=8)
    clock.begin_step()          # never ended (amp overflow-skip path)
    clock.begin_step()
    clock.end_step()
    assert clock.steps == 1
    clock.end_step()            # double end: no-op
    assert clock.steps == 1


def test_verdict_input_bound_through_dataloader():
    """A decode-throttled run must label input-bound: the DataLoader's
    fetch spans feed data_wait, dwarfing the tiny model's compute."""
    telemetry.enable()

    class SlowDS(gluon.data.ArrayDataset):
        def __getitem__(self, idx):
            time.sleep(0.01)
            return super().__getitem__(idx)

    ds = SlowDS(mx.nd.array(np.random.randn(16, 3).astype(np.float32)))
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    for x in gluon.data.DataLoader(ds, batch_size=4):
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(4)
    assert telemetry.STEP_CLOCK.verdict() == "input-bound"
    rep = telemetry.report()
    assert "verdict: input-bound" in rep
    assert "data_wait" in rep
    # the labeled histogram series recorded every step
    h = telemetry.REGISTRY.get("mxnet_step_phase_seconds",
                               labels={"phase": "data_wait"})
    assert h is not None and h.count == 4


def test_verdict_comms_bound_through_chaos_delay():
    """A comms-heavy run must label comms-bound: chaos latency injection
    at kvstore.allreduce (the dist store's per-key reduce) dominates."""
    from mxnet_tpu.resilience import chaos
    telemetry.enable()
    kv = mx.kv.create("dist_tpu_sync")
    kv.set_bucket_size(0)      # per-key pushes cross the allreduce site
    chaos.inject("kvstore.allreduce", kind="delay", times=0, delay_s=0.02)
    try:
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=kv)
        x = mx.nd.array(np.ones((2, 3), np.float32))
        for _ in range(3):
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            tr.step(2)
    finally:
        chaos.clear()
    assert telemetry.STEP_CLOCK.verdict() == "comms-bound"
    assert "verdict: comms-bound" in telemetry.report()


def test_report_cli_merges_and_reports(tmp_path):
    telemetry.enable()
    with telemetry.span("cliwork", "test"):
        pass
    aggregate.export_snapshot(directory=str(tmp_path))
    aggregate.set_rank(1)
    aggregate.export_snapshot(directory=str(tmp_path))
    trace_out = tmp_path / "merged_trace.json"
    prom_out = tmp_path / "merged.prom"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         "--dir", str(tmp_path), "--trace", str(trace_out),
         "--prom", str(prom_out)],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr[-500:]
    assert "2 rank(s)" in res.stdout
    assert "job verdict:" in res.stdout
    with open(trace_out) as f:
        trace = json.load(f)
    assert {e.get("pid") for e in trace["traceEvents"]
            if e.get("ph") == "X"} == {0, 1}
    assert "mxnet_step_phase_seconds" in prom_out.read_text()
    # --json mode
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_report.py"),
         "--dir", str(tmp_path), "--json"],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0
    assert [r["rank"] for r in json.loads(res.stdout)["ranks"]] == [0, 1]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def _dumps_in(d):
    if not os.path.isdir(d):
        return []
    return sorted(f for f in os.listdir(d)
                  if f.startswith("flightrec-") and f.endswith(".json"))


def test_flightrec_dump_contents(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    flightrec._reset_dump_cap_for_test()
    telemetry.enable()
    with telemetry.span("pre-crash", "test"):
        pass
    flightrec.note("about_to_die", step=3)
    try:
        raise RuntimeError("synthetic failure")
    except RuntimeError as e:
        path = flightrec.dump("test.reason", exc=e)
    assert path and os.path.exists(path)
    with open(path) as f:
        rec = json.load(f)
    assert rec["reason"] == "test.reason"
    assert rec["rank"] == 0 and rec["pid"] == os.getpid()
    assert rec["exception"]["type"] == "RuntimeError"
    assert "synthetic failure" in rec["exception"]["message"]
    assert any(e["name"] == "pre-crash" for e in rec["spans"])
    assert any(c["event"] == "about_to_die" for c in rec["breadcrumbs"])
    assert "MXNET_FLIGHTREC" in rec["config"]
    assert "armed_sites" in rec["chaos"]
    assert any(m["name"] == "mxnet_op_dispatch_total"
               for m in rec["metrics"])


def test_flightrec_dump_cap(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_FLIGHTREC_MAX_DUMPS", "2")
    flightrec._reset_dump_cap_for_test()
    assert flightrec.dump("one") and flightrec.dump("two")
    assert flightrec.dump("three") is None
    assert len(_dumps_in(str(tmp_path))) == 2
    flightrec._reset_dump_cap_for_test()


def test_flightrec_disabled_no_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_FLIGHTREC", "0")
    flightrec._reset_dump_cap_for_test()
    assert flightrec.dump("nope") is None
    assert _dumps_in(str(tmp_path)) == []


def test_flightrec_unhandled_exception_subprocess(tmp_path):
    res = _subprocess(
        "import mxnet_tpu\nraise RuntimeError('chaos-lane death')",
        env={"MXNET_FLIGHTREC_DIR": str(tmp_path)})
    assert res.returncode == 1
    assert "chaos-lane death" in res.stderr   # excepthook chains through
    dumps = _dumps_in(str(tmp_path))
    assert len(dumps) == 1 and "exception.RuntimeError" in dumps[0]
    with open(tmp_path / dumps[0]) as f:
        rec = json.load(f)
    assert rec["exception"]["message"] == "chaos-lane death"


def test_flightrec_chaos_exit_subprocess(tmp_path):
    """chaos 'exit' is os._exit — no excepthook, no atexit.  The dump must
    happen INSIDE chaos.hit, and it also exports the telemetry shard so a
    dead rank still appears in the merged trace."""
    code = """
import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.resilience import chaos
telemetry.enable()
with telemetry.span('doomed-work', 'test'):
    pass
chaos.inject('trainer.step', kind='exit', times=1)
chaos.hit('trainer.step')
raise AssertionError('unreachable')
"""
    res = _subprocess(code, env={"MXNET_FLIGHTREC_DIR": str(tmp_path),
                                 "MXNET_TELEMETRY_DIR": str(tmp_path)})
    assert res.returncode == 1
    dumps = _dumps_in(str(tmp_path))
    assert len(dumps) == 1 and "chaos.exit.trainer.step" in dumps[0]
    with open(tmp_path / dumps[0]) as f:
        rec = json.load(f)
    assert any(e["name"] == "doomed-work" for e in rec["spans"])
    assert rec["chaos"]["faults_fired"] == 1
    # the dying rank's telemetry shard was exported too
    snaps = aggregate.load_snapshots(str(tmp_path))
    assert len(snaps) == 1
    assert any(e["name"] == "doomed-work" for e in snaps[0]["events"])


def test_flightrec_deadline_dump(tmp_path, monkeypatch):
    from mxnet_tpu.resilience import Deadline, KVStoreTimeoutError
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    flightrec._reset_dump_cap_for_test()
    with pytest.raises(KVStoreTimeoutError):
        Deadline(timeout_s=0.05, site="test.site").call(time.sleep, 5)
    dumps = _dumps_in(str(tmp_path))
    assert len(dumps) == 1 and "deadline.test.site" in dumps[0]


@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"), reason="no SIGUSR2")
def test_flightrec_sigusr2_on_demand(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    flightrec._reset_dump_cap_for_test()
    flightrec.install()   # idempotent; installed at import in main thread
    os.kill(os.getpid(), signal.SIGUSR2)
    deadline = time.time() + 5
    while time.time() < deadline and not _dumps_in(str(tmp_path)):
        time.sleep(0.01)
    dumps = _dumps_in(str(tmp_path))
    assert len(dumps) == 1 and "sigusr2" in dumps[0]
    # the process keeps running (this assertion executing is the proof)
