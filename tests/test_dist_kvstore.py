"""Multi-process dist_tpu_sync integration test (reference
tests/nightly/dist_sync_kvstore.py run under tools/launch.py --launcher
local, SURVEY §4.2 'distributed without a cluster').

Two REAL processes on the CPU platform, rendezvoused through
jax.distributed on localhost; the kvstore reduce is the compiled
shard_map psum over the process mesh — the same code path a TPU pod
takes, minus the ICI."""

import os
import subprocess
import sys

import pytest


def test_two_process_sync_kvstore():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        from launch import launch_local
    finally:
        sys.path.pop(0)
    worker = os.path.join(repo, "tests", "_dist_worker.py")
    env = {"MXNET_TPU_JIT_IMPERATIVE": "1"}
    codes = launch_local(2, [sys.executable, worker], env_extra=env,
                         cpu_devices_per_worker=1)
    assert codes == [0, 0], f"worker exit codes {codes}"


def test_launch_rejects_servers():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "-s", "1", "echo", "hi"],
        capture_output=True, text=True)
    assert res.returncode != 0
    assert "no server role" in res.stderr
