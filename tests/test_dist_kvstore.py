"""Multi-process dist_tpu_sync integration test (reference
tests/nightly/dist_sync_kvstore.py run under tools/launch.py --launcher
local, SURVEY §4.2 'distributed without a cluster').

Two REAL processes on the CPU platform, rendezvoused through
jax.distributed on localhost; the kvstore reduce is the compiled
shard_map psum over the process mesh — the same code path a TPU pod
takes, minus the ICI."""

import os
import subprocess
import sys

import pytest


def _run_sync_kvstore(n, timeout=180, env_extra=None):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        from launch import launch_local
    finally:
        sys.path.pop(0)
    worker = os.path.join(repo, "tests", "_dist_worker.py")
    # deflake (ISSUE 3 satellite): deadline-bound every blocking dist call
    # inside the workers (a wedged peer now exits with KVStoreTimeoutError
    # instead of hanging to the launcher kill), keep the launcher timeout
    # well under the tier-1 budget, and retry the launch once — the
    # residual flake is the localhost coordinator rendezvous, which is
    # process-lifetime state a fresh launch resets.
    env = {"MXNET_TPU_JIT_IMPERATIVE": "1", "MXNET_KVSTORE_TIMEOUT_S": "60"}
    env.update(env_extra or {})
    for attempt in range(2):
        codes = launch_local(n, [sys.executable, worker], env_extra=env,
                             cpu_devices_per_worker=1, timeout=timeout)
        if codes == [0] * n:
            break
    assert codes == [0] * n, f"worker exit codes {codes}"


def test_two_process_sync_kvstore(tmp_path):
    """The exact-value dist body, with the ISSUE 10 aggregation plane
    riding along: both workers run with telemetry on and a collection
    dir, export rank-tagged snapshots at exit, and this (rank-0-role)
    process merges them into ONE Chrome trace and ONE Prometheus
    snapshot."""
    teldir = str(tmp_path / "telemetry")
    _run_sync_kvstore(2, env_extra={"MXNET_TELEMETRY": "1",
                                    "MXNET_TELEMETRY_DIR": teldir})
    from mxnet_tpu.telemetry import aggregate
    snaps = aggregate.load_snapshots(teldir)
    assert [s["rank"] for s in snaps] == [0, 1]
    trace = aggregate.merged_chrome_trace(snaps)
    evs = trace["traceEvents"]
    labels = {e["args"]["name"] for e in evs
              if e.get("name") == "process_name"}
    assert {"mxnet_tpu rank 0", "mxnet_tpu rank 1"} <= labels
    pids = {e["pid"] for e in evs
            if e.get("ph") == "X" and e.get("cat") == "kvstore"}
    assert pids == {0, 1}     # both ranks' kvstore spans, pid = rank
    prom = aggregate.merged_prometheus(snaps)
    merged = {ln.split()[0]: float(ln.split()[1])
              for ln in prom.splitlines()
              if ln.startswith("mxnet_kvstore_allreduce_bytes_total")}
    per_rank = [
        m["value"] for s in snaps for m in s["metrics"]
        if m["name"] == "mxnet_kvstore_allreduce_bytes_total"]
    assert len(per_rank) == 2 and all(v > 0 for v in per_rank)
    assert merged["mxnet_kvstore_allreduce_bytes_total"] == sum(per_rank)


@pytest.mark.slow
def test_four_process_sync_kvstore():
    """ISSUE 7 satellite (ROADMAP 4): the same exact-value body —
    dense/structured allreduce, fused pushpull_list, 2-bit compression —
    at n=4, proving the gloo mesh and the compression quantize/dequantize
    wire format scale past the pairwise case.  Slow tier: four jax
    processes rendezvousing over localhost gRPC on shared CPUs."""
    _run_sync_kvstore(4, timeout=300)


def test_launch_rejects_servers():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "-s", "1", "echo", "hi"],
        capture_output=True, text=True)
    assert res.returncode != 0
    assert "no server role" in res.stderr


def test_kvstore_backend_registration():
    """Reference 1.7 KVStoreBase.register: a custom backend class becomes
    creatable by its class name through mx.kv.create (the extension point
    the horovod backend used upstream)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.kvstore import KVStoreBase

    @KVStoreBase.register
    class MyHorovod(KVStoreBase):
        def __init__(self, scale=1.0):
            self.scale = scale

        @property
        def type(self):
            return "myhorovod"

        def broadcast(self, key, value, out):
            for o in out if isinstance(out, (list, tuple)) else [out]:
                o[:] = value

        def pushpull(self, key, value, out=None, priority=0):
            if out is not None:
                out[:] = value * self.scale
            return value

    from mxnet_tpu.kvstore import base as kv_base
    try:
        assert "myhorovod" in KVStoreBase.list_backends()
        kv = mx.kv.create("MyHorovod", scale=2.0)   # case-insensitive
        assert kv.type == "myhorovod"
        assert kv.rank == 0 and kv.num_workers == 1
        v = mx.nd.array(np.ones((3,), np.float32))
        out = mx.nd.array(np.zeros((3,), np.float32))
        kv.pushpull("w0", v, out=out)
        np.testing.assert_allclose(out.asnumpy(), 2.0 * np.ones(3))

        # built-ins are not shadowed by registration
        class Local(KVStoreBase):
            pass
        KVStoreBase.register(Local)
        assert type(mx.kv.create("local")).__name__ == "KVStoreLocal"
    finally:
        kv_base._BACKENDS.pop("myhorovod", None)
        kv_base._BACKENDS.pop("local", None)
