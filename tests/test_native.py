"""Native C++ recordio scanner (mxnet_tpu/src/recordio.cc via ctypes) —
byte-format parity with the pure-python reader and the bulk read lane.
Reference role: dmlc-core recordio + src/io/ C++ readers (N19/N26)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio, native


def _write_rec(tmp_path, n=32, indexed=True, seed=0):
    r = np.random.RandomState(seed)
    rec_path = os.path.join(str(tmp_path), "data.rec")
    idx_path = os.path.join(str(tmp_path), "data.idx")
    payloads = [r.bytes(int(r.randint(1, 200))) for _ in range(n)]
    if indexed:
        w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
        for i, p in enumerate(payloads):
            w.write_idx(i, p)
    else:
        w = recordio.MXRecordIO(rec_path, "w")
        for p in payloads:
            w.write(p)
    w.close()
    return rec_path, idx_path, payloads


def test_native_lib_builds():
    assert native.native_available(), \
        "g++ is in the image; the native recordio lane must build"


def test_native_index_matches_python_scan(tmp_path):
    rec_path, _, payloads = _write_rec(tmp_path, indexed=False)
    scan = native.index_recordio(rec_path)
    assert scan is not None
    offs, lens = scan
    assert len(offs) == len(payloads)
    np.testing.assert_array_equal(lens,
                                  [len(p) for p in payloads])
    # python sequential read sees the same payloads at those lengths
    rd = recordio.MXRecordIO(rec_path, "r")
    for p in payloads:
        assert rd.read() == p
    rd.close()


def test_native_bulk_read_parity(tmp_path):
    rec_path, _, payloads = _write_rec(tmp_path, indexed=False, seed=3)
    offs, lens = native.index_recordio(rec_path)
    got = native.read_recordio_batch(rec_path, offs, lens)
    assert got == payloads


def test_indexed_read_batch_native_and_fallback(tmp_path):
    rec_path, idx_path, payloads = _write_rec(tmp_path, seed=5)
    rd = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    picks = [3, 0, 17, 31, 8]
    got = rd.read_batch(picks)
    assert got == [payloads[i] for i in picks]
    # forced-fallback path returns identical bytes
    os.environ["MXNET_USE_NATIVE"] = "0"
    try:
        native._lib, native._tried = None, False
        got2 = rd.read_batch(picks)
        assert got2 == got
    finally:
        del os.environ["MXNET_USE_NATIVE"]
        native._lib, native._tried = None, False
    rd.close()


def test_native_rejects_garbage(tmp_path):
    bad = os.path.join(str(tmp_path), "bad.rec")
    with open(bad, "wb") as f:
        f.write(b"definitely not recordio framing")
    with pytest.raises(mx.MXNetError, match="framing"):
        native.index_recordio(bad)


def test_native_truncated_tail_rejected(tmp_path):
    """A record whose payload is cut off must fail the scan (not be indexed
    at its claimed length) — read_batch then falls back to python."""
    rec_path, _, payloads = _write_rec(tmp_path, indexed=False, seed=9)
    with open(rec_path, "r+b") as f:
        f.truncate(os.path.getsize(rec_path) - 3)
    with pytest.raises(mx.MXNetError):
        native.index_recordio(rec_path)


def test_read_batch_on_writer_raises(tmp_path):
    rec_path = os.path.join(str(tmp_path), "w.rec")
    idx_path = os.path.join(str(tmp_path), "w.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    w.write_idx(0, b"abc")
    with pytest.raises(mx.MXNetError, match="writing"):
        w.read_batch([0])
    w.close()


def test_image_record_iter_bulk_path(tmp_path):
    """ImageRecordIter over a real .rec: one native bulk read per batch,
    correct shapes/labels (reference iter_image_recordio_2.cc contract)."""
    import cv2
    rec_path = os.path.join(str(tmp_path), "img.rec")
    idx_path = os.path.join(str(tmp_path), "img.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    r = np.random.RandomState(0)
    n = 12
    for i in range(n):
        img = (r.rand(10, 10, 3) * 255).astype(np.uint8)
        ok, buf = cv2.imencode(".png", img)
        assert ok
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 3), i, 0), buf.tobytes()))
    w.close()

    it = mx.io.ImageRecordIter(path_imgrec=rec_path, path_imgidx=idx_path,
                               data_shape=(3, 8, 8), batch_size=4,
                               preprocess_threads=2)
    seen_labels = []
    batches = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 8, 8)
        seen_labels.extend(batch.label[0].asnumpy().tolist())
        batches += 1
    assert batches == n // 4
    assert sorted(set(seen_labels)) == [0.0, 1.0, 2.0]


def test_image_record_iter_process_decoder(tmp_path):
    """decoder='processes' (multiprocess decode pool — the reference's
    decode-worker role without the GIL) yields the same deterministic
    batches as in-process decode (no augmentation => exact match)."""
    import cv2
    rec_path = os.path.join(str(tmp_path), "imgp.rec")
    idx_path = os.path.join(str(tmp_path), "imgp.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    r = np.random.RandomState(5)
    for i in range(8):
        img = (r.rand(12, 12, 3) * 255).astype(np.uint8)
        ok, buf = cv2.imencode(".png", img)
        assert ok
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), buf.tobytes()))
    w.close()

    def collect(decoder, threads):
        it = mx.io.ImageRecordIter(
            path_imgrec=rec_path, path_imgidx=idx_path,
            data_shape=(3, 8, 8), batch_size=4, decoder=decoder,
            preprocess_threads=threads, ctx=mx.cpu())
        out = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it]
        it.close()
        return out

    ref = collect("threads", 1)
    got = collect("processes", 2)
    assert len(ref) == len(got) == 2
    for (rd, rl), (gd, gl) in zip(ref, got):
        np.testing.assert_allclose(gd, rd)
        np.testing.assert_allclose(gl, rl)


# -- r5: native fused JPEG decode (src/jpeg_decode.cc) ---------------------

def _jpeg_bytes(img_rgb, quality=95):
    import cv2
    ok, buf = cv2.imencode(".jpg", cv2.cvtColor(img_rgb, cv2.COLOR_RGB2BGR),
                           [cv2.IMWRITE_JPEG_QUALITY, quality])
    assert ok
    return buf.tobytes()


def test_jpeg_decode_parity_and_mirror():
    """Fused decode+crop+normalize matches the cv2 reference path within
    the documented IFAST tolerance (<= ~4/255), incl. mirror and offsets."""
    import cv2
    from mxnet_tpu import native
    if not native.jpeg_decode_available():
        pytest.skip("no native jpeg decoder on this host")
    yy, xx = np.mgrid[0:96, 0:96]
    img = np.stack([xx * 2, yy * 2, xx + yy], -1).astype(np.uint8)
    b = _jpeg_bytes(img)
    assert native.jpeg_dims(b) == (96, 96)
    full = cv2.cvtColor(cv2.imdecode(np.frombuffer(b, np.uint8),
                                     cv2.IMREAD_COLOR), cv2.COLOR_BGR2RGB)
    mean, std = (10.0, 20.0, 30.0), (50.0, 60.0, 70.0)
    for xy, mirror in (((0, 0), False), ((5, 9), False), ((5, 9), True)):
        out = native.jpeg_decode_crop_norm(b, (64, 64), crop_xy=xy,
                                           mirror=mirror, mean=mean,
                                           std=std)
        ref = full[xy[1]:xy[1] + 64, xy[0]:xy[0] + 64].astype(np.float32)
        if mirror:
            ref = ref[:, ::-1]
        ref = (ref - np.array(mean, np.float32)) / np.array(std, np.float32)
        diff = np.abs(ref.transpose(2, 0, 1) - out)
        # IFAST DCT + plain upsampling: <= ~4 raw units / min(std)
        assert diff.max() <= 5.0 / 50.0, (xy, mirror, diff.max())


def test_jpeg_decode_scaled_and_fallbacks():
    from mxnet_tpu import native
    if not native.jpeg_decode_available():
        pytest.skip("no native jpeg decoder on this host")
    img = np.random.RandomState(0).randint(0, 255, (512, 512, 3), np.uint8)
    b = _jpeg_bytes(img)
    # min_side <= 0: FULL decode (crop semantics demand original pixels)
    out = native.jpeg_decode_crop_norm(b, (96, 96), crop_xy=(400, 400))
    assert out is not None and out.shape == (3, 96, 96)
    # min_side > 0: scaled IDCT may shrink, still covering crop+min_side
    out = native.jpeg_decode_crop_norm(b, (224, 224), min_side=256)
    assert out is not None and out.shape == (3, 224, 224)
    # undersized image -> None (caller falls back to the resize path)
    small = _jpeg_bytes(np.zeros((32, 32, 3), np.uint8))
    assert native.jpeg_decode_crop_norm(small, (64, 64)) is None
    # non-JPEG payload -> None
    assert native.jpeg_decode_crop_norm(b"not a jpeg", (8, 8)) is None
    assert native.jpeg_dims(b"nope") is None
