"""graftcheck v2 tests (ISSUE 19): the interprocedural rules GC06–GC10
— trigger + suppress pair per rule, the historical sparse_ps lock-order
inversion reproduced from a fixture, the lock-order baseline diff (new
edge = red), the CLI surface (--select/--ignore/--sarif/--stats,
--write-lock-baseline), the chaos-registry meta-test, and the
MXNET_LOCKCHECK runtime validator on the real router and the resilience
Deadline."""

import json
import os
import sys
import textwrap
import threading

import pytest

from mxnet_tpu import analysis
from mxnet_tpu.analysis import check_source, check_sources
from mxnet_tpu.analysis import core as gc_core

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return [f.rule for f in findings]


def _check(src, rel):
    return check_source(textwrap.dedent(src), rel=rel)


# --------------------------------------------------------------------------
# GC06 — lock-order cycles
# --------------------------------------------------------------------------

def test_gc06_direct_cycle():
    findings, _ = _check("""
        import threading

        _alpha_lock = threading.Lock()
        _beta_lock = threading.Lock()

        def forward():
            with _alpha_lock:
                with _beta_lock:
                    pass

        def backward():
            with _beta_lock:
                with _alpha_lock:
                    pass
        """, rel="serving/engine.py")
    assert _rules(findings) == ["GC06"]
    msg = findings[0].message
    # both witness paths are named, not just the cycle's existence
    assert "forward" in msg and "backward" in msg


def test_gc06_interprocedural_cycle_through_calls():
    """One side of the inversion only materializes two calls deep."""
    findings, _ = _check("""
        import threading

        _alpha_lock = threading.Lock()
        _beta_lock = threading.Lock()

        def _leaf():
            with _beta_lock:
                pass

        def _mid():
            _leaf()

        def forward():
            with _alpha_lock:
                _mid()

        def backward():
            with _beta_lock:
                with _alpha_lock:
                    pass
        """, rel="serving/engine.py")
    assert _rules(findings) == ["GC06"]
    assert "_mid" in findings[0].message and "_leaf" in findings[0].message


def test_gc06_sparse_ps_inversion_fixture():
    """The historical bug PR 4 fixed by hand, reverted in a fixture:
    set_optimizer nests SparsePS._lock -> _Table.lock while push nests
    the opposite way.  GC06 must reproduce it mechanically."""
    findings, _ = _check("""
        import threading

        class _Table:
            def __init__(self, value):
                self.value = value
                self.lock = threading.Lock()

        class SparsePS:
            def __init__(self):
                self._lock = threading.Lock()
                self._tables = {}
                self._updaters = {}

            def set_optimizer(self, opt):
                with self._lock:
                    self._updaters.clear()
                    for tbl in self._tables.values():
                        with tbl.lock:
                            tbl.value *= 0

            def push(self, key, grad):
                tbl = self._tables[key]
                with tbl.lock:                  # reverted fix: table
                    with self._lock:            # lock taken FIRST
                        upd = self._updaters.setdefault(key, object())
                    tbl.value += grad
                return upd
        """, rel="kvstore/sparse_ps.py")
    assert "GC06" in _rules(findings)
    msg = [f for f in findings if f.rule == "GC06"][0].message
    assert "SparsePS._lock" in msg and "_Table.lock" in msg
    assert "set_optimizer" in msg and "push" in msg


def test_gc06_dag_is_clean_and_suppression_works():
    clean, _ = _check("""
        import threading

        _alpha_lock = threading.Lock()
        _beta_lock = threading.Lock()

        def forward():
            with _alpha_lock:
                with _beta_lock:
                    pass

        def also_forward():
            with _alpha_lock:
                with _beta_lock:
                    pass
        """, rel="serving/engine.py")
    assert _rules(clean) == []
    suppressed, kept = _check("""
        import threading

        _alpha_lock = threading.Lock()
        _beta_lock = threading.Lock()

        def forward():
            with _alpha_lock:
                # graftcheck: ignore[GC06] — fixture: order proven safe
                with _beta_lock:
                    pass

        def backward():
            with _beta_lock:
                with _alpha_lock:
                    pass
        """, rel="serving/engine.py")
    assert _rules(suppressed) == []
    assert kept


# --------------------------------------------------------------------------
# GC07 — use-after-donate
# --------------------------------------------------------------------------

def test_gc07_flags_read_after_donate():
    findings, _ = _check("""
        import jax

        def _f(x):
            return x * 2

        step = jax.jit(_f, donate_argnums=0)

        def run(buf):
            out = step(buf)
            total = buf.sum()
            return out, total
        """, rel="serving/models.py")
    assert _rules(findings) == ["GC07"]
    assert "buf" in findings[0].message


def test_gc07_rebinding_over_the_result_is_clean():
    findings, _ = _check("""
        import jax

        def _f(x):
            return x * 2

        step = jax.jit(_f, donate_argnums=0)

        def run(buf):
            buf = step(buf)
            return buf.sum()
        """, rel="serving/models.py")
    assert _rules(findings) == []


def test_gc07_loop_carried_donation():
    findings, _ = _check("""
        import jax

        def _f(x):
            return x * 2

        step = jax.jit(_f, donate_argnums=0)

        def train(buf, n):
            for _ in range(n):
                step(buf)
        """, rel="serving/models.py")
    assert _rules(findings) == ["GC07"]
    assert "loop" in findings[0].message
    clean, _ = _check("""
        import jax

        def _f(x):
            return x * 2

        step = jax.jit(_f, donate_argnums=0)

        def train(buf, n):
            for _ in range(n):
                buf = step(buf)
            return buf
        """, rel="serving/models.py")
    assert _rules(clean) == []


def test_gc07_builder_and_conditional_donation():
    """Donating jits reach bindings through a builder function and a
    conditional donate tuple — both still tracked."""
    findings, _ = _check("""
        import jax

        def make_step(donate):
            d = (0,) if donate else ()
            return jax.jit(lambda x: x * 2, donate_argnums=d)

        def run(v):
            fn = make_step(True)
            fn(v)
            return v + 1
        """, rel="parallel.py")
    assert _rules(findings) == ["GC07"]


def test_gc07_suppression():
    findings, kept = _check("""
        import jax

        def _f(x):
            return x * 2

        step = jax.jit(_f, donate_argnums=0)

        def run(buf):
            out = step(buf)
            # graftcheck: ignore[GC07] — buf is a host mirror, not the donated jax array
            total = buf.sum()
            return out, total
        """, rel="serving/models.py")
    assert _rules(findings) == []
    assert kept


# --------------------------------------------------------------------------
# GC08 — atomic-protocol write discipline
# --------------------------------------------------------------------------

def test_gc08_flags_direct_protocol_write():
    findings, _ = _check("""
        import json
        import os

        def save_state(workdir, state):
            with open(os.path.join(workdir, "router.json"), "w") as f:
                json.dump(state, f)
        """, rel="serving/router.py")
    assert _rules(findings) == ["GC08"]
    assert "router.json" in findings[0].message


def test_gc08_write_temp_then_replace_is_clean():
    findings, _ = _check("""
        import json
        import os

        def save_state(workdir, state):
            path = os.path.join(workdir, "controller.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)
        """, rel="resilience/controller.py")
    assert _rules(findings) == []


def test_gc08_replace_through_a_helper_is_clean():
    findings, _ = _check("""
        import json
        import os

        def _commit(tmp, path):
            os.replace(tmp, path)

        def beat(workdir, rank, state):
            path = os.path.join(workdir, f"hb-rank{rank:05d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(state, f)
            _commit(tmp, path)
        """, rel="resilience/heartbeat.py")
    assert _rules(findings) == []


def test_gc08_append_only_logs_and_reads_exempt():
    findings, _ = _check("""
        def log_cancel(workdir, rid):
            with open(workdir + "/cancels-replica-0001.log", "a") as f:
                f.write(rid)

        def read_state(workdir):
            with open(workdir + "/router.json") as f:
                return f.read()
        """, rel="serving/replica.py")
    assert _rules(findings) == []


def test_gc08_suppression():
    findings, kept = _check("""
        import json

        def save_state(path, state):
            # graftcheck: ignore[GC08] — single-process test harness, no concurrent reader
            with open(path + "/manifest.json", "w") as f:
                json.dump(state, f)
        """, rel="checkpoint.py")
    assert _rules(findings) == []
    assert kept


# --------------------------------------------------------------------------
# GC09 — registry drift
# --------------------------------------------------------------------------

_CHAOS_FIXTURE = """
SITES = ("kvstore.allreduce", "router.dispatch")

def hit(site):
    return None
"""


def test_gc09_unregistered_chaos_site():
    findings, _ = check_sources({
        "resilience/chaos.py": _CHAOS_FIXTURE,
        "serving/router.py": textwrap.dedent("""
            from ..resilience import chaos

            def dispatch():
                chaos.hit("router.dispatch")
                chaos.hit("router.dispach")
            """),
    })
    assert _rules(findings) == ["GC09"]
    assert "router.dispach" in findings[0].message


def test_gc09_non_literal_site_flagged():
    findings, _ = check_sources({
        "resilience/chaos.py": _CHAOS_FIXTURE,
        "serving/router.py": textwrap.dedent("""
            from ..resilience import chaos

            def dispatch(site):
                chaos.hit(site)
            """),
    })
    assert _rules(findings) == ["GC09"]
    assert "non-literal" in findings[0].message


def test_gc09_metric_name_conventions():
    findings, _ = _check("""
        def register(reg):
            reg.counter("mxnet_foo")
            reg.histogram("mxnet_bar_ms")
            reg.gauge("mxnet_baz_total")
            reg.counter("mxnet_Bad_name_total")
            reg.counter("mxnet_ok_total")
            reg.histogram("mxnet_ok_seconds")
            reg.gauge("mxnet_ok_depth")
        """, rel="telemetry/extras.py")
    assert _rules(findings) == ["GC09"] * 4


def test_gc09_suppression():
    findings, kept = _check("""
        def register(reg):
            # graftcheck: ignore[GC09] — legacy dashboard name, migration tracked
            reg.counter("mxnet_foo")
        """, rel="telemetry/extras.py")
    assert _rules(findings) == []
    assert kept


def test_every_chaos_site_is_armed_by_a_test():
    """Meta-test backing the GC09 registry contract: each committed
    chaos site is referenced by at least one test in this directory."""
    from mxnet_tpu.resilience import chaos
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    blob = "\n".join(
        open(os.path.join(tests_dir, fn), encoding="utf-8").read()
        for fn in sorted(os.listdir(tests_dir)) if fn.endswith(".py"))
    assert chaos.SITES, "the chaos registry must not be empty"
    for site in chaos.SITES:
        assert site in blob, f"chaos site {site!r} is armed by no test"


# --------------------------------------------------------------------------
# GC10 — thread lifecycle
# --------------------------------------------------------------------------

def test_gc10_nondaemon_unjoined_thread():
    findings, _ = _check("""
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                return None
        """, rel="serving/engine.py")
    assert _rules(findings) == ["GC10"]
    assert "daemon" in findings[0].message


def test_gc10_daemon_or_joined_is_clean():
    findings, _ = _check("""
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
                self._w = threading.Thread(target=self._run)
                self._w.start()

            def close(self):
                self._w.join()

            def _run(self):
                return None
        """, rel="serving/engine.py")
    assert _rules(findings) == []


def test_gc10_unstoppable_while_true():
    findings, _ = _check("""
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                while True:
                    self._work()

            def _work(self):
                return None
        """, rel="serving/engine.py")
    assert _rules(findings) == ["GC10"]
    assert "while True" in findings[0].message


def test_gc10_stop_flag_or_sentinel_return_is_clean():
    findings, _ = _check("""
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
                self._s = threading.Thread(target=self._sender, daemon=True)
                self._s.start()

            def _run(self):
                while True:
                    if self._stop:
                        break
                    self._work()

            def _sender(self):
                while True:
                    item = self._q.get()
                    if item is None:
                        return
                    self._work()

            def _work(self):
                return None
        """, rel="serving/engine.py")
    assert _rules(findings) == []


def test_gc10_while_true_reached_through_calls():
    """The loop lives in a helper the thread target calls — still
    reachable, still checked."""
    findings, _ = _check("""
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                self._pump()

            def _pump(self):
                while True:
                    self._work()

            def _work(self):
                return None
        """, rel="serving/engine.py")
    assert _rules(findings) == ["GC10"]


def test_gc10_suppression():
    findings, kept = _check("""
        import threading

        class Worker:
            def start(self):
                # graftcheck: ignore[GC10] — process-lifetime supervisor, reaped by atexit
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                return None
        """, rel="serving/engine.py")
    assert _rules(findings) == []
    assert kept


# --------------------------------------------------------------------------
# CLI: --select / --ignore / --sarif / --stats / lock baseline
# --------------------------------------------------------------------------

_DIRTY = "import os\nv = os.environ.get('MXNET_ROGUE')\n"


def _mk_pkg(tmp_path, files):
    pkg = tmp_path / "mxnet_tpu"
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(pkg)


def test_cli_select_and_ignore(tmp_path):
    pkg = _mk_pkg(tmp_path, {"bad.py": _DIRTY})
    root = str(tmp_path)
    assert gc_core.main([pkg, "-q"], repo_root=root) == 1
    assert gc_core.main([pkg, "-q", "--select", "GC06,GC07"],
                        repo_root=root) == 0
    assert gc_core.main([pkg, "-q", "--ignore", "GC03"],
                        repo_root=root) == 0
    assert gc_core.main([pkg, "-q", "--select", "GC03"],
                        repo_root=root) == 1


def test_cli_sarif_output(tmp_path):
    pkg = _mk_pkg(tmp_path, {"bad.py": _DIRTY})
    out = tmp_path / "out.sarif"
    assert gc_core.main([pkg, "-q", "--sarif", str(out)],
                        repo_root=str(tmp_path)) == 1
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"GC00", "GC01", "GC06", "GC07", "GC08", "GC09",
            "GC10"} <= rule_ids
    res = run["results"]
    assert res and res[0]["ruleId"] == "GC03"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert res[0]["partialFingerprints"]["graftcheck/v1"]


def test_cli_stats_table(tmp_path, capsys):
    pkg = _mk_pkg(tmp_path, {"ok.py": "X = 1\n"})
    assert gc_core.main([pkg, "-q", "--stats"],
                        repo_root=str(tmp_path)) == 0
    err = capsys.readouterr().err
    for rule in ("GC01", "GC06", "GC10"):
        assert rule in err


_NESTED = """
import threading

_alpha_lock = threading.Lock()
_beta_lock = threading.Lock()
_gamma_lock = threading.Lock()

def forward():
    with _alpha_lock:
        with _beta_lock:
            pass
"""


def test_cli_lock_baseline_diff(tmp_path):
    """The CI contract: a new lock-order edge not in the committed
    baseline is a loud failure; a stale baseline edge too."""
    pkg = _mk_pkg(tmp_path, {"serving/engine.py": _NESTED})
    root = str(tmp_path)
    base = tmp_path / "graftcheck-lockorder.json"
    assert gc_core.main([pkg, "-q", "--write-lock-baseline", str(base)],
                        repo_root=root) == 0
    edges = json.loads(base.read_text())["edges"]
    assert [(e["from"], e["to"]) for e in edges] == \
        [("serving/engine.py::_alpha_lock", "serving/engine.py::_beta_lock")]
    # observed set matches the baseline -> clean
    assert gc_core.main([pkg, "-q"], repo_root=root) == 1 - 1
    # inject a NEW (acyclic) edge -> red until the baseline is regenerated
    _mk_pkg(tmp_path, {"serving/engine.py": _NESTED + textwrap.dedent("""
        def deeper():
            with _beta_lock:
                with _gamma_lock:
                    pass
        """)})
    assert gc_core.main([pkg, "-q"], repo_root=root) == 1
    assert gc_core.main([pkg, "-q", "--write-lock-baseline", str(base)],
                        repo_root=root) == 0
    assert gc_core.main([pkg, "-q"], repo_root=root) == 0
    # remove the nesting -> the baseline edge is stale -> red again
    _mk_pkg(tmp_path, {"serving/engine.py": "X = 1\n"})
    assert gc_core.main([pkg, "-q"], repo_root=root) == 1


def test_repo_lock_baseline_is_current():
    """The committed graftcheck-lockorder.json matches the tree (the
    same invariant the CI lane enforces)."""
    base = os.path.join(REPO_ROOT, "graftcheck-lockorder.json")
    assert os.path.exists(base), "commit the lock-order baseline"
    pkg = os.path.join(REPO_ROOT, "mxnet_tpu")
    findings, _, _ = analysis.analyze_paths([pkg], repo_root=REPO_ROOT)
    gc06 = [f for f in findings if f.rule == "GC06"]
    assert gc06 == [], "\n".join(f.render() for f in gc06)


# --------------------------------------------------------------------------
# MXNET_LOCKCHECK — the GC06 runtime twin
# --------------------------------------------------------------------------

@pytest.fixture
def lockcheck():
    analysis.arm_lockcheck(True)
    analysis.lockcheck_reset()
    yield
    analysis.arm_lockcheck(None)
    analysis.lockcheck_reset()


def test_lockcheck_disarmed_returns_raw_lock():
    lk = threading.Lock()
    assert analysis.tracked(lk, "raw") is lk


def test_lockcheck_raises_on_inversion(lockcheck):
    a = analysis.tracked(threading.Lock(), "A")
    b = analysis.tracked(threading.Lock(), "B")
    with a:
        with b:
            pass
    with pytest.raises(analysis.LockOrderError) as ei:
        with b:
            with a:
                pass
    assert "A" in str(ei.value) and "B" in str(ei.value)
    assert ("A", "B") in analysis.lockcheck_edges()


def test_lockcheck_transitive_cycle(lockcheck):
    a = analysis.tracked(threading.Lock(), "A")
    b = analysis.tracked(threading.Lock(), "B")
    c = analysis.tracked(threading.Lock(), "C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(analysis.LockOrderError):
        with c:
            with a:
                pass


def test_lockcheck_router(lockcheck, tmp_path):
    """The router's locks flow through tracked(): a real tier bring-up +
    request records Router acquisition edges and raises nothing."""
    from mxnet_tpu.serving.router import Router
    stub = [sys.executable,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "_stub_replica.py")]
    r = Router(stub, 1, str(tmp_path),
               env_extra={"MXNET_ELASTIC_HEARTBEAT_S": "0.1"}).start()
    try:
        h = r.submit([1, 2, 3], max_new_tokens=4)
        assert len(h.result(timeout=30)) == 4
    finally:
        r.stop()
    held_first = {a for a, _ in analysis.lockcheck_edges()}
    assert any(name.startswith("Router.") for name in held_first), \
        "expected the router to record tracked acquisition edges"


def test_lockcheck_controller_deadline(lockcheck):
    """The resilience tier's Deadline lock is tracked: a guarded call
    under the armed validator runs clean (and the lock really is the
    validating proxy, not a bare Lock)."""
    from mxnet_tpu.analysis.runtime import _TrackedLock
    from mxnet_tpu.resilience import Deadline
    d = Deadline(timeout_s=5, site="lockcheck.unit")
    assert isinstance(d._lock, _TrackedLock)
    assert d.call(lambda: "ok") == "ok"
    d.close()
