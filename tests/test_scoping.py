"""mx.name.NameManager / mx.AttrScope / mx.rtc (reference name.py,
attribute.py, rtc.py — P21 misc infra)."""

import numpy as np
import pytest

import mxnet_tpu as mx


def test_name_manager_auto_names():
    with mx.name.NameManager():
        a = mx.sym.Variable("x")
        d1 = mx.sym.FullyConnected(a, num_hidden=4)
        d2 = mx.sym.FullyConnected(a, num_hidden=4)
    assert d1.name == "fullyconnected0"
    assert d2.name == "fullyconnected1"
    # explicit names always win
    with mx.name.NameManager():
        d3 = mx.sym.FullyConnected(a, num_hidden=4, name="fc")
    assert d3.name == "fc"


def test_name_prefix():
    with mx.name.Prefix("enc_"):
        s = mx.sym.softmax(mx.sym.Variable("x"))
    assert s.name.startswith("enc_softmax")


def test_attr_scope_attaches_and_execution_unaffected():
    x = mx.sym.Variable("data")
    with mx.AttrScope(__ctx_group__="dev1", __lr_mult__="2"):
        y = mx.sym.FullyConnected(x, num_hidden=3, name="fc")
    assert y.attr("__ctx_group__") == "dev1"
    assert y.attr("__lr_mult__") == "2"
    # dunder attrs must not leak into the operator kwargs: bind + forward
    ex = y.simple_bind(mx.cpu(), data=(2, 5))
    ex.forward(data=mx.nd.ones((2, 5)))
    assert ex.outputs[0].shape == (2, 3)
    # nested scopes accumulate; inner wins on conflict
    with mx.AttrScope(__ctx_group__="a"):
        with mx.AttrScope(__ctx_group__="b"):
            z = mx.sym.relu(x)
    assert z.attr("__ctx_group__") == "b"


def test_attr_scope_rejects_non_dunder():
    with pytest.raises(ValueError, match="dunder"):
        mx.AttrScope(ctx_group="dev1")


def test_rtc_dropped_with_rationale():
    with pytest.raises(mx.MXNetError, match="Pallas"):
        mx.rtc.CudaModule("kernel source")


def test_attr_scope_applies_to_variables():
    with mx.AttrScope(__lr_mult__="2"):
        w = mx.sym.Variable("w")
    assert w.attr("__lr_mult__") == "2"


def test_non_dunder_attr_dict_rejected():
    x = mx.sym.Variable("x")
    with pytest.raises(mx.MXNetError, match="dunder"):
        mx.sym.relu(x, attr={"mood": "happy"})


def test_viz_print_summary():
    x = mx.sym.Variable("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(x, num_hidden=16,
                                                name="fc1"),
                          act_type="relu", name="act1")
    out = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    total = mx.viz.print_summary(out, shape={"data": (2, 8)})
    assert total == (16 * 8 + 16) + (4 * 16 + 4)


def test_viz_plot_network():
    x = mx.sym.Variable("data")
    sym = mx.sym.relu(mx.sym.FullyConnected(x, num_hidden=2, name="fc"))
    try:
        import graphviz  # noqa: F401
    except ImportError:
        with pytest.raises(mx.MXNetError, match="graphviz"):
            mx.viz.plot_network(sym)
        return
    dot = mx.viz.plot_network(sym)
    src = dot.source
    assert "fc" in src and "->" in src
