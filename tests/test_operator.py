"""Operator numeric checks (reference tests/python/unittest/test_operator.py).

Pattern preserved: each op checked against a numpy reference; gradients via
check_numeric_gradient for representative ops (finite differences vs the
autograd path — SURVEY §4.2 numeric oracles)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient)
import scipy.special as sps


UNARY_CASES = [
    ("relu", lambda x: np.maximum(x, 0)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
    ("tanh", np.tanh),
    ("exp", np.exp),
    ("log", lambda x: np.log(np.abs(x) + 1.1)),
    ("sqrt", lambda x: np.sqrt(np.abs(x) + 1.0)),
    ("square", np.square),
    ("abs", np.abs),
    ("floor", np.floor),
    ("erf", sps.erf),
    ("gammaln", lambda x: sps.gammaln(np.abs(x) + 1.0)),
]


@pytest.mark.parametrize("name,ref", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary(name, ref):
    x = np.random.uniform(-2, 2, (3, 4)).astype("float32")
    if name in ("log", "sqrt"):
        xin = np.abs(x) + (1.1 if name == "log" else 1.0)
    elif name == "gammaln":
        xin = np.abs(x) + 1.0
    else:
        xin = x
    out = getattr(nd, name)(nd.array(xin)).asnumpy()
    assert_almost_equal(out, ref(x) if name not in ("log", "sqrt", "gammaln")
                        else ref(x), rtol=1e-4, atol=1e-4)


def test_broadcast_binary():
    a = np.random.uniform(-2, 2, (3, 1, 4)).astype("float32")
    b = np.random.uniform(0.5, 2, (1, 5, 4)).astype("float32")
    na, nb = nd.array(a), nd.array(b)
    assert_almost_equal(nd.broadcast_add(na, nb).asnumpy(), a + b)
    assert_almost_equal(nd.broadcast_mul(na, nb).asnumpy(), a * b)
    assert_almost_equal(nd.broadcast_div(na, nb).asnumpy(), a / b)
    assert_almost_equal(nd.broadcast_maximum(na, nb).asnumpy(),
                        np.maximum(a, b))
    assert_almost_equal(nd.broadcast_power(nb, nb).asnumpy(), b ** b,
                        rtol=1e-3, atol=1e-3)


def test_softmax_family():
    x = np.random.uniform(-3, 3, (4, 7)).astype("float32")
    ex = np.exp(x - x.max(-1, keepdims=True))
    sm = ex / ex.sum(-1, keepdims=True)
    assert_almost_equal(nd.softmax(nd.array(x)).asnumpy(), sm, rtol=1e-4,
                        atol=1e-5)
    assert_almost_equal(nd.log_softmax(nd.array(x)).asnumpy(), np.log(sm),
                        rtol=1e-4, atol=1e-4)
    t = 2.0
    ext = np.exp(x / t - (x / t).max(-1, keepdims=True))
    assert_almost_equal(nd.softmax(nd.array(x), temperature=t).asnumpy(),
                        ext / ext.sum(-1, keepdims=True), rtol=1e-4,
                        atol=1e-5)


def test_fully_connected():
    x = np.random.uniform(-1, 1, (5, 3, 4)).astype("float32")
    w = np.random.uniform(-1, 1, (8, 12)).astype("float32")
    b = np.random.uniform(-1, 1, (8,)).astype("float32")
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                            num_hidden=8)
    ref = x.reshape(5, 12).dot(w.T) + b
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-4, atol=1e-4)
    out2 = nd.FullyConnected(nd.array(x), nd.array(
        np.random.uniform(-1, 1, (8, 4)).astype("float32")), None,
        num_hidden=8, flatten=False, no_bias=True)
    assert out2.shape == (5, 3, 8)


def test_convolution_vs_explicit():
    import jax
    x = np.random.uniform(-1, 1, (2, 3, 8, 8)).astype("float32")
    w = np.random.uniform(-1, 1, (4, 3, 3, 3)).astype("float32")
    b = np.zeros((4,), "float32")
    out = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), num_filter=4).asnumpy()
    # direct correlation reference
    ref = np.zeros((2, 4, 6, 6), "float32")
    for n in range(2):
        for f in range(4):
            for i in range(6):
                for j in range(6):
                    ref[n, f, i, j] = (x[n, :, i:i + 3, j:j + 3]
                                       * w[f]).sum()
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-3)


def test_convolution_grouped_strided():
    x = np.random.uniform(-1, 1, (2, 4, 9, 9)).astype("float32")
    w = np.random.uniform(-1, 1, (6, 2, 3, 3)).astype("float32")
    out = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                         num_filter=6, num_group=2, stride=(2, 2),
                         pad=(1, 1), no_bias=True)
    assert out.shape == (2, 6, 5, 5)


def test_deconvolution_shape():
    x = np.random.uniform(-1, 1, (2, 4, 5, 5)).astype("float32")
    w = np.random.uniform(-1, 1, (4, 3, 3, 3)).astype("float32")
    out = nd.Deconvolution(nd.array(x), nd.array(w), kernel=(3, 3),
                           num_filter=3, stride=(2, 2), no_bias=True)
    assert out.shape == (2, 3, 11, 11)


def test_pooling():
    x = np.random.uniform(-1, 1, (2, 3, 6, 6)).astype("float32")
    mp = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                    pool_type="max").asnumpy()
    ref = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    assert_almost_equal(mp, ref)
    ap = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                    pool_type="avg").asnumpy()
    assert_almost_equal(ap, x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5)),
                        rtol=1e-5, atol=1e-6)
    gp = nd.Pooling(nd.array(x), global_pool=True, pool_type="avg").asnumpy()
    assert_almost_equal(gp[:, :, 0, 0], x.mean(axis=(2, 3)), rtol=1e-5,
                        atol=1e-6)


def test_batchnorm_train_stats():
    x = np.random.uniform(-1, 1, (8, 4, 3, 3)).astype("float32")
    gamma = np.ones(4, "float32")
    beta = np.zeros(4, "float32")
    mm = nd.zeros((4,))
    mv = nd.ones((4,))
    from mxnet_tpu import autograd
    with autograd.train_mode():
        out = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                           mm, mv, fix_gamma=False, momentum=0.9)
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    ref = (x - mean[None, :, None, None]) / np.sqrt(
        var[None, :, None, None] + 1e-3)
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-3, atol=1e-3)
    assert_almost_equal(mm.asnumpy(), 0.1 * mean, rtol=1e-4, atol=1e-5)
    assert_almost_equal(mv.asnumpy(), 0.9 + 0.1 * var, rtol=1e-4, atol=1e-5)


def test_layernorm():
    x = np.random.uniform(-1, 1, (4, 6)).astype("float32")
    g = np.random.uniform(0.5, 1.5, (6,)).astype("float32")
    b = np.random.uniform(-0.5, 0.5, (6,)).astype("float32")
    out = nd.LayerNorm(nd.array(x), nd.array(g), nd.array(b)).asnumpy()
    mu = x.mean(-1, keepdims=True)
    sig = x.var(-1, keepdims=True)
    assert_almost_equal(out, (x - mu) / np.sqrt(sig + 1e-5) * g + b,
                        rtol=1e-4, atol=1e-4)


def test_gradients_numeric():
    check_numeric_gradient(lambda x: (x * x).sum(),
                           [np.random.uniform(-1, 1, (3, 3)).astype("float32")])
    check_numeric_gradient(lambda x: nd.tanh(x).sum(),
                           [np.random.uniform(-1, 1, (4,)).astype("float32")])
    check_numeric_gradient(
        lambda a, b: nd.dot(a, b).sum(),
        [np.random.uniform(-1, 1, (3, 4)).astype("float32"),
         np.random.uniform(-1, 1, (4, 2)).astype("float32")])


def test_embedding_and_grad():
    from mxnet_tpu import autograd
    w = nd.array(np.random.uniform(-1, 1, (10, 4)).astype("float32"))
    w.attach_grad()
    idx = nd.array(np.array([1, 3, 1]))
    with autograd.record():
        out = nd.Embedding(idx, w, input_dim=10, output_dim=4)
        loss = out.sum()
    loss.backward()
    g = w.grad.asnumpy()
    assert g[1].sum() == pytest.approx(8.0)  # row 1 hit twice
    assert g[3].sum() == pytest.approx(4.0)
    assert g[0].sum() == 0


def test_where_clip():
    x = np.random.uniform(-2, 2, (3, 4)).astype("float32")
    c = (x > 0).astype("float32")
    out = nd.where(nd.array(c), nd.array(x), nd.array(-x))
    assert_almost_equal(out.asnumpy(), np.abs(x))
    assert_almost_equal(nd.clip(nd.array(x), a_min=-1, a_max=1).asnumpy(),
                        np.clip(x, -1, 1))


def test_gather_scatter_nd():
    x = np.random.uniform(size=(3, 4)).astype("float32")
    idx = np.array([[0, 2], [1, 3]])
    out = nd.gather_nd(nd.array(x), nd.array(idx))
    assert_almost_equal(out.asnumpy(), x[[0, 2], [1, 3]])
    sc = nd.scatter_nd(nd.array(np.array([5.0, 6.0], "float32")),
                       nd.array(idx), shape=(3, 4))
    ref = np.zeros((3, 4), "float32")
    ref[0, 1] = 5
    ref[2, 3] = 6
    assert_almost_equal(sc.asnumpy(), ref)


def test_sequence_ops():
    x = np.random.uniform(size=(4, 3, 2)).astype("float32")  # (T, N, C)
    slen = np.array([2, 4, 1], "float32")
    masked = nd.sequence_mask(nd.array(x), nd.array(slen),
                              use_sequence_length=True, value=-1.0)
    m = masked.asnumpy()
    assert (m[2:, 0] == -1).all() and (m[1:, 2] == -1).all()
    assert (m[:, 1] == x[:, 1]).all()
    last = nd.sequence_last(nd.array(x), nd.array(slen),
                            use_sequence_length=True)
    assert_almost_equal(last.asnumpy()[0], x[1, 0])
    rev = nd.sequence_reverse(nd.array(x), nd.array(slen),
                              use_sequence_length=True)
    assert_almost_equal(rev.asnumpy()[0, 0], x[1, 0])


def test_rnn_lstm_shapes():
    T, N, I, H, L = 5, 3, 4, 6, 2
    ng = 4
    size = 0
    for l in range(L):
        in_sz = I if l == 0 else H
        size += ng * H * in_sz + ng * H * H + 2 * ng * H
    params = nd.array(np.random.uniform(-0.1, 0.1, (size,)).astype("float32"))
    x = nd.array(np.random.uniform(-1, 1, (T, N, I)).astype("float32"))
    h0 = nd.zeros((L, N, H))
    c0 = nd.zeros((L, N, H))
    out = nd.RNN(x, params, h0, c0, state_size=H, num_layers=L, mode="lstm",
                 state_outputs=True)
    assert out[0].shape == (T, N, H)
    assert out[1].shape == (L, N, H)
    assert out[2].shape == (L, N, H)


def test_interleaved_attention_consistency():
    """qk/valatt fused ops == explicit attention math."""
    L, B, H, D = 4, 2, 3, 5
    qkv = np.random.uniform(-1, 1, (L, B, 3 * H * D)).astype("float32")
    att = nd.contrib.interleaved_matmul_selfatt_qk(nd.array(qkv), heads=H)
    assert att.shape == (B * H, L, L)
    x = qkv.reshape(L, B, H, 3, D)
    q, k, v = x[:, :, :, 0], x[:, :, :, 1], x[:, :, :, 2]
    ref = np.einsum("qbhd,kbhd->bhqk", q / np.sqrt(D), k).reshape(B * H, L, L)
    assert_almost_equal(att.asnumpy(), ref, rtol=1e-4, atol=1e-4)
    out = nd.contrib.interleaved_matmul_selfatt_valatt(
        nd.array(qkv), att, heads=H)
    ref_out = np.einsum("bhqk,kbhd->qbhd",
                        ref.reshape(B, H, L, L), v).reshape(L, B, H * D)
    assert_almost_equal(out.asnumpy(), ref_out, rtol=1e-4, atol=1e-4)


def test_optimizer_ops_match_numpy():
    w = np.random.uniform(-1, 1, (6,)).astype("float32")
    g = np.random.uniform(-1, 1, (6,)).astype("float32")
    out = nd.sgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.01)
    assert_almost_equal(out.asnumpy(), w - 0.1 * (g + 0.01 * w), rtol=1e-5,
                        atol=1e-6)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    wn, mn, vn = nd.adam_update(nd.array(w), nd.array(g), nd.array(m),
                                nd.array(v), lr=0.01)
    m_ref = 0.1 * g
    v_ref = 0.001 * g * g
    assert_almost_equal(mn.asnumpy(), m_ref, rtol=1e-4, atol=1e-6)
    assert_almost_equal(
        wn.asnumpy(), w - 0.01 * m_ref / (np.sqrt(v_ref) + 1e-8),
        rtol=1e-4, atol=1e-5)


def test_linalg():
    a = np.random.uniform(0.5, 1.5, (3, 3)).astype("float32")
    spd = a.dot(a.T) + 3 * np.eye(3, dtype="float32")
    l = nd.linalg.potrf(nd.array(spd)).asnumpy()
    assert_almost_equal(l.dot(l.T), spd, rtol=1e-3, atol=1e-3)
    assert_almost_equal(nd.linalg.inverse(nd.array(spd)).asnumpy(),
                        np.linalg.inv(spd), rtol=1e-2, atol=1e-3)
    x = np.random.uniform(-1, 1, (2, 3, 4)).astype("float32")
    y = np.random.uniform(-1, 1, (2, 4, 5)).astype("float32")
    assert_almost_equal(
        nd.linalg.gemm2(nd.array(x), nd.array(y)).asnumpy(),
        np.matmul(x, y), rtol=1e-4, atol=1e-4)


def test_softmax_output_backward():
    from mxnet_tpu import autograd
    x = nd.array(np.random.uniform(-1, 1, (4, 3)).astype("float32"))
    label = nd.array(np.array([0, 1, 2, 1], "float32"))
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, label)
    out.backward()
    p = out.asnumpy()
    oh = np.eye(3)[[0, 1, 2, 1]]
    assert_almost_equal(x.grad.asnumpy(), p - oh, rtol=1e-4, atol=1e-5)


def test_boolean_mask():
    x = np.random.uniform(size=(5, 3)).astype("float32")
    mask = np.array([1, 0, 1, 0, 1], "float32")
    out = nd.boolean_mask(nd.array(x), nd.array(mask))
    assert_almost_equal(out.asnumpy(), x[[0, 2, 4]])


# -- r5 operator tail: regression heads, center_loss, im2col/col2im --------

def test_regression_output_heads():
    from mxnet_tpu import autograd
    rng = np.random.RandomState(0)
    d = rng.randn(4, 3).astype("float32")
    l = rng.randn(4, 3).astype("float32")

    x = nd.array(d); x.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(x, nd.array(l), grad_scale=2.0)
    out.backward()
    assert_almost_equal(out.asnumpy(), d)
    assert_almost_equal(x.grad.asnumpy(), (d - l) * 2.0 / 3, rtol=1e-5)

    x = nd.array(d); x.attach_grad()
    with autograd.record():
        out = nd.MAERegressionOutput(x, nd.array(l))
    out.backward()
    assert_almost_equal(x.grad.asnumpy(), np.sign(d - l) / 3, rtol=1e-5)

    lb = (rng.rand(4, 3) > 0.5).astype("float32")
    x = nd.array(d); x.attach_grad()
    with autograd.record():
        out = nd.LogisticRegressionOutput(x, nd.array(lb))
    out.backward()
    sig = 1 / (1 + np.exp(-d))
    assert_almost_equal(out.asnumpy(), sig, rtol=1e-5)
    assert_almost_equal(x.grad.asnumpy(), (sig - lb) / 3, rtol=1e-5)


def test_regression_output_module_fit():
    """Module-era workflow: LinearRegressionOutput head learns a linear
    map under Module.fit (reference model.py usage of the heads)."""
    rng = np.random.RandomState(0)
    X = rng.randn(200, 8).astype("float32")
    W = rng.randn(8, 1).astype("float32")
    y = (X @ W).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True,
                           label_name="lin_label")
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
    net = mx.sym.LinearRegressionOutput(net, name="lin")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("lin_label",))
    mod.fit(it, num_epoch=10, optimizer="adam",
            optimizer_params=(("learning_rate", 0.05),),
            eval_metric="mse")
    mse = mod.score(it, "mse")[0][1]
    assert mse < 0.05, f"LinearRegressionOutput failed to learn (mse={mse})"


def test_center_loss():
    from mxnet_tpu import autograd
    rng = np.random.RandomState(0)
    f = rng.randn(6, 4).astype("float32")
    y = rng.randint(0, 3, (6,)).astype("float32")
    c0 = rng.randn(3, 4).astype("float32")

    x = nd.array(f); x.attach_grad()
    centers = nd.array(c0.copy())
    with autograd.record():
        loss = nd.center_loss(x, nd.array(y), centers, grad_scale=1.0,
                              alpha=0.5)
    loss.backward()
    diff = f - c0[y.astype(int)]
    assert_almost_equal(loss.asnumpy(),
                        0.5 * (diff ** 2).sum(axis=1), rtol=1e-5)
    # loss gradient flows to features only (centers are aux state)
    assert_almost_equal(x.grad.asnumpy(), diff, rtol=1e-5)
    # aux update: c_j += alpha * sum(diff_j) / (1 + n_j), training mode only
    cn = centers.asnumpy()
    expect = c0.copy()
    for j in range(3):
        sel = y.astype(int) == j
        expect[j] += 0.5 * diff[sel].sum(axis=0) / (1 + sel.sum())
    assert_almost_equal(cn, expect, rtol=1e-5)
    # inference mode: centers stay put
    centers2 = nd.array(c0.copy())
    nd.center_loss(nd.array(f), nd.array(y), centers2, alpha=0.5)
    assert_almost_equal(centers2.asnumpy(), c0)


def test_im2col_col2im():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 5, 5).astype("float32")
    out = nd.im2col(nd.array(x), kernel=(3, 3), stride=(1, 1), pad=(1, 1))
    assert out.shape == (2, 27, 25)
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    cols = np.zeros((2, 3, 3, 3, 5, 5), np.float32)
    for kh in range(3):
        for kw in range(3):
            cols[:, :, kh, kw] = xp[:, :, kh:kh + 5, kw:kw + 5]
    assert_almost_equal(out.asnumpy(), cols.reshape(2, 27, 25))
    # col2im is im2col's transpose: scatter-adds overlapping patches; a
    # ones-column image counts how many patches cover each pixel
    ones = nd.array(np.ones((1, 9, 25), np.float32))
    cover = nd.col2im(ones, output_size=(5, 5), kernel=(3, 3),
                      stride=(1, 1), pad=(1, 1)).asnumpy()
    assert cover[0, 0, 2, 2] == 9.0 and cover[0, 0, 0, 0] == 4.0
    # kernel=1 roundtrip is exact
    x1 = rng.randn(2, 3, 4, 4).astype("float32")
    c1 = nd.im2col(nd.array(x1), kernel=(1, 1))
    assert_almost_equal(
        nd.col2im(c1, output_size=(4, 4), kernel=(1, 1)).asnumpy(), x1)


def test_r5_op_additions():
    """AdaptiveAvgPooling2D / BilinearResize2D / activations / LQ /
    maketrian / BatchNormWithReLU / getnnz / amp_multicast (r5 tail)."""
    rng = np.random.RandomState(0)
    x = nd.array(np.arange(2 * 3 * 4 * 6, dtype=np.float32)
                 .reshape(2, 3, 4, 6))
    out = nd.contrib.AdaptiveAvgPooling2D(x, output_size=(2, 3))
    xn = x.asnumpy()
    ref = np.zeros((2, 3, 2, 3), np.float32)
    for i in range(2):
        for j in range(3):
            y0, y1 = (i * 4) // 2, -(-((i + 1) * 4) // 2)
            x0, x1 = (j * 6) // 3, -(-((j + 1) * 6) // 3)
            ref[:, :, i, j] = xn[:, :, y0:y1, x0:x1].mean(axis=(2, 3))
    assert_almost_equal(out.asnumpy(), ref)
    g = nd.contrib.AdaptiveAvgPooling2D(x, output_size=1)
    assert_almost_equal(g.asnumpy()[:, :, 0, 0], xn.mean(axis=(2, 3)))

    r = rng.randn(1, 2, 5, 7).astype(np.float32)
    same = nd.contrib.BilinearResize2D(nd.array(r), height=5, width=7)
    assert_almost_equal(same.asnumpy(), r, rtol=1e-5)
    up = nd.contrib.BilinearResize2D(nd.array(r), height=9, width=13)
    # align_corners: the corner samples are exact
    assert_almost_equal(up.asnumpy()[0, :, 0, 0], r[0, :, 0, 0], rtol=1e-5)
    assert_almost_equal(up.asnumpy()[0, :, -1, -1], r[0, :, -1, -1],
                        rtol=1e-5)

    xs = np.linspace(-4, 4, 9).astype(np.float32)
    assert_almost_equal(nd.log_sigmoid(nd.array(xs)).asnumpy(),
                        np.log(1 / (1 + np.exp(-xs))), rtol=1e-5)
    assert_almost_equal(nd.mish(nd.array(xs)).asnumpy(),
                        xs * np.tanh(np.log1p(np.exp(xs))), rtol=1e-4)

    A = rng.randn(4, 6).astype(np.float32)
    L, Q = nd.linalg.gelqf(nd.array(A))
    assert_almost_equal(L.asnumpy() @ Q.asnumpy(), A, rtol=1e-4, atol=1e-5)
    assert_almost_equal(Q.asnumpy() @ Q.asnumpy().T, np.eye(4), atol=1e-5)
    assert_almost_equal(np.triu(L.asnumpy(), 1), 0)   # L is lower

    S = np.tril(rng.randn(4, 4)).astype(np.float32)
    assert_almost_equal(
        nd.linalg.maketrian(nd.linalg.extracttrian(nd.array(S))).asnumpy(),
        S)

    d = nd.array(rng.randn(2, 4, 3, 3).astype(np.float32))
    ones, zeros = nd.array(np.ones(4, np.float32)), \
        nd.array(np.zeros(4, np.float32))
    o = nd.BatchNormWithReLU(d, ones, zeros, nd.array(np.zeros(4, np.float32)),
                             nd.array(np.ones(4, np.float32)))
    assert (o.asnumpy() >= 0).all()
    ref_bn = nd.BatchNorm(d, ones, zeros, nd.array(np.zeros(4, np.float32)),
                          nd.array(np.ones(4, np.float32)))
    assert_almost_equal(o.asnumpy(), np.maximum(ref_bn.asnumpy(), 0))

    z = nd.array(np.array([[1, 0, 2], [0, 0, 3]], np.float32))
    assert int(nd.contrib.getnnz(z).asnumpy()) == 3
    outs = nd.amp_multicast(nd.array(np.ones(3, np.float32)),
                            nd.array(np.ones(3, np.float16)),
                            num_outputs=2)
    assert str(outs[0].dtype) == "float32"
    assert str(outs[1].dtype) == "float32"
    assert nd.contrib.boolean_mask(
        z, nd.array(np.array([1, 0], np.float32))).shape == (1, 3)
    assert nd.cast_storage(z, "row_sparse").stype == "row_sparse"


def test_callable_memo_hot_path():
    """registry._callable_for memoizes the (op, attrs) → callable mapping
    (ISSUE 2 satellite): repeat dispatches are one dict probe, unhashable
    attrs (PRNG keys, list-valued attrs) skip the memo but still work."""
    from mxnet_tpu.ops import registry
    op = registry.get("clip")
    registry._callable_memo.clear()
    attrs = {"a_min": 0.0, "a_max": 1.0}
    f1 = registry._callable_for(op, attrs)
    f2 = registry._callable_for(op, dict(attrs))
    assert f1 is f2  # memo hit across equal attr dicts
    assert len(registry._callable_memo) == 1
    # unhashable attr values bypass the memo without breaking dispatch
    import jax.numpy as jnp
    g = registry._callable_for(registry.get("broadcast_add"), {})
    assert g(jnp.ones(2), jnp.ones(2)) is not None
    bad = registry._callable_for(op, {"a_min": [0.0], "a_max": 1.0})
    assert bad is not None
    assert all(not isinstance(k[2], list) for k in registry._callable_memo)
    # transient Op objects (numpy wrappers, autograd backward replays,
    # CachedOp) carry per-instance closures: they must NEVER enter the
    # memo, even under a name collision with an interned op
    transient = registry.Op("clip", lambda x: x + 1.0, jit=False)
    before = dict(registry._callable_memo)
    ft = registry._callable_for(transient, {})
    assert registry._callable_memo == before
    import jax.numpy as jnp
    np.testing.assert_allclose(ft(jnp.zeros(2)), [1.0, 1.0])
    # ... and the interned op still resolves to its own impl afterwards
    out = mx.nd.clip(mx.nd.array(np.array([-1.0, 2.0], np.float32)),
                     a_min=0.0, a_max=1.0)
    np.testing.assert_allclose(out.asnumpy(), [0.0, 1.0])
