"""model_zoo.vision tests (reference test_gluon_model_zoo.py patterns).

Forward passes use small inputs / small nets to keep the CPU-platform
suite fast; every zoo name must at least construct and hold the right
classifier shape.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo import get_model, vision


ALL_MODELS = sorted(vision._models)


def test_all_names_construct():
    for name in ALL_MODELS:
        net = get_model(name, classes=7)
        assert net is not None, name


def test_unknown_name_raises():
    with pytest.raises(MXNetError, match="not in the model zoo"):
        get_model("resnet1999_v9")


def test_pretrained_raises():
    with pytest.raises(MXNetError, match="pretrained"):
        get_model("resnet18_v1", pretrained=True)


@pytest.mark.parametrize("name", ["resnet18_v1", "resnet18_v2",
                                  "mobilenet0.25", "squeezenet1.1"])
def test_small_models_forward(name, seeded):
    net = get_model(name, classes=10)
    net.initialize()
    out = net(mx.nd.ones((2, 3, 32, 32)))
    assert out.shape == (2, 10)


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_resnet_thumbnail_trains(seeded):
    # CIFAR-style lane: thumbnail avoids the 7x7/maxpool stem
    net = vision.resnet18_v1(classes=4, thumbnail=True)
    net.initialize(mx.initializer.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    r = np.random.RandomState(0)
    x = mx.nd.array(r.randn(8, 3, 16, 16).astype(np.float32))
    y = mx.nd.array(r.randint(0, 4, (8,)))
    losses = []
    for _ in range(8):
        with autograd.record():
            loss = lossf(net(x), y)
        loss.backward()
        tr.step(8)
        losses.append(float(loss.mean().asnumpy()))
    assert min(losses[1:]) < losses[0]  # optimizing (BN+adam jitter allowed)
    assert all(np.isfinite(l) for l in losses)


def test_resnet50_structure():
    net = vision.resnet50_v1(classes=11)
    params = net.collect_params()
    keys = list(params.keys())
    # bottleneck stages: 3+4+6+3 blocks, each 3 convs + stem + downsamples
    n_convs = sum(1 for k in keys if "conv" in k and k.endswith("weight"))
    assert n_convs == 1 + (3 + 4 + 6 + 3) * 3 + 4  # stem + body + downsample
    dense_w = next(k for k in keys if "dense" in k and k.endswith("weight"))
    assert params[dense_w].shape[0] == 11


def test_hybridize_parity_resnet(seeded):
    net = vision.resnet18_v1(classes=5, thumbnail=True)
    net.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(1).randn(2, 3, 16, 16)
                    .astype(np.float32))
    imp = net(x).asnumpy()
    net.hybridize()
    hyb = net(x).asnumpy()
    np.testing.assert_allclose(imp, hyb, rtol=1e-4, atol=1e-5)


# -- r5: Transformer-base MT (BASELINE config 3) + YOLOv3 (config 2) -------

def test_transformer_causality_and_enc_mask(seeded):
    from mxnet_tpu.gluon.model_zoo import transformer
    m = transformer.transformer_model("transformer_test", vocab_size=50,
                                      max_length=32, dropout=0.0)
    m.initialize(mx.initializer.Normal(0.05))
    r = np.random.RandomState(0)
    src = mx.nd.array(r.randint(0, 50, (3, 10)).astype(np.int32))
    tgt = mx.nd.array(r.randint(0, 50, (3, 8)).astype(np.int32))
    vl = mx.nd.array(np.array([10, 7, 4], np.int32))
    logits = m(src, tgt, vl)
    assert logits.shape == (3, 8, 50)
    # decoder causality: perturbing tgt[:, 5] leaves logits[:, :5] unchanged
    t2 = tgt.asnumpy().copy()
    t2[:, 5] = (t2[:, 5] + 1) % 50
    l2 = m(src, mx.nd.array(t2), vl)
    d = np.abs(logits.asnumpy() - l2.asnumpy()).max(axis=(0, 2))
    np.testing.assert_allclose(d[:5], 0, atol=1e-5)
    assert d[5:].max() > 1e-3
    # encoder padding mask: tokens beyond valid_length are invisible
    s2 = src.asnumpy().copy()
    s2[1, 8] = (s2[1, 8] + 3) % 50      # beyond vl=7
    l3 = m(mx.nd.array(s2), tgt, vl)
    np.testing.assert_allclose(logits.asnumpy(), l3.asnumpy(), atol=1e-5)


def test_transformer_tied_embedding(seeded):
    from mxnet_tpu.gluon.model_zoo import transformer
    m = transformer.transformer_model("transformer_test", vocab_size=30,
                                      max_length=16)
    params = m.collect_params()
    embeds = [k for k in params.keys() if "embed_weight" in k]
    assert len(embeds) == 1          # one table: src = tgt = softmax


def test_label_smoothed_ce_loss():
    from mxnet_tpu.gluon.loss import LabelSmoothedCELoss
    r = np.random.RandomState(0)
    logits = mx.nd.array(r.randn(4, 6, 10).astype(np.float32))
    labels = np.array(r.randint(1, 10, (4, 6)), np.float32)
    labels[0, 3:] = 0                # padding
    # smoothing=0 + no padding == plain softmax CE
    plain = LabelSmoothedCELoss(smoothing=0.0)(
        logits, mx.nd.array(labels)).asnumpy()
    logp = logits.asnumpy() - np.log(
        np.exp(logits.asnumpy()).sum(-1, keepdims=True))
    nll = np.take_along_axis(
        logp, labels.astype(int)[..., None], -1)[..., 0]
    np.testing.assert_allclose(plain, -nll.mean(-1), rtol=1e-4, atol=1e-5)
    # padding rows contribute zero under ignore_index
    l_pad = LabelSmoothedCELoss(smoothing=0.1, ignore_index=0)(
        logits, mx.nd.array(labels)).asnumpy()
    sm = 0.9 * (-nll) + 0.1 * (-logp.mean(-1))
    want0 = sm[0, :3].sum() / 3      # only the 3 valid positions
    np.testing.assert_allclose(l_pad[0], want0, rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # >10s on the tier-1 budget clock (r7 audit); runs in the CI slow lane
def test_yolo3_structure_and_targets(seeded):
    from mxnet_tpu.gluon.model_zoo import yolo
    net = yolo.YOLOV3(
        backbone=yolo.Darknet(layers=(1, 1, 1, 1, 1),
                              channels=(4, 8, 16, 32, 64, 128)),
        classes=3, channels=(32, 16, 8))
    net.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(2, 3, 64, 64).astype(np.float32))
    outs = net(x)
    # strides 32/16/8 on a 64px input, 3 anchors each, 5+classes channels
    assert [tuple(o.shape) for o in outs] == \
        [(2, 2 * 2 * 3, 8), (2, 4 * 4 * 3, 8), (2, 8 * 8 * 3, 8)]
    gen = yolo.YOLOV3TargetGenerator(classes=3, input_size=64)
    labels = np.array([[[1, .1, .1, .5, .5], [-1, 0, 0, 0, 0]],
                       [[2, .3, .2, .9, .8], [0, 0, 0, .2, .3]]],
                      np.float32)
    targets = gen(labels)
    # every non-padding gt claims exactly one positive anchor
    n_pos = sum(t[4].sum() for t in targets)
    assert n_pos == 3
    loss = yolo.YOLOV3Loss()(
        mx.nd, outs, [[mx.nd.array(t) for t in s] for s in targets])
    assert np.isfinite(float(loss.asnumpy()))
    det = yolo.yolo3_decode(outs, input_size=64, conf_thresh=0.0, topk=5)
    assert det.shape == (2, 5, 6)


def test_yolo3_darknet53_constructs():
    from mxnet_tpu.gluon.model_zoo import yolo
    net = yolo.yolo3_darknet53(classes=80)
    n_convs = sum(1 for k in net.collect_params().keys()
                  if "conv" in k and k.endswith("weight"))
    assert n_convs >= 52 + 3        # darknet53 + heads


def test_transformer_hybridize_parity(seeded):
    from mxnet_tpu.gluon.model_zoo import transformer
    m = transformer.transformer_model("transformer_test", vocab_size=40,
                                      max_length=16, dropout=0.0)
    m.initialize(mx.initializer.Normal(0.05))
    r = np.random.RandomState(3)
    src = mx.nd.array(r.randint(0, 40, (2, 10)).astype(np.int32))
    tgt = mx.nd.array(r.randint(0, 40, (2, 8)).astype(np.int32))
    imp = m(src, tgt).asnumpy()
    m.hybridize()
    hyb = m(src, tgt).asnumpy()
    np.testing.assert_allclose(imp, hyb, rtol=1e-4, atol=1e-5)


def test_yolo3_hybridize_parity(seeded):
    from mxnet_tpu.gluon.model_zoo import yolo
    net = yolo.YOLOV3(
        backbone=yolo.Darknet(layers=(1, 1, 1, 1, 1),
                              channels=(4, 8, 16, 32, 64, 128)),
        classes=2, channels=(32, 16, 8))
    net.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(5)
                    .randn(2, 3, 64, 64).astype(np.float32))
    imp = [o.asnumpy() for o in net(x)]
    net.hybridize()
    hyb = [o.asnumpy() for o in net(x)]
    for a, b in zip(imp, hyb):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_beam_search_decode(seeded):
    from mxnet_tpu.gluon.model_zoo import transformer
    m = transformer.transformer_model("transformer_test", vocab_size=30,
                                      max_length=16, dropout=0.0)
    m.initialize(mx.initializer.Normal(0.05))
    r = np.random.RandomState(0)
    src = mx.nd.array(r.randint(3, 30, (3, 8)).astype(np.int32))
    vl = mx.nd.array(np.array([8, 6, 4], np.int32))
    for k in (1, 4):
        out, scores = transformer.beam_search_decode(
            m, src, 1, 2, beam_size=k, max_len=12, src_valid_length=vl)
        assert out.shape[0] == 3 and out.shape[1] <= 12
        assert (out[:, 0] == 1).all()                 # BOS prefix
        assert ((out >= 0) & (out < 30)).all()
        # every row terminates with EOS (completed pool or fallback pad)
        assert (out == 2).any(axis=1).all()
        assert np.isfinite(scores).all()
        # deterministic: same inputs -> same beams
        out2, scores2 = transformer.beam_search_decode(
            m, src, 1, 2, beam_size=k, max_len=12, src_valid_length=vl)
        np.testing.assert_array_equal(out, out2)
        np.testing.assert_allclose(scores, scores2)
