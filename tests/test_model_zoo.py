"""model_zoo.vision tests (reference test_gluon_model_zoo.py patterns).

Forward passes use small inputs / small nets to keep the CPU-platform
suite fast; every zoo name must at least construct and hold the right
classifier shape.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo import get_model, vision


ALL_MODELS = sorted(vision._models)


def test_all_names_construct():
    for name in ALL_MODELS:
        net = get_model(name, classes=7)
        assert net is not None, name


def test_unknown_name_raises():
    with pytest.raises(MXNetError, match="not in the model zoo"):
        get_model("resnet1999_v9")


def test_pretrained_raises():
    with pytest.raises(MXNetError, match="pretrained"):
        get_model("resnet18_v1", pretrained=True)


@pytest.mark.parametrize("name", ["resnet18_v1", "resnet18_v2",
                                  "mobilenet0.25", "squeezenet1.1"])
def test_small_models_forward(name, seeded):
    net = get_model(name, classes=10)
    net.initialize()
    out = net(mx.nd.ones((2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_resnet_thumbnail_trains(seeded):
    # CIFAR-style lane: thumbnail avoids the 7x7/maxpool stem
    net = vision.resnet18_v1(classes=4, thumbnail=True)
    net.initialize(mx.initializer.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    r = np.random.RandomState(0)
    x = mx.nd.array(r.randn(8, 3, 16, 16).astype(np.float32))
    y = mx.nd.array(r.randint(0, 4, (8,)))
    losses = []
    for _ in range(8):
        with autograd.record():
            loss = lossf(net(x), y)
        loss.backward()
        tr.step(8)
        losses.append(float(loss.mean().asnumpy()))
    assert min(losses[1:]) < losses[0]  # optimizing (BN+adam jitter allowed)
    assert all(np.isfinite(l) for l in losses)


def test_resnet50_structure():
    net = vision.resnet50_v1(classes=11)
    params = net.collect_params()
    keys = list(params.keys())
    # bottleneck stages: 3+4+6+3 blocks, each 3 convs + stem + downsamples
    n_convs = sum(1 for k in keys if "conv" in k and k.endswith("weight"))
    assert n_convs == 1 + (3 + 4 + 6 + 3) * 3 + 4  # stem + body + downsample
    dense_w = next(k for k in keys if "dense" in k and k.endswith("weight"))
    assert params[dense_w].shape[0] == 11


def test_hybridize_parity_resnet(seeded):
    net = vision.resnet18_v1(classes=5, thumbnail=True)
    net.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(1).randn(2, 3, 16, 16)
                    .astype(np.float32))
    imp = net(x).asnumpy()
    net.hybridize()
    hyb = net(x).asnumpy()
    np.testing.assert_allclose(imp, hyb, rtol=1e-4, atol=1e-5)
