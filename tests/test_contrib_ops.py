"""Contrib op tail: fft, count_sketch, ctc_loss, SSD multibox family,
PSROIPooling, DeformableConvolution, gluon.contrib.nn layers.

Reference anchors: src/operator/contrib/{fft,count_sketch,multibox_prior,
multibox_target,multibox_detection,psroi_pooling,deformable_convolution}.cc,
src/operator/nn/ctc_loss.cc, python/mxnet/gluon/contrib/nn/basic_layers.py.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_fft_ifft_roundtrip_and_values():
    r = np.random.RandomState(0)
    x = r.randn(3, 8).astype(np.float32)
    f = nd.contrib.fft(nd.array(x)).asnumpy()
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(f[:, 0::2], ref.real, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(f[:, 1::2], ref.imag, rtol=1e-4, atol=1e-5)
    # reference ifft is unnormalized (cuFFT): ifft(fft(x)) == n * x
    back = nd.contrib.ifft(nd.array(f)).asnumpy()
    np.testing.assert_allclose(back, 8 * x, rtol=1e-4, atol=1e-4)


def test_count_sketch_projection():
    d = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
    h = nd.array(np.array([0, 1, 0, 2], np.float32))
    s = nd.array(np.array([1, -1, 1, 1], np.float32))
    out = nd.contrib.count_sketch(nd.array(d), h, s, out_dim=3).asnumpy()
    np.testing.assert_allclose(out, [[1 + 3, -2, 4]])


def test_ctc_loss_matches_gluon_and_grad():
    T, N, C = 12, 2, 5
    r = np.random.RandomState(1)
    logits = nd.array(r.randn(T, N, C).astype(np.float32))
    label = nd.array(np.array([[1, 2, 0], [3, 1, 2]], np.float32))
    loss = nd.ctc_loss(logits, label)
    assert loss.shape == (N,)
    assert (loss.asnumpy() > 0).all()
    # imperative gradient flows (op registered differentiable via optax)
    logits.attach_grad()
    with autograd.record():
        l = nd.ctc_loss(logits, label).sum()
    l.backward()
    g = logits.grad.asnumpy()
    assert np.abs(g).max() > 0 and np.isfinite(g).all()


def test_ctc_loss_label_lengths_only():
    """Passing ONLY label_lengths must not shift it into the data_lengths
    slot (None positionals are dropped by op wrappers)."""
    import optax
    from mxnet_tpu.gluon import loss as gloss
    r = np.random.RandomState(7)
    T, N, C = 12, 1, 5
    pred = r.randn(N, T, C).astype(np.float32)       # NTC gluon layout
    label = np.array([[1, 2, 2]], np.float32)
    ll = np.array([2], np.float32)                    # only first 2 labels
    out = gloss.CTCLoss(blank_label="first")(
        nd.array(pred), nd.array(label), None, nd.array(ll)).asnumpy()
    ref = optax.ctc_loss(pred, np.zeros((N, T), np.float32),
                         label.astype(np.int32),
                         (np.arange(3)[None] >= ll[:, None])
                         .astype(np.float32), blank_id=0)
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-4)


def test_multibox_target_pad_rows_cannot_steal_anchor0():
    """A pad row (cls=-1) must not unassign or claim anchor 0 even when a
    real gt's best anchor IS anchor 0."""
    # anchors: anchor 0 exactly overlaps the gt, others far away
    anchors = nd.array(np.array([[[0.0, 0.0, 0.3, 0.3],
                                  [0.7, 0.7, 0.9, 0.9]]], np.float32))
    label = nd.array(np.array(
        [[[2, 0.0, 0.0, 0.3, 0.3], [-1, 0, 0, 0, 0]]], np.float32))
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        anchors, label, nd.zeros((1, 4, 2)))
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 3.0                    # class 2 → target 3 at anchor 0
    assert ct[1] == 0.0                    # far anchor stays background
    assert np.isfinite(loc_t.asnumpy()).all()
    # the matched anchor's offsets are ~0 (exact overlap), not degenerate
    np.testing.assert_allclose(loc_t.asnumpy()[0][:4], 0.0, atol=1e-5)


def test_multibox_target_hard_negative_mining():
    """negative_mining_ratio=1 with one positive: exactly one hard negative
    (the one the classifier is most confident about) stays background 0,
    other unmatched anchors become ignore_label -1."""
    anchors = nd.array(np.array([[[0.0, 0.0, 0.3, 0.3],
                                  [0.4, 0.4, 0.6, 0.6],
                                  [0.7, 0.7, 0.9, 0.9]]], np.float32))
    label = nd.array(np.array([[[0, 0.0, 0.0, 0.3, 0.3]]], np.float32))
    cls_pred = np.zeros((1, 3, 3), np.float32)
    cls_pred[0, 1, 2] = 0.9         # anchor 2 = most object-confident
    cls_pred[0, 1, 1] = 0.2
    _, _, cls_t = nd.contrib.MultiBoxTarget(
        anchors, label, nd.array(cls_pred), negative_mining_ratio=1.0,
        negative_mining_thresh=0.5)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 1.0             # the positive
    assert ct[2] == 0.0             # hardest negative kept as background
    assert ct[1] == -1.0            # remaining negative ignored


def test_multibox_detection_emits_secondary_classes():
    """An anchor confident for two classes yields candidates for both
    (reference emits one candidate per non-background class, not argmax)."""
    anchors = nd.array(np.array([[[0.1, 0.1, 0.5, 0.5]]], np.float32))
    cls_prob = np.zeros((1, 3, 1), np.float32)
    cls_prob[0, 1, 0] = 0.45                # class 0
    cls_prob[0, 2, 0] = 0.44                # class 1
    det = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.zeros((1, 4)), anchors).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert len(kept) == 1                   # same-anchor: capped by A rows
    # without force_suppress, different classes don't suppress each other —
    # but output is capped at A rows; widen A to see both
    anchors2 = nd.array(np.array([[[0.1, 0.1, 0.5, 0.5],
                                   [0.6, 0.6, 0.9, 0.9]]], np.float32))
    cls_prob2 = np.zeros((1, 3, 2), np.float32)
    cls_prob2[0, 1, 0] = 0.45
    cls_prob2[0, 2, 0] = 0.44
    det2 = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob2), nd.zeros((1, 8)), anchors2).asnumpy()[0]
    kept2 = det2[det2[:, 0] >= 0]
    assert sorted(kept2[:, 0].tolist()) == [0.0, 1.0]


def test_multibox_prior_layout():
    feat = nd.zeros((1, 8, 4, 5))
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.4, 0.2),
                                       ratios=(1, 2, 0.5))
    # A = sizes + ratios - 1 = 4 per pixel
    assert anchors.shape == (1, 4 * 5 * 4, 4)
    a = anchors.asnumpy()[0].reshape(4, 5, 4, 4)
    # first anchor at pixel (0,0): size .4, ratio 1, centered (0.5/5, 0.5/4)
    cx, cy = 0.5 / 5, 0.5 / 4
    np.testing.assert_allclose(a[0, 0, 0],
                               [cx - 0.2, cy - 0.2, cx + 0.2, cy + 0.2],
                               atol=1e-6)


def test_multibox_target_matching():
    feat = nd.zeros((1, 4, 4, 4))
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.3,), ratios=(1,))
    A = anchors.shape[1]
    # one gt box matching the anchor near (0.375, 0.375)
    label = nd.array(np.array(
        [[[1, 0.25, 0.25, 0.5, 0.5], [-1, 0, 0, 0, 0]]], np.float32))
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        anchors, label, nd.zeros((1, 3, A)))
    ct = cls_t.asnumpy()[0]
    assert (ct > 0).sum() >= 1              # at least the forced best anchor
    assert set(np.unique(ct)) <= {0.0, 2.0}  # class id 1 → target 2 (1+cls)
    lm = loc_m.asnumpy()[0].reshape(A, 4)
    assert ((lm.sum(1) > 0) == (ct > 0)).all()  # mask aligns with matches


def test_multibox_detection_decodes_and_nms():
    feat = nd.zeros((1, 4, 2, 2))
    # two sizes per pixel → same-center boxes with IoU 0.69: NMS fodder
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.5, 0.6), ratios=(1,))
    A = anchors.shape[1]
    cls_prob = np.zeros((1, 2, A), np.float32)
    cls_prob[0, 0] = 0.1
    cls_prob[0, 1] = 0.9                     # all anchors confident class 0
    det = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.zeros((1, A * 4)), anchors,
        nms_threshold=0.3).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert len(kept) == A // 2               # one survivor per pixel
    np.testing.assert_allclose(kept[0, 1], 0.9, atol=1e-6)


def test_psroi_pooling_position_sensitivity():
    """Each output bin must read its own channel group: constant-per-channel
    input makes output bin (d, ph, pw) equal the value of its group chan."""
    D, g = 2, 2
    C = D * g * g
    data = np.zeros((1, C, 8, 8), np.float32)
    for c in range(C):
        data[0, c] = c
    rois = nd.array(np.array([[0, 0, 0, 8, 8]], np.float32))
    out = nd.contrib.PSROIPooling(nd.array(data), rois, output_dim=D,
                                  pooled_size=g, group_size=g).asnumpy()
    for d in range(D):
        for py in range(g):
            for px in range(g):
                expect = (d * g + py) * g + px
                np.testing.assert_allclose(out[0, d, py, px], expect)


def test_deformable_conv_zero_offset_equals_conv():
    r = np.random.RandomState(2)
    x = nd.array(r.randn(2, 3, 10, 10).astype(np.float32))
    w = nd.array(r.randn(5, 3, 3, 3).astype(np.float32))
    off = nd.zeros((2, 18, 8, 8))
    out = nd.contrib.DeformableConvolution(x, off, w, kernel=(3, 3),
                                           num_filter=5)
    ref = nd.Convolution(x, w, kernel=(3, 3), num_filter=5, no_bias=True)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_shift():
    """Constant integer offset == sampling the shifted image."""
    r = np.random.RandomState(3)
    x_np = r.randn(1, 2, 9, 9).astype(np.float32)
    w = nd.array(r.randn(3, 2, 3, 3).astype(np.float32))
    off_np = np.zeros((1, 18, 7, 7), np.float32)
    off_np[:, 0::2] = 1.0                    # shift all taps down 1 px
    out = nd.contrib.DeformableConvolution(
        nd.array(x_np), nd.array(off_np), w, kernel=(3, 3), num_filter=3)
    shifted = np.pad(x_np, ((0, 0), (0, 0), (0, 1), (0, 0)))[:, :, 1:, :]
    ref = nd.Convolution(nd.array(shifted), w, kernel=(3, 3), num_filter=3,
                         no_bias=True)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_proposal_rpn():
    """RPN proposals: right shape, batch indices, image clipping, and NMS
    keeping the highest-objectness box first."""
    r = np.random.RandomState(0)
    N, A, H, W = 2, 9, 6, 6
    kw = dict(scales=(8, 16, 32), ratios=(0.5, 1, 2))
    cls = nd.array(r.rand(N, 2 * A, H, W).astype(np.float32))
    bbox = nd.array((r.randn(N, 4 * A, H, W) * 0.1).astype(np.float32))
    info = nd.array(np.array([[96, 96, 1.0]] * N, np.float32))
    rois = nd.contrib.Proposal(cls, bbox, info, rpn_pre_nms_top_n=100,
                               rpn_post_nms_top_n=20, rpn_min_size=4,
                               **kw).asnumpy()
    assert rois.shape == (N * 20, 5)
    assert (rois[:20, 0] == 0).all() and (rois[20:, 0] == 1).all()
    assert (rois[:, 1:] >= 0).all() and (rois[:, 1:] <= 95).all()
    rois2, sc = nd.contrib.Proposal(cls, bbox, info, rpn_post_nms_top_n=10,
                                    output_score=True, **kw)
    sc = sc.asnumpy()
    # first kept roi per image carries the max objectness of its image
    fg = cls.asnumpy()[:, A:]
    assert sc[0, 0] >= fg[0].max() - 1e-4 or sc[0, 0] > 0.99
    # MultiProposal is the batch alias
    mr = nd.contrib.MultiProposal(cls, bbox, info, rpn_post_nms_top_n=20,
                                  **kw).asnumpy()
    assert mr.shape == (N * 20, 5)


def test_sync_batch_norm_and_contrib_layers():
    from mxnet_tpu.gluon.contrib import nn as cnn
    from mxnet_tpu.gluon import nn
    sbn = cnn.SyncBatchNorm(in_channels=4, num_devices=8)
    sbn.initialize()
    x = nd.array(np.random.RandomState(4).randn(2, 4, 3, 3)
                 .astype(np.float32))
    with autograd.record():
        y = sbn(x)
    # training-mode BN: per-channel batch stats normalize to ~0 mean
    m = y.asnumpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, 0, atol=1e-5)

    ident = cnn.Identity()
    np.testing.assert_array_equal(ident(x).asnumpy(), x.asnumpy())

    conc = cnn.Concurrent(axis=1)
    conc.add(cnn.Identity())
    conc.add(cnn.Identity())
    assert conc(x).shape == (2, 8, 3, 3)


def test_deformable_conv_numeric_gradient():
    """Finite-difference check through the bilinear-gather deformable conv
    (test_utils.check_numeric_gradient, the reference's universal grad
    oracle)."""
    r = np.random.RandomState(5)
    x = nd.array(r.randn(1, 2, 6, 6).astype(np.float32))
    w = nd.array(r.randn(2, 2, 3, 3).astype(np.float32) * 0.5)
    off = nd.array((r.randn(1, 18, 4, 4) * 0.3).astype(np.float32))
    x.attach_grad(); w.attach_grad(); off.attach_grad()
    with autograd.record():
        out = nd.contrib.DeformableConvolution(x, off, w, kernel=(3, 3),
                                               num_filter=2)
        loss = (out * out).sum()
    loss.backward()
    eps = 1e-2
    xn = x.asnumpy()
    for (i, j) in [(0, 0), (1, 3)]:
        pert = xn.copy(); pert[0, 0, i, j] += eps
        lp = float((nd.contrib.DeformableConvolution(
            nd.array(pert), off, w, kernel=(3, 3), num_filter=2) ** 2)
            .sum().asnumpy())
        pert[0, 0, i, j] -= 2 * eps
        lm = float((nd.contrib.DeformableConvolution(
            nd.array(pert), off, w, kernel=(3, 3), num_filter=2) ** 2)
            .sum().asnumpy())
        fd = (lp - lm) / (2 * eps)
        np.testing.assert_allclose(x.grad.asnumpy()[0, 0, i, j], fd,
                                   rtol=0.05, atol=0.05)


def test_psroi_pooling_gradient_flows():
    data = nd.array(np.random.RandomState(6)
                    .randn(1, 8, 6, 6).astype(np.float32))
    rois = nd.array(np.array([[0, 0, 0, 5, 5]], np.float32))
    data.attach_grad()
    with autograd.record():
        out = nd.contrib.PSROIPooling(data, rois, output_dim=2,
                                      pooled_size=2, group_size=2)
        loss = out.sum()
    loss.backward()
    g = data.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_fft_gradient_roundtrip():
    x = nd.array(np.random.RandomState(7).randn(2, 8).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        loss = (nd.contrib.ifft(nd.contrib.fft(x)) / 8).sum()
    loss.backward()
    # d/dx sum(ifft(fft(x))/n) == 1 elementwise (linear roundtrip)
    np.testing.assert_allclose(x.grad.asnumpy(), 1.0, rtol=1e-4, atol=1e-4)


def test_masked_encdec_att_matches_unfused_chain():
    """The fused cross-attention op (r5) ≡ the reference-shaped unfused
    chain interleaved_matmul_encdec_qk → (mask) → softmax →
    interleaved_matmul_encdec_valatt — the layout contract both share."""
    r = np.random.RandomState(7)
    Lq, Lk, B, H, D = 6, 9, 2, 2, 4
    q = nd.array(r.randn(Lq, B, H * D).astype(np.float32))
    kv = nd.array(r.randn(Lk, B, 2 * H * D).astype(np.float32))
    vl = nd.array(np.array([9, 5], np.float32))

    fused = nd.contrib.masked_encdec_att(q, kv, vl, heads=H).asnumpy()

    att = nd.contrib.interleaved_matmul_encdec_qk(q, kv, heads=H)
    # source-padding mask between qk and softmax (GluonNLP decoder contract)
    a = att.asnumpy().reshape(B, H, Lq, Lk)
    mask = np.arange(Lk)[None, :] < vl.asnumpy()[:, None]
    a = np.where(mask[:, None, None, :], a, -1e9)
    p = np.exp(a - a.max(-1, keepdims=True))
    p = (p / p.sum(-1, keepdims=True)).reshape(B * H, Lq, Lk)
    chain = nd.contrib.interleaved_matmul_encdec_valatt(
        kv, nd.array(p.astype(np.float32)), heads=H).asnumpy()
    np.testing.assert_allclose(fused, chain, rtol=1e-4, atol=1e-5)


def test_masked_encdec_att_grads_flow():
    from mxnet_tpu import autograd
    r = np.random.RandomState(8)
    q = nd.array(r.randn(4, 2, 8).astype(np.float32))
    kv = nd.array(r.randn(5, 2, 16).astype(np.float32))
    q.attach_grad()
    kv.attach_grad()
    with autograd.record():
        out = nd.contrib.masked_encdec_att(q, kv, None, heads=2)
        loss = (out * out).sum()
    loss.backward()
    assert np.isfinite(q.grad.asnumpy()).all()
    assert np.abs(kv.grad.asnumpy()).sum() > 0


# ---------------------------------------------------------------------------
# multihead_attention_* named wrappers (ISSUE 14 satellite; VERDICT
# missing #2): parity against ops.contrib._dense_sdpa, the tree's ONE
# attention-numerics oracle.
# ---------------------------------------------------------------------------

def _mha_ref(q, k, v, H, valid_length=None, causal=False):
    """Key-only-masked oracle on (L, B, H*D) inputs: _dense_sdpa for the
    mask-free cases (the shared numerics core) and an explicit
    keys-masked softmax otherwise — queries are ALWAYS valid, the op's
    documented contract (independent of Lq == Lk)."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.contrib import _dense_sdpa

    def heads(x):
        L, B, E = x.shape
        return jnp.transpose(
            jnp.asarray(x).reshape(L, B, H, E // H), (1, 2, 0, 3))

    D = q.shape[-1] // H
    Lq, B = q.shape[0], q.shape[1]
    if valid_length is None:
        out = np.asarray(_dense_sdpa(heads(q), heads(k), heads(v), None,
                                     causal, 1.0 / float(D) ** 0.5))
        return out.transpose(2, 0, 1, 3).reshape(Lq, B, -1)
    Lk = k.shape[0]
    att = np.einsum("qbhd,kbhd->bhqk",
                    q.reshape(Lq, B, H, D) / np.sqrt(D),
                    k.reshape(Lk, B, H, D))
    att = np.where((np.arange(Lk)[None, :] < valid_length[:, None])
                   [:, None, None, :], att, -1e9)
    if causal:
        att = np.where(np.tril(np.ones((Lq, Lk), bool))[None, None],
                       att, -1e9)
    p = np.exp(att - att.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,kbhd->qbhd", p,
                     v.reshape(Lk, B, H, D)).reshape(Lq, B, H * D)


def test_multihead_attention_matches_dense_sdpa():
    r = np.random.RandomState(11)
    L, B, H, D = 6, 3, 2, 4
    q = r.randn(L, B, H * D).astype(np.float32)
    k = r.randn(L, B, H * D).astype(np.float32)
    v = r.randn(L, B, H * D).astype(np.float32)
    for vl, causal in ((None, False), (np.array([6, 3, 5]), False),
                      (None, True), (np.array([4, 6, 2]), True)):
        got = nd.contrib.multihead_attention(
            nd.array(q), nd.array(k), nd.array(v),
            None if vl is None else nd.array(vl.astype(np.float32)),
            heads=H, causal=causal).asnumpy()
        want = _mha_ref(q, k, v, H, valid_length=vl, causal=causal)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   err_msg=f"vl={vl} causal={causal}")


def test_multihead_attention_cross_lengths():
    """Lq != Lk takes the cross path: key-side masking only."""
    r = np.random.RandomState(12)
    Lq, Lk, B, H, D = 5, 9, 2, 2, 4
    q = r.randn(Lq, B, H * D).astype(np.float32)
    k = r.randn(Lk, B, H * D).astype(np.float32)
    v = r.randn(Lk, B, H * D).astype(np.float32)
    vl = np.array([9, 4])
    got = nd.contrib.multihead_attention(
        nd.array(q), nd.array(k), nd.array(v),
        nd.array(vl.astype(np.float32)), heads=H).asnumpy()
    # oracle: _dense_sdpa_cross == _dense_sdpa with key-side-only seg;
    # build it by masking scores directly
    att = np.einsum("qbhd,kbhd->bhqk",
                    q.reshape(Lq, B, H, D) / np.sqrt(D),
                    k.reshape(Lk, B, H, D))
    att = np.where((np.arange(Lk)[None, :] < vl[:, None])
                   [:, None, None, :], att, -1e9)
    p = np.exp(att - att.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,kbhd->qbhd", p,
                     v.reshape(Lk, B, H, D)).reshape(Lq, B, H * D)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_multihead_attention_mask_independent_of_length_coincidence():
    """Key-only masking must NOT flip to the self-attention two-sided
    mask just because Lq happens to equal Lk (review regression): the
    first Lq query rows of an (Lq, Lk+1)-shaped cross call — key row
    Lk padded away by valid_length — must equal the (Lq, Lq)-shaped
    call on the same keys."""
    r = np.random.RandomState(15)
    L, B, H, D = 6, 2, 2, 4
    q = r.randn(L, B, H * D).astype(np.float32)
    k = r.randn(L + 1, B, H * D).astype(np.float32)
    v = r.randn(L + 1, B, H * D).astype(np.float32)
    vl = np.array([3.0, 5.0], np.float32)
    eq = nd.contrib.multihead_attention(
        nd.array(q), nd.array(k[:L]), nd.array(v[:L]), nd.array(vl),
        heads=H).asnumpy()
    cross = nd.contrib.multihead_attention(
        nd.array(q), nd.array(k), nd.array(v), nd.array(vl),
        heads=H).asnumpy()
    np.testing.assert_allclose(eq, cross, rtol=1e-5, atol=1e-6)


def test_multihead_attention_causal_cross_raises():
    r = np.random.RandomState(16)
    q = nd.array(r.randn(4, 2, 8).astype(np.float32))
    kv = nd.array(r.randn(5, 2, 8).astype(np.float32))
    with pytest.raises(mx.base.MXNetError, match="causal"):
        nd.contrib.multihead_attention(q, kv, kv, heads=2, causal=True)


def test_multihead_attention_qk_valatt_chain():
    """qk → softmax → valatt ≡ the fused op (all-valid, non-causal) —
    and the qk scores match the interleaved op's on the same content."""
    r = np.random.RandomState(13)
    L, B, H, D = 6, 2, 2, 4
    q = r.randn(L, B, H * D).astype(np.float32)
    k = r.randn(L, B, H * D).astype(np.float32)
    v = r.randn(L, B, H * D).astype(np.float32)
    att = nd.contrib.multihead_attention_qk(nd.array(q), nd.array(k),
                                            heads=H).asnumpy()
    assert att.shape == (B * H, L, L)
    p = np.exp(att - att.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    chain = nd.contrib.multihead_attention_valatt(
        nd.array(p.astype(np.float32)), nd.array(v), heads=H).asnumpy()
    fused = nd.contrib.multihead_attention(
        nd.array(q), nd.array(k), nd.array(v), heads=H).asnumpy()
    np.testing.assert_allclose(chain, fused, rtol=1e-4, atol=1e-5)
    # scores equal the interleaved op's on identically-interleaved qkv
    qkv = np.stack([q.reshape(L, B, H, D), k.reshape(L, B, H, D),
                    v.reshape(L, B, H, D)], axis=3).reshape(L, B, 3 * H * D)
    want = nd.contrib.interleaved_matmul_selfatt_qk(
        nd.array(qkv), heads=H).asnumpy()
    np.testing.assert_allclose(att, want, rtol=1e-5, atol=1e-6)


def test_multihead_attention_grads_flow():
    r = np.random.RandomState(14)
    q = nd.array(r.randn(4, 2, 8).astype(np.float32))
    k = nd.array(r.randn(4, 2, 8).astype(np.float32))
    v = nd.array(r.randn(4, 2, 8).astype(np.float32))
    for x in (q, k, v):
        x.attach_grad()
    with autograd.record():
        out = nd.contrib.multihead_attention(q, k, v, heads=2,
                                             causal=True)
        loss = (out * out).sum()
    loss.backward()
    for x in (q, k, v):
        assert np.isfinite(x.grad.asnumpy()).all()
        assert np.abs(x.grad.asnumpy()).sum() > 0
