"""Multi-device data parallelism tests on the 8-virtual-CPU mesh.

Covers VERDICT r1 item 1: split_and_load + per-ctx replicas + kvstore
'device' reduction match single-device numerics, and the fused SPMD
TrainStep (mxnet_tpu.parallel) matches the imperative loop.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal

N_DEV = 8


@pytest.fixture
def ctxs():
    from mxnet_tpu import parallel
    cs = parallel.data_parallel_ctxs()
    assert len(cs) >= N_DEV, "conftest must force 8 cpu devices"
    return cs[:N_DEV]


def _mlp(seed=7):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential(prefix="mlp_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    return net


def _init_net(net, ctx, seed=7):
    mx.random.seed(seed)
    np.random.seed(seed)
    net.initialize(mx.initializer.Xavier(rnd_type="uniform"), ctx=ctx)


def test_split_and_load(ctxs):
    x = nd.array(np.arange(32, dtype="float32").reshape(16, 2))
    parts = gluon.utils.split_and_load(x, ctxs)
    assert len(parts) == N_DEV
    assert all(p.shape == (2, 2) for p in parts)
    for i, p in enumerate(parts):
        assert p.ctx == ctxs[i]
    back = np.concatenate([p.asnumpy() for p in parts])
    assert_almost_equal(back, x.asnumpy())


def test_parameter_replicas(ctxs):
    p = gluon.Parameter("w", shape=(3, 3))
    p.initialize(ctx=ctxs)
    assert len(p.list_data()) == N_DEV
    assert len(p.list_ctx()) == N_DEV
    for c, d in zip(ctxs, p.list_data()):
        assert p.data(c) is d
    # set_data propagates to every replica
    val = np.random.randn(3, 3).astype("float32")
    p.set_data(nd.array(val))
    for d in p.list_data():
        assert_almost_equal(d.asnumpy(), val)


def test_kvstore_device_reduces(ctxs):
    kv = mx.kv.create("device")
    base = nd.zeros((4,))
    kv.init(3, base)
    grads = [nd.array(np.full(4, float(i + 1), "float32"), ctx=c)
             for i, c in enumerate(ctxs)]
    kv.push(3, grads)
    kv.pull(3, grads)
    expect = np.full(4, sum(range(1, N_DEV + 1)), "float32")
    for g, c in zip(grads, ctxs):
        assert_almost_equal(g.asnumpy(), expect)
        assert g.ctx == c


def test_multictx_training_matches_single(ctxs):
    """The defining DP test: 8-replica training == 1-device training."""
    data = np.random.randn(16, 8).astype("float32")
    label = np.random.randn(16, 4).astype("float32")

    def run(ctx_list, steps=3):
        net = _mlp()
        _init_net(net, ctx_list)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05}, kvstore="device")
        x_all = nd.array(data)
        y_all = nd.array(label)
        for _ in range(steps):
            xs = gluon.utils.split_and_load(x_all, ctx_list)
            ys = gluon.utils.split_and_load(y_all, ctx_list)
            with autograd.record():
                losses = [((net(x) - y) ** 2).sum() for x, y in zip(xs, ys)]
            for l in losses:
                l.backward()
            trainer.step(len(data))
        return {k: v.data().asnumpy()
                for k, v in net.collect_params().items()}

    single = run([ctxs[0]])
    multi = run(ctxs)
    assert single.keys() == multi.keys()
    for k in single:
        assert_almost_equal(multi[k], single[k], rtol=1e-5, atol=1e-6)


def test_trainstep_matches_imperative():
    """parallel.TrainStep (fused SPMD step) == imperative loop, incl. the
    traced-t Adam bias correction across steps."""
    from mxnet_tpu import parallel
    data = np.random.randn(16, 8).astype("float32")
    label = np.random.randn(16, 4).astype("float32")

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    # imperative reference
    net_a = _mlp()
    _init_net(net_a, mx.cpu(0))
    opt_a = mx.optimizer.Adam(learning_rate=0.01)
    trainer = gluon.Trainer(net_a.collect_params(), opt_a, kvstore=None)
    for _ in range(3):
        with autograd.record():
            l = loss_fn(net_a(nd.array(data)), nd.array(label))
        l.backward()
        trainer.step(1)

    # fused step over an 8-device dp mesh
    mesh = parallel.make_mesh(axis_names=("dp",))
    net_b = _mlp()
    _init_net(net_b, mx.cpu(0))
    step = parallel.TrainStep(net_b, loss_fn,
                              mx.optimizer.Adam(learning_rate=0.01),
                              mesh=mesh, donate=False)
    losses = [float(step(data, label).asscalar()) for _ in range(3)]
    assert losses[2] < losses[0]  # it learns

    pa = {k: v.data().asnumpy() for k, v in net_a.collect_params().items()}
    pb = {k: v.data().asnumpy() for k, v in net_b.collect_params().items()}
    for k in pa:
        assert_almost_equal(pb[k], pa[k], rtol=1e-4, atol=1e-5)


def test_allreduce_eager(ctxs):
    from mxnet_tpu import parallel
    mesh = parallel.DeviceMesh(axis_names=("dp",))
    vals = [nd.array(np.full((2, 2), float(i), "float32"), ctx=c)
            for i, c in enumerate(ctxs)]
    out = parallel.allreduce(vals, mesh=mesh)
    expect = np.full((2, 2), sum(range(N_DEV)), "float32")
    for o in out:
        assert_almost_equal(o.asnumpy(), expect)


def test_multictx_adam_replicas_stay_sync(ctxs):
    """code-review r2: shared optimizer counters must advance once per
    logical step, not once per replica (Adam bias correction)."""
    two = ctxs[:2]
    net = _mlp(seed=11)
    _init_net(net, two, seed=11)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01}, kvstore="device")
    x = nd.array(np.random.randn(8, 8).astype("float32"))
    y = nd.array(np.random.randn(8, 4).astype("float32"))
    for _ in range(2):
        xs = gluon.utils.split_and_load(x, two)
        ys = gluon.utils.split_and_load(y, two)
        with autograd.record():
            losses = [((net(a) - b) ** 2).sum() for a, b in zip(xs, ys)]
        for l in losses:
            l.backward()
        trainer.step(8)
    assert trainer.optimizer._index_update_count[0] == 2
    for p in net.collect_params().values():
        reps = [d.asnumpy() for d in p.list_data()]
        assert_almost_equal(reps[0], reps[1])


def test_hybridized_multictx_forward(ctxs):
    """code-review r2: hybridized forward with replicas off the default ctx."""
    sub = ctxs[1:3]
    net = _mlp(seed=13)
    _init_net(net, sub, seed=13)
    net.hybridize()
    x = nd.array(np.random.randn(4, 8).astype("float32"), ctx=sub[0])
    out1 = net(x).asnumpy()
    x2 = x.as_in_context(sub[1])
    out2 = net(x2).asnumpy()
    assert_almost_equal(out1, out2, rtol=1e-6)


def test_shared_subgraph_double_backward_raises():
    """code-review r2: freed shared subgraph must raise, not drop grads."""
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        l1 = y.sum()
        l2 = (y * 3).sum()
    l1.backward()
    with pytest.raises(mx.MXNetError):
        l2.backward()


def test_allreduce_mean(ctxs):
    from mxnet_tpu import parallel
    mesh = parallel.DeviceMesh(axis_names=("dp",))
    vals = [nd.array(np.full((3,), float(i), "float32"), ctx=c)
            for i, c in enumerate(ctxs)]
    out = parallel.allreduce(vals, mesh=mesh, op="mean")
    expect = np.full((3,), np.mean(range(N_DEV)), "float32")
    for o in out:
        assert_almost_equal(o.asnumpy(), expect)
    with pytest.raises(mx.MXNetError):
        parallel.allreduce(vals, mesh=mesh, op="max")


def test_trainer_states_roundtrip(tmp_path, ctxs):
    """update_on_kvstore=True states live in the store (code-review r2)."""
    net = _mlp(seed=17)
    _init_net(net, [ctxs[0]], seed=17)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01},
                            kvstore="device", update_on_kvstore=True)
    x = nd.array(np.random.randn(8, 8).astype("float32"))
    y = nd.array(np.random.randn(8, 4).astype("float32"))
    with autograd.record():
        l = ((net(x) - y) ** 2).sum()
    l.backward()
    trainer.step(8)
    fname = str(tmp_path / "states")
    trainer.save_states(fname)
    import pickle
    with open(fname, "rb") as f:
        states = pickle.loads(f.read())
    assert states, "saved optimizer state must not be empty"
    trainer.load_states(fname)
    # invalid combination raises
    with pytest.raises(mx.MXNetError):
        t2 = gluon.Trainer(net.collect_params(), "sgd", kvstore=None,
                           update_on_kvstore=True)
        t2._init_kvstore()


def test_allgather_eager(ctxs):
    from mxnet_tpu import parallel
    vals = [nd.array(np.full((2,), float(i), "float32"), ctx=c)
            for i, c in enumerate(ctxs[:4])]
    out = parallel.allgather(vals)
    expect = np.repeat(np.arange(4, dtype="float32"), 2)
    assert len(out) == 4
    for o in out:
        assert_almost_equal(o.asnumpy(), expect)


def test_allreduce_subset_of_mesh(ctxs):
    """code-review r2: allreduce over fewer devices than the current mesh
    must not crash nor clobber the global mesh."""
    from mxnet_tpu import parallel
    parallel.make_mesh()  # global 8-device mesh
    vals = [nd.array(np.full((2,), float(i + 1), "float32"), ctx=c)
            for i, c in enumerate(ctxs[:4])]
    out = parallel.allreduce(vals)
    for o in out:
        assert_almost_equal(o.asnumpy(), np.full((2,), 10.0, "float32"))
    assert parallel.current_mesh().size == N_DEV  # untouched
