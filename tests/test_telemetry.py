"""mx.telemetry + mx.profiler facade + mx.monitor hook coverage (ISSUE 1).

Covers: ledger accumulation via record_op, span nesting and the
Chrome-trace JSON schema (parses with json.load; events carry
name/ph/ts/dur), metrics exporter output, profiler state-machine fixes
(scope no-op, pause/stop trace lifecycle, dumps formats, aggregate_stats
off), Monitor install/uninstall symmetry on ops.registry, and the
end-to-end smoke test asserting the dispatch/kvstore/trainer wiring stays
alive.
"""

import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler, telemetry
from mxnet_tpu import kvstore as kvs
from mxnet_tpu.base import MXNetError


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Each test starts and ends with telemetry off and empty."""
    def reset():
        telemetry.disable()
        telemetry.clear()
        telemetry.REGISTRY.reset()
        telemetry.ledger.set_aggregate_stats(True)
        profiler._state["running"] = False
        profiler._state["xla_trace"] = False
        profiler._state["tel_owner"] = False
    reset()
    yield
    reset()


def _events():
    return telemetry.get_tracer().events()


# -- ledger ------------------------------------------------------------------

def test_ledger_accumulation_via_record_op():
    profiler.record_op("opA", 0.002)
    profiler.record_op("opA", 0.004)
    profiler.record_op("opB", 0.001)
    snap = telemetry.ledger.snapshot()
    cnt, tot, mn, mx_ = snap["opA"]
    assert cnt == 2
    assert tot == pytest.approx(0.006)
    assert mn == pytest.approx(0.002)
    assert mx_ == pytest.approx(0.004)
    table = profiler.dumps()
    first_cols = [ln.split()[0] for ln in table.splitlines()[2:]]
    assert first_cols == ["opA", "opB"]  # sorted by total time desc
    # reset=True drains the ledger
    profiler.dumps(reset=True)
    assert telemetry.ledger.snapshot() == {}


def test_set_config_aggregate_stats_off_skips_ledger():
    profiler.set_config(filename="unused.json", aggregate_stats=False)
    profiler.record_op("skipped", 1.0)
    assert telemetry.ledger.snapshot() == {}
    profiler.set_config(filename="unused.json", aggregate_stats=True)
    profiler.record_op("kept", 1.0)
    assert "kept" in telemetry.ledger.snapshot()


def test_dumps_formats():
    profiler.record_op("fmt_op", 0.001)
    table = profiler.dumps()
    assert "Name" in table and "fmt_op" in table
    data = json.loads(profiler.dumps(format="json"))
    assert data["fmt_op"]["calls"] == 1
    assert data["fmt_op"]["total_ms"] == pytest.approx(1.0)
    with pytest.raises(MXNetError):
        profiler.dumps(format="csv")


# -- span tracer -------------------------------------------------------------

def test_span_noop_when_disabled():
    with telemetry.span("invisible", "test") as sp:
        pass
    assert sp is telemetry.NULL_SPAN
    assert _events() == []


def test_span_nesting_and_chrome_schema(tmp_path):
    telemetry.enable()
    with telemetry.span("outer", "test", level=1):
        with telemetry.span("inner", "test"):
            pass
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.dump()
    with open(tmp_path / "trace.json") as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    spans = {e["name"]: e for e in events if e.get("ph") == "X"}
    assert {"outer", "inner"} <= set(spans)
    for ev in spans.values():
        assert {"name", "ph", "ts", "dur", "pid", "tid", "cat"} <= set(ev)
    outer, inner = spans["outer"], spans["inner"]
    # nesting: inner lies within outer on the timeline
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"level": 1}


def test_tracer_ring_buffer_drops_oldest():
    tr = telemetry.Tracer(capacity=4)
    for i in range(10):
        tr.add_event(f"e{i}", "test", 0, 1)
    evs = tr.events()
    assert len(evs) == 4
    assert evs[0]["name"] == "e6"
    assert tr.dropped == 6
    assert tr.chrome_trace()["otherData"]["droppedEvents"] == 6


def test_instant_events():
    telemetry.enable()
    telemetry.instant("mark", "test", k=2)
    (ev,) = _events()
    assert ev["ph"] == "i" and ev["args"] == {"k": 2}


# -- metrics -----------------------------------------------------------------

def test_counter_and_gauge():
    c = telemetry.counter("t_requests_total", "help text")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = telemetry.gauge("t_depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2
    # get-or-create returns the same object; kind conflicts raise
    assert telemetry.counter("t_requests_total") is c
    with pytest.raises(TypeError):
        telemetry.gauge("t_requests_total")


def test_histogram_buckets():
    h = telemetry.histogram("t_latency_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["buckets"] == {0.01: 1, 0.1: 2, 1.0: 3}
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(5.555)
    # get-or-create with the same bounds returns the same histogram;
    # conflicting bounds raise instead of being silently ignored
    assert telemetry.histogram("t_latency_seconds",
                               buckets=(0.01, 0.1, 1.0)) is h
    with pytest.raises(ValueError):
        telemetry.histogram("t_latency_seconds", buckets=(2.0,))


def test_labeled_metrics_series_and_escaping():
    """ISSUE 10 satellite: label support with exposition-format escaping.
    One name may carry several label combinations (each its own series,
    one HELP/TYPE header) and label values escape backslash/quote/newline."""
    a = telemetry.counter("t_phase_total", "per-phase", labels={"phase": "io"})
    b = telemetry.counter("t_phase_total", labels={"phase": "net"})
    assert a is not b
    assert telemetry.counter("t_phase_total", labels={"phase": "io"}) is a
    a.inc(2)
    b.inc(5)
    text = telemetry.to_prometheus()
    assert text.count("# TYPE t_phase_total counter") == 1
    assert 't_phase_total{phase="io"} 2' in text
    assert 't_phase_total{phase="net"} 5' in text
    # stable ordering: the io series renders before net every time
    assert text.index('phase="io"') < text.index('phase="net"')
    assert text == telemetry.to_prometheus()
    # escaping: backslash first, then quote, then newline
    evil = telemetry.counter("t_evil_total",
                             labels={"p": 'a"b\\c\nd'})
    evil.inc()
    assert 't_evil_total{p="a\\"b\\\\c\\nd"} 1' in telemetry.to_prometheus()
    # kind conflicts are caught across label sets of the same name
    with pytest.raises(TypeError):
        telemetry.gauge("t_phase_total", labels={"phase": "other"})
    # json keys carry the label suffix; unlabeled keys stay bare
    data = json.loads(telemetry.to_json())
    assert data['t_phase_total{phase="io"}']["value"] == 2
    assert data['t_phase_total{phase="io"}']["labels"] == {"phase": "io"}


def test_labeled_histogram_renders_le_with_labels():
    h = telemetry.histogram("t_lab_seconds", buckets=(0.5,),
                            labels={"phase": "x"})
    h.observe(0.1)
    text = telemetry.to_prometheus()
    assert 't_lab_seconds_bucket{phase="x",le="0.5"} 1' in text
    assert 't_lab_seconds_bucket{phase="x",le="+Inf"} 1' in text
    assert 't_lab_seconds_sum{phase="x"} 0.1' in text
    assert 't_lab_seconds_count{phase="x"} 1' in text


def test_histogram_inf_bound_normalized():
    """An explicit +Inf bound must not render a duplicate +Inf row: the
    implicit tail bucket is THE +Inf bucket, emitted exactly once."""
    h = telemetry.histogram("t_inf_seconds",
                            buckets=(0.1, float("inf"), 0.5, 0.5))
    assert h.buckets == (0.1, 0.5)   # dedup + inf dropped
    h.observe(9.0)
    text = telemetry.to_prometheus()
    assert text.count('t_inf_seconds_bucket{le="+Inf"}') == 1
    assert 't_inf_seconds_bucket{le="+Inf"} 1' in text
    with pytest.raises(ValueError):
        telemetry.histogram("t_only_inf", buckets=(float("inf"),))


def test_histogram_absorb_merges_raw_counts():
    h1 = telemetry.Histogram("m", buckets=(0.1, 1.0))
    h2 = telemetry.Histogram("m", buckets=(0.1, 1.0))
    h1.observe(0.05)
    h2.observe(0.5)
    h2.observe(5.0)
    h1._absorb(*h2._raw())
    snap = h1.snapshot()
    assert snap["count"] == 3
    assert snap["buckets"] == {0.1: 1, 1.0: 2}
    assert snap["sum"] == pytest.approx(5.55)
    # mismatched bounds: count/sum stay truthful via the +Inf tail
    h3 = telemetry.Histogram("m", buckets=(7.0,))
    h3.observe(1.0)
    h1._absorb(*h3._raw())
    snap = h1.snapshot()
    assert snap["count"] == 4 and snap["buckets"] == {0.1: 1, 1.0: 2}


def test_prometheus_and_json_export():
    telemetry.counter("t_ops_total", "ops").inc(7)
    telemetry.histogram("t_seconds", "lat", buckets=(0.5,)).observe(0.1)
    text = telemetry.to_prometheus()
    assert "# TYPE t_ops_total counter" in text
    assert "t_ops_total 7" in text
    assert "# TYPE t_seconds histogram" in text
    assert 't_seconds_bucket{le="0.5"} 1' in text
    assert 't_seconds_bucket{le="+Inf"} 1' in text
    assert "t_seconds_count 1" in text
    data = json.loads(telemetry.to_json())
    assert data["t_ops_total"]["value"] == 7
    assert data["t_seconds"]["type"] == "histogram"


# -- profiler state machine (satellite fixes) --------------------------------

def test_scope_cheap_noop_when_stopped():
    with profiler.scope("idle"):
        pass
    assert telemetry.ledger.snapshot() == {}
    assert _events() == []


def test_scope_records_without_trace_annotation(monkeypatch):
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: None, raising=False)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: None, raising=False)
    monkeypatch.delattr(jax.profiler, "TraceAnnotation", raising=False)
    profiler.start()
    with profiler.scope("annotated"):
        pass
    profiler.stop()
    assert "scope:annotated" in telemetry.ledger.snapshot()


def test_pause_then_stop_closes_xla_trace(monkeypatch):
    import jax
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append("start"), raising=False)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"), raising=False)
    profiler.start()
    assert telemetry.enabled()
    profiler.pause()
    assert not profiler.is_running()
    assert not telemetry.enabled()          # host recording suspended
    assert profiler._state["xla_trace"]     # device trace still open
    profiler.resume()
    assert profiler.is_running() and telemetry.enabled()
    profiler.pause()
    profiler.stop()                          # must close the device trace
    assert calls == ["start", "stop"]
    assert not profiler._state["xla_trace"]
    assert not telemetry.enabled()


def test_start_begins_fresh_trace_window(monkeypatch):
    """Back-to-back profile sessions must not leak spans across dump()s."""
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: None, raising=False)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: None, raising=False)
    profiler.start()
    with telemetry.span("workload_a", "test"):
        pass
    profiler.stop()
    profiler.start()
    assert _events() == []  # session A's spans dropped
    with telemetry.span("workload_b", "test"):
        pass
    profiler.stop()
    names = {e["name"] for e in _events()}
    assert "workload_b" in names and "workload_a" not in names


def test_profiler_does_not_steal_user_enabled_telemetry(monkeypatch):
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: None, raising=False)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: None, raising=False)
    telemetry.enable()
    profiler.start()
    profiler.stop()
    assert telemetry.enabled()  # user turned it on; stop() leaves it on


# -- monitor hook symmetry ---------------------------------------------------

def test_monitor_install_uninstall_symmetry():
    from mxnet_tpu.monitor import Monitor
    from mxnet_tpu.ops import registry as reg
    n0 = len(reg._monitor_hooks)
    mon = Monitor(interval=1)
    mon.install()
    mon.install()  # idempotent
    assert len(reg._monitor_hooks) == n0 + 1
    mon.uninstall()
    assert len(reg._monitor_hooks) == n0
    mon.uninstall()  # idempotent
    assert len(reg._monitor_hooks) == n0


def test_monitor_hook_overhead_metric():
    from mxnet_tpu.monitor import Monitor
    telemetry.enable()
    mon = Monitor(interval=1)
    mon.install()
    try:
        mon.tic()
        _ = mx.nd.ones((2, 2)) + 1
        assert mon.toc()  # stats collected through the dispatch hook
        assert telemetry.histogram("mxnet_monitor_hook_seconds").count >= 1
    finally:
        mon.uninstall()


# -- end-to-end wiring (CI smoke: keeps instrumentation from rotting) --------

def test_train_step_telemetry_smoke(tmp_path):
    assert mx.telemetry is telemetry  # lazy top-level name resolves
    telemetry.enable()
    net = gluon.nn.Dense(4, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1},
                            kvstore=kvs.create("local"))
    x = mx.nd.array(np.ones((2, 3), np.float32))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(2)

    out = tmp_path / "trace.json"
    profiler.set_config(filename=str(out))
    profiler.dump()
    with open(out) as f:
        trace = json.load(f)
    cats = {e.get("cat") for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert {"dispatch", "kvstore", "trainer"} <= cats
    names = {e["name"] for e in trace["traceEvents"]}
    # dense grads ride the fused bucket path (ISSUE 2); per-key
    # kvstore.push/pull spans only appear on the fallback paths.  With
    # the fused optimizer on (ISSUE 5, the default) the reduced buckets
    # stay FLAT (pushpull_flat); either fused span proves it
    assert {"trainer.step", "trainer.allreduce"} <= names
    assert {"kvstore.fused_pushpull", "kvstore.fused_pushpull_flat"} & names
    assert trace["otherData"]["opAggregates"]  # per-op ledger rides along

    text = telemetry.to_prometheus()
    assert "mxnet_op_dispatch_total" in text
    assert "mxnet_op_dispatch_seconds_bucket" in text
    assert telemetry.counter("mxnet_op_dispatch_total").value > 0
    assert telemetry.counter("mxnet_kvstore_fused_bytes_total").value > 0
    assert telemetry.counter("mxnet_trainer_steps_total").value == 1


def test_dataloader_telemetry():
    telemetry.enable()
    ds = gluon.data.ArrayDataset(mx.nd.array(np.arange(12).reshape(6, 2)))
    n = sum(1 for _ in gluon.data.DataLoader(ds, batch_size=3))
    assert n == 2
    assert telemetry.counter("mxnet_dataloader_batches_total").value == 2
    assert telemetry.histogram("mxnet_dataloader_batch_seconds").count == 2
    assert "data" in {e.get("cat") for e in _events()}


def test_checkpoint_telemetry(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from mxnet_tpu import checkpoint
    telemetry.enable()
    mgr = checkpoint.CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=1)
    mgr.save(0, extra={"w": mx.nd.ones((2, 2))})
    step, extra = mgr.restore()
    assert step == 0 and "w" in extra
    cats = {e.get("cat") for e in _events()}
    assert "checkpoint" in cats
    assert telemetry.histogram("mxnet_checkpoint_save_seconds").count >= 1
    assert telemetry.histogram("mxnet_checkpoint_restore_seconds").count >= 1


def test_disabled_dispatch_records_nothing():
    _ = mx.nd.ones((2, 2)) * 2
    assert telemetry.counter("mxnet_op_dispatch_total").value == 0
    assert _events() == []


# -- profiler facade paths the ISSUE-12 rewrites left thin -------------------

def test_nested_scope_ledger_and_spans(monkeypatch, tmp_path):
    """scope() nests: both levels land in the span buffer AND the per-op
    aggregate ledger, and the inner span lies within the outer one."""
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: None, raising=False)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: None, raising=False)
    profiler.set_config(filename=str(tmp_path / "p.json"))
    profiler.start()
    with profiler.scope("outer"):
        _ = (mx.nd.ones((4, 4)) * 2).asnumpy()
        with profiler.scope("inner"):
            _ = (mx.nd.ones((4, 4)) + 1).asnumpy()
    profiler.stop()
    snap = telemetry.ledger.snapshot()
    assert snap["scope:outer"][0] == 1
    assert snap["scope:inner"][0] == 1
    # a nested scope's time is contained in its parent's
    assert snap["scope:inner"][1] <= snap["scope:outer"][1]
    spans = {e["name"]: e for e in _events() if e.get("ph") == "X"}
    assert {"scope:outer", "scope:inner"} <= set(spans)
    o, i = spans["scope:outer"], spans["scope:inner"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1  # 1us rounding


def test_pause_resume_around_dump(monkeypatch, tmp_path):
    """pause() stops host recording but dump() still renders what was
    captured; resume() continues into the same session; stop() after a
    pause still closes the device trace exactly once."""
    import jax
    stops = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: None, raising=False)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stops.append(1), raising=False)
    profiler.set_config(filename=str(tmp_path / "p.json"))
    profiler.start()
    with profiler.scope("before_pause"):
        pass
    profiler.pause()
    assert not profiler.is_running()
    assert profiler._state["xla_trace"]          # device trace stays open
    with profiler.scope("while_paused"):         # cheap no-op: not recorded
        pass
    profiler.dump()                              # dump mid-pause works
    with open(tmp_path / "p.json") as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "scope:before_pause" in names
    assert "scope:while_paused" not in names
    assert "scope:before_pause" in trace["otherData"]["opAggregates"]
    profiler.resume()
    with profiler.scope("after_resume"):
        pass
    profiler.stop()
    assert stops == [1]                          # closed exactly once
    snap = telemetry.ledger.snapshot()
    assert "scope:after_resume" in snap
    assert "scope:while_paused" not in snap


def test_aggregate_stats_off_with_cost_ledger_armed():
    """aggregate_stats=False turns the per-op aggregate OFF without
    touching the ISSUE-12 cost ledger: an armed dispatch still records
    its executable while the profiler table stays empty."""
    from mxnet_tpu.telemetry import costmodel
    profiler.set_config(filename="unused.json", aggregate_stats=False)
    telemetry.enable()
    costmodel.LEDGER.clear()
    costmodel.arm()
    try:
        _ = (mx.nd.ones((8, 8)) @ mx.nd.ones((8, 8))).asnumpy()
        assert telemetry.ledger.snapshot() == {}         # aggregate off
        sites = {e["site"] for e in costmodel.LEDGER.entries()}
        assert any(s.startswith("op:") for s in sites)   # ledger on
        assert telemetry.counter("mxnet_op_dispatch_total").value >= 1
    finally:
        costmodel.disarm()
        costmodel.LEDGER.clear()
