"""Analytic performance observatory (ISSUE 12): the per-executable
cost/memory ledger, hardware-free MFU/roofline reports, the
fits-per-shape estimator, and the live HTTP plane.

Everything here runs with JAX_PLATFORMS=cpu on the virtual 8-device
platform — the whole point of the observatory is that XLA's cost model
needs no hardware attached.
"""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel, telemetry
from mxnet_tpu.telemetry import costmodel, httpd


@pytest.fixture
def armed():
    """Arm the ledger for one test, restoring the disarmed default and a
    clean ledger afterwards (the registry rearm hook re-clears op jit
    caches on both transitions)."""
    costmodel.LEDGER.clear()
    costmodel.arm()
    yield costmodel.LEDGER
    costmodel.disarm()
    costmodel.LEDGER.clear()


def _tiny_step(donate=False, mesh=None, rules=None, data_spec=None,
               seed=3):
    from mxnet_tpu.gluon.model_zoo.llama import llama_model
    mx.random.seed(seed)
    net = llama_model("llama_tiny", vocab_size=64)
    net.initialize(mx.initializer.Normal(0.05))

    def loss_fn(o, l):
        return mx.nd.softmax_cross_entropy(
            o.reshape((-1, o.shape[-1])), l.reshape((-1,))) / l.size

    step = parallel.TrainStep(
        net, loss_fn, mx.optimizer.Adam(learning_rate=1e-3),
        mesh=mesh, donate=donate, partition_rules=rules,
        data_spec=data_spec)
    r = np.random.RandomState(seed)
    toks = r.randint(0, 64, (8, 16)).astype("int32")
    labs = np.roll(toks, -1, 1).astype("int32")
    return net, step, toks, labs


# ---------------------------------------------------------------------------
# the wrapper + ledger
# ---------------------------------------------------------------------------

def test_wrap_jit_records_entries_and_calls(armed):
    import jax
    import jax.numpy as jnp
    w = costmodel.wrap_jit(jax.jit(lambda x: (x @ x).sum()), "t.site")
    x = jnp.ones((32, 32), jnp.float32)
    for _ in range(3):
        w(x)
    ents = armed.entries("t.site")
    assert len(ents) == 1
    e = ents[0]
    assert e["flops"] > 0 and e["bytes_accessed"] > 0
    # memory_analysis ran: args = exactly the one 32x32 f32 input
    assert e["arg_bytes"] == x.nbytes == 32 * 32 * 4
    assert e["peak_bytes"] >= e["arg_bytes"]
    assert armed.calls("t.site") == 3
    # a second shape = a second executable at the same site
    w(jnp.ones((16, 16), jnp.float32))
    assert len(armed.entries("t.site")) == 2


def test_wrap_jit_disarmed_records_nothing():
    import jax
    import jax.numpy as jnp
    costmodel.LEDGER.clear()
    assert not costmodel.armed()
    w = costmodel.wrap_jit(jax.jit(lambda x: x + 1), "t.off")
    np.testing.assert_allclose(np.asarray(w(jnp.ones(4))), 2.0)
    assert costmodel.LEDGER.entries("t.off") == []
    assert costmodel.LEDGER.calls("t.off") == 0


def test_late_arming_analyzes_existing_executable():
    """An executable built BEFORE arm() is recorded lazily on its next
    armed dispatch (the first-call cache probe)."""
    import jax
    import jax.numpy as jnp
    costmodel.LEDGER.clear()
    w = costmodel.wrap_jit(jax.jit(lambda x: x * 2), "t.late")
    x = jnp.ones((8, 8))
    w(x)                                    # compiled while disarmed
    assert costmodel.LEDGER.entries("t.late") == []
    costmodel.arm()
    try:
        w(x)
        ents = costmodel.LEDGER.entries("t.late")
        assert len(ents) == 1 and ents[0]["flops"] >= 0
    finally:
        costmodel.disarm()
        costmodel.LEDGER.clear()


def test_registry_dispatch_ledger(armed):
    """Armed, imperative op dispatch records per-op sites; the rearm hook
    rebuilt the jit cache so the wrapper is actually in the path."""
    a = nd.array(np.random.randn(16, 16).astype(np.float32))
    (a @ a).asnumpy()
    sites = {e["site"] for e in armed.entries()}
    assert any(s.startswith("op:") for s in sites), sites


def test_trainstep_entry_and_lane_summary(armed):
    _net, step, toks, labs = _tiny_step()
    for _ in range(2):
        step(nd.array(toks, dtype="int32"), nd.array(labs, dtype="int32"))
    ents = armed.entries("parallel.TrainStep")
    assert len(ents) == 1, [e["site"] for e in armed.entries()]
    e = ents[0]
    assert e["flops"] > 1e6 and e["bytes_accessed"] > 1e6
    assert e["temp_bytes"] > 0 and e["arg_bytes"] > 0
    assert e["compile_s"] > 0           # attributed from jax.monitoring
    lane = costmodel.lane_summary(step_seconds=0.01, dtype="float32")
    assert lane["flops"] == e["flops"]
    assert lane["verdict"] in ("compute-bound", "memory-bound")
    assert lane["analytic_mfu"] > 0
    assert lane["peak_hbm_bytes"] == e["peak_bytes"]
    assert lane["executables"] == 1
    # steady state: dispatches grew, executables did not
    assert armed.calls("parallel.TrainStep") == 2


def test_report_cost_renders_table(armed):
    _net, step, toks, labs = _tiny_step()
    step(nd.array(toks, dtype="int32"), nd.array(labs, dtype="int32"))
    out = telemetry.report(cost=True)
    assert "cost ledger" in out
    assert "parallel.TrainStep" in out
    assert "verdict" not in costmodel.report_text().splitlines()[0]
    # without cost the table stays out
    assert "cost ledger" not in telemetry.report()


def test_roofline_and_peak_knobs(monkeypatch):
    monkeypatch.setenv("MXNET_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("MXNET_PEAK_HBM_GBS", "100")   # 1e11 B/s
    r = costmodel.roofline(2e9, 1e9, seconds=0.01, dtype="bfloat16")
    assert r["peak_flops"] == 1e12
    assert r["peak_hbm_bytes_per_s"] == 1e11
    assert r["ridge_flops_per_byte"] == 10.0
    assert r["arithmetic_intensity"] == 2.0
    assert r["verdict"] == "memory-bound"
    assert r["roofline_mfu_bound"] == 0.2
    assert r["analytic_mfu"] == pytest.approx(2e9 / (0.01 * 1e12))
    # above the ridge: compute-bound
    assert costmodel.roofline(2e10, 1e9)["verdict"] == "compute-bound"


def test_telemetry_clear_clears_ledger(armed):
    import jax
    import jax.numpy as jnp
    w = costmodel.wrap_jit(jax.jit(lambda x: x + 1), "t.clear")
    w(jnp.ones(4))
    assert armed.entries("t.clear")
    telemetry.clear()
    assert armed.entries() == []
    assert armed.calls("t.clear") == 0


# ---------------------------------------------------------------------------
# fits-per-shape estimator vs memory_analysis (the auto-sharder contract)
# ---------------------------------------------------------------------------

def test_estimate_memory_matches_memory_analysis_2x2x2(armed):
    """ISSUE 12 acceptance: the analytic estimate lands within 10% of the
    compiled memory_analysis on the (2,2,2) llama lane, and the exact
    (params + optimizer state + batch) portion matches the executable's
    argument bytes to within the traced scalars."""
    from mxnet_tpu import sharding as shd
    mesh = parallel.DeviceMesh(shape=(2, 2, 2),
                               axis_names=("dp", "tp", "sp"))
    net, step, toks, labs = _tiny_step(
        donate=True, mesh=mesh, rules=shd.llama_rules(),
        data_spec=("dp", "sp"))
    step(nd.array(toks, dtype="int32"), nd.array(labs, dtype="int32"))
    e = [x for x in armed.entries("parallel.TrainStep")
         if not x.get("error")][-1]
    est = costmodel.estimate_memory(
        net, {"dp": 2, "tp": 2, "sp": 2}, "llama", batch=8, seq=16)
    rel = abs(est["total_bytes"] - e["peak_bytes"]) / e["peak_bytes"]
    assert rel <= 0.10, (est, e)
    args_est = (est["params_bytes"] + est["opt_state_bytes"]
                + est["batch_bytes"])
    # args are exact modulo the traced step scalars (key/t/lr_vec/rescale)
    assert abs(args_est - e["arg_bytes"]) < 4096, (args_est, e["arg_bytes"])


def test_estimate_memory_single_device(armed):
    """Replicated single-chip case: the first-order activation model is
    looser here (XLA fusion workspace and fp32 attention intermediates
    are invisible to it; measured ~15% under on this config) — documented
    bound 20%.  The 10% contract is pinned on the (2,2,2) lane above."""
    import jax
    mesh = parallel.DeviceMesh(shape=(1,), axis_names=("dp",),
                               devices=jax.devices()[:1])
    net, step, toks, labs = _tiny_step(donate=True, mesh=mesh)
    step(nd.array(toks, dtype="int32"), nd.array(labs, dtype="int32"))
    e = [x for x in armed.entries("parallel.TrainStep")
         if not x.get("error")][-1]
    est = costmodel.estimate_memory(net, {"dp": 1}, None, batch=8, seq=16)
    rel = abs(est["total_bytes"] - e["peak_bytes"]) / e["peak_bytes"]
    assert rel <= 0.20, (est, e)


def test_estimate_memory_shape_semantics():
    """Sharding arithmetic only — no compiles: tp halves column-parallel
    params, absent axes degrade to unsharded, dp/sp shard the tokens."""
    params = {
        "llama0_layer0_q_weight": (64, 64),
        "llama0_layer0_o_weight": (64, 64),
        "llama0_norm_weight": (64,),
        "llama0_tok_weight": (128, 64),
    }
    base = costmodel.estimate_memory(params, {"dp": 2}, "llama",
                                     batch=8, seq=16)
    tp = costmodel.estimate_memory(params, {"dp": 1, "tp": 2}, "llama",
                                   batch=8, seq=16)
    # q (tp, None), o (None, tp), tok (tp, None) halve; the 1-d norm
    # replicates => params shrink by exactly the three 2-d tables' halves
    halved = (64 * 64 + 64 * 64 + 128 * 64) * 4 // 2
    assert base["params_bytes"] - tp["params_bytes"] == halved
    assert tp["opt_state_bytes"] == 2 * tp["params_bytes"]
    # tokens shard over dp*sp only
    assert base["tokens_per_device"] == 8 * 16 // 2
    sp = costmodel.estimate_memory(params, {"dp": 2, "sp": 2}, "llama",
                                   batch=8, seq=16)
    assert sp["tokens_per_device"] == 8 * 16 // 4
    # an indivisible dim refuses to shard (resolve_spec degradation)
    odd = costmodel.estimate_memory({"a_q_weight": (63, 64)},
                                    {"tp": 2}, "llama", batch=1, seq=1)
    assert odd["params_bytes"] == 63 * 64 * 4
    with pytest.raises(ValueError):
        costmodel.estimate_memory(params, {"dp": 2}, "llama", batch=8,
                                  seq=16, optimizer="rmsprop")


# ---------------------------------------------------------------------------
# the live HTTP plane
# ---------------------------------------------------------------------------

@pytest.fixture
def server():
    port = httpd.start(port=0, host="127.0.0.1")
    yield port
    httpd.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def test_httpd_metrics_identical_under_concurrent_scrape(server):
    telemetry.counter("mxnet_test_httpd_total", "t").inc(7)
    want = telemetry.to_prometheus()
    results, errors = [], []

    def scrape():
        try:
            results.append(_get(server, "/metrics"))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=scrape) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert len(results) == 8
    for status, ctype, body in results:
        assert status == 200
        assert ctype.startswith("text/plain")
        assert body == want             # exposition identical to registry
    assert "mxnet_test_httpd_total 7" in want


def test_httpd_statusz_and_ledger(server, armed):
    import jax
    import jax.numpy as jnp
    costmodel.wrap_jit(jax.jit(lambda x: x + 1), "t.http")(jnp.ones(4))
    status, ctype, body = _get(server, "/statusz")
    assert status == 200 and ctype == "application/json"
    s = json.loads(body)
    assert s["pid"] == os.getpid()
    assert s["costmodel_armed"] is True
    assert "MXNET_TELEMETRY_PORT" in s["knobs"]
    assert s["stepclock"]["verdict"] in (
        "idle", "input-bound", "comms-bound", "compute-bound")
    status, _ctype, body = _get(server, "/ledger.json")
    led = json.loads(body)
    assert any(e["site"] == "t.http"
               for e in led["costmodel"]["entries"])
    assert "t.http" in led["costmodel_sites"]
    status, _c, body = _get(server, "/")
    assert "/metrics" in body


def test_httpd_healthz_probe(server, monkeypatch, tmp_path):
    """ISSUE 13 satellite: /healthz is the router's liveness probe —
    200 with no heartbeat armed (the reply itself proves liveness), 200
    + {phase, heartbeat_age_s} while the armed beater is fresh, 503 once
    it goes stale past MXNET_ROUTER_HANG_S."""
    from mxnet_tpu.resilience import heartbeat as hb
    status, ctype, body = _get(server, "/healthz")
    rec = json.loads(body)
    assert status == 200 and ctype == "application/json"
    assert rec["ok"] and not rec["armed"]
    monkeypatch.setenv("MXNET_ELASTIC_HEARTBEAT_DIR", str(tmp_path))
    try:
        # long interval: exactly one beat lands, then we age it by hand
        assert hb.start(interval_s=600)
        status, _c, body = _get(server, "/healthz")
        rec = json.loads(body)
        assert status == 200 and rec["ok"] and rec["armed"]
        assert rec["phase"] == "spawned"
        assert rec["heartbeat_age_s"] < 30
        import time as _time
        monkeypatch.setattr(hb, "_last_beat",
                            _time.monotonic() - 10_000)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(server, "/healthz")
        assert ei.value.code == 503
        stale = json.loads(ei.value.read())
        assert not stale["ok"] and stale["heartbeat_age_s"] > 100
    finally:
        hb.stop()


def test_httpd_404_and_stop():
    port = httpd.start(port=0, host="127.0.0.1")
    assert httpd.running() and httpd.port() == port
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/nope")
    assert ei.value.code == 404
    httpd.stop()
    assert not httpd.running() and httpd.port() is None
    # idempotent
    httpd.stop()


# ---------------------------------------------------------------------------
# export plane: shard snapshot + offline report CLI
# ---------------------------------------------------------------------------

def test_snapshot_carries_costmodel_and_cli_reports_it(armed, tmp_path):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.telemetry import aggregate
    costmodel.wrap_jit(jax.jit(lambda x: (x @ x)), "t.cli")(
        jnp.ones((8, 8)))
    snap = aggregate.snapshot()
    assert any(e["site"] == "t.cli" for e in snap["costmodel"]["entries"])
    path = aggregate.export_snapshot(directory=str(tmp_path))
    assert path is not None

    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "telemetry_report.py"),
         "--dir", str(tmp_path), "--cost", "--json"],
        capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    cost = rep["ranks"][0]["cost"]
    assert "t.cli" in cost
    assert cost["t.cli"]["executables"] == 1
    assert cost["t.cli"]["verdict"] in ("compute-bound", "memory-bound")
