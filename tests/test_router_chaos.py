"""Router-tier chaos e2e (ISSUE 13 acceptance; the router-chaos CI lane).

Two REAL llama replicas under load, with BOTH control-plane deaths the
tier is designed around induced in one run:

- replica 0 is chaos-armed ``serving.reply:exit:1`` — it dies after
  computing its first result but BEFORE acking it (the dedup-on-retry
  window), which also strands its other in-flight requests mid-decode;
- the router itself is chaos-killed at ``router.dispatch`` (exit after 3
  dispatches) — requests journaled, some unsent, replicas mid-compute.

A second driver run (``--resume``) re-adopts the live replica through
its port file, respawns the corpse (which dies AGAIN on its first reply
— the respawn budget then retires it), re-dispatches the journal, and
submits what run 1 shed.  The test asserts the acceptance criteria:

- every accepted request completes with output TOKEN-IDENTICAL to a
  single uninterrupted engine (the in-process oracle below);
- shed requests failed fast with RouterOverloaded (progress.log carries
  sub-second shed timestamps from run 1) — they never hang;
- the merged Chrome trace covers router + both replica lanes with the
  retry/reply spans linked per rid, and the flight recorder holds the
  postmortems of every induced death.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import llama
from mxnet_tpu.telemetry import aggregate

HERE = os.path.dirname(os.path.abspath(__file__))
SEED, VOCAB, MAX_NEW = 7, 101, 6

REPLICA_CMD = [sys.executable, "-m", "mxnet_tpu.serving.replica",
               "--model", "llama_tiny", "--vocab", str(VOCAB),
               "--seed", str(SEED), "--eos", "-1",
               "--max-batch", "4", "--block-tokens", "4",
               "--max-seq", "64", "--prefill-tokens", "16"]


def _oracle_net():
    mx.random.seed(SEED)
    np.random.seed(SEED)
    net = llama.llama_model("llama_tiny", vocab_size=VOCAB)
    net.initialize(mx.initializer.Normal(0.05))
    net(mx.nd.array(np.zeros((1, 4), np.int32)))
    return net


def _ref_greedy(net, prompt, max_new, pad_to=32):
    buf = np.zeros((1, pad_to), np.int32)
    buf[0, :len(prompt)] = prompt
    n, out = len(prompt), []
    for _ in range(max_new):
        logits = net(mx.nd.array(buf)).asnumpy()
        nxt = int(logits[0, n - 1].argmax())
        out.append(nxt)
        buf[0, n] = nxt
        n += 1
    return out


@pytest.mark.slow
def test_router_chaos_e2e(tmp_path):
    r = np.random.RandomState(5)
    reqs = [{"tag": f"t{i}",
             "prompt": [int(t) for t in
                        r.randint(3, VOCAB, r.randint(3, 9))],
             "max_new_tokens": MAX_NEW}
            for i in range(8)]
    net = _oracle_net()
    oracle = {rec["tag"]: _ref_greedy(net, rec["prompt"], MAX_NEW)
              for rec in reqs}

    req_file = tmp_path / "reqs.json"
    req_file.write_text(json.dumps(reqs))
    out_file = tmp_path / "out.json"
    base = [sys.executable, os.path.join(HERE, "_router_driver.py"),
            "--workdir", str(tmp_path), "-n", "2",
            "--requests", str(req_file), "--out", str(out_file),
            "--replica-cmd", json.dumps(REPLICA_CMD),
            "--replica-env", json.dumps(
                {"0": {"MXNET_CHAOS": "1",
                       "MXNET_CHAOS_SITES": "serving.reply:exit:1"}}),
            "--max-respawns", "1", "--result-timeout", "200"]

    # run 1: 5 accepted (3 shed fast), router chaos-killed on dispatch 4
    p1 = subprocess.run(base + ["--queue-max", "5",
                                "--dispatch-exit-after", "3",
                                "--keep-replicas"],
                        timeout=300, capture_output=True)
    assert p1.returncode != 0, p1.stdout
    assert not out_file.exists()
    progress = (tmp_path / "progress.log").read_text().splitlines()
    sheds = [ln.split() for ln in progress if ln.startswith("shed ")]
    assert len(sheds) == 3, progress
    assert all(float(s[2]) < 2.0 for s in sheds), \
        f"shed must fail fast, not hang: {sheds}"
    st = json.loads((tmp_path / "router.json").read_text())
    assert st["phase"] == "running" and len(st["requests"]) == 5

    # run 2: re-adopt, respawn, retry, finish everything
    p2 = subprocess.run(base + ["--queue-max", "32", "--resume"],
                        timeout=420, capture_output=True)
    assert p2.returncode == 0, (p2.stdout, p2.stderr)
    out = json.loads(out_file.read_text())

    # every accepted request: token-identical to the uninterrupted engine
    for rec in reqs:
        got = out["results"][rec["tag"]]
        assert got.get("tokens") == oracle[rec["tag"]], \
            (rec["tag"], got, oracle[rec["tag"]])
    assert out["counters"]["mxnet_router_retries_total"] >= 1
    assert out["counters"]["mxnet_router_replica_deaths_total"] >= 1
    assert out["counters"]["mxnet_router_respawns_total"] >= 1

    # merged cross-process trace: router + both replica lanes, with the
    # request/retry/reply spans linked per rid
    snaps = aggregate.load_snapshots(str(tmp_path / "telemetry"))
    ranks = {s.get("rank") for s in snaps}
    assert {0, 1, 2} <= ranks, ranks      # replicas 0/1 + router (=2)
    trace = aggregate.merged_chrome_trace(snaps)
    evs = [e for e in trace["traceEvents"]
           if e.get("cat") == "router.request"]
    begins = {e["id"] for e in evs if e.get("ph") == "b"}
    retries = {e["id"] for e in evs if e.get("name") == "retry"}
    replies = {e["id"] for e in evs if e.get("name") == "replica_reply"}
    assert retries and retries <= begins | retries
    assert replies & begins, "replica reply markers must link router rids"
    assert len({e.get("pid") for e in evs}) >= 2, \
        "router.request spans must span router AND replica lanes"

    # flight recorder: postmortems for the induced deaths (router chaos
    # exit + replica serving.reply exits)
    dumps = [fn for fn in os.listdir(tmp_path / "flightrec")
             if fn.startswith("flightrec-") and fn.endswith(".json")]
    assert len(dumps) >= 2, dumps
    reasons = set()
    for fn in dumps:
        with open(tmp_path / "flightrec" / fn) as f:
            reasons.add(json.load(f).get("reason"))
    assert any("router.dispatch" in (r or "") for r in reasons), reasons
    assert any("serving.reply" in (r or "") for r in reasons), reasons
