"""Module API tests (reference tests/python/unittest/test_module.py).
Covers VERDICT r1 item 4: fit/score/predict through simple_bind."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp_sym():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.relu(net)
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy_data(n=200, d=10, k=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype("float32")
    W = rng.randn(d, k).astype("float32")
    y = (X @ W).argmax(axis=1).astype("float32")
    return X, y


def test_module_fit_score_predict():
    X, y = _toy_data()
    it = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=6, optimizer="adam",
            optimizer_params=(("learning_rate", 0.01),))
    acc = mod.score(it, "acc")[0][1]
    assert acc > 0.8, f"Module.fit failed to learn (acc={acc})"
    preds = mod.predict(it)
    assert preds[0].shape == (200, 4)


def test_module_forward_backward_update():
    X, y = _toy_data(n=40)
    it = mx.io.NDArrayIter(X, y, batch_size=20, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))
    batch = next(iter(it))
    w_before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
    mod.forward_backward(batch)
    mod.update()
    w_after = mod._exec.arg_dict["fc1_weight"].asnumpy()
    assert not np.allclose(w_before, w_after)
    assert mod.get_outputs()[0].shape == (20, 4)


def test_module_checkpoint_roundtrip(tmp_path):
    X, y = _toy_data(n=40)
    it = mx.io.NDArrayIter(X, y, batch_size=20, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=1, optimizer="sgd")
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 1)

    sym, arg, aux = mx.model.load_checkpoint(prefix, 1)
    assert set(arg) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    mod2 = mx.mod.Module(sym, data_names=("data",),
                         label_names=("softmax_label",))
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
              for_training=False)
    mod2.init_params(arg_params=arg, aux_params=aux)
    it.reset()
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    o1 = mod.get_outputs()[0].asnumpy()
    mod2.forward(batch, is_train=False)
    o2 = mod2.get_outputs()[0].asnumpy()
    assert_almost_equal(o1, o2, rtol=1e-5)


def test_module_input_grads():
    X, y = _toy_data(n=20)
    it = mx.io.NDArrayIter(X, y, batch_size=20, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             inputs_need_grad=True)
    mod.init_params()
    batch = next(iter(it))
    mod.forward_backward(batch)
    g = mod.get_input_grads()[0]
    assert g is not None and g.shape == (20, 10)


def test_module_multi_ctx_matches_single(seeded):
    # VERDICT r2 weak #5: context=[list] must data-parallelize, and the
    # numerics must match the single-ctx run exactly (grad sum == full-batch
    # grad for a sliced batch with the same params)
    from mxnet_tpu import parallel
    ctxs = parallel.data_parallel_ctxs(2)
    if len(ctxs) < 2:
        pytest.skip("needs 2 devices")
    X, y = _toy_data(n=80)
    def run(ctx):
        mx.random.seed(1234)  # identical init draws across the two runs
        it = mx.io.NDArrayIter(X, y, batch_size=20,
                               label_name="softmax_label")
        mod = mx.mod.Module(_mlp_sym(), data_names=("data",),
                            label_names=("softmax_label",), context=ctx)
        mod.fit(it, num_epoch=3, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.05),
                                  ("rescale_grad", 1.0 / 20)),
                initializer=mx.initializer.Uniform(0.1))
        arg, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in arg.items()}, mod

    single, _ = run(ctxs[0])
    multi, mod = run(ctxs)
    assert len(mod._execs) == 2
    for k in single:
        assert_almost_equal(single[k], multi[k], rtol=1e-4, atol=1e-5)
    # merged outputs span the whole batch
    it = mx.io.NDArrayIter(X, y, batch_size=20, label_name="softmax_label")
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    outs = mod.get_outputs()
    assert outs[0].shape[0] == 20


def test_module_multi_ctx_requires_divisible_batch():
    from mxnet_tpu import parallel
    ctxs = parallel.data_parallel_ctxs(2)
    if len(ctxs) < 2:
        pytest.skip("needs 2 devices")
    mod = mx.mod.Module(_mlp_sym(), data_names=("data",),
                        label_names=("softmax_label",), context=ctxs)
    with pytest.raises(mx.base.MXNetError, match="divide"):
        mod.bind(data_shapes=[("data", (21, 10))],
                 label_shapes=[("softmax_label", (21,))])


def test_module_multi_ctx_merges_bn_aux(seeded):
    # BN running stats must reflect BOTH batch slices (averaged across
    # executors), not just slice 0's
    from mxnet_tpu import parallel
    ctxs = parallel.data_parallel_ctxs(2)
    if len(ctxs) < 2:
        pytest.skip("needs 2 devices")
    data = mx.sym.var("data")
    net = mx.sym.BatchNorm(data, name="bn", fix_gamma=False)
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",), context=ctxs)
    mod.bind(data_shapes=[("data", (8, 3))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.initializer.Uniform(0.1))
    mod.init_optimizer(optimizer="sgd")
    # slice 0 gets zeros, slice 1 gets large values: stats must see both
    X = np.concatenate([np.zeros((4, 3), np.float32),
                        np.full((4, 3), 10.0, np.float32)])
    batch = mx.io.DataBatch(data=[mx.nd.array(X)],
                            label=[mx.nd.zeros((8,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    aux = {n: mod._exec.aux_dict[n].asnumpy() for n in mod._aux_names}
    mean_name = next(n for n in aux if "mean" in n)
    # slice-0-only stats would be ~0; merged stats reflect the 10.0 slice
    assert aux[mean_name].mean() > 0.1, aux[mean_name]
    # every executor carries the SAME merged aux after update
    for e in mod._execs[1:]:
        np.testing.assert_allclose(e.aux_dict[mean_name].asnumpy(),
                                   aux[mean_name], rtol=1e-6)
