"""gluon.rnn tests — parity between fused layers and explicit cell math
(reference tests/python/unittest/test_gluon_rnn.py patterns)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import rnn


def _np_lstm_ref(x_seq, h0, c0, wi, wh, bi, bh):
    """Numpy LSTM over time; gate order [i, f, g, o] (reference rnn-inl.h)."""
    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))
    H = h0.shape[-1]
    h, c = h0, c0
    outs = []
    for t in range(x_seq.shape[0]):
        g = x_seq[t] @ wi.T + bi + h @ wh.T + bh
        i = sig(g[:, :H])
        f = sig(g[:, H:2 * H])
        gg = np.tanh(g[:, 2 * H:3 * H])
        o = sig(g[:, 3 * H:])
        c = f * c + i * gg
        h = o * np.tanh(c)
        outs.append(h)
    return np.stack(outs), h, c


def _np_gru_ref(x_seq, h0, wi, wh, bi, bh):
    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))
    H = h0.shape[-1]
    h = h0
    outs = []
    for t in range(x_seq.shape[0]):
        xw = x_seq[t] @ wi.T + bi
        hw = h @ wh.T + bh
        r = sig(xw[:, :H] + hw[:, :H])
        z = sig(xw[:, H:2 * H] + hw[:, H:2 * H])
        n = np.tanh(xw[:, 2 * H:] + r * hw[:, 2 * H:])
        h = (1 - z) * n + z * h
        outs.append(h)
    return np.stack(outs), h


@pytest.mark.parametrize("mode", ["lstm", "gru"])
def test_fused_layer_matches_numpy(mode, seeded):
    T, N, I, H = 5, 3, 4, 6
    r = np.random.RandomState(7)
    x = r.randn(T, N, I).astype(np.float32)
    layer = (rnn.LSTM if mode == "lstm" else rnn.GRU)(H, input_size=I)
    layer.initialize(mx.initializer.Uniform(0.5))
    out, states = layer(mx.nd.array(x), layer.begin_state(N))
    p = {k.split("_", 1)[1]: v.data().asnumpy()
         for k, v in layer.collect_params().items()}
    wi, wh = p["l0_i2h_weight"], p["l0_h2h_weight"]
    bi, bh = p["l0_i2h_bias"], p["l0_h2h_bias"]
    h0 = np.zeros((N, H), np.float32)
    if mode == "lstm":
        ref, hT, cT = _np_lstm_ref(x, h0, h0.copy(), wi, wh, bi, bh)
        np.testing.assert_allclose(states[1].asnumpy()[0], cT, rtol=2e-5,
                                   atol=2e-5)
    else:
        ref, hT = _np_gru_ref(x, h0, wi, wh, bi, bh)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(states[0].asnumpy()[0], hT, rtol=2e-5,
                               atol=2e-5)


@pytest.mark.parametrize("cls,mode", [(rnn.LSTMCell, "lstm"),
                                      (rnn.GRUCell, "gru"),
                                      (rnn.RNNCell, "rnn")])
def test_cell_unroll_matches_fused_layer(cls, mode, seeded):
    T, N, I, H = 4, 2, 3, 5
    r = np.random.RandomState(3)
    x = r.randn(N, T, I).astype(np.float32)
    cell = cls(H, input_size=I)
    cell.initialize(mx.initializer.Uniform(0.5))
    outs, _ = cell.unroll(T, mx.nd.array(x), layout="NTC")

    layer_cls = {"lstm": rnn.LSTM, "gru": rnn.GRU}.get(mode)
    if layer_cls is None:
        layer = rnn.RNN(H, activation="tanh", input_size=I, layout="NTC")
    else:
        layer = layer_cls(H, input_size=I, layout="NTC")
    layer.initialize()
    layer(mx.nd.array(x))  # materialize params
    # copy cell params into the fused layer
    cp = cell.collect_params()
    lp = layer.collect_params()
    for short in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"):
        src = next(v for k, v in cp.items() if k.endswith(short))
        dst = next(v for k, v in lp.items() if k.endswith(f"l0_{short}"))
        dst.set_data(src.data())
    fused = layer(mx.nd.array(x))
    np.testing.assert_allclose(outs.asnumpy(), fused.asnumpy(), rtol=2e-5,
                               atol=2e-5)


def test_rnn_layer_hybridize_parity(seeded):
    T, N, I, H = 6, 4, 5, 7
    x = mx.nd.array(np.random.RandomState(0).randn(T, N, I)
                    .astype(np.float32))
    layer = rnn.LSTM(H, num_layers=2, input_size=I)
    layer.initialize(mx.initializer.Xavier())
    imp = layer(x).asnumpy()
    layer.hybridize()
    hyb = layer(x).asnumpy()
    np.testing.assert_allclose(imp, hyb, rtol=1e-5, atol=1e-5)


def test_rnn_layer_grad_flows(seeded):
    layer = rnn.GRU(4, num_layers=1, input_size=3)
    layer.initialize()
    x = mx.nd.array(np.random.RandomState(1).randn(5, 2, 3)
                    .astype(np.float32))
    with autograd.record():
        out = layer(x)
        loss = (out ** 2).sum()
    loss.backward()
    for name, p in layer.collect_params().items():
        g = p.list_grad()[0].asnumpy()
        assert np.isfinite(g).all(), name
        assert np.abs(g).sum() > 0, f"zero grad for {name}"


def test_bidirectional_layer_shapes():
    layer = rnn.LSTM(8, num_layers=2, bidirectional=True, input_size=5)
    layer.initialize()
    x = mx.nd.ones((7, 3, 5))
    out, states = layer(x, layer.begin_state(3))
    assert out.shape == (7, 3, 16)
    assert states[0].shape == (4, 3, 8)  # layers*dirs
    assert states[1].shape == (4, 3, 8)


def test_bidirectional_cell_unroll(seeded):
    l_cell = rnn.LSTMCell(4, input_size=3)
    r_cell = rnn.LSTMCell(4, input_size=3)
    bi = rnn.BidirectionalCell(l_cell, r_cell)
    bi.initialize()
    x = mx.nd.ones((2, 5, 3))
    outs, states = bi.unroll(5, x, layout="NTC")
    assert outs.shape == (2, 5, 8)
    assert len(states) == 4


def test_bidirectional_unroll_valid_length(seeded):
    # reverse direction must see each sample's VALID portion front-aligned:
    # a short sample unrolled alone must match its slice of the batch
    I, H = 3, 4
    l_cell = rnn.LSTMCell(H, input_size=I)
    r_cell = rnn.LSTMCell(H, input_size=I)
    bi = rnn.BidirectionalCell(l_cell, r_cell)
    bi.initialize(mx.initializer.Uniform(0.4))
    r = np.random.RandomState(2)
    x = r.randn(2, 4, I).astype(np.float32)
    vl = mx.nd.array(np.array([2, 4], np.float32))
    outs, _ = bi.unroll(4, mx.nd.array(x), layout="NTC", valid_length=vl,
                        merge_outputs=True)
    solo, _ = bi.unroll(2, mx.nd.array(x[:1, :2]), layout="NTC",
                        merge_outputs=True)
    np.testing.assert_allclose(outs.asnumpy()[0, :2], solo.asnumpy()[0],
                               rtol=2e-5, atol=2e-5)
    assert np.allclose(outs.asnumpy()[0, 2:], 0.0)  # masked tail


def test_sequential_cell_stack(seeded):
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(6, input_size=4))
    stack.add(rnn.DropoutCell(0.0))
    stack.add(rnn.GRUCell(3, input_size=6))
    stack.initialize()
    x = mx.nd.ones((2, 4))
    states = stack.begin_state(2)
    assert len(states) == 3  # 2 (lstm) + 0 (dropout) + 1 (gru)
    out, new_states = stack(x, states)
    assert out.shape == (2, 3)
    assert len(new_states) == 3
    outs, _ = stack.unroll(4, mx.nd.ones((2, 4, 4)), layout="NTC")
    assert outs.shape == (2, 4, 3)


def test_residual_and_zoneout_cells(seeded):
    base = rnn.GRUCell(4, input_size=4)
    res = rnn.ResidualCell(base)
    res.initialize()
    x = mx.nd.ones((2, 4))
    st = res.begin_state(2)
    out, _ = res(x, st)
    inner, _ = base(x, st)
    np.testing.assert_allclose(out.asnumpy(),
                               (inner + x).asnumpy(), rtol=1e-6)

    z = rnn.ZoneoutCell(rnn.LSTMCell(4, input_size=4), 0.5, 0.5)
    z.initialize()
    out, states = z(mx.nd.ones((2, 4)), z.begin_state(2))
    assert out.shape == (2, 4)  # inference: no zoneout applied
    with autograd.record():
        out2, _ = z(mx.nd.ones((2, 4)), z.begin_state(2))
    assert out2.shape == (2, 4)


def test_unroll_valid_length(seeded):
    cell = rnn.RNNCell(3, input_size=2)
    cell.initialize()
    x = mx.nd.ones((2, 4, 2))
    vl = mx.nd.array(np.array([2, 4], np.float32))
    outs, states = cell.unroll(4, x, layout="NTC", valid_length=vl,
                               merge_outputs=True)
    o = outs.asnumpy()
    assert np.allclose(o[0, 2:], 0.0)  # masked beyond valid length
    assert not np.allclose(o[1, 3], 0.0)
    # states are from the last valid step
    full, all_st = cell.unroll(2, x[:, :2], layout="NTC")
    np.testing.assert_allclose(states[0].asnumpy()[0],
                               all_st[0].asnumpy()[0], rtol=1e-5)


def test_unfuse_matches_layer(seeded):
    layer = rnn.LSTM(5, num_layers=2, input_size=4)
    layer.initialize(mx.initializer.Uniform(0.3))
    x = mx.nd.array(np.random.RandomState(5).randn(6, 2, 4)
                    .astype(np.float32))
    fused = layer(x)
    stack = layer._unfuse()
    stack.initialize()
    # copy weights layer by layer
    lp = layer.collect_params()
    sp = stack.collect_params()
    for k, dst in sp.items():
        tail = "_".join(k.rsplit("_")[-3:])  # e.g. l0_i2h_weight ... match by suffix
        src = next(v for kk, v in lp.items() if kk.endswith(tail))
        if dst.shape != src.shape:
            dst.shape_mismatch_update(src.shape)
        dst.set_data(src.data())
    outs, _ = stack.unroll(6, x, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(fused.asnumpy(), outs.asnumpy(), rtol=2e-5,
                               atol=2e-5)


def test_rnn_layer_in_training_loop(seeded):
    layer = rnn.LSTM(16, input_size=8, layout="NTC")
    dense = gluon.nn.Dense(2)
    layer.initialize()
    dense.initialize()
    params = gluon.ParameterDict()
    params.update(layer.collect_params())
    params.update(dense.collect_params())
    tr = gluon.Trainer(params, "adam", {"learning_rate": 1e-2})
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    r = np.random.RandomState(0)
    x = mx.nd.array(r.randn(8, 5, 8).astype(np.float32))
    y = mx.nd.array(r.randint(0, 2, (8,)))
    losses = []
    for _ in range(5):
        with autograd.record():
            h = layer(x)
            loss = lossf(dense(h[:, -1]), y)
        loss.backward()
        tr.step(8)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0]
