"""Scratch: measure raw chip peak + pure-jax BERT step vs framework bench."""
import time, functools
import numpy as np
import jax, jax.numpy as jnp

print("devices:", jax.devices())

# 1. raw matmul peak (bf16)
N = 4096
a = jnp.ones((N, N), jnp.bfloat16)
b = jnp.ones((N, N), jnp.bfloat16)

@jax.jit
def mm(a, b):
    for _ in range(8):
        a = (a @ b) * 0.001
    return a

mm(a, b).block_until_ready()
t0 = time.perf_counter()
r = mm(a, b)
r.block_until_ready()
dt = time.perf_counter() - t0
flops = 8 * 2 * N**3
print(f"matmul: {flops/dt/1e12:.1f} TFLOP/s")

# 2. pure-jax BERT-base train step (dense attention, bf16, adam fp32 master)
L_layers, C, H, A = 12, 768, 3072, 12
V, B, S = 30522, 128, 128
rng = np.random.RandomState(0)

def mk(shape, dtype=jnp.bfloat16):
    return jnp.asarray(rng.normal(0, 0.02, shape), dtype)

params = {"emb": mk((V, C)), "pos": mk((S, C)), "dec": mk((C, V))}
for i in range(L_layers):
    params[f"l{i}"] = {
        "qkv": mk((C, 3 * C)), "proj": mk((C, C)),
        "f1": mk((C, H)), "f2": mk((H, C)),
        "ln1s": jnp.ones(C, jnp.bfloat16), "ln1b": jnp.zeros(C, jnp.bfloat16),
        "ln2s": jnp.ones(C, jnp.bfloat16), "ln2b": jnp.zeros(C, jnp.bfloat16),
    }

def ln(x, s, b):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + 1e-5) * s + b

def fwd(p, tokens, labels):
    x = p["emb"][tokens] + p["pos"][None]
    for i in range(L_layers):
        lp = p[f"l{i}"]
        qkv = x @ lp["qkv"]
        q, k, v = jnp.split(qkv.reshape(B, S, A, 3 * C // A // 3 * 3 // 3 * 1 * 3).reshape(B, S, A, -1), 3, -1) if False else (None, None, None)
        qkv = qkv.reshape(B, S, 3, A, C // A).transpose(2, 0, 3, 1, 4)  # 3,B,A,S,D
        q, k, v = qkv[0], qkv[1], qkv[2]
        att = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(C // A)
        att = jax.nn.softmax(att.astype(jnp.float32), -1).astype(jnp.bfloat16)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", att, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, C)
        x = ln(x + ctx @ lp["proj"], lp["ln1s"], lp["ln1b"])
        h = jax.nn.gelu(x @ lp["f1"]) @ lp["f2"]
        x = ln(x + h, lp["ln2s"], lp["ln2b"])
    logits = (x @ p["dec"]).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, -1)
    ll = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
    return (lse - ll).mean()

adam_m = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), params)
adam_v = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), params)
master = jax.tree.map(lambda x: x.astype(jnp.float32), params)

@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def step(p, mw, m, v, tokens, labels):
    loss, g = jax.value_and_grad(fwd)(p, tokens, labels)
    m = jax.tree.map(lambda a, b: 0.9 * a + 0.1 * b.astype(jnp.float32), m, g)
    v = jax.tree.map(lambda a, b: 0.999 * a + 0.001 * (b.astype(jnp.float32) ** 2), v, g)
    mw = jax.tree.map(lambda w, mm_, vv: w - 1e-4 * mm_ / (jnp.sqrt(vv) + 1e-8), mw, m, v)
    p = jax.tree.map(lambda w: w.astype(jnp.bfloat16), mw)
    return p, mw, m, v, loss

tokens = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)
labels = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)

params, master, adam_m, adam_v, loss = step(params, master, adam_m, adam_v, tokens, labels)
jax.block_until_ready(loss)
STEPS = 16
t0 = time.perf_counter()
for _ in range(STEPS):
    params, master, adam_m, adam_v, loss = step(params, master, adam_m, adam_v, tokens, labels)
jax.block_until_ready(loss)
dt = (time.perf_counter() - t0) / STEPS
sps = B / dt
n_matmul = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params)) - V * C - S * C
fpt = 6 * n_matmul + 12 * L_layers * C * S
mfu = sps * S * fpt / 394e12
print(f"pure-jax BERT step: {dt*1000:.1f} ms, {sps:.0f} samples/s, mfu={mfu:.3f} (loss {loss:.3f})")
