import numpy as np, time
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn

t0=time.time()
def log(*a): print(f"[{time.time()-t0:6.1f}s]", *a, flush=True)
ctx = mx.tpu()
log("device:", ctx.jax_device())
mx.random.seed(0); np.random.seed(0)
with ctx:
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(16, 3, activation='relu'), nn.MaxPool2D(),
                nn.Flatten(), nn.Dense(64, activation='relu'), nn.Dense(10))
    net.initialize(init='xavier')
    net.hybridize()
    log("net initialized")
    x = mx.nd.array(np.random.randn(32, 1, 28, 28).astype('float32'), ctx=ctx)
    y = mx.nd.array(np.random.randint(0, 10, (32,)), ctx=ctx)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), 'adam', {'learning_rate': 1e-3})
    losses = []
    for i in range(10):
        with autograd.record():
            L = lossf(net(x), y).mean()
        L.backward(); tr.step(1); losses.append(float(L.asnumpy()))
        log(f"step {i} loss {losses[-1]:.3f}")
    assert losses[-1] < losses[0]
    m = mx.metric.Accuracy(); m.update(y, net(x))
    log("accuracy after 10 steps:", m.get())
    from mxnet_tpu.test_utils import check_consistency
    check_consistency(lambda a, b: mx.nd.dot(a, b),
                      [np.random.randn(64, 64).astype('float32'),
                       np.random.randn(64, 64).astype('float32')],
                      ctx_list=[mx.cpu(), mx.tpu()])
    log("cpu<->tpu dot consistency ok")
    log("VERIFY PASS")
