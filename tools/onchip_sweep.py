#!/usr/bin/env python3
"""onchip_sweep — one budgeted pass over every PROFILE.md r6–r12 lane.

ROADMAP item 1 ("the scripted on-chip sweep"): every perf claim since
BENCH_r04 is parked in PROFILE.md addenda because the axon tunnel died.
Each addendum ends with an "on-chip recipe" — this script IS that
recipe, executable the moment hardware appears:

    python tools/onchip_sweep.py                     # on-chip, full cost
    python tools/onchip_sweep.py --budget-s 1800     # cap total wall time
    python tools/onchip_sweep.py --dryrun            # CPU wiring proof
    python tools/onchip_sweep.py --lanes r10,r12 --json out.json

One consolidated BENCH row per lane lands on stdout (machine-parseable,
one JSON object per line — the driver's BENCH_r13.json feedstock), human
narration on stderr.  Lanes:

  r6   opt_bench       fused-optimizer dispatch collapse + step time
  r7   serve_bench     continuous-batching knee + flops/token           ┐ one
  r12  serve_bench     prefix-cache + speculative-decode ratios        ┘ run
  r8   data_bench      decode-pool images/sec
  r9   perfgate lane   dp2×fsdp2×tp2 mesh — measured vs analytic MFU
  r10  perfgate lane   bert headline — the analytic-MFU protocol row
  r11  autoshard       planner plan.json vs the committed golden

The measured-vs-analytic contract (r10 addendum): lanes that produce a
perfgate record assert ``|measured_mfu − analytic_mfu| / analytic_mfu``
within ``MXNET_PERFGATE_MFU_BAND`` (default 0.25) — *asserted* in real
mode, *reported* in ``--dryrun`` (single-core CPU wall time is noise,
the wiring is what the dryrun proves).  The fresh ``analytic_mfu`` is
additionally pinned to the committed ``tests/perf_baseline.json`` record
within the gate's own 2% band in BOTH modes — the sweep and the CI gate
answer to one set of numbers.

``--dryrun`` shrinks every lane to seconds, pins ``JAX_PLATFORMS=cpu``,
tolerates a nonzero benchmark exit (recorded in the row — some lanes
assert hardware-scale ratios) but requires parseable rows from each:
that is the end-to-end wiring proof the tier-1 test runs.

Exit codes: 0 all lanes ok, 1 lane failure / MFU-band violation, 2 bad
baseline.  Stays jax-free in the parent (every lane is a child process).
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import subprocess
import sys
import time
import types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def _load_perfgate():
    """tools/telemetry_report.py standalone trick — no jax in the parent."""
    if "mxnet_tpu" in sys.modules:
        return importlib.import_module("mxnet_tpu.telemetry.perfgate")
    pkg_name = "_telemetry_report_pkg"
    pkg = sys.modules.get(pkg_name)
    if pkg is None:
        pkg = types.ModuleType(pkg_name)
        pkg.__path__ = [os.path.join(REPO_ROOT, "mxnet_tpu")]
        sys.modules[pkg_name] = pkg
    return importlib.import_module(pkg_name + ".telemetry.perfgate")


# -- lane matrix -------------------------------------------------------------
# kind "bench":    run cmd, parse JSON rows, pick headline metrics
# kind "perfgate": run tools/perfgate.py --lane, check MFU bands
# kind "golden":   run cmd, parse ONE JSON doc, diff against a committed file
# share: lanes naming the same key reuse one child run (r7+r12 = one
# serve_bench pass; its sections cover both addenda)

_PY = sys.executable


def _serve_cmd(dry):
    if dry:
        return [_PY, "benchmark/serve_bench.py", "--config", "llama_tiny",
                "--vocab", "101", "--requests", "8", "--max-batch", "4",
                "--gen-tokens", "6", "--flops-max-len", "32",
                "--tp-max-seq", "64", "--block-tokens", "8",
                "--prefill-tokens", "16", "--prefill-tokens-prefix", "48",
                "--spec-k", "2"]
    return [_PY, "benchmark/serve_bench.py"]


LANES = [
    {"name": "r06_opt_fusion", "row": "r6", "kind": "bench",
     "desc": "fused-optimizer dispatch collapse (opt_bench)",
     "real": [_PY, "benchmark/opt_bench.py", "--dtype", "bfloat16",
              "--multi-precision"],
     "dry": [_PY, "benchmark/opt_bench.py", "--hidden", "64", "--layers",
             "2", "--vocab", "256", "--steps", "2", "--warmup", "1"],
     "headline": ("fused_vs_perparam", "optimizer_dispatches_per_step")},
    {"name": "r07_serve_knee", "row": "r7", "kind": "bench",
     "desc": "continuous-batching knee + flops/token (serve_bench)",
     "share": "serve",
     "headline": ("serve_flops_ratio", "serve_batching_ratio")},
    {"name": "r08_data_pipeline", "row": "r8", "kind": "bench",
     "desc": "multi-core decode pool images/sec (data_bench)",
     "real": [_PY, "benchmark/data_bench.py"],
     "dry": [_PY, "benchmark/data_bench.py", "--images", "48", "--workers",
             "2", "--trials", "2", "--batch", "16", "--size", "64",
             "--crop", "56"],
     "headline": ("data_bench_pooled_images_per_sec",
                  "data_bench_single_process_images_per_sec")},
    {"name": "r09_mesh_mfu", "row": "r9", "kind": "perfgate",
     "desc": "dp2×fsdp2×tp2 mesh lane — measured vs analytic MFU",
     "lane": "multichip_dp2fsdp2tp2"},
    {"name": "r10_analytic_mfu", "row": "r10", "kind": "perfgate",
     "desc": "bert headline lane — the analytic-MFU protocol row",
     "lane": "bert_headline"},
    {"name": "r11_fsdp_crossover", "row": "r11", "kind": "golden",
     "desc": "autoshard plan vs committed golden (planner determinism)",
     "real": [_PY, "tools/autoshard.py", "--model", "llama_small",
              "--vocab", "64", "--batch", "16", "--seq", "16",
              "--devices", "8", "--hbm-mb", "18.6", "--json"],
     "golden": "tests/autoshard_plan_golden.json"},
    {"name": "r12_spec_prefix", "row": "r12", "kind": "bench",
     "desc": "prefix-cache + spec-decode ratios (serve_bench, shared run)",
     "share": "serve",
     "headline": ("serve_prefix_ratio", "serve_spec_ratio")},
]


def _lane_env(dry, device_count=1):
    env = dict(os.environ)
    if dry:
        # the CPU wiring proof pins the virtual platform exactly like the
        # perfgate child env; real mode leaves the accelerator visible
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = \
            f"--xla_force_host_platform_device_count={device_count}"
    for k in ("MXNET_TELEMETRY_DIR", "MXNET_TELEMETRY_PORT"):
        env.pop(k, None)
    return env


def _run_child(cmd, env, timeout_s):
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env, cwd=REPO_ROOT)
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        err = f"timeout after {timeout_s:.0f}s"
    wall = time.monotonic() - t0
    rows = []
    for line in out.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rows.append(json.loads(line))
        except ValueError:
            pass
    return {"rc": rc, "wall_s": round(wall, 3), "rows": rows,
            "stdout": out,
            "stderr_tail": (err or "").strip().splitlines()[-4:]}


def _pick_headline(rows, wanted):
    """The consolidated row keeps only each lane's acceptance metrics."""
    out = {}
    for w in wanted:
        for r in rows:
            if r.get("metric") == w:
                out[w] = {k: v for k, v in r.items() if k != "metric"}
                break
    return out


def _mfu_bands(rec, base_lane, band):
    """(checks, ok_analytic, ok_measured) for one perfgate record."""
    analytic = rec["metrics"]["analytic_mfu"]
    measured = rec.get("observed", {}).get("measured_mfu", 0.0)
    checks = {"analytic_mfu": analytic, "measured_mfu": measured,
              "band": band}
    ok_analytic = True
    if base_lane is not None:
        base_mfu = base_lane["metrics"]["analytic_mfu"]
        rel = abs(analytic - base_mfu) / max(abs(base_mfu), 1e-9)
        ok_analytic = rel <= 0.02    # the gate's own flops-class band
        checks["baseline_analytic_mfu"] = base_mfu
        checks["analytic_vs_baseline_rel"] = round(rel, 6)
        checks["analytic_within_gate_band"] = ok_analytic
    rel_m = abs(measured - analytic) / max(abs(analytic), 1e-9)
    ok_measured = rel_m <= band
    checks["measured_vs_analytic_rel"] = round(rel_m, 6)
    checks["measured_within_band"] = ok_measured
    return checks, ok_analytic, ok_measured


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="budgeted r6–r12 perf sweep (PROFILE.md addenda)")
    ap.add_argument("--dryrun", action="store_true",
                    help="CPU wiring proof: tiny shapes, pinned platform, "
                         "benchmark rc tolerated, MFU band reported only")
    ap.add_argument("--budget-s", type=float, default=3600.0,
                    help="total wall-clock budget; lanes past it are "
                         "skipped loudly (default 3600)")
    ap.add_argument("--lanes", metavar="A,B",
                    help="restrict to these lanes (names or r-rows, "
                         "e.g. r10,r12 or r10_analytic_mfu)")
    ap.add_argument("--baseline", metavar="PATH",
                    help="perfgate baseline for the MFU pin "
                         "(default: tests/perf_baseline.json)")
    ap.add_argument("--json", metavar="PATH",
                    help="also write the full report document here")
    args = ap.parse_args(argv)

    pg = _load_perfgate()
    baseline_path = args.baseline or pg.default_baseline_path()
    base_lanes = {}
    if os.path.exists(baseline_path):
        try:
            base_lanes = pg.load_baseline(baseline_path)["lanes"]
        except pg.BaselineError as e:
            print(f"onchip_sweep: {e}", file=sys.stderr)
            return 2
    else:
        print(f"onchip_sweep: no baseline at {baseline_path} — "
              f"analytic-MFU pin skipped", file=sys.stderr)

    lanes = LANES
    if args.lanes:
        sel = {s.strip() for s in args.lanes.split(",") if s.strip()}
        lanes = [l for l in LANES if l["name"] in sel or l["row"] in sel]
        unknown = sel - {l["name"] for l in lanes} - {l["row"] for l in lanes}
        if unknown:
            raise SystemExit(
                f"unknown lane(s) {sorted(unknown)}; have "
                f"{[l['name'] for l in LANES]}")

    try:
        band = float(os.environ.get("MXNET_PERFGATE_MFU_BAND", "0.25"))
    except ValueError:
        band = 0.25

    t_start = time.monotonic()
    shared = {}
    results = []
    failed = []
    for lane in lanes:
        spent = time.monotonic() - t_start
        left = args.budget_s - spent
        if left <= 0:
            row = {"metric": f"sweep_{lane['name']}", "row": lane["row"],
                   "ok": False, "skipped": "budget exhausted",
                   "budget_s": args.budget_s, "spent_s": round(spent, 1)}
            results.append(row)
            failed.append(lane["name"])
            print(json.dumps(row, sort_keys=True))
            print(f"  [SKIP] {lane['name']} — budget exhausted "
                  f"({spent:.0f}s/{args.budget_s:.0f}s)", file=sys.stderr)
            continue
        print(f"onchip_sweep: lane {lane['name']} ({lane['desc']}) …",
              file=sys.stderr)
        row = {"metric": f"sweep_{lane['name']}", "row": lane["row"],
               "desc": lane["desc"], "mode": "dryrun" if args.dryrun
               else "onchip"}
        ok = True

        if lane["kind"] == "perfgate":
            cmd = [_PY, "tools/perfgate.py", "--lane", lane["lane"]]
            # the perfgate lanes are the analytic protocol rows: they pin
            # the virtual platform in BOTH modes (the record is the
            # hardware-free contract; on-chip MFU rides the bench lanes)
            env = _lane_env(True, pg.lane_device_count(lane["lane"]))
            res = _run_child(cmd, env, left)
            row["rc"], row["wall_s"] = res["rc"], res["wall_s"]
            if res["rc"] != 0 or not res["rows"]:
                ok = False
                row["error"] = "lane child failed"
                row["stderr_tail"] = res["stderr_tail"]
            else:
                rec = res["rows"][-1]
                checks, ok_a, ok_m = _mfu_bands(
                    rec, base_lanes.get(lane["lane"]), band)
                row["mfu"] = checks
                row["lane"] = lane["lane"]
                # analytic pin holds in BOTH modes (deterministic);
                # the measured band is hardware signal — real mode only
                ok = ok_a and (ok_m or args.dryrun)

        elif lane["kind"] == "golden":
            res = _run_child(lane["real"], _lane_env(args.dryrun), left)
            row["rc"], row["wall_s"] = res["rc"], res["wall_s"]
            golden_path = os.path.join(REPO_ROOT, lane["golden"])
            # the planner prints ONE indented JSON document (the exact
            # bytes the CI golden diff checks), not per-line rows
            plan = None
            if res["rc"] == 0:
                try:
                    plan = json.loads(res["stdout"])
                except ValueError:
                    plan = None
            if plan is None:
                ok = False
                row["error"] = "planner child failed"
                row["stderr_tail"] = res["stderr_tail"]
            else:
                with open(golden_path) as f:
                    golden = json.load(f)
                match = plan == golden
                row["golden"] = lane["golden"]
                row["plan_matches_golden"] = match
                row["mesh"] = plan.get("mesh")
                ok = match
        else:   # bench
            key = lane.get("share")
            if key is not None and key in shared:
                res = shared[key]
                row["shared_run"] = True
            else:
                cmd = lane.get("dry") if args.dryrun else lane.get("real")
                if cmd is None:
                    cmd = _serve_cmd(args.dryrun)
                res = _run_child(cmd, _lane_env(args.dryrun), left)
                if key is not None:
                    shared[key] = res
            row["rc"], row["wall_s"] = res["rc"], res["wall_s"]
            row["rows_parsed"] = len(res["rows"])
            row["headline"] = _pick_headline(res["rows"], lane["headline"])
            if not res["rows"]:
                ok = False
                row["error"] = "no parseable BENCH rows"
                row["stderr_tail"] = res["stderr_tail"]
            elif res["rc"] != 0 and not args.dryrun:
                # real mode: a failing benchmark is a failing lane; the
                # dryrun only proves wiring (tiny shapes can miss the
                # hardware-scale ratio gates) and records the rc
                ok = False
                row["error"] = f"benchmark rc={res['rc']}"
                row["stderr_tail"] = res["stderr_tail"]

        row["ok"] = ok
        if not ok:
            failed.append(lane["name"])
        results.append(row)
        print(json.dumps(row, sort_keys=True))
        state = "ok" if ok else "FAIL"
        print(f"  [{state:>4}] {lane['name']} rc={row.get('rc')} "
              f"wall={row.get('wall_s', 0):.1f}s", file=sys.stderr)

    summary = {
        "metric": "onchip_sweep_summary",
        "mode": "dryrun" if args.dryrun else "onchip",
        "lanes": len(results),
        "ok": len(results) - len(failed),
        "failed": failed,
        "mfu_band": band,
        "baseline": baseline_path if base_lanes else None,
        "budget_s": args.budget_s,
        "spent_s": round(time.monotonic() - t_start, 1),
    }
    print(json.dumps(summary, sort_keys=True))
    print(f"onchip_sweep verdict: "
          f"{'ok' if not failed else 'FAIL'} "
          f"({summary['ok']}/{summary['lanes']} lanes, "
          f"{summary['spent_s']:.0f}s/{args.budget_s:.0f}s)",
          file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"summary": summary, "lanes": results}, f, indent=1,
                      sort_keys=True)
            f.write("\n")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
