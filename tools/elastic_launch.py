#!/usr/bin/env python
"""Elastic training launcher (ISSUE 11) — the CLI over
``mxnet_tpu.resilience.ElasticController``.

Where ``tools/launch.py`` is the one-shot dmlc_tracker analog (spawn N
workers, wait, report), this launcher OWNS the job: it watches
heartbeats, restarts the world smaller on worker death, grows it back
after a checkpointed probation, and survives its own death — rerunning
the same command on the same ``--workdir`` re-adopts a live job or
finishes an interrupted resize.

Usage:
  python tools/elastic_launch.py -n 4 --workdir /tmp/job \\
      [--min-workers 2 --max-restarts 8 --regrow-steps 50 \\
       --hang-s 60 --straggler-factor 4 --grace-s 10 \\
       --cpu-devices 1 --ckpt-dir ckpt] \\
      -- python train.py --my-args

The worker command runs once per rank with injected ``MXNET_DIST_*`` /
``MXNET_ELASTIC_*`` env; per-rank logs, heartbeats, telemetry shards,
flight-recorder dumps, and the terminal report roll-up all land under
``--workdir``.  Exit code 0 = every rank completed; 1 = the job died
with the restart budget spent (see ``<workdir>/report/``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="elastic multi-process training controller "
                    "(spawn, watch, resize, survive)")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="target world size")
    ap.add_argument("--workdir", required=True,
                    help="job directory (state file, logs, heartbeats, "
                         "telemetry, flightrec, report roll-up)")
    ap.add_argument("--min-workers", type=int, default=None,
                    help="smallest world to shrink to on worker death "
                         "(default MXNET_ELASTIC_MIN_WORKERS)")
    ap.add_argument("--max-restarts", type=int, default=None,
                    help="unplanned restart budget "
                         "(default MXNET_ELASTIC_MAX_RESTARTS)")
    ap.add_argument("--regrow-steps", type=int, default=None,
                    help="committed checkpoint steps a degraded world "
                         "runs before growing back "
                         "(default MXNET_ELASTIC_REGROW_STEPS)")
    ap.add_argument("--hang-s", type=float, default=None,
                    help="heartbeat staleness = hang "
                         "(default MXNET_ELASTIC_HANG_S)")
    ap.add_argument("--straggler-factor", type=float, default=None,
                    help="stepclock straggler threshold; 0 disables "
                         "(default MXNET_ELASTIC_STRAGGLER_FACTOR)")
    ap.add_argument("--grace-s", type=float, default=None,
                    help="SIGTERM→SIGKILL drain grace "
                         "(default MXNET_ELASTIC_GRACE_S)")
    ap.add_argument("--ckpt-dir", default="ckpt",
                    help="checkpoint tree (relative to workdir) whose "
                         "manifest drives resize/regrow decisions")
    ap.add_argument("--cpu-devices", type=int, default=None,
                    help="force each worker onto N virtual CPU devices "
                         "(testing without TPUs)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="worker command (prefix with -- to separate)")
    args = ap.parse_args(argv)
    # strip only the LEADING separator — a later "--" belongs to the
    # worker command itself
    command = args.command[1:] \
        if args.command and args.command[0] == "--" else args.command
    if not command:
        ap.error("no worker command given")

    workdir = os.path.abspath(args.workdir)
    # the controller's own observability rides the job's collection
    # dirs — FORCED over any ambient redirect (the report roll-up and
    # the mid-resize postmortems read exactly these paths), and set
    # BEFORE importing mxnet_tpu so the flight recorder and exit-time
    # snapshot export arm against them
    os.environ["MXNET_TELEMETRY"] = "1"
    os.environ["MXNET_TELEMETRY_DIR"] = os.path.join(workdir, "telemetry")
    os.environ["MXNET_FLIGHTREC_DIR"] = os.path.join(workdir, "flightrec")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu.resilience import ElasticController, JobFailedError

    ctl = ElasticController(
        command, args.num_workers, workdir,
        min_workers=args.min_workers, max_restarts=args.max_restarts,
        regrow_steps=args.regrow_steps, hang_s=args.hang_s,
        straggler_factor=args.straggler_factor, grace_s=args.grace_s,
        cpu_devices_per_worker=args.cpu_devices, ckpt_dir=args.ckpt_dir)
    try:
        summary = ctl.run()
    except JobFailedError as e:
        print(f"elastic_launch: {e}", file=sys.stderr)
        return 1
    print(json.dumps(summary, indent=1))
    return 0 if summary.get("outcome") == "done" else 1


if __name__ == "__main__":
    sys.exit(main())
