#!/usr/bin/env python
"""Allreduce bandwidth oracle (reference tools/bandwidth/measure.py —
the BASELINE "KVStore allreduce BW" metric).

Measures the kvstore reduction path at increasing sizes and reports
algorithm bandwidth per the standard allreduce accounting
``algbw = 2 * (n-1)/n * bytes / time`` (ring-allreduce wire traffic).

Modes (auto-selected):
 - multi-process (launched under tools/launch.py): dist_tpu_sync psum
   over the process mesh — what a TPU pod slice does over ICI/DCN.
 - single process, multi-device: parallel.allreduce over the local mesh
   (the 'device'-kvstore path; virtual 8-CPU mesh in tests).
 - single device: reports device memory bandwidth of the reduce path
   (n=1 — no collective; printed with "devices": 1 so consumers can
   discount it).

Output: one JSON line per size + a summary line, e.g.
  {"metric": "allreduce_bw", "size_mb": 64.0, "gbps": 12.3, ...}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _maybe_init_distributed():
    """Join the jax.distributed rendezvous when launched by tools/launch.py
    (must happen before any backend query like process_count)."""
    import jax
    # honor JAX_PLATFORMS explicitly: PJRT plugins (the axon TPU tunnel)
    # can ignore the env var, and a "cpu" request silently landing on the
    # TPU would fake the multi-device measurement
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
        # multi-process CPU collectives need a host implementation,
        # configured BEFORE backend init (the ISSUE 3 dist-worker fix;
        # without it every cross-process psum raises)
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass  # older jaxlib without gloo
    coord = os.environ.get("MXNET_DIST_COORDINATOR")
    if coord:
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(os.environ["MXNET_DIST_NUM_WORKERS"]),
                process_id=int(os.environ["MXNET_DIST_RANK"]))
        except RuntimeError:
            pass  # already initialized


def measure(sizes_mb, iters=5, use_dist=None):
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import parallel

    _maybe_init_distributed()
    n_proc = jax.process_count()
    dist = use_dist if use_dist is not None else n_proc > 1
    rows = []
    if dist:
        kv = mx.kv.create("dist_tpu_sync")
        n = kv.num_workers
        reduce_arr = kv._allreduce
    else:
        mesh = parallel.make_mesh()
        n = mesh.size

        def reduce_arr(arr):
            out = parallel.allreduce([mx.nd.NDArray._from_data(arr)],
                                     mesh=mesh)
            return out[0]._data

    for mb in sizes_mb:
        elems = int(mb * 1024 * 1024 / 4)
        arr = jax.numpy.asarray(np.random.randn(elems).astype(np.float32))
        reduce_arr(arr)  # warmup/compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = reduce_arr(arr)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        nbytes = elems * 4
        factor = 2 * (n - 1) / n if n > 1 else 1.0
        algbw = factor * nbytes / dt / 1e9
        rows.append({"metric": "allreduce_bw", "size_mb": mb,
                     "gbps": round(algbw, 3), "time_ms": round(dt * 1e3, 3),
                     "devices": n, "mode": "dist" if dist else "local"})
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", default="1,4,16,64",
                    help="comma-separated message sizes in MB")
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args(argv)
    sizes = [float(s) for s in args.sizes_mb.split(",") if s]
    rows = measure(sizes, args.iters)  # initializes distributed if launched
    import jax
    if jax.process_index() == 0:
        for r in rows:
            print(json.dumps(r))
        best = max(rows, key=lambda r: r["gbps"])
        print(json.dumps({"metric": "allreduce_bw_peak",
                          "value": best["gbps"], "unit": "GB/s",
                          "size_mb": best["size_mb"],
                          "devices": best["devices"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
