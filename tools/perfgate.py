#!/usr/bin/env python3
"""perfgate — the hardware-free perf-regression gate CLI.

    python tools/perfgate.py --check [--json] [--baseline PATH]
    python tools/perfgate.py --write-baseline --reason "why" [--lanes a,b]
    python tools/perfgate.py --snapshot out.json [--lanes a,b]
    python tools/perfgate.py --lane NAME          # child mode (needs jax)
    python tools/perfgate.py --list

The parent stays jax-free (the ``telemetry_report`` standalone-load
trick): each lane runs in a fresh child process with a PINNED platform
env (``JAX_PLATFORMS=cpu``, ``XLA_FLAGS`` forced to the lane's virtual
device count, telemetry export knobs stripped) so records cannot be
skewed by an inherited override — while deliberate regression knobs
(e.g. ``MXNET_KVSTORE_BUCKET_MB=0``) pass straight through, which is
exactly how the red-path test injects its dispatch explosion.

Exit codes: 0 pass, 1 drift / lane failure, 2 unusable baseline.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import subprocess
import sys
import types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)     # child mode imports mxnet_tpu itself


def _load_perfgate():
    """Load mxnet_tpu.telemetry.perfgate without running the jax-importing
    package __init__ (tools/telemetry_report.py precedent)."""
    if "mxnet_tpu" in sys.modules:
        return importlib.import_module("mxnet_tpu.telemetry.perfgate")
    pkg_name = "_telemetry_report_pkg"
    pkg = sys.modules.get(pkg_name)
    if pkg is None:
        pkg = types.ModuleType(pkg_name)
        pkg.__path__ = [os.path.join(REPO_ROOT, "mxnet_tpu")]
        sys.modules[pkg_name] = pkg
    return importlib.import_module(pkg_name + ".telemetry.perfgate")


def _child_env(device_count):
    """The pinned lane environment: deterministic platform, regression
    knobs passed through."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={device_count}"
    for k in ("MXNET_TELEMETRY_DIR", "MXNET_TELEMETRY_PORT",
              "MXNET_PEAK_FLOPS", "MXNET_PEAK_HBM_GBS"):
        env.pop(k, None)
    return env


def _run_lane_child(pg, name, timeout_s):
    env = _child_env(pg.lane_device_count(name))
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--lane", name],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=REPO_ROOT)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
        raise RuntimeError(
            f"lane {name!r} child failed (rc={proc.returncode}):\n  "
            + "\n  ".join(tail))
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"lane {name!r} child emitted no JSON record")


def _selected_lanes(pg, arg):
    names = pg.lane_names()
    sel = arg or os.environ.get("MXNET_PERFGATE_LANES", "")
    if not sel:
        return names
    picked = [s.strip() for s in sel.split(",") if s.strip()]
    unknown = [p for p in picked if p not in names]
    if unknown:
        raise SystemExit(f"unknown lane(s) {unknown}; have {names}")
    return picked


def _snapshot(pg, lanes, timeout_s, quiet=False):
    records = {}
    for name in lanes:
        if not quiet:
            print(f"perfgate: running lane {name} …", file=sys.stderr)
        records[name] = _run_lane_child(pg, name, timeout_s)
    return records


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="analytic perf-regression gate over the cost ledger")
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="diff a fresh snapshot against the committed "
                           "baseline; exit 1 on drift")
    mode.add_argument("--write-baseline", action="store_true",
                      help="snapshot and (re)write the baseline file "
                           "(requires --reason)")
    mode.add_argument("--snapshot", metavar="PATH",
                      help="write a fresh snapshot JSON and exit")
    mode.add_argument("--lane", metavar="NAME",
                      help="child mode: run ONE lane in-process and print "
                           "its record (imports jax)")
    mode.add_argument("--list", action="store_true",
                      help="list registered lanes")
    ap.add_argument("--baseline", metavar="PATH",
                    help="baseline path (default: tests/perf_baseline.json "
                         "or $MXNET_PERFGATE_BASELINE)")
    ap.add_argument("--lanes", metavar="A,B",
                    help="restrict to these lanes "
                         "(or $MXNET_PERFGATE_LANES)")
    ap.add_argument("--reason", metavar="TEXT",
                    help="why the baseline legitimately moved "
                         "(logged append-only into the file)")
    ap.add_argument("--json", action="store_true",
                    help="emit the check report as JSON (stdout)")
    args = ap.parse_args(argv)

    if args.lane:
        # child mode runs the real runtime: import the genuine package so
        # the armed ledger/registry are the instances the lane feeds (the
        # private standalone namespace would arm a parallel copy)
        importlib.import_module("mxnet_tpu")

    pg = _load_perfgate()

    if args.list:
        for name in pg.lane_names():
            fn, devs, desc = pg.LANES[name]
            print(f"  {name:<24} devices={devs}  {desc}")
        return 0

    if args.lane:
        rec = pg.run_lane(args.lane)
        print(json.dumps(rec, sort_keys=True))
        return 0

    from_cfg = None
    try:
        from_cfg = float(os.environ.get("MXNET_PERFGATE_CHILD_TIMEOUT_S",
                                        "420"))
    except ValueError:
        from_cfg = 420.0
    timeout_s = from_cfg
    baseline_path = args.baseline or pg.default_baseline_path()
    lanes = _selected_lanes(pg, args.lanes)

    if args.snapshot:
        records = _snapshot(pg, lanes, timeout_s)
        doc = pg.canonical_doc(records, reasons=[])
        with open(args.snapshot, "w") as f:
            f.write(pg.dump_doc(doc))
        print(f"perfgate snapshot ({len(records)} lanes) -> {args.snapshot}")
        return 0

    if args.write_baseline:
        if not args.reason:
            ap.error("--write-baseline requires --reason "
                     "(the legitimate-change log is append-only)")
        reasons = []
        if os.path.exists(baseline_path):
            try:
                reasons = list(
                    pg.load_baseline(baseline_path).get("reasons") or [])
            except pg.BaselineError:
                reasons = []      # corrupt file: start the log over
        records = _snapshot(pg, lanes, timeout_s)
        reasons.append({"reason": args.reason, "lanes": sorted(records)})
        doc = pg.canonical_doc(records, reasons=reasons)
        os.makedirs(os.path.dirname(os.path.abspath(baseline_path)),
                    exist_ok=True)
        with open(baseline_path, "w") as f:
            f.write(pg.dump_doc(doc))
        print(f"perfgate baseline ({len(records)} lanes) -> {baseline_path}")
        return 0

    # --check
    try:
        base = pg.load_baseline(baseline_path)
    except pg.BaselineError as e:
        print(f"perfgate: {e}", file=sys.stderr)
        return 2
    base_lanes = base["lanes"]
    if args.lanes or os.environ.get("MXNET_PERFGATE_LANES"):
        base_lanes = {k: v for k, v in base_lanes.items() if k in lanes}
        lanes = [n for n in lanes if n in base_lanes or n in pg.lane_names()]
        print(f"perfgate: PARTIAL check over {lanes}", file=sys.stderr)
    else:
        # a lane registered in code but absent from the baseline must
        # surface as "added" — snapshot the full registry
        lanes = sorted(set(pg.lane_names()) | set(base_lanes))
        lanes = [n for n in lanes if n in pg.lane_names()]
    fresh = _snapshot(pg, lanes, timeout_s)
    report = pg.diff_snapshots(base_lanes, fresh)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        for line in pg.report_lines(report, baseline_path=baseline_path):
            print(line)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
