#!/usr/bin/env python
"""parse_log — turn training logs into a markdown table (reference
tools/parse_log.py).

Understands the framework's own log lines (Module.fit
``Epoch[k] Train-accuracy=…`` / ``Epoch[k] Validation-accuracy=…``,
estimator ``[Epoch k] … name=value``) and the reference's identical
Module format.

Usage: python tools/parse_log.py train.log [--format md|csv]
"""

from __future__ import annotations

import argparse
import re
import sys

_PATTERNS = [
    # Module.fit / reference: Epoch[3] Train-accuracy=0.91
    re.compile(r"Epoch\[(?P<epoch>\d+)\]\s+"
               r"(?P<phase>Train|Validation)-(?P<name>[\w-]+)"
               r"=(?P<value>[-\d.eE]+)"),
    # speedometer: Epoch[3] Batch [40] Speed: 123.4 samples/sec
    re.compile(r"Epoch\[(?P<epoch>\d+)\].*?"
               r"Speed:\s*(?P<value>[\d.]+)\s*(?P<name>samples)/sec"),
]


_EST_EPOCH = re.compile(r"\[Epoch (?P<epoch>\d+)\]")
_EST_PAIR = re.compile(r"(?P<name>[\w-]+)=(?P<value>[-\d.eE]+)")


def parse(lines):
    """Returns {epoch: {column: value}} (last value per column wins)."""
    table = {}
    for line in lines:
        matched = False
        for pat in _PATTERNS:
            for m in pat.finditer(line):
                d = m.groupdict()
                phase = d.get("phase")
                col = f"{phase.lower()}-{d['name']}" if phase else d["name"]
                table.setdefault(int(d["epoch"]), {})[col] = \
                    float(d["value"])
                matched = True
        if matched:
            continue
        # estimator lines carry SEVERAL name=value pairs — take them all
        me = _EST_EPOCH.search(line)
        if me:
            epoch = int(me.group("epoch"))
            for m in _EST_PAIR.finditer(line):
                table.setdefault(epoch, {})[m.group("name")] = \
                    float(m.group("value"))
    return table


def render(table, fmt="md", out=sys.stdout):
    cols = sorted({c for row in table.values() for c in row})
    if fmt == "csv":
        out.write(",".join(["epoch"] + cols) + "\n")
        for e in sorted(table):
            out.write(",".join([str(e)] + [
                f"{table[e].get(c, '')}" for c in cols]) + "\n")
        return
    out.write("| epoch | " + " | ".join(cols) + " |\n")
    out.write("|" + "---|" * (len(cols) + 1) + "\n")
    for e in sorted(table):
        cells = [f"{table[e][c]:g}" if c in table[e] else ""
                 for c in cols]
        out.write(f"| {e} | " + " | ".join(cells) + " |\n")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("logfile")
    ap.add_argument("--format", choices=["md", "csv"], default="md")
    args = ap.parse_args(argv)
    with open(args.logfile) as f:
        table = parse(f)
    if not table:
        print("no recognizable log lines found", file=sys.stderr)
        return 1
    render(table, args.format)
    return 0


if __name__ == "__main__":
    sys.exit(main())
