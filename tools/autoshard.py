#!/usr/bin/env python3
"""autoshard — pick mesh + rule pack + microbatch/remat under an HBM
budget, analytically (ISSUE 14; the ROADMAP-3 auto-sharder CLI).

    python tools/autoshard.py --model llama_small --batch 16 --seq 16 \\
        --devices 8 --hbm-mb 20 --out plan.json

Prints the scored candidate table (fit verdict per layout) and writes
the chosen ``plan.json`` — a deterministic artifact (same inputs ⇒
byte-identical file; CI goldens it) that ``parallel.TrainStep(plan=
autoshard.load_plan(path))`` consumes directly.

Model selection: ``--model`` names a zoo config (``llama_tiny``,
``llama_small``, ``llama3_8b``, ``bert_...``, ``transformer``-family via
``--family``), or ``--shapes shapes.json`` supplies a raw
``{param_name: shape}`` table for models not in the zoo.  Zoo models
build param SHAPES only — no weights are initialized, so planning an
llama3_8b layout needs megabytes, not the model.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="analytic auto-sharder: mesh + rules + microbatch "
                    "under an HBM budget")
    ap.add_argument("--model", help="zoo config name (llama_*, bert_*)")
    ap.add_argument("--shapes", help="JSON file {param_name: shape}")
    ap.add_argument("--family", default=None,
                    help="rule-pack family override "
                         "(llama|bert|transformer; inferred by default)")
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--batch", type=int, required=True,
                    help="GLOBAL batch size")
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--devices", type=int, required=True)
    ap.add_argument("--hbm-mb", type=float, default=None,
                    help="per-device HBM budget (MB); default knob "
                         "MXNET_AUTOSHARD_HBM_GB, else unbounded")
    ap.add_argument("--optimizer", default="adam",
                    choices=("adam", "sgd"))
    ap.add_argument("--multi-precision", action="store_true")
    ap.add_argument("--max-micro", type=int, default=None)
    ap.add_argument("--no-remat", action="store_true",
                    help="exclude remat candidates")
    ap.add_argument("--candidates", type=int, default=12,
                    help="how many scored candidates to print")
    ap.add_argument("--out", default=None, help="write plan.json here")
    ap.add_argument("--json", action="store_true",
                    help="print the plan JSON to stdout instead of the "
                         "table")
    args = ap.parse_args(argv)

    if bool(args.model) == bool(args.shapes):
        raise SystemExit("autoshard: exactly one of --model/--shapes")
    from mxnet_tpu import autoshard
    from mxnet_tpu.base import MXNetError

    if args.shapes:
        with open(args.shapes) as f:
            shapes = {k: tuple(v) for k, v in json.load(f).items()}
        family = args.family
    else:
        try:
            shapes, family = autoshard.zoo_shapes(args.model,
                                                  vocab=args.vocab)
        except MXNetError as e:
            raise SystemExit(f"autoshard: {e}")
        family = args.family or family

    if args.hbm_mb is not None:
        budget = int(args.hbm_mb * 2 ** 20)
    else:
        # resolve the knob fallback HERE so the printed table's fit
        # column and the chosen plan agree (plan() applies the same
        # default when hbm_budget_bytes is None)
        from mxnet_tpu import config as _config
        gb = _config.get_float("MXNET_AUTOSHARD_HBM_GB", 0.0)
        budget = int(gb * 2 ** 30) if gb > 0 else None
    cands, family = autoshard.enumerate_candidates(
        shapes, args.devices, args.batch, seq=args.seq, family=family,
        optimizer=args.optimizer, multi_precision=args.multi_precision,
        max_micro=args.max_micro, allow_remat=not args.no_remat)
    try:
        plan = autoshard.plan(
            shapes, args.batch, n_devices=args.devices, seq=args.seq,
            hbm_budget_bytes=budget, candidates=(cands, family))
    except MXNetError as e:
        print(f"NO FIT: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(plan.to_json(), end="")
    else:
        print(f"{len(cands)} candidates for {args.devices} devices, "
              f"batch {args.batch}"
              + (f", seq {args.seq}" if args.seq else "")
              + (f", budget {budget / 2**20:.1f}MB/dev" if budget
                 else ", unbounded")
              + f" (family {family}):")
        print(f"  {'mesh':<24} {'pack':<18} {'micro':>5} {'remat':>5} "
              f"{'est MB/dev':>11} {'fit':>4} {'step est':>10} "
              f"{'eff':>5}")
        for c in cands[:args.candidates]:
            dims = "x".join(f"{a}{s}" for a, s in sorted(
                c["mesh"].items(),
                key=lambda kv: ("dp", "fsdp", "tp", "sp").index(kv[0])))
            tot = c["estimate"]["total_bytes"]
            fit = "yes" if budget is None or tot <= budget else "no"
            print(f"  {dims:<24} {str(c['rule_pack']):<18} "
                  f"{c['n_micro']:>5} {str(c['remat']):>5} "
                  f"{tot / 2**20:>11.2f} {fit:>4} "
                  f"{c['step_time_s']:>10.2e} {c['matmul_eff']:>5.2f}")
        print(f"chosen: {plan}")
    if args.out:
        plan.save(args.out)
        print(f"plan written to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
