#!/usr/bin/env python
"""Decomposition profiler for the bench lanes (VERDICT r4 items 1-2).

The axon tunnel records no device-side trace plane (r4 traces carry only
host events), so per-op device time is reconstructed by measuring each
step component STANDALONE at the exact bench shapes, scanned inside one
jit (lax.scan) so dispatch cost is amortized exactly like bench.py:

  full        the real TrainStep (what bench.py times)
  attention   the flash kernel fwd+bwd, one layer's shape x num_layers
  dense       one encoder cell minus attention (qkv/proj/ffn/gelu/ln),
              fwd+bwd, x num_layers
  head        MLM decoder matmul + softmax-CE fwd+bwd (the vocab matmul)
  embed       token+position gather + embed layernorm fwd+bwd
  adam        optimizer update over all params

The residual (full - sum of parts) is scan/bookkeeping overhead.  Each
component prints ms/step and its share of the ideal roofline.

Usage:
  python tools/profile_lane.py --lane bert512   # the 0.43-MFU regime
  python tools/profile_lane.py --lane llama2048
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timed_scan(fn, carry, n_steps, n_rep=3, name=""):
    """Median wall ms/step of fn scanned n_steps times inside one jit."""
    import jax

    @jax.jit
    def run(c):
        def body(c, _):
            return fn(c), None
        c, _ = jax.lax.scan(body, c, None, length=n_steps)
        return c

    out = run(carry)
    jax.block_until_ready(out)
    times = []
    for _ in range(n_rep):
        t0 = time.perf_counter()
        out = run(carry)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) / n_steps * 1e3)
    ms = float(np.median(times))
    print(f"    [{name or 'component'}] {ms:.2f} ms/step", flush=True)
    return ms


def profile_bert512(batch=32, seq=512, scan_steps=32):
    import jax
    import jax.numpy as jnp
    import ml_dtypes
    jax.config.update("jax_default_matmul_precision", "default")
    bf16 = ml_dtypes.bfloat16
    layers, units, hidden, heads, vocab = 12, 768, 3072, 12, 30522
    d_head = units // heads
    r = np.random.RandomState(0)

    def t(*shape, dt=bf16, scale=0.02):
        return jnp.asarray((r.randn(*shape) * scale).astype(dt))

    results = {}

    # ---- attention: flash kernel fwd+bwd at one layer's shape ----------
    from mxnet_tpu.kernels.flash_attention import flash_attention
    q = t(batch, heads, seq, d_head, scale=1.0)
    k = t(batch, heads, seq, d_head, scale=1.0)
    v = t(batch, heads, seq, d_head, scale=1.0)

    def att_step(qq):
        def f(qi):
            return flash_attention(qi, k, v,
                                   sm_scale=1.0 / np.sqrt(d_head)).sum()
        g = jax.grad(f)(qq)
        return (qq + g.astype(qq.dtype) * bf16(1e-8)).astype(qq.dtype)

    per_layer = _timed_scan(att_step, q, scan_steps, name="attention/layer")
    results["attention"] = per_layer * layers

    # ---- dense: one encoder cell minus attention, fwd+bwd --------------
    wqkv = t(units, 3 * units)
    wproj = t(units, units)
    w1 = t(units, hidden)
    w2 = t(hidden, units)
    gam = jnp.ones((units,), bf16)
    x0 = t(seq, batch, units, scale=1.0)

    def ln(h):
        h32 = h.astype(jnp.float32)
        m = h32.mean(-1, keepdims=True)
        vr = ((h32 - m) ** 2).mean(-1, keepdims=True)
        return ((h32 - m) * jax.lax.rsqrt(vr + 1e-12)).astype(h.dtype) * gam

    def cell_no_att(xx):
        def f(xi):
            qkv = xi @ wqkv
            # fold the full qkv projection into the consumed value (summed
            # thirds, NOT a slice): a sliced dot lets XLA narrow the
            # matmul to 1/3 and the component under-measures
            ctxv = (qkv[..., :units] + qkv[..., units:2 * units]
                    + qkv[..., 2 * units:])       # attention itself is
            out = ln(xi + ctxv @ wproj)           # measured separately
            h = jax.nn.gelu(out @ w1) @ w2
            return ln(out + h).astype(jnp.float32).sum()
        g = jax.grad(f)(xx)
        return (xx + g.astype(xx.dtype) * bf16(1e-8)).astype(xx.dtype)

    results["dense"] = _timed_scan(cell_no_att, x0, scan_steps, name="dense/layer") * layers

    # ---- head: MLM decoder matmul + softmax CE fwd+bwd -----------------
    wdec = t(units, vocab)
    labels = jnp.asarray(r.randint(0, vocab, (batch * seq,)), jnp.int32)
    xh = t(batch * seq, units, scale=1.0)

    def head_step(xx):
        def f(xi):
            logits = (xi @ wdec).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            picked = jnp.take_along_axis(logits, labels[:, None],
                                         axis=-1)[:, 0]
            return (lse - picked).mean()
        g = jax.grad(f)(xx)
        return (xx + g.astype(xx.dtype) * bf16(1e-8)).astype(xx.dtype)

    results["head"] = _timed_scan(head_step, xh, scan_steps, name="head")

    # ---- embed: gathers + embed LN fwd+bwd ------------------------------
    wemb = t(vocab, units)
    wpos = t(512, units)
    toks = jnp.asarray(r.randint(0, vocab, (batch, seq)), jnp.int32)

    def embed_step(we_):
        def f(wi):
            e = wi[toks] + wpos[None, :seq]
            return ln(e).astype(jnp.float32).sum()
        g = jax.grad(f)(we_)
        return (we_ + g.astype(we_.dtype) * bf16(1e-8)).astype(we_.dtype)

    results["embed"] = _timed_scan(embed_step, wemb, scan_steps, name="embed")

    # ---- adam: the optimizer update over all params ---------------------
    n_params = (layers * (units * 3 * units + 3 * units + units * units
                          + units + units * hidden + hidden
                          + hidden * units + units + 4 * units)
                + vocab * units + 512 * units + 2 * units
                + units * units + units + units * vocab + vocab)
    p32 = jnp.asarray(r.randn(n_params).astype(np.float32))
    gr = jnp.asarray(r.randn(n_params).astype(np.float32) * 1e-3)

    # NOTE: gr rides the CARRY, not a closure — closed-over device arrays
    # are baked into the HLO as constants, and a 440MB constant overflows
    # the axon remote-compile request (HTTP 413)
    def adam_step(state):
        p, m, v, g = state
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        p = p - 1e-4 * m / (jnp.sqrt(v) + 1e-8)
        return (p, m, v, g)

    results["adam"] = _timed_scan(adam_step,
                                  (p32, jnp.zeros_like(p32),
                                   jnp.zeros_like(p32), gr), scan_steps,
                                  name="adam")
    return results


def profile_llama2048(batch=4, seq=2048, scan_steps=8):
    import jax
    import jax.numpy as jnp
    import ml_dtypes
    jax.config.update("jax_default_matmul_precision", "default")
    bf16 = ml_dtypes.bfloat16
    # mirror bench.run_llama_once's arch (same env override); components
    # here measure the NO-remat cost — the remat lane's extra forward
    # shows up as part of the full-step residual
    arch = os.environ.get("MXNET_BENCH_LLAMA_ARCH", "8,2048,5504,16,8,0")
    layers, units, hidden, heads, kv_heads =         [int(x) for x in arch.split(",")][:5]
    vocab = 8192
    d_head = units // heads
    r = np.random.RandomState(0)

    def t(*shape, dt=bf16, scale=0.02):
        return jnp.asarray((r.randn(*shape) * scale).astype(dt))

    results = {}
    from mxnet_tpu.kernels.flash_attention import flash_attention
    q = t(batch, heads, seq, d_head, scale=1.0)
    k = t(batch, heads, seq, d_head, scale=1.0)
    v = t(batch, heads, seq, d_head, scale=1.0)

    def att_step(qq):
        def f(qi):
            return flash_attention(qi, k, v, causal=True,
                                   sm_scale=1.0 / np.sqrt(d_head)).sum()
        g = jax.grad(f)(qq)
        return (qq + g.astype(qq.dtype) * bf16(1e-8)).astype(qq.dtype)

    results["attention"] = _timed_scan(att_step, q, scan_steps, name="attention/layer") * layers

    wq = t(units, units)
    wk = t(units, units // (heads // kv_heads))
    wv = t(units, units // (heads // kv_heads))
    wo = t(units, units)
    wg = t(units, hidden)
    wu = t(units, hidden)
    wd = t(hidden, units)
    x0 = t(batch, seq, units, scale=1.0)

    def rms(h):
        h32 = h.astype(jnp.float32)
        return (h32 * jax.lax.rsqrt((h32 ** 2).mean(-1, keepdims=True)
                                    + 1e-6)).astype(h.dtype)

    def cell_no_att(xx):
        def f(xi):
            xn = rms(xi)
            qq = xn @ wq
            kk = xn @ wk             # folded into the output below — dead
            vv = xn @ wv             # projections would be DCE'd by XLA
            out = xi + qq @ wo
            out = out + jnp.pad(kk + vv,
                                ((0, 0), (0, 0), (0, units - kk.shape[-1])))
            xn2 = rms(out)
            h = (jax.nn.silu(xn2 @ wg) * (xn2 @ wu)) @ wd
            return rms(out + h).astype(jnp.float32).sum()
        g = jax.grad(f)(xx)
        return (xx + g.astype(xx.dtype) * bf16(1e-8)).astype(xx.dtype)

    results["dense"] = _timed_scan(cell_no_att, x0, scan_steps, name="dense/layer") * layers

    wdec = t(units, vocab)
    labels = jnp.asarray(r.randint(0, vocab, (batch * seq,)), jnp.int32)
    xh = t(batch * seq, units, scale=1.0)

    def head_step(xx):
        def f(xi):
            logits = (xi @ wdec).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            picked = jnp.take_along_axis(logits, labels[:, None],
                                         axis=-1)[:, 0]
            return (lse - picked).mean()
        g = jax.grad(f)(xx)
        return (xx + g.astype(xx.dtype) * bf16(1e-8)).astype(xx.dtype)

    results["head"] = _timed_scan(head_step, xh, scan_steps, name="head")
    return results


def _full_step_ms(lane):
    """Run the real bench lane in-process and return its step_ms."""
    import bench
    if lane == "bert512":
        res = bench.run_once("bert_12_768_12", 32, 512, "bfloat16", 32, 1)
    else:
        res = bench.run_llama_once(4, 2048, "bfloat16", 8, 1)
    return res["extra"]["step_ms"], res["extra"]["mfu"]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--lane", choices=["bert512", "llama2048"],
                    default="bert512")
    ap.add_argument("--skip-full", action="store_true",
                    help="only the component measurements")
    args = ap.parse_args(argv)
    os.environ.setdefault("MXNET_FUSED_ATTENTION", "1")

    full_ms = mfu = None
    if not args.skip_full:
        full_ms, mfu = _full_step_ms(args.lane)
    parts = profile_bert512() if args.lane == "bert512" \
        else profile_llama2048()

    print(f"\n== {args.lane} decomposition (ms/step, scan-amortized) ==")
    total = sum(parts.values())
    for name, ms in sorted(parts.items(), key=lambda kv: -kv[1]):
        print(f"  {name:<10} {ms:8.2f} ms")
    print(f"  {'SUM':<10} {total:8.2f} ms")
    if full_ms is not None:
        print(f"  {'FULL step':<10} {full_ms:8.2f} ms   (mfu {mfu:.4f})")
        print(f"  {'residual':<10} {full_ms - total:8.2f} ms  "
              "(scan/bookkeeping/fusion differences)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
