#!/usr/bin/env python
"""Serving-tier launcher (ISSUE 13) — the CLI over
``mxnet_tpu.serving.Router``.

Brings up a router over N llama engine replicas, pushes a prompt file
through the tier, prints one JSON line per result, and shuts the tier
down.  Rerunning with ``--resume`` on the same ``--workdir`` re-adopts a
dead router's live replicas (state journal + replica port files) and
finishes its journaled in-flight requests first.

Usage:
  python tools/serve_router.py -n 2 --workdir /tmp/tier \\
      --model llama_tiny --vocab 101 --seed 7 \\
      --prompts prompts.json [--queue-max 64 --hedge-s 0.05] [--resume]

``prompts.json`` is a JSON list of ``{"prompt": [ints],
"max_new_tokens": N[, "deadline_s": s][, "tag": str]}``.  Without
``--prompts`` the CLI just proves the tier comes up and prints its
health view.  ``--keep`` leaves the replicas running at exit (a later
``--resume`` run re-adopts them).  Exit code 0 = every submitted
request completed; 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fault-tolerant serving tier: router over N engine "
                    "replica subprocesses")
    ap.add_argument("-n", "--replicas", type=int, default=2)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--model", default="llama_tiny")
    ap.add_argument("--vocab", type=int, default=101)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--eos", type=int, default=-1)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--block-tokens", type=int, default=None)
    ap.add_argument("--max-seq", type=int, default=None)
    ap.add_argument("--prefill-tokens", type=int, default=None)
    ap.add_argument("--replica-cmd", default=None,
                    help="override the replica argv (JSON list); "
                         "default builds the llama worker from the "
                         "--model/--vocab/--seed flags")
    ap.add_argument("--prompts", default=None,
                    help="JSON request file (see module docstring)")
    ap.add_argument("--queue-max", type=int, default=None)
    ap.add_argument("--hedge-s", type=float, default=None)
    ap.add_argument("--max-retries", type=int, default=None)
    ap.add_argument("--max-respawns", type=int, default=None)
    ap.add_argument("--hang-s", type=float, default=None)
    ap.add_argument("--resume", action="store_true",
                    help="(re-)run on an existing workdir: re-adopt "
                         "live replicas and finish the journal")
    ap.add_argument("--keep", action="store_true",
                    help="leave replicas running at exit")
    ap.add_argument("--result-timeout", type=float, default=300.0)
    args = ap.parse_args(argv)

    workdir = os.path.abspath(args.workdir)
    os.environ["MXNET_TELEMETRY"] = "1"
    os.environ["MXNET_TELEMETRY_DIR"] = os.path.join(workdir, "telemetry")
    os.environ["MXNET_FLIGHTREC_DIR"] = os.path.join(workdir, "flightrec")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu.serving.router import Router, RouterOverloaded

    if args.replica_cmd:
        cmd = json.loads(args.replica_cmd)
    else:
        cmd = [sys.executable, "-m", "mxnet_tpu.serving.replica",
               "--model", args.model, "--vocab", str(args.vocab),
               "--seed", str(args.seed), "--eos", str(args.eos)]
        for flag, val in (("--max-batch", args.max_batch),
                          ("--block-tokens", args.block_tokens),
                          ("--max-seq", args.max_seq),
                          ("--prefill-tokens", args.prefill_tokens)):
            if val is not None:
                cmd += [flag, str(val)]

    router = Router(cmd, args.replicas, workdir,
                    queue_max=args.queue_max, hedge_s=args.hedge_s,
                    max_retries=args.max_retries,
                    max_respawns=args.max_respawns,
                    hang_s=args.hang_s).start()
    failed = 0
    try:
        up = router.wait_up(timeout_s=300)
        print(json.dumps({"event": "tier_up", "replicas_up": up,
                          "status": router.replica_status()}))
        handles = dict(router.recovered()) if args.resume else {}
        if args.prompts:
            with open(args.prompts) as f:
                want = json.load(f)
            for i, rec in enumerate(want):
                tag = rec.get("tag", f"req-{i}")
                if tag in handles:
                    continue
                try:
                    handles[tag] = router.submit(
                        rec["prompt"], rec.get("max_new_tokens", 32),
                        deadline_s=rec.get("deadline_s"), tag=tag)
                except RouterOverloaded as exc:
                    failed += 1
                    print(json.dumps({"tag": tag, "error":
                                      "RouterOverloaded",
                                      "message": str(exc)[:120]}))
        for tag, h in handles.items():
            try:
                print(json.dumps({
                    "tag": tag,
                    "tokens": h.result(timeout=args.result_timeout),
                    "stats": h.stats()}))
            except Exception as exc:  # noqa: BLE001 — reported per request
                failed += 1
                print(json.dumps({"tag": tag,
                                  "error": type(exc).__name__,
                                  "message": str(exc)[:200]}))
    finally:
        router.stop(shutdown_replicas=not args.keep)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
