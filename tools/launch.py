#!/usr/bin/env python
"""Multi-process training launcher (reference tools/launch.py +
3rdparty/dmlc-core/tracker, SURVEY N26/P22/§3.4).

The reference spawns scheduler/server/worker processes over
ssh/mpi/sge/yarn and wires them with DMLC_* env vars.  The TPU-native
stack has NO server or scheduler processes (SURVEY §7.1 N13/N14/N17 rows):
``jax.distributed`` needs only a coordinator address and one process per
host, so this launcher:

 - ``--launcher local`` (default): fork N worker processes on this machine
   — the integration-test path, mirroring the reference's
   ``--launcher local`` used by ``tests/nightly/dist_sync_kvstore.py``.
   Each worker gets MXNET_DIST_COORDINATOR / MXNET_DIST_RANK /
   MXNET_DIST_NUM_WORKERS (read by ``kvstore.create('dist_tpu_sync')``)
   plus JAX CPU-platform vars so a laptop run uses N virtual CPU workers.
 - ``--launcher ssh``: real ssh fan-out (the dmlc_tracker/ssh.py role):
   one worker per hostfile line (round-robin if -n exceeds the host
   count), rank/coordinator env inlined into the remote command, all
   workers awaited with the same straggler-kill policy as local mode.
   ``--dry-run`` prints the exact ssh commands instead of running them
   (useful on pods where the cloud runtime is the launcher).

Usage:
  python tools/launch.py -n 2 python train.py --kv-store dist_tpu_sync
  python tools/launch.py -n 4 --launcher ssh -H hosts.txt python train.py
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _await_workers(procs, timeout):
    """Wait for workers; one hung worker must not hang the launch: after
    ``timeout`` seconds (or once any worker fails, after a short grace)
    stragglers are killed and reported with code -9."""
    import time as _time
    codes = [None] * len(procs)
    deadline = _time.time() + timeout
    while any(c is None for c in codes):
        for i, p in enumerate(procs):
            if codes[i] is None:
                codes[i] = p.poll()
        if all(c is not None for c in codes):
            break
        if _time.time() > deadline or any(c not in (None, 0) for c in codes):
            # timeout, or a peer already failed (collectives would hang):
            # give stragglers a short grace, then kill
            grace = min(deadline, _time.time() + 15)
            while _time.time() < grace and any(
                    p.poll() is None for p in procs):
                _time.sleep(0.2)
            for i, p in enumerate(procs):
                if p.poll() is None:
                    p.kill()
                    codes[i] = -9
                else:
                    codes[i] = p.returncode
            break
        _time.sleep(0.2)
    return codes


def _cpu_device_env(n_devices, base_flags=""):
    """Env overrides forcing a worker onto n virtual CPU devices."""
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (f"{base_flags} --xla_force_host_platform_device_"
                      f"count={n_devices}").strip(),
    }


def launch_local(n, command, env_extra=None, cpu_devices_per_worker=None,
                 timeout=600):
    """Spawn n local worker processes; returns their exit codes."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["MXNET_DIST_COORDINATOR"] = coord
        env["MXNET_DIST_NUM_WORKERS"] = str(n)
        env["MXNET_DIST_RANK"] = str(rank)
        if cpu_devices_per_worker:
            env.update(_cpu_device_env(cpu_devices_per_worker,
                                       env.get("XLA_FLAGS", "")))
        procs.append(subprocess.Popen(command, env=env))
    return _await_workers(procs, timeout)


def build_ssh_commands(n, hosts, command, port=29400, env_extra=None,
                       ssh_opts=()):
    """Per-rank ``ssh`` argv lists (dmlc_tracker/ssh.py role): rank r runs
    on hosts[r % len(hosts)]; the coordinator is hosts[0]:port; env rides
    inline `env K=V ...` so no remote shell profile is required."""
    import shlex
    if not hosts:
        raise ValueError("ssh launcher needs a hostfile with >= 1 host")
    coord = f"{hosts[0]}:{port}"
    cmds = []
    for rank in range(n):
        host = hosts[rank % len(hosts)]
        envs = {"MXNET_DIST_COORDINATOR": coord,
                "MXNET_DIST_NUM_WORKERS": str(n),
                "MXNET_DIST_RANK": str(rank)}
        envs.update(env_extra or {})
        remote = "env " + " ".join(
            f"{k}={shlex.quote(v)}" for k, v in sorted(envs.items()))
        remote += " " + " ".join(shlex.quote(c) for c in command)
        cmds.append(["ssh", "-o", "StrictHostKeyChecking=no",
                     *ssh_opts, host, remote])
    return cmds


def launch_ssh(n, hosts, command, port=29400, env_extra=None,
               timeout=600, dry_run=False):
    """ssh fan-out: spawn one remote worker per rank and await them with
    the same straggler-kill policy as local mode."""
    cmds = build_ssh_commands(n, hosts, command, port=port,
                              env_extra=env_extra)
    if dry_run:
        for c in cmds:
            print(" ".join(c))
        return [0] * n
    procs = [subprocess.Popen(c) for c in cmds]
    return _await_workers(procs, timeout)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="launch multi-process mxnet_tpu training "
                    "(reference tools/launch.py analog)")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference-CLI parity; the TPU stack "
                         "has no server processes (optimizer stays on "
                         "device) so this must be 0")
    ap.add_argument("--launcher", choices=["local", "ssh"], default="local")
    ap.add_argument("-H", "--hostfile", default=None,
                    help="hostfile (one host per line) for --launcher ssh")
    ap.add_argument("-p", "--port", type=int, default=29400,
                    help="coordinator port (ssh launcher)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the ssh commands instead of running them")
    ap.add_argument("--timeout", type=int, default=600,
                    help="seconds before stragglers are killed")
    ap.add_argument("--cpu-devices", type=int, default=None,
                    help="force each worker onto N virtual CPU devices "
                         "(testing without TPUs)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="training command to run on every worker")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no training command given")
    if args.num_servers:
        ap.error("dist_tpu_sync has no server role: run with -s 0 "
                 "(the optimizer stays on device; SURVEY §7.1)")

    if args.launcher == "ssh":
        if not args.hostfile:
            ap.error("--launcher ssh needs -H/--hostfile")
        with open(args.hostfile) as f:
            hosts = [s for s in (h.strip() for h in f)
                     if s and not s.startswith("#")]
        env_extra = _cpu_device_env(args.cpu_devices) \
            if args.cpu_devices else None
        codes = launch_ssh(args.num_workers, hosts, args.command,
                           port=args.port, timeout=args.timeout,
                           env_extra=env_extra, dry_run=args.dry_run)
    else:
        codes = launch_local(args.num_workers, args.command,
                             cpu_devices_per_worker=args.cpu_devices,
                             timeout=args.timeout)
    bad = [c for c in codes if c != 0]
    if bad:
        print(f"launch: {len(bad)}/{len(codes)} workers failed "
              f"(codes {codes})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
