#!/usr/bin/env python3
"""telemetry_report — merge a job's telemetry collection dir and report.

Every process of a run with ``MXNET_TELEMETRY_DIR`` set leaves one
rank-tagged snapshot (``telemetry-rank*-pid*.json``) in the collection
directory — at exit, and on every flight-recorder dump.  This CLI is the
rank-0 / offline side of the protocol:

    python tools/telemetry_report.py --dir /path/to/telemetry
    python tools/telemetry_report.py --dir DIR --trace merged_trace.json \\
        --prom merged.prom
    python tools/telemetry_report.py --dir DIR --json

It prints a per-rank table (spans, steps, step-phase medians, bottleneck
verdict, headline counters), the job-wide verdict tally, and optionally
writes the merged Chrome trace (``pid`` = rank, Perfetto-labeled) and the
merged Prometheus snapshot (counters/histograms summed across ranks).

Loads ``mxnet_tpu.telemetry`` standalone (the graftcheck trick), so it
runs without jax installed.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_telemetry():
    """Load mxnet_tpu.telemetry (+ its config dependency) under private
    names so mxnet_tpu's package __init__ (which imports jax) never runs."""
    if "mxnet_tpu" in sys.modules:
        return importlib.import_module("mxnet_tpu.telemetry")
    pkg_name = "_telemetry_report_pkg"
    pkg = sys.modules.get(pkg_name)
    if pkg is None:
        pkg = types.ModuleType(pkg_name)
        pkg.__path__ = [os.path.join(REPO_ROOT, "mxnet_tpu")]
        sys.modules[pkg_name] = pkg
    return importlib.import_module(pkg_name + ".telemetry")


def _fmt_ms(v):
    return f"{v * 1e3:.3f}"


def _rank_row(snap):
    sc = snap.get("stepclock") or {}
    phases = sc.get("phases") or {}
    meds = {p: (phases.get(p) or {}).get("median", 0.0)
            for p in ("data_wait", "h2d", "compute", "comms", "optimizer",
                      "total")}
    counters = {}
    for e in snap.get("metrics", ()):
        if e.get("kind") == "counter" and e.get("value"):
            counters[e["name"]] = e["value"]
    return {
        "rank": snap.get("rank"),
        "pid": snap.get("pid"),
        "host": snap.get("host"),
        "spans": len(snap.get("events") or ()),
        "steps": sc.get("steps", 0),
        "verdict": sc.get("verdict", "idle"),
        "phase_median_ms": {p: round(v * 1e3, 3) for p, v in meds.items()},
        "counters": counters,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge + report a MXNET_TELEMETRY_DIR collection")
    ap.add_argument("--dir", default=os.environ.get("MXNET_TELEMETRY_DIR"),
                    help="collection directory "
                         "(default: $MXNET_TELEMETRY_DIR)")
    ap.add_argument("--trace", metavar="PATH",
                    help="write the merged Chrome trace JSON here")
    ap.add_argument("--prom", metavar="PATH",
                    help="write the merged Prometheus snapshot here")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    ap.add_argument("--all-shards", action="store_true",
                    help="keep every shard (default: newest per rank)")
    ap.add_argument("--cost", action="store_true",
                    help="also render each rank's analytic cost ledger "
                         "(per-site flops / arithmetic intensity / "
                         "peak-HBM / roofline verdict)")
    ap.add_argument("--perf-diff", metavar="BASELINE",
                    help="diff every rank's exported cost ledger against "
                         "a committed perfgate baseline "
                         "(tests/perf_baseline.json); exit 2 on drift")
    args = ap.parse_args(argv)
    if not args.dir:
        ap.error("no collection dir: pass --dir or set MXNET_TELEMETRY_DIR")

    telemetry = _load_telemetry()
    agg = telemetry.aggregate
    snaps = agg.load_snapshots(args.dir,
                               latest_per_rank=not args.all_shards)
    if not snaps:
        print(f"no telemetry snapshots under {args.dir}", file=sys.stderr)
        return 1

    rows = [_rank_row(s) for s in snaps]
    if args.cost:
        cm = telemetry.costmodel
        for r, s in zip(rows, snaps):
            block = s.get("costmodel") or {}
            summ = cm.summarize_entries(block.get("entries") or (),
                                        block.get("calls") or {})
            for site, v in summ.items():
                v.update(cm.roofline(v["flops"], v["bytes_accessed"]))
            r["cost"] = summ
    if args.json:
        print(json.dumps({"ranks": rows}, indent=1))
    else:
        print(f"telemetry report — {len(rows)} rank(s) from {args.dir}")
        hdr = (f"  {'rank':>4} {'steps':>5} {'spans':>6} {'verdict':<14} "
               f"{'data_wait':>10} {'h2d':>8} {'compute':>9} {'comms':>8} "
               f"{'optimizer':>10}   (median ms)")
        print(hdr)
        for r in rows:
            m = r["phase_median_ms"]
            print(f"  {r['rank']:>4} {r['steps']:>5} {r['spans']:>6} "
                  f"{r['verdict']:<14} {m['data_wait']:>10.3f} "
                  f"{m['h2d']:>8.3f} {m['compute']:>9.3f} "
                  f"{m['comms']:>8.3f} {m['optimizer']:>10.3f}")
        tally: dict = {}
        for r in rows:
            tally[r["verdict"]] = tally.get(r["verdict"], 0) + 1
        job = max(tally, key=tally.get)
        print(f"job verdict: {job} "
              f"({', '.join(f'{k}×{v}' for k, v in sorted(tally.items()))})")
        if args.cost:
            cm = telemetry.costmodel
            for r in rows:
                if not r.get("cost"):
                    continue
                print(f"cost ledger — rank {r['rank']}:")
                for line in cm.site_table_lines(r["cost"]):
                    print(line)

    if args.perf_diff:
        # post-mortem gate (ISSUE 16 satellite): dumps from elastic /
        # router runs diffed offline against the committed analytic
        # baseline — per-site flops/bytes/peak-HBM only, since a shard
        # captures one workload, not the gate's lane matrix
        pg = telemetry.perfgate
        cm = telemetry.costmodel
        try:
            base = pg.load_baseline(args.perf_diff)
        except pg.BaselineError as e:
            print(f"perf-diff: {e}", file=sys.stderr)
            return 2
        drifted = False
        for s in snaps:
            block = s.get("costmodel") or {}
            summ = cm.summarize_entries(block.get("entries") or (),
                                        block.get("calls") or {})
            counters = {e.get("name"): e.get("value")
                        for e in s.get("metrics", ())
                        if e.get("kind") == "counter" and e.get("value")}
            delta = pg.live_delta(base, summ, counters)
            drifted = drifted or not delta["ok"]
            if args.json:
                print(json.dumps({"rank": s.get("rank"),
                                  "perf_diff": delta}, indent=1,
                                 sort_keys=True))
                continue
            print(f"perf-diff — rank {s.get('rank')} vs {args.perf_diff} "
                  f"({delta['overlap_sites']} overlapping sites):")
            for lane, v in sorted(delta["lanes"].items()):
                if v["verdict"] == "no-overlap":
                    continue
                print(f"  [{v['verdict'].upper():<5}] {lane}")
                for f in v["failures"][:8]:
                    rel = f" (rel {f['rel']:+.2%})" if "rel" in f else ""
                    print(f"      {f['metric']}: baseline={f['base']!r} "
                          f"live={f['got']!r}{rel}")
        if drifted:
            print("perf-diff verdict: DRIFT", file=sys.stderr)
            return 2
        print("perf-diff verdict: ok")

    if args.trace:
        with open(args.trace, "w") as f:
            json.dump(agg.merged_chrome_trace(snaps), f)
        print(f"merged Chrome trace -> {args.trace}")
    if args.prom:
        with open(args.prom, "w") as f:
            f.write(agg.merged_prometheus(snaps))
        print(f"merged Prometheus snapshot -> {args.prom}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
