#!/usr/bin/env python
"""im2rec — pack an image folder (or .lst file) into RecordIO shards
(reference tools/im2rec.py / im2rec.cc, N27).

Two passes like the reference:
  1. ``--list``: walk an image root, assign integer labels per
     subdirectory, write ``prefix.lst`` (``idx\\tlabel\\trelpath`` rows,
     the reference's tab format).
  2. default: read ``prefix.lst``, encode each image (cv2 JPEG, falling
     back to raw PIL bytes when cv2 is unavailable) and append
     ``IRHeader + payload`` records to ``prefix.rec`` with a
     ``prefix.idx`` index — the exact byte format
     ``mx.recordio.MXIndexedRecordIO``/``ImageRecordIter`` consume.

Usage:
  python tools/im2rec.py --list data/train data/imgs
  python tools/im2rec.py data/train data/imgs --quality 90
"""

from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def make_list(prefix, root, shuffle=True, seed=0):
    """Pass 1: folder → .lst (label per subdirectory, sorted)."""
    root = os.path.abspath(root)
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    rows = []
    if classes:
        for c in classes:
            cdir = os.path.join(root, c)
            for f in sorted(os.listdir(cdir)):
                if os.path.splitext(f)[1].lower() in _EXTS:
                    rows.append((label_of[c], os.path.join(c, f)))
    else:  # flat folder: label 0
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in _EXTS:
                rows.append((0, f))
    if shuffle:
        random.Random(seed).shuffle(rows)
    lst = prefix + ".lst"
    with open(lst, "w") as f:
        for i, (label, rel) in enumerate(rows):
            f.write(f"{i}\t{float(label)}\t{rel}\n")
    return lst, len(rows), classes


def read_list(path, pack_label=False):
    """.lst rows: idx \\t label... \\t relpath.  With ``pack_label`` every
    middle column becomes a float vector label (the detection format:
    [A, B, extras, (cls x0 y0 x1 y1)*N] — reference im2rec.py
    --pack-label)."""
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            if pack_label:
                label = [float(x) for x in parts[1:-1]]
            else:
                label = float(parts[1])
            yield int(parts[0]), label, parts[-1]


def _encode(img_path, quality, resize=0):
    try:
        import cv2
        img = cv2.imread(img_path, cv2.IMREAD_COLOR)
        if img is None:
            return None
        if resize:
            h, w = img.shape[:2]
            s = resize / min(h, w)
            img = cv2.resize(img, (int(w * s), int(h * s)))
        ok, buf = cv2.imencode(".jpg", img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        return buf.tobytes() if ok else None
    except ImportError:
        with open(img_path, "rb") as f:
            return f.read()  # pass through already-encoded bytes


def make_rec(prefix, root, quality=95, resize=0, pack_label=False):
    """Pass 2: .lst → .rec/.idx (IRHeader-packed JPEG records); with
    ``pack_label`` the header carries the full float label vector
    (detection boxes — fed by mx.image.ImageDetIter)."""
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n, skipped = 0, 0
    for idx, label, rel in read_list(prefix + ".lst", pack_label=pack_label):
        payload = _encode(os.path.join(root, rel), quality, resize)
        if payload is None:
            skipped += 1
            continue
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, payload))
        n += 1
    rec.close()
    return n, skipped


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate prefix.lst instead of packing")
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter edge to N pixels (0 = keep)")
    ap.add_argument("--pack-label", action="store_true",
                    help="pack every middle .lst column as a float vector "
                         "label (detection boxes)")
    args = ap.parse_args(argv)
    if args.list:
        lst, n, classes = make_list(args.prefix, args.root,
                                    shuffle=not args.no_shuffle)
        print(f"wrote {lst}: {n} images, {len(classes)} classes")
        return 0
    if not os.path.exists(args.prefix + ".lst"):
        make_list(args.prefix, args.root, shuffle=not args.no_shuffle)
    n, skipped = make_rec(args.prefix, args.root, args.quality, args.resize,
                          pack_label=args.pack_label)
    print(f"wrote {args.prefix}.rec: {n} records ({skipped} skipped)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
