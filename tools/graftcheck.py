#!/usr/bin/env python3
"""graftcheck CLI — repo-native static analysis, importable without the
mxnet_tpu runtime.

CI runs this lane before any dependency install, so this launcher loads
``mxnet_tpu/analysis/{core,passes}.py`` as a standalone package instead
of importing ``mxnet_tpu`` (whose __init__ pulls in jax).  With the
runtime available, ``python -m mxnet_tpu.analysis.core`` paths work too.

    python tools/graftcheck.py                 # scan mxnet_tpu/
    python tools/graftcheck.py mxnet_tpu/ --json
    python tools/graftcheck.py --list-rules
    python tools/graftcheck.py --write-baseline graftcheck-baseline.json
    python tools/graftcheck.py --baseline graftcheck-baseline.json

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

import importlib
import os
import sys
import types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS_DIR = os.path.join(REPO_ROOT, "mxnet_tpu", "analysis")


def _load_analysis():
    """Load the analysis package under a private name so ``mxnet_tpu``'s
    package __init__ (which imports jax) never runs."""
    if "mxnet_tpu.analysis" in sys.modules:
        return sys.modules["mxnet_tpu.analysis"]
    pkg_name = "_graftcheck_analysis"
    pkg = sys.modules.get(pkg_name)
    if pkg is None:
        pkg = types.ModuleType(pkg_name)
        pkg.__path__ = [_ANALYSIS_DIR]
        sys.modules[pkg_name] = pkg
    importlib.import_module(pkg_name + ".passes")  # registers GC01–GC05
    return importlib.import_module(pkg_name + ".core")


def main(argv=None):
    core = _load_analysis()
    return core.main(argv, repo_root=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
