"""Base utilities: error types, dtype tables, handle plumbing.

TPU-native rebuild of the role played by the reference's ``python/mxnet/base.py``
(ctypes ``_LIB`` loading, ``check_call``, ``MXNetError``) and parts of
``include/mxnet/base.h``.  There is no C ABI here — the "backend" is JAX/XLA —
so this module keeps only the *semantic* surface: the error type every API
raises, the canonical dtype table (MXNet type-flag integers preserved for
``.params`` serialization compat), and small shared helpers.

Reference anchors: python/mxnet/base.py :: MXNetError, _LIB, check_call;
include/mxnet/base.h :: Context (dev type enums).
"""

from __future__ import annotations

import numpy as _np

__all__ = [
    "MXNetError",
    "NotSupportedForSparseNDArray",
    "string_types",
    "numeric_types",
    "integer_types",
    "DTYPE_ID_TO_NP",
    "NP_TO_DTYPE_ID",
    "mx_real_t",
    "mx_uint",
    "check_call",
]


class MXNetError(RuntimeError):
    """Default error type for all mxnet_tpu API failures.

    The reference surfaces C++ ``dmlc::Error`` through ``MXGetLastError`` and
    re-raises it as ``MXNetError``; here errors originate in Python/JAX but the
    public type is preserved so user ``except MXNetError`` code keeps working.
    """


class NotSupportedForSparseNDArray(MXNetError):
    def __init__(self, function, alias, *args):
        argstr = ", ".join(str(a) for a in args)
        super().__init__(
            f"Function {getattr(function, '__name__', function)} "
            f"(alias {alias}) with arguments ({argstr}) is not supported for SparseNDArray"
        )


string_types = (str,)
integer_types = (int, _np.integer)
numeric_types = (float, int, _np.generic)

# MXNet dtype type-flag table (src/common/utils.h / mshadow type switch order).
# Preserved verbatim so the `.params` binary format round-trips with reference
# checkpoints.  bfloat16 uses the 1.x extension slot (12) used by AMP-era forks.
DTYPE_ID_TO_NP = {
    0: _np.float32,
    1: _np.float64,
    2: _np.float16,
    3: _np.uint8,
    4: _np.int32,
    5: _np.int8,
    6: _np.int64,
    7: _np.bool_,
    8: _np.int16,
    9: _np.uint16,
    10: _np.uint32,
    11: _np.uint64,
    12: "bfloat16",  # resolved lazily against ml_dtypes below
}

try:  # bfloat16 numpy dtype ships with jax via ml_dtypes
    import ml_dtypes as _ml_dtypes

    bfloat16 = _np.dtype(_ml_dtypes.bfloat16)
    DTYPE_ID_TO_NP[12] = bfloat16
except ImportError:  # pragma: no cover - ml_dtypes is a jax hard dep
    bfloat16 = None

NP_TO_DTYPE_ID = {}
for _k, _v in DTYPE_ID_TO_NP.items():
    try:
        NP_TO_DTYPE_ID[_np.dtype(_v)] = _k
    except TypeError:
        pass

mx_real_t = _np.float32
mx_uint = _np.uint32


def check_call(ret):
    """Compatibility shim for reference-style ``check_call(_LIB.MX...)`` code.

    In the reference every C-ABI call returns an int status checked here.  We
    keep the function so mechanical call sites survive, but the only accepted
    value is 0/None (success).
    """
    if ret:  # non-zero status
        raise MXNetError(f"backend call failed with status {ret}")


def dtype_from_any(dtype):
    """Normalize str/np.dtype/type-flag int into a numpy dtype."""
    if dtype is None:
        return _np.dtype(mx_real_t)
    if isinstance(dtype, int) and not isinstance(dtype, bool):
        if dtype not in DTYPE_ID_TO_NP:
            raise MXNetError(f"unknown dtype type-flag {dtype}")
        return _np.dtype(DTYPE_ID_TO_NP[dtype])
    return _np.dtype(dtype)


def dtype_to_id(dtype):
    d = _np.dtype(dtype)
    if d not in NP_TO_DTYPE_ID:
        raise MXNetError(f"dtype {d} has no MXNet type-flag (not serializable)")
    return NP_TO_DTYPE_ID[d]
