"""Pipeline parallelism (GPipe-style) over a TPU mesh axis.

NEW capability relative to the reference: SURVEY §2.4 flags pipeline
parallelism ABSENT upstream (nothing beyond manual ``group2ctx`` placement +
engine async overlap — no GPipe/1F1B schedule anywhere).  The TPU-native
design follows the scaling-book recipe rather than any reference code:

 - the model's homogeneous trunk (e.g. transformer layers) is split into
   ``n_stages`` stages whose parameters are **stacked** along a leading
   stage dimension and sharded over a ``'pp'`` mesh axis — one stage per
   device group;
 - microbatches flow through the stages on a ``lax.scan`` schedule; stage
   boundaries are ``lax.ppermute`` shifts that ride ICI;
 - the whole schedule is a pure function, so ``jax.grad`` through it yields
   the reverse (backward) pipeline automatically — GPipe semantics
   (all-forward, all-backward) with XLA overlapping the bubble where it can;
 - combining with data parallelism is just a 2-D mesh ('dp','pp'): batch
   sharded over 'dp', stage params over 'pp'.

Embedding/head layers (whose activation shapes differ from the trunk's)
stay outside the pipelined region, exactly like megatron-style stacks.

The schedule: with S stages and M microbatches, tick t ∈ [0, S+M-1):
stage 0 feeds microbatch t (while t < M), stage s computes the activation
it received from stage s-1 at tick t-1, and stage S-1 emits the output for
microbatch t-(S-1).  Bubble fraction = (S-1)/(M+S-1), the GPipe bound.
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError

__all__ = ["gpipe", "pipeline_apply", "stack_blocks", "PipelinedBlock"]


def _shard_map():
    """(jax, shard_map) with the replication check normalized — single
    definition lives in kernels.shard_map_compat."""
    import jax
    from .kernels import shard_map_compat
    return jax, shard_map_compat()


def gpipe(stage_fn, n_stages, n_microbatches, mesh, axis="pp",
          data_axis=None):
    """Build the SPMD GPipe schedule for a homogeneous stage function.

    Parameters
    ----------
    stage_fn : callable ``(stage_params, activation) -> activation``
        One pipeline stage.  Must preserve the activation shape (pipeline
        the homogeneous trunk; put embedding/head outside).
    n_stages : int — must equal the mesh's ``axis`` size.
    n_microbatches : int — microbatches per call; the global batch dim must
        divide by it.
    mesh : DeviceMesh with a ``'pp'`` (or ``axis``) axis.
    axis : name of the pipeline mesh axis.
    data_axis : optional name of a data-parallel axis; when given, the
        activation batch dim is sharded over it as well.

    Returns
    -------
    ``fn(stacked_params, x) -> y`` — jit-compiled; ``stacked_params`` is a
    pytree whose leaves have leading dim ``n_stages`` (sharded over
    ``axis``), ``x`` the trunk input ``(batch, ...)``.  Differentiable.
    """
    jax, shard_map = _shard_map()
    import jax.numpy as jnp

    if mesh.axis_size(axis) != n_stages:
        raise MXNetError(
            f"gpipe: mesh axis {axis!r} has size {mesh.axis_size(axis)}, "
            f"need n_stages={n_stages}")
    S, M = int(n_stages), int(n_microbatches)

    def schedule(params_stacked, x):
        # local views: leading stage dim is 1 on each pp group
        params = jax.tree_util.tree_map(lambda p: p[0], params_stacked)
        idx = jax.lax.axis_index(axis)
        b = x.shape[0]
        micro = x.reshape((M, b // M) + x.shape[1:])
        zero = jnp.zeros_like(micro[0])
        shift_perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            state, outbuf = carry
            feed = jnp.where(t < M, micro[jnp.minimum(t, M - 1)], zero)
            inp = jnp.where(idx == 0, feed, state)
            y = stage_fn(params, inp)
            m = t - (S - 1)
            valid = jnp.logical_and(m >= 0, idx == S - 1)
            upd = jax.lax.dynamic_update_slice(
                outbuf, y[None].astype(outbuf.dtype),
                (jnp.maximum(m, 0),) + (0,) * y.ndim)
            outbuf = jnp.where(valid, upd, outbuf)
            if S > 1:
                state = jax.lax.ppermute(y, axis, shift_perm)
            else:
                state = y
            return (state, outbuf), None

        (_, outbuf), _ = jax.lax.scan(
            tick, (zero, jnp.zeros_like(micro)), jnp.arange(S + M - 1))
        # only the last stage wrote non-zeros; psum replicates the result
        # across the pipeline axis (grad of psum = identity broadcast)
        out = jax.lax.psum(outbuf, axis)
        return out.reshape(x.shape)

    P = jax.sharding.PartitionSpec
    stage_spec = P(axis)
    act_spec = P(data_axis) if data_axis else P()

    dp = mesh.axis_size(data_axis) if data_axis else 1

    def wrapped(params_stacked, x):
        # validate up front: a non-divisible batch otherwise fails deep
        # inside shard_map with an opaque jax reshape error
        bglobal = x.shape[0]
        if bglobal % dp != 0 or (bglobal // dp) % M != 0:
            raise MXNetError(
                f"gpipe: batch {bglobal} (/{dp} data-parallel shards -> "
                f"{bglobal // dp if bglobal % dp == 0 else bglobal}/shard) "
                f"must be divisible by n_microbatches={M}")
        in_specs = (jax.tree_util.tree_map(lambda _: stage_spec,
                                           params_stacked), act_spec)
        f = shard_map(schedule, mesh=mesh.mesh, in_specs=in_specs,
                      out_specs=act_spec)
        return f(params_stacked, x)

    return jax.jit(wrapped)


def pipeline_apply(stage_fn, stacked_params, x, mesh, n_microbatches=None,
                   axis="pp", data_axis=None):
    """One-shot convenience wrapper over :func:`gpipe` (builds + calls)."""
    import jax
    leaves = jax.tree_util.tree_leaves(stacked_params)
    n_stages = leaves[0].shape[0]
    if n_microbatches is None:
        n_microbatches = max(2 * n_stages, 1)
    fn = gpipe(stage_fn, n_stages, n_microbatches, mesh, axis=axis,
               data_axis=data_axis)
    return fn(stacked_params, x)


# --------------------------------------------------------------------------
# Gluon bridge: stack identically-structured blocks into one stage pytree
# --------------------------------------------------------------------------

def stack_blocks(blocks, probe):
    """Stack N identically-structured Gluon blocks into (stage_fn, params).

    ``blocks`` — a list of HybridBlocks with identical parameter structure
    (e.g. N transformer encoder cells).  ``probe`` — an example activation
    NDArray used to finish deferred shape inference.

    Returns ``(stage_fn, stacked)``: ``stacked`` is a dict name→jnp array
    with leading dim N; ``stage_fn(params, x)`` runs ONE stage functionally
    by temporarily pointing the template block's parameter slots at the
    traced values (the same slot-swap discipline TrainStep uses).
    """
    import jax.numpy as jnp
    from . import autograd
    from .ndarray.ndarray import NDArray

    template = blocks[0]
    with autograd.pause():
        for blk in blocks:
            blk(probe)  # deferred init
    names = list(template.collect_params().keys())
    per_block = []
    for blk in blocks:
        ps = blk.collect_params()
        ks = list(ps.keys())
        if len(ks) != len(names):
            raise MXNetError("stack_blocks: blocks differ in structure")
        per_block.append([ps[k].data()._data for k in ks])
    stacked = {
        name: jnp.stack([vals[i] for vals in per_block])
        for i, name in enumerate(names)}
    t_params = [template.collect_params()[k] for k in names]

    from .ndarray.ndarray import swap_slot_values

    def stage_fn(params, x):
        with swap_slot_values((p._data, params[name])
                              for p, name in zip(t_params, names)):
            out = template(NDArray._from_data(x))
            return out._data

    return stage_fn, stacked


class PipelinedBlock:
    """Pipeline-parallel wrapper for a homogeneous stack of Gluon blocks.

    ``PipelinedBlock(blocks, mesh, n_microbatches)`` shards the blocks'
    stacked parameters over the mesh's ``'pp'`` axis and exposes a callable
    ``(x) -> y`` running the GPipe schedule.  Used for the trunk of a deep
    model; compose embedding/head around it.
    """

    def __init__(self, blocks, mesh, n_microbatches=None, axis="pp",
                 data_axis=None):
        self.blocks = list(blocks)
        self.mesh = mesh
        self.axis = axis
        self.data_axis = data_axis
        self.n_stages = len(self.blocks)
        self.n_microbatches = n_microbatches or 2 * self.n_stages
        self._fn = None
        self._stage_fn = None
        self.stacked = None

    def _build(self, probe_nd):
        import jax
        self._stage_fn, self.stacked = stack_blocks(self.blocks, probe_nd)
        stage_sh = self.mesh.sharded(self.axis)
        self.stacked = {k: jax.device_put(v, stage_sh)
                        for k, v in self.stacked.items()}
        self._fn = gpipe(self._stage_fn, self.n_stages, self.n_microbatches,
                         self.mesh, axis=self.axis, data_axis=self.data_axis)

    def __call__(self, x):
        from . import ndarray as nd
        from .ndarray.ndarray import NDArray
        if not isinstance(x, NDArray):
            x = nd.array(x)
        if self._fn is None:
            probe = NDArray._from_data(x._data[:max(1, x.shape[0] //
                                                    self.n_microbatches)])
            self._build(probe)
        import jax
        act_sh = self.mesh.sharded(self.data_axis) if self.data_axis \
            else self.mesh.replicated()
        xv = jax.device_put(x._data, act_sh)
        return NDArray._from_data(self._fn(self.stacked, xv))
