"""mx.rtc — user runtime-compiled kernels (reference python/mxnet/rtc.py).

Explicitly DROPPED on TPU with rationale (the SURVEY §7.4 three-way
ledger): the reference's CudaModule compiles user CUDA C source via NVRTC
at runtime; there is no CUDA on this stack, and the TPU-native equivalent
of a hand kernel is a Pallas kernel (see ``mxnet_tpu/kernels/`` for
worked examples) registered as a custom op via ``mx.operator.CustomOp``
or used directly.  Importing the module works; constructing its classes
raises with this guidance, mirroring how other dropped subsystems behave.
"""

from __future__ import annotations

from .base import MXNetError

__all__ = ["CudaModule", "CudaKernel"]

_MSG = ("mx.rtc is CUDA-specific and not part of the TPU rebuild: write a "
        "Pallas kernel instead (patterns in mxnet_tpu/kernels/) and expose "
        "it as a custom op via mx.operator.CustomOp")


class CudaModule:
    def __init__(self, *args, **kwargs):  # noqa: ARG002
        raise MXNetError(_MSG)


class CudaKernel:
    def __init__(self, *args, **kwargs):  # noqa: ARG002
        raise MXNetError(_MSG)
