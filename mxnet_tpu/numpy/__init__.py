"""mx.np — NumPy-compatible array API (reference python/mxnet/numpy/, P3).

The reference maintains a parallel ~80k-LoC operator corpus
(src/operator/numpy/*) mirroring NumPy semantics.  TPU-native rebuild: mx.np
delegates straight to jax.numpy — which IS a NumPy-semantics operator corpus
compiled by XLA — wrapping results in the same versioned-slot NDArray
(presented as mx.np.ndarray).  Autograd records through the same tape: every
mx.np function dispatches via a registry op, so record()/backward(), hybridize
tracing and the profiler all see np ops like nd ops.

``npx.set_np()`` (mxnet_tpu.util.set_np) flips Gluon blocks to np arrays —
here nd and np share one array type, so the switch only changes namespace
semantics (e.g. zero-dim shapes are always supported).
"""

from __future__ import annotations

import builtins as _builtins  # this module shadows any/all/min/max/sum
import sys as _sys
import types as _types

import numpy as _onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array as _nd_array
from ..ops import registry as _reg
from ..context import current_context

ndarray = NDArray  # mx.np.ndarray is the same array type

_float32 = _onp.float32
float32 = _onp.float32
float64 = _onp.float64
float16 = _onp.float16
int8 = _onp.int8
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
pi = _onp.pi
inf = _onp.inf
nan = _onp.nan
newaxis = None


def array(object, dtype=None, ctx=None):
    """np-semantics dtype inference: ints stay integral (reference mx.np
    keeps int64 for python ints, float32 for python floats).  Sources
    that carry an explicit numpy dtype keep it — downcasting a float64
    ndarray would silently lose precision (x64 mode is on)."""
    if dtype is None and not isinstance(object, NDArray) \
            and not hasattr(object, "dtype"):
        inferred = _onp.asarray(object).dtype
        dtype = _onp.float32 if inferred.kind == "f" else inferred
    return _nd_array(object, ctx=ctx, dtype=dtype)


def _wrap_jnp(name, jfn):
    """Expose a jax.numpy function as a recorded registry op."""
    opname = f"np.{name}"
    try:
        op = _reg.get(opname)
    except MXNetError:
        def impl(*arrays, **kw):
            return jfn(*arrays, **kw)
        impl.__name__ = name
        op = _reg.Op(opname, impl, num_outputs=-1, jit=False,
                     doc=getattr(jfn, "__doc__", None))
        _reg._REGISTRY[opname] = op

    def fn(*args, **kwargs):
        # NDArrays may arrive bare or inside a list/tuple (np.concatenate
        # etc.) — collect them as op inputs and rebuild the call spec
        inputs = []
        spec = []
        for a in args:
            if isinstance(a, NDArray):
                spec.append(("arr", None))
                inputs.append(a)
            elif isinstance(a, (list, tuple)) and \
                    _builtins.any(isinstance(x, NDArray) for x in a):
                sub = []
                for x in a:
                    if isinstance(x, NDArray):
                        sub.append(None)
                        inputs.append(x)
                    else:
                        sub.append(x)
                spec.append(("seq", (type(a), sub)))
            else:
                spec.append(("lit", a))
        if not inputs:
            import jax.numpy as jnp
            out = jfn(*args, **kwargs)
            if hasattr(out, "dtype"):
                return NDArray._from_data(jnp.asarray(out),
                                          ctx=current_context())
            return out
        # dispatch through invoke so autograd/tracing see it; non-array
        # positional args are bound via a closure attr
        def bound(*arrs, _kw=tuple(sorted(kwargs.items()))):
            it = iter(arrs)
            full = []
            for kind, payload in spec:
                if kind == "arr":
                    full.append(next(it))
                elif kind == "lit":
                    full.append(payload)
                else:
                    t, sub = payload
                    full.append(t(next(it) if s is None else s for s in sub))
            return jfn(*full, **dict(_kw))
        call_op = _reg.Op(opname, bound, num_outputs=-1, jit=False)
        res = _reg.invoke(call_op, inputs, {})
        return res
    fn.__name__ = name
    fn.__doc__ = getattr(jfn, "__doc__", None)
    return fn


_NP_FUNCS = [
    # creation / manipulation
    "zeros", "ones", "full", "empty", "arange", "linspace", "logspace",
    "zeros_like", "ones_like", "full_like", "empty_like", "copy",
    "eye", "identity", "meshgrid", "tri", "tril", "triu", "diag", "diagonal",
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "broadcast_to", "broadcast_arrays",
    "concatenate", "stack", "vstack", "hstack", "dstack", "column_stack",
    "split", "array_split", "hsplit", "vsplit", "dsplit", "tile", "repeat",
    "flip", "fliplr", "flipud", "roll", "rot90", "pad", "append", "insert",
    "delete", "unique", "sort", "argsort", "where", "extract", "searchsorted",
    "atleast_1d", "atleast_2d", "atleast_3d", "trim_zeros", "flatnonzero",
    # math
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "power", "float_power", "sqrt", "cbrt", "square",
    "absolute", "abs", "fabs", "sign", "exp", "expm1", "exp2", "log", "log2",
    "log10", "log1p", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "arctan2", "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    "hypot", "degrees", "radians", "deg2rad", "rad2deg", "floor", "ceil",
    "rint", "trunc", "fix", "around", "round", "clip", "maximum", "minimum",
    "fmax", "fmin", "nan_to_num", "reciprocal", "positive", "negative",
    "heaviside", "gcd", "lcm", "ldexp", "copysign", "nextafter",
    "logaddexp", "logaddexp2", "sinc", "interp", "ediff1d", "gradient",
    "diff", "cross", "trapezoid", "convolve", "correlate",
    # reductions / scans
    "sum", "prod", "mean", "std", "var", "median", "average", "percentile",
    "quantile", "min", "max", "amin", "amax", "ptp", "argmin", "argmax",
    "nanmin", "nanmax", "nansum", "nanprod", "nanmean", "nanstd", "nanvar",
    "nanmedian", "nanargmin", "nanargmax", "cumsum", "cumprod", "nancumsum",
    "nancumprod", "count_nonzero", "bincount", "histogram", "histogram2d",
    "digitize", "cov", "corrcoef",
    # logic / comparison
    "all", "any", "logical_and", "logical_or", "logical_not", "logical_xor",
    "greater", "greater_equal", "less", "less_equal", "equal", "not_equal",
    "isclose", "allclose", "array_equal", "isnan", "isinf", "isfinite",
    "isposinf", "isneginf", "iscomplex", "isreal", "signbit",
    # linalg-ish in main namespace
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "trace",
    # indexing
    "take", "take_along_axis", "put_along_axis", "choose", "compress",
    "nonzero", "argwhere", "indices", "unravel_index", "ravel_multi_index",
    "triu_indices", "tril_indices", "diag_indices", "select", "piecewise",
    # shape info
    "shape", "ndim", "size", "copyto", "may_share_memory", "result_type",
    "promote_types", "can_cast", "real", "imag", "conj", "conjugate", "angle",
    "i0", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "invert",
    "left_shift", "right_shift",
    # delegated-surface round 6 (ISSUE 15 satellite): set ops, window
    # functions, polynomial helpers, bit packing, the array-API aliases
    # (concat/permute_dims/matrix_transpose/vecdot), and the apply/
    # fromfunction functional constructors
    "apply_along_axis", "apply_over_axes", "argpartition", "array_equiv",
    "bartlett", "blackman", "hamming", "hanning", "kaiser",
    "broadcast_shapes", "concat", "diagflat", "diag_indices_from",
    "divmod", "frexp", "fromfunction", "geomspace", "histogram_bin_edges",
    "histogramdd", "intersect1d", "isin", "iscomplexobj", "isrealobj",
    "isscalar", "ix_", "lexsort", "matrix_transpose", "modf",
    "nanpercentile", "nanquantile", "packbits", "unpackbits", "partition",
    "permute_dims", "polyadd", "polyder", "polyint", "polymul", "polysub",
    "polyval", "resize", "setdiff1d", "setxor1d", "sort_complex",
    "spacing", "tril_indices_from", "triu_indices_from", "union1d",
    "unwrap", "vander", "vecdot",
    # delegated-surface round 7 (ISSUE 16 satellite): the array-API
    # trig/bitwise aliases (acos/atan2/pow/bitwise_left_shift/…),
    # cumulative_sum/prod + unstack/astype, the polynomial solvers
    # (poly/polyfit/polydiv/roots), popcount, block assembly, and the
    # unique_* array-API quartet.  put/place/fill_diagonal are bound as
    # host-side shims below — jnp requires ``inplace=False`` there and
    # returns the updated copy (jax arrays are immutable; numpy mutates);
    # block gets a deep-unwrap shim (nested argument lists).
    "acos", "acosh", "asin", "asinh", "atan", "atan2", "atanh", "pow",
    "bitwise_count", "bitwise_invert", "bitwise_left_shift",
    "bitwise_right_shift", "block", "cumulative_prod", "cumulative_sum",
    "astype", "fmod", "isdtype", "poly", "polydiv", "polyfit", "roots",
    "unique_all", "unique_counts", "unique_inverse", "unique_values",
    "unstack",
    # delegated-surface round 8 (ISSUE 19 satellite): the host-data
    # constructors (no NDArray inputs — the no-inputs path wraps the
    # result).  The round's main body is the np.fft subnamespace and the
    # linalg array-API additions bound in _populate below, plus the
    # host-returning helpers (array_repr/array_str/einsum_path/
    # issubdtype/iterable/vectorize) that must NOT route through the
    # registry delegation (they produce strings/bools/callables, not op
    # outputs — the jnp.shape precedent).  fromiter is NOT here: jnp
    # refuses it (consuming an iterator is impure under jit), so it gets
    # a host-side numpy bind in _populate.
    "frombuffer", "from_dlpack",
]

_self = _sys.modules[__name__]


def asarray(object, dtype=None, ctx=None):
    """Alias of :func:`array` — must share its dtype-inference rule
    (python floats → float32), not jnp.asarray's float64 under x64."""
    return array(object, dtype=dtype, ctx=ctx)


# jax arrays are immutable, so contiguity is moot — same alias
ascontiguousarray = asarray


def _populate():
    import jax.numpy as jnp
    # np.fix (truncate toward zero) — jnp.fix is deprecated for jnp.trunc;
    # bind trunc up front so the table loop never touches the warning attr
    setattr(_self, "fix", _wrap_jnp("fix", jnp.trunc))
    for name in _NP_FUNCS:
        if hasattr(_self, name) or not hasattr(jnp, name):
            continue
        setattr(_self, name, _wrap_jnp(name, getattr(jnp, name)))
    # numpy returns INTEGER counts from an unweighted, non-density
    # histogram; jnp.histogram hands back floats — cast the counts so
    # the delegated surface keeps numpy's result-dtype contract
    _hist_raw = _self.histogram

    def histogram(a, bins=10, range=None, weights=None, density=None):
        counts, edges = _hist_raw(a, bins=bins, range=range,
                                  weights=weights, density=density)
        if weights is None and not density:
            counts = counts.astype("int64")
        return counts, edges

    histogram.__doc__ = _hist_raw.__doc__
    _self.histogram = histogram
    # jnp.shape returns a plain tuple of python ints — routing it
    # through the registry delegation would try to rebuild that tuple
    # as op outputs (ISSUE 14 round-5 catch); bind the introspection
    # helper host-side like numpy's

    def shape(a):
        return tuple(a.shape) if hasattr(a, "shape") else jnp.shape(a)

    shape.__doc__ = jnp.shape.__doc__
    _self.shape = shape
    # jnp.mask_indices CALLS the user's mask_func on a jax array and
    # feeds the result to jnp.nonzero — a delegated mx.np.triu/tril
    # returns an NDArray there and jnp chokes on it (ISSUE 15 round-6
    # catch).  Bind host-side with a shim that unwraps NDArray results,
    # so the natural `mx.np.mask_indices(3, mx.np.triu)` spelling works.

    def mask_indices(n, mask_func, k=0):
        def _mf(a, kk):
            out = mask_func(a, kk)
            return out._data if isinstance(out, NDArray) else out
        return tuple(NDArray._from_data(i, ctx=current_context())
                     for i in jnp.mask_indices(n, _mf, k))

    mask_indices.__doc__ = jnp.mask_indices.__doc__
    _self.mask_indices = mask_indices
    # numpy's put/place/fill_diagonal mutate their first argument and
    # return None; jax arrays are immutable, so jnp exposes them only
    # with ``inplace=False`` (anything else raises) and returns the
    # updated copy.  Bind host-side shims that unwrap NDArrays, pass
    # inplace=False, and return the copy — the documented divergence
    # (ISSUE 16 round-7 catch, same family as the mask_indices shim).

    def _unwrap(v):
        return v._data if isinstance(v, NDArray) else v

    def _rewrap(out):
        return NDArray._from_data(out, ctx=current_context())

    def put(a, ind, v, mode="clip"):
        return _rewrap(jnp.put(_unwrap(a), _unwrap(ind), _unwrap(v),
                               mode=mode, inplace=False))

    put.__doc__ = jnp.put.__doc__
    _self.put = put

    def place(arr, mask, vals):
        return _rewrap(jnp.place(_unwrap(arr), _unwrap(mask),
                                 _unwrap(vals), inplace=False))

    place.__doc__ = jnp.place.__doc__
    _self.place = place

    def fill_diagonal(a, val, wrap=False):
        return _rewrap(jnp.fill_diagonal(_unwrap(a), _unwrap(val),
                                         wrap=wrap, inplace=False))

    fill_diagonal.__doc__ = jnp.fill_diagonal.__doc__
    _self.fill_diagonal = fill_diagonal
    # jnp.block takes NESTED lists of arrays; the registry delegation
    # only unwraps flat argument lists, so NDArrays one level down reach
    # jnp verbatim and it chokes (same round-7 catch) — deep-unwrap here

    def block(arrays):
        def _deep(v):
            if isinstance(v, (list, tuple)):
                return [_deep(u) for u in v]
            return _unwrap(v)
        return _rewrap(jnp.block(_deep(arrays)))

    block.__doc__ = jnp.block.__doc__
    _self.block = block
    # round 8 (ISSUE 19 satellite): helpers whose results are strings,
    # bools, or callables — the registry delegation would try to rebuild
    # those as op outputs; bind host-side with NDArray unwrapping

    def array_repr(arr, *a, **kw):
        return jnp.array_repr(_unwrap(arr), *a, **kw)

    array_repr.__doc__ = jnp.array_repr.__doc__
    _self.array_repr = array_repr

    def array_str(a, *args, **kw):
        return jnp.array_str(_unwrap(a), *args, **kw)

    array_str.__doc__ = jnp.array_str.__doc__
    _self.array_str = array_str

    def einsum_path(subscripts, *operands, **kw):
        return jnp.einsum_path(subscripts, *[_unwrap(o) for o in operands],
                               **kw)

    einsum_path.__doc__ = jnp.einsum_path.__doc__
    _self.einsum_path = einsum_path

    def iterable(y):
        return jnp.iterable(_unwrap(y))

    iterable.__doc__ = jnp.iterable.__doc__
    _self.iterable = iterable
    _self.issubdtype = jnp.issubdtype  # pure dtype-lattice logic

    def vectorize(pyfunc, **kw):
        vf = jnp.vectorize(pyfunc, **kw)

        def vectorized(*args, **kwargs):
            out = vf(*[_unwrap(a) for a in args], **kwargs)
            if isinstance(out, tuple):
                return tuple(_rewrap(o) for o in out)
            return _rewrap(out)

        vectorized.__doc__ = getattr(pyfunc, "__doc__", None)
        return vectorized

    vectorize.__doc__ = jnp.vectorize.__doc__
    _self.vectorize = vectorize

    def fromiter(iterable, dtype, count=-1):
        # jnp.fromiter raises NotImplementedError (consuming an iterator
        # is impure under jit) — build on host, then move on-device
        return _rewrap(jnp.asarray(_onp.fromiter(iterable, dtype=dtype,
                                                 count=count)))

    fromiter.__doc__ = _onp.fromiter.__doc__
    _self.fromiter = fromiter
    # subnamespaces
    # np.fft (round 8) — the whole jnp.fft surface delegates like the
    # main namespace (complex outputs ride the same versioned NDArray
    # slot; fftfreq/rfftfreq take no array inputs and wrap host-side)
    fftm = _types.ModuleType(__name__ + ".fft")
    import jax.numpy.fft as jfft
    for name in ("fft", "ifft", "rfft", "irfft", "fft2", "ifft2", "fftn",
                 "ifftn", "rfft2", "irfft2", "rfftn", "irfftn", "hfft",
                 "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift"):
        if hasattr(jfft, name):
            setattr(fftm, name, _wrap_jnp("fft." + name, getattr(jfft, name)))
    _sys.modules[fftm.__name__] = fftm
    _self.fft = fftm
    lin = _types.ModuleType(__name__ + ".linalg")
    import jax.numpy.linalg as jla
    for name in ("norm", "inv", "det", "slogdet", "solve", "lstsq", "pinv",
                 "matrix_rank", "matrix_power", "cholesky", "qr", "svd",
                 "svdvals", "eig", "eigh", "eigvals", "eigvalsh", "cond",
                 "tensorinv", "tensorsolve", "multi_dot", "cross", "outer",
                 "matmul", "trace", "vector_norm", "matrix_norm",
                 # round 8: the remaining linalg array-API members
                 "diagonal", "matrix_transpose", "tensordot", "vecdot"):
        if hasattr(jla, name):
            setattr(lin, name, _wrap_jnp("linalg." + name, getattr(jla, name)))
    _sys.modules[lin.__name__] = lin
    _self.linalg = lin
    # np.random — stateful facade over the context key stream
    rnd = _types.ModuleType(__name__ + ".random")

    def _rand_wrap(name):
        import jax
        def fn(*args, size=None, dtype=None, ctx=None, **kw):
            from .. import random as _mxr
            key = _mxr.get_key(ctx or current_context())
            jr = getattr(jax.random, name)
            out = _dispatch_random(jr, name, key, args, size, dtype, kw)
            return NDArray._from_data(out)
        fn.__name__ = name
        return fn

    def _dispatch_random(jr, name, key, args, size, dtype, kw):
        import jax.numpy as jnp
        shape = size if size is not None else ()
        if isinstance(shape, int):
            shape = (shape,)
        if name == "uniform":
            low = args[0] if len(args) > 0 else 0.0
            high = args[1] if len(args) > 1 else 1.0
            return jr(key, shape, minval=low, maxval=high)
        if name == "normal":
            loc = args[0] if len(args) > 0 else 0.0
            scale = args[1] if len(args) > 1 else 1.0
            return jr(key, shape) * scale + loc
        if name == "randint":
            low = args[0]
            high = args[1] if len(args) > 1 else None
            if high is None:
                low, high = 0, low
            return jr(key, shape, low, high)
        return jr(key, *args, shape)

    import jax.random as _jr
    for name in ("uniform", "normal", "randint"):
        setattr(rnd, name, _rand_wrap(name))

    def _rand(*dims):
        return rnd.uniform(0.0, 1.0, size=tuple(dims) if dims else ())

    def _randn(*dims):
        return rnd.normal(0.0, 1.0, size=tuple(dims) if dims else ())

    def _choice(a, size=None, replace=True, p=None, ctx=None):
        import jax
        from .. import random as _mxr
        key = _mxr.get_key(ctx or current_context())
        arr = a._data if isinstance(a, NDArray) else a
        shape = (size,) if isinstance(size, int) else (size or ())
        out = jax.random.choice(key, arr, shape, replace=replace,
                                p=p._data if isinstance(p, NDArray) else p)
        return NDArray._from_data(out)

    def _shuffle(x):
        import jax
        from .. import random as _mxr
        key = _mxr.get_key(current_context())
        x._set_data(jax.random.permutation(key, x._data, axis=0))

    def _seed(s):
        from .. import random as _mxr
        _mxr.seed(s)

    rnd.rand = _rand
    rnd.randn = _randn
    rnd.choice = _choice
    rnd.shuffle = _shuffle
    rnd.seed = _seed
    _sys.modules[rnd.__name__] = rnd
    _self.random = rnd


_populate()
