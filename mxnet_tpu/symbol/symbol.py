"""Symbol: the lazy computation-graph API.

Rebuild of python/mxnet/symbol/symbol.py + nnvm's Symbol/Graph (N25, P4).
A Symbol is a DAG node over the SAME operator registry the imperative path
uses; ``bind`` lowers the whole graph into one ``jax.jit``-compiled function
(the GraphExecutor N6 role — shape inference, memory planning, device
placement and bulking are all XLA's job now, SURVEY §7.1).

Supported reference surface: var/Group, composition, list_arguments/
list_outputs/list_auxiliary_states, infer_shape/infer_type (via abstract
evaluation), bind/simple_bind → Executor(forward/backward/outputs),
eval, tojson/load_json/save/load, attributes (incl. ``__ctx_group__`` — the
manual model-parallel hint, mapped to sharding annotations by the parallel
trainer), and the generated mx.sym.<op> namespaces.
"""

from __future__ import annotations

import json

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from ..ops import registry as _reg

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "zeros", "ones", "register_backend"]

# Subgraph-backend registry (reference SubgraphBackendRegistry, N9):
# name -> pass fn(symbol, args, aux, **kwargs) -> symbol
_BACKEND_REGISTRY: dict = {}


def register_backend(name):
    """Register a graph-rewrite backend for ``sym.optimize_for(name)``."""
    def deco(fn):
        _BACKEND_REGISTRY[str(name)] = fn
        return fn
    return deco


def _xla_identity_pass(sym, args=None, aux=None, **kwargs):  # noqa: ARG001
    # fusion/memory-planning/layout are XLA compiler passes on this stack;
    # the partitioner has nothing to carve out (SURVEY §7.1 N8/N9 rows)
    return sym


for _n in ("default", "TPU", "xla"):
    _BACKEND_REGISTRY[_n] = _xla_identity_pass


class Symbol:
    def __init__(self, op=None, inputs=(), attrs=None, name=None,
                 num_outputs=1, out_index=None):
        self._op = op                  # None for var; "group" for Group
        self._inputs = list(inputs)
        self._attrs = dict(attrs or {})
        self._name = name or (op.name if op else "var")
        self._num_outputs = num_outputs
        self._out_index = out_index    # int when slicing one output

    # -- introspection -------------------------------------------------------
    @property
    def name(self):
        return self._name

    def attr(self, key):
        return self._attrs.get(key)

    def _set_attr(self, **kwargs):
        self._attrs.update(kwargs)

    def list_attr(self):
        return {k: str(v) for k, v in self._attrs.items()}

    def _walk(self, seen=None, order=None):
        if seen is None:
            seen, order = set(), []
        if id(self) in seen:
            return order
        seen.add(id(self))
        for i in self._inputs:
            i._walk(seen, order)
        order.append(self)
        return order

    def list_arguments(self):
        return [s._name for s in self._walk()
                if s._op is None and not s._attrs.get("__aux__")]

    def list_auxiliary_states(self):
        return [s._name for s in self._walk()
                if s._op is None and s._attrs.get("__aux__")]

    def list_inputs(self):
        return [s._name for s in self._walk() if s._op is None]

    def list_outputs(self):
        if self._op == "group":
            return [o for i in self._inputs for o in i.list_outputs()]
        return [f"{self._name}_output"]

    @property
    def num_outputs(self):
        if self._op == "group":
            return sum(i.num_outputs for i in self._inputs)
        return 1 if self._out_index is not None else self._num_outputs

    def __getitem__(self, index):
        if isinstance(index, str):
            # reference convention: internals['fc2_output'] selects the
            # node named 'fc2' (the '_output' suffix marks its output)
            base = index[:-7] if index.endswith("_output") else index
            pool = self._inputs if self._op == "group" else \
                list(self._walk())
            for s in pool:
                if s._name in (base, index):
                    return s
            raise MXNetError(f"no internal symbol named {index!r}")
        if self._op == "group":
            return self._inputs[index]
        if isinstance(index, int):
            if self._num_outputs == 1 and index == 0:
                return self
            return Symbol("output_slice", [self], {"index": index},
                          name=f"{self._name}[{index}]")
        raise MXNetError("symbol indexing requires an int or name")

    def get_internals(self):
        return Group(*[s for s in self._walk() if s._op is not None])

    def get_children(self):
        return Group(*self._inputs) if self._inputs else None

    # -- composition sugar (same dunder surface as NDArray) ------------------
    def _binop(self, opname, other, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            ins = [other, self] if reverse else [self, other]
            return _make(opname, ins, {})
        return _make(scalar_op, [self],
                     {"scalar": float(other), "reverse": reverse})

    def __add__(self, o):
        return self._binop("broadcast_add", o, "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop("broadcast_sub", o, "_minus_scalar")

    def __rsub__(self, o):
        return self._binop("broadcast_sub", o, "_minus_scalar", True)

    def __mul__(self, o):
        return self._binop("broadcast_mul", o, "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop("broadcast_div", o, "_div_scalar")

    def __rtruediv__(self, o):
        return self._binop("broadcast_div", o, "_div_scalar", True)

    def __pow__(self, o):
        return self._binop("broadcast_power", o, "_power_scalar")

    def __neg__(self):
        return _make("negative", [self], {})

    def reshape(self, shape):
        return _make("reshape", [self], {"shape": tuple(shape)})

    def transpose(self, axes=None):
        return _make("transpose", [self], {"axes": axes})

    # -- evaluation ----------------------------------------------------------
    def _visible_head(self, main):
        """Truncate a multi-output head to its visible outputs (reference
        'visible outputs': BatchNorm exposes 1 of its 3)."""
        if (self._op is not None
                and self._op not in ("group", "output_slice")
                and isinstance(main, tuple)
                and self._op.visible_outputs is not None):
            vis = main[:self._op.visible_outputs]
            return vis[0] if len(vis) == 1 else vis
        return main

    def _leaf_syms(self):
        return [s for s in self._walk() if s._op is None]

    def _build_fn(self, train_mode=False, collect_mutations=False):
        """Lower the DAG to ``run(key, *leaf_arrays)`` (traceable).

        ``train_mode`` feeds each op's wrap_train flag (Dropout/BatchNorm
        behavior); RNG-consuming ops get per-node splits of ``key``.  With
        ``collect_mutations`` the run also returns the updated values of
        mutated leaf inputs (FMutateInputs — BatchNorm moving stats), as
        ``(main_out, (mut_val, ...))``; ``mut_specs`` names them.
        """
        leaves = self._leaf_syms()
        leaf_pos = {id(s): i for i, s in enumerate(leaves)}
        order = self._walk()
        op_nodes = [s for s in order
                    if s._op is not None
                    and s._op not in ("group", "output_slice")]
        rng_idx = {id(s): i for i, s in enumerate(
            [s for s in op_nodes if s._op.wrap_key is not None])}
        mut_specs = []   # (leaf_name, node, out_idx)
        if collect_mutations:
            for s in op_nodes:
                for oi, ii in s._op.mutate_inputs:
                    tgt = s._inputs[ii]
                    if tgt._op is None:
                        mut_specs.append((tgt._name, s, oi))

        def run(key, *arrays):
            import jax
            cache = {}
            subkeys = jax.random.split(key, max(len(rng_idx), 1))

            def ev(s):
                if id(s) in cache:
                    return cache[id(s)]
                if s._op is None:
                    v = arrays[leaf_pos[id(s)]]
                elif s._op == "group":
                    v = tuple(x for i in s._inputs
                              for x in _as_tuple(ev(i)))
                elif s._op == "output_slice":
                    v = _as_tuple(ev(s._inputs[0]))[s._attrs["index"]]
                else:
                    ins = []
                    for i in s._inputs:
                        x = ev(i)
                        # a multi-output producer feeds its first output
                        # unless explicitly sliced (reference nnvm entries)
                        ins.append(x[0] if isinstance(x, (tuple, list)) else x)
                    attrs = _op_attrs(s)
                    op = s._op
                    if op.wrap_train is not None or op.wrap_key is not None:
                        attrs = dict(attrs)
                        if op.wrap_train is not None:
                            attrs[op.wrap_train] = train_mode
                        if op.wrap_key is not None:
                            attrs[op.wrap_key] = subkeys[rng_idx[id(s)]]
                    v = _reg.invoke_arrays(op, ins, attrs)
                    if isinstance(v, list):
                        v = tuple(v)
                cache[id(s)] = v
                return v

            main = ev(self)
            main = self._visible_head(main)
            if not collect_mutations:
                return main
            muts = tuple(_as_tuple(cache[id(node)])[oi]
                         for (_, node, oi) in mut_specs)
            return main, muts

        return run, leaves, mut_specs

    def eval(self, ctx=None, **kwargs):
        from .. import random as _rnd
        run, leaves, _ = self._build_fn()
        arrays = []
        for s in leaves:
            if s._name not in kwargs:
                raise MXNetError(f"eval missing argument {s._name!r}")
            v = kwargs[s._name]
            arrays.append(v._data if isinstance(v, NDArray) else v)
        out = run(_rnd.get_key(), *arrays)
        outs = _as_tuple(out)
        return [NDArray._from_data(o, ctx=ctx) for o in outs]

    def infer_shape(self, **kwargs):
        """arg_shapes, out_shapes, aux_shapes.

        Forward abstract evaluation node-by-node, with per-op ``infer_args``
        rules filling parameter shapes from data shapes — the bidirectional
        role of the reference's InferShape pass (simple_bind only needs the
        data/label shapes, like the reference)."""
        import jax
        shape_of = {}   # id(sym) -> shape tuple | tuple-of-tuples
        dtype_of = {}
        order = self._walk()
        for s in order:
            if s._op is None:
                shp = kwargs.get(s._name, s._attrs.get("__shape__"))
                shape_of[id(s)] = tuple(shp) if shp is not None else None
                dtype_of[id(s)] = s._attrs.get("__dtype__", _np.float32)
        for s in order:
            if s._op is None:
                continue
            if s._op == "group":
                outs = []
                for i in s._inputs:
                    v = shape_of.get(id(i))
                    outs.extend(v if isinstance(v, list) else [v])
                shape_of[id(s)] = outs
                continue
            if s._op == "output_slice":
                v = shape_of.get(id(s._inputs[0]))
                shape_of[id(s)] = v[s._attrs["index"]] \
                    if isinstance(v, list) else v
                continue
            in_shapes = []
            for i in s._inputs:
                v = shape_of.get(id(i))
                in_shapes.append(v[0] if isinstance(v, list) else v)
            if s._op.infer_args is not None and any(
                    sh is None for sh in in_shapes):
                filled = s._op.infer_args(in_shapes, _op_attrs(s))
                for i, sh in zip(s._inputs, filled):
                    if sh is not None and shape_of.get(id(i)) is None \
                            and i._op is None:
                        shape_of[id(i)] = tuple(sh)
                in_shapes = filled
            if any(sh is None for sh in in_shapes):
                return None, None, None
            structs = [jax.ShapeDtypeStruct(tuple(sh),
                                            dtype_of.get(id(i), _np.float32))
                       for i, sh in zip(s._inputs, in_shapes)]
            try:
                out = jax.eval_shape(
                    lambda *a, _s=s: _reg.invoke_arrays(
                        _s._op, list(a), _op_attrs(_s)), *structs)
            except Exception as e:
                raise MXNetError(
                    f"infer_shape failed at node {s._name!r}: {e}") from e
            if isinstance(out, (tuple, list)):
                shape_of[id(s)] = [tuple(o.shape) for o in out]
            else:
                shape_of[id(s)] = tuple(out.shape)
        args = self.list_arguments()
        auxs = self.list_auxiliary_states()
        name2shape = {s._name: shape_of.get(id(s))
                      for s in order if s._op is None}
        head = shape_of.get(id(self))
        if isinstance(head, list):
            if (self._op not in (None, "group", "output_slice")
                    and self._op.visible_outputs is not None):
                head = head[:self._op.visible_outputs]  # drop hidden outputs
            out_shapes = head
        else:
            out_shapes = [head]
        return ([name2shape[a] for a in args], out_shapes,
                [name2shape[a] for a in auxs])

    def infer_type(self, **kwargs):
        args = self.list_arguments()
        dt = kwargs.get(args[0], _np.float32) if args else _np.float32
        return ([dt] * len(args), [dt], [])

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):  # noqa: ARG002
        from .executor import Executor
        return Executor(self, ctx or current_context(), args, args_grad,
                        grad_req, aux_states)

    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        arg_shapes, _, aux_shapes = self.infer_shape(**shapes)
        if arg_shapes is None:
            raise MXNetError("simple_bind could not infer all shapes; pass "
                             "every input shape")
        args = {a: nd.zeros(s, ctx=ctx)
                for a, s in zip(self.list_arguments(), arg_shapes)}
        args_grad = {a: nd.zeros(s, ctx=ctx)
                     for a, s in zip(self.list_arguments(), arg_shapes)} \
            if grad_req != "null" else None
        aux = {a: nd.zeros(s, ctx=ctx)
               for a, s in zip(self.list_auxiliary_states(), aux_shapes)}
        return self.bind(ctx, args, args_grad, grad_req, aux)

    def optimize_for(self, backend, args=None, aux=None, **kwargs):
        """Graph-rewrite entry (reference sym.optimize_for →
        MXOptimizeForBackend + SubgraphBackendRegistry, N9/N6).

        Backends are python passes ``fn(symbol, args, aux, **kwargs) ->
        symbol`` registered via ``register_backend``.  Built-ins:
        'default'/'TPU'/'xla' — identity with rationale (operator fusion,
        memory planning and layout belong to XLA's compiler passes here,
        so there is nothing left for a hand-rolled partitioner to do) —
        and 'INT8', a REAL rewrite that swaps FullyConnected nodes for
        the quantize -> int8-MXU FC -> dequantize chain
        (``symbol/int8_pass.py``; kwargs: excluded_sym_names,
        calib_ranges).  Unknown backends RAISE (the reference errors for
        unregistered backends too; silently returning self would hide
        missing MKLDNN/TensorRT-style integrations).
        """
        fn = _BACKEND_REGISTRY.get(str(backend))
        if fn is None:
            from ..base import MXNetError
            raise MXNetError(
                f"subgraph backend {backend!r} is not registered "
                f"(known: {sorted(_BACKEND_REGISTRY)}); register one with "
                "mxnet_tpu.symbol.register_backend(name)(pass_fn)")
        return fn(self, args, aux, **kwargs)

    # -- serialization -------------------------------------------------------
    def tojson(self):
        """Serialize the DAG.  Schema is documented ('mxnet_tpu.sym.v1'): the
        reference's nnvm JSON needs op names/attrs we preserve 1:1, so graphs
        round-trip within this framework; cross-loading reference JSON is a
        best-effort name-match."""
        order = self._walk()
        idx = {id(s): i for i, s in enumerate(order)}
        nodes = []
        for s in order:
            nodes.append({
                "op": "null" if s._op is None else (
                    s._op if isinstance(s._op, str) else s._op.name),
                "name": s._name,
                "attrs": {k: repr(v) for k, v in s._attrs.items()},
                "inputs": [[idx[id(i)], 0, 0] for i in s._inputs],
            })
        return json.dumps({"format": "mxnet_tpu.sym.v1", "nodes": nodes,
                           "heads": [[len(order) - 1, 0, 0]]}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def __repr__(self):
        return f"<Symbol {self._name}>"


def _as_tuple(v):
    if isinstance(v, tuple):
        return v
    if isinstance(v, list):
        return tuple(v)
    return (v,)


def _op_attrs(s):
    """Operator kwargs for a node: Symbol._attrs minus the __dunder__
    string annotations (AttrScope/shape/aux markers) — the ONE exclusion
    rule every execution/inference site shares."""
    return {k: v for k, v in s._attrs.items() if not k.startswith("__")}


def _name_hint(opname):
    """NameManager hint for an op — ONE derivation shared with
    symbol/register.py so both construction paths name alike."""
    return opname.split(".")[-1].lower()


def _make(opname, inputs, attrs, name=None):
    op = _reg.get(opname)
    from ..name import NameManager
    from ..attribute import AttrScope
    return Symbol(op, inputs, AttrScope.current().get(attrs),
                  name=NameManager.current().get(name, _name_hint(opname)))


def var(name, attr=None, shape=None, dtype=None, init=None, stype=None,
        **kwargs):  # noqa: ARG001
    from ..attribute import AttrScope
    s = Symbol(None, name=name)
    if shape is not None:
        s._attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        s._attrs["__dtype__"] = dtype
    # scope attrs apply to Variables too — the reference's primary use
    # (lr_mult/wd_mult/ctx_group annotations on parameters)
    merged = AttrScope.current().get(attr)
    if merged:
        s._attrs.update(merged)
    s._attrs.update(kwargs)
    return s


Variable = var


def Group(*symbols):
    if len(symbols) == 1 and isinstance(symbols[0], (list, tuple)):
        symbols = tuple(symbols[0])
    return Symbol("group", list(symbols), name="group")


def load_json(json_str):
    data = json.loads(json_str)
    nodes = data["nodes"]
    built = []
    import ast
    for n in nodes:
        attrs = {}
        for k, v in n.get("attrs", {}).items():
            try:
                attrs[k] = ast.literal_eval(v)
            except (ValueError, SyntaxError):
                attrs[k] = v
        ins = [built[i[0]] for i in n.get("inputs", [])]
        if n["op"] == "null":
            s = Symbol(None, name=n["name"], attrs=attrs)
        elif n["op"] in ("group", "output_slice"):
            s = Symbol(n["op"], ins, attrs, name=n["name"])
        else:
            s = Symbol(_reg.get(n["op"]), ins, attrs, name=n["name"])
        built.append(s)
    head = data.get("heads", [[len(built) - 1, 0, 0]])[0][0]
    return built[head]


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def zeros(shape, dtype=None, **kwargs):
    return _make("_zeros", [], {"shape": tuple(shape),
                                "dtype": dtype or "float32"}, **kwargs)


def ones(shape, dtype=None, **kwargs):
    return _make("_ones", [], {"shape": tuple(shape),
                               "dtype": dtype or "float32"}, **kwargs)
