"""Generate mx.sym.<op> namespaces from the registry (reference
python/mxnet/symbol/register.py) — mirrors ndarray.register but produces
Symbols."""

from __future__ import annotations

import sys
import types

from ..ops import registry as _reg
from .symbol import Symbol, _make

_counter = {}


def _auto_name(opname):
    base = opname.split(".")[-1].lower()
    n = _counter.get(base, 0)
    _counter[base] = n + 1
    return f"{base}{n}"


def _make_sym_func(op):
    def fn(*args, name=None, attr=None, **attrs):
        inputs = [a for a in args if isinstance(a, Symbol)]
        s = Symbol(op, inputs, attrs, name=name or _auto_name(op.name),
                   num_outputs=op.num_outputs if op.num_outputs > 0 else 1)
        if attr:
            s._attrs.update(attr)
        return s
    fn.__name__ = op.name.split(".")[-1]
    fn.__doc__ = op.doc or f"symbolic wrapper for operator {op.name!r}"
    return fn


def populate(target_module, prefix=""):
    installed = []
    for name in _reg.list_ops():
        local = name
        fn = _make_sym_func(_reg.get(name))
        if "." in local:
            ns, leaf = local.split(".", 1)
            if "." in leaf:
                continue
            modname = f"{target_module.__name__}.{ns}"
            mod = sys.modules.get(modname)
            if mod is None:
                mod = types.ModuleType(modname)
                sys.modules[modname] = mod
            if not hasattr(target_module, ns):
                setattr(target_module, ns, mod)
            sub = getattr(target_module, ns)
            if not hasattr(sub, leaf):
                setattr(sub, leaf, fn)
                installed.append(f"{ns}.{leaf}")
            flat = local.replace(".", "_")
            if not hasattr(target_module, flat):
                setattr(target_module, flat, fn)
        else:
            if not hasattr(target_module, local):
                setattr(target_module, local, fn)
                installed.append(local)
    return installed
