"""Generate mx.sym.<op> namespaces from the registry (reference
python/mxnet/symbol/register.py) — mirrors ndarray.register but produces
Symbols."""

from __future__ import annotations

import sys
import types

from ..base import MXNetError
from ..ops import registry as _reg
from .symbol import Symbol, _make

def _auto_name(opname, name=None):
    # route through mx.name.NameManager so Prefix()/custom managers apply;
    # hint derivation shared with symbol._make
    from ..name import NameManager
    from .symbol import _name_hint
    return NameManager.current().get(name, _name_hint(opname))


def _make_sym_func(op):
    def fn(*args, name=None, attr=None, **attrs):
        from .symbol import var
        inputs = [a for a in args if isinstance(a, Symbol)]
        sym_name = _auto_name(op.name, name)
        if op.input_names is not None:
            # reference nnvm composition: keyword Symbols fill their named
            # slot; missing inputs become auto-created variables
            # "<name>_<input>" (aux slots flagged, excluded from arguments)
            omit = op.omit_inputs(attrs) if op.omit_inputs else set()
            wanted = [n for n in op.input_names if n not in omit]
            by_name = {}
            for n in wanted:
                if n in attrs and isinstance(attrs[n], Symbol):
                    by_name[n] = attrs.pop(n)
            pos = list(inputs)
            full = []
            for n in wanted:
                if n in by_name:
                    v = by_name[n]
                elif pos:
                    v = pos.pop(0)
                else:
                    v = var(f"{sym_name}_{n}")
                # aux-ness follows the op's declared slot (reference
                # FListAuxiliaryStates), however the input was supplied
                if n in op.aux_names and v._op is None:
                    v._attrs["__aux__"] = True
                full.append(v)
            if pos:
                raise MXNetError(
                    f"operator {op.name!r} takes inputs {wanted} "
                    f"(attrs {sorted(omit)} omitted); {len(pos)} extra "
                    f"positional symbol(s) could not be placed")
            inputs = full
        leftover = [k for k, v in attrs.items() if isinstance(v, Symbol)]
        if leftover:
            raise MXNetError(
                f"operator {op.name!r}: symbol(s) passed for "
                f"non-input keyword(s) {leftover} (reference nnvm "
                f"composition rejects unplaceable inputs)")
        s = Symbol(op, inputs, attrs, name=sym_name,
                   num_outputs=op.num_outputs if op.num_outputs > 0 else 1)
        from ..attribute import AttrScope
        if attr:
            bad = [k for k in attr
                   if not (k.startswith("__") and k.endswith("__"))]
            if bad:
                raise MXNetError(
                    f"attr keys must be __dunder__ strings, got {bad} "
                    "(non-dunder keys would collide with operator kwargs)")
        scope_attr = AttrScope.current().get(attr)
        if scope_attr:
            s._attrs.update(scope_attr)
        return s
    fn.__name__ = op.name.split(".")[-1]
    fn.__doc__ = op.doc or f"symbolic wrapper for operator {op.name!r}"
    return fn


def populate(target_module, prefix=""):
    installed = []
    for name in _reg.list_ops():
        local = name
        fn = _make_sym_func(_reg.get(name))
        if "." in local:
            ns, leaf = local.split(".", 1)
            if "." in leaf:
                continue
            modname = f"{target_module.__name__}.{ns}"
            mod = sys.modules.get(modname)
            if mod is None:
                mod = types.ModuleType(modname)
                sys.modules[modname] = mod
            if not hasattr(target_module, ns):
                setattr(target_module, ns, mod)
            sub = getattr(target_module, ns)
            if not hasattr(sub, leaf):
                setattr(sub, leaf, fn)
                installed.append(f"{ns}.{leaf}")
            flat = local.replace(".", "_")
            if not hasattr(target_module, flat):
                setattr(target_module, flat, fn)
        else:
            if not hasattr(target_module, local):
                setattr(target_module, local, fn)
                installed.append(local)
    return installed
