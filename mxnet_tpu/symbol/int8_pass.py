"""INT8 subgraph backend — a REAL graph-rewrite pass through the
``optimize_for`` seam (reference quantize_graph_pass.cc routed through the
SubgraphBackendRegistry, SURVEY N9/N11; VERDICT r3 weak item 6: "worth one
real pass to prove the seam").

``sym.optimize_for('INT8')`` walks the DAG and swaps every eligible
FullyConnected node for the int8 MXU chain

    quantize_v2(data) + quantize_v2(weight)
        -> quantized_fully_connected (int8 x int8 -> int32 on the MXU)
        -> dequantize (+ float-side bias add)

exactly like ``contrib.quantization.quantize_net`` does for Gluon blocks,
but at the symbol level so Module/executor users get the same path.
Per-node calibration ranges (from `contrib.quantization` calibrators) ride
in via ``calib_ranges={node_name: (min, max)}`` and become static
quantize_v2 attrs; without them quantization is online (per-batch
min/max).  Nodes listed in ``excluded_sym_names`` keep float math.
"""

from __future__ import annotations

from .symbol import Symbol, register_backend


def _op_name(node):
    if node._op is None:
        return None
    return node._op if isinstance(node._op, str) else node._op.name


def _truthy(v):
    return str(v).lower() in ("1", "true")


@register_backend("INT8")
def int8_pass(sym, args=None, aux=None, excluded_sym_names=(),
              calib_ranges=None, **kwargs):  # noqa: ARG001
    from .. import symbol as S
    excluded = set(excluded_sym_names or ())
    calib = dict(calib_ranges or {})
    mapping = {}
    quantized = 0
    for node in sym._walk():
        new_inputs = [mapping.get(id(i), i) for i in node._inputs]
        if _op_name(node) == "FullyConnected" and node._name not in excluded:
            data, weight = new_inputs[0], new_inputs[1]
            no_bias = _truthy(node._attrs.get("no_bias", False))
            bias = new_inputs[2] if (len(new_inputs) > 2 and not no_bias) \
                else None
            dkw = {}
            if node._name in calib:
                dkw = {"min_calib_range": float(calib[node._name][0]),
                       "max_calib_range": float(calib[node._name][1])}
            qx = S.contrib.quantize_v2(data, name=node._name + "_qdata",
                                       **dkw)
            qw = S.contrib.quantize_v2(weight, name=node._name + "_qweight")
            o = S.contrib.quantized_fully_connected(
                qx[0], qw[0], qx[1], qx[2], qw[1], qw[2],
                num_hidden=int(node._attrs.get("num_hidden", 0)),
                flatten=_truthy(node._attrs.get("flatten", True)),
                name=node._name + "_quantized")
            out = S.contrib.dequantize(o[0], o[1], o[2],
                                       name=node._name + "_dequantize")
            if bias is not None:
                out = S.broadcast_add(out, bias,
                                      name=node._name + "_bias_add")
            # preserve the original node name so downstream name lookups
            # (internals['fc_output'], arg binding) keep resolving
            out._name = node._name
            mapping[id(node)] = out
            quantized += 1
        elif node._op is None or new_inputs == node._inputs:
            mapping[id(node)] = node
        else:
            mapping[id(node)] = Symbol(
                op=node._op, inputs=new_inputs, attrs=dict(node._attrs),
                name=node._name, num_outputs=node._num_outputs,
                out_index=node._out_index)
    out = mapping[id(sym)]
    out._set_attr(__int8_quantized_nodes__=str(quantized))
    return out
