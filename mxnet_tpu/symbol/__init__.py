"""mx.sym — symbolic graph API (reference python/mxnet/symbol/, P4)."""

import sys as _sys

from .symbol import (  # noqa: F401
    Symbol, var, Variable, Group, load, load_json, zeros, ones,
    register_backend,
)
from . import register as _register

_GENERATED = _register.populate(_sys.modules[__name__])

from . import contrib  # noqa: F401,E402
from . import int8_pass  # noqa: F401,E402 — registers the 'INT8' backend
