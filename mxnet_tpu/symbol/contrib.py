"""Control-flow sugar (reference python/mxnet/symbol/contrib.py ::
foreach/while_loop/cond and src/operator/control_flow.cc).

TPU-native: these are thin wrappers over lax.scan/while_loop/cond working on
BOTH NDArrays (imperative, traceable under hybridize) and raw jax arrays —
the reference's subgraph-op machinery (_foreach/_while_loop/_cond stateful
ops with autograd through loops) is exactly what lax gives natively,
including differentiation through scan.
"""

from __future__ import annotations

from ..ndarray.ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _unwrap(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap(x):
    import jax
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    if isinstance(x, (jax.Array,)) or hasattr(x, "dtype"):
        return NDArray._from_data(x)
    return x


def foreach(body, data, init_states):
    """reference contrib.foreach: scan body(data_slice, states) ->
    (out, new_states) over axis 0 of data."""
    import jax

    def jbody(states, x):
        out, new_states = body(_wrap(x), _wrap(states))
        return _unwrap(new_states), _unwrap(out)

    states0 = _unwrap(init_states)
    xs = _unwrap(data)
    final_states, outs = jax.lax.scan(jbody, states0, xs)
    return _wrap(outs), _wrap(final_states)


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """reference contrib.while_loop.  Static shapes require max_iterations;
    lax.while_loop is used when no per-step outputs are collected."""
    import jax
    import jax.numpy as jnp

    if max_iterations is None:
        # pure state evolution, no stacked outputs
        def jcond(vs):
            r = cond_fn(*_wrap(list(vs)))
            return r._data.astype(bool).reshape(()) \
                if isinstance(r, NDArray) else jnp.asarray(r, bool).reshape(())

        def jbody(vs):
            _, new_vars = func(*_wrap(list(vs)))
            return tuple(_unwrap(new_vars))

        out_vars = jax.lax.while_loop(jcond, jbody,
                                      tuple(_unwrap(loop_vars)))
        return [], _wrap(list(out_vars))

    # bounded loop with collected outputs: scan with an active mask
    def jbody(carry, _):
        vs, active, count = carry
        pred = cond_fn(*_wrap(list(vs)))
        pred = pred._data.astype(bool).reshape(()) \
            if isinstance(pred, NDArray) else jnp.asarray(pred, bool)
        step_out, new_vars = func(*_wrap(list(vs)))
        step_out = _unwrap(step_out if isinstance(step_out, (list, tuple))
                           else [step_out])
        new_vars = tuple(_unwrap(new_vars))
        take = jnp.logical_and(active, pred)
        vs_next = tuple(jnp.where(take, nv, ov)
                        for nv, ov in zip(new_vars, vs))
        count = count + take.astype(jnp.int32)
        return (vs_next, take, count), tuple(step_out)

    vs0 = tuple(_unwrap(loop_vars))
    (vs_f, _, n), outs = jax.lax.scan(
        jbody, (vs0, jnp.asarray(True), jnp.asarray(0, jnp.int32)),
        None, length=max_iterations)
    return _wrap(list(outs)), _wrap(list(vs_f))


def cond(pred, then_func, else_func, inputs=None):
    """reference contrib.cond → lax.cond."""
    import jax
    import jax.numpy as jnp
    p = pred() if callable(pred) else pred
    if isinstance(p, NDArray):
        p = p._data
    p = jnp.asarray(p).astype(bool).reshape(())
    out = jax.lax.cond(p,
                       lambda _: _unwrap(then_func()),
                       lambda _: _unwrap(else_func()),
                       operand=None)
    return _wrap(out)
