"""Executor — the bound symbolic graph (reference src/executor/
graph_executor.cc N6 + python/mxnet/executor.py).

Bind lowers the Symbol DAG into one jitted forward (and a vjp-backed
backward); memory planning/in-place/bulking are XLA's.  API parity:
forward(is_train, **kwargs), backward(out_grads), outputs, arg_dict,
grad_dict, aux_dict, copy_params_from, reshape.
"""

from __future__ import annotations

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray


class Executor:
    def __init__(self, symbol, ctx, args, args_grad, grad_req, aux_states):
        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        self.arg_dict = dict(args or {})
        self.grad_dict = dict(args_grad or {})
        self.aux_dict = dict(aux_states or {})
        self.grad_req = grad_req
        missing = [a for a in arg_names if a not in self.arg_dict]
        if missing:
            raise MXNetError(f"bind missing arguments: {missing}")
        # one build per train mode (wrap_train flags differ); training mode
        # also threads out mutated aux states (BN moving stats writeback)
        self._builds = {}
        self._leaves = None
        self.outputs = []
        self._vjp = None

    def _get_build(self, is_train):
        from ..ops import registry as _reg
        if getattr(self, "_builds_epoch", None) != _reg.dispatch_epoch():
            self._builds.clear()  # amp on/off ⇒ stale cast decisions
            self._builds_epoch = _reg.dispatch_epoch()
        entry = self._builds.get(is_train)
        if entry is None:
            import jax
            run, leaves, mut_specs = self._symbol._build_fn(
                train_mode=is_train, collect_mutations=is_train)
            entry = (jax.jit(run), leaves, mut_specs)
            self._builds[is_train] = entry
        self._leaves = entry[1]
        return entry

    def _leaf_arrays(self, extra=None):
        arrays = []
        for s in self._leaves:
            name = s._name
            src = None
            if extra and name in extra:
                src = extra[name]
            elif name in self.arg_dict:
                src = self.arg_dict[name]
            elif name in self.aux_dict:
                src = self.aux_dict[name]
            else:
                raise MXNetError(f"no value bound for input {name!r}")
            arrays.append(src._data if isinstance(src, NDArray) else src)
        return arrays

    def forward(self, is_train=False, **kwargs):
        import jax
        from .. import autograd, random as _rnd
        jit_run, leaves, mut_specs = self._get_build(is_train)
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v._data if isinstance(v, NDArray) else v)
        arrays = self._leaf_arrays()
        key = _rnd.get_key(self._ctx)
        with autograd._scope(training=is_train):
            if is_train and self.grad_req != "null":
                f = lambda *a: jit_run(key, *a)  # noqa: E731
                out, self._vjp = jax.vjp(f, *arrays)
            else:
                out = jit_run(key, *arrays)
                self._vjp = None
        if is_train:
            out, muts = out
            # FMutateInputs writeback: updated aux states land in aux_dict
            for (leaf_name, _, _), val in zip(mut_specs, muts):
                dst = self.aux_dict.get(leaf_name,
                                        self.arg_dict.get(leaf_name))
                if dst is not None:
                    dst._set_data(val)
        self._out_was_tuple = isinstance(out, tuple)
        outs = out if self._out_was_tuple else (out,)
        self.outputs = [NDArray._from_data(o, ctx=self._ctx) for o in outs]
        return self.outputs

    def backward(self, out_grads=None, is_train=True):  # noqa: ARG002
        import jax.numpy as jnp
        if self._vjp is None:
            raise MXNetError("backward requires forward(is_train=True) first")
        if out_grads is None:
            cts = tuple(jnp.ones(o.shape, o.dtype) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(g._data for g in out_grads)
        ct_main = cts if self._out_was_tuple else cts[0]
        # training forward returns (main, mutated_aux): zero cotangents for
        # the aux updates (they are state writes, not differentiated outputs)
        _, _, mut_specs = self._get_build(True)
        mut_cts = tuple(
            jnp.zeros(self.aux_dict[n].shape, self.aux_dict[n].dtype)
            if n in self.aux_dict else
            jnp.zeros(self.arg_dict[n].shape, self.arg_dict[n].dtype)
            for (n, _, _) in mut_specs)
        grads = self._vjp((ct_main, mut_cts))
        for s, g in zip(self._leaves, grads):
            dst = self.grad_dict.get(s._name)
            if dst is None:
                continue
            if self.grad_req == "add":
                dst._set_data(dst._data + g)
            elif self.grad_req != "null":
                dst._set_data(g)

    @property
    def arg_arrays(self):
        return [self.arg_dict[a] for a in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(a)
                for a in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[a]
                for a in self._symbol.list_auxiliary_states()]

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(v._data)
            elif not allow_extra_params:
                raise MXNetError(f"extra param {k}")
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._set_data(v._data)
                elif not allow_extra_params:
                    raise MXNetError(f"extra aux {k}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):  # noqa: ARG002
        args = {k: nd.zeros(v, ctx=self._ctx) for k, v in kwargs.items()
                if k in self.arg_dict}
        new_args = dict(self.arg_dict)
        new_args.update(args)
        grads = {k: nd.zeros(v.shape, ctx=self._ctx)
                 for k, v in new_args.items()} \
            if self.grad_req != "null" else None
        return Executor(self._symbol, self._ctx, new_args, grads,
                        self.grad_req, dict(self.aux_dict))
