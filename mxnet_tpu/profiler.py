"""mx.profiler — facade over jax.profiler + a host-side dispatch ledger.

Rebuild of src/profiler/* (N20) + python/mxnet/profiler.py (P20).  The
reference hooks the engine's ExecuteOprBlock to emit Chrome-trace JSON and
per-op aggregates; here the XLA/TensorBoard trace comes from jax.profiler
(device timeline incl. fusion boundaries), and the per-op aggregate table
comes from a ledger the op dispatcher feeds when profiling is on
(SURVEY §5.1 TPU mapping).

API parity: set_config, set_state('run'/'stop'), start/stop, dump, dumps,
scope/Task/Counter/Marker objects, pause/resume.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import defaultdict

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "scope", "Task", "Frame", "Counter", "Marker"]

_state = {
    "running": False,
    "filename": "profile.json",
    "trace_dir": None,
    "aggregate": defaultdict(lambda: [0, 0.0, float("inf"), 0.0]),
    # name -> [count, total_s, min_s, max_s]
    "lock": threading.Lock(),
}


def set_config(filename="profile.json", profile_all=False, profile_symbolic=False,
               profile_imperative=False, profile_memory=False, profile_api=False,
               aggregate_stats=True, continuous_dump=False, **kwargs):  # noqa: ARG001
    _state["filename"] = filename
    _state["trace_dir"] = os.path.splitext(filename)[0] + "_xla_trace"


def is_running():
    return _state["running"]


def set_state(state="stop", profile_process="worker"):  # noqa: ARG001
    if state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):  # noqa: ARG001
    if _state["running"]:
        return
    _state["running"] = True
    _state["aggregate"].clear()
    trace_dir = _state["trace_dir"] or "profile_xla_trace"
    try:
        import jax
        jax.profiler.start_trace(trace_dir)
        _state["xla_trace"] = True
    except Exception:
        _state["xla_trace"] = False


def stop(profile_process="worker"):  # noqa: ARG001
    if not _state["running"]:
        return
    _state["running"] = False
    if _state.get("xla_trace"):
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass


def pause(profile_process="worker"):  # noqa: ARG001
    _state["running"] = False


def resume(profile_process="worker"):  # noqa: ARG001
    _state["running"] = True


def record_op(name, seconds):
    """Fed by ops.registry dispatch when profiling is on (the
    ExecuteOprBlock hook analog)."""
    with _state["lock"]:
        ent = _state["aggregate"][name]
        ent[0] += 1
        ent[1] += seconds
        ent[2] = min(ent[2], seconds)
        ent[3] = max(ent[3], seconds)


def dumps(reset=False, format="table"):  # noqa: ARG001
    """Aggregate per-op stats table (reference aggregate_stats.cc output)."""
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
             f"{'Max(ms)':>10}{'Avg(ms)':>10}"]
    lines.append("-" * 90)
    with _state["lock"]:
        rows = sorted(_state["aggregate"].items(),
                      key=lambda kv: -kv[1][1])
        for name, (cnt, tot, mn, mx) in rows:
            lines.append(f"{name:<40}{cnt:>8}{tot*1e3:>12.3f}{mn*1e3:>10.3f}"
                         f"{mx*1e3:>10.3f}{tot/cnt*1e3:>10.3f}")
        if reset:
            _state["aggregate"].clear()
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):  # noqa: ARG001
    with open(_state["filename"], "w") as f:
        f.write(dumps())


@contextlib.contextmanager
def scope(name="<unk>"):
    """Profiling scope — annotates the XLA trace and the ledger."""
    import jax
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        if _state["running"]:
            record_op(f"scope:{name}", time.perf_counter() - t0)


class Task:
    def __init__(self, name, domain=None):  # noqa: ARG002
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None and _state["running"]:
            record_op(f"task:{self.name}", time.perf_counter() - self._t0)


Frame = Task


class Counter:
    def __init__(self, name, domain=None, value=0):  # noqa: ARG002
        self.name = name
        self.value = value

    def set_value(self, value):
        self.value = value

    def increment(self, delta=1):
        self.value += delta

    def decrement(self, delta=1):
        self.value -= delta

    __iadd__ = lambda self, d: (self.increment(d), self)[1]
    __isub__ = lambda self, d: (self.decrement(d), self)[1]


class Marker:
    def __init__(self, name, domain=None):  # noqa: ARG002
        self.name = name

    def mark(self, scope="process"):  # noqa: ARG002
        if _state["running"]:
            record_op(f"marker:{self.name}", 0.0)
