"""mx.profiler — facade over mx.telemetry + jax.profiler.

Rebuild of src/profiler/* (N20) + python/mxnet/profiler.py (P20).  The
reference hooks the engine's ExecuteOprBlock to emit Chrome-trace JSON and
per-op aggregates; here the host-side timeline + per-op table come from
mxnet_tpu.telemetry (span tracer + dispatch ledger fed by ops.registry),
and the device timeline (fusion boundaries, HLO ops) from the XLA trace
jax.profiler writes alongside (SURVEY §5.1 host/device split).

API parity: set_config, set_state('run'/'stop'), start/stop, dump, dumps,
scope/Task/Counter/Marker objects, pause/resume.  ``dump()`` writes genuine
Chrome-trace JSON (the reference profile_output); the human table moved to
``dumps(format="table")`` (default) with ``format="json"`` for machines.

State discipline: the XLA trace lifecycle is tracked in ``xla_trace``
*independently* of ``running`` — ``pause()`` stops host-side recording but
keeps the device trace open, and a later ``stop()`` still closes it.
"""

from __future__ import annotations

import contextlib
import json
import os
import time

from . import telemetry
from .base import MXNetError

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "scope", "Task", "Frame", "Counter", "Marker"]

_state = {
    "running": False,
    "filename": "profile.json",
    "trace_dir": None,
    "xla_trace": False,   # device trace open — independent of `running`
    "tel_owner": False,   # start() flipped telemetry on, so stop() turns it off
}


def set_config(filename="profile.json", profile_all=False, profile_symbolic=False,
               profile_imperative=False, profile_memory=False, profile_api=False,
               aggregate_stats=True, continuous_dump=False, **kwargs):  # noqa: ARG001
    _state["filename"] = filename
    _state["trace_dir"] = os.path.splitext(filename)[0] + "_xla_trace"
    telemetry.ledger.set_aggregate_stats(aggregate_stats)


def is_running():
    return _state["running"]


def set_state(state="stop", profile_process="worker"):  # noqa: ARG001
    if state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):  # noqa: ARG001
    if _state["running"]:
        return
    _state["running"] = True
    # fresh profiling session: drop buffered spans AND ledger rows so dump()
    # covers one window (the reference start() resets its aggregates too)
    telemetry.clear()
    _state["tel_owner"] = not telemetry.enable()
    if not _state["xla_trace"]:
        trace_dir = _state["trace_dir"] or "profile_xla_trace"
        try:
            import jax
            jax.profiler.start_trace(trace_dir)
            _state["xla_trace"] = True
        except Exception:
            _state["xla_trace"] = False


def stop(profile_process="worker"):  # noqa: ARG001
    _state["running"] = False
    # tel_owner alone encodes ownership: if telemetry was already on at
    # start() (env switch or user enable), tel_owner is False and we leave it
    if _state["tel_owner"]:
        telemetry.disable()
        _state["tel_owner"] = False
    if _state["xla_trace"]:
        # closes the device trace even after a pause() (running already False)
        _state["xla_trace"] = False
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass


def pause(profile_process="worker"):  # noqa: ARG001
    """Suspend host-side recording; the XLA trace stays open so resume()
    continues into the same device timeline."""
    _state["running"] = False
    if _state["tel_owner"]:
        telemetry.disable()


def resume(profile_process="worker"):  # noqa: ARG001
    _state["running"] = True
    if _state["tel_owner"]:
        telemetry.enable()


def record_op(name, seconds):
    """Feed the per-op aggregate ledger (the ExecuteOprBlock hook analog;
    ops.registry now reports through telemetry.record_dispatch directly)."""
    telemetry.ledger.record_op(name, seconds)


def _ledger_rows(reset=False):
    snap = telemetry.ledger.snapshot(reset=reset)
    return sorted(snap.items(), key=lambda kv: -kv[1][1])


def _aggregate_dict(rows):
    """Ledger rows as the machine-readable aggregate schema (shared by
    dumps(format="json") and dump()'s otherData.opAggregates)."""
    return {
        name: {"calls": cnt, "total_ms": tot * 1e3, "min_ms": mn * 1e3,
               "max_ms": mx * 1e3, "avg_ms": tot / cnt * 1e3}
        for name, (cnt, tot, mn, mx) in rows}


def dumps(reset=False, format="table"):  # noqa: A002
    """Aggregate per-op stats (reference aggregate_stats.cc output).

    format="table" — the human-readable text table (default);
    format="json"  — machine-readable {name: {calls, total_ms, ...}}.
    """
    if format == "json":
        return json.dumps(_aggregate_dict(_ledger_rows(reset)),
                          indent=2, sort_keys=True)
    if format != "table":
        raise MXNetError(f"unknown dumps format {format!r}: "
                         "expected 'table' or 'json'")
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
             f"{'Max(ms)':>10}{'Avg(ms)':>10}"]
    lines.append("-" * 90)
    for name, (cnt, tot, mn, mx) in _ledger_rows(reset):
        lines.append(f"{name:<40}{cnt:>8}{tot*1e3:>12.3f}{mn*1e3:>10.3f}"
                     f"{mx*1e3:>10.3f}{tot/cnt*1e3:>10.3f}")
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):  # noqa: ARG001
    """Write the host timeline as Chrome-trace JSON (chrome://tracing /
    Perfetto); the per-op aggregate ledger rides under otherData."""
    trace = telemetry.chrome_trace()
    trace.setdefault("otherData", {})["opAggregates"] = \
        _aggregate_dict(_ledger_rows())
    with open(_state["filename"], "w") as f:
        json.dump(trace, f)


@contextlib.contextmanager
def scope(name="<unk>"):
    """Profiling scope — annotates the XLA trace, the span tracer, and the
    ledger.  A cheap no-op (no jax import, no recording) when neither the
    profiler nor telemetry is active."""
    if not (_state["running"] or telemetry.enabled()):
        yield
        return
    ann_cm = contextlib.nullcontext()
    if _state["xla_trace"]:
        try:
            import jax
            ann = getattr(jax.profiler, "TraceAnnotation", None)
            if ann is not None:
                ann_cm = ann(name)
        except Exception:
            pass
    t0 = time.perf_counter()
    try:
        with telemetry.span(f"scope:{name}", "scope"), ann_cm:
            yield
    finally:
        if _state["running"]:
            record_op(f"scope:{name}", time.perf_counter() - t0)


class Task:
    def __init__(self, name, domain=None):  # noqa: ARG002
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter_ns()

    def stop(self):
        if self._t0 is None:
            return
        t1 = time.perf_counter_ns()
        if telemetry.enabled():
            telemetry.get_tracer().add_event(
                f"task:{self.name}", "task", self._t0, t1)
        if _state["running"]:
            record_op(f"task:{self.name}", (t1 - self._t0) / 1e9)


Frame = Task


class Counter:
    def __init__(self, name, domain=None, value=0):  # noqa: ARG002
        self.name = name
        self.value = value

    def set_value(self, value):
        self.value = value

    def increment(self, delta=1):
        self.value += delta

    def decrement(self, delta=1):
        self.value -= delta

    __iadd__ = lambda self, d: (self.increment(d), self)[1]
    __isub__ = lambda self, d: (self.decrement(d), self)[1]


class Marker:
    def __init__(self, name, domain=None):  # noqa: ARG002
        self.name = name

    def mark(self, scope="process"):  # noqa: ARG002
        telemetry.instant(f"marker:{self.name}", "marker")
        if _state["running"]:
            record_op(f"marker:{self.name}", 0.0)
