"""mx.resilience — fault injection, retry/deadline policies, and
preemption-safe recovery (ISSUE 3 tentpole).

The reference assumes long multi-host runs where workers die and
preemption is routine, but ships no way to bound, recover from, or even
*test* those failures (SURVEY §5.3).  This subsystem is that layer for
the TPU rebuild, wired into the same chokepoints telemetry instruments:

- ``policies`` — composable ``Retry`` (exponential backoff + jitter) and
  ``Deadline`` (per-call timeout → ``KVStoreTimeoutError``) applied to
  dist-kvstore init/push/pull/pushpull_list/barrier and process-group
  bring-up.
- ``chaos`` — deterministic fault injection (delays, transient errors,
  worker death) at named sites, env- and API-driven, so every recovery
  path runs on CPU in CI.
- elastic resume — ``mx.checkpoint`` gained an atomic commit manifest,
  corruption fallback, SIGTERM-triggered emergency save, and an
  ``auto_resume`` restart policy that replays from the last good step.
- graceful degradation — DataLoader worker crashes fall back to
  in-process fetch; fused kvstore bucket failures fall back per-key.

Every recovery event flows through mx.telemetry:
``mxnet_resilience_{retries,faults_injected,deadline_exceeded,resumes,
fallbacks}_total`` plus the ``mxnet_resilience_retry_backoff_seconds``
histogram.  Nothing here imports jax.
"""

from __future__ import annotations

from .. import telemetry as _tel
from . import chaos, policies  # noqa: F401
from .policies import (  # noqa: F401
    Deadline, KVStoreTimeoutError, ResilienceError, Retry,
    RetryExhaustedError, TransientError, is_transient, protect,
)
from .chaos import (  # noqa: F401
    ChaosError, ChaosTransientError, ChaosWorkerDeath,
)
from . import heartbeat  # noqa: F401  (worker-side liveness protocol)
from . import controller  # noqa: F401
from .controller import ElasticController, JobFailedError  # noqa: F401

__all__ = [
    "Retry", "Deadline", "protect", "is_transient",
    "ResilienceError", "TransientError", "RetryExhaustedError",
    "KVStoreTimeoutError",
    "ChaosError", "ChaosTransientError", "ChaosWorkerDeath",
    "chaos", "policies", "record_fallback", "record_resume",
    "heartbeat", "controller", "ElasticController", "JobFailedError",
]

# shared recovery counters (the per-policy ones live in policies.py)
_M_RESUMES = _tel.counter(
    "mxnet_resilience_resumes_total",
    "Elastic resumes: auto_resume restoring state from a checkpoint "
    "(at entry and after an in-run fault).")
_M_FALLBACKS = _tel.counter(
    "mxnet_resilience_fallbacks_total",
    "Graceful degradation EVENTS (one per occurrence): a dataloader batch "
    "refetched in-process, or a fused kvstore bucket replayed per-key.")


def record_fallback(n=1):
    _M_FALLBACKS.inc(n)


def record_resume(n=1):
    _M_RESUMES.inc(n)
