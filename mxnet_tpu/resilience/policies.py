"""Retry / Deadline policies — the composable half of mx.resilience.

The reference PS stack handles worker failure with ZeroMQ-level
retransmission and van timeouts (ps-lite ``van.cc``); the TPU rebuild's
blocking points are instead jax.distributed bring-up and compiled
collectives, which hang rather than error when a peer is gone.  These two
wrappers bound every such call:

- ``Retry`` — exponential backoff with jitter around *transient* failures
  (chaos-injected faults, connection resets).  Permanent errors and
  deadline expirations propagate immediately: retrying a wedged collective
  would only desynchronize the collective ordering across ranks.
- ``Deadline`` — runs a callable on a daemon worker thread and joins with
  a timeout, so a hung ``psum``/barrier/bring-up surfaces as
  ``KVStoreTimeoutError`` instead of blocking the process forever.  The
  wedged thread is abandoned (daemon → never blocks interpreter exit);
  that leak is the price of interrupting a call XLA gives us no handle to
  cancel.

Both read their defaults from config (``MXNET_RESILIENCE_MAX_RETRIES``,
``MXNET_RESILIENCE_BACKOFF_S``, ``MXNET_RESILIENCE_BACKOFF_MAX_S``,
``MXNET_KVSTORE_TIMEOUT_S``) and compose: ``Retry.call(Deadline.call, fn)``
or the ``protect()`` helper.  Nothing here imports jax.
"""

from __future__ import annotations

import queue as _queue
import random
import threading
import time
import weakref

from ..analysis.runtime import tracked as _tracked
from ..base import MXNetError
from .. import config
from .. import telemetry as _tel

__all__ = [
    "TransientError", "ResilienceError", "RetryExhaustedError",
    "KVStoreTimeoutError", "Retry", "Deadline", "protect", "is_transient",
]

_M_RETRIES = _tel.counter(
    "mxnet_resilience_retries_total",
    "Transient failures absorbed by a Retry policy (one per re-attempt).")
_M_DEADLINE = _tel.counter(
    "mxnet_resilience_deadline_exceeded_total",
    "Calls that exceeded their Deadline and raised KVStoreTimeoutError.")
_M_BACKOFF_SECONDS = _tel.histogram(
    "mxnet_resilience_retry_backoff_seconds",
    "Backoff slept before each retry attempt.")


class ResilienceError(MXNetError):
    """Base for errors raised by the resilience layer itself."""


class TransientError(Exception):
    """Marker mix-in: failures safe to retry (the operation did not
    partially commit).  Chaos transient faults and wrappable I/O errors
    carry it; ``Retry`` only re-attempts exceptions that are transient."""


class RetryExhaustedError(ResilienceError):
    """A Retry policy ran out of attempts; ``__cause__`` is the last
    underlying failure."""


class KVStoreTimeoutError(ResilienceError):
    """A deadline-bounded blocking call (dist bring-up, allreduce,
    barrier) did not complete in time — the failure mode of a dead or
    wedged peer, which would otherwise hang forever."""


def is_transient(exc):
    """True when ``exc`` is safe to retry: marked TransientError, flagged
    ``transient=True``, or a connection-level OS error."""
    if isinstance(exc, TransientError) or getattr(exc, "transient", False):
        return True
    return isinstance(exc, (ConnectionError, BrokenPipeError))


class Retry:
    """Exponential backoff + full jitter around transient failures.

    ``max_retries`` re-attempts AFTER the first try (0 = fail fast);
    attempt ``k`` sleeps ``backoff_s * 2**k`` capped at ``backoff_max_s``,
    scaled by a uniform jitter in ``[1 - jitter, 1 + jitter]`` so a fleet
    of workers retrying the same stalled endpoint doesn't stampede in
    lockstep.
    """

    def __init__(self, max_retries=None, backoff_s=None, backoff_max_s=None,
                 jitter=0.25, retry_on=None, site=""):
        self.max_retries = max_retries if max_retries is not None \
            else config.get_int("MXNET_RESILIENCE_MAX_RETRIES", 3)
        self.backoff_s = backoff_s if backoff_s is not None \
            else config.get_float("MXNET_RESILIENCE_BACKOFF_S", 0.05)
        self.backoff_max_s = backoff_max_s if backoff_max_s is not None \
            else config.get_float("MXNET_RESILIENCE_BACKOFF_MAX_S", 2.0)
        self.jitter = float(jitter)
        self.retry_on = retry_on  # extra exception types to treat transient
        self.site = site

    def _retryable(self, exc):
        if self.retry_on is not None and isinstance(exc, self.retry_on):
            return True
        return is_transient(exc)

    def backoff_delay(self, attempt):
        """Jittered delay (seconds) before re-attempt ``attempt`` (0 =
        first retry) — the policy's schedule exposed for callers that
        escalate OUTSIDE call() (the elastic controller sleeps this
        between whole-job restarts).  Negative attempts cost nothing."""
        if attempt < 0:
            return 0.0
        delay = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        if self.jitter:
            delay *= 1 + self.jitter * (2 * random.random() - 1)
        return max(0.0, delay)

    def call(self, fn, *args, **kwargs):
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 — filtered just below
                if not self._retryable(exc):
                    raise
                if attempt >= self.max_retries:
                    raise RetryExhaustedError(
                        f"{self.site or 'call'} failed after "
                        f"{attempt + 1} attempts: {exc}") from exc
                delay = self.backoff_delay(attempt)
                _M_RETRIES.inc()
                _M_BACKOFF_SECONDS.observe(delay)
                if delay > 0:
                    time.sleep(delay)
                attempt += 1

    def wrap(self, fn):
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        return wrapped


def _deadline_worker(ref, q):
    """Daemon loop serving one Deadline's calls.  Exits on the ``None``
    sentinel, when its owner is gone, or when the owner abandoned this
    queue after a timeout (a fresh worker owns the replacement)."""
    while True:
        task = q.get()
        if task is None:
            return
        fn, args, kwargs, done, box = task
        try:
            box["value"] = fn(*args, **kwargs)
        except BaseException as exc:  # noqa: BLE001 — re-raised by call()
            box["error"] = exc
        done.set()
        # drop the call's refs (args/result can be multi-MB arrays) so an
        # idle worker blocked in q.get() doesn't pin them
        task = fn = args = kwargs = done = box = None
        owner = ref()
        if owner is None or owner._task_queue is not q:
            return


class Deadline:
    """Per-call timeout for blocking operations that cannot be cancelled.

    ``timeout_s <= 0`` disables the bound (direct call, zero overhead).
    Calls run on ONE persistent daemon worker thread (created lazily, no
    per-call spawn cost on the kvstore dispatch path); on expiry the
    worker — wedged inside a call XLA gives us no handle to cancel — is
    abandoned (daemon: never blocks interpreter exit) and a fresh one
    serves subsequent calls.  Calls on one Deadline serialize; use one
    instance per call-site, not a shared global.
    """

    def __init__(self, timeout_s=None, site=""):
        self.timeout_s = timeout_s if timeout_s is not None \
            else config.get_float("MXNET_KVSTORE_TIMEOUT_S", 300.0)
        self.site = site
        self._lock = _tracked(threading.Lock(), "Deadline._lock")
        self._task_queue = None
        self._worker = None

    def _submit(self, task):
        with self._lock:
            if self._task_queue is None or self._worker is None \
                    or not self._worker.is_alive():
                self._task_queue = _queue.SimpleQueue()
                self._worker = threading.Thread(
                    target=_deadline_worker,
                    args=(weakref.ref(self), self._task_queue),
                    daemon=True,
                    name=f"mx-deadline-{self.site or 'call'}")
                self._worker.start()
            self._task_queue.put(task)

    def _abandon(self):
        """Forget the wedged worker; the daemon thread dies with its call
        (or notices the queue swap and exits if the call ever returns)."""
        with self._lock:
            self._task_queue = None
            self._worker = None

    def call(self, fn, *args, **kwargs):
        t = self.timeout_s
        if not t or t <= 0:
            return fn(*args, **kwargs)
        box = {}
        done = threading.Event()
        self._submit((fn, args, kwargs, done, box))
        if not done.wait(t):
            self._abandon()
            _M_DEADLINE.inc()
            # flight recorder (ISSUE 10): a blown deadline is exactly the
            # "dead peer" moment the black box exists for — every survivor
            # of a chaos-lane worker death leaves a postmortem here
            _tel.flightrec.dump(f"deadline.{self.site or 'call'}")
            raise KVStoreTimeoutError(
                f"{self.site or 'call'} exceeded its {t:g}s deadline "
                "(MXNET_KVSTORE_TIMEOUT_S); a peer is likely dead or "
                "wedged — the blocked call was abandoned")
        if "error" in box:
            raise box["error"]
        return box["value"]

    def close(self):
        """Stop the idle worker (optional; daemon threads never block
        exit, this just tidies long-lived processes)."""
        with self._lock:
            q = self._task_queue
            self._task_queue = None
            self._worker = None
        if q is not None:
            q.put(None)

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def wrap(self, fn):
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        return wrapped


def protect(fn, retry=None, deadline=None, site=""):
    """Compose retry-around-deadline: each attempt is deadline-bounded,
    transient failures back off and re-attempt, timeouts propagate (a
    wedged collective must not be blindly re-entered)."""
    retry = retry if retry is not None else Retry(site=site)
    deadline = deadline if deadline is not None else Deadline(site=site)

    def protected(*args, **kwargs):
        return retry.call(deadline.call, fn, *args, **kwargs)

    return protected
