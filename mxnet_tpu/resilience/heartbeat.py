"""Worker-side liveness heartbeat — the file protocol the elastic
controller watches (ISSUE 11 tentpole, worker half).

A synchronous SPMD worker has exactly two observable failure shapes: it
*dies* (exit code) or it *wedges* (a collective blocked on a dead peer,
a stuck input pipeline).  Exit codes cover the first; this module covers
the second.  When ``MXNET_ELASTIC_HEARTBEAT_DIR`` is set (the controller
injects it per incarnation), a daemon thread atomically rewrites one
small JSON file

    <dir>/hb-rank<RANK>.json

every ``MXNET_ELASTIC_HEARTBEAT_S`` seconds::

    {"rank": 2, "pid": 4711, "time": <unix>, "phase": "running",
     "step": 17, "incarnation": 1, "world": 3,
     "stepclock": {...StepClock.summary()...}, "error": null}

- ``phase`` walks ``spawned → bringup → running → done | failed``; the
  dist kvstore drives the bringup/running transitions at
  ``_ensure_dist`` and marks ``failed`` when the rendezvous times out —
  that is how a bring-up failure is *surfaced to the controller* (which
  then restarts at the same world size instead of shrinking it).
- ``stepclock`` embeds the rolling per-phase medians and the
  input-/comms-/compute-bound verdict (telemetry.stepclock), which is
  what feeds the controller's straggler detection: peers comms-bound,
  one rank compute-bound and slow → that rank is the straggler.
- staleness (``time`` older than ``MXNET_ELASTIC_HANG_S``) is the
  controller's hang signal; writes are write-then-rename so the
  controller never reads a torn file.

Unset dir = fully inert (no thread, no files).  Nothing here imports
jax; the module is safe at any point of worker bring-up.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .. import config
from .. import telemetry as _tel

__all__ = [
    "enabled", "heartbeat_dir", "start", "stop", "beat", "set_phase",
    "set_step", "mark_failed", "mark_done", "read_all", "path_for",
    "status",
]

PREFIX = "hb-rank"

_lock = threading.Lock()
_thread = None
_stop = None           # threading.Event of the running beater
_phase = "spawned"
_step = None
_error = None
_last_beat = None      # monotonic time of the last successful beat


def enabled():
    """True when a heartbeat directory is configured for this process."""
    return bool(config.get("MXNET_ELASTIC_HEARTBEAT_DIR"))


def heartbeat_dir():
    return config.get("MXNET_ELASTIC_HEARTBEAT_DIR")


def _rank():
    return config.get_int("MXNET_DIST_RANK", 0)


def path_for(rank, directory=None):
    directory = directory or heartbeat_dir()
    return os.path.join(directory, f"{PREFIX}{int(rank):05d}.json")


def _record():
    with _lock:
        phase, step, error = _phase, _step, _error
    rec = {
        "rank": _rank(),
        "pid": os.getpid(),
        "time": time.time(),
        "phase": phase,
        "step": step,
        "incarnation": config.get_int("MXNET_ELASTIC_INCARNATION", 0),
        "world": config.get_int("MXNET_DIST_NUM_WORKERS", 1),
        "stepclock": _tel.stepclock.STEP_CLOCK.summary(),
        "error": error,
    }
    return rec


def beat(directory=None):
    """Write one heartbeat now (atomic write-then-rename).  Returns the
    path, or None when no directory is configured.  Never raises — a
    full disk must not kill the training step."""
    global _last_beat
    directory = directory or heartbeat_dir()
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        path = path_for(_rank(), directory)
        tmp = f"{path}.tmp.{os.getpid()}"
        # rename-atomic but deliberately NOT fsynced (unlike the
        # checkpoint manifest): beats are periodic and disposable — the
        # next one supersedes a lost write, and fsync at beat frequency
        # would thrash the disk for nothing
        with open(tmp, "w") as f:
            json.dump(_record(), f)
        os.replace(tmp, path)
        with _lock:
            _last_beat = time.monotonic()
        return path
    except OSError:
        return None


def status():
    """In-process liveness view for the /healthz probe: the current
    phase, whether a beater thread is armed, and the age (seconds) of
    the last successful beat (None until one lands)."""
    with _lock:
        armed = _thread is not None and _thread.is_alive()
        age = None if _last_beat is None \
            else max(0.0, time.monotonic() - _last_beat)
        return {"phase": _phase, "armed": armed,
                "heartbeat_age_s": age}


def set_phase(phase):
    """Advance the lifecycle phase and beat immediately (phase changes
    are exactly the moments the controller must not miss)."""
    global _phase
    with _lock:
        _phase = str(phase)
    beat()


def set_step(step):
    """Record the step the worker is about to run (cheap: the periodic
    beater ships it; no file write here — this sits on the step path)."""
    global _step
    with _lock:
        _step = int(step)


def mark_failed(error):
    """Terminal failure beat (bring-up timeout, unrecoverable fault):
    the controller reads ``phase=failed`` + ``error`` to classify the
    death — a failure before ``running`` is a bring-up problem and the
    world size is NOT shrunk for it."""
    global _phase, _error
    with _lock:
        _phase = "failed"
        _error = str(error)[:500]
    beat()


def mark_done():
    """Clean-completion beat: an adopted worker (the restarted
    controller holds no Popen handle, so no exit code) is judged by
    this."""
    global _phase
    with _lock:
        _phase = "done"
    beat()


def _beater(stop_ev, interval_s):
    while not stop_ev.wait(interval_s):
        with _lock:
            mine = _stop is stop_ev
        if not mine:       # a newer start() owns the file now
            return
        beat()


def start(interval_s=None):
    """Start the periodic beater (idempotent; inert when no directory is
    configured).  Called by the dist kvstore at bring-up; standalone
    workers may call it directly."""
    global _thread, _stop
    if not enabled():
        return False
    if interval_s is None:
        interval_s = config.get_float("MXNET_ELASTIC_HEARTBEAT_S", 2.0)
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        _stop = threading.Event()
        _thread = threading.Thread(
            target=_beater, args=(_stop, max(0.05, float(interval_s))),
            daemon=True, name="mx-heartbeat")
        _thread.start()
    beat()
    return True


def stop():
    """Stop the beater (the final phase beat, if any, stays on disk)."""
    global _thread, _stop
    with _lock:
        ev, _stop = _stop, None
        _thread = None
    if ev is not None:
        ev.set()


def read_all(directory):
    """Controller side: parse every heartbeat file in ``directory`` →
    {rank: record}.  Torn/corrupt files are skipped (atomic renames make
    them rare)."""
    out = {}
    if not directory or not os.path.isdir(directory):
        return out
    for fn in sorted(os.listdir(directory)):
        if not (fn.startswith(PREFIX) and fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, fn)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and "rank" in rec:
            out[int(rec["rank"])] = rec
    return out
