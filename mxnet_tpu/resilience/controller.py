"""Elastic training controller — jobs that outlive their workers
(ISSUE 11 tentpole; the role upstream MXNet's ``dmlc_tracker`` played,
SURVEY L0, rebuilt as a control plane over the TPU stack's own
resilience primitives).

Every recovery primitive below it already exists — deadline-bounded
collectives (ISSUE 3), topology-free gather-on-save checkpoints with an
atomic commit manifest (ISSUES 3/8), per-rank flight-recorder
postmortems and mergeable telemetry shards (ISSUE 10).  This module is
the loop that *uses* them: it spawns an n-rank job, watches it, resizes
it, and survives its own death.

Spawn
    One process per rank with injected ``MXNET_DIST_*`` env (coordinator
    address, rank, world size), per-job telemetry / flight-recorder /
    heartbeat directories, per-incarnation per-rank log files, and
    ``MXNET_ELASTIC_{INCARNATION,WORLD_TARGET}`` so workers can shard a
    fixed data space over a changing world.

Watch
    Exit codes (owned workers), the heartbeat file protocol
    (``resilience.heartbeat``: staleness beyond ``MXNET_ELASTIC_HANG_S``
    = hang → SIGKILL), flight-recorder dumps (indexed into every failure
    event and the terminal roll-up), and the stepclock verdicts embedded
    in heartbeats: when every peer is comms-bound and exactly one rank
    is not — and its compute median exceeds the configurable straggler
    factor — that rank is killed and the world resized around it.

Resize
    On worker death past bring-up the world shrinks by one (never below
    ``MXNET_ELASTIC_MIN_WORKERS``) and the whole job restarts from the
    last *committed* checkpoint step with fresh rank/world env — the
    topology-free checkpoint is what makes n=4 state restartable at n=3.
    Once the degraded incarnation commits ``MXNET_ELASTIC_REGROW_STEPS``
    further steps (read from the checkpoint manifest), the controller
    drains it (SIGTERM — the workers' preemption save path) and grows
    back to the target world.  Bring-up failures (heartbeat never
    reached ``running``) restart at the *same* world size.  Unplanned
    restarts burn the ``MXNET_ELASTIC_MAX_RESTARTS`` budget and back off
    with the Retry policy's exponential schedule; planned resizes are
    free.

Survive
    Every transition is committed to ``controller.json`` first (atomic
    write-then-rename, the checkpoint manifest discipline) so a
    controller killed at ANY point — including mid-resize, which the
    ``controller.resize`` chaos site exercises deliberately — can be
    restarted on the same workdir and *re-adopt* the job: live recorded
    pids are adopted (judged thereafter by heartbeat phase, since an
    adopted worker has no waitable exit code), a half-finished drain is
    finished, a half-finished spawn is killed and respawned.

On any terminal outcome the controller writes a postmortem roll-up
(``<workdir>/report/``): the merged Chrome trace and merged Prometheus
snapshot over every rank's telemetry shard plus its own, a per-rank
verdict table, the flight-recorder dump index, and ``summary.json`` with
the full event history.  Nothing here imports jax — the control plane
must come up (and report) even when the accelerator stack cannot.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import time

from ..base import MXNetError
from .. import config
from .. import telemetry as _tel
from . import chaos as _chaos
from . import heartbeat as _hb
from .policies import Retry

__all__ = ["ElasticController", "JobFailedError", "find_straggler"]

STATE_FILE = "controller.json"
STATE_VERSION = 1

_M_RESTARTS = _tel.counter(
    "mxnet_controller_restarts_total",
    "Unplanned whole-job restarts the controller performed (burns the "
    "MXNET_ELASTIC_MAX_RESTARTS budget).")
_M_RESIZES = _tel.counter(
    "mxnet_controller_resizes_total",
    "World-size changes (shrink on failure, grow-back after probation).")
_M_FAILURES = _tel.counter(
    "mxnet_controller_worker_failures_total",
    "Worker failure events observed (nonzero exits, hangs, stragglers).")
_M_HANGS = _tel.counter(
    "mxnet_controller_hangs_total",
    "Workers SIGKILLed for heartbeat staleness (MXNET_ELASTIC_HANG_S).")
_M_STRAGGLERS = _tel.counter(
    "mxnet_controller_stragglers_total",
    "Workers killed by straggler detection (peers comms-bound, one rank "
    "compute-bound beyond MXNET_ELASTIC_STRAGGLER_FACTOR).")
_G_WORLD = _tel.gauge(
    "mxnet_controller_world_size", "Current live world size.")
_G_LIVE = _tel.gauge(
    "mxnet_controller_live_workers", "Workers currently alive.")
_G_HB_AGE = _tel.gauge(
    "mxnet_controller_heartbeat_age_seconds",
    "Oldest live worker's heartbeat age at the last poll — the "
    "controller-side liveness view of the job.")


class JobFailedError(MXNetError):
    """The job died terminally: restart budget exhausted (or failure
    with restarts disabled).  The postmortem roll-up is already on disk
    when this raises."""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _pid_alive(pid):
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def _pid_matches(pid, workdir):
    """Best-effort guard against PID reuse when adopting: every worker
    is spawned with cwd=workdir, so a recorded pid whose /proc cwd no
    longer points there is some other process wearing a recycled pid.
    Unknowable platforms (no /proc) answer True."""
    try:
        cwd = os.readlink(f"/proc/{int(pid)}/cwd")
    except OSError:
        return True
    return os.path.realpath(cwd) == os.path.realpath(workdir)


def _kill(pid, sig):
    try:
        os.kill(int(pid), sig)
    except OSError:
        pass


def find_straggler(heartbeats, factor):
    """The straggler rank, or None.

    Fed by the stepclock comms-bound verdict each heartbeat embeds: in a
    synchronous job a straggler makes every *peer* wait inside the
    collective (verdict ``comms-bound``) while the straggler itself is
    the one rank that is not — and its compute median exceeds ``factor``
    times the fastest peer's.  Requires >= 3 reporting ranks (with two,
    "everyone else" is one rank — no quorum).  ``factor <= 0`` disables.
    """
    if not factor or factor <= 0:
        return None
    live = [h for h in heartbeats.values()
            if h.get("phase") == "running"
            and (h.get("stepclock") or {}).get("steps")]
    if len(live) < 3:
        return None
    comms = [h for h in live
             if h["stepclock"].get("verdict") == "comms-bound"]
    rest = [h for h in live
            if h["stepclock"].get("verdict") != "comms-bound"]
    if len(rest) != 1 or len(comms) != len(live) - 1:
        return None
    med = (rest[0]["stepclock"].get("phases", {})
           .get("compute", {}).get("median", 0.0))
    peer_meds = [h["stepclock"].get("phases", {})
                 .get("compute", {}).get("median", 0.0) for h in comms]
    if med > float(factor) * max(min(peer_meds), 1e-9):
        return int(rest[0]["rank"])
    return None


class _Worker:
    """One rank of the current incarnation.  ``proc`` is None for an
    ADOPTED worker (spawned by a previous controller incarnation): no
    exit code exists for it, so a dead adopted worker is judged by its
    final heartbeat phase (``done`` = clean, anything else = failure)."""

    __slots__ = ("rank", "pid", "proc", "log", "started", "exit_code",
                 "killed")

    def __init__(self, rank, pid, proc=None, log=None):
        self.rank = int(rank)
        self.pid = int(pid)
        self.proc = proc
        self.log = log
        self.started = time.time()
        self.exit_code = None
        self.killed = False

    def alive(self):
        return self.exit_code is None


class ElasticController:
    """Spawn, watch, resize, survive (module docstring has the story).

    ``command`` is the worker argv (every rank runs it; rank identity
    arrives via the injected env).  ``workdir`` owns everything: the
    state file, heartbeat/telemetry/flightrec collection dirs, per-rank
    logs, the report roll-up — and, by convention, the job's checkpoint
    tree at ``<workdir>/<ckpt_dir>`` whose ``manifest.json`` the
    controller reads (jax-free) for resize/regrow decisions.
    """

    def __init__(self, command, nprocs, workdir, *, min_workers=None,
                 max_restarts=None, regrow_steps=None, hang_s=None,
                 straggler_factor=None, grace_s=None, heartbeat_s=None,
                 env_extra=None, cpu_devices_per_worker=None,
                 poll_s=0.2, ckpt_dir="ckpt"):
        if not command:
            raise MXNetError("elastic controller needs a worker command")
        self._command = [str(c) for c in command]
        self._target = int(nprocs)
        if self._target < 1:
            raise MXNetError(f"nprocs must be >= 1, got {nprocs}")
        self._workdir = os.path.abspath(workdir)
        mw = min_workers if min_workers is not None \
            else config.get_int("MXNET_ELASTIC_MIN_WORKERS", 1)
        # clamp into [1, nprocs]: a floor of 0 would let a failure
        # shrink the world to nothing, which the watch loop would read
        # as vacuous success
        self._min_workers = max(1, min(int(mw), self._target))
        self._max_restarts = max_restarts if max_restarts is not None \
            else config.get_int("MXNET_ELASTIC_MAX_RESTARTS", 8)
        self._regrow_steps = regrow_steps if regrow_steps is not None \
            else config.get_int("MXNET_ELASTIC_REGROW_STEPS", 0)
        self._hang_s = hang_s if hang_s is not None \
            else config.get_float("MXNET_ELASTIC_HANG_S", 60.0)
        self._straggler_factor = straggler_factor \
            if straggler_factor is not None \
            else config.get_float("MXNET_ELASTIC_STRAGGLER_FACTOR", 0.0)
        self._grace_s = grace_s if grace_s is not None \
            else config.get_float("MXNET_ELASTIC_GRACE_S", 10.0)
        self._heartbeat_s = heartbeat_s if heartbeat_s is not None \
            else config.get_float("MXNET_ELASTIC_HEARTBEAT_S", 2.0)
        self._env_extra = dict(env_extra or {})
        self._cpu_devices = cpu_devices_per_worker
        self._poll_s = float(poll_s)
        self._ckpt_dir = ckpt_dir if os.path.isabs(ckpt_dir) \
            else os.path.join(self._workdir, ckpt_dir)
        # escalation schedule: the SAME exponential-backoff policy the
        # kvstore retries use, applied between whole-job restarts
        self._backoff = Retry(site="controller.restart")

        self._workers = []
        self._world = 0
        self._incarnation = -1          # first spawn makes it 0
        self._restarts = 0
        self._regrow_at = None
        self._coordinator = None
        self._history = []
        self._outcome = None

    # -- paths --------------------------------------------------------------

    @property
    def workdir(self):
        return self._workdir

    def _state_path(self):
        return os.path.join(self._workdir, STATE_FILE)

    def _telemetry_dir(self):
        return os.path.join(self._workdir, "telemetry")

    def _flightrec_dir(self):
        return os.path.join(self._workdir, "flightrec")

    def _hb_dir(self, incarnation=None):
        k = self._incarnation if incarnation is None else incarnation
        return os.path.join(self._workdir, "hb", f"inc{int(k):04d}")

    def _log_path(self, rank):
        return os.path.join(self._workdir, "logs",
                            f"inc{self._incarnation:04d}-rank{rank}.log")

    def _report_dir(self):
        return os.path.join(self._workdir, "report")

    # -- crash-consistent state (write-then-rename, manifest discipline) ----

    def _save_state(self, phase, **extra):
        st = {
            "version": STATE_VERSION,
            "phase": phase,
            "command": self._command,
            "target_world": self._target,
            "world": self._world,
            "incarnation": self._incarnation,
            "restarts": self._restarts,
            "regrow_at": self._regrow_at,
            "coordinator": self._coordinator,
            "workers": [{"rank": w.rank, "pid": w.pid, "log": w.log}
                        for w in self._workers],
            "history": self._history[-200:],
        }
        st.update(extra)
        os.makedirs(self._workdir, exist_ok=True)
        path = self._state_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(st, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _load_state(self):
        try:
            with open(self._state_path()) as f:
                st = json.load(f)
        except (FileNotFoundError, ValueError, OSError):
            return None
        return st if isinstance(st, dict) and "phase" in st else None

    def _event(self, name, **attrs):
        ev = {"t": time.time(), "event": name,
              "incarnation": self._incarnation, "world": self._world}
        ev.update(attrs)
        self._history.append(ev)
        _tel.instant(f"controller.{name}", "controller", **attrs)

    # -- spawn --------------------------------------------------------------

    def _worker_env(self, rank, world):
        env = dict(os.environ)
        # per-job observability: every rank exports a mergeable telemetry
        # shard and leaves flight-recorder postmortems where the roll-up
        # reads them — FORCED over ambient env (an inherited
        # MXNET_TELEMETRY_DIR would divert the shards and leave the
        # merged report empty); an explicit env_extra may still override
        env["MXNET_TELEMETRY"] = "1"
        env["MXNET_TELEMETRY_DIR"] = self._telemetry_dir()
        env["MXNET_FLIGHTREC_DIR"] = self._flightrec_dir()
        env.update(self._env_extra)
        env["MXNET_DIST_COORDINATOR"] = self._coordinator
        env["MXNET_DIST_NUM_WORKERS"] = str(world)
        env["MXNET_DIST_RANK"] = str(rank)
        env["MXNET_ELASTIC_INCARNATION"] = str(self._incarnation)
        env["MXNET_ELASTIC_WORLD_TARGET"] = str(self._target)
        env["MXNET_ELASTIC_HEARTBEAT_DIR"] = self._hb_dir()
        env["MXNET_ELASTIC_HEARTBEAT_S"] = str(self._heartbeat_s)
        if self._cpu_devices:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"{env.get('XLA_FLAGS', '')} --xla_force_host_platform_"
                f"device_count={self._cpu_devices}").strip()
        return env

    def _spawn_world(self, world):
        """Bring up one incarnation at ``world`` ranks.  The pid list is
        committed to the state file as each worker spawns, so a
        controller death mid-spawn leaves every orphan findable."""
        if _chaos._ACTIVE:
            _chaos.hit("controller.spawn")
        world = int(world)
        self._incarnation += 1
        self._world = world
        self._workers = []
        self._coordinator = f"127.0.0.1:{_free_port()}"
        os.makedirs(self._hb_dir(), exist_ok=True)
        os.makedirs(os.path.join(self._workdir, "logs"), exist_ok=True)
        self._save_state("spawning")
        with _tel.span("controller.spawn", "controller", world=world,
                       incarnation=self._incarnation):
            for rank in range(world):
                log = self._log_path(rank)
                with open(log, "ab") as lf:
                    proc = subprocess.Popen(
                        self._command, env=self._worker_env(rank, world),
                        stdout=lf, stderr=subprocess.STDOUT,
                        cwd=self._workdir)
                self._workers.append(_Worker(rank, proc.pid, proc, log))
                self._save_state("spawning")
        # degraded worlds run on probation: after REGROW_STEPS further
        # committed checkpoint steps the controller grows back
        if world < self._target and self._regrow_steps > 0:
            latest = self._manifest_latest()
            self._regrow_at = (latest if latest is not None else -1) \
                + self._regrow_steps
        else:
            self._regrow_at = None
        self._save_state("running")
        _G_WORLD.set(world)
        self._event("spawned", world=world, incarnation=self._incarnation,
                    coordinator=self._coordinator)

    # -- watch --------------------------------------------------------------

    def _read_heartbeats(self):
        return _hb.read_all(self._hb_dir())

    def _manifest_latest(self):
        """Latest COMMITTED checkpoint step, read jax-free straight from
        the manifest (the atomicity layer makes this safe to poll)."""
        try:
            with open(os.path.join(self._ckpt_dir, "manifest.json")) as f:
                steps = json.load(f).get("committed") or []
            return max(int(s) for s in steps) if steps else None
        except (OSError, ValueError):
            return None

    def _flightrec_dumps(self):
        d = self._flightrec_dir()
        try:
            return sorted(fn for fn in os.listdir(d)
                          if fn.startswith("flightrec-")
                          and fn.endswith(".json"))
        except OSError:
            return []

    def _poll_workers(self, heartbeats):
        """Refresh exit codes.  Owned workers report via wait(); adopted
        workers via pid liveness + their final heartbeat phase — with
        the pid-reuse guard re-checked, so a recycled pid reads as the
        worker's death, not as an immortal (and later SIGKILLable)
        stranger."""
        for w in self._workers:
            if not w.alive():
                continue
            if w.proc is not None:
                code = w.proc.poll()
                if code is not None:
                    w.exit_code = code
            elif not (_pid_alive(w.pid)
                      and _pid_matches(w.pid, self._workdir)):
                hb = heartbeats.get(w.rank)
                w.exit_code = 0 if hb and hb.get("phase") == "done" else 1

    def _check_hangs(self, heartbeats, now):
        """SIGKILL workers whose heartbeat went stale (a wedged rank
        holds every peer hostage inside the collective).  A worker that
        never beat is measured from its spawn time — bring-up counts."""
        if self._hang_s <= 0:
            return None
        hung = None
        oldest = 0.0
        for w in self._workers:
            if not w.alive():
                continue
            hb = heartbeats.get(w.rank)
            last = hb.get("time", w.started) if hb else w.started
            age = now - last
            oldest = max(oldest, age)
            if age > self._hang_s and hung is None:
                hung = w
        _G_HB_AGE.set(oldest)
        if hung is None:
            return None
        _M_HANGS.inc()
        self._event("worker_hang", rank=hung.rank, pid=hung.pid,
                    age_s=round(now - (heartbeats.get(hung.rank) or {})
                                .get("time", hung.started), 3))
        _kill(hung.pid, signal.SIGKILL)
        hung.killed = True
        hung.exit_code = -9
        return hung.rank

    def _check_straggler(self, heartbeats):
        r = find_straggler(heartbeats, self._straggler_factor)
        if r is None:
            return None
        w = next((w for w in self._workers if w.rank == r and w.alive()),
                 None)
        if w is None:
            return None
        _M_STRAGGLERS.inc()
        self._event("straggler", rank=r, pid=w.pid)
        _kill(w.pid, signal.SIGKILL)
        w.killed = True
        w.exit_code = -9
        return r

    def _reached_running(self, heartbeats):
        return any(h.get("phase") in ("running", "done")
                   for h in heartbeats.values())

    # -- resize -------------------------------------------------------------

    def _drain(self, reason, next_world=None, phase="draining"):
        """Stop every live worker: SIGTERM (the preemption-save path the
        checkpoint SIGTERM hook and flight recorder both handle), a
        grace period, then SIGKILL.  The drain intent is committed to
        the state file FIRST so a controller death mid-drain is
        resumable.  A TERMINAL drain passes phase='failed' — the
        outcome must be on disk before the reaping starts, or a crash
        mid-drain would let a rerun resurrect a budget-exhausted job."""
        self._save_state(phase, reason=reason, next_world=next_world)
        with _tel.span("controller.drain", "controller", reason=reason):
            for w in self._workers:
                if w.alive():
                    _kill(w.pid, signal.SIGTERM)
            deadline = time.time() + max(0.0, self._grace_s)
            while time.time() < deadline:
                self._poll_workers(self._read_heartbeats())
                if not any(w.alive() for w in self._workers):
                    break
                time.sleep(min(0.1, self._poll_s))
            for w in self._workers:
                if w.alive():
                    _kill(w.pid, signal.SIGKILL)
                    w.killed = True
                    w.exit_code = -9
                    if w.proc is not None:
                        try:  # reap: a long-lived controller spawns many
                            w.proc.wait(timeout=5)
                        except Exception:  # noqa: BLE001
                            pass

    def _resize(self, next_world, reason, planned):
        """Drain the current incarnation and bring up the next one at
        ``next_world``.  The ``controller.resize`` chaos site fires in
        the crash window this method is designed around: old world down,
        new world not yet up, state = draining(next_world)."""
        with _tel.span("controller.resize", "controller",
                       from_world=self._world, to_world=next_world,
                       reason=reason, planned=planned):
            old = self._world
            self._drain(reason, next_world=next_world)
            if _chaos._ACTIVE:
                _chaos.hit("controller.resize")
            if not planned:
                delay = self._backoff.backoff_delay(self._restarts - 1)
                if delay > 0:
                    time.sleep(delay)
            self._spawn_world(next_world)
        if next_world != old:
            _M_RESIZES.inc()
            self._event("resized", from_world=old, to_world=next_world,
                        reason=reason, planned=planned)

    def _on_failure(self, kind, heartbeats, detail=None):
        """Classify a failure and restart the job.  Raises
        JobFailedError when the restart budget is spent."""
        _M_FAILURES.inc()
        codes = {w.rank: w.exit_code for w in self._workers}
        bringup = not self._reached_running(heartbeats)
        dumps = self._flightrec_dumps()
        self._event("worker_failure", kind=kind, detail=detail,
                    exit_codes=codes, bringup=bringup,
                    flightrec=len(dumps))
        if self._restarts >= self._max_restarts:
            self._event("budget_exhausted", restarts=self._restarts)
            # terminal path still owns the survivors: a hang/straggler
            # kill leaves healthy peers running — reap them before
            # dying, with the 'failed' outcome committed first
            self._drain(f"terminal.{kind}", phase="failed")
            self._finish("failed", f"restart budget exhausted after "
                                   f"{self._restarts} restarts "
                                   f"(last failure: {kind})")
            raise JobFailedError(
                f"elastic job failed: {kind} with the restart budget "
                f"({self._max_restarts}) exhausted; postmortem roll-up "
                f"in {self._report_dir()}")
        self._restarts += 1
        _M_RESTARTS.inc()
        # bring-up failures (rendezvous timeout surfaced through the
        # heartbeat 'failed' phase) keep the world size: no rank proved
        # dead mid-training, shrinking would only shed capacity
        if bringup:
            next_world = self._world
        else:
            next_world = max(self._min_workers, self._world - 1)
        self._resize(next_world, reason=kind, planned=False)

    # -- re-adoption --------------------------------------------------------

    def _recover(self, st):
        """Resume a previous controller's job from its state file.
        Every phase has exactly one recovery action (the state write
        always PRECEDES the action it describes)."""
        self._target = int(st.get("target_world", self._target))
        self._world = int(st.get("world", 0))
        self._incarnation = int(st.get("incarnation", -1))
        self._restarts = int(st.get("restarts", 0))
        self._regrow_at = st.get("regrow_at")
        self._coordinator = st.get("coordinator")
        self._history = list(st.get("history") or [])
        phase = st["phase"]
        self._event("recover", phase=phase)
        if phase in ("done", "failed"):
            self._outcome = phase
            return
        recorded = st.get("workers") or []
        if phase == "running":
            # adopt live pids; dead ones are classified by the poll loop
            # from their final heartbeat phase
            self._workers = []
            heartbeats = self._read_heartbeats()
            for rec in recorded:
                w = _Worker(rec["rank"], rec["pid"], proc=None,
                            log=rec.get("log"))
                if not (_pid_alive(w.pid)
                        and _pid_matches(w.pid, self._workdir)):
                    hb = heartbeats.get(w.rank)
                    w.exit_code = 0 if hb and hb.get("phase") == "done" \
                        else 1
                self._workers.append(w)
            self._event("adopted",
                        live=[w.rank for w in self._workers if w.alive()])
            self._save_state("running")
            return
        # spawning / draining: the old incarnation must not survive into
        # the new one — kill every recorded pid, then take the one step
        # the dead controller never reached
        for rec in recorded:
            if _pid_alive(rec["pid"]) \
                    and _pid_matches(rec["pid"], self._workdir):
                _kill(rec["pid"], signal.SIGKILL)
        if phase == "draining":
            nxt = st.get("next_world") or self._world or self._target
            self._event("resume_resize", to_world=nxt)
            self._spawn_world(nxt)
        else:  # spawning: partial world — respawn the incarnation fresh
            nxt = self._world or self._target
            self._event("resume_spawn", to_world=nxt)
            self._spawn_world(nxt)

    # -- run ----------------------------------------------------------------

    def run(self):
        """Drive the job to a terminal outcome.  Returns the summary
        dict (also written to ``<workdir>/report/summary.json``); raises
        JobFailedError when the job dies for good."""
        os.makedirs(self._workdir, exist_ok=True)
        if not _tel.enabled():
            _tel.enable()
        st = self._load_state()
        if st is not None:
            # the state file owns the target: a rerun with a different
            # -n must not re-target the job (or mis-rank the controller)
            self._target = int(st.get("target_world", self._target))
        # the controller is its own observability rank: one PAST the
        # worker ranks (stable across resizes — the target is fixed)
        _tel.aggregate.set_rank(self._target)
        _tel.tracer.get_tracer().set_process_label("mxnet_tpu controller")
        _tel.flightrec.note("controller.start", workdir=self._workdir,
                            target=self._target)
        with _tel.span("controller.job", "controller",
                       target=self._target):
            if st is not None:
                self._recover(st)
                if self._outcome is not None:
                    return self._summary(self._outcome)
            else:
                self._spawn_world(self._target)
            return self._watch_loop()

    def _watch_loop(self):
        while True:
            heartbeats = self._read_heartbeats()
            self._poll_workers(heartbeats)
            live = sum(1 for w in self._workers if w.alive())
            _G_LIVE.set(live)
            if live == 0:
                codes = [w.exit_code for w in self._workers]
                if all(c == 0 for c in codes):
                    self._finish("done", "all ranks completed")
                    return self._summary("done")
                self._on_failure("worker_death", heartbeats,
                                 detail={"exit_codes": codes})
                continue
            if any(w.exit_code not in (None, 0) for w in self._workers):
                # a dead rank strands every live peer inside the next
                # collective — drain now, don't wait for their deadlines
                self._on_failure("worker_death", heartbeats)
                continue
            now = time.time()
            if self._check_hangs(heartbeats, now) is not None:
                self._on_failure("hang", heartbeats)
                continue
            if self._check_straggler(heartbeats) is not None:
                self._on_failure("straggler", heartbeats)
                continue
            if self._regrow_at is not None:
                latest = self._manifest_latest()
                if latest is not None and latest >= self._regrow_at:
                    self._event("regrow", at_step=latest,
                                to_world=self._target)
                    self._resize(self._target, reason="regrow",
                                 planned=True)
                    continue
            time.sleep(self._poll_s)

    # -- terminal roll-up ---------------------------------------------------

    def _finish(self, outcome, detail):
        self._outcome = outcome
        self._event(outcome, detail=detail)
        self._save_state(outcome, detail=detail)
        _G_LIVE.set(0)
        self._rollup(outcome, detail)

    def _summary(self, outcome):
        return {
            "outcome": outcome,
            "target_world": self._target,
            "final_world": self._world,
            "incarnations": self._incarnation + 1,
            "restarts": self._restarts,
            "history": list(self._history),
            "workdir": self._workdir,
            "report": self._report_dir(),
        }

    def _rollup(self, outcome, detail):
        """The terminal postmortem: merged Chrome trace + merged
        Prometheus snapshot over every rank's shard (and the
        controller's own), per-rank verdict table, flight-recorder dump
        index, full event history.  Best-effort — reporting must never
        mask the job's real outcome."""
        try:
            rd = self._report_dir()
            os.makedirs(rd, exist_ok=True)
            teldir = self._telemetry_dir()
            try:
                _tel.aggregate.export_snapshot(directory=teldir)
            except Exception:  # noqa: BLE001
                pass
            snaps = _tel.aggregate.load_snapshots(teldir)
            trace = _tel.aggregate.merged_chrome_trace(snaps)
            with open(os.path.join(rd, "merged_trace.json"), "w") as f:
                json.dump(trace, f)
            with open(os.path.join(rd, "merged.prom"), "w") as f:
                f.write(_tel.aggregate.merged_prometheus(snaps))
            dumps = self._flightrec_dumps()
            summary = self._summary(outcome)
            summary["detail"] = detail
            summary["flightrec"] = dumps
            summary["chaos"] = {"armed_sites": _chaos.sites(),
                                "faults_fired": {
                                    s: _chaos.fault_count(s)
                                    for s in ("controller.spawn",
                                              "controller.resize")}}
            with open(os.path.join(rd, "summary.json"), "w") as f:
                json.dump(summary, f, indent=1)
            lines = [f"elastic job {outcome}: {detail}",
                     f"  target world {self._target}, final world "
                     f"{self._world}, {self._incarnation + 1} "
                     f"incarnation(s), {self._restarts} unplanned "
                     f"restart(s)", ""]
            for s in snaps:
                sc = s.get("stepclock") or {}
                lines.append(
                    f"  rank {s.get('rank')}: verdict "
                    f"{sc.get('verdict', 'idle')} over "
                    f"{sc.get('steps', 0)} step(s)")
            if dumps:
                lines.append("")
                lines.append(f"  {len(dumps)} flight-recorder dump(s):")
                lines.extend(f"    {d}" for d in dumps)
            with open(os.path.join(rd, "report.txt"), "w") as f:
                f.write("\n".join(lines) + "\n")
        except Exception:  # noqa: BLE001 — the roll-up is best-effort
            pass
