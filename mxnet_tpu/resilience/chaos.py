"""Deterministic fault injection — the testable half of mx.resilience.

Every recovery path in this codebase must be exercisable on a laptop CPU
run: the reference could only observe PS failures in production (SURVEY
§5.3), which is why its elastic story stayed "near-absent".  This module
plants named *chaos sites* at the runtime chokepoints

    ``kvstore.allreduce``  — dist kvstore cross-process reduction
    ``dist.barrier``       — dist kvstore barrier
    ``dataloader.fetch``   — DataLoader batch materialization
    ``checkpoint.save``    — after data write, before manifest commit
    ``trainer.step``       — top of gluon.Trainer.step

and lets tests (API) or the environment (``MXNET_CHAOS=1`` +
``MXNET_CHAOS_SITES``) arm faults at them:

    chaos.inject("kvstore.allreduce", kind="transient", times=2)
    chaos.inject("trainer.step", kind="fatal", after=3)
    chaos.inject("dataloader.fetch", kind="delay", delay_s=0.05)

    MXNET_CHAOS=1 MXNET_CHAOS_SITES="kvstore.allreduce:transient:2"

Faults fire on deterministic hit counts (``after`` skips the first K hits,
``times`` bounds how many fire; ``times=0`` = unbounded), so a chaos test
reproduces exactly.  Hot-path discipline: instrumented code guards with
``if chaos._ACTIVE: chaos.hit(site)`` — one module-attribute check when no
fault is armed, matching the telemetry gating pattern.
"""

from __future__ import annotations

import threading
import time

from .. import config
from .. import telemetry as _tel
from .policies import ResilienceError, TransientError

__all__ = [
    "ChaosError", "ChaosTransientError", "ChaosWorkerDeath",
    "inject", "clear", "hit", "active", "sites", "fault_count", "SITES",
    "arm_from_spec",
]

# the documented site names (informational; hit() accepts any string so
# downstream code can add sites without touching this module).
# ``io.decode`` fires INSIDE a decode-pool worker process (io/pipeline.py)
# — arm it via the environment (workers re-arm from the parent's spec);
# kind 'exit' there is a real worker kill.
# ``controller.spawn`` / ``controller.resize`` fire inside the ELASTIC
# CONTROLLER process (resilience/controller.py): spawn hits before each
# incarnation comes up, resize hits in the crash window between draining
# the old world and spawning the new one — kind 'exit' there is a real
# control-plane death, which the controller's state file must survive.
# ``router.dispatch`` / ``router.replica_spawn`` fire inside the serving
# ROUTER process (serving/router.py): dispatch hits between journaling a
# request and sending it to a replica (the router-death crash window),
# replica_spawn hits before each replica subprocess comes up.
# ``serving.reply`` fires inside a REPLICA worker (serving/replica.py)
# after a request's tokens are computed but BEFORE the ack is written —
# kind 'exit' there is the dedup-on-retry window a router resubmission
# must cover without duplicating tokens.
SITES = ("kvstore.allreduce", "dist.barrier", "dataloader.fetch",
         "checkpoint.save", "trainer.step", "io.decode",
         "controller.spawn", "controller.resize",
         "router.dispatch", "router.replica_spawn", "serving.reply")

_M_FAULTS = _tel.counter(
    "mxnet_resilience_faults_injected_total",
    "Chaos faults fired (delays, transient errors, and worker deaths).")


class ChaosError(ResilienceError):
    """Base for injected faults."""


class ChaosTransientError(ChaosError, TransientError):
    """Injected transient failure — Retry policies absorb it."""


class ChaosWorkerDeath(ChaosError):
    """Injected permanent failure (simulated worker death) — NOT
    transient; recovery means fallback or checkpoint resume, not retry."""


class _Fault:
    __slots__ = ("kind", "times", "after", "delay_s", "message",
                 "hits", "fired")

    def __init__(self, kind, times, after, delay_s, message):
        if kind not in ("delay", "transient", "fatal", "exit"):
            raise ValueError(f"unknown chaos kind {kind!r}")
        self.kind = kind
        self.times = int(times)      # 0 = unbounded
        self.after = int(after)      # skip the first `after` hits
        self.delay_s = float(delay_s)
        self.message = message
        self.hits = 0
        self.fired = 0


_lock = threading.Lock()
_faults: dict = {}   # site -> list[_Fault]
_counts: dict = {}   # site -> total faults fired (survives clear())

# single flag hot paths read as a module attribute (telemetry pattern)
_ACTIVE = False


def active():
    """True when at least one fault is armed."""
    return _ACTIVE


def sites():
    """Site names with armed faults."""
    with _lock:
        return sorted(_faults)


def fault_count(site=None):
    """Faults fired at ``site`` (or everywhere) since process start."""
    with _lock:
        if site is not None:
            return _counts.get(site, 0)
        return sum(_counts.values())


def inject(site, kind="transient", times=1, after=0, delay_s=0.0,
           message=None):
    """Arm a fault at ``site``.

    kind:
      - ``delay``: sleep ``delay_s`` (latency injection)
      - ``transient``: raise ChaosTransientError (retryable)
      - ``fatal``: raise ChaosWorkerDeath (permanent — simulated death)
      - ``exit``: ``os._exit(1)`` — REAL process death, for subprocess /
        dataloader-worker tests only
    """
    global _ACTIVE
    f = _Fault(kind, times, after, delay_s,
               message or f"chaos[{kind}]@{site}")
    with _lock:
        _faults.setdefault(site, []).append(f)
        _ACTIVE = True
    return f


def clear(site=None):
    """Disarm faults at ``site`` (or everywhere).  Fired counts persist."""
    global _ACTIVE
    with _lock:
        if site is None:
            _faults.clear()
        else:
            _faults.pop(site, None)
        _ACTIVE = bool(_faults)


def hit(site, **ctx):
    """Evaluate armed faults at ``site``; called from instrumented code
    behind an ``if chaos._ACTIVE`` guard.  Raises per the armed kind."""
    with _lock:
        flist = _faults.get(site)
        if not flist:
            return
        todo = []
        for f in flist:
            f.hits += 1
            if f.hits <= f.after:
                continue
            if f.times and f.fired >= f.times:
                continue
            f.fired += 1
            _counts[site] = _counts.get(site, 0) + 1
            todo.append(f)
    for f in todo:
        _M_FAULTS.inc()
        _tel.instant(f"chaos.{f.kind}", "resilience", site=site, **ctx)
        if f.kind == "delay":
            time.sleep(f.delay_s)
        elif f.kind == "transient":
            raise ChaosTransientError(f.message)
        elif f.kind == "fatal":
            raise ChaosWorkerDeath(f.message)
        elif f.kind == "exit":
            import os
            # os._exit skips every hook (atexit, excepthook) — the flight
            # recorder's postmortem must be written BEFORE the plug pulls
            _tel.flightrec.dump(f"chaos.exit.{site}")
            os._exit(1)


def arm_from_spec(spec):
    """Arm faults from a "site:kind[:times[:delay_s[:after]]],..." spec
    string — the MXNET_CHAOS_SITES grammar, callable directly so
    decode-pool workers can re-arm from the spec their PARENT resolved (a
    forkserver child may inherit a stale environment).  The optional 5th
    field maps to ``inject(after=)``: skip the first N hits before
    firing (arming a mid-stream death from the environment)."""
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            fields = part.split(":")
            site = fields[0]
            kind = fields[1] if len(fields) > 1 else "transient"
            times = int(fields[2]) if len(fields) > 2 else 1
            delay_s = float(fields[3]) if len(fields) > 3 else 0.0
            after = int(fields[4]) if len(fields) > 4 else 0
            inject(site, kind=kind, times=times, delay_s=delay_s,
                   after=after)
        except ValueError as exc:
            # a spec typo must not break `import mxnet_tpu` (this runs at
            # import, deep under every module that wires chaos sites)
            import warnings
            warnings.warn(
                f"ignoring malformed MXNET_CHAOS_SITES entry {part!r}: "
                f"{exc}", stacklevel=2)


def _arm_from_env():
    """MXNET_CHAOS=1 + MXNET_CHAOS_SITES arms faults at import, so chaos
    lanes need no code changes."""
    if not config.get_bool("MXNET_CHAOS"):
        return
    arm_from_spec(config.get("MXNET_CHAOS_SITES", "") or "")


_arm_from_env()
